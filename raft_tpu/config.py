"""Global output-type configuration.

Re-design of pylibraft.config (python/pylibraft/pylibraft/config.py:15-46):
``set_output_as`` installs a global conversion applied by
``@auto_convert_output`` on the array-returning top-level entry points
(pairwise_distance, brute-force knn, select_k, IVF/CAGRA search, kmeans
predict/transform — the surface pylibraft converts). Index objects and
dataclass outputs stay JAX pytrees. Supported targets: ``"jax"`` (default,
no conversion), ``"numpy"``, ``"torch"`` (CPU tensors via dlpack when torch
is importable), or any callable ``jax.Array -> Any``.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax

__all__ = ["set_output_as", "get_output_as", "auto_convert_output"]

_output_as: str | Callable = "jax"


def set_output_as(output: str | Callable) -> None:
    """Set the global output conversion (ref: pylibraft config.set_output_as,
    config.py:20-46 — there 'cupy'/'torch'/callable; here 'jax'/'numpy'/
    'torch'/callable)."""
    global _output_as
    if not (output in ("jax", "numpy", "torch") or callable(output)):
        raise ValueError("output_as must be 'jax', 'numpy', 'torch', or a callable")
    _output_as = output


def get_output_as() -> str | Callable:
    return _output_as


def _convert(value: Any) -> Any:
    if _output_as == "jax":
        return value
    if isinstance(value, jax.Array):
        if callable(_output_as):
            return _output_as(value)
        if _output_as == "numpy":
            import numpy as np

            return np.asarray(value)
        if _output_as == "torch":
            import torch

            return torch.from_dlpack(value)
    if isinstance(value, tuple):
        return tuple(_convert(v) for v in value)
    return value


def auto_convert_output(fn: Callable) -> Callable:
    """Decorator applying the global conversion to the return value (ref:
    pylibraft config auto_convert_output)."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        return _convert(fn(*args, **kwargs))

    return wrapper
