"""Global configuration: output types + the persistent compilation cache.

Re-design of pylibraft.config (python/pylibraft/pylibraft/config.py:15-46):
``set_output_as`` installs a global conversion applied by
``@auto_convert_output`` on the array-returning top-level entry points
(pairwise_distance, brute-force knn, select_k, IVF/CAGRA search, kmeans
predict/transform — the surface pylibraft converts). Index objects and
dataclass outputs stay JAX pytrees. Supported targets: ``"jax"`` (default,
no conversion), ``"numpy"``, ``"torch"`` (CPU tensors via dlpack when torch
is importable), or any callable ``jax.Array -> Any``.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax

__all__ = ["set_output_as", "get_output_as", "auto_convert_output",
           "enable_compilation_cache"]

_output_as: str | Callable = "jax"


def enable_compilation_cache(path: str | None = None) -> str:
    """Persist XLA compilations across processes (the warm-build story).

    1M-scale index builds are dominated by cold-jit compilation (IVF-Flat
    ~120 s, CAGRA ~320 s cold vs seconds warm — BASELINE.md); the reference
    avoids this class of cost with ahead-of-time compiled kernels in libraft
    (SURVEY.md R1/R2 explicit instantiations). The TPU analogue is JAX's
    persistent compilation cache: with it enabled, a second process rebuilding
    or re-searching the same shapes skips compilation entirely. Combine with
    ``neighbors.*.save``/``load`` so repeat users pay neither compile nor
    build cost.

    Returns the cache directory in effect (default
    ``~/.cache/raft_tpu/jit``).
    """
    import os

    import jax

    path = path or os.path.join(
        os.path.expanduser("~"), ".cache", "raft_tpu", "jit")
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # cache every entry, however small/fast — index pipelines are many
    # medium-sized programs, and the defaults skip anything that compiles
    # in under a second
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    # jax latches "cache unused" once per task on the FIRST compile: if
    # anything compiled before this call, the new dir would silently never
    # be consulted. Reset the latch so enabling mid-process takes effect.
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:  # pragma: no cover - private API drift: best effort
        pass
    from .core.logger import logger

    logger.info("persistent compilation cache enabled at %s", path)
    return path


def set_output_as(output: str | Callable) -> None:
    """Set the global output conversion (ref: pylibraft config.set_output_as,
    config.py:20-46 — there 'cupy'/'torch'/callable; here 'jax'/'numpy'/
    'torch'/callable)."""
    global _output_as
    if not (output in ("jax", "numpy", "torch") or callable(output)):
        raise ValueError("output_as must be 'jax', 'numpy', 'torch', or a callable")
    _output_as = output


def get_output_as() -> str | Callable:
    return _output_as


def _convert(value: Any) -> Any:
    if _output_as == "jax":
        return value
    if isinstance(value, jax.core.Tracer):
        # inside someone's jit trace (a converted entry point called from a
        # user-jitted function, or one entry point composing another):
        # host conversion is impossible and wrong — pass tracers through;
        # the OUTERMOST eager call converts the final outputs
        return value
    if isinstance(value, jax.Array):
        if callable(_output_as):
            return _output_as(value)
        if _output_as == "numpy":
            import numpy as np

            return np.asarray(value)
        if _output_as == "torch":
            import torch

            return torch.from_dlpack(value)
    if isinstance(value, tuple):
        return tuple(_convert(v) for v in value)
    # lists and dicts of arrays (multi-output returns) convert element-wise
    # too — pylibraft's config converts any cai-exposing leaf; only
    # converting tuples here silently leaked jax arrays from list/dict
    # returns under set_output_as("numpy"/"torch")
    if isinstance(value, list):
        return [_convert(v) for v in value]
    if isinstance(value, dict):
        return {k: _convert(v) for k, v in value.items()}
    return value


def auto_convert_output(fn: Callable) -> Callable:
    """Decorator applying the global conversion to the return value (ref:
    pylibraft config auto_convert_output)."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        return _convert(fn(*args, **kwargs))

    return wrapper
