"""raft_tpu.spatial — legacy spatial::knn compatibility surface.

Reference: cpp/include/raft/spatial/knn/ — the deprecated pre-``neighbors``
API kept for source compatibility (spatial/knn/knn.cuh aliases into
raft::neighbors). This package mirrors that: thin aliases plus the
haversine kNN entry point (spatial/knn/detail/haversine_distance.cuh).
"""

from .knn import (
    approx_knn_build_index,
    approx_knn_search,
    brute_force_knn,
    haversine_knn,
    knn,
    select_k,
)

__all__ = [
    "knn",
    "brute_force_knn",
    "haversine_knn",
    "select_k",
    "approx_knn_build_index",
    "approx_knn_search",
]
