"""Deprecated spatial::knn aliases (reference: spatial/knn/knn.cuh,
spatial/knn/ball_cover.cuh forwards, detail/haversine_distance.cuh)."""

from __future__ import annotations

from ..matrix.select_k import select_k  # noqa: F401  (spatial/knn/knn.cuh select_k alias)
from ..neighbors.brute_force import knn as brute_force_knn  # noqa: F401

# spatial::knn::knn was the original name of brute_force::knn
knn = brute_force_knn


def approx_knn_build_index(params, dataset, metric="sqeuclidean"):
    """Legacy approximate-kNN entry (reference:
    spatial/knn/detail/ann_quantized.cuh:42 approx_knn_build_index — a
    dispatcher over IVF-Flat / IVF-PQ index params). ``params`` is an
    ivf_flat.IndexParams or ivf_pq.IndexParams."""
    import dataclasses

    from ..neighbors import ivf_flat, ivf_pq

    if isinstance(params, ivf_flat.IndexParams):
        return ivf_flat.build(dataclasses.replace(params, metric=metric), dataset)
    if isinstance(params, ivf_pq.IndexParams):
        return ivf_pq.build(dataclasses.replace(params, metric=metric), dataset)
    raise TypeError(f"unsupported legacy ANN params: {type(params)!r}")


def approx_knn_search(index, queries, k: int, n_probes: int = 20):
    """Legacy approximate-kNN search (reference: ann_quantized.cuh:96)."""
    from ..neighbors import ivf_flat, ivf_pq

    if isinstance(index, ivf_flat.IvfFlatIndex):
        return ivf_flat.search(ivf_flat.SearchParams(n_probes=n_probes), index, queries, k)
    if isinstance(index, ivf_pq.IvfPqIndex):
        return ivf_pq.search(ivf_pq.SearchParams(n_probes=n_probes), index, queries, k)
    raise TypeError(f"unsupported legacy ANN index: {type(index)!r}")


def haversine_knn(dataset, queries, k: int):
    """k nearest neighbors under the haversine great-circle metric.

    Reference: raft::spatial::knn::detail::haversine_knn
    (spatial/knn/detail/haversine_distance.cuh). Inputs are (n, 2) arrays of
    (latitude, longitude) in radians.
    """
    return brute_force_knn(dataset, queries, k, metric="haversine")
