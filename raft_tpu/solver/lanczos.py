"""Restarted Lanczos eigensolver (reference: raft/sparse/solver/lanczos.cuh
computeSmallestEigenvectors:68 / computeLargestEigenvectors:132, detail in
sparse/solver/detail/lanczos.cuh).

TPU-first design: the reference runs implicitly-restarted Lanczos with scalar
alpha/beta recurrences and host-side LAPACK on the tridiagonal system. Here we
use *thick-restart* Lanczos with full two-pass reorthogonalization: every
expansion step is two dense (n, m) GEMVs (``Vᵀw`` and ``V @ h``) that ride the
MXU, the projected system is a small (m, m) symmetric matrix solved with
``jnp.linalg.eigh`` on device, and the restart loop is a ``lax.while_loop`` so
the whole solve is one XLA computation — no host round-trips per iteration.
Full reorthogonalization costs 2x FLOPs vs the scalar recurrence but is what
makes float32 viable (the reference needs periodic reorth too,
detail/lanczos.cuh lanczosRestart) and the GEMV formulation is exactly what
the hardware wants.
"""

from __future__ import annotations

import functools
import math
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.tree_util import Partial

from ..core.errors import expects
from ..random.rng import as_key
from ..sparse.types import CsrMatrix

__all__ = ["eigsh", "compute_smallest_eigenvectors", "compute_largest_eigenvectors"]


@functools.partial(jax.jit, static_argnames=("k", "m", "max_restarts"))
def _lanczos_thick_restart(matvec: Callable, v0: jax.Array, k: int, m: int,
                           max_restarts: int, tol: jax.Array):
    """Core thick-restart loop. Returns (eigenvalues (k,), eigenvectors (n, k),
    n_restarts, residuals (k,)).

    Basis buffer V is (n, m+1) with unbuilt columns zero, so the two-pass
    Gram-Schmidt ``h = Vᵀw; w -= V h`` automatically restricts to the built
    basis. H is the (m+1, m) projected matrix; after a restart it is
    arrow-shaped (locked Ritz diag + coupling row), which the symmetrized
    Ritz extraction handles uniformly.
    """
    n = v0.shape[0]
    dtype = v0.dtype
    eps = jnp.asarray(1e-30, dtype)
    key = as_key(7)

    def expand(V, H, j0, salt):
        def body(j, carry):
            V, H = carry

            def do(V, H):
                w = matvec(V[:, j])
                h1 = V.T @ w
                w = w - V @ h1
                h2 = V.T @ w
                w = w - V @ h2
                h = h1 + h2
                beta = jnp.linalg.norm(w)
                # breakdown (invariant subspace): continue with a fresh
                # orthonormalized random direction, coupling ~0
                r = jax.random.normal(jax.random.fold_in(key, salt + j), (n,), dtype)
                r = r - V @ (V.T @ r)
                r = r / jnp.maximum(jnp.linalg.norm(r), eps)
                ok = beta > jnp.asarray(1e-6, dtype) * jnp.maximum(
                    jnp.linalg.norm(h), jnp.asarray(1.0, dtype))
                vnext = jnp.where(ok, w / jnp.maximum(beta, eps), r)
                h = h.at[j + 1].set(jnp.where(ok, beta, 0.0))
                H2 = H.at[:, j].set(h)
                V2 = V.at[:, j + 1].set(vnext)
                return V2, H2

            return lax.cond(j >= j0, do, lambda V, H: (V, H), V, H)

        return lax.fori_loop(0, m, body, (V, H))

    def ritz(H):
        t = H[:m, :m]
        t = (t + t.T) * 0.5
        theta, s = jnp.linalg.eigh(t)  # ascending
        res = jnp.abs(H[m, m - 1] * s[m - 1, :])
        return theta, s, res

    def cond(carry):
        V, H, j0, r, done = carry
        return jnp.logical_and(r < max_restarts, jnp.logical_not(done))

    def step(carry):
        V, H, j0, r, done = carry
        V, H = expand(V, H, j0, r * (m + 1))
        theta, s, res = ritz(H)
        scale = jnp.maximum(jnp.max(jnp.abs(theta[:k])), jnp.asarray(1.0, dtype))
        converged = jnp.max(res[:k]) < tol * scale
        # thick restart: lock k Ritz vectors, keep the residual basis vector
        locked = V[:, :m] @ s[:, :k]  # (n, k)
        Vn = jnp.zeros_like(V)
        Vn = Vn.at[:, :k].set(locked)
        Vn = Vn.at[:, k].set(V[:, m])
        Hn = jnp.zeros_like(H)
        Hn = Hn.at[jnp.arange(k), jnp.arange(k)].set(theta[:k])
        Hn = Hn.at[k, :k].set(H[m, m - 1] * s[m - 1, :k])
        return Vn, Hn, k, r + 1, converged

    V0 = jnp.zeros((n, m + 1), dtype).at[:, 0].set(v0)
    H0 = jnp.zeros((m + 1, m), dtype)
    V, H, _, n_restarts, _ = lax.while_loop(cond, step, (V0, H0, 0, 0, False))
    # after a restart the locked block carries the answer directly
    w = jnp.diagonal(H)[:k]
    vecs = V[:, :k]
    res = jnp.abs(H[k, :k])
    return w, vecs, n_restarts, res


def _csr_mv(a, x):
    from ..sparse.linalg import spmv

    return spmv(a, x)


def _dense_mv(a, x):
    return a @ x


def _neg_mv(mv, x):
    return -mv(x)


def _as_matvec(a, n):
    """Wrap the operator as a jax.tree_util.Partial so it crosses the jit
    boundary as a pytree — module-level inner functions keep the jit cache
    warm across calls with the same shapes."""
    if isinstance(a, CsrMatrix):
        expects(a.shape[0] == a.shape[1], "matrix must be square")
        return Partial(_csr_mv, a), a.shape[0], a.dtype
    if callable(a):
        expects(n is not None, "n is required for a callable operator")
        return (a if isinstance(a, Partial) else Partial(a)), int(n), jnp.float32
    arr = jnp.asarray(a)
    expects(arr.ndim == 2 and arr.shape[0] == arr.shape[1], "matrix must be square")
    return Partial(_dense_mv, arr), arr.shape[0], arr.dtype


def eigsh(a, k: int = 6, which: str = "SA", n: int | None = None,
          ncv: int | None = None, max_iter: int = 4000, tol: float = 1e-6,
          seed=42, v0=None):
    """k extremal eigenpairs of a symmetric operator.

    ``a`` may be a :class:`CsrMatrix`, a dense (n, n) array, or a matvec
    callable (pass ``n``). ``which`` is ``"SA"`` (smallest algebraic, the
    reference's computeSmallestEigenvectors) or ``"LA"`` (largest,
    computeLargestEigenvectors — internally solved on ``-A``).

    Returns ``(eigenvalues (k,), eigenvectors (n, k), n_restarts)`` with
    eigenvalues ascending, mirroring scipy.sparse.linalg.eigsh.
    """
    matvec, n, dtype = _as_matvec(a, n)
    dtype = jnp.promote_types(dtype, jnp.float32)
    expects(1 <= k < n, "need 1 <= k < n")
    expects(which in ("SA", "LA"), "which must be 'SA' or 'LA'")
    m = ncv if ncv is not None else min(n - 1, max(2 * k + 8, 20))
    m = max(m, k + 2)
    expects(m <= n, "ncv must be <= n (matrix too small for this k/ncv)")
    max_restarts = max(1, math.ceil(max(max_iter - m, 0) / max(m - k, 1)) + 1)

    if which == "LA":
        matvec = Partial(_neg_mv, matvec)

    if v0 is None:
        v0 = jax.random.normal(as_key(seed), (n,), dtype)
    else:
        v0 = jnp.asarray(v0, dtype)
    v0 = v0 / jnp.linalg.norm(v0)

    w, v, n_restarts, _ = _lanczos_thick_restart(matvec, v0, k, m, max_restarts,
                                                 jnp.asarray(tol, dtype))
    if which == "LA":
        w = -w[::-1]
        v = v[:, ::-1]
    return w, v, n_restarts


def compute_smallest_eigenvectors(a, k: int, max_iter: int = 4000,
                                  restart_iter: int | None = None,
                                  tol: float = 1e-6, seed=42, v0=None):
    """Reference parity: raft/sparse/solver/lanczos.cuh:68."""
    return eigsh(a, k=k, which="SA", ncv=restart_iter, max_iter=max_iter,
                 tol=tol, seed=seed, v0=v0)


def compute_largest_eigenvectors(a, k: int, max_iter: int = 4000,
                                 restart_iter: int | None = None,
                                 tol: float = 1e-6, seed=42, v0=None):
    """Reference parity: raft/sparse/solver/lanczos.cuh:132."""
    return eigsh(a, k=k, which="LA", ncv=restart_iter, max_iter=max_iter,
                 tol=tol, seed=seed, v0=v0)
