"""raft_tpu.solver — combinatorial/iterative solvers.

Reference: raft/sparse/solver (MST S8, Lanczos S9) + raft/solver (LAP K5).
"""

from .lanczos import (
    compute_largest_eigenvectors,
    compute_smallest_eigenvectors,
    eigsh,
)
from .lap import LapOutput, lap_solve
from .mst import MstOutput, mst

__all__ = [
    "LapOutput",
    "MstOutput",
    "compute_largest_eigenvectors",
    "compute_smallest_eigenvectors",
    "eigsh",
    "lap_solve",
    "mst",
]
