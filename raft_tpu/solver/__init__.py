"""raft_tpu.solver — combinatorial/iterative solvers.

Reference: raft/sparse/solver (MST S8, Lanczos S9) + raft/solver (LAP K5).
"""

from .lap import LapOutput, lap_solve
from .mst import MstOutput, mst

__all__ = ["LapOutput", "MstOutput", "lap_solve", "mst"]
