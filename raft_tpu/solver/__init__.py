"""raft_tpu.solver — raft/solver + raft/sparse/solver (S8-S9, K5). Under construction."""
