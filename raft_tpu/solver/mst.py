"""Minimum spanning tree / forest — Borůvka, batch-synchronous.

Reference: raft/sparse/solver/mst_solver.cuh + detail/mst_{solver_inl,kernels,
utils}.cuh — a CUDA Borůvka with per-supervertex min-edge kernels, color
propagation, and alternating-tree cycle avoidance.

TPU re-design: one `lax.while_loop` whose body is entirely dense vector ops:

1. every edge's (weight, id) is pre-ranked once (a single argsort) so the
   per-component argmin is a scatter-min of int32 ranks — float tie-break
   issues disappear and both endpoint components deterministically agree on
   the same cheapest connecting edge (the reference's `alteration` weight
   jitter, detail/mst_utils.cuh, solves the same tie problem numerically);
2. winner edges hook max-color → min-color (strictly decreasing ⇒ no cycles),
   and colors converge by pointer jumping (log₂n fixed-count inner loop) —
   the analogue of the reference's min_pair_colors + label propagation;
3. terminates when no cross-component edge remains (spanning forest if the
   graph is disconnected).
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.errors import expects
from ..sparse.types import CooMatrix, CsrMatrix

__all__ = ["MstOutput", "mst"]


class MstOutput(NamedTuple):
    """Reference: Graph_COO output of mst_solver (mst_solver.cuh)."""

    src: jax.Array  # (cap,) int32, padding = n
    dst: jax.Array  # (cap,) int32
    weights: jax.Array  # (cap,) f32, padding = +inf
    n_edges: jax.Array  # int32 scalar
    colors: jax.Array  # (n,) int32 final component labels


@functools.partial(jax.jit, static_argnames=("n", "jump_steps"))
def _boruvka(rows, cols, weights, valid, n: int, jump_steps: int):
    cap = rows.shape[0]
    big = jnp.int32(2**31 - 1)

    # global (weight, id) rank per edge: unique int32 keys for argmin
    order = jnp.argsort(jnp.where(valid, weights, jnp.inf), stable=True)
    rank = jnp.zeros((cap,), jnp.int32).at[order].set(jnp.arange(cap, dtype=jnp.int32))

    def cond(state):
        _, _, again = state
        return again

    def body(state):
        color, mst_mask, _ = state
        cu = color[jnp.minimum(rows, n - 1)]
        cv = color[jnp.minimum(cols, n - 1)]
        cand = valid & (cu != cv)
        key = jnp.where(cand, rank, big)
        # per-component min outgoing rank, both directions
        best = jnp.full((n,), big, jnp.int32)
        best = best.at[jnp.where(cand, cu, n)].min(key, mode="drop")
        best = best.at[jnp.where(cand, cv, n)].min(key, mode="drop")
        winner = cand & ((rank == best[cu]) | (rank == best[cv]))
        mst_mask = mst_mask | winner
        # hook max-color -> min-color for winner edges
        cmin = jnp.minimum(cu, cv)
        cmax = jnp.maximum(cu, cv)
        parent = jnp.arange(n, dtype=jnp.int32)
        parent = parent.at[jnp.where(winner, cmax, n)].min(cmin, mode="drop")
        # pointer jumping to roots (parent[c] <= c ⇒ converges, no cycles)
        parent = lax.fori_loop(0, jump_steps, lambda _, p: p[p], parent)
        color = parent[color]
        again = jnp.any(cand)
        return color, mst_mask, again

    color0 = jnp.arange(n, dtype=jnp.int32)
    mask0 = jnp.zeros((cap,), bool)
    color, mst_mask, _ = lax.while_loop(cond, body, (color0, mask0, jnp.bool_(True)))

    # compact MST edges to the front, sorted by weight (ref: single_linkage
    # sorts the MST output, cluster/detail/mst.cuh sorted MST)
    sort_key = jnp.where(mst_mask, weights, jnp.inf)
    out_order = jnp.argsort(sort_key, stable=True)
    kept = mst_mask[out_order]
    src = jnp.where(kept, rows[out_order], n)
    dst = jnp.where(kept, cols[out_order], n)
    w = jnp.where(kept, weights[out_order], jnp.inf)
    n_edges = jnp.sum(mst_mask.astype(jnp.int32))
    return MstOutput(src, dst, w, n_edges, color)


def mst(graph, n_vertices: int | None = None) -> MstOutput:
    """Minimum spanning forest of an undirected weighted graph.

    ``graph`` is a CooMatrix/CsrMatrix whose entries are (symmetric) edge
    weights. Returns edges sorted ascending by weight, padding rows = n.

    Reference: raft::sparse::solver::mst (sparse/solver/mst_solver.cuh).
    """
    if isinstance(graph, CsrMatrix):
        from ..sparse.convert import csr_to_coo

        graph = csr_to_coo(graph)
    expects(graph.shape[0] == graph.shape[1], "graph must be square")
    n = graph.shape[0] if n_vertices is None else n_vertices
    expects(n >= graph.shape[0], "n_vertices=%d < graph dimension %d", n, graph.shape[0])
    # drop one direction of each symmetric pair (keep u < v) — Borůvka scans
    # both endpoints of every edge anyway
    keep = graph.valid_mask() & (graph.rows < graph.cols)
    jump = max(int(math.ceil(math.log2(max(n, 2)))) + 1, 1)
    return _boruvka(graph.rows, graph.cols, graph.vals.astype(jnp.float32), keep, n, jump)
