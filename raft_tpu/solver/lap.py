"""Linear assignment problem (LAP), batched.

Reference: raft/solver/linear_assignment.cuh — `LinearAssignmentProblem`
(:54, ctor :88 takes (size, batchsize, epsilon), solve :119, dual/primal
accessors :150-184) implementing the Date–Nagi GPU Hungarian algorithm
(steps 0-6 in detail/lap_functions.cuh) over a batch of square cost
matrices.

TPU re-design: the Hungarian steps are branchy row/column covering with
augmenting-path chases — hostile to XLA. The same optimum is reached by
Bertsekas' **auction algorithm with ε-scaling**, whose bidding phase is a
dense, batched top-2 reduction over the value matrix (MXU/VPU friendly) and
whose assignment phase is two scatter rounds — all inside one
`lax.while_loop`. Each round every unassigned person bids for its best
object with increment (v₁−v₂+ε); prices only rise, so the loop terminates,
and on completion the assignment is within n·ε of optimal (exact for
integer costs once ε < 1/(n+1), the default). Prices are the column duals,
matching the reference's getColDualVector; row duals are the residual max.
Batch = `vmap`, replacing the reference's explicit batch loops.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.errors import expects

__all__ = ["LapOutput", "lap_solve"]

_f32 = jnp.float32


class LapOutput(NamedTuple):
    """Reference accessors: getAssignmentVector/getRowAssignments (solve),
    getRowDualVector :150, getColDualVector :160, getPrimalObjectiveValue :170."""

    row_assignment: jax.Array  # (..., n) int32: column assigned to each row
    col_assignment: jax.Array  # (..., n) int32: row assigned to each column
    objective: jax.Array  # (...,) cost-sense objective value
    row_duals: jax.Array  # (..., n) f32
    col_duals: jax.Array  # (..., n) f32
    converged: jax.Array  # (...,) bool: every row assigned within max_iter


def _auction(benefit, eps_final: float, max_iter: int):
    n = benefit.shape[0]
    neg_inf = _f32(-jnp.inf)
    rng = jnp.maximum(jnp.max(benefit) - jnp.min(benefit), 1.0)
    eps0 = rng / 2.0

    def cond(state):
        _, _, _, _, done, it = state
        return (~done) & (it < max_iter)

    def body(state):
        prices, row_assign, col_owner, eps, done, it = state
        unassigned = row_assign < 0

        values = benefit - prices[None, :]
        top2_v, top2_i = lax.top_k(values, 2)
        jstar = top2_i[:, 0]
        # winning price the bidder is willing to pay for its best object
        new_price = benefit[jnp.arange(n), jstar] - top2_v[:, 1] + eps

        bid_to = jnp.where(unassigned, jstar, n)
        colmax = jnp.full((n,), neg_inf, _f32).at[bid_to].max(
            jnp.where(unassigned, new_price, neg_inf), mode="drop"
        )
        bided = colmax > neg_inf
        is_winner = unassigned & (new_price >= colmax[jstar])
        winner = jnp.full((n,), n, jnp.int32).at[bid_to].min(
            jnp.where(is_winner, jnp.arange(n, dtype=jnp.int32), n), mode="drop"
        )

        # evict previous owners of columns that received bids, then assign
        evicted = (row_assign >= 0) & bided[jnp.minimum(row_assign, n - 1)]
        row_assign = jnp.where(evicted, -1, row_assign)
        col_owner = jnp.where(bided, winner, col_owner)
        row_assign = row_assign.at[jnp.where(bided, winner, n)].set(
            jnp.arange(n, dtype=jnp.int32), mode="drop"
        )
        prices = jnp.where(bided, colmax, prices)

        all_assigned = jnp.all(row_assign >= 0)
        at_final = eps <= eps_final
        done = all_assigned & at_final
        # ε-scaling: on completion of a scale, tighten ε and restart the
        # assignment (prices are kept — that is what makes scaling fast)
        rescale = all_assigned & ~at_final
        eps = jnp.where(rescale, jnp.maximum(eps / 4.0, eps_final), eps)
        row_assign = jnp.where(rescale, -1, row_assign)
        col_owner = jnp.where(rescale, -1, col_owner)
        return prices, row_assign, col_owner, eps, done, it + 1

    state = (
        jnp.zeros((n,), _f32),
        jnp.full((n,), -1, jnp.int32),
        jnp.full((n,), -1, jnp.int32),
        jnp.maximum(eps0, _f32(eps_final)),
        jnp.bool_(False),
        jnp.int32(0),
    )
    prices, row_assign, col_owner, _, done, _ = lax.while_loop(cond, body, state)
    row_duals = jnp.max(benefit - prices[None, :], axis=1)
    return row_assign, col_owner, prices, row_duals, done


def _solve_one(c, eps: float, max_iter: int, maximize: bool):
    n = c.shape[-1]
    benefit = c if maximize else -c
    ra, ca, prices, rd, done = _auction(benefit, eps, max_iter)
    # unassigned rows (only possible when not converged) contribute 0
    obj = jnp.sum(jnp.where(ra >= 0, c[jnp.arange(n), jnp.maximum(ra, 0)], 0.0))
    if not maximize:
        prices, rd = -prices, -rd
    return LapOutput(ra, ca, obj, rd, prices, done)


@functools.partial(jax.jit, static_argnames=("eps", "max_iter", "maximize"))
def _solve_jit(c, eps: float, max_iter: int, maximize: bool):
    return _solve_one(c, eps, max_iter, maximize)


@functools.partial(jax.jit, static_argnames=("eps", "max_iter", "maximize"))
def _solve_batch_jit(c, eps: float, max_iter: int, maximize: bool):
    return jax.vmap(lambda m: _solve_one(m, eps, max_iter, maximize))(c)


def lap_solve(
    cost,
    eps: float | None = None,
    maximize: bool = False,
    max_iter: int | None = None,
) -> LapOutput:
    """Solve square assignment problems (reference: linear_assignment.cuh:119).

    ``cost`` is ``(n, n)`` or batched ``(b, n, n)`` (the reference's
    ``batchsize``). Minimizes by default. ``eps`` is the final auction
    epsilon (reference ctor's ``epsilon``): the objective is within
    ``n*eps`` of optimal, and exact for integer-valued costs with the
    default ``1/(n+1)``. ``converged`` in the output is False for problems
    where the iteration cap was hit before every row was assigned — the
    assignment for those is partial (-1 rows).
    """
    cost = jnp.asarray(cost, _f32)
    expects(cost.ndim in (2, 3), "cost must be (n,n) or (b,n,n), got %dd", cost.ndim)
    n = cost.shape[-1]
    expects(cost.shape[-2] == n, "cost matrices must be square")
    if n == 1:
        shape = cost.shape[:-2]
        zero = jnp.zeros(shape + (1,), jnp.int32)
        return LapOutput(zero, zero, cost[..., 0, 0],
                         jnp.zeros(shape + (1,), _f32), jnp.zeros(shape + (1,), _f32),
                         jnp.ones(shape, bool))
    if eps is None:
        eps = 1.0 / (n + 1)
    if max_iter is None:
        # each round raises ≥1 price by ≥ε and prices are bounded ⇒ generous cap
        max_iter = 2000 * n + 20_000

    fn = _solve_jit if cost.ndim == 2 else _solve_batch_jit
    return fn(cost, float(eps), int(max_iter), bool(maximize))
