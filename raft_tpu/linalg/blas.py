"""BLAS-style dense operations.

Re-design of the reference's cuBLAS wrappers + mdspan free functions
(cpp/include/raft/linalg/gemm.cuh, gemv.cuh, axpy.cuh, dot.cuh,
transpose.cuh; detail/cublas_wrappers.hpp). On TPU the "vendor library" is
the MXU via lax.dot_general with f32 accumulation; alpha/beta epilogues fuse.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

__all__ = ["gemm", "gemv", "axpy", "dot", "transpose"]


def _mm(a, b):
    return lax.dot_general(
        a, b, (((1,), (0,)), ((), ())),
        precision=lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )


def gemm(a, b, c=None, alpha: float = 1.0, beta: float = 0.0, trans_a: bool = False, trans_b: bool = False):
    """alpha·op(A)·op(B) + beta·C (reference: linalg/gemm.cuh)."""
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    if trans_a:
        a = a.T
    if trans_b:
        b = b.T
    out = alpha * _mm(a, b)
    if c is not None and beta != 0.0:
        out = out + beta * jnp.asarray(c)
    return out.astype(a.dtype)


def gemv(a, x, y=None, alpha: float = 1.0, beta: float = 0.0, trans: bool = False):
    """alpha·op(A)·x + beta·y (reference: linalg/gemv.cuh)."""
    a = jnp.asarray(a)
    x = jnp.asarray(x)
    if trans:
        a = a.T
    out = alpha * _mm(a, x[:, None])[:, 0]
    if y is not None and beta != 0.0:
        out = out + beta * jnp.asarray(y)
    return out.astype(a.dtype)


def axpy(alpha: float, x, y):
    """y + alpha·x (reference: linalg/axpy.cuh)."""
    return jnp.asarray(y) + alpha * jnp.asarray(x)


def dot(x, y):
    """Vector inner product (reference: linalg/dot.cuh)."""
    return jnp.vdot(jnp.asarray(x), jnp.asarray(y))


def transpose(a):
    """Materialized transpose (reference: linalg/transpose.cuh)."""
    return jnp.asarray(a).T
