"""Elementwise maps, reductions, norms.

Re-design of the reference's map/reduce family (cpp/include/raft/linalg/:
map.cuh, map_reduce.cuh, unary_op.cuh, binary_op.cuh, ternary_op.cuh,
add.cuh..divide.cuh, power.cuh, sqrt.cuh, eltwise.cuh, reduce.cuh,
coalesced_reduction.cuh, strided_reduction.cuh, norm.cuh, normalize.cuh,
reduce_rows_by_key.cuh, reduce_cols_by_key.cuh, mean_squared_error.cuh,
matrix_vector_op.cuh). All are XLA-fused jnp compositions; the coalesced-vs-
strided kernel split dies — XLA picks reduction layouts.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.errors import expects

__all__ = [
    "map",
    "map_reduce",
    "unary_op",
    "binary_op",
    "ternary_op",
    "eltwise_add",
    "eltwise_sub",
    "eltwise_multiply",
    "eltwise_divide",
    "power",
    "sqrt",
    "reduce",
    "norm",
    "normalize",
    "row_norm",
    "col_norm",
    "reduce_rows_by_key",
    "reduce_cols_by_key",
    "mean_squared_error",
    "matrix_vector_op",
    "NormType",
]

_builtin_map = map


def map(fn, *arrays):  # noqa: A001 (reference name)
    """Elementwise map over aligned arrays (reference: linalg/map.cuh)."""
    return fn(*[jnp.asarray(a) for a in arrays])


def map_reduce(fn, reduce_fn, *arrays):
    """Fused map + full reduction (reference: linalg/map_reduce.cuh; the
    reference's neutral-element argument is implied by ``reduce_fn`` here)."""
    return reduce_fn(fn(*[jnp.asarray(a) for a in arrays]))


unary_op = map
binary_op = map
ternary_op = map


def eltwise_add(x, y):
    return jnp.asarray(x) + jnp.asarray(y)


def eltwise_sub(x, y):
    return jnp.asarray(x) - jnp.asarray(y)


def eltwise_multiply(x, y):
    return jnp.asarray(x) * jnp.asarray(y)


def eltwise_divide(x, y):
    return jnp.asarray(x) / jnp.asarray(y)


def power(x, p):
    return jnp.power(jnp.asarray(x), p)


def sqrt(x):
    return jnp.sqrt(jnp.asarray(x))


def reduce(m, axis: int = 1, op=jnp.sum, main_op=None, final_op=None):
    """Generalized row/col reduction with pre/post ops (reference:
    linalg/reduce.cuh — main_op maps elements, op reduces, final_op maps the
    result; covers coalesced_reduction/strided_reduction)."""
    m = jnp.asarray(m)
    if main_op is not None:
        m = main_op(m)
    out = op(m, axis=axis)
    return final_op(out) if final_op is not None else out


class NormType:
    """Reference: linalg/norm_types.hpp (L1Norm/L2Norm/LinfNorm)."""

    L1 = "l1"
    L2 = "l2"
    Linf = "linf"


def norm(m, norm_type: str = NormType.L2, axis: int = 1, sqrt: bool = True):
    """Row/col norms (reference: linalg/norm.cuh rowNorm/colNorm). For L2,
    ``sqrt=False`` returns squared norms — the reference's default for
    expanded-distance precomputation."""
    m = jnp.asarray(m).astype(jnp.float32)
    if norm_type == NormType.L1:
        return jnp.sum(jnp.abs(m), axis=axis)
    if norm_type == NormType.Linf:
        return jnp.max(jnp.abs(m), axis=axis)
    expects(norm_type == NormType.L2, "unknown norm type %s", norm_type)
    sq = jnp.sum(m * m, axis=axis)
    return jnp.sqrt(sq) if sqrt else sq


def row_norm(m, norm_type=NormType.L2, sqrt=True):
    return norm(m, norm_type, axis=1, sqrt=sqrt)


def col_norm(m, norm_type=NormType.L2, sqrt=True):
    return norm(m, norm_type, axis=0, sqrt=sqrt)


def normalize(m, norm_type: str = NormType.L2, eps: float = 1e-10):
    """Row-normalize (reference: linalg/normalize.cuh)."""
    n = norm(m, norm_type, axis=1, sqrt=True)
    return jnp.asarray(m) / jnp.maximum(n, eps)[:, None]


def reduce_rows_by_key(m, keys, n_keys: int, weights=None):
    """Segment-sum rows into per-key accumulators (reference:
    linalg/reduce_rows_by_key.cuh — the k-means centroid update primitive).
    On TPU this is one one-hot matmul: (n_keys, m)·(m, d) rides the MXU."""
    m = jnp.asarray(m).astype(jnp.float32)
    keys = jnp.asarray(keys)
    onehot = jax.nn.one_hot(keys, n_keys, dtype=jnp.float32, axis=0)  # (n_keys, m)
    if weights is not None:
        onehot = onehot * jnp.asarray(weights)[None, :]
    return onehot @ m


def reduce_cols_by_key(m, keys, n_keys: int):
    """Sum columns sharing a key (reference: linalg/reduce_cols_by_key.cuh)."""
    m = jnp.asarray(m).astype(jnp.float32)
    keys = jnp.asarray(keys)
    onehot = jax.nn.one_hot(keys, n_keys, dtype=jnp.float32)  # (n_cols, n_keys)
    return m @ onehot


def mean_squared_error(a, b, weight: float = 1.0):
    """Reference: linalg/mean_squared_error.cuh."""
    a = jnp.asarray(a).astype(jnp.float32)
    b = jnp.asarray(b).astype(jnp.float32)
    return weight * jnp.mean(jnp.square(a - b))


def matrix_vector_op(m, vec, op, along_rows: bool = True):
    """Broadcast a vector against matrix lines (reference:
    linalg/matrix_vector_op.cuh). ``along_rows=True`` applies vec[j] to
    column j of every row."""
    m = jnp.asarray(m)
    vec = jnp.asarray(vec)
    if along_rows:
        expects(vec.shape[0] == m.shape[1], "vector must have len n_cols")
        return op(m, vec[None, :])
    expects(vec.shape[0] == m.shape[0], "vector must have len n_rows")
    return op(m, vec[:, None])
