"""raft_tpu.linalg — raft/linalg (P1-P6). Under construction."""
