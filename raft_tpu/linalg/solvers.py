"""Dense decompositions and solvers.

Re-design of the reference's cuSOLVER-backed layer (cpp/include/raft/linalg/:
eig.cuh (syevd/jacobi), qr.cuh, svd.cuh, rsvd.cuh (randomized), lstsq.cuh,
cholesky_r1_update.cuh). XLA provides eigh/qr/svd natively on TPU; rsvd keeps
the reference's randomized-projection structure (the part worth keeping — it
turns a (m, n) SVD into a (m, k) GEMM pipeline that rides the MXU).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.errors import expects
from ..random.rng import as_key

__all__ = ["eig_dc", "eigh", "qr", "svd", "rsvd", "lstsq", "cholesky_r1_update"]


def eigh(a):
    """Symmetric eigendecomposition, ascending eigenvalues (reference:
    linalg/eig.cuh eigDC — cusolver syevd). Returns (eigenvalues, eigenvectors)."""
    w, v = jnp.linalg.eigh(jnp.asarray(a))
    return w, v


eig_dc = eigh


def qr(a):
    """Thin QR (reference: linalg/qr.cuh qrGetQR). Returns (Q, R)."""
    return jnp.linalg.qr(jnp.asarray(a), mode="reduced")


def svd(a, full_matrices: bool = False):
    """SVD (reference: linalg/svd.cuh svdQR). Returns (U, S, Vᵀ rows as V^T)."""
    return jnp.linalg.svd(jnp.asarray(a), full_matrices=full_matrices)


def rsvd(a, k: int, p: int = 10, n_iter: int = 2, seed=0):
    """Randomized truncated SVD (reference: linalg/rsvd.cuh).

    Projection sketch + power iterations + small exact SVD — the standard
    Halko-Martinsson-Tropp scheme the reference implements with cuBLAS GEMMs;
    here every step is an MXU matmul.
    Returns (U (m, k), S (k,), Vt (k, n)).
    """
    a = jnp.asarray(a).astype(jnp.float32)
    m, n = a.shape
    l = min(k + p, n)
    omega = jax.random.normal(as_key(seed), (n, l), dtype=a.dtype)
    y = a @ omega
    q, _ = jnp.linalg.qr(y)
    for _ in range(n_iter):
        q, _ = jnp.linalg.qr(a.T @ q)
        q, _ = jnp.linalg.qr(a @ q)
    b = q.T @ a  # (l, n)
    ub, s, vt = jnp.linalg.svd(b, full_matrices=False)
    return (q @ ub)[:, :k], s[:k], vt[:k]


def lstsq(a, b):
    """Least-squares solve min‖Ax - b‖ (reference: linalg/lstsq.cuh lstsqEig —
    solves the normal equations via eigendecomposition; here QR for stability)."""
    a = jnp.asarray(a).astype(jnp.float32)
    b = jnp.asarray(b).astype(jnp.float32)
    return jnp.linalg.lstsq(a, b)[0]


def cholesky_r1_update(l, x, uplo_lower: bool = True):
    """Rank-1 Cholesky update: given L with A = L·Lᵀ, return L' with
    A + x·xᵀ = L'·L'ᵀ (reference: linalg/cholesky_r1_update.cuh).

    Uses the classic Givens-style scan; the sequential dependency over columns
    is a lax.fori_loop — O(n) steps of O(n) vector work, matching the
    algorithm's intrinsic critical path.
    """
    l = jnp.asarray(l).astype(jnp.float32)
    x = jnp.asarray(x).astype(jnp.float32).copy()
    n = l.shape[0]
    expects(l.shape == (n, n) and x.shape == (n,), "L must be (n,n), x (n,)")
    if not uplo_lower:
        l = l.T

    def body(k, carry):
        lmat, xv = carry
        lkk = lmat[k, k]
        xk = xv[k]
        r = jnp.sqrt(lkk * lkk + xk * xk)
        c = r / lkk
        s = xk / lkk
        col = lmat[:, k]
        mask = jnp.arange(n) > k
        new_col = jnp.where(mask, (col + s * xv) / c, col)
        new_col = new_col.at[k].set(r)
        xv = jnp.where(mask, c * xv - s * new_col, xv)
        return lmat.at[:, k].set(new_col), xv

    l_out, _ = jax.lax.fori_loop(0, n, body, (l, x))
    return l_out if uplo_lower else l_out.T
