"""raft_tpu.utils — small helpers (reference: raft/util residue; most of
that toolkit — warp primitives, vectorized IO, Pow2 — dissolves into XLA)."""
