"""raft_tpu.utils — misc helpers (ref: raft/util residue). Under construction."""
