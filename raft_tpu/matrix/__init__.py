"""raft_tpu.matrix — matrix utilities + top-k selection.

Reference: cpp/include/raft/matrix/ (L3, P7/P8).
"""

from .ops import (
    argmax,
    argmin,
    col_wise_sort,
    copy,
    eye,
    fill,
    gather,
    gather_if,
    get_diagonal,
    linewise_op,
    lower_triangular,
    reverse,
    set_diagonal,
    sign_flip,
    slice,
    upper_triangular,
)
from .select_k import select_k

__all__ = [
    "select_k",
    "argmax",
    "argmin",
    "gather",
    "gather_if",
    "slice",
    "copy",
    "fill",
    "eye",
    "linewise_op",
    "col_wise_sort",
    "reverse",
    "sign_flip",
    "upper_triangular",
    "lower_triangular",
    "get_diagonal",
    "set_diagonal",
]
