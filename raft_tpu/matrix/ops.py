"""Dense matrix utilities.

Re-design of the reference's raft::matrix toolbox (cpp/include/raft/matrix/:
argmax.cuh, argmin.cuh, gather.cuh, slice.cuh, copy.cuh, init.cuh,
linewise_op.cuh, col_wise_sort.cuh, reverse.cuh, sign_flip.cuh,
triangular.cuh, diagonal.cuh). Most entries are one-liner XLA compositions —
they exist to give reference users a familiar, named surface; XLA fuses them
into neighbors at compile time.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.errors import expects

__all__ = [
    "argmax",
    "argmin",
    "gather",
    "gather_if",
    "slice",
    "copy",
    "fill",
    "eye",
    "linewise_op",
    "col_wise_sort",
    "reverse",
    "sign_flip",
    "upper_triangular",
    "lower_triangular",
    "get_diagonal",
    "set_diagonal",
]


def argmax(m):
    """Row-wise argmax (reference: matrix/argmax.cuh)."""
    return jnp.argmax(jnp.asarray(m), axis=1).astype(jnp.int32)


def argmin(m):
    """Row-wise argmin (reference: matrix/argmin.cuh)."""
    return jnp.argmin(jnp.asarray(m), axis=1).astype(jnp.int32)


def gather(m, row_ids):
    """Gather rows by index (reference: matrix/gather.cuh)."""
    return jnp.take(jnp.asarray(m), jnp.asarray(row_ids), axis=0)


def gather_if(m, row_ids, mask, fill_value=0):
    """Gather rows where ``mask`` holds, else a fill row (reference: gatherIf)."""
    out = gather(m, row_ids)
    return jnp.where(jnp.asarray(mask)[:, None], out, fill_value)


def slice(m, row_start, row_end, col_start=0, col_end=None):  # noqa: A001 (ref name)
    """Submatrix copy (reference: matrix/slice.cuh)."""
    m = jnp.asarray(m)
    col_end = m.shape[1] if col_end is None else col_end
    return m[row_start:row_end, col_start:col_end]


def copy(m):
    """Materialized copy (reference: matrix/copy.cuh)."""
    return jnp.array(jnp.asarray(m), copy=True)


def fill(shape, value, dtype=jnp.float32):
    """Constant-initialized matrix (reference: matrix/init.cuh)."""
    return jnp.full(shape, value, dtype=dtype)


def eye(n, dtype=jnp.float32):
    return jnp.eye(n, dtype=dtype)


def linewise_op(m, vec, along_rows: bool, op):
    """Broadcast a vector op along rows or columns (reference: matrix/linewise_op.cuh;
    the linalg matrix_vector_op in its matrix form)."""
    m = jnp.asarray(m)
    vec = jnp.asarray(vec)
    if along_rows:
        expects(vec.shape[0] == m.shape[1], "row-wise vector must have len n_cols")
        return op(m, vec[None, :])
    expects(vec.shape[0] == m.shape[0], "col-wise vector must have len n_rows")
    return op(m, vec[:, None])


def col_wise_sort(m, ascending: bool = True):
    """Sort each row's entries (reference: matrix/col_wise_sort.cuh — CUB
    segmented sort; here one fused XLA sort). Returns (sorted, source_indices).
    Descending order reverses the ascending sort (no negation, so unsigned and
    boolean dtypes sort correctly)."""
    m = jnp.asarray(m)
    order = jnp.argsort(m, axis=1, stable=True)
    if not ascending:
        order = order[:, ::-1]
    return jnp.take_along_axis(m, order, axis=1), order.astype(jnp.int32)


def reverse(m, along_rows: bool = True):
    """Reverse entries within each row (``along_rows=True``, the reference's
    col_reverse — column order swaps) or within each column (row order swaps,
    row_reverse) (reference: matrix/reverse.cuh)."""
    return jnp.flip(jnp.asarray(m), axis=1 if along_rows else 0)


def sign_flip(m):
    """Flip each column's sign so its max-|.| entry is positive — SVD/eig sign
    canonicalization (reference: matrix/detail/math.cuh signFlip)."""
    m = jnp.asarray(m)
    piv = jnp.take_along_axis(m, jnp.argmax(jnp.abs(m), axis=0)[None, :], axis=0)
    return m * jnp.where(piv < 0, -1.0, 1.0)


def upper_triangular(m):
    """Reference: matrix/triangular.cuh."""
    return jnp.triu(jnp.asarray(m))


def lower_triangular(m):
    return jnp.tril(jnp.asarray(m))


def get_diagonal(m):
    """Reference: matrix/diagonal.cuh."""
    return jnp.diagonal(jnp.asarray(m))


def set_diagonal(m, d):
    m = jnp.asarray(m)
    n = min(m.shape)
    idx = jnp.arange(n)
    return m.at[idx, idx].set(jnp.asarray(d)[:n])
