"""Batched top-k selection with index payloads.

Re-design of the reference's select_k (cpp/include/raft/matrix/select_k.cuh;
two CUDA algorithms — 11-bit radix filter detail/select_radix.cuh and warp
bitonic queues detail/select_warpsort.cuh — picked by a learned heuristic,
detail/select_k-inl.cuh:46). The TPU mirror of that two-algorithm split:
XLA's native TopK (`lax.top_k`, a tuned sort) below ~64k columns, and the
threshold-gated streaming Pallas selector (raft_tpu.ops.topk_pallas, one HBM
pass) for wide rows with k <= 256 (r06 lift). The payload
(caller-provided source indices, used when merging per-shard candidate lists)
is carried by gathering with the top-k permutation.
"""

from __future__ import annotations

from ..config import auto_convert_output

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..core.errors import expects
from ..obs.instrument import instrument, nrows

__all__ = ["select_k", "set_wide_cols_threshold", "wide_cols_threshold"]

# Widest k the TPU streaming selector is dispatched for — MUST equal
# ops.topk.TOPK_MAX_K (pinned by tests/test_matrix.py::test_select_k_dispatch
# _cap_matches_kernel_limit so neither can silently drift). History: r05
# capped dispatch at 128 because two kh=256 kernel instances in one XLA
# program hit a TPU-internal Mosaic error; r06's half-width merge
# (ops/topk.py wide_merge="half") keeps every merge intermediate <= kh lanes
# and lifts the cap to the kernel's full 256. RAFT_TPU_WIDE_SELECT_CAP can
# re-impose a lower cap at runtime (e.g. =128) if a future toolchain
# regresses — the escape hatch the repro harness (bench/topk_chain_repro.py)
# documents.
SELECT_K_DISPATCH_MAX_K = 256


# Column width above which the streaming selector wins over lax.top_k
# (measured at r05: parity below ~64k cols, 1.3x at 100k). A parked
# conservative guess until a TPU run moves it — which is why it is now a
# TUNABLE: raft_tpu.tune.sweep_select_k measures the crossover and
# tune.apply_global pins it here (or set RAFT_TPU_WIDE_SELECT_COLS).
WIDE_SELECT_COLS_DEFAULT = 65536

_wide_cols_override: int | None = None


def set_wide_cols_threshold(n: int | None) -> None:
    """Pin (or with None, reset) the wide-select column threshold — the
    application point of a ``select_k`` tune decision
    (:func:`raft_tpu.tune.apply_global`). Read at TRACE time: programs
    already compiled for a shape keep the dispatch they traced with."""
    global _wide_cols_override
    expects(n is None or int(n) >= 1,
            "wide-select threshold must be >= 1 columns, got %r", n)
    _wide_cols_override = None if n is None else int(n)


def wide_cols_threshold() -> int:
    """The live wide-select column threshold: a :func:`set_wide_cols_
    threshold` pin, else RAFT_TPU_WIDE_SELECT_COLS, else the measured
    65536-column default."""
    import os

    if _wide_cols_override is not None:
        return _wide_cols_override
    env = os.environ.get("RAFT_TPU_WIDE_SELECT_COLS")
    if not env:
        return WIDE_SELECT_COLS_DEFAULT
    try:
        return int(env)
    except ValueError:
        raise ValueError(
            f"RAFT_TPU_WIDE_SELECT_COLS must be an integer, got {env!r}")


def _dispatch_cap() -> int:
    # Read at TRACE time: programs already compiled for a shape keep the
    # dispatch they traced with — apply the escape hatch in a fresh process
    # (or before the first search of a shape), not mid-flight.
    import os

    cap = os.environ.get("RAFT_TPU_WIDE_SELECT_CAP")
    if not cap:
        return SELECT_K_DISPATCH_MAX_K
    try:
        return min(int(cap), SELECT_K_DISPATCH_MAX_K)
    except ValueError:
        raise ValueError(
            f"RAFT_TPU_WIDE_SELECT_CAP must be an integer, got {cap!r}")


def wide_dispatch_ok(n: int, k: int, dtype, backend: str | None = None) -> bool:
    """True when (n, k, dtype) is in the streaming Pallas selector's measured
    win regime on the given backend (default: the ambient one). The single
    definition of the dispatch rule — used by :func:`select_k` and by the
    in-jit routed selects inside ivf_pq's scan (the CAGRA build chunk's
    k=gpu_top_k+1 select reaches the kernel through this same predicate).
    The column threshold is tunable (see :func:`wide_cols_threshold`)."""
    if backend is None:
        backend = jax.default_backend()
    return (backend == "tpu" and n >= wide_cols_threshold()
            and 0 < k <= _dispatch_cap()
            and dtype in (jnp.float32, jnp.bfloat16, jnp.float16))


def select_k_impl(values, in_idx, k: int, select_min: bool,
                  impl: str = "auto"):
    """In-jit routed top-k: the trace-time dispatch between lax.top_k and the
    streaming Pallas selector, callable from inside jitted pipelines (no
    nested-jit re-dispatch; shapes are static at trace time).

    ``impl``: "auto" applies :func:`wide_dispatch_ok`; "xla" forces
    lax.top_k; "pallas" forces the Pallas kernel (float inputs only — the
    kernel ranks after an f32 cast) and is the A/B lever
    ``bench/cagra_build_select_ab.py`` uses at the CAGRA build-chunk shapes.
    """
    expects(impl in ("auto", "xla", "pallas"),
            "select impl must be 'auto', 'xla' or 'pallas', got %r", impl)
    n = values.shape[1]
    use_pallas = (impl == "pallas" or
                  (impl == "auto" and wide_dispatch_ok(n, k, values.dtype)))
    if use_pallas:
        expects(values.dtype in (jnp.float32, jnp.bfloat16, jnp.float16),
                "the Pallas selector ranks after an f32 cast; integer or "
                "f64 values (%s) need the exact lax.top_k path "
                "(same restriction as the public select_k dispatch)",
                values.dtype)
        from ..ops.topk import topk_pallas

        out_v, pos = topk_pallas(values, int(k), select_min=bool(select_min))
        out_i = (pos if in_idx is None
                 else jnp.take_along_axis(in_idx, pos, axis=1))
        return out_v, out_i.astype(jnp.int32)
    return _select_k(values, in_idx, int(k), bool(select_min))


@functools.partial(jax.jit, static_argnames=("k", "select_min"))
def _select_k(values, in_idx, k: int, select_min: bool):
    if jnp.issubdtype(values.dtype, jnp.integer):
        # integer scores (e.g. exact int32 distances from the s8 MXU search
        # paths): ~v is the wrap-free order flip in the SAME dtype for both
        # families (unsigned: max - v; signed: -v - 1 — unlike negation,
        # which wraps at INT_MIN, and unlike a python-int `max - v`, which
        # overflows the weak-typed i32 scalar path for uint32). Output
        # values are gathered from the input, so they keep the caller's
        # dtype and exact magnitudes.
        key = ~values if select_min else values
        _, top_i = lax.top_k(key, k)
        top_v = jnp.take_along_axis(values, top_i, axis=1)
        if in_idx is not None:
            top_i = jnp.take_along_axis(in_idx, top_i, axis=1)
        return top_v, top_i.astype(jnp.int32)
    v = -values if select_min else values
    top_v, top_i = lax.top_k(v, k)  # ties resolved by lowest index, like the ref
    if select_min:
        top_v = -top_v
    if in_idx is not None:
        top_i = jnp.take_along_axis(in_idx, top_i, axis=1)
    return top_v, top_i.astype(jnp.int32)


@instrument("matrix.select_k",
            items=lambda a, kw: nrows(a[0] if a else kw["values"]),
            labels=lambda a, kw: {"k": a[1] if len(a) > 1 else kw["k"]})
@auto_convert_output
def select_k(values, k: int, select_min: bool = True, indices=None):
    """Select the k smallest (or largest) entries per row, with their indices.

    Reference: raft::matrix::select_k (matrix/select_k.cuh) and the pylibraft
    binding (matrix/select_k.pyx). ``indices`` optionally supplies the payload
    ids of each column (shape == values.shape); by default the column offsets
    0..n-1 are returned — exactly the reference's in_idx=nullopt behavior.

    Returns ``(out_values (m, k), out_indices (m, k) int32)``.
    """
    values = jnp.asarray(values)
    expects(values.ndim == 2, "select_k expects a 2-D (batch, n) matrix")
    n = values.shape[1]
    expects(0 < k <= n, "k=%d must be in (0, n=%d]", k, n)
    if indices is not None:
        indices = jnp.asarray(indices)
        expects(indices.shape == values.shape, "indices payload must match values shape")
    # Wide rows on TPU: the streaming Pallas selector (ops/topk.py) reads the
    # matrix once vs the TopK custom call's ~3 sort passes — measured 1.3x at
    # (1000, 100k) k=10 (18.3 vs 23.8 ms/iter chained); parity below ~64k
    # columns, so the dispatch stays conservative. Restricted to <=32-bit
    # floats: the kernel ranks after an f32 cast, so under jax_enable_x64 a
    # float64 row whose entries differ only beyond f32 precision would be
    # silently misranked vs the exact lax.top_k path.
    # k <= 64 is the r05-measured narrow path; 64 < k <= 256 is the
    # bitonic-merge wide path (ops/topk.py) — 3.06x lax.top_k at (10k, 65k)
    # k=128, 1.5-1.7x at k=193/256 in-process (BASELINE.md "Round-5 wide-k
    # selector study"). r05 capped dispatch at 128 (two kh=256 instances per
    # program hit a Mosaic error); r06's half-width merge lifts the cap to
    # the kernel's full 256 (SELECT_K_DISPATCH_MAX_K above has the history
    # and the RAFT_TPU_WIDE_SELECT_CAP escape hatch).
    # Integer values (exact int32 scores from the s8 search paths, uint8
    # payload matrices, ...) stay on the lax.top_k path: the Pallas
    # selector ranks after an f32 cast, which would misrank int32 values
    # differing only beyond 2^24; _select_k handles them exactly.
    if (not jnp.issubdtype(values.dtype, jnp.integer)
            and wide_dispatch_ok(n, int(k), values.dtype)):
        from ..ops.topk import topk_pallas

        out_v, pos = topk_pallas(values, int(k), select_min=bool(select_min))
        out_i = (pos if indices is None
                 else jnp.take_along_axis(indices, pos, axis=1))
        return out_v, out_i.astype(jnp.int32)
    return _select_k(values, indices, int(k), bool(select_min))
