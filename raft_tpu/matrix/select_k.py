"""Batched top-k selection with index payloads.

Re-design of the reference's select_k (cpp/include/raft/matrix/select_k.cuh;
two CUDA algorithms — 11-bit radix filter detail/select_radix.cuh and warp
bitonic queues detail/select_warpsort.cuh — picked by a learned heuristic,
detail/select_k-inl.cuh:46). The TPU mirror of that two-algorithm split:
XLA's native TopK (`lax.top_k`, a tuned sort) below ~64k columns, and the
threshold-gated streaming Pallas selector (raft_tpu.ops.topk_pallas, one HBM
pass) for wide rows with k <= 64. The payload
(caller-provided source indices, used when merging per-shard candidate lists)
is carried by gathering with the top-k permutation.
"""

from __future__ import annotations

from ..config import auto_convert_output

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..core.errors import expects
from ..obs.instrument import instrument, nrows

__all__ = ["select_k"]


@functools.partial(jax.jit, static_argnames=("k", "select_min"))
def _select_k(values, in_idx, k: int, select_min: bool):
    if jnp.issubdtype(values.dtype, jnp.integer):
        # integer scores (e.g. exact int32 distances from the s8 MXU search
        # paths): ~v is the wrap-free order flip in the SAME dtype for both
        # families (unsigned: max - v; signed: -v - 1 — unlike negation,
        # which wraps at INT_MIN, and unlike a python-int `max - v`, which
        # overflows the weak-typed i32 scalar path for uint32). Output
        # values are gathered from the input, so they keep the caller's
        # dtype and exact magnitudes.
        key = ~values if select_min else values
        _, top_i = lax.top_k(key, k)
        top_v = jnp.take_along_axis(values, top_i, axis=1)
        if in_idx is not None:
            top_i = jnp.take_along_axis(in_idx, top_i, axis=1)
        return top_v, top_i.astype(jnp.int32)
    v = -values if select_min else values
    top_v, top_i = lax.top_k(v, k)  # ties resolved by lowest index, like the ref
    if select_min:
        top_v = -top_v
    if in_idx is not None:
        top_i = jnp.take_along_axis(in_idx, top_i, axis=1)
    return top_v, top_i.astype(jnp.int32)


@instrument("matrix.select_k",
            items=lambda a, kw: nrows(a[0] if a else kw["values"]),
            labels=lambda a, kw: {"k": a[1] if len(a) > 1 else kw["k"]})
@auto_convert_output
def select_k(values, k: int, select_min: bool = True, indices=None):
    """Select the k smallest (or largest) entries per row, with their indices.

    Reference: raft::matrix::select_k (matrix/select_k.cuh) and the pylibraft
    binding (matrix/select_k.pyx). ``indices`` optionally supplies the payload
    ids of each column (shape == values.shape); by default the column offsets
    0..n-1 are returned — exactly the reference's in_idx=nullopt behavior.

    Returns ``(out_values (m, k), out_indices (m, k) int32)``.
    """
    values = jnp.asarray(values)
    expects(values.ndim == 2, "select_k expects a 2-D (batch, n) matrix")
    n = values.shape[1]
    expects(0 < k <= n, "k=%d must be in (0, n=%d]", k, n)
    if indices is not None:
        indices = jnp.asarray(indices)
        expects(indices.shape == values.shape, "indices payload must match values shape")
    # Wide rows on TPU: the streaming Pallas selector (ops/topk.py) reads the
    # matrix once vs the TopK custom call's ~3 sort passes — measured 1.3x at
    # (1000, 100k) k=10 (18.3 vs 23.8 ms/iter chained); parity below ~64k
    # columns, so the dispatch stays conservative. Restricted to <=32-bit
    # floats: the kernel ranks after an f32 cast, so under jax_enable_x64 a
    # float64 row whose entries differ only beyond f32 precision would be
    # silently misranked vs the exact lax.top_k path.
    # k <= 128 includes the r05 bitonic-merge wide path (ops/topk.py),
    # measured 3.06x lax.top_k at (10k, 65k) k=128 in-process
    # (BASELINE.md "Round-5 wide-k selector study"). 128 < k <= 256 also
    # measured ahead (1.5-1.7x) but is NOT dispatched: two kh=256 kernel
    # instances inside one XLA program hit a TPU-internal error (standalone
    # calls are fine — callers can invoke ops.topk_pallas directly), and
    # this dispatch can be embedded anywhere.
    # Integer values (exact int32 scores from the s8 search paths, uint8
    # payload matrices, ...) also stay on the lax.top_k path: the Pallas
    # selector ranks after an f32 cast, which would misrank int32 values
    # differing only beyond 2^24; _select_k handles them exactly.
    if (jax.default_backend() == "tpu" and n >= 65536 and 0 < k <= 128
            and values.dtype in (jnp.float32, jnp.bfloat16, jnp.float16)):
        from ..ops.topk import topk_pallas

        out_v, pos = topk_pallas(values, int(k), select_min=bool(select_min))
        out_i = (pos if indices is None
                 else jnp.take_along_axis(indices, pos, axis=1))
        return out_v, out_i.astype(jnp.int32)
    return _select_k(values, indices, int(k), bool(select_min))
