"""Shared constructor for the ``batched_searcher`` serving hooks.

Every index module exposes ``batched_searcher(index, params) -> fn`` where
``fn(queries, k) -> (distances, ids)`` carries ``kind``/``dim``/
``query_dtype`` attributes — the stable surface :mod:`raft_tpu.serve`
dispatches, warms, and hot-swaps through. The hook CONTRACT lives here in
one place (attribute set, byte-dtype rule); the per-module functions only
supply the search closure, so a contract change cannot silently miss one
index kind.
"""

from __future__ import annotations

from typing import Callable

__all__ = ["make_hook"]


def make_hook(search_fn: Callable, kind: str, dim: int,
              data_kind: str = "float32") -> Callable:
    """Wrap ``search_fn(queries, k)`` as a serving hook. ``data_kind`` is
    the index's storage contract: byte indexes ("int8"/"uint8") serve byte
    queries of the SAME dtype (serve warmup draws them that way, so the s8
    programs compile exactly as production runs them); everything else
    serves float32."""

    def fn(queries, k):
        return search_fn(queries, k)

    fn.kind = kind
    fn.dim = int(dim)
    fn.query_dtype = data_kind if data_kind in ("int8", "uint8") else "float32"
    return fn
