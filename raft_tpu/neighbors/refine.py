"""Exact re-ranking of ANN candidate lists.

Re-design of the reference's refine (cpp/include/raft/neighbors/refine.cuh;
detail/refine.cuh refine_device :80 / refine_host :169). Gather each query's
candidate vectors, compute exact distances, keep the best k — one batched
gather + one batched distance contraction on TPU, no per-query kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..core.errors import expects
from ..core.resources import Resources, default_resources
from ..distance.types import DistanceType, resolve_metric

__all__ = ["refine", "refine_gathered"]


def _score_candidates(cand_vecs, queries, candidates, k: int,
                      metric: DistanceType):
    """Exact re-rank of PRE-GATHERED candidate rows — the scoring body
    shared (traced, not called) by both jitted entry points, so the
    all-HBM gather-inside-jit path and the tiered host-gather path run
    the IDENTICAL scoring program (the tiered-vs-HBM bit-parity contract
    rides on it)."""
    valid = candidates >= 0  # negative ids = padding slots
    q = queries[:, None, :].astype(jnp.float32)
    c = cand_vecs.astype(jnp.float32)
    if metric == DistanceType.InnerProduct:
        scores = jnp.einsum("mkd,mod->mk", c, q)
        scores = jnp.where(valid, scores, -jnp.inf)
        top_v, top_pos = lax.top_k(scores, k)
    else:
        d2 = jnp.sum(jnp.square(c - q), axis=-1)  # (m, k0)
        if metric in (DistanceType.L2SqrtExpanded, DistanceType.L2SqrtUnexpanded):
            d2 = jnp.sqrt(jnp.maximum(d2, 0.0))
        d2 = jnp.where(valid, d2, jnp.inf)
        top_v, top_pos = lax.top_k(-d2, k)
        top_v = -top_v
    ids = jnp.where(
        jnp.take_along_axis(valid, top_pos, axis=1),
        jnp.take_along_axis(candidates, top_pos, axis=1),
        -1,
    )
    return top_v, ids.astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("k", "metric"))
def _refine(dataset, queries, candidates, k: int, metric: DistanceType):
    safe = jnp.maximum(candidates, 0)
    cand_vecs = jnp.take(dataset, safe, axis=0)  # (m, k0, d)
    return _score_candidates(cand_vecs, queries, candidates, k, metric)


@functools.partial(jax.jit, static_argnames=("k", "metric"))
def _refine_gathered(cand_vecs, queries, candidates, k: int,
                     metric: DistanceType):
    return _score_candidates(cand_vecs, queries, candidates, k, metric)


def refine(dataset, queries, candidates, k: int, metric="sqeuclidean", res: Resources | None = None):
    """Re-rank ``candidates`` (m, k0) by exact distance; return the top
    ``k <= k0`` (reference: neighbors/refine.cuh, pylibraft
    neighbors/refine.pyx). Negative candidate ids are treated as padding:
    they sort last (distance ±inf) and surface as id -1."""
    res = res or default_resources()
    dataset = jnp.asarray(dataset)
    queries = jnp.asarray(queries)
    candidates = jnp.asarray(candidates).astype(jnp.int32)
    expects(candidates.ndim == 2 and candidates.shape[0] == queries.shape[0],
            "candidates must be (n_queries, k0)")
    expects(k <= candidates.shape[1], "k must be <= candidate width")
    mt = resolve_metric(metric)
    return _refine(dataset, queries, candidates, int(k), mt)


def refine_gathered(cand_vecs, queries, candidates, k: int,
                    metric="sqeuclidean"):
    """:func:`refine` over candidate rows ALREADY gathered to the device
    — the tiered-storage refine epilogue: the (m, k0, d) ``cand_vecs``
    arrive through :meth:`raft_tpu.stream.tiered.TieredStore.fetch`'s
    double-buffered host→device hop (or a device-mirror gather), and this
    runs exactly the scoring program :func:`refine` traces after its own
    in-jit gather — same k0 candidates, bit-identical distances. Negative
    ``candidates`` are padding: their (arbitrary) gathered row is masked,
    sorts last, and surfaces as id ``-1``."""
    queries = jnp.asarray(queries)
    cand_vecs = jnp.asarray(cand_vecs)
    candidates = jnp.asarray(candidates).astype(jnp.int32)
    expects(candidates.ndim == 2 and candidates.shape[0] == queries.shape[0],
            "candidates must be (n_queries, k0)")
    expects(cand_vecs.shape[:2] == candidates.shape,
            "cand_vecs must be (n_queries, k0, d) matching candidates")
    expects(k <= candidates.shape[1], "k must be <= candidate width")
    return _refine_gathered(cand_vecs, queries, candidates, int(k),
                            resolve_metric(metric))
