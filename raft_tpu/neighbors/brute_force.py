"""Brute-force (exact) k-nearest neighbors.

Re-design of the reference's tiled brute-force kNN
(cpp/include/raft/neighbors/brute_force.cuh; detail/knn_brute_force.cuh:
memory-aware tile sizing chooseTileSize :78, per-tile select + merge :232-273,
knn_merge_parts detail/knn_merge_parts.cuh). TPU shape: queries are processed
in row tiles under lax.map — each tile is one MXU distance GEMM fused with
top-k — so the (n_queries, n_dataset) matrix never materializes. Per-shard
results merge with one select_k over concatenated candidates, the same merge
the reference runs after stream-pool multi-probe (knn_brute_force.cuh:490).
"""

from __future__ import annotations

from ..config import auto_convert_output

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..core.errors import expects
from ..core.resources import Resources, default_resources
from ..distance.pairwise import _PRECISIONS, _choose_tile, _pairwise, _pad_to_tiles
from ..distance.types import DistanceType, resolve_metric
from ..matrix.select_k import select_k
from ..obs import mem as obs_mem
from ..obs.instrument import dtype_of, instrument, nrows

__all__ = ["knn", "knn_merge_parts", "BruteForce"]

# metrics the fused Pallas kernel handles natively (ops/fused_knn.py);
# everything else stays on the XLA GEMM + top_k path
_FUSED_L2 = {
    DistanceType.L2Expanded: False,
    DistanceType.L2SqrtExpanded: True,
    DistanceType.L2Unexpanded: False,
    DistanceType.L2SqrtUnexpanded: True,
}


def _as_signed(x):
    """uint8 -> int8 by the -128 shift (L2-invariant; ip callers correct via
    row_bias); int8 passes through. The reference instantiates int8_t and
    uint8_t kernels separately (cpp/src/neighbors/*_int8_t_*.cu /
    *_uint8_t_*.cu); on TPU the MXU's integer path is s8 x s8, so uint8
    rides the same kernel shifted."""
    if x.dtype == jnp.uint8:
        return (x.astype(jnp.int16) - 128).astype(jnp.int8)
    return x


def _coerce_queries(data_kind: str, queries):
    """Move queries into a byte index's storage domain — the search-side
    half of the _as_signed contract, shared by every index type
    (ivf_flat/ivf_pq/cagra, single-chip and distributed): integer queries
    must match the index's original dtype and shift with it; float queries
    against a shifted-uint8 index shift by -128 (L2-invariant)."""
    if data_kind not in ("int8", "uint8"):
        return queries
    if queries.dtype in (jnp.dtype(jnp.int8), jnp.dtype(jnp.uint8)):
        expects(str(queries.dtype) == data_kind,
                "this index stores %s vectors; got %s queries",
                data_kind, queries.dtype)
        return _as_signed(queries).astype(jnp.float32)
    if data_kind == "uint8":
        return queries.astype(jnp.float32) - 128.0
    return queries


def _bf_knn_s8(dataset, queries, k, metric, keep_mask):
    """int8 MXU dispatch (~2x bf16 peak, 1-byte operand DMAs). Distances are
    EXACT integers for d <= ~340 (see ops/fused_knn mode='s8')."""
    from ..ops.fused_knn import fused_backend_ok, fused_knn

    _, interpret = fused_backend_ok()
    shifted = dataset.dtype == jnp.uint8
    ds = _as_signed(dataset)
    qs = _as_signed(queries)
    if metric in _FUSED_L2:
        return fused_knn(ds, qs, k, metric="l2", mode="s8",
                         keep_mask=keep_mask, sqrt=_FUSED_L2[metric],
                         interpret=interpret)
    # inner product: q·v = q'·v' + 128·Σv' + 128·Σq' + 128²·d for shifted
    # operands — the Σv' term rides the kernel's row-bias operand, the
    # per-query constant is added outside
    if not shifted:
        return fused_knn(ds, qs, k, metric="ip", mode="s8",
                         keep_mask=keep_mask, interpret=interpret)
    d = dataset.shape[1]
    row_bias = -128.0 * jnp.sum(ds.astype(jnp.float32), axis=1)
    sim, idx = fused_knn(ds, qs, k, metric="ip", mode="s8",
                         keep_mask=keep_mask, row_bias=row_bias,
                         interpret=interpret)
    qconst = (128.0 * jnp.sum(qs.astype(jnp.float32), axis=1, keepdims=True)
              + 16384.0 * d)
    return jnp.where(jnp.isinf(sim), sim, sim + qconst), idx


def _fused_eligible(metric, k, n, d, mode, compute):
    from ..ops.fused_knn import fused_backend_ok, shapes_eligible

    backend_ok, _ = fused_backend_ok()
    return (
        backend_ok
        and mode == "exact"
        and compute in ("float32", "float32x3", "bfloat16")
        and shapes_eligible(n, d, k)
        and (metric in _FUSED_L2
             or metric in (DistanceType.InnerProduct, DistanceType.CosineExpanded))
    )


def _bf_knn_fused(dataset, queries, k, metric, compute, keep_mask):
    """Route to the fused Pallas kernel (scores never leave VMEM)."""
    from ..ops.fused_knn import fused_backend_ok, fused_knn

    mode = {"float32": "f32", "float32x3": "f32x3", "bfloat16": "bf16"}[compute]
    _, interpret = fused_backend_ok()
    if metric in _FUSED_L2:
        return fused_knn(dataset, queries, k, metric="l2", mode=mode,
                         keep_mask=keep_mask, sqrt=_FUSED_L2[metric],
                         interpret=interpret)
    if metric == DistanceType.InnerProduct:
        return fused_knn(dataset, queries, k, metric="ip", mode=mode,
                         keep_mask=keep_mask, interpret=interpret)
    # CosineExpanded: 1 - cos = 1 - ip over normalized rows (distance/pairwise
    # _cosine uses the same normalization)
    qn = jnp.linalg.norm(queries.astype(jnp.float32), axis=1, keepdims=True)
    yn = jnp.linalg.norm(dataset.astype(jnp.float32), axis=1, keepdims=True)
    sim, idx = fused_knn(dataset / jnp.maximum(yn, 1e-30),
                         queries / jnp.maximum(qn, 1e-30), k,
                         metric="ip", mode=mode, keep_mask=keep_mask,
                         interpret=interpret)
    dist = jnp.where(jnp.isinf(sim), jnp.inf, 1.0 - sim)
    return dist, idx


@functools.partial(
    jax.jit,
    static_argnames=("k", "metric", "metric_arg", "tile", "inner_tile", "approx", "compute"),
)
def _bf_knn(dataset, queries, k: int, metric: DistanceType, metric_arg: float,
            tile: int, inner_tile: int, keep_mask=None, approx: bool = False,
            compute: str = "float32"):
    m = queries.shape[0]
    n = dataset.shape[0]
    # kNN ordering is identical under expanded vs unexpanded L2, so route the
    # L2 family through the norms+GEMM path (the reference's knn makes the
    # same substitution — knn_brute_force.cuh uses expanded L2 fast paths).
    metric = {
        DistanceType.L2Unexpanded: DistanceType.L2Expanded,
        DistanceType.L2SqrtUnexpanded: DistanceType.L2SqrtExpanded,
    }.get(metric, metric)
    qt, num = _pad_to_tiles(queries, tile)
    select_min = metric != DistanceType.InnerProduct

    def body(qb):
        d = _pairwise(qb, dataset, metric, metric_arg, inner_tile, compute)  # (tile, n)
        if keep_mask is not None:
            # fused predicate filter (ref: neighbors/sample_filter_types.hpp)
            d = jnp.where(keep_mask[None, :], d, jnp.inf if select_min else -jnp.inf)
        if approx:
            # TPU-native PartialReduce selection (lax.approx_*_k): ~2x faster
            # than the exact sort-based TopK at >0.99 expected recall — the
            # TPU counterpart of the reference's recall/QPS trade knobs
            if select_min:
                top_v, top_i = lax.approx_min_k(d, k, recall_target=0.99)
            else:
                top_v, top_i = lax.approx_max_k(d, k, recall_target=0.99)
            return top_v, top_i.astype(jnp.int32)
        v = -d if select_min else d
        top_v, top_i = lax.top_k(v, k)
        return (-top_v if select_min else top_v), top_i.astype(jnp.int32)

    dists, idx = lax.map(body, qt)
    dists = dists.reshape(num * tile, k)[:m]
    idx = idx.reshape(num * tile, k)[:m]
    if keep_mask is not None:
        # when fewer than k rows pass the filter, top_k fills slots with
        # ±inf scores carrying excluded ids — report those as -1
        idx = jnp.where(jnp.isinf(dists), -1, idx)
    return dists, idx


@instrument(
    "brute_force.knn",
    items=lambda a, kw: nrows(a[1] if len(a) > 1 else kw["queries"]),
    labels=lambda a, kw: {
        "dtype": dtype_of(a[0] if a else kw["dataset"]),
        "k": a[2] if len(a) > 2 else kw["k"],
    },
)
@auto_convert_output
def knn(dataset, queries, k: int, metric="sqeuclidean", metric_arg: float = 2.0,
        sample_filter=None, mode: str = "exact", compute: str = "float32",
        res: Resources | None = None):
    """Exact kNN of ``queries`` in ``dataset`` (reference:
    brute_force::knn, neighbors/brute_force.cuh; pylibraft
    neighbors/brute_force.pyx knn). ``sample_filter`` is an optional
    :class:`~raft_tpu.neighbors.sample_filter.BitsetFilter` / boolean keep-mask
    over dataset rows. ``mode``: "exact" (sort-based TopK) or "approx"
    (TPU PartialReduce, ≥0.99 expected recall, ~2x faster). ``compute``:
    "float32" (bit-accurate distances), "float32x3" (compensated bf16x3
    contraction, f32-class accuracy at roughly half the MXU cost; falls back to
    "float32" when the fused kernel is not engaged) or "bfloat16"
    (single-pass MXU contraction — same neighbor ordering in all but
    razor-thin margins, several times the GEMM throughput).

    int8/uint8 datasets are first-class (reference: the int8_t/uint8_t
    brute-force instantiations): integer dataset+query pairs dispatch to the
    s8 x s8 -> s32 MXU kernel (~2x bf16 peak, 1-byte gathers) with EXACT
    integer distances; uint8 rides the same kernel shifted by -128 (L2 is
    shift-invariant, inner products are bias-corrected). ``compute="int8"``
    asserts intent; integer inputs use this path by default.

    On TPU, L2/inner-product/cosine searches with k ≤ 64, n ≥ 4096 and
    64 ≤ d ≤ 4096 dispatch to the fused Pallas kernel (ops/fused_knn.py;
    smaller d would mostly multiply 128-lane padding) — same neighbor sets;
    within-1-ULP distance ties may order differently.
    Returns (distances (m, k), indices (m, k))."""
    from .sample_filter import resolve_filter

    res = res or default_resources()
    dataset = jnp.asarray(dataset)
    queries = jnp.asarray(queries)
    expects(dataset.ndim == 2 and queries.ndim == 2, "inputs must be 2-D")
    expects(dataset.shape[1] == queries.shape[1], "feature dims must match")
    n = dataset.shape[0]
    expects(0 < k <= n, "k=%d must be in (0, n=%d]", k, n)
    expects(mode in ("exact", "approx"), "mode must be 'exact' or 'approx', got %r", mode)
    expects(compute in _PRECISIONS or compute in ("float32x3", "int8"),
            "compute must be one of %s, got %r",
            sorted(_PRECISIONS) + ["float32x3", "int8"], compute)
    mt = resolve_metric(metric)
    keep_mask = resolve_filter(sample_filter)
    if keep_mask is not None:
        expects(keep_mask.shape == (n,), "sample filter must cover all %d dataset rows", n)
    int_dtypes = (jnp.dtype(jnp.int8), jnp.dtype(jnp.uint8))
    expects(compute != "int8"
            or (dataset.dtype in int_dtypes and queries.dtype in int_dtypes),
            "compute='int8' requires int8/uint8 dataset AND queries, got "
            "%s/%s — the s8 MXU path has no meaning for float inputs",
            dataset.dtype, queries.dtype)
    if dataset.dtype in int_dtypes or queries.dtype in int_dtypes:
        # int8/uint8 ingestion (reference: brute_force int8_t/uint8_t
        # instantiations). Integer pairs route to the s8 MXU kernel —
        # distances are exact integers at these dtypes — and anything the
        # kernel can't take (mixed-precision pairs, cosine, tiny shapes,
        # no TPU) falls back to the f32 pipeline, which is also exact for
        # 8-bit integer values.
        if dataset.dtype in int_dtypes and queries.dtype in int_dtypes:
            expects(dataset.dtype == queries.dtype,
                    "int8/uint8 dataset and queries must share a dtype "
                    "(mixing signed and shifted domains is a data error), "
                    "got %s/%s", dataset.dtype, queries.dtype)
            from ..ops.fused_knn import fused_backend_ok, shapes_eligible

            if (mode == "exact" and compute in ("float32", "int8")
                    and (mt in _FUSED_L2 or mt == DistanceType.InnerProduct)
                    and fused_backend_ok()[0]
                    and shapes_eligible(n, dataset.shape[1], int(k))):
                return _bf_knn_s8(dataset, queries, int(k), mt, keep_mask)
        dataset = dataset.astype(jnp.float32)
        queries = queries.astype(jnp.float32)
    if compute == "int8":
        compute = "float32"  # explicit int8 on a non-integer/fallback path
    if _fused_eligible(mt, int(k), n, dataset.shape[1], mode, compute):
        return _bf_knn_fused(dataset, queries, int(k), mt, compute, keep_mask)
    if compute == "float32x3":
        compute = "float32"  # XLA fallback has no compensated mode
    # outer tile bounds the (tile, n) score block; inner tile bounds the
    # elementwise-metric broadcast within _pairwise. This is the
    # Resources.workspace_bytes contract in action (the fused Pallas path
    # above sizes from VMEM instead); the implied transient workspace is
    # recorded so capacity planning can see it (obs.mem, pinned <= the
    # budget by test).
    tile = _choose_tile(queries.shape[0], n, 1, res.workspace_bytes)
    inner_tile = _choose_tile(tile, n, dataset.shape[1], res.workspace_bytes)
    obs_mem.note_workspace(
        "brute_force.knn",
        max(tile * n * 3 * 4,
            inner_tile * n * (dataset.shape[1] + 2) * 4))
    return _bf_knn(dataset, queries, int(k), mt, float(metric_arg), tile, inner_tile,
                   keep_mask, approx=mode == "approx", compute=compute)


def knn_merge_parts(part_dists, part_ids, k: int | None = None, select_min: bool = True):
    """Merge per-shard kNN candidate lists (reference:
    detail/knn_merge_parts.cuh — warp heap merge; here one select_k over the
    concatenated candidates).

    ``part_dists``/``part_ids``: (n_parts, n_queries, k_part) stacked results
    whose ids are already global. Returns merged (dists, ids) of width
    ``k or k_part``.
    """
    part_dists = jnp.asarray(part_dists)
    part_ids = jnp.asarray(part_ids)
    expects(part_dists.ndim == 3, "expected (n_parts, n_queries, k)")
    n_parts, nq, kp = part_dists.shape
    k = kp if k is None else k
    flat_d = jnp.moveaxis(part_dists, 0, 1).reshape(nq, n_parts * kp)
    flat_i = jnp.moveaxis(part_ids, 0, 1).reshape(nq, n_parts * kp)
    return select_k(flat_d, k, select_min=select_min, indices=flat_i)


class BruteForce:
    """Index-style wrapper (reference: brute_force::index,
    neighbors/brute_force_types.hpp — stores the dataset and optional
    precomputed norms)."""

    def __init__(self, metric="sqeuclidean", metric_arg: float = 2.0):
        self.metric = metric
        self.metric_arg = metric_arg
        self.dataset = None
        # pinned operating point (raft_tpu.tune decision dict; None =
        # untuned) — brute force has no search knobs, but the record still
        # rides save/load (raft_tpu/9) so provenance survives uniformly
        self.tuned = None

    def build(self, dataset, res: Resources | None = None):
        # gate BEFORE the device upload ("a refused build spends
        # nothing"): size from the host-side view — for brute force the
        # dataset IS the index. Stored dtype caps at 4 bytes/elt (jax
        # downcasts f64 to f32 at asarray; byte dtypes store natively).
        import numpy as np

        from ..core import chunked

        res = res or default_resources()
        arr = (dataset if hasattr(dataset, "shape")
               and hasattr(dataset, "dtype") else np.asarray(dataset))
        if chunked.is_reader(dataset):
            # out-of-core ingest: the dataset still lands device-whole
            # (it IS the scan operand) but arrives through the staged
            # chunk pipeline — no second full-size host copy. Priced
            # against BOTH budgets before any chunk stages.
            n, d = (int(s) for s in arr.shape)
            pl = obs_mem.plan("brute_force", None, n, d,
                              dtype=str(arr.dtype), streamed=True,
                              chunk_rows=dataset.chunk_rows)
            obs_mem.gate(res, pl["build_peak_bytes"],
                         site="build_stream",
                         host_bytes=pl["host_peak_bytes"],
                         detail=f"brute_force {n}x{d} streamed")
            self.dataset = chunked.device_materialize(dataset,
                                                      kind="brute_force")
        else:
            need = arr.shape[0] * arr.shape[1] * min(arr.dtype.itemsize, 4)
            obs_mem.gate(res, need, site="build",
                         detail=f"brute_force {arr.shape[0]}x{arr.shape[1]}")
            self.dataset = jnp.asarray(dataset)
        obs_mem.account_index(self)  # ledger hook (docs/observability.md)
        return self

    def search(self, queries, k: int, res: Resources | None = None):
        expects(self.dataset is not None, "index is not built")
        return knn(self.dataset, queries, k, self.metric, self.metric_arg, res=res)


def write_index(f, index: BruteForce) -> None:
    """Serialize to an open binary stream (new in raft_tpu/8 — the
    brute-force index is the stream wrapper's simplest sealed kind, so it
    needs the same composable serialization as the ANN indexes; reference:
    brute_force::index stores dataset + metric, brute_force_types.hpp)."""
    from ..core.serialize import (serialize_header, serialize_mdspan,
                                  serialize_scalar, serialize_tuned)

    expects(index.dataset is not None, "index is not built")
    serialize_header(f, "brute_force")
    serialize_scalar(f, int(resolve_metric(index.metric)))
    serialize_scalar(f, float(index.metric_arg))
    serialize_mdspan(f, index.dataset)
    serialize_tuned(f, index.tuned)


def read_index(f) -> BruteForce:
    """Deserialize from an open binary stream (pairs with
    :func:`write_index`)."""
    import jax.numpy as jnp

    from ..core.serialize import (check_header, deserialize_mdspan,
                                  deserialize_scalar, deserialize_tuned)

    ver = check_header(f, "brute_force")
    metric = DistanceType(deserialize_scalar(f))
    metric_arg = float(deserialize_scalar(f))
    idx = BruteForce(metric=metric, metric_arg=metric_arg)
    idx.dataset = jnp.asarray(deserialize_mdspan(f))
    idx.tuned = deserialize_tuned(f, ver)
    return idx


def save(index: BruteForce, path: str) -> None:
    """Serialize atomically (temp file + rename — a crashed save leaves
    the previous file readable; :func:`core.serialize.atomic_write`)."""
    from ..core.serialize import atomic_write

    with atomic_write(path) as f:
        write_index(f, index)


def load(path: str, res: Resources | None = None) -> BruteForce:
    with open(path, "rb") as f:
        return read_index(f)


def batched_searcher(index: BruteForce, params=None):
    """Stable serving hook (raft_tpu.serve; contract in
    :mod:`._hooks`): ``fn(queries, k) -> (distances, ids)`` with
    ``.kind``/``.dim``/``.query_dtype`` attributes. Brute force has no
    search params; ``params`` must be None."""
    from ._hooks import make_hook

    expects(index.dataset is not None, "index is not built")
    expects(params is None, "brute_force has no search params")
    return make_hook(index.search, "brute_force",
                     index.dataset.shape[1], str(index.dataset.dtype))
