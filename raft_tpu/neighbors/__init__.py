"""raft_tpu.neighbors — ANN indexes.

Reference: cpp/include/raft/neighbors/ (L4, N1-N10).
"""

from . import ball_cover, brute_force, cagra, ivf_flat, ivf_pq, sample_filter
from .brute_force import BruteForce, knn, knn_merge_parts
from .epsilon_neighborhood import eps_neighbors_l2sq
from .refine import refine
from .sample_filter import BitsetFilter, NoFilter

__all__ = [
    "brute_force",
    "cagra",
    "ivf_flat",
    "ivf_pq",
    "BruteForce",
    "knn",
    "knn_merge_parts",
    "refine",
    "eps_neighbors_l2sq",
    "ball_cover",
    "sample_filter",
    "BitsetFilter",
    "NoFilter",
]
