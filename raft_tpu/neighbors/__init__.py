"""raft_tpu.neighbors — ANN indexes.

Reference: cpp/include/raft/neighbors/ (L4, N1-N10).
"""

from . import brute_force, cagra, ivf_flat, ivf_pq
from .brute_force import BruteForce, knn, knn_merge_parts
from .refine import refine

__all__ = [
    "brute_force",
    "cagra",
    "ivf_flat",
    "ivf_pq",
    "BruteForce",
    "knn",
    "knn_merge_parts",
    "refine",
]
