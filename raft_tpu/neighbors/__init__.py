"""raft_tpu.neighbors — raft/neighbors (N1-N10). Under construction."""
