"""raft_tpu.neighbors — ANN indexes: brute-force, refine; IVF-Flat, IVF-PQ,
CAGRA, ball cover follow.

Reference: cpp/include/raft/neighbors/ (L4, N1-N10).
"""

from . import brute_force
from .brute_force import BruteForce, knn, knn_merge_parts
from .refine import refine

__all__ = ["brute_force", "BruteForce", "knn", "knn_merge_parts", "refine"]
