"""Candidate sample filters for ANN searches.

Re-design of raft::neighbors::filtering (cpp/include/raft/neighbors/
sample_filter_types.hpp — none_ivf_sample_filter, bitset_filter). The
reference evaluates a device predicate per (query, sample) inside the scan
kernels; the TPU formulation is a boolean keep-mask over global dataset ids,
gathered per candidate and fused into the score epilogue (masked-out
candidates score ±inf and can never win select_k).
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["NoFilter", "BitsetFilter", "resolve_filter", "apply_id_filter"]


class NoFilter:
    """Keep everything (ref: none_ivf_sample_filter)."""

    mask = None


class BitsetFilter:
    """Keep dataset row ``i`` iff ``bitset[i]`` (ref: bitset_filter,
    sample_filter_types.hpp — a packed bitset over dataset indices)."""

    def __init__(self, bitset):
        self.mask = jnp.asarray(bitset, bool)


def resolve_filter(f):
    """Normalize a filter argument to a keep-mask array or None."""
    if f is None or isinstance(f, NoFilter):
        return None
    if isinstance(f, BitsetFilter):
        return f.mask
    return jnp.asarray(f, bool)


def validate_filter_covers(index, keep_mask) -> None:
    """Check the keep-mask covers every stored id. IVF indexes hold explicit
    ``list_ids`` whose max needs a device reduction + host sync, so it is
    memoized on the index instance (invalidated by extend(), which returns a
    new index object); dense row-id indexes (cagra, whose stored ids ARE the
    dataset row offsets) cover ``[0, size)`` by construction."""
    from ..core.errors import expects

    max_id = getattr(index, "_max_id_cache", None)
    if max_id is None:
        ids = getattr(index, "list_ids", None)
        max_id = index.size - 1 if ids is None else int(jnp.max(ids))
        index._max_id_cache = max_id
    expects(
        keep_mask.shape[0] > max_id,
        "sample filter length %d must cover max stored id %d",
        keep_mask.shape[0],
        max_id,
    )


def apply_id_filter(scores, ids, keep_mask, select_min: bool):
    """Fused mask epilogue: invalidate scores whose candidate id is filtered.

    ``ids`` may contain −1 padding, which stays invalid.
    """
    bad = -jnp.inf if not select_min else jnp.inf
    valid = ids >= 0
    kept = jnp.take(keep_mask, jnp.clip(ids, 0), axis=0) & valid
    return jnp.where(kept, scores, bad)
