"""Shared inverted-list machinery for IVF indexes.

The reference factors this as ivf::list (neighbors/ivf_list.hpp) shared by
IVF-Flat and IVF-PQ; same idea here: within-list position assignment for the
padded scatter, and the search-time (query_tile, probe_chunk) sizing plan.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..distance.fused_nn import _fused_l2_nn
from ..distance.types import DistanceType

__all__ = ["round_up", "list_positions", "plan_search_tiles", "assign_to_lists",
           "split_oversized", "spatial_split_key", "bound_capacity",
           "pq_scan_bytes_per_probe_row", "funnel_scan_bytes_per_probe_row"]


def round_up(x: int, mult: int) -> int:
    return -(-x // mult) * mult


def list_cap_target(rows: int, n_lists: int, factor: float) -> int:
    """The shared list-capacity policy bound (:func:`bound_capacity`):
    lists larger than ``factor`` x the mean split, so allocated capacity
    is at most this. ``obs.mem.plan`` sizes its IVF estimates from the
    SAME expression — a policy change here moves both, which is what
    keeps the estimator's ±20% contract from silently drifting."""
    mean = max(rows / max(n_lists, 1), 1.0)
    return round_up(max(int(mean * factor), 8), 8)


def assign_to_lists(x, centers, metric: DistanceType, tile: int):
    """List assignment consistent with the index metric (the reference uses
    kmeans_balanced::predict with the index metric so storage placement and
    search probing agree)."""
    if metric == DistanceType.InnerProduct:
        scores = jnp.asarray(x).astype(jnp.float32) @ jnp.asarray(centers).T
        return jnp.argmax(scores, axis=1).astype(jnp.int32)
    return _fused_l2_nn(x, centers, False, tile)[1]


def list_positions(labels, n_lists: int):
    """Within-list position of each row = its rank among same-label rows,
    via one stable argsort (no (n, n_lists) intermediate).

    Returns (pos (n,) int32, counts (n_lists,) int32).
    """
    n = labels.shape[0]
    order = jnp.argsort(labels, stable=True)
    sorted_labels = jnp.take(labels, order)
    counts = jnp.bincount(labels, length=n_lists)
    starts = jnp.cumsum(counts) - counts
    pos_sorted = jnp.arange(n, dtype=jnp.int32) - jnp.take(starts, sorted_labels).astype(jnp.int32)
    pos = jnp.zeros((n,), jnp.int32).at[order].set(pos_sorted)
    return pos, counts.astype(jnp.int32)


def split_oversized(labels, n_lists: int, cap_target: int, order_key=None):
    """Split lists larger than ``cap_target`` into sub-lists.

    The padded layout prices every list at the MAX size, so one hot cluster
    inflates all scans; bounding capacity by sub-list splitting is the
    coarse-grained analogue of the reference's fixed 32-vector interleaved
    groups (ivf_flat_build.cuh:135-153).

    ``order_key`` (optional, (n,) float) controls HOW members divide among a
    list's sub-lists: with None, by input order (arbitrary — fine when
    sub-lists share the parent's center and are probed together); with a
    per-row spatial key (e.g. projection on the list's principal axis,
    :func:`spatial_split_key`), each sub-list is a spatially coherent SLAB,
    so a caller that re-centers sub-lists on their member means gets
    differentiated coarse scores and queries probe only nearby slabs — the
    fix for Zipf-population data, where an order-split mega-cluster
    scattered every query's neighbors uniformly over ~population/cap
    identical-score sub-lists (BASELINE.md "Round-5 heavytail family").

    Returns ``(new_labels (n,), rep (n_lists,) host int array)`` where
    ``rep[l]`` is how many sub-lists list ``l`` became (all 1 = no change);
    the new list count is ``rep.sum()``. Callers repeat center-indexed arrays
    with ``np.repeat(arr, rep, axis=0)``.
    """
    import numpy as np

    if order_key is None:
        pos, counts = list_positions(labels, n_lists)
        counts_h = np.asarray(counts)
    else:
        # within-list rank by the spatial key: one lexicographic sort by
        # (label, key) — the proj-ordered twin of list_positions
        n = labels.shape[0]
        idx = jnp.arange(n, dtype=jnp.int32)
        _, _, s_idx = jax.lax.sort(
            (labels.astype(jnp.int32), order_key.astype(jnp.float32), idx),
            num_keys=2)
        counts = jnp.bincount(labels, length=n_lists)
        starts = jnp.cumsum(counts) - counts
        pos_sorted = (jnp.arange(n, dtype=jnp.int32)
                      - jnp.take(starts, jnp.take(labels, s_idx)).astype(jnp.int32))
        pos = jnp.zeros((n,), jnp.int32).at[s_idx].set(pos_sorted)
        counts_h = np.asarray(counts)
    rep = np.maximum(1, -(-counts_h // cap_target)).astype(np.int64)
    base = np.concatenate([[0], np.cumsum(rep)[:-1]]).astype(np.int32)
    new_labels = jnp.asarray(base)[labels] + (pos // cap_target).astype(jnp.int32)
    return new_labels, rep


def spatial_split_key(x, labels, n_lists: int, n_iters: int = 3):
    """Per-row projection onto its list's principal axis — the spatial
    order key for :func:`split_oversized`. Fully vectorized across lists:
    per-list means by segment sum, then ``n_iters`` power iterations of the
    per-list covariance action (each iteration is two passes over (n, d)),
    then the scalar projection. The reference reaches the same goal through
    hierarchical balanced k-means (detail/kmeans_balanced.cuh
    build_hierarchical); a principal-axis slab split is the one-shot TPU
    form (slabs are contiguous ranks, so the split stays exactly
    capacity-balanced)."""
    return _spatial_key_impl(x, labels, n_lists, n_iters)


@functools.partial(jax.jit, static_argnames=("n_lists", "n_iters"))
def _spatial_key_impl(x, labels, n_lists: int, n_iters: int):
    xf = x.astype(jnp.float32)
    n, d = xf.shape
    lab = labels.astype(jnp.int32)
    onehot_sum = jnp.zeros((n_lists, d), jnp.float32).at[lab].add(xf)
    counts = jnp.zeros((n_lists,), jnp.float32).at[lab].add(1.0)
    means = onehot_sum / jnp.maximum(counts, 1.0)[:, None]
    xc = xf - means[lab]
    key = jax.random.key(0)
    v = jax.random.normal(key, (n_lists, d), jnp.float32)

    def body(i, v):
        w = jnp.sum(xc * v[lab], axis=1)                     # (n,)
        v2 = jnp.zeros((n_lists, d), jnp.float32).at[lab].add(
            w[:, None] * xc)
        return v2 / jnp.maximum(
            jnp.linalg.norm(v2, axis=1, keepdims=True), 1e-20)

    v = jax.lax.fori_loop(0, n_iters, body, v)
    return jnp.sum(xc * v[lab], axis=1)


def bound_capacity(labels, n_lists: int, factor: float = 1.3, x=None):
    """Shared capacity policy for IVF fills: lists larger than ``factor`` x
    the mean split into sub-lists (see :func:`split_oversized`); otherwise
    capacity is the max size rounded to the sublane tile. Lower factors cut
    the padded-gather bytes every scan pays (the 1M-scale search bottleneck)
    at the cost of more sub-lists competing for probe slots.

    ``x`` (optional, (n, d)): when given, oversized lists split SPATIALLY
    along their principal axis (see :func:`split_oversized`); the caller
    should then re-center split sub-lists on their member means.

    Returns ``(labels, rep, n_lists, capacity, spatial)`` where ``rep`` is
    None when no splitting happened, else the host repeat-count array for
    center-indexed arrays (``np.repeat(arr, rep, axis=0)``), and ``spatial``
    is None or a host bool array over ORIGINAL lists marking which were
    slab-ordered (the caller should recenter exactly those lists' children).
    """
    import numpy as np

    sizes = jnp.bincount(labels, length=n_lists)
    max_size = max(int(jnp.max(sizes)), 1)
    cap_target = list_cap_target(labels.shape[0], n_lists, factor)
    if max_size <= cap_target:
        return labels, None, n_lists, round_up(max_size, 8), None
    # spatial splitting only for lists that shatter SEVERELY (>= 8
    # sub-lists — a mega-cluster the coarse trainer could not divide, e.g.
    # n_lists below the natural cluster count on population-skewed data).
    # The 8x-size threshold is a measured compromise: it sits just above
    # the hot-list tail balanced k-means leaves on ordinary clustered data
    # (isotropic-1M: max list 7.4x cap, 3 lists past 4x; recentring those
    # measured -0.0014 recall on the flagship row), at the cost of leaving
    # lists in the (4x, 8x) band on the order split, where the
    # ~n_probes/rep recall cap is partial (rep up to 8 at the default
    # p=8) rather than the catastrophic many-fold cap this path exists
    # to fix.
    # Mild splits keep the order split + duplicated centers bit-for-bit:
    # siblings tie in coarse score and are probed together, and an r05 A/B
    # measured the spatial form ~0.001-0.003 recall WORSE there
    # (recentring perturbs probe ranking for no coverage gain), while on a
    # shattered mega-cluster the order split caps recall at ~n_probes/rep
    # (tests/test_ivf_flat.py::test_spatial_split_recall_on_skewed_population).
    # Selectivity is PER LIST: the spatial key applies only to severe
    # lists' rows (everyone else keys to 0, and the stable sort preserves
    # their input order exactly), and `spatial` reports which original
    # lists were slab-ordered so the caller recenters exactly those.
    order_key = None
    spatial = None
    severe_h = np.asarray(sizes) >= 8 * cap_target
    if x is not None and severe_h.any():
        proj = spatial_split_key(x, labels, n_lists)
        severe = jnp.asarray(severe_h)
        order_key = jnp.where(severe[labels], proj, 0.0)
        spatial = severe_h
    new_labels, rep = split_oversized(labels, n_lists, cap_target, order_key)
    return new_labels, rep, int(rep.sum()), cap_target, spatial


def pq_scan_bytes_per_probe_row(capacity: int, pq_dim: int, n_codes: int) -> int:
    """Memory model for one (query, probe) pair of the PQ LUT scan, shared by
    the single-chip and distributed searches: codes gather (uint8) + gathered
    LUT values (f32) + scores (f32) per capacity slot, plus the LUT itself;
    x2 for XLA temporaries (the gather and its consumer co-exist) —
    undercounting here OOMed the device at 1M scale."""
    return 2 * (capacity * pq_dim * 9 + pq_dim * n_codes * 8)


def funnel_scan_bytes_per_probe_row(capacity: int, sig_words: int) -> int:
    """Memory model for one (query, probe) pair of the fast-scan funnel's
    binary tier (ivf_pq fast_scan): packed signature gather (uint8) +
    estimator scores (f32) per capacity slot, plus the 32-entry nibble LUT;
    same x2 temporaries convention as :func:`pq_scan_bytes_per_probe_row`.
    The PQ rerank that follows touches only the k_widen survivors, so the
    binary tier dominates the per-probe footprint."""
    return 2 * (capacity * (sig_words * 9 + 4) + sig_words * 32 * 8)


def plan_search_tiles(m: int, n_probes: int, k: int, capacity: int,
                      bytes_per_probe_row: int, budget_bytes: int,
                      max_query_tile: int = 256):
    """Pick (query_tile, probe_chunk) so the per-step gather block fits the
    workspace budget while every chunk still holds >= k candidates — the
    shared analogue of the reference's memory-aware tile sizing
    (knn_brute_force.cuh:78 applied to list scans).

    ``bytes_per_probe_row``: bytes a single (query, probe) pair contributes
    (list payload + LUT etc.).
    """
    min_chunk = -(-k // capacity)
    if min_chunk > n_probes:
        raise ValueError(
            f"k={k} exceeds the probed candidate pool "
            f"(n_probes={n_probes} x capacity={capacity})"
        )
    probe_chunk = n_probes
    query_tile = min(m, max_query_tile)

    def cost(qt, pc):
        return qt * pc * bytes_per_probe_row

    while probe_chunk // 2 >= min_chunk and probe_chunk % 2 == 0 and cost(query_tile, probe_chunk) > budget_bytes:
        probe_chunk //= 2
    while query_tile > 8 and cost(query_tile, probe_chunk) > budget_bytes:
        query_tile //= 2
    while n_probes % probe_chunk:
        probe_chunk -= 1
    probe_chunk = max(probe_chunk, min_chunk)
    while n_probes % probe_chunk:
        probe_chunk += 1
    return query_tile, probe_chunk
