"""Shared inverted-list machinery for IVF indexes.

The reference factors this as ivf::list (neighbors/ivf_list.hpp) shared by
IVF-Flat and IVF-PQ; same idea here: within-list position assignment for the
padded scatter, and the search-time (query_tile, probe_chunk) sizing plan.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..distance.fused_nn import _fused_l2_nn
from ..distance.types import DistanceType

__all__ = ["round_up", "list_positions", "plan_search_tiles", "assign_to_lists",
           "split_oversized", "bound_capacity"]


def round_up(x: int, mult: int) -> int:
    return -(-x // mult) * mult


def assign_to_lists(x, centers, metric: DistanceType, tile: int):
    """List assignment consistent with the index metric (the reference uses
    kmeans_balanced::predict with the index metric so storage placement and
    search probing agree)."""
    if metric == DistanceType.InnerProduct:
        scores = jnp.asarray(x).astype(jnp.float32) @ jnp.asarray(centers).T
        return jnp.argmax(scores, axis=1).astype(jnp.int32)
    return _fused_l2_nn(x, centers, False, tile)[1]


def list_positions(labels, n_lists: int):
    """Within-list position of each row = its rank among same-label rows,
    via one stable argsort (no (n, n_lists) intermediate).

    Returns (pos (n,) int32, counts (n_lists,) int32).
    """
    n = labels.shape[0]
    order = jnp.argsort(labels, stable=True)
    sorted_labels = jnp.take(labels, order)
    counts = jnp.bincount(labels, length=n_lists)
    starts = jnp.cumsum(counts) - counts
    pos_sorted = jnp.arange(n, dtype=jnp.int32) - jnp.take(starts, sorted_labels).astype(jnp.int32)
    pos = jnp.zeros((n,), jnp.int32).at[order].set(pos_sorted)
    return pos, counts.astype(jnp.int32)


def split_oversized(labels, n_lists: int, cap_target: int):
    """Split lists larger than ``cap_target`` into sub-lists that share the
    parent's center.

    The padded layout prices every list at the MAX size, so one hot cluster
    inflates all scans; bounding capacity by sub-list splitting is the
    coarse-grained analogue of the reference's fixed 32-vector interleaved
    groups (ivf_flat_build.cuh:135-153). Sub-lists duplicate their parent's
    coarse center, so a query's coarse top-k naturally ranks them adjacently
    (identical scores) and probes them together.

    Returns ``(new_labels (n,), rep (n_lists,) host int array)`` where
    ``rep[l]`` is how many sub-lists list ``l`` became (all 1 = no change);
    the new list count is ``rep.sum()``. Callers repeat center-indexed arrays
    with ``np.repeat(arr, rep, axis=0)``.
    """
    import numpy as np

    pos, counts = list_positions(labels, n_lists)
    counts_h = np.asarray(counts)
    rep = np.maximum(1, -(-counts_h // cap_target)).astype(np.int64)
    base = np.concatenate([[0], np.cumsum(rep)[:-1]]).astype(np.int32)
    new_labels = jnp.asarray(base)[labels] + (pos // cap_target).astype(jnp.int32)
    return new_labels, rep


def bound_capacity(labels, n_lists: int, factor: float = 1.3):
    """Shared capacity policy for IVF fills: lists larger than ``factor`` x
    the mean split into sub-lists (see :func:`split_oversized`); otherwise
    capacity is the max size rounded to the sublane tile. Lower factors cut
    the padded-gather bytes every scan pays (the 1M-scale search bottleneck)
    at the cost of more sub-lists competing for probe slots.

    Returns ``(labels, rep, n_lists, capacity)`` where ``rep`` is None when no
    splitting happened, else the host repeat-count array for center-indexed
    arrays (``np.repeat(arr, rep, axis=0)``).
    """
    import numpy as np

    sizes = jnp.bincount(labels, length=n_lists)
    max_size = max(int(jnp.max(sizes)), 1)
    mean_size = max(labels.shape[0] / n_lists, 1.0)
    cap_target = round_up(max(int(mean_size * factor), 8), 8)
    if max_size <= cap_target:
        return labels, None, n_lists, round_up(max_size, 8)
    new_labels, rep = split_oversized(labels, n_lists, cap_target)
    return new_labels, rep, int(rep.sum()), cap_target


def pq_scan_bytes_per_probe_row(capacity: int, pq_dim: int, n_codes: int) -> int:
    """Memory model for one (query, probe) pair of the PQ LUT scan, shared by
    the single-chip and distributed searches: codes gather (uint8) + gathered
    LUT values (f32) + scores (f32) per capacity slot, plus the LUT itself;
    x2 for XLA temporaries (the gather and its consumer co-exist) —
    undercounting here OOMed the device at 1M scale."""
    return 2 * (capacity * pq_dim * 9 + pq_dim * n_codes * 8)


def plan_search_tiles(m: int, n_probes: int, k: int, capacity: int,
                      bytes_per_probe_row: int, budget_bytes: int,
                      max_query_tile: int = 256):
    """Pick (query_tile, probe_chunk) so the per-step gather block fits the
    workspace budget while every chunk still holds >= k candidates — the
    shared analogue of the reference's memory-aware tile sizing
    (knn_brute_force.cuh:78 applied to list scans).

    ``bytes_per_probe_row``: bytes a single (query, probe) pair contributes
    (list payload + LUT etc.).
    """
    min_chunk = -(-k // capacity)
    if min_chunk > n_probes:
        raise ValueError(
            f"k={k} exceeds the probed candidate pool "
            f"(n_probes={n_probes} x capacity={capacity})"
        )
    probe_chunk = n_probes
    query_tile = min(m, max_query_tile)

    def cost(qt, pc):
        return qt * pc * bytes_per_probe_row

    while probe_chunk // 2 >= min_chunk and probe_chunk % 2 == 0 and cost(query_tile, probe_chunk) > budget_bytes:
        probe_chunk //= 2
    while query_tile > 8 and cost(query_tile, probe_chunk) > budget_bytes:
        query_tile //= 2
    while n_probes % probe_chunk:
        probe_chunk -= 1
    probe_chunk = max(probe_chunk, min_chunk)
    while n_probes % probe_chunk:
        probe_chunk += 1
    return query_tile, probe_chunk
