"""CAGRA: graph-based ANN index.

Re-design of the reference's CAGRA (cpp/include/raft/neighbors/cagra.cuh;
build detail/cagra/cagra_build.cuh — kNN graph via IVF-PQ :42,86 + refine
:167-184, then detour-count pruning graph_core.cuh:128 kern_prune + reverse
-edge merge; search detail/cagra/search_plan.cuh + single/multi-CTA persistent
kernels, bitonic itopk + visited hashmap). SURVEY.md flags the search as "the
one algorithm whose control flow is fundamentally device-side dynamic"; the
TPU re-think makes it batch-synchronous:

- **Build**: identical pipeline shape — IVF-PQ over the dataset, batched
  search (queries = dataset), exact refine, then *vectorized* detour pruning:
  the reference counts 2-hop detours per edge with a per-node CUDA kernel;
  here the detour count of edge (u→v) = number of w ∈ N(u) ranked closer
  than v with v ∈ N(w) — computed for all edges at once with one batched
  membership test over the neighbor lists (einsum of one-hot comparisons),
  then reverse-edge merge.
- **Search**: best-first beam search over the whole query batch in lockstep
  under lax.while_loop: each hop expands the best unvisited beam entry per
  query, gathers its fixed-degree adjacency row (one row DMA per query),
  scores all expansions with an MXU batched dot, and merges into the beam
  with one sort — the bitonic itopk + hashmap of the persistent kernel
  becomes sort-based dedup on (id, score) pairs, fully static shapes.
"""

from __future__ import annotations

from ..config import auto_convert_output

import dataclasses
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core import tracing
from ..core.errors import expects
from ..core.logger import logger
from ..core.resources import Resources, default_resources
from ..core.serialize import (check_header, deserialize_mdspan, deserialize_scalar,
                              deserialize_tuned, serialize_header,
                              serialize_mdspan, serialize_scalar,
                              serialize_tuned)
from ..distance.types import DistanceType, resolve_metric
from ..obs import build as _build_metrics
from ..obs import mem as obs_mem
from ..obs import metrics as _metrics
from ..obs.instrument import dtype_of, instrument, nrows
from ..random.rng import as_key
from . import ivf_pq as ivf_pq_mod
from .refine import refine

__all__ = ["IndexParams", "SearchParams", "CagraIndex", "build", "search",
           "build_knn_graph", "optimize", "save", "load"]


@dataclasses.dataclass(frozen=True)
class IndexParams:
    """Reference: cagra::index_params (cagra_types.hpp:48-64)."""

    intermediate_graph_degree: int = 64  # ref :55
    graph_degree: int = 32  # ref :57
    metric: str | DistanceType = "sqeuclidean"
    # 0 → auto: pq_bits=4 when the dataset is high-dimensional (pq_dim >= 32,
    # e.g. d >= 64) — the TPU-fast LUT scan (one-hot contraction axis 16
    # codes, ~10x the pq8 QPS), with graph quality restored by the exact
    # refine pass — and pq_bits=8 for low-dim data where 16 codes per
    # subspace quantize too coarsely and the pq8 axis is cheap anyway (the
    # reference always uses 8; its smem LUT is bits-insensitive).
    build_pq_bits: int = 0
    build_n_lists: int = 0  # 0 → sqrt(n) heuristic
    # probes for the self-search that builds the knn graph. The r04 profile
    # (bench/cagra_build_profile.py) put 98% of the 445 s 1M build in this
    # search, scaling linearly in probes — and a full-build A/B measured
    # p=8 vs p=32 recall IDENTICAL to 4 decimals (0.9714 @ itopk32 /
    # 0.9964 @ itopk64) at 122.6 s vs 445 s on clustered data: a dataset
    # point's top-64 neighbors live in its home + adjacent lists. On
    # small/uniform data the same drop costs real graph quality (0.80 →
    # 0.63 edge recall at 4k x 24 uniform), so 0 (default) = MEASURED auto:
    # chunk 0 is built at p=32 and p=8 and the cheap setting is kept for
    # the remaining chunks only when its refined edge lists overlap the
    # wide ones >= 95% (escalating to 16, then 32). Explicit values are
    # honored as-is. (BASELINE.md "Round-4 CAGRA build".)
    build_n_probes: int = 0
    # gpu_top_k multiplier (ref cagra_build.cuh:99 defaults 2.0 against pq8);
    # 3.0 compensates pq4's coarser candidate ordering — the wider exact
    # refine pool costs far less than pq8's 10x-slower LUT scan
    refine_rate: float = 3.0
    # NOTE a bf16 hop-scoring dataset copy was tried and measured WORSE on
    # both axes at 1M x 128 (QPS 28.5k -> 26.1k, recall 0.971 -> 0.699 at
    # itopk=32): the per-hop vector fetches are latency-bound, not
    # bandwidth-bound, and bf16 score noise misorders the beam on tight
    # clusters. Removed; measurement recorded in BASELINE.md.
    # query rows per device dispatch during the self-search/refine phases —
    # keeps any single device program under watchdog/VMEM pressure limits.
    # Honored down to 1 (lower = more, smaller dispatches; useful when VMEM
    # limits bite at high d); values below ~1024 cost dispatch overhead
    build_chunk: int = 16384
    # top-k implementation for the build self-search's candidate selects
    # (k = gpu_top_k + 1, 193 at defaults — the call site the wide-k Pallas
    # selector was commissioned for, VERDICT r4 #5 / r5 #3). Threads into
    # ivf_pq.SearchParams.select_impl inside _build_chunk_step:
    #   "auto"   — the measured select_k dispatch rule (k <= 256 reachable
    #              since r06's half-width merge lifted the chaining cap;
    #              build-chunk per-chunk widths of ~10-40k cols sit below
    #              the 65536-col wide-k threshold, so auto stays on
    #              lax.top_k until the driver A/B justifies lowering it).
    #   "pallas" — force the streaming selector (the A/B arm
    #              bench/cagra_build_select_ab.py measures; two wide
    #              instances per program — per-chunk + final merge — is
    #              exactly the composition the r06 workaround unlocked).
    #   "xla"    — force lax.top_k.
    build_select_impl: str = "auto"
    # coarse-trainer EM policy for the build's internal IVF-PQ index
    # (ivf_pq.IndexParams.kmeans_train_mode/kmeans_batch_rows — same
    # contract): "auto" runs mini-batch EM above 2 x batch_rows trainset
    # rows, so the 1M self-search index build sheds its ~20 full-trainset
    # assignment passes. Build speed is a serving feature here: the stream
    # Compactor's CAGRA rebuild path means this wall bounds sustainable
    # write churn (docs/streaming.md).
    build_kmeans_train_mode: str = "auto"
    build_kmeans_batch_rows: int = 65536
    seed: int = 0


@dataclasses.dataclass(frozen=True)
class SearchParams:
    """Reference: cagra::search_params (cagra_types.hpp:66-120)."""

    itopk_size: int = 64  # beam width (ref :66)
    max_iterations: int = 0  # 0 → auto (ref :71)
    search_width: int = 1  # beam entries expanded per hop (ref :93)
    # entry-point candidate pool: the beam is seeded with the best
    # `n_init` of `seed_pool` uniformly-sampled dataset points, scored by one
    # (m, seed_pool) MXU GEMM. The reference seeds from `num_pickup` purely
    # random points (search_plan.cuh random_samplings); a scored pool costs
    # one cheap matmul and keeps recall on clustered data where random
    # entries land in the wrong basin and the graph has no cross-cluster
    # edges. Pool size sets the entry-coverage recall ceiling at scale:
    # measured at 1M x 128 / 2000 clusters (itopk=32), pool 4096 → 0.846
    # recall, 16384 → 0.973 at identical QPS — the GEMM is not the hop
    # loop's bottleneck. THE POOL MUST SCALE WITH THE DATA'S LOCAL MODES: on
    # multi-scale (near-duplicate-clump) data with ~32k clumps, 16384 →
    # 0.880 but 65536 → 0.979 (-13% QPS) and 131072 → 0.995 (-24%) at
    # itopk=32 (r04, BASELINE.md "Round-4 SIFT-class 1M harness sweep") —
    # the beam cannot hop into a clump no seed landed near.
    #   -1 (default) → AUTO: use the index's measured seed_pool_hint (the
    #     build estimates the local-mode count from the knn graph's
    #     neighbor-distance jump profile — the search-side twin of the r04
    #     build_n_probes autotune; reference analogue: adjust_search_params,
    #     detail/cagra/search_plan.cuh:119), falling back to 16384 when the
    #     build saw no clump structure.
    #   0 → plain random entries (reference behavior).
    #   >0 → explicit pool size, honored as-is.
    seed_pool: int = -1
    # hop-loop implementation (r05, VERDICT r4 #1; full study in
    # BASELINE.md "Round-5 fused hop study"; r06 arena iteration in
    # "Round-6 arena residual attack"):
    #   "auto" → "fused_arena" on TPU when eligible (itopk +
    #     search_width*degree <= 128), else the XLA loop.
    #   "fused_arena" — ONE Pallas launch per hop (scoring + dedup + merge +
    #     pick, beam state VMEM-resident; gathers stay in XLA per the r04
    #     head-to-head) with a threshold-gated arena merge: candidates
    #     insert over the arena's worst only while they beat it, so late
    #     hops pay ~0 merge passes. Since r06 the insertion loop carries
    #     its gate in a register and its candidate scores as loop values —
    #     the r05 profile named the per-candidate SMEM handshake + pool
    #     scratch round-trips as the ~5 us/query residual between the
    #     shipped 1.27x and the profiled 1.6x merge-free ceiling, and this
    #     form removes exactly those terms.
    #   "fused_arena_smem" — the r05 arena loop kept verbatim (SMEM gate,
    #     scratch-stashed pool): the control arm for the r06 A/B
    #     (bench/cagra_hop_ab.py). Measured 1.27x the XLA loop in-process
    #     at 1M itopk=32, identical recall.
    #   "fused" — same kernel with the sorted extraction merge (itopk
    #     unconditional passes); measured NEUTRAL vs XLA — kept as the
    #     study's control.
    #   "xla" — the op-at-a-time hop loop (reference shape).
    hop_impl: str = "auto"
    # RNG seed (int / RngState / raw key) for the seed-pool draw (ref
    # search_params :118 rand_xor_mask). Determinism contract: the same
    # (seed, index, queries, params) always searches the same sampled pool,
    # so results are bitwise reproducible; vary the seed to decorrelate the
    # entry-coverage ceiling across calls (VERDICT r3 weak #3 — a fixed
    # key tied every search to one 16384-point draw).
    seed: int = 0


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class CagraIndex:
    """Reference: cagra::index (cagra_types.hpp:123-220) — dataset + fixed
    -degree neighbor graph."""

    dataset: jax.Array  # (n, d) — f32, or int8 for byte datasets (the
    # reference's dtype-generic cagra::index<T>: int8/uint8 datasets store
    # native bytes, quartering the hop loop's vector-gather traffic; uint8
    # is held shifted by -128 in the s8 domain — L2 is shift-invariant and
    # queries shift the same way at search)
    graph: jax.Array  # (n, graph_degree) int32
    metric: DistanceType = DistanceType.L2Expanded
    # "float32" | "int8" | "uint8": what the stored dataset IS (uint8 kinds
    # hold shifted s8 bytes); governs extend/search query coercion
    data_kind: str = "float32"
    # measured at build time from the knn graph's neighbor-distance jump
    # profile: the seed-pool size that covers the data's local modes
    # (0 = no clump structure detected; SearchParams.seed_pool=-1 consumes
    # this). The reference stores no search hints on the index — its
    # adjust_search_params (search_plan.cuh:119) rescales at search time
    # from itopk alone, which cannot see data clumpiness.
    # NOT part of the pytree (neither child nor aux): search() resolves it
    # on the host BEFORE the jit boundary, and putting it in aux would make
    # indexes differing only in hint recompile _cagra_search (minutes at
    # 1M). Pytree round trips (device_put, tree_map) drop it back to 0 —
    # the default pool, never an error; save/load preserves it.
    seed_pool_hint: int = 0
    # pinned operating point (raft_tpu.tune decision dict; None = untuned):
    # consulted by batched_searcher when no explicit params are given,
    # persisted by save/load (raft_tpu/9). Same non-pytree contract as
    # seed_pool_hint: tree round trips drop it back to None.
    tuned: dict | None = None

    @property
    def size(self) -> int:
        return self.dataset.shape[0]

    @property
    def dim(self) -> int:
        return self.dataset.shape[1]

    @property
    def graph_degree(self) -> int:
        return self.graph.shape[1]

    def tree_flatten(self):
        return (self.dataset, self.graph), (self.metric, self.data_kind)

    @classmethod
    def tree_unflatten(cls, aux, children):
        metric, kind = aux if isinstance(aux, tuple) else (aux, "float32")
        return cls(*children, metric=metric, data_kind=kind)


def knn_build_plan(params: IndexParams, n: int, d: int):
    """Derived internal-build parameters (k, gpu_top_k, n_lists, pq_bits) —
    one definition shared by build_knn_graph and bench/cagra_build_profile
    so the profiler always measures the real pipeline."""
    k = params.intermediate_graph_degree
    gpu_top_k = min(int(k * params.refine_rate), n - 1)
    n_lists = params.build_n_lists or max(int(n ** 0.5), 8)
    n_lists = min(n_lists, n // 4 if n >= 32 else n)
    # threshold evaluated against the reference-equivalent ~d/2 heuristic
    # (pq_bits=8 arg) so the bits-aware default change in _default_pq_dim
    # does not shift this auto decision (pq4 from d >= 64, as documented)
    pq_bits = params.build_pq_bits or (
        4 if ivf_pq_mod._default_pq_dim(d, 8) >= 32 else 8)
    return k, gpu_top_k, n_lists, pq_bits


def build_knn_graph(params: IndexParams, dataset, res: Resources | None = None):
    """Stage 1 (reference: build_knn_graph, cagra_build.cuh:42): IVF-PQ over
    the dataset, search with queries = dataset, exact refine."""
    res = res or default_resources()
    x = jnp.asarray(dataset)
    n, d = x.shape
    k, gpu_top_k, n_lists, pq_bits = knn_build_plan(params, n, d)
    pq = ivf_pq_mod.build(
        ivf_pq_mod.IndexParams(
            n_lists=n_lists,
            metric=params.metric,
            pq_bits=pq_bits,
            kmeans_train_mode=params.build_kmeans_train_mode,
            kmeans_batch_rows=params.build_kmeans_batch_rows,
            seed=params.seed,
        ),
        x,
        res=res,
    )
    # query the dataset against itself in host-side chunks (one giant
    # dispatch trips device watchdogs at 100k+ rows; the reference batches
    # here too — cagra_build.cuh:86 loops over max_batch_size query blocks),
    # k+1 then drop self. The whole per-chunk pipeline (PQ search + exact
    # refine + self-edge drop) is ONE jitted program: on a slow tunnel the
    # per-dispatch RPC dominates the build (identical code measured 228 s to
    # 20+ min), so 62 chunks must cost 62 round trips, not ~400.
    chunk = max(int(params.build_chunk), 1)
    mt = resolve_metric(params.metric)

    # per-chunk walls force a host-device sync per chunk (they would break
    # the async dispatch pipeline on EVERY build — metrics are on by
    # default), so they are strictly opt-in: set RAFT_TPU_BUILD_CHUNK_WALLS=1
    # when profiling. The always-on phase wall is the single total
    # "cagra/knn_graph" observation in build() — one sync per build.
    import os

    chunk_walls = (_metrics._enabled
                   and os.environ.get("RAFT_TPU_BUILD_CHUNK_WALLS", "") == "1")

    def chunk_step(s, probes):
        xb = x[s:s + chunk]
        rows = jnp.arange(s, min(s + chunk, n), dtype=jnp.int32)
        if not chunk_walls:
            return _build_chunk_step(x, pq, xb, rows, probes, int(gpu_top_k),
                                     int(k), mt, int(res.workspace_bytes),
                                     params.build_select_impl)
        t0 = time.perf_counter()
        out = _build_chunk_step(x, pq, xb, rows, probes, int(gpu_top_k),
                                int(k), mt, int(res.workspace_bytes),
                                params.build_select_impl)
        jax.block_until_ready(out)
        _build_metrics.build_phase().observe(time.perf_counter() - t0,
                                             phase="cagra/knn_chunk")
        return out

    probes = int(params.build_n_probes)
    parts = []
    if probes == 0:
        # measured auto (r04, BASELINE.md "Round-4 CAGRA build"): the
        # self-search is 98% of the build and linear in probes, but how few
        # probes preserve the graph depends on the data (clustered 1M: p=8
        # == p=32 to 4 decimals of search recall; uniform 4k: p=8 costs
        # 0.17 edge recall). So pay p=32 once on chunk 0 — whose edges are
        # kept, nothing is wasted — and adopt the cheapest of p=8/16 whose
        # refined edge lists overlap it >= 95% for the remaining chunks.
        import numpy as np

        probes = 32
        wide = chunk_step(0, 32)
        parts.append(wide)
        if n > chunk:  # autotune only pays when more chunks follow
            # the decision sample is up to 2048 rows drawn UNIFORMLY across
            # [0, n) — row order often correlates with structure (data
            # appended cluster-by-cluster), so a head slice would judge p=8
            # on one unrepresentative region (r04 advisor finding). The
            # extra wide search of the sample is cheap relative to the
            # build. Clamped by build_chunk: the user's chunk bound exists
            # to keep any single dispatch under VMEM/watchdog limits, and
            # trial dispatches must honor it too.
            t_rows = min(2048, chunk, n)
            rng = np.random.default_rng(params.seed)
            sample = np.sort(rng.choice(n, size=t_rows, replace=False))
            rt = jnp.asarray(sample, dtype=jnp.int32)
            xt = x[rt]
            wide_h = np.asarray(_build_chunk_step(
                x, pq, xt, rt, 32, int(gpu_top_k), int(k), mt,
                int(res.workspace_bytes), params.build_select_impl))
            for p_try in (8, 16):
                trial = np.asarray(_build_chunk_step(
                    x, pq, xt, rt, p_try, int(gpu_top_k), int(k), mt,
                    int(res.workspace_bytes), params.build_select_impl))
                overlap = np.mean([
                    len(set(a) & set(b)) / len(a)
                    for a, b in zip(trial.tolist(), wide_h.tolist())])
                if overlap >= 0.95:
                    probes = p_try
                    logger.info(
                        "cagra build_n_probes auto: p=%d edge lists overlap "
                        "p=32 at %.3f — using %d probes for the remaining "
                        "chunks", p_try, overlap, p_try)
                    break
            else:
                logger.info("cagra build_n_probes auto: keeping 32 probes "
                            "(cheaper settings overlapped < 0.95)")
    for s in range(chunk if parts else 0, n, chunk):
        parts.append(chunk_step(s, probes))
    return jnp.concatenate(parts, axis=0) if len(parts) > 1 else parts[0]


@functools.partial(
    jax.jit,
    static_argnames=("n_probes", "gpu_top_k", "k", "metric", "workspace_bytes",
                     "select_impl"))
def _build_chunk_step(x, pq, xb, rows, n_probes: int, gpu_top_k: int, k: int,
                      metric, workspace_bytes: int, select_impl: str = "auto"):
    """One knn-graph build chunk — PQ search + exact refine + self-edge drop —
    as a single program: on a slow tunnel the per-dispatch RPC dominates the
    build (identical code measured 228 s to 20+ min), so N chunks must cost N
    round trips, not ~6N. Module-level and argument-passing (x/pq are jit
    arguments, not closure constants) so the compilation caches across
    build() calls. ``workspace_bytes`` (static) threads the caller's
    Resources budget into the dominant build phase, so a constrained
    workspace bounds the PQ scan block here too."""
    from . import ivf_pq as ivf_pq_mod
    from .refine import refine
    from ..core.resources import Resources

    chunk_res = Resources(workspace_bytes=workspace_bytes)
    # select_impl threads the wide-k selector into the k = gpu_top_k + 1
    # candidate selects below (the r05-commissioned call site; see
    # IndexParams.build_select_impl)
    sp = ivf_pq_mod.SearchParams(n_probes=n_probes, select_impl=select_impl)
    _, cand = ivf_pq_mod.search(sp, pq, xb, gpu_top_k + 1, res=chunk_res)
    _, refined = refine(x, xb, cand, k + 1, metric=metric, res=chunk_res)
    # drop self-edges (ref: build_knn_graph removes the query itself)
    self_col = refined == rows[:, None]
    # shift left past self matches: mask self then take first k valid
    big = jnp.where(
        self_col, jnp.iinfo(jnp.int32).max,
        jnp.arange(k + 1, dtype=jnp.int32)[None, :]
    )
    order = jnp.argsort(big, axis=1)[:, :k]
    return jnp.take_along_axis(refined, order, axis=1)


@functools.partial(jax.jit, static_argnames=("out_degree", "tile"))
def _prune_graph(graph, out_degree: int, tile: int):
    """Stage 2 (reference: optimize/kern_prune, graph_core.cuh:128).

    Edge (u→v_j) is detourable if some higher-ranked neighbor w of u also has
    v in *its* list — i.e. a 2-hop path u→w→v with both hops ranked better.
    The reference counts these per edge with a per-node kernel; here a
    vectorized membership test — N(N(u)) vs N(u) — evaluated per node tile
    under lax.map so the (tile, k, k, k) comparison block stays bounded.
    Keep the out_degree lowest-detour-count edges (rank-stable).
    """
    n, k = graph.shape
    num = -(-n // tile)
    pad = num * tile - n
    gp = jnp.pad(graph, ((0, pad), (0, 0))) if pad else graph
    gt = gp.reshape(num, tile, k)
    rank_lt = jnp.tril(jnp.ones((k, k), jnp.bool_), -1).T  # i < j mask, (k_i, k_j)

    def per_tile(g):
        nbr_of_nbr = graph[g]  # (t, k, k): N(w) for each w = g[u, i]
        v = g[:, None, :, None]  # (t, 1, k, 1) target ids
        w_lists = nbr_of_nbr[:, :, None, :]  # (t, k, 1, k)
        hit = jnp.any(v == w_lists, axis=-1)  # (t, k, k): hit[u, i, j] = v_j ∈ N(w_i)
        detours = jnp.sum(jnp.where(rank_lt[None], hit, False), axis=1)  # (t, k)
        score = detours.astype(jnp.int32) * k + jnp.arange(k, dtype=jnp.int32)[None, :]
        keep = jnp.argsort(score, axis=1)[:, :out_degree]
        return jnp.take_along_axis(g, jnp.sort(keep, axis=1), axis=1)

    out = lax.map(per_tile, gt)
    return out.reshape(num * tile, out_degree)[:n]


@functools.partial(jax.jit, static_argnames=("out_degree",))
def _reverse_merge(graph, out_degree: int):
    """Reverse-edge merge (reference: graph_core.cuh optimize tail): half the
    final degree comes from pruned forward edges, half from the highest
    -priority reverse edges."""
    n, k = graph.shape
    fwd_keep = out_degree - out_degree // 2
    rev_keep = out_degree // 2

    # reverse edge priority: rank of u in v's list (lower = stronger).
    # Scatter-free formulation: a (dst, rank) scatter over n·k updates
    # serializes on TPU (measured 520 s at 100k x 64 — XLA lowers
    # non-trivial scatters to a sequential loop). Instead sort edges once by
    # the combined key dst·k + rank (unique, so one stable sort orders by
    # (dst, rank)), then for each destination v GATHER its best incoming
    # sources from the contiguous run [searchsorted(v·k), +rev_keep) — sort +
    # binary search + gather are all TPU-native.
    expects(n * k < 2 ** 31, "reverse merge packs dst*degree+rank into int32; "
            "n*degree=%d overflows — shard the graph first", n * k)
    src = jnp.repeat(jnp.arange(n, dtype=jnp.int32), k)
    key = graph.reshape(-1).astype(jnp.int32) * k + jnp.tile(
        jnp.arange(k, dtype=jnp.int32), n
    )
    s_key, s_src = lax.sort((key, src), num_keys=1)
    starts = jnp.searchsorted(s_key, jnp.arange(n, dtype=jnp.int32) * k)  # (n,)
    # ends[v] == starts[v+1] (all keys < n*k) — no second binary-search sweep
    ends = jnp.concatenate([starts[1:], jnp.array([n * k], starts.dtype)])
    offs = jnp.arange(rev_keep, dtype=jnp.int32)[None, :]
    take = starts[:, None] + offs  # (n, rev_keep)
    valid = take < ends[:, None]
    rev = jnp.where(valid, jnp.take(s_src, jnp.minimum(take, n * k - 1)), -1)

    merged = jnp.concatenate([graph[:, :fwd_keep], rev], axis=1)
    # fill -1 slots (nodes with few reverse edges) from remaining fwd edges
    fill = graph[:, fwd_keep:fwd_keep + rev_keep]
    if fill.shape[1] < rev_keep:
        fill = jnp.pad(fill, ((0, 0), (0, rev_keep - fill.shape[1])), constant_values=-1)
    tail = merged[:, fwd_keep:]
    tail = jnp.where(tail >= 0, tail, fill)
    tail = jnp.where(tail >= 0, tail, graph[:, :rev_keep])  # last resort: dup fwd
    return jnp.concatenate([merged[:, :fwd_keep], tail], axis=1)


def optimize(knn_graph, out_degree: int, res: Resources | None = None):
    """Prune + reverse merge (reference: cagra::optimize → graph_core.cuh)."""
    res = res or default_resources()
    g = jnp.asarray(knn_graph)
    expects(out_degree <= g.shape[1], "out_degree must be <= input degree")
    k = g.shape[1]
    tile = max(min(g.shape[0], res.workspace_bytes // max(k * k * k, 1)), 8)
    pruned = _prune_graph(g, out_degree, min(tile, 4096))
    return _reverse_merge(pruned, out_degree)


@jax.jit
def _neighbor_dist_profile(x, knn_graph, sample_ids):
    """Sorted squared L2 from sampled rows to their knn-graph neighbors —
    the raw material for the seed-pool autotune (one small gather + dot)."""
    xs = x[sample_ids].astype(jnp.float32)  # (t, d)
    vecs = x[knn_graph[sample_ids]].astype(jnp.float32)  # (t, kk, d)
    d2 = jnp.sum((vecs - xs[:, None, :]) ** 2, axis=-1)
    return jnp.sort(d2, axis=1)


# calibrated neighbor-distance jump threshold for clump detection (see
# estimate_seed_pool's docstring for the r05 measurement); interpolated
# into the decision AND the logs so the diagnostics always report the rule
# actually applied (ADVICE r5 low)
_SEED_JUMP_RATIO = 2.0


def estimate_seed_pool(dataset, knn_graph, seed: int = 0) -> int:
    """Measured seed-pool policy (the search-side twin of the r04
    build_n_probes autotune; reference analogue: adjust_search_params,
    detail/cagra/search_plan.cuh:119 — which rescales from itopk alone and
    cannot see data structure).

    Mechanism: the search seeds the beam from a uniformly-sampled pool, and
    the pruned graph rarely crosses between near-duplicate clumps — so
    recall at scale is capped by how many local modes the pool covers
    (BASELINE.md r04 seed_pool sweep: 16384 → 0.880 on ~32k-clump data,
    65536 → 0.979). The clump scale is read off the knn graph the build just
    produced: on multi-scale data each node's sorted neighbor distances jump
    at the clump boundary; the median jump position is the clump size s,
    n/s the mode count M, and pool = ~2M samples seed ≥85% of modes
    (1 - e^-2), which the beam's cross-clump hops finish off.

    The ≥2.0 squared-distance ratio threshold is MEASURED (r05, true-64NN
    profiles over 2048 sampled rows): the SIFT-class 1M set shows a median
    max-ratio of 2.68 at a tight position (~30 = its ~31-point clumps, so
    >50% of rows clear 2.0), while the isotropic clustered set's median is
    1.046 with ZERO rows reaching 2.0 — within-cluster distances ramp
    smoothly (~1.05x steps) and high-dim concentration keeps every
    consecutive ratio near 1. An earlier ≥4.0 threshold missed the real
    clump boundary (~2.7x: the nearest SIBLING clump sits much closer than
    the mean offset) and shipped the 0.880-recall default on exactly the
    data the autotune exists for. Isotropic data keeps the default pool (a
    bigger pool there is a pure QPS loss — r02: -18% QPS for +0.0001
    recall).
    """
    import numpy as np

    x = jnp.asarray(dataset)
    g = jnp.asarray(knn_graph)
    n = x.shape[0]
    if n < 4096 or g.shape[1] < 8:
        return 0  # below any scale where pool coverage binds
    t = min(2048, n)
    rng = np.random.default_rng(seed)
    sample = jnp.asarray(
        np.sort(rng.choice(n, size=t, replace=False)), dtype=jnp.int32)
    d2 = np.asarray(_neighbor_dist_profile(x, g, sample))
    # floor: exact duplicates give d2=0; ratios need a scale-relative floor
    floor = max(float(np.median(d2[:, -1])), 1e-30) * 1e-6
    d2 = np.maximum(d2, floor)
    ratios = d2[:, 1:] / d2[:, :-1]
    jump = ratios.max(axis=1)
    pos = ratios.argmax(axis=1) + 1  # in-clump neighbor count before the jump
    clumpy = jump >= _SEED_JUMP_RATIO  # measured calibration: see docstring
    frac = float(np.mean(clumpy))
    if frac < 0.5:
        logger.info("cagra seed_pool auto: no clump structure (%.0f%% of "
                    "sampled rows show a >=%.0fx neighbor-distance jump; "
                    "median max-ratio %.2f) — default pool", frac * 100,
                    _SEED_JUMP_RATIO, float(np.median(jump)))
        return 0
    s = float(np.median(pos[clumpy])) + 1.0  # + self
    modes = n / s
    pool = 1 << int(np.ceil(np.log2(max(2.0 * modes, 1.0))))
    pool = int(min(max(pool, 0), 131072))
    if pool <= 16384:
        logger.info("cagra seed_pool auto: clump size ~%.0f → ~%.0f modes — "
                    "default pool covers them", s, modes)
        return 0
    logger.info("cagra seed_pool auto: %.0f%% of rows jump >=%.0fx at median "
                "position %.0f → ~%.0f local modes → seed_pool_hint=%d",
                frac * 100, _SEED_JUMP_RATIO, s, modes, pool)
    return pool


@instrument("cagra.build",
            items=lambda a, kw: nrows(a[1] if len(a) > 1 else kw["dataset"]),
            labels=lambda a, kw: {
                "dtype": dtype_of(a[1] if len(a) > 1 else kw["dataset"])})
def build(params: IndexParams, dataset, res: Resources | None = None) -> CagraIndex:
    """Full CAGRA build (reference: cagra::build, cagra.cuh; the int8_t /
    uint8_t instantiations map to byte datasets here: the index stores the
    dataset in its native 8-bit dtype — uint8 shifted by -128 into the s8
    domain, L2-invariant — and the whole build pipeline (IVF-PQ self-search,
    exact refine, pruning) runs on the exact f32 image of those bytes)."""
    from ..core import chunked

    res = res or default_resources()
    stream = chunked.is_reader(dataset)
    if stream:
        # out-of-core ingest: price the streamed upload against BOTH
        # budgets, then land the corpus device-whole through the staged
        # chunk pipeline (the graph build itself runs in-core — CAGRA's
        # scan operand is the dataset)
        n, d = (int(s) for s in dataset.shape)
        kind = (str(dataset.dtype)
                if np.dtype(dataset.dtype) in (np.dtype(np.int8),
                                               np.dtype(np.uint8))
                else "float32")
        pl = obs_mem.plan("cagra", params, n, d, dtype=kind,
                          streamed=True, chunk_rows=dataset.chunk_rows)
        obs_mem.gate(res, pl["build_peak_bytes"], site="build_stream",
                     host_bytes=pl["host_peak_bytes"],
                     detail=f"cagra {n}x{d} streamed")
        x = chunked.device_materialize(dataset, kind="cagra")
    else:
        x = jnp.asarray(dataset)
    expects(x.ndim == 2, "dataset must be (n, d)")
    expects(params.graph_degree <= params.intermediate_graph_degree,
            "graph_degree must be <= intermediate_graph_degree")
    mt = resolve_metric(params.metric)
    expects(
        mt in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
               DistanceType.L2Unexpanded, DistanceType.L2SqrtUnexpanded),
        "cagra supports L2 metrics (reference parity), got %s", mt.name,
    )
    kind = "float32"
    if x.dtype in (jnp.dtype(jnp.int8), jnp.dtype(jnp.uint8)):
        from .brute_force import _as_signed

        kind = str(x.dtype)
        x = _as_signed(x)  # stored (and scored) in the shifted s8 domain
    # memory-budget admission (no-op unless res.memory_budget_bytes is
    # set): refuse BEFORE the knn-graph self-search spends anything; the
    # streamed gate above already priced the chunked upload
    if not stream:
        obs_mem.gate(res, lambda: obs_mem.plan(
            "cagra", params, x.shape[0], x.shape[1],
            dtype=kind)["index_bytes"],
            site="build", detail=f"cagra {x.shape[0]}x{x.shape[1]}")
    t0 = time.perf_counter()
    with tracing.range("cagra.build.knn_graph"):
        knn_graph = build_knn_graph(params, x, res=res)
    if _metrics._enabled:
        jax.block_until_ready(knn_graph)
        _build_metrics.build_phase().observe(time.perf_counter() - t0,
                                             phase="cagra/knn_graph")
    hint = estimate_seed_pool(x, knn_graph, seed=params.seed)
    t0 = time.perf_counter()
    with tracing.range("cagra.build.optimize"):
        graph = optimize(knn_graph, params.graph_degree, res=res)
    if _metrics._enabled:
        jax.block_until_ready(graph)
        _build_metrics.build_phase().observe(time.perf_counter() - t0,
                                 phase="cagra/optimize")
    out = CagraIndex(dataset=x, graph=graph, metric=mt, data_kind=kind,
                     seed_pool_hint=hint)
    obs_mem.account_index(out)  # ledger hook (docs/observability.md)
    return out


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------


@functools.partial(
    jax.jit,
    static_argnames=("k", "itopk", "max_iter", "search_width", "sqrt_out",
                     "seed_pool", "hop_impl"),
)
def _cagra_search(index: CagraIndex, queries, key, k: int, itopk: int,
                  max_iter: int, search_width: int, sqrt_out: bool,
                  seed_pool: int = 16384, hop_impl: str = "xla",
                  keep_mask=None):
    n, d = index.dataset.shape
    m = queries.shape[0]
    deg = index.graph_degree
    qf = queries.astype(jnp.float32)
    data = index.dataset
    dn2 = jnp.sum(data.astype(jnp.float32) ** 2, axis=1)  # (n,) vector norms
    width = search_width
    exp_per_hop = width * deg

    def dist_to(q, ids):
        """Squared L2 from query rows to dataset rows ids: (m, e)."""
        vecs = data[ids]  # (m, e, d)
        dots = jnp.einsum("md,med->me", q, vecs.astype(jnp.float32),
                          precision=lax.Precision.HIGHEST)
        return dn2[ids] - 2.0 * dots  # + ‖q‖² added at the end

    # ---- init beam: entry points (ref: search_plan random_samplings) ----
    n_init = min(max(itopk, exp_per_hop), n)
    pool = min(int(seed_pool), n)  # small datasets: score every point
    if pool > n_init:
        # score a sampled pool with one MXU GEMM, seed per-query best entries
        pool_ids = jax.random.choice(key, n, (pool,), replace=False).astype(jnp.int32)
        pool_vecs = data[pool_ids].astype(jnp.float32)  # (S, d)
        pool_d = dn2[pool_ids][None, :] - 2.0 * jnp.einsum(
            "md,sd->ms", qf, pool_vecs, precision=lax.Precision.DEFAULT
        )  # (m, S)
        if keep_mask is not None:
            # mask the entry pool too: the n_init seeds must be the best
            # SURVIVING pool candidates, or a heavy filter could leave a
            # query with an all-filtered beam while kept rows exist
            pool_d = jnp.where(keep_mask[pool_ids][None, :], pool_d, jnp.inf)
        _, best = lax.top_k(-pool_d, n_init)
        init_ids = pool_ids[best]  # (m, n_init), per-query seeds
        # re-score selected seeds exactly: the bf16 pool scores only pick
        # entries; beam/output distances must match the expanded nodes'
        # HIGHEST-precision scale or near-tie dedup keeps the wrong copy
        init_d = dist_to(qf, init_ids)
    else:
        init_ids = jax.random.choice(key, n, (n_init,), replace=False)
        init_ids = jnp.broadcast_to(init_ids[None, :], (m, n_init)).astype(jnp.int32)
        init_d = dist_to(qf, init_ids)
    if keep_mask is not None:
        # mask epilogue on the entry candidates (same contract as the
        # ivf_pq/ivf_flat scan epilogues): filtered seeds carry +inf scores
        # and can never win a beam slot. Like those scans — and unlike
        # FreshDiskANN's traverse-through-deletes — filtered nodes are not
        # expanded either (each hop's candidates are masked below), so heavy
        # filtering should widen itopk to keep beam coverage.
        init_d = jnp.where(jnp.take(keep_mask, init_ids, axis=0),
                           init_d, jnp.inf)

    pad = itopk + exp_per_hop - n_init
    beam_ids = jnp.pad(init_ids, ((0, 0), (0, max(pad, 0))), constant_values=-1)[:, : itopk + exp_per_hop]
    beam_d = jnp.pad(init_d, ((0, 0), (0, max(pad, 0))), constant_values=jnp.inf)[:, : itopk + exp_per_hop]
    beam_visited = jnp.zeros(beam_ids.shape, jnp.bool_)

    def dedup_sort(ids, dists, visited):
        """Distance-sorted beam with duplicate ids killed (keep closest) —
        the TPU form of the reference's visited hashmap + bitonic itopk.
        Two multi-operand lax.sorts (payloads carried in-sort, no argsort +
        gather rounds): (id, dist)-lexsort groups duplicates with the
        closest copy first, then a dist-sort restores beam order."""
        sid, sd, sv = lax.sort((ids, dists, visited), dimension=1, num_keys=2)
        dup = jnp.concatenate(
            [jnp.zeros((ids.shape[0], 1), jnp.bool_), sid[:, 1:] == sid[:, :-1]], axis=1
        )
        sd = jnp.where(dup | (sid < 0), jnp.inf, sd)
        sd2, sid2, sv2 = lax.sort((sd, sid, sv), dimension=1, num_keys=1)
        return sid2, sd2, sv2

    beam_ids, beam_d, beam_visited = dedup_sort(beam_ids, beam_d, beam_visited)

    if hop_impl in ("fused", "fused_arena", "fused_arena_smem"):
        # one Pallas launch per hop: scoring+dedup+merge+pick with beam state
        # VMEM-resident (VERDICT r4 #1; ops/cagra_hop.py docstring has the
        # profile-driven rationale). Beam distances carry the FULL ||v-q||^2
        # inside this loop (the kernel scores directly), so +qn moves to init.
        from ..ops.cagra_hop import cagra_hop, hop_backend_ok

        _, interpret = hop_backend_ok()
        merge = {"fused": "extract", "fused_arena": "arena",
                 "fused_arena_smem": "arena_smem"}[hop_impl]
        qn = jnp.sum(qf * qf, axis=1, keepdims=True)
        P = 128
        bd = jnp.full((m, P), jnp.inf, jnp.float32
                      ).at[:, :itopk].set(
                          jnp.maximum(beam_d[:, :itopk] + qn, 0.0))
        bi = jnp.full((m, P), -1, jnp.int32).at[:, :itopk].set(
            beam_ids[:, :itopk])
        bv = jnp.ones((m, P), jnp.int32).at[:, :itopk].set(
            beam_visited[:, :itopk].astype(jnp.int32))
        # prime: candidates masked (valid=0) — merge is a no-op re-sort, and
        # the kernel emits the first hop's picks
        cw = width * deg
        zero_nbrs = jnp.full((m, cw), -1, jnp.int32)
        zero_vecs = jnp.zeros((m, cw, d), data.dtype)
        bd, bi, bv, pick, nocand = cagra_hop(
            qf, bd, bi, bv, zero_nbrs, zero_vecs,
            jnp.zeros((m, cw), jnp.int32), itopk, width,
            interpret=interpret, merge=merge)

        def fcond(state):
            _, _, _, _, nocand, it = state
            # a query is done when its FIRST pick found nothing unvisited
            # (picks are best-first, so later picks can only also fail)
            return jnp.logical_and(it < max_iter,
                                   jnp.logical_not(jnp.all(nocand[:, 0] > 0)))

        def fbody(state):
            bd, bi, bv, pick, nocand, it = state
            safe = jnp.minimum(pick, n - 1)              # (m, width)
            nbrs = index.graph[safe].reshape(m, cw)      # (m, width*deg)
            # native-dtype gather: byte datasets move 1 byte/dim into the
            # kernel (a quarter of f32 DMA bytes); the f32 upcast happens
            # INSIDE the kernel at the tile level (exact for 8-bit values)
            vecs = data[jnp.maximum(nbrs, 0)]
            valid = jnp.repeat(1 - nocand, deg, axis=1)  # per-candidate
            if keep_mask is not None:
                # filtered candidates ride the kernel's existing validity
                # lane — masked before the in-VMEM merge/select, zero extra
                # kernel passes
                valid = valid * jnp.take(
                    keep_mask, jnp.maximum(nbrs, 0), axis=0).astype(jnp.int32)
            bd, bi, bv, pick, nocand = cagra_hop(
                qf, bd, bi, bv, nbrs, vecs, valid, itopk, width,
                interpret=interpret, merge=merge)
            return bd, bi, bv, pick, nocand, it + 1

        bd, bi, bv, _, _, _ = lax.while_loop(
            fcond, fbody, (bd, bi, bv, pick, nocand, 0))
        if merge in ("arena", "arena_smem"):
            # arena beam is unsorted — one final sort (the XLA path pays a
            # sort per hop; arena pays it once here)
            from ..matrix.select_k import _select_k

            bd, bi = _select_k(bd, bi, itopk, True)
        out_d = jnp.maximum(bd[:, :k], 0.0)
        if sqrt_out:
            out_d = jnp.sqrt(out_d)
        # slots the (possibly filtered) beam never filled report the shared
        # empty-slot sentinel: id -1 with the +inf score already in place
        return out_d, jnp.where(jnp.isinf(out_d), -1, bi[:, :k])

    def cond(state):
        _, _, visited, it, done = state
        return jnp.logical_and(it < max_iter, jnp.logical_not(done))

    def body(state):
        ids, dists, visited, it, _ = state
        # pick the best `width` unvisited entries within the itopk window
        # (the all-converged early-exit check rides along: an r04 interleaved
        # A/B measured it free at m=10k — 28.5-31.0k QPS with vs 28.4-29.6k
        # without — so it stays unconditional)
        cand_d = jnp.where(visited[:, :itopk], jnp.inf, dists[:, :itopk])
        pick = jnp.argsort(cand_d, axis=1, stable=True)[:, :width]  # (m, w)
        pick_ids = jnp.take_along_axis(ids, pick, axis=1)  # (m, w)
        no_cand = jnp.all(jnp.isinf(jnp.take_along_axis(cand_d, pick, axis=1)), axis=1)
        visited = visited.at[jnp.arange(m)[:, None], pick].set(True)

        # expand: gather adjacency rows (ref: single-CTA graph row fetch)
        safe_pick = jnp.maximum(pick_ids, 0)
        nbrs = index.graph[safe_pick].reshape(m, exp_per_hop)  # (m, w*deg)
        nbrs = jnp.where(pick_ids.repeat(deg, axis=1) >= 0, nbrs, -1)
        ok = nbrs >= 0
        if keep_mask is not None:
            # candidate mask epilogue: filtered expansions score +inf before
            # the beam merge select (the ivf scan epilogue contract)
            ok = ok & jnp.take(keep_mask, jnp.maximum(nbrs, 0), axis=0)
        nd = jnp.where(ok, dist_to(qf, jnp.maximum(nbrs, 0)), jnp.inf)

        # merge expansions into the beam tail, re-sort, dedup
        ids = ids.at[:, itopk:].set(nbrs)
        dists = dists.at[:, itopk:].set(nd)
        visited = visited.at[:, itopk:].set(False)
        ids, dists, visited = dedup_sort(ids, dists, visited)

        done = jnp.all(no_cand)
        return ids, dists, visited, it + 1, done

    beam_ids, beam_d, beam_visited, _, _ = lax.while_loop(
        cond, body, (beam_ids, beam_d, beam_visited, 0, False)
    )

    out_d = beam_d[:, :k] + jnp.sum(qf * qf, axis=1, keepdims=True)
    out_d = jnp.maximum(out_d, 0.0)
    if sqrt_out:
        out_d = jnp.sqrt(out_d)
    # slots the (possibly filtered) beam never filled report the shared
    # empty-slot sentinel: id -1 with the +inf score already in place
    return out_d, jnp.where(jnp.isinf(out_d), -1, beam_ids[:, :k])


def resolve_max_iterations(params: SearchParams) -> int:
    """Default hop budget (reference: adjust_search_params, cagra_search.cuh)."""
    return params.max_iterations or (
        params.itopk_size // max(params.search_width, 1) + 10)


def resolve_seed_pool(params: SearchParams, hint: int = 0) -> int:
    """seed_pool=-1 (auto) → the index's measured hint, else the r02 default.
    Shared by the single-chip and distributed drivers so -1 never leaks into
    _cagra_search (where a negative pool would silently mean random entries)."""
    pool = int(params.seed_pool)
    if pool < 0:
        pool = int(hint) or 16384
    return pool


def resolve_hop_impl(params: SearchParams, graph_degree: int, dim: int,
                     itemsize: int = 4) -> str:
    """Validate + resolve ``params.hop_impl`` (shared by the single-chip and
    distributed searches — same eligibility rules, same clear errors).
    ``itemsize`` is the dataset element size: byte datasets stage a quarter
    of the candidate-block VMEM, widening fused eligibility at high d."""
    from ..ops.cagra_hop import hop_backend_ok, hop_shapes_eligible

    expects(params.hop_impl in ("auto", "xla", "fused", "fused_arena",
                                "fused_arena_smem"),
            "hop_impl must be 'auto', 'xla', 'fused', 'fused_arena' or "
            "'fused_arena_smem', got %r", params.hop_impl)
    eligible = (hop_backend_ok()[0] and hop_shapes_eligible(
        params.itopk_size, graph_degree, params.search_width, dim,
        itemsize=itemsize))
    if params.hop_impl == "auto":
        # fused_arena is the measured winner (r05 study, BASELINE.md):
        # 41-42k vs 32-33k XLA QPS at 1M itopk=32, identical 0.9714 recall
        # (1.27x in-process); plain "fused" (sorted extraction merge)
        # measured NEUTRAL and stays as the study's control
        return "fused_arena" if eligible else "xla"
    if params.hop_impl in ("fused", "fused_arena", "fused_arena_smem"):
        expects(eligible, "hop_impl='fused' needs itopk + "
                "search_width*graph_degree <= 128, the staged candidate "
                "block (128*search_width*graph_degree*d_pad*itemsize bytes, "
                "double-buffered) within the kernel VMEM budget, and a TPU "
                "backend (or RAFT_TPU_CAGRA_HOP_INTERPRET=1 for tests); "
                "got itopk=%d width=%d degree=%d d=%d itemsize=%d",
                params.itopk_size, params.search_width, graph_degree, dim,
                itemsize)
    return params.hop_impl


@instrument(
    "cagra.search",
    items=lambda a, kw: nrows(a[2] if len(a) > 2 else kw["queries"]),
    labels=lambda a, kw: {"k": a[3] if len(a) > 3 else kw["k"],
                          "itopk": (a[0] if a else kw["params"]).itopk_size},
)
@auto_convert_output
def search(params: SearchParams, index: CagraIndex, queries, k: int,
           sample_filter=None, res: Resources | None = None):
    """Batch-synchronous beam search (reference: cagra::search,
    cagra_search.cuh:70; SINGLE_CTA persistent kernel re-shaped for SPMD).

    ``sample_filter`` is an optional
    :class:`~raft_tpu.neighbors.sample_filter.BitsetFilter` / boolean
    keep-mask over dataset rows — the same ``resolve_filter`` /
    ``validate_filter_covers`` contract as ivf_pq/ivf_flat: filtered
    candidates take +inf scores in the mask epilogue BEFORE the beam select,
    and slots the filtered beam cannot fill report id -1 with +inf distance.
    Filtered nodes are also not expanded (unlike FreshDiskANN's
    traverse-through-deletes), so at heavy filter ratios widen
    ``itopk_size`` to preserve recall."""
    from .brute_force import _coerce_queries
    from .sample_filter import resolve_filter, validate_filter_covers

    res = res or default_resources()
    queries = jnp.asarray(queries)
    expects(queries.ndim == 2 and queries.shape[1] == index.dim, "query dim mismatch")
    expects(k <= params.itopk_size, "k must be <= itopk_size (ref cagra_types.hpp:66)")
    # byte indexes: integer queries must match the index dtype and shift
    # with it; float queries against a uint8 index shift by -128 (same
    # contract as ivf_flat/ivf_pq)
    queries = _coerce_queries(index.data_kind, queries)
    itopk = params.itopk_size
    max_iter = resolve_max_iterations(params)
    sqrt_out = index.metric in (DistanceType.L2SqrtExpanded, DistanceType.L2SqrtUnexpanded)
    pool = resolve_seed_pool(params, index.seed_pool_hint)
    impl = resolve_hop_impl(params, index.graph_degree, index.dim,
                            itemsize=index.dataset.dtype.itemsize)
    keep_mask = resolve_filter(sample_filter)
    if keep_mask is not None:
        validate_filter_covers(index, keep_mask)
    return _cagra_search(index, queries, as_key(params.seed), int(k),
                         int(itopk), int(max_iter),
                         int(params.search_width), sqrt_out, pool, impl,
                         keep_mask)


def write_index(f, index: CagraIndex) -> None:
    """Serialize to an open binary stream (the composable half of
    :func:`save` — :mod:`raft_tpu.stream` embeds sealed indexes this way)."""
    serialize_header(f, "cagra")
    serialize_scalar(f, int(index.metric))
    serialize_scalar(f, int(index.seed_pool_hint))
    serialize_scalar(f, index.data_kind)
    serialize_mdspan(f, index.dataset)
    serialize_mdspan(f, index.graph)
    serialize_tuned(f, index.tuned)


def read_index(f) -> CagraIndex:
    """Deserialize from an open binary stream (pairs with
    :func:`write_index`)."""
    ver = check_header(f, "cagra")
    metric = DistanceType(deserialize_scalar(f))
    # raft_tpu/4 added the measured seed_pool_hint; older files search
    # with the default pool (correct, just not data-tuned)
    hint = deserialize_scalar(f) if ver not in (
        "raft_tpu/2", "raft_tpu/3") else 0
    # raft_tpu/6 added data_kind (byte datasets); older files could
    # only hold float data
    kind = deserialize_scalar(f) if ver not in (
        "raft_tpu/2", "raft_tpu/3", "raft_tpu/4", "raft_tpu/5") else "float32"
    dataset = jnp.asarray(deserialize_mdspan(f))
    graph = jnp.asarray(deserialize_mdspan(f))
    # raft_tpu/9 appended the optional tuned record (pinned operating
    # point); older files are untuned
    tuned = deserialize_tuned(f, ver)
    return CagraIndex(dataset=dataset, graph=graph, metric=metric,
                      data_kind=kind, seed_pool_hint=hint, tuned=tuned)


def save(index: CagraIndex, path: str) -> None:
    """Serialize (reference: cagra_serialize.cuh).
    Atomic: temp file + rename, a crashed save keeps the previous file."""
    from ..core.serialize import atomic_write

    with atomic_write(path) as f:
        write_index(f, index)


def load(path: str, res: Resources | None = None) -> CagraIndex:
    with open(path, "rb") as f:
        return read_index(f)


def batched_searcher(index: CagraIndex, params: SearchParams | None = None):
    """Stable serving hook (raft_tpu.serve; contract in :mod:`._hooks`) —
    the surface the serve registry warms and hot-swaps through. The serving
    ``k`` must satisfy ``k <= itopk_size`` (search()'s own precondition).
    With no explicit ``params``, an attached tune decision (``index.tuned``,
    e.g. restored by a raft_tpu/9 load) supplies the pinned operating
    point — docs/tuning.md."""
    from ._hooks import make_hook

    if params is None and index.tuned is not None:
        from ..tune.apply import make_searcher as tuned_searcher

        return tuned_searcher(index, True, degrade_without_rows=True)
    sp = params or SearchParams()
    return make_hook(lambda queries, k: search(sp, index, queries, k),
                     "cagra", index.dim, index.data_kind)
