"""IVF-Flat: inverted-file index over raw vectors.

Re-design of the reference's IVF-Flat (cpp/include/raft/neighbors/ivf_flat-inl.cuh;
build detail/ivf_flat_build.cuh — balanced-kmeans coarse quantizer, interleaved
list groups :86,135-153; search detail/ivf_flat_search-inl.cuh:130 — coarse GEMM
+ select_k, fused interleaved scan). The TPU re-think:

- **List layout**: the reference interleaves vectors in groups of 32 for
  coalesced warp reads; the TPU analogue is a dense padded (n_lists, capacity,
  d) array — capacity is the max list size rounded to the f32 sublane tile (8),
  balanced k-means keeps the padding overhead small, and every scan is a
  contiguous block DMA.
- **Search**: coarse scoring is one MXU GEMM + select_k (same two-stage shape
  as the reference); the list scan gathers each query's probed lists and
  scores them with an einsum that contracts d on the MXU, tiled over
  (query-tile, probe-chunk) under lax.map so the gathered block stays inside
  the workspace budget. Stored-vector norms are precomputed at build, so L2
  scores are ‖v‖² - 2·q·v — no recomputation per query.
- **Static shapes**: probes, capacity, k are all static; padding slots carry
  +inf scores and id -1, and can never win select_k.
"""

from __future__ import annotations

from ..config import auto_convert_output

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..cluster import kmeans_balanced
from ..cluster.kmeans_balanced import KMeansBalancedParams
from ..core import chunked, tracing
from ..core.errors import expects
from ..core.resources import Resources, default_resources
from ..core.serialize import (check_header, deserialize_mdspan, deserialize_scalar,
                              deserialize_tuned, serialize_header,
                              serialize_mdspan, serialize_scalar,
                              serialize_tuned)
from ..distance.pairwise import _choose_tile
from ..distance.types import DistanceType, resolve_metric
from ..matrix.select_k import _select_k
from ..obs.instrument import dtype_of, instrument, nrows
from ..obs import mem as obs_mem
from ._list_utils import (assign_to_lists, bound_capacity, list_positions,
                          plan_search_tiles, round_up)

__all__ = ["IndexParams", "SearchParams", "IvfFlatIndex", "build", "extend", "search", "save", "load"]


@dataclasses.dataclass(frozen=True)
class IndexParams:
    """Reference: ivf_flat::index_params (neighbors/ivf_flat_types.hpp)."""

    n_lists: int = 1024
    metric: str | DistanceType = "sqeuclidean"
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    # coarse-trainer EM cost policy (KMeansBalancedParams.train_mode /
    # batch_rows): "auto" runs mini-batch EM when the trainset exceeds
    # 2 x kmeans_batch_rows — at 1M scale this collapses the ~22
    # full-trainset assignment passes (the Round-6-measured dominant build
    # cost) to the two closing passes. "full" pins the pre-r07 behavior.
    kmeans_train_mode: str = "auto"
    kmeans_batch_rows: int = 65536
    add_data_on_build: bool = True
    seed: int = 0
    # storage dtype of list vectors (reference: the float/half/int8_t/uint8_t
    # ivf_flat instantiations, cpp/src/neighbors/ivf_flat_build_*.cu):
    #   "auto"     — float32 for float data, int8 for int8/uint8 data.
    #   "bfloat16" — halves the scan's HBM gather traffic (the 1M-scale
    #                bottleneck) at negligible recall cost; norms stay f32,
    #                scoring accumulates in f32 on the MXU.
    #   "int8"     — RAW 8-bit data stored as-is (uint8 shifted by -128 into
    #                the s8 domain; L2 is shift-invariant): 1-byte gathers
    #                (half of bf16) and s8 x s8 -> s32 MXU scoring with
    #                EXACT integer partial scores. Requires int8/uint8 input
    #                (quantized storage for float data is IVF-PQ's job).
    #   "float32"  — float storage for any input.
    list_dtype: str = "auto"
    # capacity bound for sub-list splitting, as a multiple of the mean list
    # size (see _list_utils.bound_capacity). 1.3 measured +24% search QPS at
    # identical 0.9999 recall vs 2.0 at 1M x 128 (the scan is bound by
    # padded-gather bytes; sibling sub-lists tie in coarse score and are
    # probed together, so tighter capacity costs no coverage here)
    split_factor: float = 1.3


@dataclasses.dataclass(frozen=True)
class SearchParams:
    """Reference: ivf_flat::search_params (neighbors/ivf_flat_types.hpp)."""

    n_probes: int = 20


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class IvfFlatIndex:
    """Reference: ivf_flat::index (neighbors/ivf_flat_types.hpp:224)."""

    centers: jax.Array  # (n_lists, d) f32
    list_data: jax.Array  # (n_lists, capacity, d)
    list_ids: jax.Array  # (n_lists, capacity) int32, -1 = padding
    list_norms: jax.Array  # (n_lists, capacity) f32, +inf on padding
    list_sizes: jax.Array  # (n_lists,) int32
    metric: DistanceType
    # build-time capacity policy; extend() inherits it so the no-split /
    # split behavior chosen at build survives incremental additions
    split_factor: float = 1.3
    # what the list vectors ARE: "float32"/"bfloat16" (float storage),
    # "int8" (signed bytes as given), "uint8" (bytes stored shifted by
    # -128 into the s8 domain — queries shift the same way at search)
    data_kind: str = "float32"
    # pinned operating point (raft_tpu.tune decision dict; None = untuned):
    # consulted by batched_searcher when no explicit params are given,
    # persisted by save/load (raft_tpu/9). NOT part of the pytree (same
    # contract as cagra's seed_pool_hint): tree round trips drop it back
    # to None — defaults, never an error.
    tuned: dict | None = None

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def capacity(self) -> int:
        return self.list_data.shape[1]

    @property
    def size(self) -> int:
        """Total stored vectors. Computed on host so it stays concrete even
        when an enclosing jit trace is active (e.g. a user wrapping search()
        in jax.jit captures the index as a closure constant — staging the sum
        would make int() fail on a tracer). Unavailable when the index itself
        is a traced jit argument."""
        import numpy as np

        return int(np.asarray(jax.device_get(self.list_sizes)).sum())

    def tree_flatten(self):
        return (
            (self.centers, self.list_data, self.list_ids, self.list_norms, self.list_sizes),
            (self.metric, self.split_factor, self.data_kind),
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        metric, split_factor, kind = (aux if len(aux) == 3
                                      else (*aux, "float32"))
        return cls(*children, metric=metric, split_factor=split_factor,
                   data_kind=kind)


def _count_fill_pass(kb: KMeansBalancedParams, n: int) -> None:
    """Count the build's list-fill assignment pass (one full-dataset
    nearest-center pass outside the trainer's fit) under the same
    raft_tpu_build_* series the trainer emits, so driver="single" and
    driver="distributed" report the identical em/final/fill decomposition
    (docs/observability.md). Shared by the ivf_flat and ivf_pq builds."""
    from ..obs import build as build_metrics
    from ..obs import metrics as _metrics

    if not _metrics._enabled:
        return
    mode = kmeans_balanced.resolve_train_mode(
        kb.train_mode, min(kb.max_train_points or n, n), kb.batch_rows)
    build_metrics.assignment_passes().inc(1, phase="fill", mode=mode,
                                          driver="single")


@functools.partial(jax.jit, static_argnames=("n_lists", "capacity"))
def _fill_lists(x, ids, labels, n_lists: int, capacity: int):
    """Scatter vectors into padded lists (ref: ivf_flat_build.cuh:160
    process-and-fill; one vectorized scatter instead of per-vector atomics)."""
    n, d = x.shape
    pos, counts = list_positions(labels, n_lists)
    data = jnp.zeros((n_lists, capacity, d), x.dtype)
    idbuf = jnp.full((n_lists, capacity), -1, jnp.int32)
    norms = jnp.full((n_lists, capacity), jnp.inf, jnp.float32)
    data = data.at[labels, pos].set(x)
    idbuf = idbuf.at[labels, pos].set(ids.astype(jnp.int32))
    xf = x.astype(jnp.float32)
    norms = norms.at[labels, pos].set(jnp.sum(xf * xf, axis=1))
    return data, idbuf, norms, counts.astype(jnp.int32)


def _resolve_storage(list_dtype: str, x, mt: DistanceType):
    """Resolve the list_dtype policy for a dataset: returns (data_kind,
    storage-domain x, f32 working view). Shared by the single-chip build and
    the distributed build (parallel/ivf.py) so both ingest int8/uint8
    identically."""
    expects(list_dtype in ("auto", "float32", "bfloat16", "int8"),
            "list_dtype must be 'auto', 'float32', 'bfloat16' or 'int8', "
            "got %r", list_dtype)
    int_in = x.dtype in (jnp.dtype(jnp.int8), jnp.dtype(jnp.uint8))
    ld = list_dtype
    if ld == "auto":
        ld = "int8" if int_in else "float32"
    if ld == "int8":
        expects(int_in, "list_dtype='int8' stores raw 8-bit data; got a %s "
                "dataset (quantized storage for float data is IVF-PQ's "
                "job)", x.dtype)
        # uint8 under IP is NOT shift-invariant and the per-vector sum
        # correction is not stored; int8 IP needs no shift and is exact
        expects(mt != DistanceType.InnerProduct or x.dtype == jnp.int8,
                "uint8 + inner_product is unsupported in int8 storage "
                "(the -128 shift changes inner products); use "
                "list_dtype='float32'")
        kind = str(x.dtype)
        from .brute_force import _as_signed

        x = _as_signed(x)  # all further work in the shifted s8 domain
        return kind, x, x.astype(jnp.float32)
    x = x.astype(jnp.float32) if int_in else x
    return ld, x, x.astype(jnp.float32)


def _stream_probe(dtype, d: int):
    """A zero-row device array in the reader's CANONICALIZED dtype: lets
    :func:`_resolve_storage` run its full validation/resolution without the
    corpus ever materializing. Canonicalization through the device matches
    what ``jnp.asarray`` does to the in-core twin (f64 host rows land f32),
    so the resolved storage dtype is identical in both modes."""
    return jnp.asarray(np.zeros((0, d), dtype))


def _stream_f32_view(kind: str):
    """Device-side conversion raw chunk -> the f32 working view the coarse
    trainer sees — the streamed twin of :func:`_resolve_storage`'s third
    return. Elementwise (byte shift, upcast), so it COMMUTES with the
    trainset row gather: ``convert(take(corpus, idx)) ==
    take(convert(corpus), idx)`` bitwise — half the bit-equality
    contract (core/chunked module docstring)."""
    if kind in ("int8", "uint8"):
        from .brute_force import _as_signed

        return lambda v: _as_signed(v).astype(jnp.float32)
    return lambda v: v.astype(jnp.float32)


@instrument("ivf_flat.build",
            items=lambda a, kw: nrows(a[1] if len(a) > 1 else kw["dataset"]),
            labels=lambda a, kw: {
                "dtype": dtype_of(a[1] if len(a) > 1 else kw["dataset"]),
                "n_lists": (a[0] if a else kw["params"]).n_lists,
            })
def build(params: IndexParams, dataset, res: Resources | None = None) -> IvfFlatIndex:
    """Build the index (reference: ivf_flat::build, ivf_flat-inl.cuh;
    coarse centers via balanced k-means on a training subsample, then fill)."""
    res = res or default_resources()
    stream = chunked.is_reader(dataset)
    x = None if stream else jnp.asarray(dataset)
    src = dataset if stream else x
    expects(src.ndim == 2, "dataset must be (n, d)")
    n, d = (int(s) for s in src.shape)
    expects(params.n_lists <= n, "n_lists > n_samples")
    mt = resolve_metric(params.metric)
    expects(
        mt
        in (
            DistanceType.L2Expanded,
            DistanceType.L2SqrtExpanded,
            DistanceType.L2Unexpanded,
            DistanceType.L2SqrtUnexpanded,
            DistanceType.InnerProduct,
        ),
        "ivf_flat supports L2 / inner_product metrics, got %s",
        mt.name,
    )

    if stream:
        # dtype-only storage resolution (same validation, on an empty
        # probe — the corpus never materializes here), then the STREAMED
        # admission: price the chunked build peak against BOTH budgets
        # before the coarse trainer spends anything
        kind, probe_x, _ = _resolve_storage(
            params.list_dtype, _stream_probe(dataset.dtype, d), mt)
        plan_kw = dict(
            dtype=kind if kind in ("int8", "uint8", "bfloat16") else "float32",
            streamed=True, chunk_rows=dataset.chunk_rows)
        obs_mem.gate(
            res,
            lambda: obs_mem.plan("ivf_flat", params, n, d,
                                 **plan_kw)["build_peak_bytes"],
            site="build_stream", detail=f"ivf_flat {n}x{d} ooc",
            host_bytes=lambda: obs_mem.plan("ivf_flat", params, n, d,
                                            **plan_kw)["host_peak_bytes"])
        xf = chunked.converted(dataset, _stream_f32_view(kind))
    else:
        kind, x, xf = _resolve_storage(params.list_dtype, x, mt)
        # memory-budget admission (no-op unless res.memory_budget_bytes is
        # set): refuse BEFORE the coarse trainer spends anything
        obs_mem.gate(res, lambda: obs_mem.plan(
            "ivf_flat", params, n, d,
            dtype=kind if kind in ("int8", "uint8", "bfloat16") else "float32"
        )["index_bytes"], site="build", detail=f"ivf_flat {n}x{d}")
    max_train = max(int(n * params.kmeans_trainset_fraction), params.n_lists)
    train_metric = "inner_product" if mt == DistanceType.InnerProduct else "sqeuclidean"
    kb = KMeansBalancedParams(
        n_iters=params.kmeans_n_iters, metric=train_metric, seed=params.seed,
        max_train_points=min(max_train, n),
        train_mode=params.kmeans_train_mode,
        batch_rows=params.kmeans_batch_rows,
    )
    with tracing.range("ivf_flat.build.coarse_kmeans"):
        centers = kmeans_balanced.fit(kb, xf, params.n_lists, res=res)
    if params.add_data_on_build:
        _count_fill_pass(kb, n)

    storage = {"bfloat16": jnp.bfloat16, "int8": jnp.int8,
               "uint8": jnp.int8}.get(kind, probe_x.dtype if stream
                                      else x.dtype)

    if not params.add_data_on_build:
        cap = 8
        empty = IvfFlatIndex(
            centers=centers,
            list_data=jnp.zeros((params.n_lists, cap, d), storage),
            list_ids=jnp.full((params.n_lists, cap), -1, jnp.int32),
            list_norms=jnp.full((params.n_lists, cap), jnp.inf, jnp.float32),
            list_sizes=jnp.zeros((params.n_lists,), jnp.int32),
            metric=mt,
            split_factor=params.split_factor,
            data_kind=kind,
        )
        obs_mem.account_index(empty)
        return empty

    seed = IvfFlatIndex(
        centers=centers,
        list_data=jnp.zeros((params.n_lists, 0, d), storage),
        list_ids=jnp.zeros((params.n_lists, 0), jnp.int32),
        list_norms=jnp.zeros((params.n_lists, 0), jnp.float32),
        list_sizes=jnp.zeros((params.n_lists,), jnp.int32),
        metric=mt,
        split_factor=params.split_factor,
        data_kind=kind,
    )
    if stream:
        return _extend_stream_signed(seed, dataset, None, res=res)
    return _extend_signed(seed, x, jnp.arange(n, dtype=jnp.int32), res=res)


# host batches past this size stream through the chunked path instead of
# one whole-batch ``jnp.asarray`` — the extend() full-materialization fix
# (a 1M x 128 f32 batch is 512 MiB of device scratch the chunked path
# replaces with two 32 MiB staged chunks)
_STREAM_EXTEND_BYTES = 256 << 20


@instrument("ivf_flat.extend",
            items=lambda a, kw: nrows(a[1] if len(a) > 1 else kw["new_vectors"]))
def extend(index: IvfFlatIndex, new_vectors, new_ids=None, res: Resources | None = None,
           split_factor: float | None = None) -> IvfFlatIndex:
    """Append vectors (reference: ivf_flat::extend, ivf_flat-inl.cuh:160,287).

    Capacity is data-dependent, so extend re-packs lists host-orchestrated:
    existing + new vectors are re-scattered into a freshly sized padded array
    (the reference reallocates lists too — ivf_list.hpp resize).

    A :class:`~raft_tpu.core.chunked.ChunkedReader` batch (or any host
    ndarray past ``_STREAM_EXTEND_BYTES``) takes the out-of-core path:
    per-chunk assign + scatter, never materializing the batch on device."""
    if (not chunked.is_reader(new_vectors)
            and isinstance(new_vectors, np.ndarray)
            and new_vectors.ndim == 2
            and new_vectors.nbytes > _STREAM_EXTEND_BYTES):
        new_vectors = chunked.ChunkedReader(new_vectors)
    if chunked.is_reader(new_vectors):
        return _extend_stream_signed(index, new_vectors, new_ids, res=res,
                                     split_factor=split_factor)
    x = jnp.asarray(new_vectors)
    if index.data_kind in ("int8", "uint8"):
        # 8-bit indexes take vectors in the index's ORIGINAL dtype; a plain
        # astype would wrap uint8 values mod 256 instead of shifting them
        expects(str(x.dtype) == index.data_kind,
                "this index stores %s vectors; got %s", index.data_kind,
                x.dtype)
        from .brute_force import _as_signed

        x = _as_signed(x)
    return _extend_signed(index, x, new_ids, res=res,
                          split_factor=split_factor)


def _extend_signed(index: IvfFlatIndex, new_vectors, new_ids=None,
                   res: Resources | None = None,
                   split_factor: float | None = None) -> IvfFlatIndex:
    """extend() after domain conversion: vectors already live in the index's
    storage domain (s8-shifted for uint8 kinds)."""
    res = res or default_resources()
    # storage dtype travels with the index (build's list_dtype choice)
    x = jnp.asarray(new_vectors).astype(index.list_data.dtype)
    expects(x.ndim == 2 and x.shape[1] == index.dim, "vector dim mismatch")
    n_new = x.shape[0]
    if new_ids is None:
        new_ids = index.size + jnp.arange(n_new, dtype=jnp.int32)
    else:
        new_ids = jnp.asarray(new_ids, jnp.int32)

    tile = _choose_tile(n_new, index.n_lists, 1, res.workspace_bytes)
    xa = x.astype(jnp.float32) if x.dtype == jnp.int8 else x
    with tracing.range("ivf_flat.extend.assign"):
        labels = assign_to_lists(xa, index.centers, index.metric, tile)

    # merge with existing list contents (flatten old lists back to rows)
    if index.capacity > 0 and index.size > 0:
        old_mask = index.list_ids.reshape(-1) >= 0
        old_x = index.list_data.reshape(-1, index.dim)[old_mask]
        old_ids = index.list_ids.reshape(-1)[old_mask]
        old_labels = jnp.repeat(jnp.arange(index.n_lists), index.capacity)[old_mask]
        x = jnp.concatenate([old_x, x])
        new_ids = jnp.concatenate([old_ids, new_ids])
        labels = jnp.concatenate([old_labels.astype(jnp.int32), labels])

    import numpy as np

    # shared capacity policy: hot lists split into sub-lists. SEVERELY
    # oversized lists (>= 8x the cap — a mega-cluster the coarse trainer
    # could not divide) split SPATIALLY into principal-axis slabs and get
    # their OWN member-mean centers below; mild splits keep the order
    # split + duplicated centers (bound_capacity decides — see its
    # docstring for the measured rationale). IVF-Flat can re-center freely
    # because centers only drive probing/assignment, not scoring (the scan
    # reads raw vectors); differentiated centers let a query probe only
    # its nearby slabs instead of ~n_probes arbitrary siblings.
    sf = index.split_factor if split_factor is None else split_factor
    labels, rep, n_lists, capacity, spatial = bound_capacity(
        labels, index.n_lists, sf, x=x.astype(jnp.float32))
    centers = index.centers
    with tracing.range("ivf_flat.extend.fill_lists"):
        data, idbuf, norms, sizes = _fill_lists(x, new_ids, labels, n_lists, capacity)
    if rep is not None:
        centers = jnp.asarray(np.repeat(np.asarray(centers), rep, axis=0))
        if spatial is not None and spatial.any():
            # recenter EXACTLY the slab-ordered lists' children on their
            # member means (bound_capacity's per-list gate — keeping the
            # two sides aligned so order-split siblings are never
            # recentered, the regime measured worse)
            mask = idbuf >= 0
            sums = jnp.sum(jnp.where(mask[..., None],
                                     data.astype(jnp.float32), 0.0), axis=1)
            means = sums / jnp.maximum(sizes, 1)[:, None].astype(jnp.float32)
            child = jnp.asarray(np.repeat(spatial, rep))
            centers = jnp.where(child[:, None], means, centers)
    out = IvfFlatIndex(centers, data, idbuf, norms, sizes, index.metric, sf,
                       index.data_kind)
    # ledger hook (docs/observability.md): the new padded lists are the
    # long-lived allocation; the superseded index's entry auto-releases
    # when the caller drops it
    obs_mem.account_index(out)
    return out


@functools.partial(jax.jit, static_argnames=("n_lists",),
                   donate_argnums=(0, 1, 2, 3))
def _fill_chunk(data, idbuf, norms, offsets, x, ids, labels, n_lists: int):
    """One streamed scatter pass: place a chunk's rows at their running
    within-list offsets (``offsets`` carries each list's fill level across
    chunks — chunk-local rank + prior count equals the full-array rank
    ``list_positions`` would assign, since both orderings are stable by
    input position). Pad rows arrive labelled ``n_lists`` (one past the
    last list): their position math lands in the sentinel slot of the
    extended count/offset vectors and the scatter drops them out of
    bounds — no host-side filtering, so the chunk loop never syncs.
    Donation reuses the accumulator buffers in place, which is what keeps
    the build's device peak FLAT in chunk count."""
    pos_local, counts = list_positions(labels, n_lists + 1)
    offs = jnp.concatenate([offsets, jnp.zeros((1,), jnp.int32)])
    pos = pos_local + jnp.take(offs, labels)
    data = data.at[labels, pos].set(x, mode="drop")
    idbuf = idbuf.at[labels, pos].set(ids.astype(jnp.int32), mode="drop")
    xf = x.astype(jnp.float32)
    norms = norms.at[labels, pos].set(jnp.sum(xf * xf, axis=1), mode="drop")
    return data, idbuf, norms, offsets + counts[:n_lists]


def _extend_stream_signed(index: IvfFlatIndex, reader, new_ids=None,
                          res: Resources | None = None,
                          split_factor: float | None = None) -> IvfFlatIndex:
    """The streamed twin of :func:`_extend_signed`: two passes over the
    reader's chunks (assign, then scatter) instead of one whole-corpus
    device array. Bit-equal to the in-core path because every per-row
    quantity — ingest conversion, nearest-center label, within-list rank,
    norm — comes from the SAME helpers and none couples rows across a
    batch (tests/test_ooc_build.py asserts the full-index equality). The
    one intentional divergence: ``bound_capacity``'s spatial mega-cluster
    split needs the whole corpus on device, so severely oversized lists
    fall back to the order split here. Device peak is index accumulators
    + two staged chunks + the label/id vectors — CONSTANT in corpus rows
    beyond the index itself (the ``ooc_build`` bench row's claim)."""
    from ..obs import build as build_metrics
    from ..obs import metrics as _metrics

    res = res or default_resources()
    n_new, d = (int(s) for s in reader.shape)
    expects(d == index.dim, "vector dim mismatch")
    storage_dt = index.list_data.dtype
    if index.data_kind in ("int8", "uint8"):
        expects(str(reader.dtype) == index.data_kind,
                "this index stores %s vectors; got %s", index.data_kind,
                reader.dtype)
        from .brute_force import _as_signed

        def ingest(v):
            return _as_signed(v).astype(storage_dt)
    else:
        def ingest(v):
            return v.astype(storage_dt)

    if new_ids is None:
        new_ids = index.size + jnp.arange(n_new, dtype=jnp.int32)
    else:
        new_ids = jnp.asarray(new_ids, jnp.int32)
        expects(int(new_ids.shape[0]) == n_new, "ids/vectors length mismatch")

    cr = int(reader.chunk_rows)
    emit = _metrics.enabled()
    stager = chunked.ChunkStager(cr, d, reader.dtype, kind="ivf_flat")
    try:
        # ---- pass A: per-chunk nearest-center assignment. Labels stay
        # DEVICE-resident parts until one concatenate at the end — the
        # loop itself never syncs the host (satellite guard:
        # test_ooc_build asserts a repeat build compiles nothing).
        tile = _choose_tile(cr, index.n_lists, 1, res.workspace_bytes)
        parts = []
        with tracing.range("ivf_flat.extend.assign_stream"):
            for start, block in reader.chunks():
                xs = ingest(stager.stage(block))
                xa = xs.astype(jnp.float32) if xs.dtype == jnp.int8 else xs
                parts.append(assign_to_lists(xa, index.centers,
                                             index.metric, tile))
                if emit:
                    build_metrics.ooc_chunks().inc(1, kind="ivf_flat",
                                                   stage="assign")
        labels = jnp.concatenate(parts)[:n_new]  # drop pad-row garbage
        del parts

        # merge with existing list contents (flatten old lists back to
        # rows — same ordering as _extend_signed: OLD FIRST, so stable
        # ranks, and therefore the final layout, agree with the in-core
        # twin)
        n_old = 0
        old_x = old_ids = None
        if index.capacity > 0 and index.size > 0:
            old_mask = index.list_ids.reshape(-1) >= 0
            old_x = index.list_data.reshape(-1, d)[old_mask]
            old_ids = index.list_ids.reshape(-1)[old_mask]
            old_labels = jnp.repeat(jnp.arange(index.n_lists),
                                    index.capacity)[old_mask]
            n_old = int(old_x.shape[0])
            labels = jnp.concatenate([old_labels.astype(jnp.int32), labels])

        # capacity policy over the FULL label vector (one host sync for
        # the max size — per build, not per chunk). x=None: the spatial
        # split would need the whole corpus device-resident, so severe
        # lists order-split instead (see docstring).
        sf = index.split_factor if split_factor is None else split_factor
        labels, rep, n_lists2, capacity, _ = bound_capacity(
            labels, index.n_lists, sf, x=None)
        centers = index.centers
        if rep is not None:
            centers = jnp.asarray(np.repeat(np.asarray(centers), rep,
                                            axis=0))

        # ---- pass B: chunked scatter into the sealed layout -----------
        data = jnp.zeros((n_lists2, capacity, d), storage_dt)
        idbuf = jnp.full((n_lists2, capacity), -1, jnp.int32)
        norms = jnp.full((n_lists2, capacity), jnp.inf, jnp.float32)
        offsets = jnp.zeros((n_lists2,), jnp.int32)
        # transient ledger entry: the accumulators + label/id vectors ARE
        # the streamed build's device working set (plan()'s streamed-mode
        # estimate prices exactly this); released before the sealed index
        # is accounted so /debug/mem never double-counts the layout
        ooc_tok = obs_mem.account(
            "build/ooc", name="ivf_flat",
            device_bytes=int(data.nbytes + idbuf.nbytes + norms.nbytes
                             + offsets.nbytes + labels.nbytes
                             + new_ids.nbytes),
            owner=stager)
        with tracing.range("ivf_flat.extend.fill_stream"):
            if n_old > 0:
                data, idbuf, norms, offsets = _fill_chunk(
                    data, idbuf, norms, offsets, old_x, old_ids,
                    labels[:n_old], n_lists=n_lists2)
                labels = labels[n_old:]
            # pad the tail so every chunk's slice is full-size (ONE
            # executable): sentinel label n_lists2 -> scatter dropped
            pad = -(-n_new // cr) * cr - n_new
            lab_p = (jnp.concatenate(
                [labels, jnp.full((pad,), n_lists2, jnp.int32)])
                if pad else labels)
            ids_p = (jnp.concatenate(
                [new_ids, jnp.full((pad,), -1, jnp.int32)])
                if pad else new_ids)
            for start, block in reader.chunks():
                xs = ingest(stager.stage(block))
                st = jnp.int32(start)  # operand, not executable key
                lab_c = lax.dynamic_slice_in_dim(lab_p, st, cr)
                ids_c = lax.dynamic_slice_in_dim(ids_p, st, cr)
                data, idbuf, norms, offsets = _fill_chunk(
                    data, idbuf, norms, offsets, xs, ids_c, lab_c,
                    n_lists=n_lists2)
                if emit:
                    build_metrics.ooc_chunks().inc(1, kind="ivf_flat",
                                                   stage="fill")
        sizes = offsets
        obs_mem.release(ooc_tok)
    finally:
        stager.release()
    out = IvfFlatIndex(centers, data, idbuf, norms, sizes, index.metric, sf,
                       index.data_kind)
    obs_mem.account_index(out)
    return out


@functools.partial(
    jax.jit, static_argnames=("n_probes", "k", "query_tile", "probe_chunk", "metric")
)
def _ivf_search(index: IvfFlatIndex, queries, n_probes: int, k: int,
                query_tile: int, probe_chunk: int, metric: DistanceType,
                keep_mask=None):
    m, d = queries.shape
    qf = queries.astype(jnp.float32)
    inner = metric == DistanceType.InnerProduct

    # ---- stage 1: coarse scoring (ref: ivf_flat_search-inl.cuh:130) ----
    with tracing.range("ivf_flat.search.coarse"):
        cscore = qf @ index.centers.T  # (m, L) MXU
        if not inner:
            cn = jnp.sum(index.centers * index.centers, axis=1)
            cscore = cn[None, :] - 2.0 * cscore
        _, probes = _select_k(cscore, None, n_probes, not inner)  # (m, p)

    # pad queries to tile multiple
    num = -(-m // query_tile)
    pad = num * query_tile - m
    qp = jnp.pad(qf, ((0, pad), (0, 0))) if pad else qf
    pp = jnp.pad(probes, ((0, pad), (0, 0))) if pad else probes
    qt = qp.reshape(num, query_tile, d)
    pt = pp.reshape(num, query_tile, n_probes)

    n_chunks = n_probes // probe_chunk
    cap = index.capacity

    def per_tile(args):
        q, pr = args  # (T, d), (T, p)

        def per_chunk(c, _):
            pc = lax.dynamic_slice_in_dim(pr, c * probe_chunk, probe_chunk, axis=1)  # (T, pc)
            vecs = index.list_data[pc]  # (T, pc, cap, d) gather
            ids = index.list_ids[pc]  # (T, pc, cap)
            # NOTE: bf16 storage deliberately upcasts to f32 + HIGHEST here.
            # Measured at 1M x 128 (p=8): a native bf16 DEFAULT-precision
            # einsum is no faster (13.0k vs 15.4k QPS — the scan is bound by
            # the padded-list gather, not the matvec) and rounding the query
            # to bf16 costs recall (0.9697 vs 0.9756).
            # int8 lists ride the same upcast: the gather (the measured
            # bottleneck) moves 1 byte/dim — half of bf16 — and the f32
            # convert fuses into the dot's operand pipeline. Scoring is
            # EXACT for 8-bit values (every intermediate is an integer
            # below 2^24). A native s8 x s8 -> s32 einsum was tried and
            # REJECTED: on TPU the batched 4-d einsum decays to an inexact
            # bf16 lowering (measured 2.8% distance error, r05); only the
            # Pallas fused-kNN kernel's 2-d dot takes the true s8 MXU path.
            dots = jnp.einsum(
                "td,tpcd->tpc", q, vecs.astype(jnp.float32),
                precision=lax.Precision.HIGHEST,
            )
            if inner:
                scores = jnp.where(ids >= 0, dots, -jnp.inf)
            else:
                norms = index.list_norms[pc]
                scores = norms - 2.0 * dots  # +inf padding stays +inf
            if keep_mask is not None:
                from .sample_filter import apply_id_filter

                scores = apply_id_filter(scores, ids, keep_mask, not inner)
            flat_s = scores.reshape(query_tile, probe_chunk * cap)
            flat_i = ids.reshape(query_tile, probe_chunk * cap)
            return c + 1, _select_k(flat_s, flat_i, k, not inner)

        _, (cv, ci) = lax.scan(per_chunk, 0, None, length=n_chunks)
        # (chunks, T, k) → per-query merge
        cv = jnp.moveaxis(cv, 0, 1).reshape(query_tile, n_chunks * k)
        ci = jnp.moveaxis(ci, 0, 1).reshape(query_tile, n_chunks * k)
        return _select_k(cv, ci, k, not inner)

    with tracing.range("ivf_flat.search.scan"):
        dists, idx = lax.map(per_tile, (qt, pt))
    dists = dists.reshape(num * query_tile, k)[:m]
    idx = idx.reshape(num * query_tile, k)[:m]
    if not inner:
        # convert ‖v‖²-2qv partial scores to true squared L2 by adding ‖q‖²
        qn = jnp.sum(qf * qf, axis=1, keepdims=True)
        dists = jnp.where(jnp.isfinite(dists), jnp.maximum(dists + qn, 0.0), dists)
        if metric in (DistanceType.L2SqrtExpanded, DistanceType.L2SqrtUnexpanded):
            dists = jnp.where(jnp.isfinite(dists), jnp.sqrt(dists), dists)
    if keep_mask is not None:
        # filtered-out candidates carry ±inf scores — report id -1, matching
        # the documented empty-slot sentinel
        idx = jnp.where(jnp.isinf(dists), -1, idx)
    return dists, idx


@instrument(
    "ivf_flat.search",
    items=lambda a, kw: nrows(a[2] if len(a) > 2 else kw["queries"]),
    labels=lambda a, kw: {"k": a[3] if len(a) > 3 else kw["k"],
                          "n_probes": (a[0] if a else kw["params"]).n_probes},
)
@auto_convert_output
def search(params: SearchParams, index: IvfFlatIndex, queries, k: int,
           sample_filter=None, res: Resources | None = None):
    """Search the index (reference: ivf_flat::search, ivf_flat-inl.cuh;
    pylibraft neighbors/ivf_flat search; filtered overload
    neighbors/ivf_flat.cuh search_with_filtering). Returns
    (distances (m,k), ids (m,k)); id -1 marks slots beyond the probed
    candidate count."""
    from .brute_force import _coerce_queries
    from .sample_filter import resolve_filter

    res = res or default_resources()
    queries = jnp.asarray(queries)
    expects(queries.ndim == 2 and queries.shape[1] == index.dim, "query dim mismatch")
    queries = _coerce_queries(index.data_kind, queries)
    expects(index.capacity > 0, "index is empty")
    if not isinstance(index.list_sizes, jax.core.Tracer):
        expects(index.size > 0, "index is empty")
    n_probes = min(params.n_probes, index.n_lists)
    m = queries.shape[0]
    expects(
        k <= n_probes * index.capacity,
        "k=%d exceeds the probed candidate pool (n_probes=%d x capacity=%d)",
        k, n_probes, index.capacity,
    )

    # gathered vectors (f32 staged) + norms + scores per slot; x2 for XLA
    # temporaries — the f32 staging bound holds for all storage dtypes
    query_tile, probe_chunk = plan_search_tiles(
        m, n_probes, int(k), index.capacity,
        bytes_per_probe_row=2 * index.capacity * (index.dim * 4 + 8),
        budget_bytes=res.workspace_bytes,
    )

    keep_mask = resolve_filter(sample_filter)
    if keep_mask is not None:
        from .sample_filter import validate_filter_covers

        validate_filter_covers(index, keep_mask)
    return _ivf_search(index, queries, n_probes, int(k), query_tile, probe_chunk,
                       index.metric, keep_mask)


def write_index(f, index: IvfFlatIndex) -> None:
    """Serialize to an open binary stream (the composable half of
    :func:`save` — :mod:`raft_tpu.stream` embeds sealed indexes this way)."""
    serialize_header(f, "ivf_flat")
    serialize_scalar(f, int(index.metric))
    serialize_scalar(f, float(index.split_factor))
    serialize_scalar(f, index.data_kind)
    serialize_mdspan(f, index.centers)
    serialize_mdspan(f, index.list_data)
    serialize_mdspan(f, index.list_ids)
    serialize_mdspan(f, index.list_norms)
    serialize_mdspan(f, index.list_sizes)
    serialize_tuned(f, index.tuned)


def read_index(f) -> IvfFlatIndex:
    """Deserialize from an open binary stream (pairs with
    :func:`write_index`)."""
    ver = check_header(f, "ivf_flat")
    metric = DistanceType(deserialize_scalar(f))
    split_factor = float(deserialize_scalar(f))
    # raft_tpu/5 added data_kind (int8/uint8 storage); older files —
    # including /4, whose global bump was for cagra and wrote ivf_flat
    # in the /3 layout — hold only float kinds, recoverable from the
    # stored dtype
    kind = (deserialize_scalar(f)
            if ver not in ("raft_tpu/2", "raft_tpu/3", "raft_tpu/4")
            else None)
    centers = jnp.asarray(deserialize_mdspan(f))
    data = jnp.asarray(deserialize_mdspan(f))
    ids = jnp.asarray(deserialize_mdspan(f))
    norms = jnp.asarray(deserialize_mdspan(f))
    sizes = jnp.asarray(deserialize_mdspan(f))
    if kind is None:
        kind = "bfloat16" if data.dtype == jnp.bfloat16 else "float32"
    # raft_tpu/9 appended the optional tuned record (pinned operating
    # point); older files are untuned
    tuned = deserialize_tuned(f, ver)
    return IvfFlatIndex(centers, data, ids, norms, sizes, metric, split_factor,
                        kind, tuned=tuned)


def save(index: IvfFlatIndex, path: str) -> None:
    """Serialize (reference: ivf_flat_serialize.cuh; pylibraft save).
    Atomic: temp file + rename, a crashed save keeps the previous file."""
    from ..core.serialize import atomic_write

    with atomic_write(path) as f:
        write_index(f, index)


def load(path: str, res: Resources | None = None) -> IvfFlatIndex:
    """Deserialize (reference: ivf_flat_serialize.cuh deserialize)."""
    with open(path, "rb") as f:
        return read_index(f)


def batched_searcher(index: IvfFlatIndex, params: SearchParams | None = None):
    """Stable serving hook (raft_tpu.serve; contract in :mod:`._hooks`) —
    the surface the serve registry warms and hot-swaps through. With no
    explicit ``params``, an attached tune decision (``index.tuned``, e.g.
    restored by a raft_tpu/9 load) supplies the pinned operating point —
    docs/tuning.md."""
    from ._hooks import make_hook

    if params is None and index.tuned is not None:
        from ..tune.apply import make_searcher as tuned_searcher

        return tuned_searcher(index, True, degrade_without_rows=True)
    sp = params or SearchParams()
    return make_hook(lambda queries, k: search(sp, index, queries, k),
                     "ivf_flat", index.dim, index.data_kind)
