"""Epsilon neighborhood — all pairs within a radius.

Re-design of raft::neighbors::epsilon_neighborhood::eps_neighbors_l2sq
(cpp/include/raft/neighbors/epsilon_neighborhood.cuh; kernel in
spatial/knn/detail/epsilon_neighborhood.cuh). The reference fuses a tiled
L2² computation with the ≤ eps compare and a per-row popcount (vertex
degree). On TPU the distance tile is an MXU GEMM and the compare + degree
reduction fuse into its epilogue; x rows are tiled under lax.map so only the
boolean output — never the f32 distance matrix — exists at full (m, n) size.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..core.errors import expects
from ..core.resources import Resources, default_resources
from ..distance.pairwise import _choose_tile

__all__ = ["eps_neighbors_l2sq"]

_f32 = jnp.float32


@functools.partial(jax.jit, static_argnames=("tile",))
def _eps_nn(x, y, eps_sq, tile: int):
    m, d = x.shape
    yf = y.astype(_f32)
    yn = jnp.sum(yf * yf, axis=1)
    num = -(-m // tile)
    pad = num * tile - m
    xp = jnp.pad(x.astype(_f32), ((0, pad), (0, 0))) if pad else x.astype(_f32)

    def per_tile(xb):
        d2 = (
            jnp.sum(xb * xb, axis=1)[:, None]
            + yn[None, :]
            - 2.0
            * lax.dot_general(
                xb, yf, (((1,), (1,)), ((), ())), precision=lax.Precision.HIGHEST,
                preferred_element_type=_f32,
            )
        )
        adj = jnp.maximum(d2, 0.0) <= eps_sq
        return adj, jnp.sum(adj, axis=1, dtype=jnp.int32)

    adj, deg = lax.map(per_tile, xp.reshape(num, tile, d))
    return adj.reshape(num * tile, -1)[:m], deg.reshape(num * tile)[:m]


def eps_neighbors_l2sq(x, y=None, eps: float = 1.0, res: Resources | None = None):
    """Boolean adjacency of all (x_i, y_j) pairs with ‖x_i − y_j‖² ≤ eps.

    Reference: eps_neighbors_l2sq (neighbors/epsilon_neighborhood.cuh:78-105).
    ``eps`` is the *squared* radius, as in the reference. Returns
    ``(adj (m, n) bool, vertex_degree (m+1,) int32)`` where the final entry of
    ``vertex_degree`` is the total edge count (the reference's ``vd + m``).
    """
    res = res or default_resources()
    x = jnp.asarray(x)
    y = x if y is None else jnp.asarray(y)
    expects(x.ndim == 2 and y.ndim == 2 and x.shape[1] == y.shape[1], "bad x/y shapes")
    tile = _choose_tile(x.shape[0], y.shape[0], 1, res.workspace_bytes)
    adj, deg = _eps_nn(x, y, _f32(eps), tile)
    vd = jnp.concatenate([deg, jnp.sum(deg, keepdims=True)])
    return adj, vd
