"""Epsilon neighborhood — all pairs within a radius.

Re-design of raft::neighbors::epsilon_neighborhood::eps_neighbors_l2sq
(cpp/include/raft/neighbors/epsilon_neighborhood.cuh; kernel in
spatial/knn/detail/epsilon_neighborhood.cuh). The reference fuses a tiled
L2² computation with the ≤ eps compare and a per-row popcount (vertex
degree). On TPU the distance tile is an MXU GEMM and the compare + degree
reduction fuse into its epilogue.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.errors import expects

__all__ = ["eps_neighbors_l2sq"]

_f32 = jnp.float32


@jax.jit
def _eps_nn(x, y, eps_sq):
    xf = x.astype(_f32)
    yf = y.astype(_f32)
    d2 = (
        jnp.sum(xf * xf, axis=1)[:, None]
        + jnp.sum(yf * yf, axis=1)[None, :]
        - 2.0
        * lax.dot_general(
            xf, yf, (((1,), (1,)), ((), ())), precision=lax.Precision.HIGHEST,
            preferred_element_type=_f32,
        )
    )
    adj = jnp.maximum(d2, 0.0) <= eps_sq
    deg = jnp.sum(adj, axis=1, dtype=jnp.int32)
    return adj, deg


def eps_neighbors_l2sq(x, y=None, eps: float = 1.0):
    """Boolean adjacency of all (x_i, y_j) pairs with ‖x_i − y_j‖² ≤ eps.

    Reference: eps_neighbors_l2sq (neighbors/epsilon_neighborhood.cuh:78-105).
    ``eps`` is the *squared* radius, as in the reference. Returns
    ``(adj (m, n) bool, vertex_degree (m+1,) int32)`` where the final entry of
    ``vertex_degree`` is the total edge count (the reference's ``vd + m``).
    """
    x = jnp.asarray(x)
    y = x if y is None else jnp.asarray(y)
    expects(x.ndim == 2 and y.ndim == 2 and x.shape[1] == y.shape[1], "bad x/y shapes")
    adj, deg = _eps_nn(x, y, _f32(eps))
    vd = jnp.concatenate([deg, jnp.sum(deg, keepdims=True)])
    return adj, vd
