"""Random ball cover (RBC) — exact kNN with triangle-inequality pruning.

Re-design of raft::neighbors::ball_cover (cpp/include/raft/neighbors/
ball_cover-inl.cuh, ball_cover_types.hpp:34-110; kernels
spatial/knn/detail/ball_cover.cuh and detail/ball_cover/registers-inl.cuh).
The reference samples ``sqrt(n)`` landmarks, assigns every point to its
closest landmark, and answers queries by scanning landmark lists in order of
query→landmark distance, pruning lists whose lower bound
``d(q, L) − radius(L)`` exceeds the current kth distance, with a
post-processing pass that guarantees exactness.

TPU shape: landmark lists live in the same padded (L, cap, d) layout as
IVF-Flat, so a probe scan is a contiguous gather + MXU einsum. The
batch-synchronous equivalent of the reference's per-query pruning loop is a
two-pass search:

1. probe the ``n_probes`` closest landmarks → per-query kth-distance bound u;
2. host-round the *worst-case* number of lists any query still needs
   (``d(q, L) − radius(L) ≤ u``, the reference's exactness condition) up to a
   pow2 probe budget, and scan those lists, ranked by lower bound.

Pass 2's budget is data-dependent but bucketed, so recompilation is rare; the
result is exact for L2 metrics, like the reference.
"""

from __future__ import annotations

import dataclasses
import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

from ..core.errors import expects
from ..core.resources import Resources, default_resources
from ..distance.pairwise import _choose_tile
from ..distance.types import DistanceType, resolve_metric
from ..matrix.select_k import _select_k
from ._list_utils import assign_to_lists, list_positions, plan_search_tiles, round_up

__all__ = ["BallCoverIndex", "build", "knn_query", "all_knn_query", "eps_nn_query"]

_f32 = jnp.float32


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class BallCoverIndex:
    """Reference: BallCoverIndex (neighbors/ball_cover_types.hpp:34) — raw
    data, sampled landmarks, per-point landmark 1-NN, landmark ball radii."""

    landmarks: jax.Array  # (L, d) f32
    list_data: jax.Array  # (L, cap, d)
    list_ids: jax.Array  # (L, cap) int32, -1 padding
    list_norms: jax.Array  # (L, cap) f32, +inf padding
    radii: jax.Array  # (L,) f32 — max member distance per landmark ball
    metric: DistanceType

    @property
    def n_landmarks(self) -> int:
        return self.landmarks.shape[0]

    @property
    def dim(self) -> int:
        return self.landmarks.shape[1]

    @property
    def capacity(self) -> int:
        return self.list_data.shape[1]

    def tree_flatten(self):
        return (
            (self.landmarks, self.list_data, self.list_ids, self.list_norms, self.radii),
            self.metric,
        )

    @classmethod
    def tree_unflatten(cls, metric, children):
        return cls(*children, metric=metric)


def build(dataset, metric="sqeuclidean", n_landmarks: int | None = None,
          seed: int = 0, res: Resources | None = None) -> BallCoverIndex:
    """Build the RBC index (reference: rbc_build_index,
    spatial/knn/detail/ball_cover.cuh — sample sqrt(n) landmarks from the
    dataset, 1-NN assign every point, sort points by landmark)."""
    res = res or default_resources()
    x = jnp.asarray(dataset)
    expects(x.ndim == 2, "dataset must be (n, d)")
    n, d = x.shape
    mt = resolve_metric(metric)
    expects(
        mt in (
            DistanceType.L2Expanded,
            DistanceType.L2SqrtExpanded,
            DistanceType.L2Unexpanded,
            DistanceType.L2SqrtUnexpanded,
            DistanceType.Haversine,
        ),
        "ball_cover supports L2 / haversine metrics, got %s",
        mt.name,
    )
    if mt == DistanceType.Haversine:
        expects(d == 2, "haversine requires (lat, lon) inputs with d == 2")
    L = n_landmarks or max(int(math.isqrt(n)), 1)
    expects(L <= n, "n_landmarks > n_samples")

    # uniform landmark sample without replacement (ref: rbc samples index rows)
    key = jax.random.PRNGKey(seed)
    perm = jax.random.permutation(key, n)[:L]
    landmarks = x[perm].astype(_f32)

    tile = _choose_tile(n, L, 1, res.workspace_bytes)
    labels = assign_to_lists(x, landmarks, DistanceType.L2Expanded, tile)
    sizes = jnp.bincount(labels, length=L)
    capacity = round_up(max(int(jnp.max(sizes)), 1), 8)

    pos, _ = list_positions(labels, L)
    data = jnp.zeros((L, capacity, d), x.dtype).at[labels, pos].set(x)
    ids = jnp.full((L, capacity), -1, jnp.int32).at[labels, pos].set(
        jnp.arange(n, dtype=jnp.int32)
    )
    xf = x.astype(_f32)
    norms = jnp.full((L, capacity), jnp.inf, _f32).at[labels, pos].set(
        jnp.sum(xf * xf, axis=1)
    )

    # ball radius = max member distance (ref: R_radius, ball_cover.cuh
    # computes it from the sorted 1-nn distances)
    member_d = _true_dist(xf, landmarks[labels], mt)
    radii = jnp.zeros((L,), _f32).at[labels].max(member_d)
    return BallCoverIndex(landmarks, data, ids, norms, radii, mt)


def _hav(lat1, lon1, lat2, lon2):
    """Great-circle distance on broadcastable lat/lon radians (single home for
    the formula; the pairwise-metric variant is _ew_haversine in
    raft_tpu/distance/pairwise.py, which works on stacked (…, 2) tiles)."""
    s1 = jnp.sin(0.5 * (lat2 - lat1))
    s2 = jnp.sin(0.5 * (lon2 - lon1))
    h = s1 * s1 + jnp.cos(lat1) * jnp.cos(lat2) * s2 * s2
    return 2.0 * jnp.arcsin(jnp.sqrt(jnp.clip(h, 0.0, 1.0)))


def _true_dist(a, b, metric: DistanceType):
    """Rowwise distance in the index metric (a, b same shape)."""
    if metric == DistanceType.Haversine:
        return _hav(a[:, 0], a[:, 1], b[:, 0], b[:, 1])
    d2 = jnp.maximum(jnp.sum(jnp.square(a - b), axis=-1), 0.0)
    return jnp.sqrt(d2)


def _q2l(queries, index: BallCoverIndex):
    """Query→landmark *root* L2 (or haversine) distances — the triangle
    inequality needs true metric distances, not squared."""
    if index.metric == DistanceType.Haversine:
        q = queries[:, None, :]
        lm = index.landmarks[None, :, :]
        return _hav(q[..., 0], q[..., 1], lm[..., 0], lm[..., 1])
    qn = jnp.sum(queries * queries, axis=1)
    ln = jnp.sum(index.landmarks * index.landmarks, axis=1)
    # HIGHEST precision: this feeds the triangle-inequality exactness bound,
    # which a bf16-default TPU matmul would corrupt
    dots = lax.dot_general(
        queries, index.landmarks, (((1,), (1,)), ((), ())),
        precision=lax.Precision.HIGHEST, preferred_element_type=_f32,
    )
    d2 = qn[:, None] + ln[None, :] - 2.0 * dots
    return jnp.sqrt(jnp.maximum(d2, 0.0))


@functools.partial(jax.jit, static_argnames=("n_probes", "k", "query_tile", "probe_chunk", "metric"))
def _scan_lists(index: BallCoverIndex, queries, probes, n_probes: int, k: int,
                query_tile: int, probe_chunk: int, metric: DistanceType):
    """Scan the given (m, n_probes) landmark lists; returns root-metric
    (dists, ids). Same tiled gather+einsum scan as IVF-Flat."""
    m, d = queries.shape
    qf = queries.astype(_f32)
    num = -(-m // query_tile)
    pad = num * query_tile - m
    qp = jnp.pad(qf, ((0, pad), (0, 0))) if pad else qf
    pp = jnp.pad(probes, ((0, pad), (0, 0))) if pad else probes
    qt = qp.reshape(num, query_tile, d)
    pt = pp.reshape(num, query_tile, n_probes)
    n_chunks = n_probes // probe_chunk
    cap = index.capacity
    haversine = metric == DistanceType.Haversine

    def per_tile(args):
        q, pr = args

        def per_chunk(c, _):
            pc = lax.dynamic_slice_in_dim(pr, c * probe_chunk, probe_chunk, axis=1)
            vecs = index.list_data[pc].astype(_f32)  # (T, pc, cap, d)
            ids = index.list_ids[pc]
            if haversine:
                qb = q[:, None, None, :]
                scores = _hav(qb[..., 0], qb[..., 1], vecs[..., 0], vecs[..., 1])
                scores = jnp.where(ids >= 0, scores, jnp.inf)
            else:
                dots = jnp.einsum("td,tpcd->tpc", q, vecs, precision=lax.Precision.HIGHEST)
                scores = index.list_norms[pc] - 2.0 * dots  # +inf padding survives
            flat_s = scores.reshape(query_tile, probe_chunk * cap)
            flat_i = ids.reshape(query_tile, probe_chunk * cap)
            return c + 1, _select_k(flat_s, flat_i, k, True)

        _, (cv, ci) = lax.scan(per_chunk, 0, None, length=n_chunks)
        cv = jnp.moveaxis(cv, 0, 1).reshape(query_tile, n_chunks * k)
        ci = jnp.moveaxis(ci, 0, 1).reshape(query_tile, n_chunks * k)
        return _select_k(cv, ci, k, True)

    dists, idx = lax.map(per_tile, (qt, pt))
    dists = dists.reshape(num * query_tile, k)[:m]
    idx = idx.reshape(num * query_tile, k)[:m]
    if not haversine:
        qn = jnp.sum(qf * qf, axis=1, keepdims=True)
        dists = jnp.where(jnp.isfinite(dists), jnp.sqrt(jnp.maximum(dists + qn, 0.0)), dists)
    return dists, idx


def knn_query(index: BallCoverIndex, queries, k: int, n_probes: int | None = None,
              perform_post_filtering: bool = True, res: Resources | None = None):
    """Exact kNN via the ball cover (reference: ball_cover::knn_query,
    ball_cover-inl.cuh:259; exactness pass = perform_post_filtering).

    Returns (distances, indices) in the index metric (sqeuclidean distances
    are reported squared, matching the reference's L2 variants).
    """
    res = res or default_resources()
    q = jnp.asarray(queries).astype(_f32)
    expects(q.ndim == 2 and q.shape[1] == index.dim, "query dim mismatch")
    m = q.shape[0]
    L = index.n_landmarks
    cap = index.capacity
    expects(0 < k <= L * cap, "k=%d must be in (0, %d]", k, L * cap)

    p1 = n_probes or min(L, max(2, -(-int(1.5 * k) // cap) + 1))
    while p1 * cap < k:
        p1 += 1
    p1 = min(p1, L)

    q2l = _q2l(q, index)  # (m, L) root distances
    _, probes = _select_k(q2l, None, p1, True)

    qt1, pc1 = plan_search_tiles(m, p1, int(k), cap,
                                 bytes_per_probe_row=cap * index.dim * 4,
                                 budget_bytes=res.workspace_bytes)
    dists, idx = _scan_lists(index, q, probes, p1, int(k), qt1, pc1, index.metric)

    if perform_post_filtering and L > p1:
        # triangle-inequality exactness: list Lj can contain a better neighbor
        # only if d(q, Lj) − radius(Lj) < current kth distance
        # (ref: ball_cover.cuh perform_post_filtering_pass)
        u = dists[:, -1]  # root-metric kth bound
        lower = q2l - index.radii[None, :]
        flagged = lower < u[:, None]  # lists that could still hold a neighbor
        # pass 2 is needed iff any flagged list was NOT scanned in pass 1 —
        # membership, not count: a far landmark with a big radius can be
        # flagged while ranking below the p1 closest (probed) landmarks.
        probed_mask = jnp.zeros((m, L), bool).at[
            jnp.arange(m)[:, None], probes
        ].set(True)
        missing = jnp.any(flagged & ~probed_mask)
        worst = int(jnp.max(jnp.sum(flagged, axis=1)))
        if bool(missing):
            # pass 2 must also satisfy the k <= p2*cap candidate-pool bound
            need = max(worst, -(-k // cap))
            p2 = min(L, 1 << max(need - 1, 1).bit_length())
            _, probes2 = _select_k(lower, None, p2, True)
            qt2, pc2 = plan_search_tiles(m, p2, int(k), cap,
                                         bytes_per_probe_row=cap * index.dim * 4,
                                         budget_bytes=res.workspace_bytes)
            d2_, i2_ = _scan_lists(index, q, probes2, p2, int(k), qt2, pc2, index.metric)
            # merge the two candidate sets
            md = jnp.concatenate([dists, d2_], axis=1)
            mi = jnp.concatenate([idx, i2_], axis=1)
            # dedupe: same id may appear in both passes — push dups to +inf
            order = jnp.argsort(md, axis=1)
            mi_s = jnp.take_along_axis(mi, order, axis=1)
            md_s = jnp.take_along_axis(md, order, axis=1)
            w = md_s.shape[1]
            earlier = jnp.tril(jnp.ones((w, w), bool), -1)
            dup = jnp.any(
                (mi_s[:, None, :] == mi_s[:, :, None]) & earlier[None], axis=2
            )
            md_s = jnp.where(dup, jnp.inf, md_s)
            dists, idx = _select_k(md_s, mi_s, int(k), True)

    if index.metric in (DistanceType.L2Expanded, DistanceType.L2Unexpanded):
        dists = jnp.where(jnp.isfinite(dists), dists * dists, dists)
    return dists, idx


def all_knn_query(index: BallCoverIndex, k: int, res: Resources | None = None):
    """kNN of the index points against themselves (reference:
    ball_cover::all_knn_query, ball_cover-inl.cuh:112)."""
    mask = index.list_ids.reshape(-1) >= 0
    # reconstruct dataset rows in id order
    flat = index.list_data.reshape(-1, index.dim)
    ids = index.list_ids.reshape(-1)
    n = int(jnp.sum(mask))
    # padding slots scatter out-of-bounds and are dropped
    x = jnp.zeros((n, index.dim), index.list_data.dtype)
    x = x.at[jnp.where(mask, ids, n)].set(flat, mode="drop")
    return knn_query(index, x, k, res=res)


def eps_nn_query(index: BallCoverIndex, queries, eps: float, res: Resources | None = None):
    """All neighbors within radius ``eps`` in the *index metric* (reference:
    ball_cover::eps_nn, ball_cover-inl.cuh — adjacency output variant).
    Returns (adj (m, n_rows) bool over global ids, vertex_degree (m+1,));
    the exactness check ``dist ≤ eps`` subsumes the reference's landmark
    pruning (a member of an unreachable list fails it by the triangle
    inequality), so no per-slot reachability gather is needed. Query rows are
    tiled under lax.map to respect the workspace budget."""
    res = res or default_resources()
    q = jnp.asarray(queries).astype(_f32)
    expects(q.ndim == 2 and q.shape[1] == index.dim, "query dim mismatch")
    m = q.shape[0]
    flat = index.list_data.reshape(-1, index.dim).astype(_f32)
    ids = index.list_ids.reshape(-1)
    n_slots = flat.shape[0]
    haversine = index.metric == DistanceType.Haversine

    tile = _choose_tile(m, n_slots, 0, res.workspace_bytes)
    num = -(-m // tile)
    pad = num * tile - m
    qp = jnp.pad(q, ((0, pad), (0, 0))) if pad else q

    fn2 = jnp.sum(flat * flat, axis=1)

    def per_tile(qb):
        if haversine:
            dist = _hav(
                qb[:, None, 0], qb[:, None, 1], flat[None, :, 0], flat[None, :, 1]
            )
        else:
            dots = lax.dot_general(
                qb, flat, (((1,), (1,)), ((), ())),
                precision=lax.Precision.HIGHEST, preferred_element_type=_f32,
            )
            d2 = jnp.sum(qb * qb, axis=1)[:, None] + fn2[None, :] - 2.0 * dots
            dist = jnp.sqrt(jnp.maximum(d2, 0.0))
        return (dist <= eps) & (ids >= 0)[None, :]

    keep = lax.map(per_tile, qp.reshape(num, tile, index.dim))
    keep = keep.reshape(num * tile, n_slots)[:m]
    n = int(jnp.sum(ids >= 0))
    adj = jnp.zeros((m, n), bool)
    adj = adj.at[:, jnp.where(ids >= 0, ids, n)].max(keep, mode="drop")
    deg = jnp.sum(adj, axis=1, dtype=jnp.int32)
    return adj, jnp.concatenate([deg, jnp.sum(deg, keepdims=True)])
