"""IVF-PQ: inverted-file index with product-quantized residuals.

Re-design of the reference's IVF-PQ (cpp/include/raft/neighbors/ivf_pq-inl.cuh:
build :270 / search :723; build detail/ivf_pq_build.cuh — rotation matrix
make_rotation_matrix :121, residuals select_residuals :165, codebook training
train_per_subset :343 / train_per_cluster :424; search detail/ivf_pq_search.cuh
— select_clusters :68, LUT scan ivfpq_search_worker :419, fused top-k; params
ivf_pq_types.hpp:48-140). The TPU re-think:

- **Rotation**: a (d_rot, d) orthonormal matrix (QR of Gaussian noise, exactly
  the reference's construction) — one GEMM at build and per query batch.
- **Codebooks**: PER_SUBSPACE trains one codebook per pq_dim subspace over
  all residual sub-vectors (vmapped balanced-EM — all subspaces train
  simultaneously as one batched kmeans, a TPU win over the reference's
  sequential stream loop); PER_CLUSTER trains per coarse cluster.
- **Codes**: stored unpacked, one uint8 per (vector, subspace) in padded
  lists (n_lists, capacity, pq_dim) — trading the reference's bit-packed
  layout (ivf_pq_codepacking.cuh) for direct gather/byte loads; pq_bits
  still bounds the codebook size.
- **Search**: coarse GEMM + select_k, then per (query-tile, probe-chunk):
  LUT = ‖residual_sub - codebook‖² for every subspace (one batched GEMM
  against the codebooks), scores = LUT-gather summed over subspaces, fused
  select_k. The reference's fp8-LUT trick maps to bf16 LUTs (lut_dtype).
"""

from __future__ import annotations

from ..config import auto_convert_output

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..cluster import kmeans_balanced
from ..cluster.kmeans_balanced import KMeansBalancedParams
from ..core.errors import expects
from ..core.resources import Resources, default_resources
from ..core.serialize import (check_header, deserialize_mdspan, deserialize_scalar,
                              serialize_header, serialize_mdspan, serialize_scalar)
from ..distance.pairwise import _choose_tile
from ..distance.types import DistanceType, resolve_metric
from ..matrix.select_k import _select_k
from ..random.rng import as_key
from ._list_utils import (assign_to_lists, bound_capacity, list_positions,
                          plan_search_tiles, pq_scan_bytes_per_probe_row,
                          round_up)

__all__ = ["IndexParams", "SearchParams", "IvfPqIndex", "build", "extend", "search", "save", "load"]


@dataclasses.dataclass(frozen=True)
class IndexParams:
    """Reference: ivf_pq::index_params (ivf_pq_types.hpp:48-105)."""

    n_lists: int = 1024
    metric: str | DistanceType = "sqeuclidean"
    # codebook size = 2**pq_bits; 4..8 supported. DEFAULT DIFFERS FROM THE
    # REFERENCE (ivf_pq_types.hpp:68 defaults 8): the TPU LUT scan is a
    # one-hot MXU contraction whose axis is pq_dim * 2**pq_bits, so pq8
    # costs ~16x pq4 at equal code bytes (measured at 1M x 128: pq4x64
    # 41.4k QPS vs pq8x32 2.6k at the same recall point; int8/bf16 LUTs do
    # not close it). The reference's smem-gather LUT is bits-insensitive,
    # which does NOT hold here — prefer pq_bits=4 with doubled pq_dim. See
    # docs/migrating_from_raft.md.
    pq_bits: int = 4
    pq_dim: int = 0  # 0 → auto: same code bytes as the reference default (d/2 at 8 bits, d at 4)
    codebook_kind: str = "per_subspace"  # ref :43 codebook_gen
    force_random_rotation: bool = False  # ref :98
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    add_data_on_build: bool = True
    seed: int = 0
    # capacity bound for sub-list splitting (multiple of mean list size, see
    # _list_utils.bound_capacity). The LUT scan's one-hot contraction work
    # scales with capacity, so tighter capacity pays even more than for
    # ivf_flat: 1.3 measured +68% QPS (20.5k -> 34.4k at 1M, p=8) at
    # identical recall
    split_factor: float = 1.3


@dataclasses.dataclass(frozen=True)
class SearchParams:
    """Reference: ivf_pq::search_params (ivf_pq_types.hpp:108-140)."""

    n_probes: int = 20
    # "float32" | "bfloat16" | "int8" (ref lut_dtype, ivf_pq_types.hpp:122 —
    # the fp8-class smem LUT maps to bf16/int8 here; int8 quantizes per
    # (query, probe) with a symmetric scale and accumulates in int32 on the
    # MXU's int8 path, halving LUT operand bytes again vs bf16)
    lut_dtype: str = "float32"


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class IvfPqIndex:
    """Reference: ivf_pq::index (ivf_pq_types.hpp:172-300)."""

    centers: jax.Array  # (n_lists, d) f32 coarse centers
    centers_rot: jax.Array  # (n_lists, d_rot) f32 — rotated centers
    rotation: jax.Array  # (d_rot, d) f32 orthonormal
    codebooks: jax.Array  # per_subspace: (pq_dim, 2**bits, pq_len); per_cluster: (n_lists, 2**bits, pq_len)
    list_codes: jax.Array  # (n_lists, capacity, pq_dim) uint8
    list_ids: jax.Array  # (n_lists, capacity) int32, -1 padding
    list_sizes: jax.Array  # (n_lists,) int32
    metric: DistanceType = DistanceType.L2Expanded
    codebook_kind: str = "per_subspace"
    pq_bits: int = 8
    # build-time capacity policy, inherited by extend()
    split_factor: float = 1.3

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def rot_dim(self) -> int:
        return self.rotation.shape[0]

    @property
    def pq_dim(self) -> int:
        return self.list_codes.shape[2]

    @property
    def pq_len(self) -> int:
        return self.rot_dim // self.pq_dim

    @property
    def capacity(self) -> int:
        return self.list_codes.shape[1]

    @property
    def size(self) -> int:
        """Total stored vectors. Computed on host so it stays concrete even
        when an enclosing jit trace is active (e.g. a user wrapping search()
        in jax.jit captures the index as a closure constant — staging the sum
        would make int() fail on a tracer). Unavailable when the index itself
        is a traced jit argument."""
        import numpy as np

        return int(np.asarray(jax.device_get(self.list_sizes)).sum())

    def tree_flatten(self):
        children = (self.centers, self.centers_rot, self.rotation, self.codebooks,
                    self.list_codes, self.list_ids, self.list_sizes)
        return children, (self.metric, self.codebook_kind, self.pq_bits, self.split_factor)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, metric=aux[0], codebook_kind=aux[1], pq_bits=aux[2],
                   split_factor=aux[3])


def _default_pq_dim(d: int, pq_bits: int = 4) -> int:
    """Bits-aware variant of the reference heuristic (ivf_pq_types.hpp:81,
    ~d/2 at its default 8 bits): the auto pq_dim keeps CODE BYTES equal to
    the reference default — d/2 dims at 8 bits and d dims at 4 bits are both
    d/2 bytes per vector, so switching the TPU-preferred pq_bits=4 default
    does not silently halve quantization budget."""
    pq = max((d * 8) // (2 * pq_bits), 1)
    if pq >= 8:
        pq = (pq // 8) * 8
    return min(pq, d)


def _make_rotation(key, d_rot: int, d: int, force_random: bool):
    """Reference: make_rotation_matrix (ivf_pq_build.cuh:121) — random
    orthonormal via QR when forced or when d_rot != d; else identity(-pad)."""
    if not force_random and d_rot == d:
        return jnp.eye(d, dtype=jnp.float32)
    if not force_random:
        eye = jnp.zeros((d_rot, d), jnp.float32)
        return eye.at[jnp.arange(min(d_rot, d)), jnp.arange(min(d_rot, d))].set(1.0)
    g = jax.random.normal(key, (max(d_rot, d), max(d_rot, d)), jnp.float32)
    q, _ = jnp.linalg.qr(g)
    return q[:d_rot, :d]


@functools.partial(jax.jit, static_argnames=("n_codes", "n_iters"))
def _train_codebooks_batched(subvecs, key, n_codes: int, n_iters: int):
    """Train all codebooks simultaneously: subvecs (B, n, pq_len) → codebooks
    (B, n_codes, pq_len). One vmapped mini-batch EM — every subspace (or
    cluster) trains in parallel on the MXU (ref: train_per_subset :343 runs a
    stream loop; TPU batches it instead)."""

    def one(sv, k):
        n = sv.shape[0]
        # small pools (n < n_codes) seed with replacement — duplicates split
        # during EM; matches the reference's tolerance for tiny trainsets
        init_idx = jax.random.choice(k, n, (n_codes,), replace=n < n_codes)
        centers = jnp.take(sv, init_idx, axis=0)

        def body(i, c):
            d2 = (
                jnp.sum(c * c, axis=1)[None, :]
                - 2.0 * sv @ c.T
            )  # (n, n_codes)
            labels = jnp.argmin(d2, axis=1)
            onehot = jax.nn.one_hot(labels, n_codes, dtype=jnp.float32, axis=0)
            sums = onehot @ sv
            counts = jnp.sum(onehot, axis=1)
            return jnp.where(counts[:, None] > 0, sums / jnp.maximum(counts, 1.0)[:, None], c)

        return lax.fori_loop(0, n_iters, body, centers)

    keys = jax.random.split(key, subvecs.shape[0])
    return jax.vmap(one)(subvecs.astype(jnp.float32), keys)


@functools.partial(jax.jit, static_argnames=("per_cluster", "tile"))
def _encode(residuals_rot, codebooks, labels, per_cluster: bool, tile: int):
    """Nearest codebook entry per subspace, as tiled GEMMs.

    residuals_rot: (n, pq_dim, pq_len). codebooks: (pq_dim, K, L) for
    per_subspace, (n_lists, K, L) for per_cluster (selected via labels).
    Computes argmin over ‖r‖²-free scores ‖c‖² - 2·r·c (the search-LUT
    expansion) in row tiles so the (tile, pq_dim, K) block bounds memory.
    Returns (n, pq_dim) uint8.
    """
    n = residuals_rot.shape[0]
    cb = codebooks.astype(jnp.float32)
    cb_n2 = jnp.sum(cb * cb, axis=-1)  # (B, K)
    num = -(-n // tile)
    pad = num * tile - n
    r = jnp.pad(residuals_rot, ((0, pad), (0, 0), (0, 0))) if pad else residuals_rot
    lb = jnp.pad(labels, (0, pad)) if pad else labels
    rt = r.reshape(num, tile, *residuals_rot.shape[1:])
    lt = lb.reshape(num, tile)

    def body(args):
        rb, lbl = args  # (t, pq_dim, L), (t,)
        if per_cluster:
            cbl = cb[lbl]  # (t, K, L)
            dots = jnp.einsum("tsl,tkl->tsk", rb, cbl, precision=lax.Precision.HIGHEST)
            d2 = cb_n2[lbl][:, None, :] - 2.0 * dots
        else:
            dots = jnp.einsum("tsl,skl->tsk", rb, cb, precision=lax.Precision.HIGHEST)
            d2 = cb_n2[None] - 2.0 * dots
        return jnp.argmin(d2, axis=-1).astype(jnp.uint8)

    codes = lax.map(body, (rt, lt))
    return codes.reshape(num * tile, -1)[:n]


def _fill_code_lists(codes, ids, labels, n_lists: int, capacity: int):
    """Scatter codes into padded lists (shared ivf::list scheme)."""
    n, pq_dim = codes.shape
    pos, counts = list_positions(labels, n_lists)
    buf = jnp.zeros((n_lists, capacity, pq_dim), jnp.uint8)
    idbuf = jnp.full((n_lists, capacity), -1, jnp.int32)
    buf = buf.at[labels, pos].set(codes)
    idbuf = idbuf.at[labels, pos].set(ids.astype(jnp.int32))
    return buf, idbuf, counts.astype(jnp.int32)


def build(params: IndexParams, dataset, res: Resources | None = None) -> IvfPqIndex:
    """Build the index (reference: ivf_pq::build, ivf_pq-inl.cuh:270; call
    stack SURVEY.md §3.B)."""
    res = res or default_resources()
    x = jnp.asarray(dataset)
    expects(x.ndim == 2, "dataset must be (n, d)")
    n, d = x.shape
    expects(params.n_lists <= n, "n_lists > n_samples")
    expects(4 <= params.pq_bits <= 8, "pq_bits must be in [4, 8] (ref ivf_pq_types.hpp:68)")
    mt = resolve_metric(params.metric)
    expects(
        mt in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
               DistanceType.L2Unexpanded, DistanceType.L2SqrtUnexpanded,
               DistanceType.InnerProduct),
        "ivf_pq supports L2 / inner_product metrics, got %s", mt.name,
    )
    expects(params.codebook_kind in ("per_subspace", "per_cluster"),
            "codebook_kind must be per_subspace|per_cluster")

    pq_dim = params.pq_dim or _default_pq_dim(d, params.pq_bits)
    pq_len = -(-d // pq_dim)
    d_rot = pq_dim * pq_len
    n_codes = 1 << params.pq_bits
    key = as_key(params.seed)

    # 1. coarse quantizer (ref §3.B step 2)
    max_train = max(int(n * params.kmeans_trainset_fraction), params.n_lists)
    train_metric = "inner_product" if mt == DistanceType.InnerProduct else "sqeuclidean"
    kb = KMeansBalancedParams(
        n_iters=params.kmeans_n_iters, metric=train_metric, seed=params.seed,
        max_train_points=min(max_train, n),
    )
    centers = kmeans_balanced.fit(kb, x, params.n_lists, res=res)

    # 2. rotation (ref step 3)
    key, kr = jax.random.split(key)
    rotation = _make_rotation(kr, d_rot, d, params.force_random_rotation)
    centers_rot = centers @ rotation.T  # (n_lists, d_rot)

    # 3. residuals of a training subsample (ref steps 4-5 — the reference
    # trains codebooks on the same subsampled trainset as the coarse
    # quantizer, train_per_subset operates on the trainset, not the dataset)
    n_train = min(max_train, n)
    key, ks = jax.random.split(key)
    if n_train < n:
        train_idx = jax.random.choice(ks, n, (n_train,), replace=False)
        xt = jnp.take(x, train_idx, axis=0)
    else:
        xt = x
    tile = _choose_tile(n_train, params.n_lists, 1, res.workspace_bytes)
    labels = assign_to_lists(xt, centers, mt, tile)
    resid = (xt.astype(jnp.float32) - jnp.take(centers, labels, axis=0)) @ rotation.T
    resid = resid.reshape(n_train, pq_dim, pq_len)

    # 4. codebooks (ref train_per_subset :343 / train_per_cluster :424)
    key, kc = jax.random.split(key)
    if params.codebook_kind == "per_subspace":
        # (pq_dim, n_train, pq_len) — every subspace trains on all residuals
        sub = jnp.moveaxis(resid, 1, 0)
        codebooks = _train_codebooks_batched(sub, kc, n_codes, params.kmeans_n_iters)
    else:
        # per-cluster: pool subspace-vectors of each cluster's members.
        # Pad each cluster's pool to a fixed size for batching.
        pool_cap = round_up(max(int(jnp.max(jnp.bincount(labels, length=params.n_lists))), n_codes), 8)
        order = jnp.argsort(labels, stable=True)
        counts = jnp.bincount(labels, length=params.n_lists)
        starts = jnp.cumsum(counts) - counts
        # gather rows per cluster with wraparound padding (repeat members)
        offs = jnp.arange(pool_cap)[None, :] % jnp.maximum(counts, 1)[:, None]
        rows = jnp.take(order, starts[:, None] + offs)  # (n_lists, pool_cap)
        pools = jnp.take(resid.reshape(n_train, d_rot), rows, axis=0)  # (L, pool_cap, d_rot)
        pools = pools.reshape(params.n_lists, pool_cap * pq_dim, pq_len)
        codebooks = _train_codebooks_batched(pools, kc, n_codes, params.kmeans_n_iters)

    index = IvfPqIndex(
        centers=centers,
        centers_rot=centers_rot,
        rotation=rotation,
        codebooks=codebooks,
        list_codes=jnp.zeros((params.n_lists, 0, pq_dim), jnp.uint8),
        list_ids=jnp.zeros((params.n_lists, 0), jnp.int32),
        list_sizes=jnp.zeros((params.n_lists,), jnp.int32),
        metric=mt,
        codebook_kind=params.codebook_kind,
        pq_bits=params.pq_bits,
        split_factor=params.split_factor,
    )
    if not params.add_data_on_build:
        return index
    return extend(index, x, jnp.arange(n, dtype=jnp.int32), res=res)


def extend(index: IvfPqIndex, new_vectors, new_ids=None, res: Resources | None = None,
           split_factor: float | None = None) -> IvfPqIndex:
    """Encode + append vectors (reference: ivf_pq::extend; encode path
    process_and_fill_codes, detail/ivf_pq_build.cuh)."""
    res = res or default_resources()
    x = jnp.asarray(new_vectors)
    expects(x.ndim == 2 and x.shape[1] == index.dim, "vector dim mismatch")
    n_new = x.shape[0]
    if new_ids is None:
        new_ids = index.size + jnp.arange(n_new, dtype=jnp.int32)
    else:
        new_ids = jnp.asarray(new_ids, jnp.int32)

    tile = _choose_tile(n_new, index.n_lists, 1, res.workspace_bytes)
    labels = assign_to_lists(x, index.centers, index.metric, tile)
    resid = (x.astype(jnp.float32) - jnp.take(index.centers, labels, axis=0)) @ index.rotation.T
    resid = resid.reshape(n_new, index.pq_dim, index.pq_len)
    n_codes = index.codebooks.shape[-2]
    enc_tile = max(min(n_new, res.workspace_bytes // max(index.pq_dim * n_codes * 4, 1)), 8)
    codes = _encode(
        resid, index.codebooks, labels,
        per_cluster=index.codebook_kind == "per_cluster",
        tile=min(enc_tile, 8192),
    )

    if index.capacity > 0 and index.size > 0:
        old_mask = index.list_ids.reshape(-1) >= 0
        old_codes = index.list_codes.reshape(-1, index.pq_dim)[old_mask]
        old_ids = index.list_ids.reshape(-1)[old_mask]
        old_labels = jnp.repeat(jnp.arange(index.n_lists), index.capacity)[old_mask]
        codes = jnp.concatenate([old_codes, codes])
        new_ids = jnp.concatenate([old_ids, new_ids])
        labels = jnp.concatenate([old_labels.astype(jnp.int32), labels])

    import numpy as np

    # shared capacity policy: oversized lists split into sub-lists sharing
    # their parent's center (+rotated center, +per-cluster codebook).
    # Residuals/codes were computed against the parent center, which
    # sub-lists share, so codes stay valid.
    sf = index.split_factor if split_factor is None else split_factor
    labels, rep, n_lists, capacity = bound_capacity(labels, index.n_lists, sf)
    centers, centers_rot, codebooks = index.centers, index.centers_rot, index.codebooks
    if rep is not None:
        centers = jnp.asarray(np.repeat(np.asarray(centers), rep, axis=0))
        centers_rot = jnp.asarray(np.repeat(np.asarray(centers_rot), rep, axis=0))
        if index.codebook_kind == "per_cluster":
            codebooks = jnp.asarray(np.repeat(np.asarray(codebooks), rep, axis=0))
    buf, idbuf, sizes = _fill_code_lists(codes, new_ids, labels, n_lists, capacity)
    return dataclasses.replace(
        index, centers=centers, centers_rot=centers_rot, codebooks=codebooks,
        list_codes=buf, list_ids=idbuf, list_sizes=sizes, split_factor=sf,
    )


@functools.partial(
    jax.jit,
    static_argnames=("n_probes", "k", "query_tile", "probe_chunk", "metric",
                     "codebook_kind", "lut_dtype"),
)
def _pq_search(index: IvfPqIndex, queries, n_probes: int, k: int, query_tile: int,
               probe_chunk: int, metric: DistanceType, codebook_kind: str, lut_dtype: str,
               keep_mask=None):
    m, d = queries.shape
    qf = queries.astype(jnp.float32)
    inner = metric == DistanceType.InnerProduct
    pq_dim, pq_len = index.pq_dim, index.pq_len
    n_codes = index.codebooks.shape[-2]

    # ---- stage 1: coarse clusters (ref select_clusters :68) ----
    cscore = qf @ index.centers.T
    if not inner:
        cn = jnp.sum(index.centers * index.centers, axis=1)
        cscore = cn[None, :] - 2.0 * cscore
    _, probes = _select_k(cscore, None, n_probes, not inner)  # (m, p)

    # rotated queries
    qrot = qf @ index.rotation.T  # (m, d_rot)

    num = -(-m // query_tile)
    pad = num * query_tile - m
    qp = jnp.pad(qrot, ((0, pad), (0, 0))) if pad else qrot
    pp = jnp.pad(probes, ((0, pad), (0, 0))) if pad else probes
    qt = qp.reshape(num, query_tile, index.rot_dim)
    pt = pp.reshape(num, query_tile, n_probes)

    n_chunks = n_probes // probe_chunk
    cap = index.capacity

    # codebook norms (for LUT via ‖c‖² - 2·r·c)
    cb = index.codebooks.astype(jnp.float32)
    cb_n2 = jnp.sum(cb * cb, axis=-1)  # (B?, n_codes) matching codebook layout

    def per_tile(args):
        q, pr = args  # (T, d_rot), (T, p)

        def per_chunk(c, _):
            pc = lax.dynamic_slice_in_dim(pr, c * probe_chunk, probe_chunk, axis=1)  # (T, pc)
            crot = index.centers_rot[pc]  # (T, pc, d_rot)

            # ---- LUT (ref ivfpq_search_worker :419 lut computation) ----
            if inner:
                # IP(q, v) = q·c + q_rot·decoded_residual: LUT over the rotated
                # query's subvectors; the q·c bias is added to scores below.
                qs = jnp.broadcast_to(
                    q[:, None, :], (query_tile, probe_chunk, index.rot_dim)
                ).reshape(query_tile, probe_chunk, pq_dim, pq_len)
                if codebook_kind == "per_subspace":
                    lut = jnp.einsum("tpsl,skl->tpsk", qs, cb, precision=lax.Precision.HIGHEST)
                else:
                    lut = jnp.einsum("tpsl,tpkl->tpsk", qs, cb[pc], precision=lax.Precision.HIGHEST)
                bias = jnp.einsum("td,tpd->tp", q, crot, precision=lax.Precision.HIGHEST)
            else:
                # L2: ‖q - c - decoded‖² = Σ_s ‖r_s - codeword_s‖², r = q_rot - c_rot
                r = (q[:, None, :] - crot).reshape(query_tile, probe_chunk, pq_dim, pq_len)
                if codebook_kind == "per_subspace":
                    # cb: (pq_dim, n_codes, pq_len)
                    dots = jnp.einsum("tpsl,skl->tpsk", r, cb, precision=lax.Precision.HIGHEST)
                    lut = cb_n2[None, None] - 2.0 * dots  # (T, pc, pq_dim, n_codes)
                else:
                    cbl = cb[pc]  # (T, pc, n_codes, pq_len)
                    dots = jnp.einsum("tpsl,tpkl->tpsk", r, cbl, precision=lax.Precision.HIGHEST)
                    lut = cb_n2[pc][:, :, None, :] - 2.0 * dots
                # Σ_s ‖r_s‖² per probe: constant within a list, needed so
                # scores are comparable across probed lists
                bias = jnp.sum(r * r, axis=(2, 3))  # (T, pc)

            # ---- scan: score = Σ_s LUT[s, code_s] (ref compute_similarity) ----
            # One-hot MXU formulation: Σ_s LUT[s, c_s] = onehot(codes)·LUTflat.
            # An elementwise take_along_axis gather is ~4x slower on TPU
            # (measured 1.95s vs 0.52s per 1M-scale chunk) — single-element
            # HBM gathers don't vectorize; the MXU one-hot contraction is the
            # TPU analogue of ScaNN's SIMD LUT16 shuffle, and pq_bits=4
            # shrinks the contracted axis 16x for exactly that reason.
            codes = index.list_codes[pc]  # (T, pc, cap, pq_dim) gather
            ids = index.list_ids[pc]  # (T, pc, cap)
            oh = (
                codes[..., None] == jnp.arange(n_codes, dtype=codes.dtype)
            )  # (T, pc, cap, pq_dim, n_codes)
            # the contraction dtype follows lut_dtype (0/1 one-hot entries
            # are exact in any of them):
            #   float32  — exact LUT values
            #   bfloat16 — LUT rounded to ~2^-8 relative, half the bytes
            #   int8     — LUT quantized per (query, probe) with a symmetric
            #              scale (the reference's fp8 smem LUT analogue,
            #              detail/fp_8bit.cuh); int32 accumulation on the
            #              int8 MXU path, quarter the operand bytes
            ohf = oh.reshape(query_tile, probe_chunk, cap, pq_dim * n_codes)
            lutf = lut.reshape(query_tile, probe_chunk, pq_dim * n_codes)
            if lut_dtype not in ("float32", "bfloat16", "int8"):
                raise ValueError(f"unknown lut_dtype {lut_dtype!r}")
            if lut_dtype == "int8":
                amax = jnp.max(jnp.abs(lutf), axis=2, keepdims=True)  # (T,pc,1)
                scale = jnp.maximum(amax, 1e-30) / 127.0
                lut_q = jnp.clip(jnp.round(lutf / scale), -127, 127).astype(jnp.int8)
                acc = lax.dot_general(
                    ohf.astype(jnp.int8), lut_q,
                    (((3,), (2,)), ((0, 1), (0, 1))),
                    preferred_element_type=jnp.int32,
                )  # (T, pc, cap) int32
                scores = acc.astype(jnp.float32) * scale
            else:
                ct = jnp.bfloat16 if lut_dtype == "bfloat16" else jnp.float32
                scores = lax.dot_general(
                    ohf.astype(ct), lutf.astype(ct),
                    (((3,), (2,)), ((0, 1), (0, 1))),
                    preferred_element_type=jnp.float32,
                )  # (T, pc, cap)
            scores = scores + bias[:, :, None]
            scores = jnp.where(ids >= 0, scores, -jnp.inf if inner else jnp.inf)
            if keep_mask is not None:
                from .sample_filter import apply_id_filter

                scores = apply_id_filter(scores, ids, keep_mask, not inner)
            flat_s = scores.reshape(query_tile, probe_chunk * cap)
            flat_i = ids.reshape(query_tile, probe_chunk * cap)
            return c + 1, _select_k(flat_s, flat_i, k, not inner)

        _, (cv, ci) = lax.scan(per_chunk, 0, None, length=n_chunks)
        cv = jnp.moveaxis(cv, 0, 1).reshape(query_tile, n_chunks * k)
        ci = jnp.moveaxis(ci, 0, 1).reshape(query_tile, n_chunks * k)
        return _select_k(cv, ci, k, not inner)

    dists, idx = lax.map(per_tile, (qt, pt))
    dists = dists.reshape(num * query_tile, k)[:m]
    idx = idx.reshape(num * query_tile, k)[:m]
    if not inner and metric in (DistanceType.L2SqrtExpanded, DistanceType.L2SqrtUnexpanded):
        dists = jnp.where(jnp.isfinite(dists), jnp.sqrt(jnp.maximum(dists, 0.0)), dists)
    if keep_mask is not None:
        # filtered-out candidates carry ±inf scores — report id -1
        idx = jnp.where(jnp.isinf(dists), -1, idx)
    return dists, idx


@auto_convert_output
def search(params: SearchParams, index: IvfPqIndex, queries, k: int,
           sample_filter=None, res: Resources | None = None):
    """Search (reference: ivf_pq::search :723; pylibraft neighbors/ivf_pq;
    filtered overload neighbors/ivf_pq.cuh search_with_filtering).

    Returns (distances (m, k), ids (m, k)); distances are approximate
    (PQ-quantized), id -1 marks empty candidate slots.

    Tracer caveat: when ``index`` is passed as a jit argument its
    ``list_sizes`` is a tracer, so the "index is empty" guard (like the
    ``index.size`` property) cannot run — searching an empty index inside a
    user jit returns all-sentinel results (-1 ids, +inf distances) instead
    of raising."""
    from .sample_filter import resolve_filter

    res = res or default_resources()
    queries = jnp.asarray(queries)
    expects(queries.ndim == 2 and queries.shape[1] == index.dim, "query dim mismatch")
    expects(index.capacity > 0, "index is empty")
    if not isinstance(index.list_sizes, jax.core.Tracer):
        expects(index.size > 0, "index is empty")
    n_probes = min(params.n_probes, index.n_lists)
    expects(k <= n_probes * index.capacity, "k exceeds probed candidate pool")
    m = queries.shape[0]

    expects(params.lut_dtype in ("float32", "bfloat16", "int8"),
            "lut_dtype must be 'float32', 'bfloat16' or 'int8', got %r",
            params.lut_dtype)
    n_codes = index.codebooks.shape[-2]
    query_tile, probe_chunk = plan_search_tiles(
        m, n_probes, int(k), index.capacity,
        bytes_per_probe_row=pq_scan_bytes_per_probe_row(
            index.capacity, index.pq_dim, n_codes),
        budget_bytes=res.workspace_bytes,
        max_query_tile=128,
    )

    keep_mask = resolve_filter(sample_filter)
    if keep_mask is not None:
        from .sample_filter import validate_filter_covers

        validate_filter_covers(index, keep_mask)
    return _pq_search(
        index, queries, n_probes, int(k), query_tile, probe_chunk, index.metric,
        index.codebook_kind, params.lut_dtype,
        keep_mask,
    )


def save(index: IvfPqIndex, path: str) -> None:
    """Serialize (reference: ivf_pq_serialize.cuh:52-110)."""
    with open(path, "wb") as f:
        serialize_header(f, "ivf_pq")
        serialize_scalar(f, int(index.metric))
        serialize_scalar(f, index.codebook_kind)
        serialize_scalar(f, index.pq_bits)
        serialize_scalar(f, float(index.split_factor))
        for arr in (index.centers, index.centers_rot, index.rotation, index.codebooks,
                    index.list_codes, index.list_ids, index.list_sizes):
            serialize_mdspan(f, arr)


def load(path: str, res: Resources | None = None) -> IvfPqIndex:
    """Deserialize (reference: ivf_pq_serialize.cuh deserialize)."""
    with open(path, "rb") as f:
        check_header(f, "ivf_pq")
        metric = DistanceType(deserialize_scalar(f))
        codebook_kind = deserialize_scalar(f)
        pq_bits = deserialize_scalar(f)
        split_factor = float(deserialize_scalar(f))
        arrs = [jnp.asarray(deserialize_mdspan(f)) for _ in range(7)]
    return IvfPqIndex(*arrs, metric=metric, codebook_kind=codebook_kind, pq_bits=pq_bits,
                      split_factor=split_factor)
