"""IVF-PQ: inverted-file index with product-quantized residuals.

Re-design of the reference's IVF-PQ (cpp/include/raft/neighbors/ivf_pq-inl.cuh:
build :270 / search :723; build detail/ivf_pq_build.cuh — rotation matrix
make_rotation_matrix :121, residuals select_residuals :165, codebook training
train_per_subset :343 / train_per_cluster :424; search detail/ivf_pq_search.cuh
— select_clusters :68, LUT scan ivfpq_search_worker :419, fused top-k; params
ivf_pq_types.hpp:48-140). The TPU re-think:

- **Rotation**: a (d_rot, d) orthonormal matrix (QR of Gaussian noise, exactly
  the reference's construction) — one GEMM at build and per query batch.
- **Codebooks**: PER_SUBSPACE trains one codebook per pq_dim subspace over
  all residual sub-vectors (vmapped balanced-EM — all subspaces train
  simultaneously as one batched kmeans, a TPU win over the reference's
  sequential stream loop); PER_CLUSTER trains per coarse cluster.
- **Codes**: stored unpacked, one uint8 per (vector, subspace) in padded
  lists (n_lists, capacity, pq_dim) — trading the reference's bit-packed
  layout (ivf_pq_codepacking.cuh) for direct gather/byte loads; pq_bits
  still bounds the codebook size.
- **Search**: coarse GEMM + select_k, then per (query-tile, probe-chunk):
  LUT = ‖residual_sub - codebook‖² for every subspace (one batched GEMM
  against the codebooks), scores = LUT-gather summed over subspaces, fused
  select_k. The reference's fp8-LUT trick maps to bf16 LUTs (lut_dtype).
"""

from __future__ import annotations

from ..config import auto_convert_output

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..cluster import kmeans_balanced
from ..cluster.kmeans_balanced import KMeansBalancedParams
from ..core import chunked, tracing
from ..core.errors import expects
from ..core.logger import logger
from ..obs import mem as obs_mem
from ..obs import metrics
from ..obs.instrument import dtype_of, instrument, nrows
from ..core.resources import Resources, default_resources
from ..core import serialize as core_serialize
from ..core.serialize import (check_header, deserialize_mdspan, deserialize_scalar,
                              deserialize_tuned, serialize_header,
                              serialize_mdspan, serialize_scalar,
                              serialize_tuned, version_number)
from ..distance.pairwise import _choose_tile
from ..distance.types import DistanceType, resolve_metric
from ..matrix.select_k import _select_k, select_k_impl
from ..random.rng import as_key
from ._list_utils import (assign_to_lists, bound_capacity,
                          funnel_scan_bytes_per_probe_row, list_positions,
                          plan_search_tiles, pq_scan_bytes_per_probe_row,
                          round_up)

__all__ = ["IndexParams", "SearchParams", "IvfPqIndex", "build", "extend", "search", "save", "load"]


@functools.lru_cache(maxsize=None)
def _quant_opq_seconds():
    return metrics.histogram(
        "raft_tpu_quant_opq_train_seconds",
        "OPQ rotation training wall time per build", unit="s")


@functools.lru_cache(maxsize=None)
def _quant_funnel_total():
    return metrics.counter(
        "raft_tpu_quant_funnel_searches_total",
        "searches routed through the fast-scan funnel (funnel_widen > 1)")


@functools.lru_cache(maxsize=None)
def _quant_bytes_per_row():
    return metrics.gauge(
        "raft_tpu_quant_scan_bytes_per_row",
        "hot-scan HBM bytes per stored row, by tier (labels: tier)",
        unit="bytes")


@dataclasses.dataclass(frozen=True)
class IndexParams:
    """Reference: ivf_pq::index_params (ivf_pq_types.hpp:48-105)."""

    n_lists: int = 1024
    metric: str | DistanceType = "sqeuclidean"
    # codebook size = 2**pq_bits; 4..8 supported. DEFAULT DIFFERS FROM THE
    # REFERENCE (ivf_pq_types.hpp:68 defaults 8): the TPU LUT scan is a
    # one-hot MXU contraction whose axis is pq_dim * 2**pq_bits, so pq8
    # costs ~16x pq4 at equal code bytes (measured at 1M x 128: pq4x64
    # 41.4k QPS vs pq8x32 2.6k at the same recall point; int8/bf16 LUTs do
    # not close it). The reference's smem-gather LUT is bits-insensitive,
    # which does NOT hold here — prefer pq_bits=4 with doubled pq_dim. See
    # docs/migrating_from_raft.md.
    pq_bits: int = 4
    pq_dim: int = 0  # 0 → auto: same code bytes as the reference default (d/2 at 8 bits, d at 4)
    codebook_kind: str = "per_subspace"  # ref :43 codebook_gen
    force_random_rotation: bool = False  # ref :98
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    # coarse-trainer EM cost policy (KMeansBalancedParams.train_mode /
    # batch_rows; see ivf_flat.IndexParams — same contract): "auto" =
    # mini-batch EM above 2 x kmeans_batch_rows trainset rows, cutting the
    # ~22 full-trainset assignment passes to the two closing passes. The
    # PQ codebook trainers are untouched (they already train on a pooled
    # subsample).
    kmeans_train_mode: str = "auto"
    kmeans_batch_rows: int = 65536
    add_data_on_build: bool = True
    seed: int = 0
    # capacity bound for sub-list splitting (multiple of mean list size, see
    # _list_utils.bound_capacity). The LUT scan's one-hot contraction work
    # scales with capacity, so tighter capacity pays even more than for
    # ivf_flat: 1.3 measured +68% QPS (20.5k -> 34.4k at 1M, p=8) at
    # identical recall
    split_factor: float = 1.3
    # pq_bits=8 layout. True: two-stage 4+4-bit residual quantizer per
    # subspace — the codeword is cb1[hi_nibble] + cb2[lo_nibble], so the
    # scan's one-hot contraction axis is pq_dim*32 instead of pq_dim*256 (8x
    # less MXU work; for L2 the query-independent cross term 2*cb1·cb2 is
    # precomputed per vector at encode time into list_consts). Same 8 code
    # bits per subspace; the representable set is the Minkowski sum of two
    # 16-entry codebooks. False: the reference's joint 256-entry codebook
    # (ivf_pq_compute_similarity's LUT), ~8x slower to scan on TPU but a
    # finer quantizer. None (default) = metric-aware auto: split for L2
    # (measured ~12% relative bare-recall cost for a 8x QPS gain,
    # BASELINE.md), joint for inner_product (the Minkowski coarseness costs
    # IP ranking far more — measured recall@5 0.375 joint vs 0.075 split on
    # tight clusters at 4x compression).
    pq8_split: bool | None = None
    # Per-list residual scale normalization (VERDICT r5 #2, the heavytail
    # remedy; reference counterpart: PER_CLUSTER codebook_gen is the
    # reference's only scale-adaptation lever, ivf_pq_types.hpp:43 — this is
    # the cheaper half of it). Population-skewed data (the repo's heavytail
    # family: lognormal per-cluster residual scales) makes ONE codebook span
    # orders of magnitude of residual norm, so the codewords concentrate on
    # the large-scale clusters and small-scale lists quantize to mush
    # (measured collapse: 0.28 recall @ 1M, BASELINE.md "Round-5 heavytail
    # family"). True: store one f32 scale per list (RMS residual norm of the
    # training members; global RMS for lists the trainset missed), train the
    # codebooks on UNIT-scale residuals, encode r/s_list, and fold s back in
    # at search inside the LUT (s^2 for L2, s for IP) — exact scoring of
    # ||r - s*decode||^2, ~zero scan-time cost (the fold is one multiply on
    # the per-probe LUT). Composes with either codebook_kind.
    residual_scale_norm: bool = False
    # Learned rotation (quantization funnel stage a). "opq": alternate
    # codebook-fit / orthogonal-Procrustes updates on rotating mini-batches
    # (Ge et al., CVPR'13 — the same jitted mini-batch EM discipline as the
    # coarse trainer's minibatch mode) and fold the learned R into the
    # index rotation, so search pays nothing beyond the one rotation matmul
    # it already does. "none": the reference behavior (identity/QR per
    # force_random_rotation).
    rotation: str = "none"
    opq_rounds: int = 8  # alternations of fit-codebooks / Procrustes-update
    opq_batch_rows: int = 16384  # rows per rotating OPQ mini-batch
    # Codebook training loss (funnel stage b). "anisotropic": ScaNN-style
    # score-aware weighting (Guo et al., ICML'20) — residual error PARALLEL
    # to the datapoint direction costs eta x the orthogonal error, because
    # parallel error is what perturbs inner-product scores near the top of
    # a ranking. Codebook fit and encode assignment both use the weighted
    # distance; search LUTs are untouched (scores stay exact for whatever
    # code was assigned). Pays on IP workloads; needs a joint codebook
    # (incompatible with the nibble-split pq8 trainer).
    codebook_loss: str = "l2"
    # anisotropic parallel/orthogonal weight; 0.0 = auto from the ScaNN
    # threshold rule eta = (d_rot - 1) T^2 / (1 - T^2) at T = 0.2
    anisotropic_eta: float = 0.0
    # Fast-scan pre-filter tier (funnel stage c): per-row bit-packed
    # signatures of the rotated residual scanned AHEAD of the PQ scan, so
    # widen-then-refine becomes binary widen -> PQ rerank -> exact refine.
    #   "1bit" — RaBitQ-style sign bits, ceil(d_rot/8) bytes/row: the hot
    #            scan streams ~4x fewer HBM bytes than pq4 x (pq_dim=d)
    #            unpacked codes.
    #   "4bit" — per-dim 4-bit levels, ceil(d_rot/2) bytes/row: a finer
    #            estimator at pq4-class bytes.
    #   "none" — no tier; SearchParams.funnel_widen must stay 1.
    # Estimated scores pre-filter only — survivors are re-scored exactly
    # (PQ decode), so funnel results at sufficient widen match classic PQ.
    fast_scan: str = "none"


@dataclasses.dataclass(frozen=True)
class SearchParams:
    """Reference: ivf_pq::search_params (ivf_pq_types.hpp:108-140)."""

    n_probes: int = 20
    # "float32" | "bfloat16" | "int8" (ref lut_dtype, ivf_pq_types.hpp:122 —
    # the fp8-class smem LUT maps to bf16/int8 here; int8 quantizes per
    # (query, probe) with a symmetric scale and accumulates in int32 on the
    # MXU's int8 path, halving LUT operand bytes again vs bf16)
    lut_dtype: str = "float32"
    # scan formulation for Σ_s LUT[s, code_s] (ref compute_similarity's smem
    # gather, ivf_pq_compute_similarity-inl.cuh — a TPU has no smem gather,
    # so the gather is re-expressed):
    #   "onehot" — one-hot MXU contraction (the r01+ path, and what "auto"
    #              picks): a (T, pc, cap, pq_dim*K) operand XLA fuses the
    #              codes gather + compare + cast into, streamed at HBM rate.
    #   "pallas" — fused Pallas kernel (ops/pq_scan.py): LUT resident in
    #              VMEM, codes streamed as int8 planes, tpu.dynamic_gather
    #              (the hardware LUT16 shuffle) + MXU lane reduction. TPU
    #              (or interpret) only; 16-wide stages (pq4 / split pq8).
    #              Measured 0.73x the onehot path at 1M — issue-overhead
    #              bound (BASELINE.md "Round-4 PQ scan study") — kept as an
    #              option and as the starting point for a grouped scan.
    #   "select" — the compare+select chain left to XLA: 0.55x onehot at 1M
    #              (XLA materializes each of the 16 passes); reference impl.
    #   "auto"   — onehot (fastest measured everywhere).
    scan_impl: str = "auto"
    # scan ORDER (orthogonal to scan_impl):
    #   "tiled"   — query-major (r01-r03): (query_tile, probe_chunk) walks,
    #               one-hot operand rebuilt per (query, probe) pair.
    #   "grouped" — probe-major (r04): the batch's (query, probe) pairs sort
    #               by list id; each group of `group_size` pairs sharing a
    #               list scores ONE shared one-hot against all its LUTs in
    #               a real-N MXU matmul (G-way operand amortization). Needs
    #               k <= capacity. MEASURED NEUTRAL at 1M (40.7k vs the
    #               tiled path's 41.2k QPS, ~61 pairs/list): the 4x operand
    #               -traffic cut bought nothing, i.e. the tiled contraction
    #               was never operand-bound — XLA fuses the one-hot producer
    #               into the dot (BASELINE.md "Round-4 grouped scan"). Kept
    #               as a tested option; the balance may flip at higher
    #               pairs-per-list ratios or future XLA versions.
    #   "auto"    — tiled (measured at least as fast everywhere tried).
    scan_order: str = "auto"
    # pairs per group for the grouped order (padding waste rises, and
    # amortization improves, with larger G)
    group_size: int = 16
    # candidate top-k implementation for the scan's per-chunk + final-merge
    # selects (matrix/select_k.py select_k_impl):
    #   "auto"   — the measured dispatch rule (streaming Pallas selector on
    #              TPU for f32 rows >= 65536 cols, k <= 256 since r06;
    #              lax.top_k otherwise). The k <= 256 reach is what routes
    #              CAGRA's build-chunk k=gpu_top_k+1 select through the
    #              wide selector when its shapes qualify.
    #   "xla"    — force lax.top_k (the r01-r05 behavior).
    #   "pallas" — force the Pallas selector (f32 scores only); the A/B
    #              lever bench/cagra_build_select_ab.py sweeps at the CAGRA
    #              build-chunk shapes, whose per-chunk widths sit BELOW the
    #              65536-column auto threshold — the driver measurement
    #              decides whether auto's wide-k threshold should drop.
    # The coarse cluster select (k = n_probes, n_lists cols) always stays
    # on lax.top_k — never in the wide regime.
    select_impl: str = "auto"
    # Quantization-funnel width (first-class tuned knob, like refine_ratio):
    # > 1 routes search through the fast-scan tier — per probe chunk the
    # bit-packed signatures are scanned first and only the best
    # funnel_widen * k candidates reach the exact PQ decode-and-rerank; the
    # candidate merges stay on the one select_k dispatch with the shared
    # -1/±inf sentinel. Requires an index built with IndexParams.fast_scan.
    # 1 (default) = the classic full PQ scan, bit-for-bit unchanged.
    funnel_widen: int = 1


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class IvfPqIndex:
    """Reference: ivf_pq::index (ivf_pq_types.hpp:172-300)."""

    centers: jax.Array  # (n_lists, d) f32 coarse centers
    centers_rot: jax.Array  # (n_lists, d_rot) f32 — rotated centers
    rotation: jax.Array  # (d_rot, d) f32 orthonormal
    codebooks: jax.Array  # per_subspace: (pq_dim, K, pq_len); per_cluster: (n_lists, K, pq_len); K = 2**bits, or 2*16 when pq_split
    list_codes: jax.Array  # (n_lists, capacity, pq_dim) uint8
    list_ids: jax.Array  # (n_lists, capacity) int32, -1 padding
    list_sizes: jax.Array  # (n_lists,) int32
    # (n_lists, capacity) f32 per-vector scan constant for pq_split L2
    # (sum_s 2*cb1[s,hi_s]·cb2[s,lo_s]); (n_lists, 0) otherwise
    list_consts: jax.Array = None
    # (n_lists,) f32 per-list residual scales (IndexParams.residual_scale
    # _norm); (0,) = normalization disabled. Codes encode r/s_list; search
    # folds s_list back into the LUT, so scores stay exact ||r - s*decode||^2
    list_scales: jax.Array = None
    # (n_lists, capacity, sig_words) uint8 bit-packed fast-scan signatures
    # (IndexParams.fast_scan); (n_lists, 0, 0) = no tier. 1bit packs sign
    # bits of the raw rotated residual 8/byte; 4bit packs per-dim levels
    # 2/byte (lo nibble = even dim)
    list_sig: jax.Array = None
    # (n_lists,) f32 per-list signature decode scales (mean |r_j| for 1bit,
    # per-dim RMS for 4bit); (0,) = no tier
    sig_scales: jax.Array = None
    metric: DistanceType = DistanceType.L2Expanded
    codebook_kind: str = "per_subspace"
    pq_bits: int = 8
    # build-time capacity policy, inherited by extend()
    split_factor: float = 1.3
    # True: codes are hi/lo nibble pairs into two 16-entry stage codebooks
    # (codebooks[..., :16, :] and [..., 16:, :]); see IndexParams.pq8_split
    pq_split: bool = False
    # what the ingested dataset WAS (reference: the ivf_pq int8_t/uint8_t
    # instantiations, cpp/src/neighbors/ivf_pq_build_*.cu): "float32"
    # (float data), "int8" (signed bytes), "uint8" (bytes ingested shifted
    # by -128 into the s8 domain — queries shift the same way at search;
    # L2 is shift-invariant). The stored representation is PQ codes either
    # way; data_kind governs what extend() accepts and how search()
    # coerces queries, so a byte index never silently mixes domains.
    data_kind: str = "float32"
    # quantization-funnel codec provenance (raft_tpu/13 codec record):
    # rotation_kind "none"|"opq"; codebook_loss "l2"|"anisotropic" (encode
    # assignment re-derives the auto eta from d_rot); fast_scan
    # "none"|"1bit"|"4bit" (must agree with the list_sig shape)
    rotation_kind: str = "none"
    codebook_loss: str = "l2"
    fast_scan: str = "none"
    # pinned operating point (raft_tpu.tune decision dict; None = untuned):
    # consulted by batched_searcher when no explicit params are given,
    # persisted by save/load (raft_tpu/9). NOT part of the pytree (same
    # contract as cagra's seed_pool_hint): tree round trips drop it back
    # to None — defaults, never an error.
    tuned: dict | None = None

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def rot_dim(self) -> int:
        return self.rotation.shape[0]

    @property
    def pq_dim(self) -> int:
        return self.list_codes.shape[2]

    @property
    def pq_len(self) -> int:
        return self.rot_dim // self.pq_dim

    @property
    def capacity(self) -> int:
        return self.list_codes.shape[1]

    @property
    def size(self) -> int:
        """Total stored vectors. Computed on host so it stays concrete even
        when an enclosing jit trace is active (e.g. a user wrapping search()
        in jax.jit captures the index as a closure constant — staging the sum
        would make int() fail on a tracer). Unavailable when the index itself
        is a traced jit argument."""
        import numpy as np

        return int(np.asarray(jax.device_get(self.list_sizes)).sum())

    def __post_init__(self):
        if self.list_consts is None:
            self.list_consts = jnp.zeros((self.list_codes.shape[0], 0), jnp.float32)
        if self.list_scales is None:
            self.list_scales = jnp.zeros((0,), jnp.float32)
        if self.list_sig is None:
            self.list_sig = jnp.zeros((self.list_codes.shape[0], 0, 0),
                                      jnp.uint8)
        if self.sig_scales is None:
            self.sig_scales = jnp.zeros((0,), jnp.float32)

    @property
    def scale_normed(self) -> bool:
        """True when codes encode per-list-normalized residuals (shape-level
        flag, so it stays concrete inside jit traces)."""
        return self.list_scales.shape[0] > 0

    @property
    def has_fast_scan(self) -> bool:
        """True when the index carries a bit-packed fast-scan tier (shape-
        level flag — the sig word width is static inside jit traces)."""
        return self.list_sig.shape[-1] > 0

    def tree_flatten(self):
        children = (self.centers, self.centers_rot, self.rotation, self.codebooks,
                    self.list_codes, self.list_ids, self.list_sizes,
                    self.list_consts, self.list_scales, self.list_sig,
                    self.sig_scales)
        return children, (self.metric, self.codebook_kind, self.pq_bits,
                          self.split_factor, self.pq_split, self.data_kind,
                          self.rotation_kind, self.codebook_loss,
                          self.fast_scan)

    @classmethod
    def tree_unflatten(cls, aux, children):
        kind = aux[5] if len(aux) > 5 else "float32"
        # pre-funnel pytrees (9 children, 6 aux) unflatten to a codec-free
        # index — the same back-compat contract as data_kind above
        extra = aux[6:9] if len(aux) > 8 else ("none", "l2", "none")
        return cls(*children, metric=aux[0], codebook_kind=aux[1], pq_bits=aux[2],
                   split_factor=aux[3], pq_split=aux[4], data_kind=kind,
                   rotation_kind=extra[0], codebook_loss=extra[1],
                   fast_scan=extra[2])


def _resolve_pq_ingest(x, mt: DistanceType):
    """int8/uint8 dataset ingestion (reference: the ivf_pq int8_t/uint8_t
    instantiations, cpp/src/neighbors/ivf_pq_build_*.cu — BigANN-class byte
    data is PQ's home regime). Returns (data_kind, f32 working view): uint8
    shifts by -128 into the s8 domain first (L2 is shift-invariant; queries
    shift the same way at search), and all PQ math — coarse k-means,
    residuals, codebook training, encoding — runs in f32, where every 8-bit
    integer is exactly representable. Shared by the single-chip and
    distributed (parallel/ivf.build_pq) builds so both ingest identically."""
    int_dtypes = (jnp.dtype(jnp.int8), jnp.dtype(jnp.uint8))
    if x.dtype not in int_dtypes:
        return "float32", x
    # uint8 under IP is NOT shift-invariant and the per-vector sum
    # correction is not stored (same contract as ivf_flat int8 storage)
    expects(mt != DistanceType.InnerProduct or x.dtype == jnp.int8,
            "uint8 + inner_product is unsupported for ivf_pq byte ingestion "
            "(the -128 shift changes inner products); cast to float32")
    from .brute_force import _as_signed

    return str(x.dtype), _as_signed(x).astype(jnp.float32)


def _stream_ingest(data_kind: str):
    """Device-side conversion raw chunk -> the build's f32 working domain —
    the streamed twin of :func:`_resolve_pq_ingest`'s second return. Float
    data passes through untouched (exactly as in-core, where the working
    view IS the ingested array); bytes shift + upcast. Elementwise, so it
    commutes with the trainset row gather (bit-equality contract)."""
    if data_kind in ("int8", "uint8"):
        from .brute_force import _as_signed

        return lambda v: _as_signed(v).astype(jnp.float32)
    return lambda v: v


def _default_pq_dim(d: int, pq_bits: int = 4) -> int:
    """Bits-aware variant of the reference heuristic (ivf_pq_types.hpp:81,
    ~d/2 at its default 8 bits): the auto pq_dim keeps CODE BYTES equal to
    the reference default — d/2 dims at 8 bits and d dims at 4 bits are both
    d/2 bytes per vector, so switching the TPU-preferred pq_bits=4 default
    does not silently halve quantization budget."""
    pq = max((d * 8) // (2 * pq_bits), 1)
    if pq >= 8:
        pq = (pq // 8) * 8
    return min(pq, d)


def _make_rotation(key, d_rot: int, d: int, force_random: bool):
    """Reference: make_rotation_matrix (ivf_pq_build.cuh:121) — random
    orthonormal via QR when forced or when d_rot != d; else identity(-pad)."""
    if not force_random and d_rot == d:
        return jnp.eye(d, dtype=jnp.float32)
    if not force_random:
        eye = jnp.zeros((d_rot, d), jnp.float32)
        return eye.at[jnp.arange(min(d_rot, d)), jnp.arange(min(d_rot, d))].set(1.0)
    g = jax.random.normal(key, (max(d_rot, d), max(d_rot, d)), jnp.float32)
    q, _ = jnp.linalg.qr(g)
    return q[:d_rot, :d]


@functools.partial(jax.jit, static_argnames=("n_codes", "n_iters"))
def _train_codebooks_batched(subvecs, key, n_codes: int, n_iters: int):
    """Train all codebooks simultaneously: subvecs (B, n, pq_len) → codebooks
    (B, n_codes, pq_len). One vmapped mini-batch EM — every subspace (or
    cluster) trains in parallel on the MXU (ref: train_per_subset :343 runs a
    stream loop; TPU batches it instead)."""

    def one(sv, k):
        n = sv.shape[0]
        # small pools (n < n_codes) seed with replacement — duplicates split
        # during EM; matches the reference's tolerance for tiny trainsets
        init_idx = jax.random.choice(k, n, (n_codes,), replace=n < n_codes)
        centers = jnp.take(sv, init_idx, axis=0)

        def body(i, c):
            d2 = (
                jnp.sum(c * c, axis=1)[None, :]
                - 2.0 * sv @ c.T
            )  # (n, n_codes)
            labels = jnp.argmin(d2, axis=1)
            onehot = jax.nn.one_hot(labels, n_codes, dtype=jnp.float32, axis=0)
            sums = onehot @ sv
            counts = jnp.sum(onehot, axis=1)
            return jnp.where(counts[:, None] > 0, sums / jnp.maximum(counts, 1.0)[:, None], c)

        return lax.fori_loop(0, n_iters, body, centers)

    keys = jax.random.split(key, subvecs.shape[0])
    return jax.vmap(one)(subvecs.astype(jnp.float32), keys)


@functools.partial(jax.jit, static_argnames=("n_iters", "refine_rounds"))
def _train_split_codebooks(subvecs, key, n_iters: int, refine_rounds: int = 3):
    """Two-stage 4+4-bit residual codebooks (pq8_split): stage 1 is 16-means
    over the subvectors, stage 2 is 16-means over the stage-1 residuals
    (classic residual VQ), then ``refine_rounds`` of alternating
    re-fitting under the JOINT encoding (additive-quantization style: encode
    against the composed 256-codeword sum, re-fit each stage to the residual
    of the other) — recovers part of the expressiveness gap vs a free
    256-entry codebook. Returns (B, 32, pq_len): stage-1 entries in
    [..., :16, :], stage-2 in [..., 16:, :]."""
    k1, k2 = jax.random.split(key)
    sv = subvecs.astype(jnp.float32)
    cb1 = _train_codebooks_batched(sv, k1, 16, n_iters)

    def stage1_residual(s, c):
        d2 = jnp.sum(c * c, axis=1)[None, :] - 2.0 * s @ c.T
        return s - jnp.take(c, jnp.argmin(d2, axis=1), axis=0)

    resid2 = jax.vmap(stage1_residual)(sv, cb1)
    cb2 = _train_codebooks_batched(resid2, k2, 16, n_iters)

    def refine_one(s, c1, c2):
        def round_(carry, _):
            c1, c2 = carry
            comp = (c1[:, None, :] + c2[None, :, :]).reshape(256, c1.shape[-1])
            d2 = jnp.sum(comp * comp, axis=1)[None, :] - 2.0 * s @ comp.T
            code = jnp.argmin(d2, axis=1)
            hi, lo = code // 16, code % 16
            oh_hi = jax.nn.one_hot(hi, 16, dtype=jnp.float32, axis=0)  # (16, n)
            oh_lo = jax.nn.one_hot(lo, 16, dtype=jnp.float32, axis=0)
            r1 = s - jnp.take(c2, lo, axis=0)
            c1n = jnp.where(
                (oh_hi.sum(1) > 0)[:, None],
                (oh_hi @ r1) / jnp.maximum(oh_hi.sum(1), 1.0)[:, None], c1)
            r2 = s - jnp.take(c1n, hi, axis=0)
            c2n = jnp.where(
                (oh_lo.sum(1) > 0)[:, None],
                (oh_lo @ r2) / jnp.maximum(oh_lo.sum(1), 1.0)[:, None], c2)
            return (c1n, c2n), None

        (c1, c2), _ = lax.scan(round_, (c1, c2), None, length=refine_rounds)
        return jnp.concatenate([c1, c2], axis=0)

    return jax.vmap(refine_one)(sv, cb1, cb2)


def _composed_codebooks(codebooks):
    """Expand split codebooks (B, 32, L) to the effective 256-entry codebook
    (B, 256, L); entry hi*16+lo = cb1[hi] + cb2[lo] (row-major flatten keeps
    the hi/lo nibble order consistent with the scan)."""
    cb = codebooks.astype(jnp.float32)
    cb1, cb2 = cb[:, :16, :], cb[:, 16:, :]
    comp = cb1[:, :, None, :] + cb2[:, None, :, :]
    return comp.reshape(cb.shape[0], 256, cb.shape[-1])


def _per_cluster_gain(resid, labels, codebooks, split: bool, key, n_iters: int,
                      n_trial: int = 8, member_cap: int = 2048):
    """Trial-train per-cluster codebooks on the ``n_trial`` largest clusters
    and return err_per_cluster / err_per_subspace (< 1 = per-cluster
    quantizes better). The empirical basis of the codebook-kind auto
    heuristic (reference counterpart: the PER_CLUSTER codebook_gen mode,
    ivf_pq_build.cuh:424 train_per_cluster — the reference leaves the choice
    entirely to the caller)."""
    import numpy as np

    n, pq_dim, pq_len = resid.shape
    # split codebooks are compared COMPOSED (the effective 256-entry Minkowski
    # sum), not by their 16-entry stage-1 proxy — the auto decision must weigh
    # what search actually scores against (ADVICE r3)
    cb_ps = _composed_codebooks(codebooks) if split else codebooks  # (pq_dim, K, L)
    k_codes = cb_ps.shape[1]
    # ONE host round-trip for the labels: counts and the per-cluster row
    # pools both derive from the same materialized array (two separate
    # np.asarray(labels) syncs here used to stall the dispatch queue twice
    # per auto build)
    lab_h = np.asarray(labels)
    counts = np.bincount(lab_h, minlength=1)
    trial = np.argsort(counts)[::-1][:n_trial]
    trial = trial[counts[trial] > 0]
    pools = []
    cap = min(member_cap, int(counts[trial].max()))
    for c in trial:
        rows = np.nonzero(lab_h == c)[0]
        rows = rows[np.arange(cap) % len(rows)]  # wraparound to fixed size
        pools.append(rows)
    pools = jnp.asarray(np.stack(pools))  # (C, cap)
    rv = jnp.take(resid, pools, axis=0)  # (C, cap, pq_dim, L)

    # per-subspace error: each subvector against its own subspace codebook
    def ps_err(r):  # (cap, pq_dim, L)
        d = (jnp.sum(cb_ps * cb_ps, axis=-1)[None]
             - 2.0 * jnp.einsum("nsl,skl->nsk", r, cb_ps))
        return jnp.sum(jnp.min(d, axis=-1) + jnp.sum(r * r, axis=-1))

    err_ps = jnp.sum(jax.vmap(ps_err)(rv))

    # trial per-cluster codebooks: pool subvectors across subspaces per
    # cluster; split trials train the same two-stage quantizer and compose,
    # so both error terms measure 256-entry effective codebooks
    flat = rv.reshape(len(trial), cap * pq_dim, pq_len)
    if split:
        cb_pc = _composed_codebooks(_train_split_codebooks(flat, key, n_iters))
    else:
        cb_pc = _train_codebooks_batched(flat, key, k_codes, n_iters)

    def pc_err(v, c):  # (cap*pq_dim, L), (K, L)
        d = (jnp.sum(c * c, axis=-1)[None]
             - 2.0 * v @ c.T)
        return jnp.sum(jnp.min(d, axis=-1) + jnp.sum(v * v, axis=-1))

    err_pc = jnp.sum(jax.vmap(pc_err)(flat, cb_pc))
    return float(err_pc) / max(float(err_ps), 1e-30)


@functools.partial(jax.jit, static_argnames=("n_lists",))
def _per_list_residual_scales(resid, labels, n_lists: int):
    """(n_lists,) RMS residual scale per list from the training residuals:
    s_l = sqrt(mean ||r||^2 / d_rot) over l's members; lists the trainset
    missed fall back to the global RMS (a fresh list has no scale evidence,
    and 1.0 would be arbitrary on data whose scales are nowhere near 1).
    Accumulation is a chunked one-hot matmul, not a scatter-add — XLA
    serializes scatters on TPU (the _reverse_merge lesson)."""
    n, pq_dim, pq_len = resid.shape
    rn2 = jnp.sum(resid.reshape(n, -1) ** 2, axis=1)
    blk = min(16384, max(round_up(n, 8), 8))
    num = -(-n // blk)
    rp = jnp.pad(rn2, (0, num * blk - n))
    # padding rows carry label n_lists — summed into a discard bucket
    lp = jnp.pad(labels.astype(jnp.int32), (0, num * blk - n),
                 constant_values=n_lists)

    def body(args):
        r, l = args
        oh = jax.nn.one_hot(l, n_lists + 1, dtype=jnp.float32, axis=0)
        return oh @ r, jnp.sum(oh, axis=1)

    sums, counts = lax.map(body, (rp.reshape(num, blk), lp.reshape(num, blk)))
    s = jnp.sum(sums, axis=0)[:n_lists]
    c = jnp.sum(counts, axis=0)[:n_lists]
    gmean = jnp.sum(rn2) / jnp.maximum(n, 1)
    msq = jnp.where(c > 0, s / jnp.maximum(c, 1.0), gmean)
    d_rot = pq_dim * pq_len
    return jnp.sqrt(jnp.maximum(msq / d_rot, 1e-24))


def _default_aniso_eta(d_rot: int, t: float = 0.2) -> float:
    """ScaNN's threshold rule (Guo et al., ICML'20 §3.2): weight parallel
    residual error eta = (d - 1) T^2 / (1 - T^2) at relative score
    threshold T — errors along the datapoint direction perturb the
    inner-product ranking ~eta times as much as orthogonal ones."""
    return max((d_rot - 1) * t * t / (1.0 - t * t), 1.0)


@functools.partial(jax.jit, static_argnames=("n_codes", "n_iters", "eta"))
def _train_codebooks_aniso(subvecs, key, n_codes: int, n_iters: int,
                           eta: float):
    """Anisotropic weighted EM (IndexParams.codebook_loss="anisotropic"):
    subvecs (B, n, pq_len) → codebooks (B, n_codes, pq_len), same batched
    layout as :func:`_train_codebooks_batched`. Assignment minimizes
    ||x - c||^2 + (eta - 1) <u, x - c>^2 with u = x/||x|| (parallel error
    weighted eta x); the centroid update solves the per-codeword normal
    equations (count I + (eta-1) Σ u u^T) c = eta Σ x — a (n_codes,
    pq_len, pq_len) batched solve, tiny at PQ subvector widths."""

    em1 = eta - 1.0

    def one(sv, k):
        n, L = sv.shape
        norm = jnp.sqrt(jnp.maximum(jnp.sum(sv * sv, axis=1), 1e-30))
        u = sv / norm[:, None]
        init_idx = jax.random.choice(k, n, (n_codes,), replace=n < n_codes)
        centers = jnp.take(sv, init_idx, axis=0)
        eye = jnp.eye(L, dtype=jnp.float32)

        def body(i, c):
            d2 = jnp.sum(c * c, axis=1)[None, :] - 2.0 * sv @ c.T
            # <u, x - c> = ||x|| - <u, c> (u is x's own direction)
            upar = norm[:, None] - u @ c.T
            labels = jnp.argmin(d2 + em1 * upar * upar, axis=1)
            oh = jax.nn.one_hot(labels, n_codes, dtype=jnp.float32, axis=0)
            counts = jnp.sum(oh, axis=1)
            suu = jnp.einsum("kn,nl,nm->klm", oh, u, u)
            a = counts[:, None, None] * eye[None] + em1 * suu
            a = a + 1e-6 * eye[None]
            b = eta * (oh @ sv)
            sol = jnp.linalg.solve(a, b[..., None])[..., 0]
            return jnp.where(counts[:, None] > 0, sol, c)

        return lax.fori_loop(0, n_iters, body, centers)

    keys = jax.random.split(key, subvecs.shape[0])
    return jax.vmap(one)(subvecs.astype(jnp.float32), keys)


@functools.partial(jax.jit, static_argnames=("pq_dim", "n_codes", "n_iters",
                                             "rounds", "batch"))
def _train_opq_rotation(resid_flat, key, pq_dim: int, n_codes: int,
                        n_iters: int, rounds: int, batch: int):
    """OPQ rotation (Ge et al., CVPR'13 Alg. 1), mini-batched: alternate
    (1) fit per-subspace codebooks on a rotating batch of rotated residuals
    (the same jitted mini-batch EM as the coarse trainer's minibatch mode),
    (2) solve the orthogonal Procrustes problem min_R ||X R^T - Y||_F over
    the batch's reconstructions Y (R = U V^T from SVD(Y^T X)). Returns the
    (d_rot, d_rot) learned rotation to fold into the index rotation —
    search pays nothing beyond the rotation matmul it already does."""
    n, d_rot = resid_flat.shape
    pq_len = d_rot // pq_dim
    kp, kr = jax.random.split(key)
    # one shuffle, then rounds walk it in rotating windows — every round
    # sees fresh rows until the trainset wraps (the coarse minibatch-EM
    # batching discipline)
    perm = jax.random.permutation(kp, resid_flat.astype(jnp.float32))
    rot = jnp.eye(d_rot, dtype=jnp.float32)
    keys = jax.random.split(kr, rounds)
    for i in range(rounds):
        start = (i * batch) % max(n - batch + 1, 1)
        xb = lax.dynamic_slice_in_dim(perm, start, batch, axis=0)
        xr = xb @ rot.T
        sub = jnp.moveaxis(xr.reshape(batch, pq_dim, pq_len), 1, 0)
        cb = _train_codebooks_batched(sub, keys[i], n_codes, n_iters)
        cb_n2 = jnp.sum(cb * cb, axis=-1)
        dots = jnp.einsum("snl,skl->snk", sub, cb,
                          precision=lax.Precision.HIGHEST)
        code = jnp.argmin(cb_n2[:, None, :] - 2.0 * dots, axis=-1)
        recon = jnp.take_along_axis(cb, code[..., None], axis=1)
        y = jnp.moveaxis(recon, 0, 1).reshape(batch, d_rot)
        u, _, vt = jnp.linalg.svd(y.T @ xb, full_matrices=True)
        rot = u @ vt
    return rot


def _sig_words(d_rot: int, fast_scan: str) -> int:
    """Packed signature bytes per row for a fast-scan mode."""
    if fast_scan == "1bit":
        return -(-d_rot // 8)
    if fast_scan == "4bit":
        return -(-d_rot // 2)
    return 0


@functools.partial(jax.jit, static_argnames=("n_lists", "fast_scan"))
def _per_list_sig_scales(resid_flat, labels, n_lists: int, fast_scan: str):
    """(n_lists,) decode scale per list for the signature estimator, from
    the RAW rotated training residuals: the least-squares fit of r ≈ s·σ
    is s = mean|r_j| for ±1 signs (1bit); 4bit levels span ±2 per-dim RMS,
    so s = sqrt(mean r_j^2). Lists the trainset missed fall back to the
    global mean (same contract as _per_list_residual_scales); accumulation
    is the same chunked one-hot matmul (no scatter-adds on TPU)."""
    n, d_rot = resid_flat.shape
    red = (jnp.sum(jnp.abs(resid_flat), axis=1) if fast_scan == "1bit"
           else jnp.sum(resid_flat * resid_flat, axis=1))
    blk = min(16384, max(round_up(n, 8), 8))
    num = -(-n // blk)
    rp = jnp.pad(red, (0, num * blk - n))
    lp = jnp.pad(labels.astype(jnp.int32), (0, num * blk - n),
                 constant_values=n_lists)

    def body(args):
        r, l = args
        oh = jax.nn.one_hot(l, n_lists + 1, dtype=jnp.float32, axis=0)
        return oh @ r, jnp.sum(oh, axis=1)

    sums, counts = lax.map(body, (rp.reshape(num, blk), lp.reshape(num, blk)))
    s = jnp.sum(sums, axis=0)[:n_lists]
    c = jnp.sum(counts, axis=0)[:n_lists]
    gmean = jnp.sum(red) / jnp.maximum(n, 1)
    per_dim = jnp.where(c > 0, s / jnp.maximum(c, 1.0), gmean) / d_rot
    per_dim = jnp.maximum(per_dim, 1e-24)
    return per_dim if fast_scan == "1bit" else jnp.sqrt(per_dim)


@functools.partial(jax.jit, static_argnames=("fast_scan",))
def _encode_sig(resid_flat, scales, fast_scan: str):
    """Bit-pack fast-scan signatures: resid_flat (n, d_rot) RAW rotated
    residuals + per-row decode scales (n,) → (n, sig_words) uint8.
    1bit: sign bits, dim 8w+b in bit b of byte w (scale-free). 4bit:
    levels round((r/s)/step) clipped to [0, 15] around mid-level 7.5
    (span ±2 RMS), even dim in the lo nibble. Padding dims pack as zero
    bits — the query-side LUT zeroes their contribution."""
    n, d_rot = resid_flat.shape
    r = resid_flat.astype(jnp.float32)
    if fast_scan == "1bit":
        w = -(-d_rot // 8)
        bits = (r > 0).astype(jnp.uint8)
        bits = jnp.pad(bits, ((0, 0), (0, w * 8 - d_rot)))
        weights = (jnp.uint8(1) << jnp.arange(8, dtype=jnp.uint8))
        return jnp.sum(bits.reshape(n, w, 8) * weights[None, None, :],
                       axis=-1, dtype=jnp.uint8)
    w = -(-d_rot // 2)
    step = 4.0 / 15.0
    lev = jnp.clip(jnp.round(r / (scales[:, None] * step) + 7.5), 0, 15)
    lev = jnp.pad(lev, ((0, 0), (0, w * 2 - d_rot))).astype(jnp.uint8)
    lo, hi = lev[:, 0::2], lev[:, 1::2]
    return lo | (hi << 4)


def _sig_nibble_lut(r, fast_scan: str, sig_words: int):
    """Per-(query, probe) nibble LUT for the signature scan: r (..., d_rot)
    raw rotated residuals → (..., sig_words, 32) where [..., :16] scores
    the HI nibble of each packed byte and [..., 16:] the LO nibble — the
    exact layout of the nibble-split one-hot contraction, so the fast-scan
    tier reuses the pq8_split scan machinery unchanged. Entry value =
    Σ_bits r_dim · level(bit), i.e. the contraction computes <r, σ> (1bit,
    σ ∈ {-1,1}) or <r, lev> (4bit) exactly; padding dims contribute 0."""
    d_rot = r.shape[-1]
    if fast_scan == "1bit":
        pad = sig_words * 8 - d_rot
        # padded query dims are ZERO, so their ±1 level contributes 0 —
        # padding needs no masking on either side
        rp = jnp.pad(r, [(0, 0)] * (r.ndim - 1) + [(0, pad)])
        r8 = rp.reshape(*r.shape[:-1], sig_words, 8)
        v = jnp.arange(16, dtype=jnp.int32)
        b = jnp.arange(4, dtype=jnp.int32)
        pm = (2 * ((v[:, None] >> b[None, :]) & 1) - 1).astype(jnp.float32)
        lut_lo = jnp.einsum("...wb,vb->...wv", r8[..., 0:4], pm)
        lut_hi = jnp.einsum("...wb,vb->...wv", r8[..., 4:8], pm)
        return jnp.concatenate([lut_hi, lut_lo], axis=-1)
    # 4bit: byte w covers dims 2w (lo nibble) and 2w+1 (hi nibble)
    pad = sig_words * 2 - d_rot
    rp = jnp.pad(r, [(0, 0)] * (r.ndim - 1) + [(0, pad)])
    r2 = rp.reshape(*r.shape[:-1], sig_words, 2)
    step = 4.0 / 15.0
    levels = (jnp.arange(16, dtype=jnp.float32) - 7.5) * step  # (16,)
    lut_lo = r2[..., 0:1] * levels
    lut_hi = r2[..., 1:2] * levels
    return jnp.concatenate([lut_hi, lut_lo], axis=-1)


@functools.partial(jax.jit, static_argnames=("per_cluster",))
def _pq_cross_consts(codes, codebooks, labels, per_cluster: bool):
    """Per-vector scan constant for split L2 scoring: sum_s 2*cb1[s,hi_s]·
    cb2[s,lo_s] — the cross term of ||cb1+cb2||^2 that the separated hi/lo
    LUTs cannot carry. Query-independent, so it is paid once here (encode
    time) instead of per (query, probe) at search."""
    cb = codebooks.astype(jnp.float32)
    X = 2.0 * jnp.einsum("bhl,bgl->bhg", cb[:, :16, :], cb[:, 16:, :])
    Xf = X.reshape(-1)  # flat index b*256 + hi*16 + lo = b*256 + code
    n, pq_dim = codes.shape
    blk = min(65536, max(round_up(n, 8), 8))
    num = -(-n // blk)
    cp = jnp.pad(codes, ((0, num * blk - n), (0, 0))).astype(jnp.int32)
    ct = cp.reshape(num, blk, pq_dim)
    if per_cluster:
        lp = jnp.pad(labels, (0, num * blk - n)).astype(jnp.int32)
        lt = lp.reshape(num, blk)

        def body(args):
            cb_, lb_ = args
            return jnp.sum(jnp.take(Xf, lb_[:, None] * 256 + cb_, axis=0), axis=1)

        out = lax.map(body, (ct, lt))
    else:
        offs = jnp.arange(pq_dim, dtype=jnp.int32) * 256

        def body(cb_):
            return jnp.sum(jnp.take(Xf, cb_ + offs[None, :], axis=0), axis=1)

        out = lax.map(body, ct)
    return out.reshape(num * blk)[:n]


@functools.partial(jax.jit, static_argnames=("per_cluster", "tile",
                                             "aniso_eta"))
def _encode(residuals_rot, codebooks, labels, per_cluster: bool, tile: int,
            aniso_eta: float = 0.0):
    """Nearest codebook entry per subspace, as tiled GEMMs.

    residuals_rot: (n, pq_dim, pq_len). codebooks: (pq_dim, K, L) for
    per_subspace, (n_lists, K, L) for per_cluster (selected via labels).
    Computes argmin over ‖r‖²-free scores ‖c‖² - 2·r·c (the search-LUT
    expansion) in row tiles so the (tile, pq_dim, K) block bounds memory.
    ``aniso_eta > 0`` switches to the score-aware anisotropic assignment
    (codebook_loss="anisotropic"): + (eta-1)·<u, r-c>² with u = r/‖r‖,
    matching the training loss. Returns (n, pq_dim) uint8.
    """
    n = residuals_rot.shape[0]
    cb = codebooks.astype(jnp.float32)
    cb_n2 = jnp.sum(cb * cb, axis=-1)  # (B, K)
    num = -(-n // tile)
    pad = num * tile - n
    r = jnp.pad(residuals_rot, ((0, pad), (0, 0), (0, 0))) if pad else residuals_rot
    lb = jnp.pad(labels, (0, pad)) if pad else labels
    rt = r.reshape(num, tile, *residuals_rot.shape[1:])
    lt = lb.reshape(num, tile)

    def body(args):
        rb, lbl = args  # (t, pq_dim, L), (t,)
        if per_cluster:
            cbl = cb[lbl]  # (t, K, L)
            dots = jnp.einsum("tsl,tkl->tsk", rb, cbl, precision=lax.Precision.HIGHEST)
            d2 = cb_n2[lbl][:, None, :] - 2.0 * dots
        else:
            dots = jnp.einsum("tsl,skl->tsk", rb, cb, precision=lax.Precision.HIGHEST)
            d2 = cb_n2[None] - 2.0 * dots
        if aniso_eta > 0.0:
            nrm = jnp.sqrt(jnp.maximum(jnp.sum(rb * rb, axis=-1), 1e-30))
            u = rb / nrm[..., None]  # (t, pq_dim, L)
            if per_cluster:
                ucb = jnp.einsum("tsl,tkl->tsk", u, cbl,
                                 precision=lax.Precision.HIGHEST)
            else:
                ucb = jnp.einsum("tsl,skl->tsk", u, cb,
                                 precision=lax.Precision.HIGHEST)
            # <u, r - c> = ‖r‖ - <u, c>; the ‖r‖²-free d2 gains the full
            # parallel-error surcharge (the dropped ‖r‖² is code-constant)
            d2 = d2 + (aniso_eta - 1.0) * (nrm[..., None] - ucb) ** 2
        return jnp.argmin(d2, axis=-1).astype(jnp.uint8)

    codes = lax.map(body, (rt, lt))
    return codes.reshape(num * tile, -1)[:n]


def _select_scores(codes, lut, split: bool):
    """Σ_s LUT[s, code_s] as 16 compare+select passes per stage — the VPU
    re-expression of the reference's smem LUT gather (the TPU analogue of
    ScaNN's SIMD LUT16 shuffle). ``codes`` (..., cap, pq_dim) uint8;
    ``lut`` (..., pq_dim, K) with K=16 (pq4) or K=32 (nibble-split pq8:
    stage-1 entries in [..., :16], stage-2 in [..., 16:]).

    Unlike the one-hot MXU contraction this never materializes a
    (..., cap, pq_dim*K) operand in HBM and never runs an N=1 batched matvec
    (~1/128 MXU utilization); XLA fuses the compare/select/add chain straight
    into the score reduction. Accumulation is f32 regardless of the LUT
    dtype (a bf16 LUT still halves nothing here — entries are register
    values — but keeps the rounding semantics of the one-hot path).
    """
    lutf = lut.astype(jnp.float32)
    acc = jnp.zeros(codes.shape, jnp.float32)  # (..., cap, pq_dim)
    if split:
        hi, lo = codes >> 4, codes & 0xF
        for kk in range(16):
            k8 = jnp.uint8(kk)
            acc = acc + jnp.where(hi == k8, lutf[..., None, :, kk], 0.0)
            acc = acc + jnp.where(lo == k8, lutf[..., None, :, 16 + kk], 0.0)
    else:
        for kk in range(lut.shape[-1]):
            acc = acc + jnp.where(codes == jnp.uint8(kk),
                                  lutf[..., None, :, kk], 0.0)
    return jnp.sum(acc, axis=-1)


def _fill_code_lists(codes, ids, labels, n_lists: int, capacity: int,
                     consts=None, sig=None):
    """Scatter codes into padded lists (shared ivf::list scheme). ``sig``
    (n, sig_words) scatters the fast-scan tier alongside the codes so the
    two layouts can never disagree on slot positions."""
    n, pq_dim = codes.shape
    pos, counts = list_positions(labels, n_lists)
    buf = jnp.zeros((n_lists, capacity, pq_dim), jnp.uint8)
    idbuf = jnp.full((n_lists, capacity), -1, jnp.int32)
    buf = buf.at[labels, pos].set(codes)
    idbuf = idbuf.at[labels, pos].set(ids.astype(jnp.int32))
    if consts is None:
        cbuf = jnp.zeros((n_lists, 0), jnp.float32)
    else:
        cbuf = jnp.zeros((n_lists, capacity), jnp.float32).at[labels, pos].set(consts)
    if sig is None:
        sbuf = jnp.zeros((n_lists, 0, 0), jnp.uint8)
    else:
        sbuf = jnp.zeros((n_lists, capacity, sig.shape[1]), jnp.uint8
                         ).at[labels, pos].set(sig)
    return buf, idbuf, counts.astype(jnp.int32), cbuf, sbuf


@instrument("ivf_pq.build",
            items=lambda a, kw: nrows(a[1] if len(a) > 1 else kw["dataset"]),
            labels=lambda a, kw: {
                "dtype": dtype_of(a[1] if len(a) > 1 else kw["dataset"]),
                "n_lists": (a[0] if a else kw["params"]).n_lists,
            })
def build(params: IndexParams, dataset, res: Resources | None = None) -> IvfPqIndex:
    """Build the index (reference: ivf_pq::build, ivf_pq-inl.cuh:270; call
    stack SURVEY.md §3.B)."""
    res = res or default_resources()
    stream = chunked.is_reader(dataset)
    x = None if stream else jnp.asarray(dataset)
    src = dataset if stream else x
    expects(src.ndim == 2, "dataset must be (n, d)")
    n, d = (int(s) for s in src.shape)
    expects(params.n_lists <= n, "n_lists > n_samples")
    expects(4 <= params.pq_bits <= 8, "pq_bits must be in [4, 8] (ref ivf_pq_types.hpp:68)")
    mt = resolve_metric(params.metric)
    expects(
        mt in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
               DistanceType.L2Unexpanded, DistanceType.L2SqrtUnexpanded,
               DistanceType.InnerProduct),
        "ivf_pq supports L2 / inner_product metrics, got %s", mt.name,
    )
    expects(params.codebook_kind in ("per_subspace", "per_cluster", "auto"),
            "codebook_kind must be per_subspace|per_cluster|auto")
    expects(params.rotation in ("none", "opq"),
            "rotation must be 'none' or 'opq', got %r", params.rotation)
    expects(params.codebook_loss in ("l2", "anisotropic"),
            "codebook_loss must be 'l2' or 'anisotropic', got %r",
            params.codebook_loss)
    expects(params.fast_scan in ("none", "1bit", "4bit"),
            "fast_scan must be 'none', '1bit' or '4bit', got %r",
            params.fast_scan)

    if stream:
        # dtype-only ingest resolution (same validation, on an empty
        # probe — the corpus never materializes here), then the STREAMED
        # admission: price the chunked build peak against BOTH budgets
        # before the coarse trainer spends anything
        from .ivf_flat import _stream_probe

        data_kind, _ = _resolve_pq_ingest(_stream_probe(dataset.dtype, d),
                                          mt)
        plan_kw = dict(
            dtype=data_kind if data_kind in ("int8", "uint8") else "float32",
            streamed=True, chunk_rows=dataset.chunk_rows)
        obs_mem.gate(
            res,
            lambda: obs_mem.plan("ivf_pq", params, n, d,
                                 **plan_kw)["build_peak_bytes"],
            site="build_stream", detail=f"ivf_pq {n}x{d} ooc",
            host_bytes=lambda: obs_mem.plan("ivf_pq", params, n, d,
                                            **plan_kw)["host_peak_bytes"])
        # the coarse trainer and the trainset gather below see the reader
        # through the build's exact working-domain conversion
        x = chunked.converted(dataset, _stream_ingest(data_kind))
    else:
        data_kind, x = _resolve_pq_ingest(x, mt)
        # memory-budget admission (no-op unless res.memory_budget_bytes is
        # set): refuse BEFORE the coarse trainer spends anything
        obs_mem.gate(res, lambda: obs_mem.plan(
            "ivf_pq", params, n, d)["index_bytes"],
            site="build", detail=f"ivf_pq {n}x{d}")
    pq_dim = params.pq_dim or _default_pq_dim(d, params.pq_bits)
    pq_len = -(-d // pq_dim)
    d_rot = pq_dim * pq_len
    n_codes = 1 << params.pq_bits
    key = as_key(params.seed)

    # 1. coarse quantizer (ref §3.B step 2)
    max_train = max(int(n * params.kmeans_trainset_fraction), params.n_lists)
    train_metric = "inner_product" if mt == DistanceType.InnerProduct else "sqeuclidean"
    kb = KMeansBalancedParams(
        n_iters=params.kmeans_n_iters, metric=train_metric, seed=params.seed,
        max_train_points=min(max_train, n),
        train_mode=params.kmeans_train_mode,
        batch_rows=params.kmeans_batch_rows,
    )
    with tracing.range("ivf_pq.build.coarse_kmeans"):
        centers = kmeans_balanced.fit(kb, x, params.n_lists, res=res)
    if params.add_data_on_build:
        from .ivf_flat import _count_fill_pass

        _count_fill_pass(kb, n)

    # 2. rotation (ref step 3)
    key, kr = jax.random.split(key)
    rotation = _make_rotation(kr, d_rot, d, params.force_random_rotation)
    centers_rot = centers @ rotation.T  # (n_lists, d_rot)

    # 3. residuals of a training subsample (ref steps 4-5 — the reference
    # trains codebooks on the same subsampled trainset as the coarse
    # quantizer, train_per_subset operates on the trainset, not the dataset)
    n_train = min(max_train, n)
    key, ks = jax.random.split(key)
    if n_train < n:
        train_idx = jax.random.choice(ks, n, (n_train,), replace=False)
        # take_rows: jnp.take in-core, a host page-gather off the reader
        # streamed — SAME indices, bit-equal rows (core/chunked docstring)
        xt = chunked.take_rows(x, train_idx)
    else:
        xt = chunked.materialize(x) if stream else x
    tile = _choose_tile(n_train, params.n_lists, 1, res.workspace_bytes)
    with tracing.range("ivf_pq.build.residuals"):
        labels = assign_to_lists(xt, centers, mt, tile)
        resid = (xt.astype(jnp.float32) - jnp.take(centers, labels, axis=0)) @ rotation.T
        resid = resid.reshape(n_train, pq_dim, pq_len)
    list_scales = jnp.zeros((0,), jnp.float32)
    if params.residual_scale_norm:
        # per-list scale normalization (see IndexParams docstring): train
        # the codebooks — and the auto per-cluster trial below — on
        # unit-scale residuals; encode/search re-apply s_list exactly
        with tracing.range("ivf_pq.build.residual_scales"):
            list_scales = _per_list_residual_scales(resid, labels,
                                                    params.n_lists)
        resid = resid / jnp.take(list_scales, labels)[:, None, None]

    # 3b. learned rotation (funnel stage a): alternate codebook-fit /
    # Procrustes on rotating mini-batches of the (unit-scale) residual
    # trainset, then FOLD the learned R into the index rotation — the
    # query transform is still one matmul, and the per-list scales stay
    # valid (orthogonal R preserves residual norms)
    if params.rotation == "opq":
        import time as _time

        key, ko = jax.random.split(key)
        split_pref_ = (params.pq8_split if params.pq8_split is not None
                       else mt != DistanceType.InnerProduct)
        opq_codes = 16 if (params.pq_bits == 8 and split_pref_) else n_codes
        batch = min(int(params.opq_batch_rows), n_train)
        t0 = _time.perf_counter()
        with tracing.range("ivf_pq.build.opq"):
            r_opq = _train_opq_rotation(
                resid.reshape(n_train, d_rot), ko, pq_dim, opq_codes,
                min(params.kmeans_n_iters, 10), int(params.opq_rounds),
                batch)
            r_opq = jax.block_until_ready(r_opq)
        if metrics.enabled():
            _quant_opq_seconds().observe(_time.perf_counter() - t0)
        rotation = r_opq @ rotation
        centers_rot = centers @ rotation.T
        resid = (resid.reshape(n_train, d_rot) @ r_opq.T
                 ).reshape(n_train, pq_dim, pq_len)

    # 4. codebooks (ref train_per_subset :343 / train_per_cluster :424)
    key, kc = jax.random.split(key)
    split_pref = (params.pq8_split if params.pq8_split is not None
                  else mt != DistanceType.InnerProduct)
    split = params.pq_bits == 8 and split_pref
    aniso_eta = 0.0
    if params.codebook_loss == "anisotropic":
        expects(not split, "codebook_loss='anisotropic' needs a joint "
                "codebook — nibble-split pq8 trains a two-stage residual "
                "quantizer (set pq8_split=False or pq_bits < 8)")
        aniso_eta = float(params.anisotropic_eta
                          or _default_aniso_eta(d_rot))

    def train(pools):
        if split:
            return _train_split_codebooks(pools, kc, params.kmeans_n_iters)
        if aniso_eta > 0.0:
            return _train_codebooks_aniso(pools, kc, n_codes,
                                          params.kmeans_n_iters, aniso_eta)
        return _train_codebooks_batched(pools, kc, n_codes, params.kmeans_n_iters)

    kind = params.codebook_kind
    if kind != "per_cluster":
        # (pq_dim, n_train, pq_len) — every subspace trains on all residuals
        sub = jnp.moveaxis(resid, 1, 0)
        with tracing.range("ivf_pq.build.train_codebooks"):
            codebooks = train(sub)
        # codebook-kind heuristic: for "auto" ONLY, trial-train per-cluster
        # codebooks on the largest clusters and adopt them when they quantize
        # markedly better (the caller opted into the trial + possible ~3x
        # build cost by choosing auto). Plain per_subspace builds — including
        # internal ones like CAGRA's knn-graph IVF-PQ, which expose no
        # codebook knob — pay nothing.
        if kind == "auto":
            if params.n_lists >= 16 and n_train >= 4 * params.n_lists:
                key, kt = jax.random.split(key)
                ratio = _per_cluster_gain(resid, labels, codebooks, split, kt,
                                          min(params.kmeans_n_iters, 10))
                if ratio < 0.9:
                    logger.info(
                        "ivf_pq auto codebooks: per-cluster trial error is "
                        "%.2fx per-subspace — training per-cluster codebooks "
                        "(reference PER_CLUSTER mode, ivf_pq_build.cuh:424)",
                        ratio)
                    kind = "per_cluster"
                else:
                    logger.info(
                        "ivf_pq auto codebooks: per-cluster trial gains "
                        "little (%.2fx) — keeping per-subspace codebooks",
                        ratio)
            if kind == "auto":
                kind = "per_subspace"
    if kind == "per_cluster":
        # per-cluster: pool subspace-vectors of each cluster's members.
        # Pad each cluster's pool to a fixed size for batching.
        pool_cap = round_up(max(int(jnp.max(jnp.bincount(labels, length=params.n_lists))), n_codes), 8)
        order = jnp.argsort(labels, stable=True)
        counts = jnp.bincount(labels, length=params.n_lists)
        starts = jnp.cumsum(counts) - counts
        # gather rows per cluster with wraparound padding (repeat members)
        offs = jnp.arange(pool_cap)[None, :] % jnp.maximum(counts, 1)[:, None]
        rows = jnp.take(order, starts[:, None] + offs)  # (n_lists, pool_cap)
        pools = jnp.take(resid.reshape(n_train, d_rot), rows, axis=0)  # (L, pool_cap, d_rot)
        pools = pools.reshape(params.n_lists, pool_cap * pq_dim, pq_len)
        with tracing.range("ivf_pq.build.train_codebooks"):
            codebooks = train(pools)

    # 5. fast-scan tier decode scales (funnel stage c): fit per-list from
    # the RAW rotated residuals (signatures are scale-norm-independent —
    # restoring s_list here keeps the estimator exact either way)
    sig_scales = jnp.zeros((0,), jnp.float32)
    sig_w = _sig_words(d_rot, params.fast_scan)
    if params.fast_scan != "none":
        raw = resid.reshape(n_train, d_rot)
        if params.residual_scale_norm:
            raw = raw * jnp.take(list_scales, labels)[:, None]
        with tracing.range("ivf_pq.build.sig_scales"):
            sig_scales = _per_list_sig_scales(raw, labels, params.n_lists,
                                              params.fast_scan)

    index = IvfPqIndex(
        centers=centers,
        centers_rot=centers_rot,
        rotation=rotation,
        codebooks=codebooks,
        list_codes=jnp.zeros((params.n_lists, 0, pq_dim), jnp.uint8),
        list_ids=jnp.zeros((params.n_lists, 0), jnp.int32),
        list_sizes=jnp.zeros((params.n_lists,), jnp.int32),
        list_scales=list_scales,
        list_sig=jnp.zeros((params.n_lists, 0, sig_w), jnp.uint8),
        sig_scales=sig_scales,
        metric=mt,
        codebook_kind=kind,
        pq_bits=params.pq_bits,
        split_factor=params.split_factor,
        pq_split=split,
        data_kind=data_kind,
        rotation_kind=params.rotation,
        codebook_loss=params.codebook_loss,
        fast_scan=params.fast_scan,
    )
    if not params.add_data_on_build:
        obs_mem.account_index(index)
        return index
    if stream:
        return _extend_stream_f32(index, dataset, None, res=res)
    # x is already the f32 working view (byte data was shifted+upcast above)
    return _extend_f32(index, x, jnp.arange(n, dtype=jnp.int32), res=res)


def resolve_scan_impl(params: SearchParams, index: IvfPqIndex, n_codes: int) -> str:
    """Validate + resolve ``params.scan_impl`` (shared by the single-chip and
    distributed searches, so both fail with the same clear errors instead of
    opaque trace-time ones)."""
    expects(params.scan_impl in ("auto", "onehot", "select", "pallas"),
            "scan_impl must be 'auto', 'onehot', 'select' or 'pallas', got %r",
            params.scan_impl)
    scan_impl = params.scan_impl
    narrow_stages = index.pq_split or n_codes <= 16
    if scan_impl == "auto":
        # the one-hot MXU contraction everywhere: the r04 kernel study
        # (BASELINE.md "Round-4 PQ scan study") measured every alternative
        # slower at 1M — XLA "select" chain 0.55x, Pallas compare+select
        # 0.7x, Pallas tpu.dynamic_gather (the hardware LUT16) 0.73x — the
        # one-hot path fuses gather+compare+cast into the contraction and
        # saturates HBM, which nothing code-streaming beat in-search
        scan_impl = "onehot"
    expects(scan_impl == "onehot" or narrow_stages,
            "scan_impl=%r needs 16-wide LUT stages (pq_bits=4 or "
            "nibble-split pq8); this index has %d-entry codebooks",
            scan_impl, n_codes)
    expects(scan_impl == "onehot" or params.lut_dtype != "int8",
            "lut_dtype='int8' is a one-hot-contraction optimization; use "
            "scan_impl='onehot' (or lut_dtype float32/bfloat16) instead")
    if scan_impl == "pallas":
        from ..ops.pq_scan import pq_scan_backend_ok

        ok, _ = pq_scan_backend_ok()
        expects(ok, "scan_impl='pallas' needs a TPU backend (or "
                "RAFT_TPU_PQ_SCAN_INTERPRET=1 to opt into interpret mode "
                "for tests)")
    return scan_impl


def _check_split_consts(index: IvfPqIndex) -> None:
    """A pq_split L2 index must carry per-vector cross-term constants; a
    hand-constructed index without them would otherwise fail deep inside the
    jitted scan with an opaque broadcast error (ADVICE r3)."""
    if (index.pq_split and index.metric != DistanceType.InnerProduct
            and index.capacity > 0):
        expects(index.list_consts.shape == index.list_ids.shape,
                "pq_split L2 index needs list_consts of shape %s (per-vector "
                "cross terms), got %s — build via build()/extend(), which "
                "populate them", index.list_ids.shape, index.list_consts.shape)


@instrument("ivf_pq.extend",
            items=lambda a, kw: nrows(a[1] if len(a) > 1 else kw["new_vectors"]))
def extend(index: IvfPqIndex, new_vectors, new_ids=None, res: Resources | None = None,
           split_factor: float | None = None) -> IvfPqIndex:
    """Encode + append vectors (reference: ivf_pq::extend; encode path
    process_and_fill_codes, detail/ivf_pq_build.cuh). Byte indexes
    (data_kind int8/uint8) take vectors in the index's ORIGINAL dtype —
    a plain astype would wrap uint8 values mod 256 instead of shifting.

    A :class:`~raft_tpu.core.chunked.ChunkedReader` batch (or any host
    ndarray past the streaming threshold) takes the out-of-core path:
    per-chunk assign + encode + scatter, never materializing the batch on
    device."""
    from .ivf_flat import _STREAM_EXTEND_BYTES

    if (not chunked.is_reader(new_vectors)
            and isinstance(new_vectors, np.ndarray)
            and new_vectors.ndim == 2
            and new_vectors.nbytes > _STREAM_EXTEND_BYTES):
        new_vectors = chunked.ChunkedReader(new_vectors)
    if chunked.is_reader(new_vectors):
        return _extend_stream_f32(index, new_vectors, new_ids, res=res,
                                  split_factor=split_factor)
    x = jnp.asarray(new_vectors)
    if index.data_kind in ("int8", "uint8"):
        expects(str(x.dtype) == index.data_kind,
                "this index stores %s vectors; got %s", index.data_kind,
                x.dtype)
        from .brute_force import _as_signed

        x = _as_signed(x).astype(jnp.float32)
    return _extend_f32(index, x, new_ids, res=res, split_factor=split_factor)


def _extend_f32(index: IvfPqIndex, new_vectors, new_ids=None,
                res: Resources | None = None,
                split_factor: float | None = None) -> IvfPqIndex:
    """extend() after domain conversion: vectors already live in the index's
    f32 working domain (s8-shifted for uint8 kinds)."""
    res = res or default_resources()
    _check_split_consts(index)
    x = jnp.asarray(new_vectors)
    expects(x.ndim == 2 and x.shape[1] == index.dim, "vector dim mismatch")
    n_new = x.shape[0]
    if new_ids is None:
        new_ids = index.size + jnp.arange(n_new, dtype=jnp.int32)
    else:
        new_ids = jnp.asarray(new_ids, jnp.int32)

    tile = _choose_tile(n_new, index.n_lists, 1, res.workspace_bytes)
    with tracing.range("ivf_pq.extend.assign"):
        labels = assign_to_lists(x, index.centers, index.metric, tile)
    resid = (x.astype(jnp.float32) - jnp.take(index.centers, labels, axis=0)) @ index.rotation.T
    sig = None
    if index.has_fast_scan:
        # signatures pack the RAW rotated residual (scale-norm independent)
        with tracing.range("ivf_pq.extend.encode_sig"):
            sig = _encode_sig(resid, jnp.take(index.sig_scales, labels),
                              index.fast_scan)
    resid = resid.reshape(n_new, index.pq_dim, index.pq_len)
    if index.scale_normed:
        # codes encode UNIT-scale residuals; search re-applies s_list in the
        # LUT (IndexParams.residual_scale_norm)
        resid = resid / jnp.take(index.list_scales, labels)[:, None, None]
    per_cluster = index.codebook_kind == "per_cluster"
    # split indexes encode against the effective composed 256-entry codebook
    # (joint argmin over the Minkowski sum — optimal for this codebook, and
    # the flat composed index IS hi*16+lo)
    enc_cb = _composed_codebooks(index.codebooks) if index.pq_split else index.codebooks
    n_codes = enc_cb.shape[-2]
    enc_tile = max(min(n_new, res.workspace_bytes // max(index.pq_dim * n_codes * 4, 1)), 8)
    with tracing.range("ivf_pq.extend.encode"):
        codes = _encode(
            resid, enc_cb, labels,
            per_cluster=per_cluster,
            tile=min(enc_tile, 8192),
            aniso_eta=(_default_aniso_eta(index.rot_dim)
                       if index.codebook_loss == "anisotropic" else 0.0),
        )
    consts = None
    if index.pq_split and index.metric != DistanceType.InnerProduct:
        # L2 scoring needs the per-vector cross term; IP scoring is exactly
        # separable, so split IP indexes keep the empty (n_lists, 0) buffer
        # (no dead capacity-sized zeros stored/serialized/sharded)
        consts = _pq_cross_consts(codes, index.codebooks, labels, per_cluster)
        if index.scale_normed:
            # the stored cross term enters scoring raw, so the s^2 of
            # ||r - s*(cb1+cb2)||^2 folds in HERE, at encode time
            consts = consts * jnp.take(index.list_scales, labels) ** 2

    if index.capacity > 0 and index.size > 0:
        old_mask = index.list_ids.reshape(-1) >= 0
        old_codes = index.list_codes.reshape(-1, index.pq_dim)[old_mask]
        old_ids = index.list_ids.reshape(-1)[old_mask]
        old_labels = jnp.repeat(jnp.arange(index.n_lists), index.capacity)[old_mask]
        codes = jnp.concatenate([old_codes, codes])
        new_ids = jnp.concatenate([old_ids, new_ids])
        labels = jnp.concatenate([old_labels.astype(jnp.int32), labels])
        if consts is not None:
            old_consts = index.list_consts.reshape(-1)[old_mask]
            consts = jnp.concatenate([old_consts, consts])
        if sig is not None:
            old_sig = index.list_sig.reshape(-1, index.list_sig.shape[2])[old_mask]
            sig = jnp.concatenate([old_sig, sig])

    import numpy as np

    # shared capacity policy: oversized lists split into sub-lists sharing
    # their parent's center (+rotated center, +per-cluster codebook).
    # Residuals/codes were computed against the parent center, which
    # sub-lists share, so codes stay valid.
    sf = index.split_factor if split_factor is None else split_factor
    labels, rep, n_lists, capacity, _ = bound_capacity(labels, index.n_lists, sf)
    centers, centers_rot, codebooks = index.centers, index.centers_rot, index.codebooks
    list_scales, sig_scales = index.list_scales, index.sig_scales
    if rep is not None:
        centers = jnp.asarray(np.repeat(np.asarray(centers), rep, axis=0))
        centers_rot = jnp.asarray(np.repeat(np.asarray(centers_rot), rep, axis=0))
        if index.codebook_kind == "per_cluster":
            codebooks = jnp.asarray(np.repeat(np.asarray(codebooks), rep, axis=0))
        if index.scale_normed:
            # sub-lists share their parent's center AND its residual scale
            # (codes were encoded against both)
            list_scales = jnp.asarray(
                np.repeat(np.asarray(list_scales), rep, axis=0))
        if index.has_fast_scan:
            # ... and its signature decode scale, for the same reason
            sig_scales = jnp.asarray(
                np.repeat(np.asarray(sig_scales), rep, axis=0))
    with tracing.range("ivf_pq.extend.fill_lists"):
        buf, idbuf, sizes, cbuf, sbuf = _fill_code_lists(
            codes, new_ids, labels, n_lists, capacity, consts, sig)
    out = dataclasses.replace(
        index, centers=centers, centers_rot=centers_rot, codebooks=codebooks,
        list_codes=buf, list_ids=idbuf, list_sizes=sizes, list_consts=cbuf,
        list_scales=list_scales, list_sig=sbuf, sig_scales=sig_scales,
        split_factor=sf,
    )
    # ledger hook (docs/observability.md): the re-packed lists are the
    # long-lived allocation; a superseded index's entry auto-releases
    # when its last reference drops
    obs_mem.account_index(out)
    if metrics.enabled():
        g = _quant_bytes_per_row()
        g.set(index.pq_dim + 4, tier="pq")
        if index.has_fast_scan:
            g.set(index.list_sig.shape[2] + 4, tier="sig")
    return out


@functools.partial(jax.jit, static_argnames=("n_lists",),
                   donate_argnums=(0, 1, 2, 3, 4))
def _fill_pq_chunk(buf, idbuf, cbuf, sbuf, offsets, codes, ids, labels,
                   consts, sig, n_lists: int):
    """One streamed scatter pass over the PQ list layout — the ivf_pq twin
    of ``ivf_flat._fill_chunk`` (same running-offset position math, same
    sentinel-label OOB drop for pad rows, same in-place donation; see that
    docstring). ``consts``/``sig`` ride along when the index carries
    cross-term constants / fast-scan signatures so the four layouts can
    never disagree on slot positions."""
    pos_local, counts = list_positions(labels, n_lists + 1)
    offs = jnp.concatenate([offsets, jnp.zeros((1,), jnp.int32)])
    pos = pos_local + jnp.take(offs, labels)
    buf = buf.at[labels, pos].set(codes, mode="drop")
    idbuf = idbuf.at[labels, pos].set(ids.astype(jnp.int32), mode="drop")
    if consts is not None:
        cbuf = cbuf.at[labels, pos].set(consts, mode="drop")
    if sig is not None:
        sbuf = sbuf.at[labels, pos].set(sig, mode="drop")
    return buf, idbuf, cbuf, sbuf, offsets + counts[:n_lists]


def _extend_stream_f32(index: IvfPqIndex, reader, new_ids=None,
                       res: Resources | None = None,
                       split_factor: float | None = None) -> IvfPqIndex:
    """The streamed twin of :func:`_extend_f32`: two passes over the
    reader's chunks — assign, then residual/encode/scatter — instead of
    one whole-corpus device array. Bit-equal to the in-core path: every
    per-row quantity (ingest conversion, label, residual, signature, code,
    cross-term constant) comes from the SAME helpers, none couples rows
    across a batch, and the post-split gathers against ``np.repeat``ed
    per-list arrays return exactly the parent values the in-core path
    reads pre-split (split children share their parent's center, scale and
    codebook). Device peak: index accumulators + two staged chunks + the
    label/id vectors — CONSTANT in corpus rows beyond the index itself."""
    from ..obs import build as build_metrics

    res = res or default_resources()
    _check_split_consts(index)
    n_new, d = (int(s) for s in reader.shape)
    expects(d == index.dim, "vector dim mismatch")
    if index.data_kind in ("int8", "uint8"):
        expects(str(reader.dtype) == index.data_kind,
                "this index stores %s vectors; got %s", index.data_kind,
                reader.dtype)
    ingest = _stream_ingest(index.data_kind)
    if new_ids is None:
        new_ids = index.size + jnp.arange(n_new, dtype=jnp.int32)
    else:
        new_ids = jnp.asarray(new_ids, jnp.int32)
        expects(int(new_ids.shape[0]) == n_new, "ids/vectors length mismatch")

    pq_dim, pq_len = index.pq_dim, index.pq_len
    cr = int(reader.chunk_rows)
    emit = metrics.enabled()
    stager = chunked.ChunkStager(cr, d, reader.dtype, kind="ivf_pq")
    try:
        # ---- pass A: per-chunk nearest-center assignment (labels stay
        # device-resident; no per-chunk host syncs)
        tile = _choose_tile(cr, index.n_lists, 1, res.workspace_bytes)
        parts = []
        with tracing.range("ivf_pq.extend.assign_stream"):
            for start, block in reader.chunks():
                xs = ingest(stager.stage(block))
                parts.append(assign_to_lists(xs, index.centers,
                                             index.metric, tile))
                if emit:
                    build_metrics.ooc_chunks().inc(1, kind="ivf_pq",
                                                   stage="assign")
        labels = jnp.concatenate(parts)[:n_new]  # drop pad-row garbage
        del parts

        # merge with existing list contents (old rows FIRST — stable
        # ranks, and therefore the final layout, match the in-core twin)
        n_old = 0
        old_codes = old_ids = old_consts = old_sig = None
        want_consts = (index.pq_split
                       and index.metric != DistanceType.InnerProduct)
        if index.capacity > 0 and index.size > 0:
            old_mask = index.list_ids.reshape(-1) >= 0
            old_codes = index.list_codes.reshape(-1, pq_dim)[old_mask]
            old_ids = index.list_ids.reshape(-1)[old_mask]
            old_labels = jnp.repeat(jnp.arange(index.n_lists),
                                    index.capacity)[old_mask]
            n_old = int(old_codes.shape[0])
            labels = jnp.concatenate([old_labels.astype(jnp.int32), labels])
            if want_consts:
                old_consts = index.list_consts.reshape(-1)[old_mask]
            if index.has_fast_scan:
                old_sig = index.list_sig.reshape(
                    -1, index.list_sig.shape[2])[old_mask]

        # capacity policy over the FULL label vector — identical to the
        # in-core call (ivf_pq never spatial-splits: sub-lists must share
        # their parent's center for the codes to stay valid)
        sf = index.split_factor if split_factor is None else split_factor
        labels, rep, n_lists2, capacity, _ = bound_capacity(
            labels, index.n_lists, sf)
        centers, centers_rot = index.centers, index.centers_rot
        codebooks = index.codebooks
        list_scales, sig_scales = index.list_scales, index.sig_scales
        if rep is not None:
            centers = jnp.asarray(np.repeat(np.asarray(centers), rep,
                                            axis=0))
            centers_rot = jnp.asarray(np.repeat(np.asarray(centers_rot),
                                                rep, axis=0))
            if index.codebook_kind == "per_cluster":
                codebooks = jnp.asarray(np.repeat(np.asarray(codebooks),
                                                  rep, axis=0))
            if index.scale_normed:
                list_scales = jnp.asarray(
                    np.repeat(np.asarray(list_scales), rep, axis=0))
            if index.has_fast_scan:
                sig_scales = jnp.asarray(
                    np.repeat(np.asarray(sig_scales), rep, axis=0))

        # ---- pass B: per-chunk residual -> encode -> scatter ----------
        # Gathers run against the REPEATED arrays with POST-split labels:
        # bitwise the parent values the in-core path uses pre-split (and
        # repeat/compose commute for the split-codebook expansion).
        per_cluster = index.codebook_kind == "per_cluster"
        enc_cb = (_composed_codebooks(codebooks) if index.pq_split
                  else codebooks)
        n_codes = enc_cb.shape[-2]
        enc_tile = max(min(cr, res.workspace_bytes
                           // max(pq_dim * n_codes * 4, 1)), 8)
        aniso_eta = (_default_aniso_eta(index.rot_dim)
                     if index.codebook_loss == "anisotropic" else 0.0)
        sig_w = index.list_sig.shape[2] if index.has_fast_scan else 0
        buf = jnp.zeros((n_lists2, capacity, pq_dim), jnp.uint8)
        idbuf = jnp.full((n_lists2, capacity), -1, jnp.int32)
        cbuf = (jnp.zeros((n_lists2, capacity), jnp.float32) if want_consts
                else jnp.zeros((n_lists2, 0), jnp.float32))
        sbuf = (jnp.zeros((n_lists2, capacity, sig_w), jnp.uint8)
                if index.has_fast_scan
                else jnp.zeros((n_lists2, 0, 0), jnp.uint8))
        offsets = jnp.zeros((n_lists2,), jnp.int32)
        # transient ledger entry — the streamed build's device working set
        # (released before the sealed index is accounted)
        ooc_tok = obs_mem.account(
            "build/ooc", name="ivf_pq",
            device_bytes=int(buf.nbytes + idbuf.nbytes + cbuf.nbytes
                             + sbuf.nbytes + offsets.nbytes + labels.nbytes
                             + new_ids.nbytes),
            owner=stager)
        with tracing.range("ivf_pq.extend.fill_stream"):
            if n_old > 0:
                buf, idbuf, cbuf, sbuf, offsets = _fill_pq_chunk(
                    buf, idbuf, cbuf, sbuf, offsets, old_codes, old_ids,
                    labels[:n_old],
                    old_consts if want_consts else None,
                    old_sig if index.has_fast_scan else None,
                    n_lists=n_lists2)
                labels = labels[n_old:]
            pad = -(-n_new // cr) * cr - n_new
            lab_p = (jnp.concatenate(
                [labels, jnp.full((pad,), n_lists2, jnp.int32)])
                if pad else labels)
            ids_p = (jnp.concatenate(
                [new_ids, jnp.full((pad,), -1, jnp.int32)])
                if pad else new_ids)
            for start, block in reader.chunks():
                xs = ingest(stager.stage(block))
                st = jnp.int32(start)  # operand, not executable key
                lab_c = lax.dynamic_slice_in_dim(lab_p, st, cr)
                ids_c = lax.dynamic_slice_in_dim(ids_p, st, cr)
                resid = (xs.astype(jnp.float32)
                         - jnp.take(centers, lab_c, axis=0)
                         ) @ index.rotation.T
                sig_c = None
                if index.has_fast_scan:
                    sig_c = _encode_sig(resid,
                                        jnp.take(sig_scales, lab_c),
                                        index.fast_scan)
                resid = resid.reshape(cr, pq_dim, pq_len)
                if index.scale_normed:
                    resid = resid / jnp.take(list_scales,
                                             lab_c)[:, None, None]
                codes_c = _encode(resid, enc_cb, lab_c,
                                  per_cluster=per_cluster,
                                  tile=min(enc_tile, 8192),
                                  aniso_eta=aniso_eta)
                consts_c = None
                if want_consts:
                    consts_c = _pq_cross_consts(codes_c, codebooks, lab_c,
                                                per_cluster)
                    if index.scale_normed:
                        consts_c = (consts_c
                                    * jnp.take(list_scales, lab_c) ** 2)
                buf, idbuf, cbuf, sbuf, offsets = _fill_pq_chunk(
                    buf, idbuf, cbuf, sbuf, offsets, codes_c, ids_c,
                    lab_c, consts_c, sig_c, n_lists=n_lists2)
                if emit:
                    build_metrics.ooc_chunks().inc(1, kind="ivf_pq",
                                                   stage="fill")
        sizes = offsets
        obs_mem.release(ooc_tok)
    finally:
        stager.release()
    out = dataclasses.replace(
        index, centers=centers, centers_rot=centers_rot, codebooks=codebooks,
        list_codes=buf, list_ids=idbuf, list_sizes=sizes, list_consts=cbuf,
        list_scales=list_scales, list_sig=sbuf, sig_scales=sig_scales,
        split_factor=sf,
    )
    obs_mem.account_index(out)
    if emit:
        g = _quant_bytes_per_row()
        g.set(pq_dim + 4, tier="pq")
        if index.has_fast_scan:
            g.set(index.list_sig.shape[2] + 4, tier="sig")
    return out


@functools.partial(
    jax.jit,
    static_argnames=("n_probes", "k", "query_tile", "probe_chunk", "metric",
                     "codebook_kind", "lut_dtype", "scan_impl", "select_impl"),
)
def _pq_search(index: IvfPqIndex, queries, n_probes: int, k: int, query_tile: int,
               probe_chunk: int, metric: DistanceType, codebook_kind: str, lut_dtype: str,
               keep_mask=None, scan_impl: str = "onehot",
               select_impl: str = "auto"):
    m, d = queries.shape
    qf = queries.astype(jnp.float32)
    inner = metric == DistanceType.InnerProduct
    pq_dim, pq_len = index.pq_dim, index.pq_len
    n_codes = index.codebooks.shape[-2]

    # ---- stage 1: coarse clusters (ref select_clusters :68) ----
    with tracing.range("ivf_pq.search.coarse"):
        cscore = qf @ index.centers.T
        if not inner:
            cn = jnp.sum(index.centers * index.centers, axis=1)
            cscore = cn[None, :] - 2.0 * cscore
        _, probes = _select_k(cscore, None, n_probes, not inner)  # (m, p)

    # rotated queries
    qrot = qf @ index.rotation.T  # (m, d_rot)

    num = -(-m // query_tile)
    pad = num * query_tile - m
    qp = jnp.pad(qrot, ((0, pad), (0, 0))) if pad else qrot
    pp = jnp.pad(probes, ((0, pad), (0, 0))) if pad else probes
    qt = qp.reshape(num, query_tile, index.rot_dim)
    pt = pp.reshape(num, query_tile, n_probes)

    n_chunks = n_probes // probe_chunk
    cap = index.capacity

    # codebook norms (for LUT via ‖c‖² - 2·r·c)
    cb = index.codebooks.astype(jnp.float32)
    cb_n2 = jnp.sum(cb * cb, axis=-1)  # (B?, n_codes) matching codebook layout

    def per_tile(args):
        q, pr = args  # (T, d_rot), (T, p)

        def per_chunk(c, _):
            pc = lax.dynamic_slice_in_dim(pr, c * probe_chunk, probe_chunk, axis=1)  # (T, pc)
            crot = index.centers_rot[pc]  # (T, pc, d_rot)

            # ---- LUT (ref ivfpq_search_worker :419 lut computation) ----
            # per-list residual scales (IndexParams.residual_scale_norm):
            # codes decode to s_list * codeword, so the fold is one multiply
            # on the per-probe LUT — s^2 for L2 (with the residual
            # pre-divided so dots see the unit-scale domain the codebooks
            # were trained in), s for IP. Bias terms stay in the RAW
            # residual domain (they carry ||r||^2 / q·c exactly).
            sc = (jnp.take(index.list_scales, pc, axis=0)
                  if index.scale_normed else None)       # (T, pc) | None
            if inner:
                # IP(q, v) = q·c + q_rot·decoded_residual: LUT over the rotated
                # query's subvectors; the q·c bias is added to scores below.
                qs = jnp.broadcast_to(
                    q[:, None, :], (query_tile, probe_chunk, index.rot_dim)
                ).reshape(query_tile, probe_chunk, pq_dim, pq_len)
                if codebook_kind == "per_subspace":
                    lut = jnp.einsum("tpsl,skl->tpsk", qs, cb, precision=lax.Precision.HIGHEST)
                else:
                    lut = jnp.einsum("tpsl,tpkl->tpsk", qs, cb[pc], precision=lax.Precision.HIGHEST)
                if sc is not None:
                    lut = lut * sc[:, :, None, None]
                bias = jnp.einsum("td,tpd->tp", q, crot, precision=lax.Precision.HIGHEST)
            else:
                # L2: ‖q - c - decoded‖² = Σ_s ‖r_s - codeword_s‖², r = q_rot - c_rot
                r = (q[:, None, :] - crot).reshape(query_tile, probe_chunk, pq_dim, pq_len)
                # Σ_s ‖r_s‖² per probe: constant within a list, needed so
                # scores are comparable across probed lists
                bias = jnp.sum(r * r, axis=(2, 3))  # (T, pc)
                if sc is not None:
                    r = r / sc[:, :, None, None]
                if codebook_kind == "per_subspace":
                    # cb: (pq_dim, n_codes, pq_len)
                    dots = jnp.einsum("tpsl,skl->tpsk", r, cb, precision=lax.Precision.HIGHEST)
                    lut = cb_n2[None, None] - 2.0 * dots  # (T, pc, pq_dim, n_codes)
                else:
                    cbl = cb[pc]  # (T, pc, n_codes, pq_len)
                    dots = jnp.einsum("tpsl,tpkl->tpsk", r, cbl, precision=lax.Precision.HIGHEST)
                    lut = cb_n2[pc][:, :, None, :] - 2.0 * dots
                if sc is not None:
                    lut = lut * (sc * sc)[:, :, None, None]

            # ---- scan: score = Σ_s LUT[s, code_s] (ref compute_similarity) ----
            # One-hot MXU formulation: Σ_s LUT[s, c_s] = onehot(codes)·LUTflat.
            # An elementwise take_along_axis gather is ~4x slower on TPU
            # (measured 1.95s vs 0.52s per 1M-scale chunk) — single-element
            # HBM gathers don't vectorize; the MXU one-hot contraction is the
            # TPU analogue of ScaNN's SIMD LUT16 shuffle, and pq_bits=4
            # shrinks the contracted axis 16x for exactly that reason.
            codes = index.list_codes[pc]  # (T, pc, cap, pq_dim) gather
            ids = index.list_ids[pc]  # (T, pc, cap)
            if scan_impl == "pallas":
                # fused Pallas sweep (ops/pq_scan.py): LUT resident in VMEM,
                # codes streamed as int8 planes, no one-hot operand at all
                from ..ops.pq_scan import pq_lut_scan, pq_scan_backend_ok

                _, interp = pq_scan_backend_ok()
                ct = jnp.bfloat16 if lut_dtype == "bfloat16" else jnp.float32
                lut_t = jnp.swapaxes(lut, 2, 3).reshape(
                    query_tile * probe_chunk, n_codes, pq_dim).astype(ct)
                cflat = codes.reshape(query_tile * probe_chunk, cap, pq_dim)
                if index.pq_split:
                    scores = pq_lut_scan(
                        (cflat >> 4).astype(jnp.int8), lut_t,
                        codes_lo=(cflat & 0xF).astype(jnp.int8),
                        interpret=interp)
                else:
                    scores = pq_lut_scan(cflat.astype(jnp.int8), lut_t,
                                         interpret=interp)
                scores = scores.reshape(query_tile, probe_chunk, cap)
            elif scan_impl == "select":
                # compare+select gather (see _select_scores): bf16 rounds the
                # LUT like the one-hot bf16 mode; accumulation stays f32
                ct = jnp.bfloat16 if lut_dtype == "bfloat16" else jnp.float32
                scores = _select_scores(codes, lut.astype(ct), index.pq_split)
            elif index.pq_split:
                # nibble-split one-hot: stage-1 hit in lanes [0,16), stage-2
                # in [16,32) — one contraction against the 32-entry LUT sums
                # LUT1[hi] + LUT2[lo]; the missing cross term rides in
                # list_consts (added below). Axis pq_dim*32 vs the joint
                # pq_dim*256: 8x less MXU work for the same 8 code bits.
                ar16 = jnp.arange(16, dtype=codes.dtype)
                oh = jnp.concatenate(
                    [(codes >> 4)[..., None] == ar16,
                     (codes & 0xF)[..., None] == ar16],
                    axis=-1)  # (T, pc, cap, pq_dim, 32)
            else:
                oh = (
                    codes[..., None] == jnp.arange(n_codes, dtype=codes.dtype)
                )  # (T, pc, cap, pq_dim, n_codes)
            if scan_impl == "onehot":
                # the contraction dtype follows lut_dtype (0/1 one-hot entries
                # are exact in any of them):
                #   float32  — exact LUT values
                #   bfloat16 — LUT rounded to ~2^-8 relative, half the bytes
                #   int8     — LUT quantized per (query, probe) with a
                #              symmetric scale (the reference's fp8 smem LUT
                #              analogue, detail/fp_8bit.cuh); int32
                #              accumulation on the int8 MXU path, quarter the
                #              operand bytes
                ohf = oh.reshape(query_tile, probe_chunk, cap, pq_dim * n_codes)
                lutf = lut.reshape(query_tile, probe_chunk, pq_dim * n_codes)
                if lut_dtype not in ("float32", "bfloat16", "int8"):
                    raise ValueError(f"unknown lut_dtype {lut_dtype!r}")
                if lut_dtype == "int8":
                    amax = jnp.max(jnp.abs(lutf), axis=2, keepdims=True)  # (T,pc,1)
                    scale = jnp.maximum(amax, 1e-30) / 127.0
                    lut_q = jnp.clip(jnp.round(lutf / scale), -127, 127).astype(jnp.int8)
                    acc = lax.dot_general(
                        ohf.astype(jnp.int8), lut_q,
                        (((3,), (2,)), ((0, 1), (0, 1))),
                        preferred_element_type=jnp.int32,
                    )  # (T, pc, cap) int32
                    scores = acc.astype(jnp.float32) * scale
                else:
                    ct = jnp.bfloat16 if lut_dtype == "bfloat16" else jnp.float32
                    scores = lax.dot_general(
                        ohf.astype(ct), lutf.astype(ct),
                        (((3,), (2,)), ((0, 1), (0, 1))),
                        preferred_element_type=jnp.float32,
                    )  # (T, pc, cap)
            scores = scores + bias[:, :, None]
            if index.pq_split and not inner:
                scores = scores + index.list_consts[pc]  # (T, pc, cap)
            scores = jnp.where(ids >= 0, scores, -jnp.inf if inner else jnp.inf)
            if keep_mask is not None:
                from .sample_filter import apply_id_filter

                scores = apply_id_filter(scores, ids, keep_mask, not inner)
            flat_s = scores.reshape(query_tile, probe_chunk * cap)
            flat_i = ids.reshape(query_tile, probe_chunk * cap)
            # candidate selects route through the dispatching selector
            # (r06): at wide k this is the call site the Pallas wide-k
            # kernel was commissioned for — CAGRA's build chunk reaches
            # here with k = gpu_top_k + 1 (193 at defaults)
            return c + 1, select_k_impl(flat_s, flat_i, k, not inner,
                                        impl=select_impl)

        _, (cv, ci) = lax.scan(per_chunk, 0, None, length=n_chunks)
        cv = jnp.moveaxis(cv, 0, 1).reshape(query_tile, n_chunks * k)
        ci = jnp.moveaxis(ci, 0, 1).reshape(query_tile, n_chunks * k)
        return select_k_impl(cv, ci, k, not inner, impl=select_impl)

    with tracing.range("ivf_pq.search.scan"):
        dists, idx = lax.map(per_tile, (qt, pt))
    dists = dists.reshape(num * query_tile, k)[:m]
    idx = idx.reshape(num * query_tile, k)[:m]
    if not inner and metric in (DistanceType.L2SqrtExpanded, DistanceType.L2SqrtUnexpanded):
        dists = jnp.where(jnp.isfinite(dists), jnp.sqrt(jnp.maximum(dists, 0.0)), dists)
    if keep_mask is not None:
        # filtered-out candidates carry ±inf scores — report id -1
        idx = jnp.where(jnp.isinf(dists), -1, idx)
    return dists, idx


@functools.partial(
    jax.jit,
    static_argnames=("n_probes", "k", "k_widen", "query_tile", "probe_chunk",
                     "metric", "codebook_kind", "lut_dtype", "select_impl"),
)
def _pq_search_funnel(index: IvfPqIndex, queries, n_probes: int, k: int,
                      k_widen: int, query_tile: int, probe_chunk: int,
                      metric: DistanceType, codebook_kind: str, lut_dtype: str,
                      keep_mask=None, select_impl: str = "auto"):
    """Three-stage quantization funnel (docs/tuning.md "Quantization
    funnel"): binary widen → PQ rerank → the caller's exact refine.

    Stage A scores EVERY probed slot against the packed fast-scan tier
    (``list_sig``) with the same nibble-split one-hot contraction as the
    pq8_split scan — the 32-entry signature LUT makes the contracted axis
    ``sig_words * 32`` (for 1bit at d=128: half of classic pq4's), and the
    operand bytes are the packed signatures, not the PQ codes. Stage B
    re-scores only the per-chunk top ``k_widen`` survivors against the
    full PQ codes by direct decode (exact PQ scores; the split cross term
    rides in the decoded sum, no list_consts needed). Both selects and the
    chunk merge route through the one ``select_k`` dispatch with the
    shared ``-1/±inf`` sentinel, so no new merge shapes are minted, and
    candidates the estimator filtered (dead slots, sample-filter hits)
    keep their ±inf score through the rerank — they cannot resurrect.
    """
    m, d = queries.shape
    qf = queries.astype(jnp.float32)
    inner = metric == DistanceType.InnerProduct
    pq_dim, pq_len = index.pq_dim, index.pq_len
    d_rot = index.rot_dim
    sig_w = index.list_sig.shape[2]
    n_codes = index.codebooks.shape[-2]

    # ---- stage 1: coarse clusters (shared with the classic scan) ----
    with tracing.range("ivf_pq.search.coarse"):
        cscore = qf @ index.centers.T
        if not inner:
            cn = jnp.sum(index.centers * index.centers, axis=1)
            cscore = cn[None, :] - 2.0 * cscore
        _, probes = _select_k(cscore, None, n_probes, not inner)  # (m, p)

    qrot = qf @ index.rotation.T  # (m, d_rot)

    num = -(-m // query_tile)
    pad = num * query_tile - m
    qp = jnp.pad(qrot, ((0, pad), (0, 0))) if pad else qrot
    pp = jnp.pad(probes, ((0, pad), (0, 0))) if pad else probes
    qt = qp.reshape(num, query_tile, d_rot)
    pt = pp.reshape(num, query_tile, n_probes)

    n_chunks = n_probes // probe_chunk
    cap = index.capacity
    fast_scan = index.fast_scan
    cb = index.codebooks.astype(jnp.float32)
    # binary contraction dtype follows lut_dtype (int8 is rejected at the
    # dispatcher — the symmetric-scale quantization is a PQ-LUT-range
    # optimization and the estimator tier is already 1-4 bits)
    ct = jnp.bfloat16 if lut_dtype == "bfloat16" else jnp.float32

    def per_tile(args):
        q, pr = args  # (T, d_rot), (T, p)

        def per_chunk(c, _):
            pc = lax.dynamic_slice_in_dim(pr, c * probe_chunk, probe_chunk, axis=1)  # (T, pc)
            crot = index.centers_rot[pc]        # (T, pc, d_rot)
            ids = index.list_ids[pc]            # (T, pc, cap)
            ss = jnp.take(index.sig_scales, pc, axis=0)  # (T, pc)

            # ---- stage A: signature estimator over every probed slot.
            # The nibble LUT carries raw = <r, σ> (1bit) / <r, lev> (4bit)
            # in the RAW residual domain (sig_scales were fit there), so
            # residual_scale_norm never enters the estimator:
            #   L2  est = ‖r‖² + s²·d_rot − 2·s·raw   (‖σ‖² = d_rot for ±1;
            #             the 4bit level-norm uses the same s²·d_rot model —
            #             levels span ±2 per-dim RMS, so E‖lev‖² ≈ d_rot)
            #   IP  est = q·c + s·raw
            if inner:
                r = jnp.broadcast_to(q[:, None, :],
                                     (query_tile, probe_chunk, d_rot))
            else:
                r = q[:, None, :] - crot
            slut = _sig_nibble_lut(r, fast_scan, sig_w)  # (T, pc, W, 32)
            sig = index.list_sig[pc]                     # (T, pc, cap, W)
            ar16 = jnp.arange(16, dtype=sig.dtype)
            oh = jnp.concatenate(
                [(sig >> 4)[..., None] == ar16,
                 (sig & 0xF)[..., None] == ar16],
                axis=-1)  # (T, pc, cap, W, 32)
            ohf = oh.reshape(query_tile, probe_chunk, cap, sig_w * 32)
            lutf = slut.reshape(query_tile, probe_chunk, sig_w * 32)
            raw = lax.dot_general(
                ohf.astype(ct), lutf.astype(ct),
                (((3,), (2,)), ((0, 1), (0, 1))),
                preferred_element_type=jnp.float32)  # (T, pc, cap)
            if inner:
                bias = jnp.einsum("td,tpd->tp", q, crot,
                                  precision=lax.Precision.HIGHEST)
                est = bias[:, :, None] + ss[:, :, None] * raw
            else:
                bias = jnp.sum(r * r, axis=-1)  # (T, pc) = ‖r‖² per probe
                est = ((bias + ss * ss * d_rot)[:, :, None]
                       - 2.0 * ss[:, :, None] * raw)
            est = jnp.where(ids >= 0, est, -jnp.inf if inner else jnp.inf)
            if keep_mask is not None:
                from .sample_filter import apply_id_filter

                est = apply_id_filter(est, ids, keep_mask, not inner)

            # ---- widen: top-k_widen flat positions through select_k ----
            flat_est = est.reshape(query_tile, probe_chunk * cap)
            flat_pos = jnp.broadcast_to(
                jnp.arange(probe_chunk * cap, dtype=jnp.int32)[None, :],
                (query_tile, probe_chunk * cap))
            est_sel, pos_sel = select_k_impl(flat_est, flat_pos, k_widen,
                                             not inner, impl=select_impl)
            probe_sel = pos_sel // cap           # (T, kw) chunk-local probe
            slot_sel = pos_sel % cap
            list_sel = jnp.take_along_axis(pc, probe_sel, axis=1)  # (T, kw)

            # ---- stage B: PQ rerank of the survivors by direct decode ----
            codes_sel = index.list_codes[list_sel, slot_sel]  # (T, kw, pq_dim)
            ids_sel = index.list_ids[list_sel, slot_sel]      # (T, kw)
            if index.pq_split:
                # nibble one-hot over the 32-entry split codebook decodes
                # cb1[hi] + cb2[lo] in one contraction (cross term included)
                ohc = jnp.concatenate(
                    [(codes_sel >> 4)[..., None] == ar16,
                     (codes_sel & 0xF)[..., None] == ar16],
                    axis=-1)  # (T, kw, pq_dim, 32)
            else:
                ohc = (codes_sel[..., None]
                       == jnp.arange(n_codes, dtype=codes_sel.dtype))
            if codebook_kind == "per_cluster":
                dec = jnp.einsum("twsk,twkl->twsl", ohc.astype(jnp.float32),
                                 cb[list_sel],
                                 precision=lax.Precision.HIGHEST)
            else:
                dec = jnp.einsum("twsk,skl->twsl", ohc.astype(jnp.float32),
                                 cb, precision=lax.Precision.HIGHEST)
            dec = dec.reshape(query_tile, k_widen, d_rot)
            if index.scale_normed:
                # codes decode to s_list · codeword (residual_scale_norm)
                dec = dec * jnp.take(index.list_scales, list_sel)[..., None]
            crot_sel = index.centers_rot[list_sel]  # (T, kw, d_rot)
            if inner:
                score = jnp.einsum("td,twd->tw", q, crot_sel + dec,
                                   precision=lax.Precision.HIGHEST)
            else:
                rr = q[:, None, :] - crot_sel - dec
                score = jnp.sum(rr * rr, axis=-1)
            # estimator-filtered survivors keep their ±inf score (their
            # slots/ids may be real rows the sample filter dropped)
            score = jnp.where(jnp.isfinite(est_sel), score, est_sel)
            return c + 1, select_k_impl(score, ids_sel, k, not inner,
                                        impl=select_impl)

        _, (cv, ci) = lax.scan(per_chunk, 0, None, length=n_chunks)
        cv = jnp.moveaxis(cv, 0, 1).reshape(query_tile, n_chunks * k)
        ci = jnp.moveaxis(ci, 0, 1).reshape(query_tile, n_chunks * k)
        return select_k_impl(cv, ci, k, not inner, impl=select_impl)

    with tracing.range("ivf_pq.search.funnel"):
        dists, idx = lax.map(per_tile, (qt, pt))
    dists = dists.reshape(num * query_tile, k)[:m]
    idx = idx.reshape(num * query_tile, k)[:m]
    if not inner and metric in (DistanceType.L2SqrtExpanded, DistanceType.L2SqrtUnexpanded):
        dists = jnp.where(jnp.isfinite(dists), jnp.sqrt(jnp.maximum(dists, 0.0)), dists)
    if keep_mask is not None:
        # filtered-out candidates carry ±inf scores — report id -1
        idx = jnp.where(jnp.isinf(dists), -1, idx)
    return dists, idx


@functools.partial(
    jax.jit,
    static_argnames=("n_probes", "k", "metric", "codebook_kind", "lut_dtype",
                     "group_size", "group_chunk", "select_impl"),
)
def _pq_search_grouped(index: IvfPqIndex, queries, n_probes: int, k: int,
                       metric: DistanceType, codebook_kind: str,
                       lut_dtype: str, keep_mask=None, group_size: int = 16,
                       group_chunk: int = 32, select_impl: str = "auto"):
    """Probe-major grouped scan (r04, BASELINE.md "Round-4 PQ scan study"):
    the per-(query, probe) one-hot contraction is an N=1 batched matvec that
    rebuilds a (cap, pq_dim*K) one-hot operand per pair. Here the (query,
    probe) pairs of the WHOLE batch are sorted by list id and split into
    groups of ``group_size`` pairs sharing one list, so each group scores
    ONE one-hot operand against all its queries' LUTs in a single real-N
    MXU matmul — operand traffic amortizes G ways. The reference reaches
    the same amortization through smem residency (its kernel pins the LUT
    per (query, probe) CTA, ivf_pq_compute_similarity-inl.cuh); a TPU has
    no smem, so sharing swaps sides: codes are shared, LUTs batch.

    Static shapes: padded slots P = m*p + (G-1)*n_lists upper-bounds the
    per-list round-up; empty groups scan list 0 masked. All reordering is
    sort/gather-based (no scatters — XLA serializes those on TPU).
    """
    m, d = queries.shape
    qf = queries.astype(jnp.float32)
    inner = metric == DistanceType.InnerProduct
    pq_dim, pq_len = index.pq_dim, index.pq_len
    n_codes = index.codebooks.shape[-2]
    L = index.list_codes.shape[0]
    cap = index.capacity
    G, Gc = group_size, group_chunk

    # ---- stage 1: coarse clusters + rotated queries (as the tiled path) ----
    cscore = qf @ index.centers.T
    if not inner:
        cn = jnp.sum(index.centers * index.centers, axis=1)
        cscore = cn[None, :] - 2.0 * cscore
    _, probes = _select_k(cscore, None, n_probes, not inner)  # (m, p)
    qrot = qf @ index.rotation.T

    # ---- pair grouping (sorted-space, scatter-free) ----
    mp = m * n_probes
    pairs = probes.reshape(-1).astype(jnp.int32)           # (mp,) list ids
    order = jnp.argsort(pairs, stable=True)                # sorted pair -> orig pair
    sorted_list = jnp.take(pairs, order)
    counts = jnp.bincount(pairs, length=L)
    padded = -(-counts // G) * G
    pstart = jnp.cumsum(padded) - padded                   # padded starts
    starts = jnp.cumsum(counts) - counts                   # sorted-run starts
    pos = jnp.arange(mp, dtype=jnp.int32) - jnp.take(starts, sorted_list)
    slot_sorted = (jnp.take(pstart, sorted_list) + pos).astype(jnp.int32)

    # static bound on padded slots: at most min(L, mp) lists have pairs, each
    # contributing < G padding (a bound of (G-1)*L would scan mostly dead
    # groups for small batches on many-list indexes)
    P = mp + (G - 1) * min(L, mp)
    n_groups = -(-P // G)
    n_chunks = -(-n_groups // Gc)
    # slot -> sorted-pair occupancy, via binary search on the monotonic
    # slot_sorted (slots without a pair are padding)
    all_slots = jnp.arange(n_chunks * Gc * G, dtype=jnp.int32)
    j_of_slot = jnp.searchsorted(slot_sorted, all_slots).astype(jnp.int32)
    jc = jnp.minimum(j_of_slot, mp - 1)
    slot_live = (j_of_slot < mp) & (jnp.take(slot_sorted, jc) == all_slots)
    # slot -> list id (group-constant; list of the sorted run covering it)
    lend = pstart + padded
    l_of_slot = (jnp.searchsorted(lend, all_slots, side="right")
                 .astype(jnp.int32))
    l_of_slot = jnp.minimum(l_of_slot, L - 1)
    # slot -> query row (0 for padding, masked later)
    orig_pair = jnp.take(order, jc)
    q_of_slot = jnp.where(slot_live, orig_pair // n_probes, 0).astype(jnp.int32)

    cb = index.codebooks.astype(jnp.float32)
    cb_n2 = jnp.sum(cb * cb, axis=-1)
    ct = jnp.bfloat16 if lut_dtype == "bfloat16" else jnp.float32
    q_slot = q_of_slot.reshape(n_chunks, Gc, G)
    l_slot = l_of_slot.reshape(n_chunks, Gc, G)
    live_slot = slot_live.reshape(n_chunks, Gc, G)
    l_group = l_slot[:, :, 0]                              # (n_chunks, Gc)

    def per_chunk(args):
        qs, ls, lg, live = args  # (Gc, G), (Gc, G), (Gc,), (Gc, G)
        # ---- LUTs for this chunk's slots ----
        qr = jnp.take(qrot, qs.reshape(-1), axis=0)        # (Gc*G, d_rot)
        crot = jnp.take(index.centers_rot, ls.reshape(-1), axis=0)
        # per-list residual scales: same LUT fold as the tiled path (s for
        # IP, s^2 for L2 with the residual pre-divided); bias stays raw
        sc = (jnp.take(index.list_scales, ls.reshape(-1), axis=0)
              if index.scale_normed else None)             # (Gc*G,) | None
        if inner:
            rs = qr.reshape(-1, pq_dim, pq_len)
            if codebook_kind == "per_subspace":
                lut = jnp.einsum("nsl,skl->nsk", rs, cb,
                                 precision=lax.Precision.HIGHEST)
            else:
                cbl = jnp.take(cb, ls.reshape(-1), axis=0)
                lut = jnp.einsum("nsl,nkl->nsk", rs, cbl,
                                 precision=lax.Precision.HIGHEST)
            if sc is not None:
                lut = lut * sc[:, None, None]
            bias = jnp.einsum("nd,nd->n", qr, crot,
                              precision=lax.Precision.HIGHEST)
        else:
            r = (qr - crot).reshape(-1, pq_dim, pq_len)
            bias = jnp.sum(r * r, axis=(1, 2))
            if sc is not None:
                r = r / sc[:, None, None]
            if codebook_kind == "per_subspace":
                dots = jnp.einsum("nsl,skl->nsk", r, cb,
                                  precision=lax.Precision.HIGHEST)
                lut = cb_n2[None] - 2.0 * dots
            else:
                cbl = jnp.take(cb, ls.reshape(-1), axis=0)
                dots = jnp.einsum("nsl,nkl->nsk", r, cbl,
                                  precision=lax.Precision.HIGHEST)
                lut = jnp.take(cb_n2, ls.reshape(-1), axis=0)[:, None] - 2.0 * dots
            if sc is not None:
                lut = lut * (sc * sc)[:, None, None]
        lutf = lut.reshape(Gc, G, pq_dim * n_codes)

        # ---- shared one-hot per group's list ----
        codes = jnp.take(index.list_codes, lg, axis=0)     # (Gc, cap, pq_dim)
        ids = jnp.take(index.list_ids, lg, axis=0)         # (Gc, cap)
        if index.pq_split:
            ar16 = jnp.arange(16, dtype=codes.dtype)
            oh = jnp.concatenate(
                [(codes >> 4)[..., None] == ar16,
                 (codes & 0xF)[..., None] == ar16], axis=-1)
        else:
            oh = codes[..., None] == jnp.arange(n_codes, dtype=codes.dtype)
        ohf = oh.reshape(Gc, cap, pq_dim * n_codes)

        # ---- ONE real-N matmul per group: (cap, D) x (D, G) ----
        if lut_dtype == "int8":
            amax = jnp.max(jnp.abs(lutf), axis=2, keepdims=True)
            scale = jnp.maximum(amax, 1e-30) / 127.0
            lut_q = jnp.clip(jnp.round(lutf / scale), -127, 127).astype(jnp.int8)
            acc = lax.dot_general(
                ohf.astype(jnp.int8), lut_q, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.int32)          # (Gc, cap, G)
            scores = acc.astype(jnp.float32) * jnp.swapaxes(scale, 1, 2)
        else:
            scores = lax.dot_general(
                ohf.astype(ct), jnp.swapaxes(lutf.astype(ct), 1, 2),
                (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)        # (Gc, cap, G)
        scores = scores + bias.reshape(Gc, 1, G)
        if index.pq_split and not inner:
            scores = scores + jnp.take(index.list_consts, lg, axis=0)[:, :, None]
        bad = jnp.inf if not inner else -jnp.inf
        scores = jnp.where(ids[:, :, None] >= 0, scores, bad)
        sc_t = jnp.swapaxes(scores, 1, 2).reshape(Gc * G, cap)
        ids_t = jnp.broadcast_to(ids[:, None, :], (Gc, G, cap)
                                 ).reshape(Gc * G, cap)
        if keep_mask is not None:
            from .sample_filter import apply_id_filter

            sc_t = apply_id_filter(sc_t, ids_t, keep_mask, not inner)
        sv, si = select_k_impl(sc_t, ids_t, k, not inner,
                               impl=select_impl)          # (Gc*G, k)
        sv = jnp.where(live.reshape(-1, 1), sv, bad)
        si = jnp.where(live.reshape(-1, 1), si, -1)
        return sv, si

    slot_v, slot_i = lax.map(per_chunk, (q_slot, l_slot, l_group, live_slot))
    slot_v = slot_v.reshape(-1, k)                         # (n_chunks*Gc*G, k)
    slot_i = slot_i.reshape(-1, k)

    # ---- un-sort: slot results -> per-pair -> per-query merge ----
    pv = jnp.take(slot_v, slot_sorted, axis=0)             # sorted-pair order
    pi = jnp.take(slot_i, slot_sorted, axis=0)
    inv = jnp.argsort(order)                               # orig-pair order
    pv = jnp.take(pv, inv, axis=0).reshape(m, n_probes * k)
    pi = jnp.take(pi, inv, axis=0).reshape(m, n_probes * k)
    dists, idx = select_k_impl(pv, pi, k, not inner, impl=select_impl)
    if not inner and metric in (DistanceType.L2SqrtExpanded,
                                DistanceType.L2SqrtUnexpanded):
        dists = jnp.where(jnp.isfinite(dists),
                          jnp.sqrt(jnp.maximum(dists, 0.0)), dists)
    empty = ~jnp.isfinite(dists)
    idx = jnp.where(empty, -1, idx)
    return dists, idx


@instrument(
    "ivf_pq.search",
    items=lambda a, kw: nrows(a[2] if len(a) > 2 else kw["queries"]),
    labels=lambda a, kw: {"k": a[3] if len(a) > 3 else kw["k"],
                          "n_probes": (a[0] if a else kw["params"]).n_probes},
)
@auto_convert_output
def search(params: SearchParams, index: IvfPqIndex, queries, k: int,
           sample_filter=None, res: Resources | None = None):
    """Search (reference: ivf_pq::search :723; pylibraft neighbors/ivf_pq;
    filtered overload neighbors/ivf_pq.cuh search_with_filtering).

    Returns (distances (m, k), ids (m, k)); distances are approximate
    (PQ-quantized), id -1 marks empty candidate slots.

    Tracer caveat: when ``index`` is passed as a jit argument its
    ``list_sizes`` is a tracer, so the "index is empty" guard (like the
    ``index.size`` property) cannot run — searching an empty index inside a
    user jit returns all-sentinel results (-1 ids, +inf distances) instead
    of raising."""
    from .sample_filter import resolve_filter
    from .brute_force import _coerce_queries

    res = res or default_resources()
    queries = jnp.asarray(queries)
    expects(queries.ndim == 2 and queries.shape[1] == index.dim, "query dim mismatch")
    queries = _coerce_queries(index.data_kind, queries)
    expects(index.capacity > 0, "index is empty")
    _check_split_consts(index)
    if not isinstance(index.list_sizes, jax.core.Tracer):
        expects(index.size > 0, "index is empty")
    n_probes = min(params.n_probes, index.n_lists)
    expects(k <= n_probes * index.capacity, "k exceeds probed candidate pool")
    m = queries.shape[0]

    expects(params.lut_dtype in ("float32", "bfloat16", "int8"),
            "lut_dtype must be 'float32', 'bfloat16' or 'int8', got %r",
            params.lut_dtype)
    n_codes = index.codebooks.shape[-2]
    scan_impl = resolve_scan_impl(params, index, n_codes)
    expects(params.select_impl in ("auto", "xla", "pallas"),
            "select_impl must be 'auto', 'xla' or 'pallas', got %r",
            params.select_impl)
    if params.select_impl == "pallas":
        from ..ops.topk import TOPK_MAX_K

        expects(k <= TOPK_MAX_K,
                "select_impl='pallas' selects with the streaming kernel: "
                "k=%d must be <= %d", k, TOPK_MAX_K)
    widen = int(params.funnel_widen)
    expects(widen >= 1, "funnel_widen must be >= 1, got %d", widen)
    if widen > 1:
        expects(index.has_fast_scan,
                "funnel_widen=%d widens through the fast-scan tier, but "
                "this index carries none — build with "
                "IndexParams.fast_scan='1bit'|'4bit'", widen)
    # funnel_widen == 1 is the classic scan BY CONSTRUCTION (ids bit-equal):
    # the funnel dispatch below is taken only for a real widen factor
    use_funnel = widen > 1
    if use_funnel:
        bytes_per_probe_row = funnel_scan_bytes_per_probe_row(
            index.capacity, index.list_sig.shape[2])
    else:
        bytes_per_probe_row = pq_scan_bytes_per_probe_row(
            index.capacity, index.pq_dim, n_codes)
    query_tile, probe_chunk = plan_search_tiles(
        m, n_probes, int(k), index.capacity,
        bytes_per_probe_row=bytes_per_probe_row,
        budget_bytes=res.workspace_bytes,
        max_query_tile=128,
    )

    keep_mask = resolve_filter(sample_filter)
    if keep_mask is not None:
        from .sample_filter import validate_filter_covers

        validate_filter_covers(index, keep_mask)
    expects(params.scan_order in ("auto", "tiled", "grouped"),
            "scan_order must be 'auto', 'tiled' or 'grouped', got %r",
            params.scan_order)
    scan_order = params.scan_order
    if scan_order == "auto":
        # tiled: the grouped order measured neutral at 1M (its 4x operand
        # -traffic cut bought nothing — the tiled one-hot contraction is not
        # operand-bound; BASELINE.md "Round-4 grouped scan")
        scan_order = "tiled"
    if use_funnel:
        expects(scan_order == "tiled",
                "funnel_widen > 1 rides the tiled scan order; set "
                "scan_order='tiled' (or 'auto')")
        expects(scan_impl == "onehot",
                "funnel_widen > 1 implements the one-hot signature "
                "contraction; set scan_impl='onehot' (or 'auto')")
        expects(params.lut_dtype != "int8",
                "lut_dtype='int8' quantizes the PQ LUT; the funnel's "
                "signature tier is already 1-4 bit — use float32/bfloat16")
        # per-chunk widen pool: at least k (the rerank must fill the final
        # select), at most every slot the chunk scans
        k_widen = max(int(k), min(widen * int(k),
                                  probe_chunk * index.capacity))
        if metrics.enabled():
            _quant_funnel_total().inc()
        return _pq_search_funnel(
            index, queries, n_probes, int(k), k_widen, query_tile,
            probe_chunk, index.metric, index.codebook_kind, params.lut_dtype,
            keep_mask, select_impl=params.select_impl)
    if scan_order == "grouped":
        expects(k <= index.capacity,
                "scan_order='grouped' selects per (pair, list): k=%d must be "
                "<= capacity=%d", k, index.capacity)
        expects(scan_impl == "onehot",
                "scan_order='grouped' implements the one-hot contraction; "
                "set scan_impl='onehot' (or 'auto')")
        expects(1 <= params.group_size <= 1024,
                "group_size must be in [1, 1024], got %d", params.group_size)
        return _pq_search_grouped(
            index, queries, n_probes, int(k), index.metric,
            index.codebook_kind, params.lut_dtype, keep_mask,
            group_size=int(params.group_size),
            select_impl=params.select_impl)
    return _pq_search(
        index, queries, n_probes, int(k), query_tile, probe_chunk, index.metric,
        index.codebook_kind, params.lut_dtype,
        keep_mask, scan_impl=scan_impl, select_impl=params.select_impl,
    )


def write_index(f, index: IvfPqIndex) -> None:
    """Serialize to an open binary stream (the composable half of
    :func:`save` — :mod:`raft_tpu.stream` embeds sealed indexes this way)."""
    serialize_header(f, "ivf_pq")
    serialize_scalar(f, int(index.metric))
    serialize_scalar(f, index.codebook_kind)
    serialize_scalar(f, index.pq_bits)
    serialize_scalar(f, float(index.split_factor))
    serialize_scalar(f, bool(index.pq_split))
    serialize_scalar(f, index.data_kind)
    for arr in (index.centers, index.centers_rot, index.rotation, index.codebooks,
                index.list_codes, index.list_ids, index.list_sizes,
                index.list_consts, index.list_scales):
        serialize_mdspan(f, arr)
    serialize_tuned(f, index.tuned)
    # raft_tpu/13 quantization-codec record (trailing, after tuned — the
    # serialize_tuned shared-layout discipline). Gated on the CURRENT
    # format version through the module attribute, so a writer pinned to
    # an older version (back-compat tests monkeypatch it) emits true
    # old-layout bytes.
    if version_number(core_serialize.SERIALIZATION_VERSION) >= 13:
        serialize_scalar(f, index.rotation_kind)
        serialize_scalar(f, index.codebook_loss)
        serialize_scalar(f, index.fast_scan)
        serialize_mdspan(f, index.list_sig)
        serialize_mdspan(f, index.sig_scales)


def read_index(f) -> IvfPqIndex:
    """Deserialize from an open binary stream (pairs with
    :func:`write_index`)."""
    ver = check_header(f, "ivf_pq")
    metric = DistanceType(deserialize_scalar(f))
    codebook_kind = deserialize_scalar(f)
    pq_bits = deserialize_scalar(f)
    split_factor = float(deserialize_scalar(f))
    pq_split = bool(deserialize_scalar(f))
    # raft_tpu/6 added data_kind (int8/uint8 byte ingestion); older
    # files could only hold float data
    kind = (deserialize_scalar(f)
            if ver not in ("raft_tpu/3", "raft_tpu/4", "raft_tpu/5")
            else "float32")
    arrs = [jnp.asarray(deserialize_mdspan(f)) for _ in range(8)]
    # raft_tpu/7 added list_scales (residual_scale_norm); older files
    # never normalized, so the disabled (0,) sentinel is exact
    if ver not in ("raft_tpu/3", "raft_tpu/4", "raft_tpu/5",
                   "raft_tpu/6"):
        arrs.append(jnp.asarray(deserialize_mdspan(f)))
    else:
        arrs.append(jnp.zeros((0,), jnp.float32))
    # raft_tpu/9 appended the optional tuned record (pinned operating
    # point); older files are untuned
    tuned = deserialize_tuned(f, ver)
    # raft_tpu/13 appended the quantization-codec record; /12-and-older
    # files carry the codec defaults exactly (no learned rotation — any
    # rotation they DO have is already folded into the serialized matrix —
    # l2 loss, no fast-scan tier)
    if version_number(ver) >= 13:
        rotation_kind = deserialize_scalar(f)
        codebook_loss = deserialize_scalar(f)
        fast_scan = deserialize_scalar(f)
        arrs.append(jnp.asarray(deserialize_mdspan(f)))  # list_sig
        arrs.append(jnp.asarray(deserialize_mdspan(f)))  # sig_scales
    else:
        rotation_kind, codebook_loss, fast_scan = "none", "l2", "none"
        n_lists = arrs[0].shape[0]
        arrs.append(jnp.zeros((n_lists, 0, 0), jnp.uint8))
        arrs.append(jnp.zeros((0,), jnp.float32))
    return IvfPqIndex(*arrs, metric=metric, codebook_kind=codebook_kind, pq_bits=pq_bits,
                      split_factor=split_factor, pq_split=pq_split,
                      data_kind=kind, rotation_kind=rotation_kind,
                      codebook_loss=codebook_loss, fast_scan=fast_scan,
                      tuned=tuned)


def save(index: IvfPqIndex, path: str) -> None:
    """Serialize (reference: ivf_pq_serialize.cuh:52-110).
    Atomic: temp file + rename, a crashed save keeps the previous file."""
    from ..core.serialize import atomic_write

    with atomic_write(path) as f:
        write_index(f, index)


def load(path: str, res: Resources | None = None) -> IvfPqIndex:
    """Deserialize (reference: ivf_pq_serialize.cuh deserialize)."""
    with open(path, "rb") as f:
        return read_index(f)


def batched_searcher(index: IvfPqIndex, params: SearchParams | None = None):
    """Stable serving hook (raft_tpu.serve; contract in :mod:`._hooks`) —
    the surface the serve registry warms and hot-swaps through. For the
    candidates+refine serving pattern, publish a hook built by the caller
    (serve accepts any callable with the hook attributes — or
    ``raft_tpu.tune.make_searcher``, which wires the refine epilogue from
    a pinned ``refine_ratio`` decision). With no explicit ``params``, an
    attached refine-free tune decision (``index.tuned``) supplies the
    operating point — docs/tuning.md."""
    from ._hooks import make_hook

    if params is None and index.tuned is not None:
        from ..tune.apply import make_searcher as tuned_searcher

        return tuned_searcher(index, True, degrade_without_rows=True)
    sp = params or SearchParams()
    return make_hook(lambda queries, k: search(sp, index, queries, k),
                     "ivf_pq", index.dim, index.data_kind)
