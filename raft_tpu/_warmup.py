"""Ahead-of-time warmup: pre-populate the persistent compilation cache.

The reference ships ahead-of-time compiled kernels in ``libraft.so`` (the
explicit-instantiation machinery, SURVEY.md R1/R2; compile-mode matrix
``cpp/test/CMakeLists.txt:183-190``), so a user's first 1M build never pays
device-code compilation. The TPU analogue is the persistent XLA compilation
cache (``config.enable_compilation_cache``) — but the cache only helps a
*second* process; a fresh host still pays minutes of cold jit on the flagship
path (1M ivf_pq: 103.6 s cold vs 7.3 s warm, BASELINE.md r04 harness).

``warmup`` closes that first-touch gap: run it once per host — at deploy
time, in a provisioning step, off the serving path — with the shapes you will
build and search at, and every subsequent process (including the first
user-facing one) compiles from the cache. It executes the real build+search
pipeline on device-generated random data of the target shapes, because the
cache is keyed by HLO: only the genuinely identical programs (same shapes,
same static config) hit.

    import raft_tpu
    raft_tpu.warmup("ivf_pq", n=1_000_000, d=128)        # once, at deploy
    # ... later, any process on this host ...
    idx = ivf_pq.build(params, dataset)                   # warm: seconds

Random data is generated ON DEVICE (a 512 MB host->device transfer would
dominate), and the warmup returns its own build/search wall times so a
provisioning script can log them.
"""

from __future__ import annotations

import time
from typing import Any

__all__ = ["warmup", "warm_buckets"]

_KINDS = ("brute_force", "ivf_flat", "ivf_pq", "cagra")


def _random_queries(key, rows: int, d: int, dtype: str, sample=None):
    import jax
    import jax.numpy as jnp

    if sample is not None:
        # rows resampled (with replacement) from the user's sample: the
        # warmed programs see the REAL data distribution, not the uniform
        # worst case
        return _resample(key, sample, rows)
    if dtype == "float32":
        return jax.random.uniform(key, (rows, d), jnp.float32)
    lo, hi = (-128, 128) if dtype == "int8" else (0, 256)
    return jax.random.randint(key, (rows, d), lo, hi, jnp.int32).astype(dtype)


def _resample(key, sample, rows: int):
    """(rows, d) drawn with replacement from the user's sample rows — the
    warmup dataset keeps the production data's cluster/clump structure, it
    just repeats points when the sample is smaller than the target n."""
    import jax
    import jax.numpy as jnp

    sample = jnp.asarray(sample)
    idx = jax.random.randint(key, (rows,), 0, sample.shape[0])
    return jnp.take(sample, idx, axis=0)


def warm_buckets(searcher, *, dim: int, buckets, k: int = 10,
                 dtype: str = "float32", seed: int = 0,
                 sample=None) -> dict:
    """Compile-warm one serving searcher at every batch-shape bucket.

    The serving-layer half of :func:`warmup` (raft_tpu.serve): a micro-
    batched service flushes only the padded power-of-two shapes in
    ``buckets``, so running ``searcher(queries, k)`` once per bucket — with
    queries drawn in the index's own query ``dtype`` — compiles the exact
    program set the hot path will dispatch. The serve registry calls this
    from ``publish`` BEFORE flipping the active pointer (warm hot-swap);
    provisioning scripts can call it directly to populate the persistent
    cache off the serving path (enable the cache first, see
    :func:`raft_tpu.config.enable_compilation_cache`).

    ``sample`` (optional, (r, dim) in the serving query dtype) draws the
    bucket queries from real data instead of uniform noise. Compilation
    does not depend on VALUES, so any sample warms the same programs — but
    data-dependent execution time does (CAGRA's hop loop runs ~3.7x longer
    on uniform data than clustered, BASELINE.md "Round-6 warmup data
    sample"), so a sample makes publish-time warms cheaper and their
    reported walls representative.

    Returns ``{bucket: {wall_s, compile_s, trace_s, programs, cache_hits,
    cache_misses}}`` via the obs compile-attribution subscription — all-warm
    buckets report ``compile_s == 0``, which is the zero-hiccup-swap proof
    ``bench.py --serve`` asserts.
    """
    import jax
    import jax.numpy as jnp

    from .core.errors import expects
    from .obs import compile as obs_compile

    expects(dtype in ("float32", "int8", "uint8"),
            "dtype must be 'float32', 'int8' or 'uint8', got %r", dtype)
    if sample is not None:
        sample = jnp.asarray(sample)
        expects(sample.ndim == 2 and sample.shape[1] == dim,
                "warm sample must be (rows, %d), got %s", dim,
                tuple(sample.shape))
        expects(str(sample.dtype) == dtype,
                "warm sample dtype %s must match the serving dtype %s",
                sample.dtype, dtype)
    out = {}
    key = jax.random.key(seed)
    for b in sorted(set(int(b) for b in buckets)):
        expects(b >= 1, "bucket sizes must be >= 1, got %d", b)
        key, kq = jax.random.split(key)
        q = _random_queries(kq, b, dim, dtype, sample=sample)
        jax.block_until_ready(q)
        t0 = time.perf_counter()
        with obs_compile.attribution() as rec:
            jax.block_until_ready(
                jax.tree_util.tree_leaves(searcher(q, k))[0])
        out[b] = {"wall_s": round(time.perf_counter() - t0, 3),
                  **rec.summary()}
    return out


def warmup(kind: str, n: int, d: int, *, k: int = 10, queries: int = 10_000,
           dtype: str = "float32", data: Any | None = None,
           index_params: Any | None = None,
           search_params: Any | None = None, cache_dir: str | None = None,
           seed: int = 0) -> dict:
    """Compile-warm one index kind at (n, d) build / (queries, d) search.

    Enables the persistent compilation cache (``cache_dir`` or the default
    ``~/.cache/raft_tpu/jit``), builds the index on uniform random data of
    the target shape, runs one search of the target batch shape, and returns
    headline walls (``build_s``/``search_s``/``cache_dir``) plus per-phase
    compile attribution (see below). Pass the same
    ``index_params``/``search_params`` you will use in production — the
    cache keys on static config (n_lists, pq_dim, itopk, ...), so a warmup
    with different params warms different programs. The same holds for
    ``k``: the search is warmed at EXACTLY the ``k`` passed here (k is a
    static argument of every search program), so a production pipeline that
    searches at several widths — e.g. IVF-PQ candidates at k=40 feeding a
    refine at k=10 — must call warmup once per width.

    ``dtype`` ("float32" | "int8" | "uint8") warms the byte-dataset search
    paths: random data is drawn in the target dtype, so the s8 kernels and
    byte list layouts compile exactly as production will run them.

    ``data`` (optional (r, d) array, any r) warms on a SAMPLE OF THE REAL
    DATA, resampled with replacement to the target (n, d) / (queries, d)
    shapes. Compiled programs are shape-keyed, so the cache outcome is
    identical either way — but the warmup's own wall time is not: uniform
    random data is the measured worst case of the data-adaptive builds
    (CAGRA's build_n_probes autotune keeps p=32 on uniform data, 483 s vs
    ~130 s at 1M on clustered — VERDICT r5 #5), so a few thousand rows of
    production data make a cagra warmup ~3.7x cheaper while warming the
    exact same programs. When ``data`` is int8/uint8, ``dtype`` must agree
    (or be left at its default, which is then inferred from the sample).

    The returned dict attributes each wall time instead of leaving it opaque
    (obs/compile.py, the jax.monitoring subscription): ``build``/``search``
    each carry ``{wall_s, compile_s, trace_s, programs, cache_hits,
    cache_misses, program_compile_s}`` — ``program_compile_s`` is the
    per-program backend-compile seconds, and the cache counters say whether
    this warmup paid cold compiles or found the cache already hot. On a jax
    without the monitoring bus the split falls back to a cold-vs-warm wall
    delta for the search (``attribution: "timing"``) and the per-program
    detail is empty. A summary INFO line goes through the raft_tpu logger
    (``core.logger.basic_config`` formats it to stderr in one call).
    """
    import jax
    import jax.numpy as jnp

    from .config import enable_compilation_cache
    from .core.errors import expects
    from .core.logger import logger
    from .obs import compile as obs_compile

    expects(kind in _KINDS, "unknown index kind %r (one of %s)", kind,
            ", ".join(_KINDS))
    expects(dtype in ("float32", "int8", "uint8"),
            "dtype must be 'float32', 'int8' or 'uint8', got %r", dtype)
    sample = None
    if data is not None:
        # validate BEFORE the cache redirect below — a bad sample must fail
        # without permanently re-pointing this process's jax cache config
        sample = jnp.asarray(data)
        expects(sample.ndim == 2 and sample.shape[1] == d,
                "data sample must be (rows, %d), got %s", d,
                tuple(sample.shape))
        if str(sample.dtype) in ("int8", "uint8") and dtype == "float32":
            dtype = str(sample.dtype)  # infer byte kinds from the sample
        expects(str(sample.dtype) == dtype,
                "data sample dtype %s must match dtype=%r", sample.dtype,
                dtype)
    cache = enable_compilation_cache(cache_dir)
    kd, kq = jax.random.split(jax.random.key(seed))
    if sample is not None:
        x = _resample(kd, sample, n)
        q = _resample(kq, sample, queries)
    elif dtype == "float32":
        x = jax.random.uniform(kd, (n, d), jnp.float32)
        q = jax.random.uniform(kq, (queries, d), jnp.float32)
    else:
        lo, hi = (-128, 128) if dtype == "int8" else (0, 256)
        x = jax.random.randint(kd, (n, d), lo, hi, jnp.int32).astype(dtype)
        q = jax.random.randint(kq, (queries, d), lo, hi, jnp.int32).astype(dtype)
    jax.block_until_ready((x, q))

    t0 = time.perf_counter()
    with obs_compile.attribution() as build_attr:
        if kind == "brute_force":
            from .neighbors import brute_force

            idx = brute_force.BruteForce().build(x)
            searcher = lambda: idx.search(q, k)
        elif kind == "ivf_flat":
            from .neighbors import ivf_flat

            idx = ivf_flat.build(
                index_params or ivf_flat.IndexParams(n_lists=1024, seed=seed), x)
            jax.block_until_ready(idx.list_data)
            searcher = lambda: ivf_flat.search(
                search_params or ivf_flat.SearchParams(n_probes=8), idx, q, k)
        elif kind == "ivf_pq":
            from .neighbors import ivf_pq

            idx = ivf_pq.build(
                index_params or ivf_pq.IndexParams(
                    n_lists=1024, pq_bits=4, pq_dim=min(64, d), seed=seed), x)
            jax.block_until_ready(idx.list_codes)
            # the caller's k, EXACTLY: the compilation cache is keyed by HLO
            # and k is a static arg of _pq_search, so the old max(k, 40)
            # override left the production k=10 program cold (ADVICE r5
            # medium). Pipelines that also search a refine-candidate width
            # (e.g. k=40 feeding refine to 10) warm that width with a second
            # warmup call.
            searcher = lambda: ivf_pq.search(
                search_params or ivf_pq.SearchParams(
                    n_probes=8, lut_dtype="bfloat16"), idx, q, k)
        else:  # cagra
            from .neighbors import cagra

            idx = cagra.build(index_params or cagra.IndexParams(seed=seed), x)
            jax.block_until_ready(idx.graph)
            searcher = lambda: cagra.search(
                search_params or cagra.SearchParams(itopk_size=32), idx, q, k)
    build_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    with obs_compile.attribution() as search_attr:
        jax.block_until_ready(jax.tree_util.tree_leaves(searcher())[0])
    search_s = time.perf_counter() - t0

    def _phase(wall_s, rec) -> dict:
        return {
            "wall_s": round(wall_s, 2),
            **rec.summary(),
            "program_compile_s": [round(s, 3) for s in rec.program_compile_s],
        }

    attribution = "jax.monitoring"
    if not search_attr.available:  # pragma: no cover - ancient jax
        # timing fallback (ops/_compat.jax_monitoring gate): a second,
        # fully warm search bounds execute time; the cold-warm delta is the
        # compile share of the first. Cache outcomes stay unknown (-1).
        attribution = "timing"
        t0 = time.perf_counter()
        jax.block_until_ready(jax.tree_util.tree_leaves(searcher())[0])
        warm_s = time.perf_counter() - t0
        search_attr.compile_s = max(search_s - warm_s, 0.0)
        search_attr.cache_hits = search_attr.cache_misses = -1
        build_attr.cache_hits = build_attr.cache_misses = -1

    out = {
        # headline walls keep their historical keys (provisioning scripts)
        "build_s": round(build_s, 2), "search_s": round(search_s, 2),
        "cache_dir": cache, "attribution": attribution,
        "build": _phase(build_s, build_attr),
        "search": _phase(search_s, search_attr),
    }
    logger.info(
        "warmup(%s, n=%d, d=%d, k=%d): build %.1fs (%.1fs compile over %d "
        "programs), search %.1fs (%.1fs compile), cache %d hits / %d misses "
        "at %s", kind, n, d, k, build_s, build_attr.compile_s,
        build_attr.programs, search_s, search_attr.compile_s,
        build_attr.cache_hits + search_attr.cache_hits,
        build_attr.cache_misses + search_attr.cache_misses, cache)
    return out
