"""Ahead-of-time warmup: pre-populate the persistent compilation cache.

The reference ships ahead-of-time compiled kernels in ``libraft.so`` (the
explicit-instantiation machinery, SURVEY.md R1/R2; compile-mode matrix
``cpp/test/CMakeLists.txt:183-190``), so a user's first 1M build never pays
device-code compilation. The TPU analogue is the persistent XLA compilation
cache (``config.enable_compilation_cache``) — but the cache only helps a
*second* process; a fresh host still pays minutes of cold jit on the flagship
path (1M ivf_pq: 103.6 s cold vs 7.3 s warm, BASELINE.md r04 harness).

``warmup`` closes that first-touch gap: run it once per host — at deploy
time, in a provisioning step, off the serving path — with the shapes you will
build and search at, and every subsequent process (including the first
user-facing one) compiles from the cache. It executes the real build+search
pipeline on device-generated random data of the target shapes, because the
cache is keyed by HLO: only the genuinely identical programs (same shapes,
same static config) hit.

    import raft_tpu
    raft_tpu.warmup("ivf_pq", n=1_000_000, d=128)        # once, at deploy
    # ... later, any process on this host ...
    idx = ivf_pq.build(params, dataset)                   # warm: seconds

Random data is generated ON DEVICE (a 512 MB host->device transfer would
dominate), and the warmup returns its own build/search wall times so a
provisioning script can log them.
"""

from __future__ import annotations

import time
from typing import Any

__all__ = ["warmup", "warm_buckets"]

_KINDS = ("brute_force", "ivf_flat", "ivf_pq", "cagra")


def _random_queries(key, rows: int, d: int, dtype: str):
    import jax
    import jax.numpy as jnp

    if dtype == "float32":
        return jax.random.uniform(key, (rows, d), jnp.float32)
    lo, hi = (-128, 128) if dtype == "int8" else (0, 256)
    return jax.random.randint(key, (rows, d), lo, hi, jnp.int32).astype(dtype)


def warm_buckets(searcher, *, dim: int, buckets, k: int = 10,
                 dtype: str = "float32", seed: int = 0) -> dict:
    """Compile-warm one serving searcher at every batch-shape bucket.

    The serving-layer half of :func:`warmup` (raft_tpu.serve): a micro-
    batched service flushes only the padded power-of-two shapes in
    ``buckets``, so running ``searcher(queries, k)`` once per bucket — with
    queries drawn in the index's own query ``dtype`` — compiles the exact
    program set the hot path will dispatch. The serve registry calls this
    from ``publish`` BEFORE flipping the active pointer (warm hot-swap);
    provisioning scripts can call it directly to populate the persistent
    cache off the serving path (enable the cache first, see
    :func:`raft_tpu.config.enable_compilation_cache`).

    Returns ``{bucket: {wall_s, compile_s, trace_s, programs, cache_hits,
    cache_misses}}`` via the obs compile-attribution subscription — all-warm
    buckets report ``compile_s == 0``, which is the zero-hiccup-swap proof
    ``bench.py --serve`` asserts.
    """
    import jax

    from .core.errors import expects
    from .obs import compile as obs_compile

    expects(dtype in ("float32", "int8", "uint8"),
            "dtype must be 'float32', 'int8' or 'uint8', got %r", dtype)
    out = {}
    key = jax.random.key(seed)
    for b in sorted(set(int(b) for b in buckets)):
        expects(b >= 1, "bucket sizes must be >= 1, got %d", b)
        key, kq = jax.random.split(key)
        q = _random_queries(kq, b, dim, dtype)
        jax.block_until_ready(q)
        t0 = time.perf_counter()
        with obs_compile.attribution() as rec:
            jax.block_until_ready(
                jax.tree_util.tree_leaves(searcher(q, k))[0])
        out[b] = {"wall_s": round(time.perf_counter() - t0, 3),
                  **rec.summary()}
    return out


def warmup(kind: str, n: int, d: int, *, k: int = 10, queries: int = 10_000,
           dtype: str = "float32", index_params: Any | None = None,
           search_params: Any | None = None, cache_dir: str | None = None,
           seed: int = 0) -> dict:
    """Compile-warm one index kind at (n, d) build / (queries, d) search.

    Enables the persistent compilation cache (``cache_dir`` or the default
    ``~/.cache/raft_tpu/jit``), builds the index on uniform random data of
    the target shape, runs one search of the target batch shape, and returns
    headline walls (``build_s``/``search_s``/``cache_dir``) plus per-phase
    compile attribution (see below). Pass the same
    ``index_params``/``search_params`` you will use in production — the
    cache keys on static config (n_lists, pq_dim, itopk, ...), so a warmup
    with different params warms different programs. The same holds for
    ``k``: the search is warmed at EXACTLY the ``k`` passed here (k is a
    static argument of every search program), so a production pipeline that
    searches at several widths — e.g. IVF-PQ candidates at k=40 feeding a
    refine at k=10 — must call warmup once per width.

    ``dtype`` ("float32" | "int8" | "uint8") warms the byte-dataset search
    paths: random data is drawn in the target dtype, so the s8 kernels and
    byte list layouts compile exactly as production will run them.

    The returned dict attributes each wall time instead of leaving it opaque
    (obs/compile.py, the jax.monitoring subscription): ``build``/``search``
    each carry ``{wall_s, compile_s, trace_s, programs, cache_hits,
    cache_misses, program_compile_s}`` — ``program_compile_s`` is the
    per-program backend-compile seconds, and the cache counters say whether
    this warmup paid cold compiles or found the cache already hot. On a jax
    without the monitoring bus the split falls back to a cold-vs-warm wall
    delta for the search (``attribution: "timing"``) and the per-program
    detail is empty. A summary INFO line goes through the raft_tpu logger
    (``core.logger.basic_config`` formats it to stderr in one call).
    """
    import jax
    import jax.numpy as jnp

    from .config import enable_compilation_cache
    from .core.errors import expects
    from .core.logger import logger
    from .obs import compile as obs_compile

    expects(kind in _KINDS, "unknown index kind %r (one of %s)", kind,
            ", ".join(_KINDS))
    expects(dtype in ("float32", "int8", "uint8"),
            "dtype must be 'float32', 'int8' or 'uint8', got %r", dtype)
    cache = enable_compilation_cache(cache_dir)
    kd, kq = jax.random.split(jax.random.key(seed))
    if dtype == "float32":
        x = jax.random.uniform(kd, (n, d), jnp.float32)
        q = jax.random.uniform(kq, (queries, d), jnp.float32)
    else:
        lo, hi = (-128, 128) if dtype == "int8" else (0, 256)
        x = jax.random.randint(kd, (n, d), lo, hi, jnp.int32).astype(dtype)
        q = jax.random.randint(kq, (queries, d), lo, hi, jnp.int32).astype(dtype)
    jax.block_until_ready((x, q))

    t0 = time.perf_counter()
    with obs_compile.attribution() as build_attr:
        if kind == "brute_force":
            from .neighbors import brute_force

            idx = brute_force.BruteForce().build(x)
            searcher = lambda: idx.search(q, k)
        elif kind == "ivf_flat":
            from .neighbors import ivf_flat

            idx = ivf_flat.build(
                index_params or ivf_flat.IndexParams(n_lists=1024, seed=seed), x)
            jax.block_until_ready(idx.list_data)
            searcher = lambda: ivf_flat.search(
                search_params or ivf_flat.SearchParams(n_probes=8), idx, q, k)
        elif kind == "ivf_pq":
            from .neighbors import ivf_pq

            idx = ivf_pq.build(
                index_params or ivf_pq.IndexParams(
                    n_lists=1024, pq_bits=4, pq_dim=min(64, d), seed=seed), x)
            jax.block_until_ready(idx.list_codes)
            # the caller's k, EXACTLY: the compilation cache is keyed by HLO
            # and k is a static arg of _pq_search, so the old max(k, 40)
            # override left the production k=10 program cold (ADVICE r5
            # medium). Pipelines that also search a refine-candidate width
            # (e.g. k=40 feeding refine to 10) warm that width with a second
            # warmup call.
            searcher = lambda: ivf_pq.search(
                search_params or ivf_pq.SearchParams(
                    n_probes=8, lut_dtype="bfloat16"), idx, q, k)
        else:  # cagra
            from .neighbors import cagra

            idx = cagra.build(index_params or cagra.IndexParams(seed=seed), x)
            jax.block_until_ready(idx.graph)
            searcher = lambda: cagra.search(
                search_params or cagra.SearchParams(itopk_size=32), idx, q, k)
    build_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    with obs_compile.attribution() as search_attr:
        jax.block_until_ready(jax.tree_util.tree_leaves(searcher())[0])
    search_s = time.perf_counter() - t0

    def _phase(wall_s, rec) -> dict:
        return {
            "wall_s": round(wall_s, 2),
            **rec.summary(),
            "program_compile_s": [round(s, 3) for s in rec.program_compile_s],
        }

    attribution = "jax.monitoring"
    if not search_attr.available:  # pragma: no cover - ancient jax
        # timing fallback (ops/_compat.jax_monitoring gate): a second,
        # fully warm search bounds execute time; the cold-warm delta is the
        # compile share of the first. Cache outcomes stay unknown (-1).
        attribution = "timing"
        t0 = time.perf_counter()
        jax.block_until_ready(jax.tree_util.tree_leaves(searcher())[0])
        warm_s = time.perf_counter() - t0
        search_attr.compile_s = max(search_s - warm_s, 0.0)
        search_attr.cache_hits = search_attr.cache_misses = -1
        build_attr.cache_hits = build_attr.cache_misses = -1

    out = {
        # headline walls keep their historical keys (provisioning scripts)
        "build_s": round(build_s, 2), "search_s": round(search_s, 2),
        "cache_dir": cache, "attribution": attribution,
        "build": _phase(build_s, build_attr),
        "search": _phase(search_s, search_attr),
    }
    logger.info(
        "warmup(%s, n=%d, d=%d, k=%d): build %.1fs (%.1fs compile over %d "
        "programs), search %.1fs (%.1fs compile), cache %d hits / %d misses "
        "at %s", kind, n, d, k, build_s, build_attr.compile_s,
        build_attr.programs, search_s, search_attr.compile_s,
        build_attr.cache_hits + search_attr.cache_hits,
        build_attr.cache_misses + search_attr.cache_misses, cache)
    return out
