"""Profiler range annotations.

TPU-native equivalent of the reference's NVTX RAII ranges
(cpp/include/raft/core/nvtx.hpp:95 push_range / common::nvtx::range). On TPU
the profiler story is xprof/Perfetto via :mod:`jax.profiler`; a
``TraceAnnotation`` shows up on the trace timeline exactly where an NVTX range
would in Nsight. Like the reference (compile-gated by RAFT_NVTX), annotation is
zero-cost when disabled — here gated by a module flag rather than a rebuild.
"""

from __future__ import annotations

import contextlib
import functools

import jax

# ``range`` (the reference's name) is intentionally NOT in __all__ so that a
# star-import cannot shadow the builtin; use ``tracing.range`` or ``push_range``.
__all__ = ["push_range", "annotate", "enable", "disable"]

_enabled = True


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


@contextlib.contextmanager
def range(name: str, *args):
    """RAII-style profiler range (reference: common::nvtx::range, nvtx.hpp:139).

    printf-style ``args`` are interpolated into ``name`` lazily, mirroring the
    reference's format-string labels.

    Two sinks, so the range is visible wherever the work lands:

    - ``jax.profiler.TraceAnnotation`` — the HOST timeline (eager phases,
      dispatch); the direct NVTX-range analogue.
    - ``jax.named_scope`` — the name is attached to every op staged while the
      range is open, so xprof's DEVICE timeline (and HLO dumps) carve into
      the same stage names. This is why ``range`` also works *inside* jitted
      functions: there it names the traced ops rather than timing the trace.
    """
    if not _enabled:
        yield
        return
    label = name % args if args else name
    with jax.profiler.TraceAnnotation(label), jax.named_scope(label):
        yield


push_range = range  # non-shadowing alias


def annotate(name: str | None = None):
    """Decorator form: annotate a whole function as a profiler range."""

    def deco(fn):
        label = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            with range(label):
                return fn(*a, **kw)

        return wrapper

    return deco
