"""Virtual-platform forcing shared by tests, benches, and the driver dryrun.

The ambient environment pre-imports jax from sitecustomize and registers a
single-chip TPU backend, so ``JAX_PLATFORMS=cpu`` exported by a script is read
too early to take effect.  The working recipe (used by tests/conftest.py,
bench/ann/run.py and ``__graft_entry__.dryrun_multichip``) is: scrub/append
``--xla_force_host_platform_device_count`` on ``XLA_FLAGS``, then flip the
platform through the config API, which works any time *before* backend
initialization.

Reference analogue: the LocalCUDACluster self-bootstrap in the reference's
raft-dask test conftest (python/raft-dask/raft_dask/test/conftest.py) — the
piece that lets multi-device code paths run without multi-device hardware.
"""

from __future__ import annotations

import contextlib
import os
import re

_COUNT_RE = re.compile(r"--xla_force_host_platform_device_count=\d+")

_ENV_KEYS = ("XLA_FLAGS", "JAX_PLATFORMS")


def force_virtual_cpu(n_devices: int) -> None:
    """Point JAX at an ``n_devices``-device virtual CPU platform.

    Mutates ``XLA_FLAGS``/``JAX_PLATFORMS`` (for subprocesses and for a
    backend that has not been created yet) and flips ``jax_platforms`` via the
    config API (for a process where jax is already imported).  A backend that
    has *already initialized* cannot be switched — callers that need to
    survive that case should fall back to a fresh subprocess.
    """
    flags = _COUNT_RE.sub("", os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={int(n_devices)}"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass  # backend already initialized; caller decides the fallback


@contextlib.contextmanager
def virtual_cpu_env(n_devices: int):
    """``force_virtual_cpu`` with env-var restoration on exit.

    The in-process platform switch is permanent once the backend initializes;
    what this protects is everything *after* the block that reads the
    environment — later subprocesses (e.g. a TPU benchmark) must not inherit
    the CPU pin.
    """
    saved = {k: os.environ.get(k) for k in _ENV_KEYS}
    try:
        force_virtual_cpu(n_devices)
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
