"""Host→device staging helper.

Re-design of raft::make_temporary_device_buffer
(cpp/include/raft/core/temporary_device_buffer.hpp) — a scoped view that
stages host data on device and, for writable buffers, copies back on
release. With unified jax.Array semantics this is a thin context manager:
device placement on entry, optional host write-back on exit.
"""

from __future__ import annotations

import contextlib

import jax
import numpy as np

__all__ = ["temporary_device_buffer"]


@contextlib.contextmanager
def temporary_device_buffer(host_array, writeback: bool = False, device=None):
    """Yield a device-resident jax.Array for ``host_array``; when
    ``writeback`` is True and the caller replaced the staged array via
    ``buf.array = ...``, the final value is copied back into ``host_array``
    (which must be a writable numpy array)."""

    class _Buf:
        def __init__(self, arr):
            self.array = arr

    staged = jax.device_put(jnp_like(host_array), device)
    buf = _Buf(staged)
    try:
        yield buf
    finally:
        if writeback:
            np.copyto(host_array, np.asarray(buf.array))


def jnp_like(x):
    import jax.numpy as jnp

    return jnp.asarray(x)
