"""Cooperative cancellation — the reference's raft::interruptible.

Re-design of cpp/include/raft/core/interruptible.hpp:71 (a per-thread token
whose ``synchronize`` turns stream waits into cancellation points, with
``cancel`` flippable from any thread) and its Python binding
(pylibraft/common/interruptible.pyx, ``cuda_interruptible`` context manager +
SIGINT hook). On TPU, XLA owns execution, so the cancellation points are the
host-side blocking waits: :func:`synchronize` checks the token, blocks until
the arrays are ready, and checks again — a long-running loop that calls it
between jitted steps aborts promptly when another thread calls
:func:`cancel`.
"""

from __future__ import annotations

import contextlib
import signal
import threading

import jax

__all__ = ["InterruptedException", "Token", "get_token", "synchronize", "yield_no_throw",
           "cancel", "interruptible"]


class InterruptedException(RuntimeError):
    """Raised at a cancellation point (ref: raft::interruptible::interrupted_exception)."""


class Token:
    """Per-thread cancellation token (ref: interruptible.hpp:71 — shared
    between the worker, which polls, and any controller, which cancels)."""

    def __init__(self) -> None:
        self._cancelled = threading.Event()

    def cancel(self) -> None:
        """Flip the flag (ref: interruptible::cancel — safe from any thread)."""
        self._cancelled.set()

    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    def check(self) -> None:
        """Cancellation point: raise and clear if cancelled (ref:
        interruptible::yield — the flag resets on throw so the thread is
        reusable afterwards)."""
        if self._cancelled.is_set():
            self._cancelled.clear()
            raise InterruptedException("raft_tpu task cancelled")


_tokens: dict[int, Token] = {}
_tokens_lock = threading.Lock()


def get_token(thread_id: int | None = None) -> Token:
    """The token of the given (default: current) thread — ref:
    interruptible::get_token(). Entries of dead threads are purged on access
    (the reference GCs its store via weak pointers, interruptible.hpp) so a
    recycled thread ident can never observe a stale cancelled token."""
    tid = threading.get_ident() if thread_id is None else thread_id
    with _tokens_lock:
        live = {t.ident for t in threading.enumerate()}
        live.add(tid)  # allow pre-registering a not-yet-seen controller target
        for dead in [t for t in _tokens if t not in live]:
            del _tokens[dead]
        tok = _tokens.get(tid)
        if tok is None:
            tok = _tokens[tid] = Token()
        return tok


def cancel(thread_id: int | None = None) -> None:
    """Cancel the given (default: current) thread's token."""
    get_token(thread_id).cancel()


def synchronize(*arrays) -> None:
    """Cancellable device wait (ref: interruptible::synchronize:83 — the
    stream sync that doubles as a cancellation point)."""
    tok = get_token()
    tok.check()
    if arrays:
        jax.block_until_ready(arrays)
    tok.check()


def yield_no_throw() -> bool:
    """Non-throwing poll (ref: interruptible::yield_no_throw). Returns True
    if the token was cancelled (and clears it)."""
    tok = get_token()
    if tok.cancelled():
        tok._cancelled.clear()
        return True
    return False


@contextlib.contextmanager
def interruptible():
    """Context manager hooking SIGINT to this thread's token — the analogue
    of pylibraft's ``cuda_interruptible`` + ``synchronize`` pairing: Ctrl-C
    inside the block cancels at the next synchronize() instead of tearing
    down the process mid-execution. Only usable from the main thread (signal
    semantics); elsewhere it degrades to a plain token scope."""
    tok = get_token()
    is_main = threading.current_thread() is threading.main_thread()
    prev = None
    if is_main:
        def handler(signum, frame):
            tok.cancel()

        prev = signal.signal(signal.SIGINT, handler)
    try:
        yield tok
    finally:
        if is_main and prev is not None:
            signal.signal(signal.SIGINT, prev)
