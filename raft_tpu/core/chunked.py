"""Out-of-core corpus access: the chunked reader + chunk staging stage.

ROADMAP item 3's build-side half: every builder used to open with
``jnp.asarray(dataset)`` — one device array the size of the corpus — so
BUILD peak, not serve capacity, capped corpus size. This module is the
seam that removes that ceiling:

- :class:`ChunkedReader` wraps any 2-D row-sliceable source (the
  canonical case is an ``np.memmap`` over a corpus file; a plain
  ``np.ndarray`` works too, which is how compaction folds reuse the
  path) and exposes it as fixed-size row chunks. All four
  ``neighbors/*`` builds accept it duck-typed (:func:`is_reader`):
  list fill and PQ residual encoding become per-chunk jitted passes
  that scatter into the sealed list layout incrementally, so the
  device never holds more than the index plus two staged chunks.
- :class:`ChunkStager` is the host→device staging stage between the
  reader and those passes: each chunk uploads from an immutable staged
  copy (mutable-buffer rotation is a use-after-rewrite race under
  async dispatch — see the class docstring) and, when pinned to a
  device, stages through the same donated identity program as the
  serve flush path (:func:`stage_fns` — factored out of
  ``serve/staging.py``), so steady-state staging bytes are CONSTANT
  (~two chunks per side) and chunk N+1's H2D overlaps chunk N's
  assign/encode under jax's async dispatch.
- :func:`take_rows` is the trainset-sampling seam: the coarse trainer
  (``cluster/kmeans_balanced``) gathers its subsample through it, so
  the SAME ``jax.random.choice`` indices hit either a device
  ``jnp.take`` (in-core) or a host fancy-gather on the reader
  (streamed) — the PRNG key chain is identical in both modes, which is
  half of the bit-equality contract (the other half: per-row math is
  chunk-batching-independent; see the streamed extend paths).

Budget pricing lives in ``obs.mem.plan(streamed=True, ...)`` and the
``site="build_stream"`` admission gate each build runs BEFORE the
coarse trainer spends anything.
"""

from __future__ import annotations

import functools

import numpy as np

from .errors import expects

__all__ = ["DEFAULT_CHUNK_ROWS", "ChunkedReader", "ChunkStager",
           "is_reader", "take_rows", "materialize", "converted",
           "device_materialize", "stage_fns"]

# default streaming granule: 64k rows x 128 d x f32 = 32 MiB/chunk —
# two staged chunks stay well inside the default 2 GiB workspace while
# amortizing per-chunk dispatch over enough rows to keep the MXU busy.
# docs/warm_builds.md ("Out-of-core build") carries the sizing rule.
DEFAULT_CHUNK_ROWS = 65536


@functools.cache
def stage_fns():
    """The donated staging program (PR 12's discipline, factored out of
    ``serve/staging.py`` so the build stager and the serve flush path
    share ONE program): the old device slot is an operand whose buffer
    XLA may reuse for the new upload — staging bytes never grow with
    chunk count."""
    import jax
    import jax.numpy as jnp

    return jax.jit(lambda old, new: jnp.where(True, new, old),
                   donate_argnums=(0,))


def is_reader(x) -> bool:
    """Duck-typed chunked-reader check used by every build entry point:
    anything exposing ``chunks()`` / ``take()`` / ``chunk_rows`` streams;
    arrays (numpy, jax, memmap passed bare) take the in-core path."""
    return (hasattr(x, "chunks") and hasattr(x, "take")
            and hasattr(x, "chunk_rows"))


class ChunkedReader:
    """Fixed-size row chunks over a 2-D corpus that need not fit in one
    array (``np.memmap`` canonical — slices are lazy views whose pages
    fault in per chunk; see module docstring)."""

    def __init__(self, source, *, chunk_rows: int = DEFAULT_CHUNK_ROWS):
        expects(hasattr(source, "ndim") and hasattr(source, "shape")
                and hasattr(source, "dtype"),
                "ChunkedReader needs an array-like source (np.memmap, "
                "np.ndarray, ...)")
        expects(source.ndim == 2, "corpus must be (n, d)")
        expects(source.shape[0] > 0 and source.shape[1] > 0,
                "corpus must be non-empty")
        expects(int(chunk_rows) >= 1, "chunk_rows must be >= 1")
        self._src = source
        self.chunk_rows = min(int(chunk_rows), int(source.shape[0]))

    @classmethod
    def from_file(cls, path, *, dtype=None, shape=None,
                  chunk_rows: int = DEFAULT_CHUNK_ROWS,
                  mode: str = "r") -> "ChunkedReader":
        """Open an on-disk corpus without reading it: ``.npy`` files map
        through ``np.load(mmap_mode=)``; raw binary needs ``dtype`` +
        ``shape``."""
        p = str(path)
        if p.endswith(".npy"):
            src = np.load(p, mmap_mode=mode)
        else:
            expects(dtype is not None and shape is not None,
                    "raw corpus files need dtype= and shape=")
            src = np.memmap(p, dtype=np.dtype(dtype), mode=mode,
                            shape=tuple(int(s) for s in shape))
        return cls(src, chunk_rows=chunk_rows)

    # -- array-like surface (what expects()/plan() read) ---------------------
    @property
    def shape(self):
        return tuple(int(s) for s in self._src.shape)

    @property
    def ndim(self) -> int:
        return 2

    @property
    def dtype(self):
        return self._src.dtype

    @property
    def nbytes(self) -> int:
        n, d = self.shape
        return n * d * self._src.dtype.itemsize

    def __len__(self) -> int:
        return self.shape[0]

    # -- streaming surface ----------------------------------------------------
    @property
    def n_chunks(self) -> int:
        return -(-self.shape[0] // self.chunk_rows)

    def chunks(self):
        """Yield ``(start, block)`` in row order; ``block`` is a lazy
        host slice of ``chunk_rows`` rows (the last may be short). No
        device work happens here — the stager owns H2D."""
        n = self.shape[0]
        cr = self.chunk_rows
        for start in range(0, n, cr):
            yield start, self._src[start:start + cr]

    def take(self, idx):
        """Host fancy-gather of the given rows (the trainset-sampling
        seam): touches only the selected pages, returns a fresh host
        array."""
        return np.asarray(self._src[np.asarray(idx)])

    def host_view(self):
        """The raw backing array (memmap or ndarray) — zero-copy; what a
        ``MutableIndex(dataset=reader)`` keeps as its cold row store."""
        return self._src


class _ConvertedReader:
    """A reader view whose ``take``/``materialize`` apply a device-side
    conversion (byte shift, f32 upcast) — how the coarse trainer sees a
    raw-dtype corpus in the build's exact working domain."""

    def __init__(self, reader, convert):
        self._reader = reader
        self._convert = convert
        self.chunk_rows = reader.chunk_rows

    @property
    def shape(self):
        return self._reader.shape

    ndim = 2

    @property
    def dtype(self):
        return self._reader.dtype

    def chunks(self):
        return self._reader.chunks()

    def take(self, idx):
        import jax.numpy as jnp

        return self._convert(jnp.asarray(self._reader.take(np.asarray(idx))))

    def materialize(self):
        import jax.numpy as jnp

        return self._convert(jnp.asarray(np.asarray(self._reader.host_view())))


def converted(reader, convert) -> _ConvertedReader:
    """Wrap ``reader`` so gathered rows come back through ``convert``
    (a device-side fn: raw chunk -> build working domain)."""
    return _ConvertedReader(reader, convert)


def take_rows(x, idx):
    """Gather rows by index with one semantics across both modes: a
    device ``jnp.take`` for arrays, a host gather (one sync on ``idx``,
    then upload) for readers. Per-row values are bit-equal either way —
    gathering commutes with the elementwise ingest conversions."""
    import jax.numpy as jnp

    if is_reader(x):
        return x.take(np.asarray(idx))
    return jnp.take(jnp.asarray(x), idx, axis=0)


def materialize(x):
    """Whole-corpus view: identity for arrays, the converted device
    image for reader views (the degenerate trainset == corpus case)."""
    import jax.numpy as jnp

    if hasattr(x, "materialize"):
        return x.materialize()
    if is_reader(x):
        return jnp.asarray(np.asarray(x.host_view()))
    return jnp.asarray(x)


class ChunkStager:
    """Double-buffered host→device chunk staging (see module docstring).

    Every upload reads from an IMMUTABLE per-chunk staged copy, never
    from a reused mutable buffer: under jax's async dispatch a staged
    array may be read long after ``stage`` returns (CPU zero-copies
    ``device_put``, so the device array aliases the host memory for its
    whole lifetime; other backends DMA-read it until the transfer
    lands), which makes rewriting a rotated buffer a use-after-rewrite
    race — the serve flush path only gets away with its rotation because
    flush-completion tracking bounds the in-flight window. Steady-state
    bytes still sit at ~two chunks per side: jax keeps at most the
    in-flight copy and its successor alive, so chunk N+1's H2D overlaps
    chunk N's assign/encode while chunk N-1 frees. ``device=`` pins
    staging and enables donation through :func:`stage_fns` (constant
    DEVICE staging bytes by construction); the default unpinned mode is
    a plain ``device_put`` whose old chunks free by reference drop. The
    ledger carries both sides under ``build/staging``."""

    def __init__(self, chunk_rows: int, dim: int, dtype, *,
                 kind: str = "build", device=None):
        from ..obs import build as build_metrics
        from ..obs import mem as obs_mem
        from ..obs import metrics

        expects(int(chunk_rows) >= 1 and int(dim) >= 1,
                "stager needs chunk_rows >= 1 and dim >= 1")
        self.chunk_rows = int(chunk_rows)
        self.dim = int(dim)
        self.dtype = np.dtype(dtype)
        self.kind = str(kind)
        self.device = device
        # assembly buffer for short (tail) chunks only: rows land here so
        # the pad tail can be zeroed, then the padded block is copied off
        # like any full chunk
        self._assembly = np.zeros((self.chunk_rows, self.dim), self.dtype)
        self._slot = None
        self._uploads = 0
        self._donation_frees = 0
        # device canonicalization caps at 4 B/elt (f64 host rows land f32)
        self._dev_chunk_bytes = (self.chunk_rows * self.dim
                                 * min(self.dtype.itemsize, 4))
        # host side: the assembly buffer + the in-flight staged copy
        self._mem = obs_mem.account(
            "build/staging", name=self.kind,
            host_bytes=2 * self._assembly.nbytes,
            device_bytes=2 * self._dev_chunk_bytes, owner=self)
        if metrics.enabled():
            build_metrics.ooc_chunk_rows().set(self.chunk_rows,
                                               kind=self.kind)

    @property
    def host_bytes(self) -> int:
        return 2 * self._assembly.nbytes

    def stage(self, block):
        """Copy ``block`` (<= chunk_rows host rows) into a fresh staged
        array (padding a short tail chunk with zeros), start the upload.
        Returns the padded ``(chunk_rows, dim)`` device array — pad rows
        are garbage the per-chunk passes drop (OOB scatter) or slice
        off. The staged copy is handed to jax and never written again
        (see class docstring for why that is load-bearing)."""
        from ..obs import build as build_metrics
        from ..obs import metrics

        import jax

        n = block.shape[0]
        if n == self.chunk_rows:
            # one copy straight off the source pages — memmap slices
            # materialize here, not earlier
            staged = np.array(block)
        else:
            buf = self._assembly
            buf[:n] = block
            buf[n:] = 0
            staged = np.array(buf)
        self._uploads += 1
        if metrics.enabled():
            build_metrics.ooc_staged_bytes().inc(staged.nbytes,
                                                 kind=self.kind)
        if self.device is None:
            dev = jax.device_put(staged)
            self._slot = dev  # latest upload; previous frees by ref drop
            return dev
        old = self._slot
        if old is None:
            dev = jax.device_put(staged, self.device)
        else:
            dev = stage_fns()(old, staged)
            if old.is_deleted():
                self._donation_frees += 1
        self._slot = dev
        return dev

    def stats(self) -> dict:
        return {"uploads": self._uploads,
                "donation_frees": self._donation_frees,
                "host_bytes": self.host_bytes,
                "device_bytes": 2 * self._dev_chunk_bytes,
                "pinned": self.device is not None}

    def release(self) -> None:
        from ..obs import mem as obs_mem

        if self._mem is not None:
            obs_mem.release(self._mem)
            self._mem = None
        self._slot = None


def device_materialize(reader, *, stager: ChunkStager | None = None,
                       kind: str = "build"):
    """Stream a reader into ONE device array of its (canonicalized)
    dtype — for the builds whose index stores the dataset itself
    (brute_force, cagra): the corpus still ends up device-resident, but
    arrives through the staged chunk pipeline instead of one host-side
    ``jnp.asarray`` of the whole corpus (no second full-size host copy,
    and H2D overlaps the concatenation scatters)."""
    from ..obs import build as build_metrics
    from ..obs import metrics

    import jax.numpy as jnp

    n, d = reader.shape
    cr = reader.chunk_rows
    own = stager is None
    if own:
        stager = ChunkStager(cr, d, reader.dtype, kind=kind)
    place = _place_fns()
    dst = jnp.zeros((n, d), jnp.asarray(np.zeros((), reader.dtype)).dtype)
    try:
        for start, block in reader.chunks():
            dev = stager.stage(block)
            n_valid = block.shape[0]
            if n_valid < cr:
                dev = dev[:n_valid]
            dst = place(dst, dev, jnp.int32(start))
            if metrics.enabled():
                build_metrics.ooc_chunks().inc(1, kind=kind,
                                               stage="materialize")
    finally:
        if own:
            stager.release()
    return dst


@functools.cache
def _place_fns():
    import jax
    from jax import lax

    # start rides as a DEVICE scalar so chunk index never enters the
    # executable key — one program per (dst, chunk) shape pair
    return jax.jit(
        lambda dst, chunk, start: lax.dynamic_update_slice(
            dst, chunk, (start, 0)),
        donate_argnums=(0,))
