"""Logging for raft_tpu.

TPU-native equivalent of the reference's spdlog wrapper
(cpp/include/raft/core/logger-ext.hpp:34, logger-macros.hpp:44-95). The
reference supports runtime level/pattern control and a callback sink so Python
can capture logs; here the standard :mod:`logging` module provides all of that
natively, so this module only pins down the logger name, the level vocabulary
(including the TRACE level spdlog has and stdlib lacks) and small helpers.
"""

from __future__ import annotations

import logging
import sys

__all__ = [
    "logger",
    "set_level",
    "basic_config",
    "OFF",
    "CRITICAL",
    "ERROR",
    "WARN",
    "INFO",
    "DEBUG",
    "TRACE",
]

# Level vocabulary mirrors the reference's RAFT_LEVEL_* (logger-macros.hpp).
OFF = logging.CRITICAL + 10
CRITICAL = logging.CRITICAL
ERROR = logging.ERROR
WARN = logging.WARNING
INFO = logging.INFO
DEBUG = logging.DEBUG
TRACE = logging.DEBUG - 5

logging.addLevelName(TRACE, "TRACE")

logger = logging.getLogger("raft_tpu")
logger.addHandler(logging.NullHandler())


def set_level(level: int) -> None:
    """Set the global raft_tpu log level (reference: logger::set_level)."""
    logger.setLevel(level)


# the handler basic_config installed, so repeat calls replace instead of stack
_handler: logging.Handler | None = None

# spdlog-ish default, the reference's "[%L] [%H:%M:%S.%f] %v" spirit in
# stdlib-formatter vocabulary
DEFAULT_PATTERN = "[%(levelname)s] [%(asctime)s] [raft_tpu] %(message)s"


def basic_config(level: int = INFO, pattern: str = DEFAULT_PATTERN,
                 stream=None) -> logging.Logger:
    """One-call formatted stderr logging (reference: logger::set_pattern +
    the callback sink, logger-ext.hpp:34 — there users wire a sink and
    pattern at runtime; here one call replaces hand-built stdlib handlers).

    Installs (or replaces, on repeat calls) a single StreamHandler on the
    ``raft_tpu`` logger with ``pattern`` as a stdlib logging format string,
    sets ``level``, and stops propagation so records are not double-printed
    through the root logger. Returns the logger. Pass a ``stream`` to
    redirect (the callback-sink analogue: any write()-able object works).
    """
    global _handler
    if _handler is not None:
        logger.removeHandler(_handler)
    _handler = logging.StreamHandler(stream or sys.stderr)
    _handler.setFormatter(logging.Formatter(pattern))
    logger.addHandler(_handler)
    logger.setLevel(level)
    logger.propagate = False
    return logger


def trace(msg: str, *args) -> None:
    logger.log(TRACE, msg, *args)
