"""Logging for raft_tpu.

TPU-native equivalent of the reference's spdlog wrapper
(cpp/include/raft/core/logger-ext.hpp:34, logger-macros.hpp:44-95). The
reference supports runtime level/pattern control and a callback sink so Python
can capture logs; here the standard :mod:`logging` module provides all of that
natively, so this module only pins down the logger name, the level vocabulary
(including the TRACE level spdlog has and stdlib lacks) and small helpers.
"""

from __future__ import annotations

import logging

__all__ = [
    "logger",
    "set_level",
    "OFF",
    "CRITICAL",
    "ERROR",
    "WARN",
    "INFO",
    "DEBUG",
    "TRACE",
]

# Level vocabulary mirrors the reference's RAFT_LEVEL_* (logger-macros.hpp).
OFF = logging.CRITICAL + 10
CRITICAL = logging.CRITICAL
ERROR = logging.ERROR
WARN = logging.WARNING
INFO = logging.INFO
DEBUG = logging.DEBUG
TRACE = logging.DEBUG - 5

logging.addLevelName(TRACE, "TRACE")

logger = logging.getLogger("raft_tpu")
logger.addHandler(logging.NullHandler())


def set_level(level: int) -> None:
    """Set the global raft_tpu log level (reference: logger::set_level)."""
    logger.setLevel(level)


def trace(msg: str, *args) -> None:
    logger.log(TRACE, msg, *args)
