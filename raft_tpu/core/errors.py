"""Error handling for raft_tpu.

TPU-native equivalent of the reference's exception/assert layer
(cpp/include/raft/core/error.hpp: RAFT_EXPECTS at :168, RAFT_FAIL at :184).
Host-side validation raises :class:`RaftError`; traced (in-jit) value checks
should use `jax.experimental.checkify` instead, since Python exceptions cannot
depend on traced values.
"""

from __future__ import annotations

__all__ = ["RaftError", "expects", "fail"]


class RaftError(RuntimeError):
    """Base exception for raft_tpu (reference: raft::exception, core/error.hpp:98)."""


def expects(cond: bool, fmt: str, *args) -> None:
    """Host-side precondition check (reference: RAFT_EXPECTS, core/error.hpp:168).

    Raises :class:`RaftError` if ``cond`` is falsy. ``fmt`` may be a printf-style
    format consumed with ``*args`` for message-construction laziness.
    """
    if not cond:
        raise RaftError(fmt % args if args else fmt)


def fail(fmt: str, *args) -> None:
    """Unconditional failure (reference: RAFT_FAIL, core/error.hpp:184)."""
    raise RaftError(fmt % args if args else fmt)
