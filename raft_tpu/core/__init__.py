"""raft_tpu.core — the runtime layer.

TPU-native re-imagining of the reference's L1 core
(cpp/include/raft/core/): resource handle, errors, logging, profiler ranges,
serialization. mdspan/mdarray collapse into ``jax.Array`` (SURVEY.md §7-2);
interruptibility maps to Python's native KeyboardInterrupt + XLA's execution
model rather than a bespoke cancellation token.
"""

from . import operators
from .errors import RaftError, expects, fail
from .interruptible import InterruptedException, cancel, interruptible, synchronize
from .logger import logger, set_level
from .resources import DeviceResources, Resources, default_resources, set_default_resources
from .temporary_buffer import temporary_device_buffer
from .serialize import (
    deserialize_json,
    deserialize_mdspan,
    deserialize_scalar,
    serialize_json,
    serialize_mdspan,
    serialize_scalar,
)
from . import tracing

__all__ = [
    "RaftError",
    "expects",
    "fail",
    "logger",
    "set_level",
    "Resources",
    "DeviceResources",
    "default_resources",
    "set_default_resources",
    "serialize_mdspan",
    "deserialize_mdspan",
    "serialize_scalar",
    "deserialize_scalar",
    "serialize_json",
    "deserialize_json",
    "tracing",
    "InterruptedException",
    "interruptible",
    "synchronize",
    "cancel",
    "temporary_device_buffer",
    "operators",
]
