"""The raft_tpu resource handle.

TPU-native redesign of the reference's resource registry + device handle
(cpp/include/raft/core/resources.hpp:47, device_resources.hpp:60). On CUDA the
handle carries streams, a stream pool, cuBLAS/cuSOLVER/cuSPARSE handles, an RMM
workspace allocator and an optional communicator. Under JAX/XLA almost all of
that dissolves: XLA owns streams and allocation, vendor libraries are the
compiler, and kernels are fused automatically. What meaningfully survives:

- the **device mesh** (multi-chip topology) — the TPU analogue of the handle's
  comms + sub-comms (device_resources.hpp:204-219),
- a **workspace budget** used by memory-aware batching heuristics (the analogue
  of rmm workspace_resource; e.g. brute-force kNN tile sizing, reference
  neighbors/detail/knn_brute_force.cuh:78),
- a default **device** for single-chip placement,
- ``sync()`` — the analogue of ``handle.sync_stream()``.

Every public raft_tpu API takes an optional ``res: Resources`` first argument
(defaulting to a process-global handle) to preserve the reference's calling
convention without burdening simple use.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax

__all__ = ["Resources", "DeviceResources", "default_resources", "set_default_resources"]


@dataclasses.dataclass
class Resources:
    """Resource handle (reference: raft::device_resources, core/device_resources.hpp:60).

    Attributes:
      device: default device for placement; ``None`` = JAX default.
      mesh: ``jax.sharding.Mesh`` for distributed algorithms; ``None`` = single
        device. Plays the role of the handle's communicator slot
        (core/resource/comms.hpp) — distributed entry points read it.
      workspace_bytes: soft budget for temporary distance/score matrices,
        honored by the XLA tiled batching heuristics (reference:
        workspace_resource + chooseTileSize, knn_brute_force.cuh:78 —
        here ``distance.pairwise._choose_tile``, consumed by the
        brute-force/IVF/kmeans scan paths; the chosen tile's implied
        workspace is observable as ``raft_tpu_mem_workspace_bytes``,
        pinned <= this budget by test). The fused Pallas kernels size
        their tiles from VMEM capacity instead and do NOT read it.
      memory_budget_bytes: HARD budget for long-lived device allocations
        (``None`` = unenforced, the default). Checked against the
        :mod:`raft_tpu.obs.mem` ledger at ``build`` / ``serve.publish`` /
        ``stream`` ``upsert`` admission; exceeding it raises
        :class:`raft_tpu.serve.errors.MemoryBudgetError` (an
        ``OverloadedError``) before any state lands. Requires obs enabled
        at gate time — the ledger does not account under
        ``obs.disable()``, so an armed budget there raises ``RaftError``
        instead of silently not enforcing.
      host_budget_bytes: HARD budget for TIERED raw-row stores in host
        RAM (``None`` = unenforced, the default) — the RAM half of the
        beyond-HBM tiering story: a ``storage="tiered"`` index keeps its
        full-precision refine rows in host RAM, and this is the budget
        those rows admit against at store construction, through the same
        :func:`raft_tpu.obs.mem.gate` and with the same whole-or-nothing
        ``MemoryBudgetError`` taxonomy as the device budget. Scope:
        tiered raw-row stores (they dominate host bytes at beyond-HBM
        scale) and the out-of-core streamed build's host peak — staging
        buffers plus the trainset gather off the corpus reader, priced
        by ``obs.mem.plan(streamed=True)`` and refused at
        ``site="build_stream/host"`` before the coarse trainer spends
        anything. The stream layer's smaller host arrays — delta
        memtables, bitsets, id maps — are ledger-visible
        (``raft_tpu_mem_host_bytes``) but not yet gated. Stores placed
        on disk (``TierPolicy.disk_path``) price nothing here — mmap
        pages are disk-backed; so does an ``np.memmap`` corpus a
        ``core.chunked.ChunkedReader`` streams from.
    """

    device: Optional[Any] = None
    mesh: Optional[jax.sharding.Mesh] = None
    workspace_bytes: int = 2 << 30
    memory_budget_bytes: Optional[int] = None
    host_budget_bytes: Optional[int] = None
    # Free-form registry for user extensions — the residue of the reference's
    # type-keyed resource factory map (core/resources.hpp:91-124).
    _registry: dict = dataclasses.field(default_factory=dict, repr=False)

    # -- registry (reference: add_resource_factory / get_resource) -----------
    def set_resource(self, key: str, value: Any) -> None:
        self._registry[key] = value

    def get_resource(self, key: str, default: Any = None) -> Any:
        return self._registry.get(key, default)

    def has_resource(self, key: str) -> bool:
        return key in self._registry

    # -- comms (reference: device_resources::get_comms/set_comms) ------------
    def set_comms(self, comms: Any) -> None:
        self._registry["comms"] = comms

    def get_comms(self) -> Any:
        from .errors import expects

        expects("comms" in self._registry, "communicator was not initialized on this handle")
        return self._registry["comms"]

    @property
    def comms_initialized(self) -> bool:
        return "comms" in self._registry

    # -- placement ------------------------------------------------------------
    def put(self, x):
        """Place an array on this handle's device (host→device staging; the
        analogue of make_temporary_device_buffer, core/temporary_device_buffer.hpp)."""
        if self.device is not None:
            return jax.device_put(x, self.device)
        return jax.device_put(x)

    def sync(self, *arrays) -> None:
        """Block until the given arrays are ready (reference: handle.sync_stream()).

        Pass the arrays whose computation you want to wait on. With no
        arguments this only drains ordered side effects (``jax.effects_barrier``)
        — it does NOT wait for pure computations, so timing code must pass the
        output arrays explicitly.
        """
        if arrays:
            jax.block_until_ready(arrays)
        else:
            jax.effects_barrier()

    @property
    def device_count(self) -> int:
        return self.mesh.size if self.mesh is not None else 1


# Legacy alias, mirroring raft::handle_t (core/handle.hpp).
DeviceResources = Resources

_default: Optional[Resources] = None


def default_resources() -> Resources:
    """Process-global default handle, created lazily."""
    global _default
    if _default is None:
        _default = Resources()
    return _default


def set_default_resources(res: Resources) -> None:
    global _default
    _default = res
