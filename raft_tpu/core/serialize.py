"""Array and index-structure serialization.

TPU-native equivalent of the reference's numpy-format mdspan serialization
(cpp/include/raft/core/serialize.hpp, core/detail/mdspan_numpy_serializer.hpp)
and the scalar serialize helpers used by index serializers
(neighbors/ivf_pq_serialize.cuh:52-110). The on-disk vocabulary is identical —
NumPy ``.npy`` streams — so artifacts are interoperable with numpy tooling.
Index classes serialize as a sequence of scalars + ``.npy`` blocks in one file.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import struct
from typing import Any, BinaryIO

import jax
import numpy as np

__all__ = [
    "serialize_mdspan",
    "deserialize_mdspan",
    "serialize_scalar",
    "deserialize_scalar",
    "serialize_json",
    "deserialize_json",
    "serialize_header",
    "check_header",
    "serialize_tuned",
    "deserialize_tuned",
    "version_number",
    "atomic_write", "fsync_dir",
    "SERIALIZATION_VERSION",
]

# Index-file format version, bumped whenever an index serializer changes its
# stream layout (the reference writes and checks serialization_version for the
# same reason — ivf_flat_serialize.cuh:37,135). A string (not an int) so that
# pre-versioning streams — whose next scalar was a small int — fail the check
# with a clear message instead of being misread.
#   raft_tpu/2: version header added; ivf_flat/ivf_pq carry split_factor.
#   raft_tpu/3: ivf_pq carries pq_split + list_consts (nibble-split pq8).
#   raft_tpu/4: cagra carries seed_pool_hint (measured search autotune).
#   raft_tpu/5: ivf_flat carries data_kind (int8/uint8 list storage).
#   raft_tpu/6: ivf_pq + cagra carry data_kind (int8/uint8 byte datasets).
#   raft_tpu/7: ivf_pq carries list_scales (per-list residual scale
#       normalization, IndexParams.residual_scale_norm).
#   raft_tpu/8: new "stream" section (raft_tpu.stream.MutableIndex — sealed
#       index + delta memtable + tombstones in one file) and a "brute_force"
#       section (the stream wrapper's simplest sealed kind); the
#       ivf_flat/ivf_pq/cagra layouts are unchanged from /7.
#   raft_tpu/9: every index section gains an optional trailing "tuned"
#       record (bool has_tuned + JSON decision, raft_tpu.tune — the pinned
#       operating point rides WITH the index, provenance inline); absent on
#       untuned indexes, skipped cleanly by the /8 layouts.
#   raft_tpu/10: the "stream" section carries wal_seq (the write-ahead-log
#       sequence the snapshot covers — raft_tpu.stream.wal replays only
#       records past it at load); ivf_flat/ivf_pq/cagra/brute_force
#       layouts are unchanged from /9.
#   raft_tpu/11: new "mesh" section — the sharded tier's topology manifest
#       (shard count, topology epoch, per-shard snapshot/WAL names and
#       wal_seq; raft_tpu.stream.ShardedMutableIndex save/load and the
#       reshard commit point). Every other section is unchanged from /10.
#   raft_tpu/12: the "stream" section carries the tier layout — storage
#       policy ("hbm"/"tiered") + the store's residency tier at save time
#       (raft_tpu.stream.tiered), so load() restores placement without
#       re-deciding; /11 files read back as storage="hbm". Every other
#       section is unchanged from /11.
#   raft_tpu/13: ivf_pq carries the quantization-codec record (trailing,
#       after tuned): rotation_kind ("none"/"opq" — the learned rotation is
#       already folded into the serialized rotation matrix), codebook_loss
#       ("l2"/"anisotropic"), fast_scan ("none"/"1bit"/"4bit") + the packed
#       signature tier (list_sig, sig_scales). /12 files read back with the
#       codec defaults (no rotation record, l2 loss, no fast-scan tier);
#       every other section is unchanged from /12.
SERIALIZATION_VERSION = "raft_tpu/13"

# Older versions each tag can still READ (ivf_pq's and cagra's layouts
# changed in raft_tpu/6, ivf_flat's in /5 — bumping the global version
# must not force rebuilds of unchanged formats; loaders branch on the
# returned version where a field was added). "stream"/"brute_force" are new
# in /8, so that is the oldest layout they accept.
_READ_COMPATIBLE: dict[str, frozenset[str]] = {
    "ivf_flat": frozenset({"raft_tpu/2", "raft_tpu/3", "raft_tpu/4",
                           "raft_tpu/5", "raft_tpu/6", "raft_tpu/7",
                           "raft_tpu/8", "raft_tpu/9", "raft_tpu/10",
                           "raft_tpu/11", "raft_tpu/12"}),
    "ivf_pq": frozenset({"raft_tpu/3", "raft_tpu/4", "raft_tpu/5",
                         "raft_tpu/6", "raft_tpu/7", "raft_tpu/8",
                         "raft_tpu/9", "raft_tpu/10", "raft_tpu/11",
                         "raft_tpu/12"}),
    "cagra": frozenset({"raft_tpu/2", "raft_tpu/3", "raft_tpu/4",
                        "raft_tpu/5", "raft_tpu/6", "raft_tpu/7",
                        "raft_tpu/8", "raft_tpu/9", "raft_tpu/10",
                        "raft_tpu/11", "raft_tpu/12"}),
    "stream": frozenset({"raft_tpu/8", "raft_tpu/9", "raft_tpu/10",
                         "raft_tpu/11", "raft_tpu/12"}),
    "brute_force": frozenset({"raft_tpu/8", "raft_tpu/9", "raft_tpu/10",
                              "raft_tpu/11", "raft_tpu/12"}),
    # "mesh" is new in /11 — that is the oldest layout it accepts
    "mesh": frozenset({"raft_tpu/11", "raft_tpu/12"}),
}


def version_number(ver: str) -> int:
    """``"raft_tpu/9" -> 9`` — loaders use ordered comparisons for fields
    added at version N ("present from /9 on") instead of growing excluded
    -version tuples forever."""
    try:
        return int(ver.rsplit("/", 1)[1])
    except (IndexError, ValueError):
        raise ValueError(f"not a raft_tpu format version string: {ver!r}")


def serialize_header(fp: BinaryIO, tag: str) -> None:
    """Write the index-file header: type tag + format version."""
    serialize_scalar(fp, tag)
    serialize_scalar(fp, SERIALIZATION_VERSION)


def check_header(fp: BinaryIO, tag: str) -> str:
    """Read and validate the header, failing with actionable messages.
    Returns the file's version string so loaders can branch on old layouts."""
    from .errors import expects

    got = deserialize_scalar(fp)
    article = "an" if tag[:1] in "aeiou" else "a"
    expects(got == tag, "not %s %s index file (tag=%r)", article, tag, got)
    ver = deserialize_scalar(fp)
    ok = ver == SERIALIZATION_VERSION or ver in _READ_COMPATIBLE.get(tag, ())
    expects(
        ok,
        "unsupported %s index file format %r (this build reads %r) — the file "
        "was written by an incompatible raft_tpu version; rebuild and re-save "
        "the index",
        tag, ver, SERIALIZATION_VERSION,
    )
    return ver


def serialize_mdspan(fp: BinaryIO, arr) -> None:
    """Write an array as a 1-byte dtype marker + .npy stream (reference:
    serialize_mdspan, core/serialize.hpp). bfloat16 — which numpy cannot
    represent natively — travels as a uint16 bit-pattern npy block behind the
    ``B`` marker; everything else is a plain npy block behind ``N``."""
    host = np.asarray(jax.device_get(arr))
    if host.dtype == np.dtype("V2") or str(arr.dtype) == "bfloat16":
        fp.write(b"B")
        np.save(fp, host.view(np.uint16), allow_pickle=False)
    else:
        fp.write(b"N")
        np.save(fp, host, allow_pickle=False)


def deserialize_mdspan(fp: BinaryIO, device=None):
    """Read a marked .npy stream back; returns a host numpy array — bfloat16
    blocks come back as a jax bfloat16-typed array (caller device_puts)."""
    marker = fp.read(1)
    if marker not in (b"N", b"B"):
        raise ValueError(f"bad mdspan marker {marker!r}")
    host = np.load(fp, allow_pickle=False)
    if marker == b"B":
        import jax.numpy as jnp

        host = host.view(jnp.bfloat16.dtype)
    return host if device is None else jax.device_put(host, device)


def serialize_scalar(fp: BinaryIO, value) -> None:
    """Write one scalar (reference: serialize_scalar used across *_serialize.cuh).

    Accepts Python and numpy scalar types (np.int32 shape fields etc. are the
    common case when writing array metadata).
    """
    if isinstance(value, (bool, np.bool_)):
        fp.write(b"b" + struct.pack("<?", bool(value)))
    elif isinstance(value, (int, np.integer)):
        fp.write(b"i" + struct.pack("<q", int(value)))
    elif isinstance(value, (float, np.floating)):
        fp.write(b"f" + struct.pack("<d", float(value)))
    elif isinstance(value, str):
        raw = value.encode()
        fp.write(b"s" + struct.pack("<i", len(raw)) + raw)
    else:
        raise TypeError(f"unsupported scalar type {type(value)}")


def deserialize_scalar(fp: BinaryIO):
    tag = fp.read(1)
    if tag == b"b":
        return struct.unpack("<?", fp.read(1))[0]
    if tag == b"i":
        return struct.unpack("<q", fp.read(8))[0]
    if tag == b"f":
        return struct.unpack("<d", fp.read(8))[0]
    if tag == b"s":
        (n,) = struct.unpack("<i", fp.read(4))
        return fp.read(n).decode()
    raise ValueError(f"bad scalar tag {tag!r}")


def serialize_json(fp: BinaryIO, obj: Any) -> None:
    """Write a small JSON header (used for params dataclasses in index files)."""
    raw = json.dumps(obj).encode()
    fp.write(struct.pack("<i", len(raw)) + raw)


def deserialize_json(fp: BinaryIO) -> Any:
    (n,) = struct.unpack("<i", fp.read(4))
    return json.loads(fp.read(n).decode())


def serialize_tuned(fp: BinaryIO, tuned: dict | None) -> None:
    """Write the optional trailing tuned record (raft_tpu/9): a presence
    bool, then the decision JSON. One helper shared by every index writer
    so the layout cannot drift per kind. Gated on the CURRENT format
    version — a writer pinned to an older version (back-compat tests)
    emits true old-layout bytes."""
    if version_number(SERIALIZATION_VERSION) < 9:
        return
    serialize_scalar(fp, tuned is not None)
    if tuned is not None:
        serialize_json(fp, tuned)


def deserialize_tuned(fp: BinaryIO, ver: str) -> dict | None:
    """Read the tuned record written by :func:`serialize_tuned`; files
    older than raft_tpu/9 have none (returns None — defaults apply)."""
    if version_number(ver) < 9:
        return None
    if not deserialize_scalar(fp):
        return None
    return deserialize_json(fp)


def fsync_dir(dirname: str) -> None:
    """fsync a directory so a just-renamed/created entry survives a
    machine crash (no-op where directories cannot be opened, e.g.
    Windows — there ``os.replace`` is already metadata-atomic)."""
    try:
        fd = os.open(dirname or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


@contextlib.contextmanager
def atomic_write(path: str):
    """Crash-safe snapshot writes: yields a binary file handle onto a
    same-directory temp file, and only on clean exit fsyncs and
    ``os.replace``\\ s it over ``path`` — a crash (or raise) mid-write
    leaves the previous file byte-identical instead of half-overwritten.
    Every index/stream ``save()`` goes through this; the
    ``serialize/atomic-write`` fault point sits between the temp write and
    the rename so tests can prove the crash window
    (:mod:`raft_tpu.testing.faults`)."""
    from ..testing import faults

    tmp = f"{path}.tmp.{os.getpid()}"
    f = open(tmp, "wb")
    try:
        yield f
        f.flush()
        os.fsync(f.fileno())
        f.close()
        # the crash window: temp file complete, rename not yet done — the
        # previous snapshot must still load
        faults.fire("serialize/atomic-write", path=path, tmp=tmp)
        os.replace(tmp, path)
        # the rename itself is only durable once the DIRECTORY entry is on
        # disk — without this a machine crash can surface the old snapshot
        # after a WAL truncation that assumed the new one, losing
        # acknowledged writes (the one ordering the WAL contract forbids)
        fsync_dir(os.path.dirname(os.path.abspath(path)))
    except BaseException:
        if not f.closed:
            f.close()
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
