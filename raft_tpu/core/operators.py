"""Composable operator vocabulary.

Parity surface for the reference's host/device functors
(cpp/include/raft/core/operators.hpp — identity/sq/abs/add/sub/mul/div/min/
max/pow/argmin-style KVP ops and the compose/plug adapters, core/kvp.hpp
KeyValuePair). Under JAX these are plain functions usable inside jit and as
``map_reduce`` arguments; KeyValuePair survives as the (key, value) pair used
by fused 1-NN reductions (distance/fused_nn.py returns exactly this shape).
"""

from __future__ import annotations

import typing

import jax.numpy as jnp

__all__ = [
    "identity_op", "void_op", "sq_op", "abs_op", "cast_op", "key_op", "value_op",
    "add_op", "sub_op", "mul_op", "div_op", "div_checkzero_op", "pow_op",
    "min_op", "max_op", "sqrt_op", "nz_op", "equal_op", "notequal_op",
    "compose_op", "plug_const_op", "KeyValuePair", "argmin_op", "argmax_op",
]


class KeyValuePair(typing.NamedTuple):
    """Reference: raft::KeyValuePair (core/kvp.hpp)."""

    key: typing.Any
    value: typing.Any


def identity_op(x):
    return x


def void_op(*_args):
    return None


def sq_op(x):
    return x * x


def abs_op(x):
    return jnp.abs(x)


def sqrt_op(x):
    return jnp.sqrt(x)


def nz_op(x):
    """1 where nonzero (ref: nz_op)."""
    return jnp.where(x != 0, 1.0, 0.0)


def cast_op(dtype):
    """Reference: cast_op<T> — returns the casting functor."""

    def f(x):
        return jnp.asarray(x).astype(dtype)

    return f


def key_op(kvp: KeyValuePair):
    return kvp.key


def value_op(kvp: KeyValuePair):
    return kvp.value


def add_op(a, b):
    return a + b


def sub_op(a, b):
    return a - b


def mul_op(a, b):
    return a * b


def div_op(a, b):
    return a / b


def div_checkzero_op(a, b):
    """a / b with 0 where b == 0 (ref: div_checkzero_op)."""
    return jnp.where(b == 0, 0.0, a / jnp.where(b == 0, 1.0, b))


def pow_op(a, b):
    return jnp.power(a, b)


def min_op(a, b):
    return jnp.minimum(a, b)


def max_op(a, b):
    return jnp.maximum(a, b)


def equal_op(a, b):
    return a == b


def notequal_op(a, b):
    return a != b


def argmin_op(a: KeyValuePair, b: KeyValuePair) -> KeyValuePair:
    """KVP reduction keeping the smaller value (ref: argmin_op, operators.hpp)."""
    take_a = (a.value < b.value) | ((a.value == b.value) & (a.key <= b.key))
    return KeyValuePair(
        jnp.where(take_a, a.key, b.key), jnp.where(take_a, a.value, b.value)
    )


def argmax_op(a: KeyValuePair, b: KeyValuePair) -> KeyValuePair:
    take_a = (a.value > b.value) | ((a.value == b.value) & (a.key <= b.key))
    return KeyValuePair(
        jnp.where(take_a, a.key, b.key), jnp.where(take_a, a.value, b.value)
    )


def compose_op(*fns):
    """Right-to-left composition (ref: compose_op — outer(inner(...)))."""

    def f(x, *args):
        for fn in reversed(fns[1:]):
            x = fn(x, *args)
            args = ()
        return fns[0](x)

    return f


def plug_const_op(const, op):
    """Bind a constant second operand (ref: plug_const_op)."""

    def f(x):
        return op(x, const)

    return f
