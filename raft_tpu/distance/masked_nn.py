"""Masked L2 nearest neighbor.

Re-design of raft::distance::masked_l2_nn (cpp/include/raft/distance/
masked_nn.cuh; detail/masked_distance_base.cuh, compress_to_bits.cuh).
The reference computes, per row of ``x``, the 1-NN over ``y`` restricted by a
boolean adjacency matrix: ``y`` rows are partitioned into groups (given as
exclusive prefix ends ``group_idxs``) and ``adj[i, g]`` says whether x_i may
match group g. On the GPU this is a tiled fused kernel that skips fully-masked
tiles; on TPU the distance tile is one MXU GEMM and the mask is a fused
select in the epilogue — XLA's fusion makes the skip a bandwidth question, and
the masked argmin is a single f32 row reduction. ``x`` rows are tiled under
lax.map so the (tile, n) score block respects the workspace budget.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.errors import expects
from ..core.resources import Resources, default_resources
from .pairwise import _choose_tile

__all__ = ["masked_l2_nn"]

_f32 = jnp.float32


@functools.partial(jax.jit, static_argnames=("sqrt", "tile"))
def _masked_nn(x, y, adj, group_ends, sqrt: bool, tile: int):
    m, d = x.shape
    n = y.shape[0]
    yf = y.astype(_f32)
    yn = jnp.sum(yf * yf, axis=1)
    # column j belongs to group g(j) = searchsorted(group_ends, j, 'right')
    col_group = jnp.searchsorted(group_ends, jnp.arange(n), side="right")

    num = -(-m // tile)
    pad = num * tile - m
    xp = jnp.pad(x.astype(_f32), ((0, pad), (0, 0))) if pad else x.astype(_f32)
    ap = jnp.pad(adj, ((0, pad), (0, 0))) if pad else adj

    def per_tile(args):
        xb, ab = args  # (tile, d), (tile, G)
        d2 = (
            jnp.sum(xb * xb, axis=1)[:, None]
            + yn[None, :]
            - 2.0
            * lax.dot_general(
                xb, yf, (((1,), (1,)), ((), ())), precision=lax.Precision.HIGHEST,
                preferred_element_type=_f32,
            )
        )
        d2 = jnp.maximum(d2, 0.0)
        if sqrt:
            d2 = jnp.sqrt(d2)
        col_mask = ab[:, col_group]
        masked = jnp.where(col_mask, d2, jnp.inf)
        idx = jnp.argmin(masked, axis=1)
        val = jnp.take_along_axis(masked, idx[:, None], axis=1)[:, 0]
        any_valid = jnp.any(col_mask, axis=1)
        return jnp.where(any_valid, val, jnp.inf), jnp.where(any_valid, idx, -1)

    vals, idxs = lax.map(per_tile, (xp.reshape(num, tile, d), ap.reshape(num, tile, -1)))
    return vals.reshape(num * tile)[:m], idxs.reshape(num * tile)[:m]


def masked_l2_nn(x, y, adj, group_idxs, sqrt: bool = False, res: Resources | None = None):
    """Masked L2 1-nearest-neighbor of each ``x`` row over admissible ``y`` groups.

    Reference: raft::distance::masked_l2_nn (masked_nn.cuh:109-150).

    Parameters
    ----------
    x : (m, d) array. y : (n, d) array.
    adj : (m, num_groups) boolean — whether x_i may match group g.
    group_idxs : (num_groups,) int — *exclusive* end offset of each group in y
        (strictly increasing, last == n), as in the reference.
    sqrt : report sqrt distances.

    Returns ``(distances (m,), indices (m,))`` — index −1 and distance +inf
    where every group is masked out.
    """
    res = res or default_resources()
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    adj = jnp.asarray(adj, bool)
    group_host = np.asarray(group_idxs, np.int64)
    expects(x.ndim == 2 and y.ndim == 2 and x.shape[1] == y.shape[1], "bad x/y shapes")
    expects(adj.shape == (x.shape[0], group_host.shape[0]), "adj must be (m, num_groups)")
    expects(
        group_host.size > 0
        and int(group_host[-1]) == y.shape[0]
        and bool(np.all(np.diff(group_host) > 0))
        and int(group_host[0]) > 0,
        "group_idxs must be strictly increasing exclusive ends with last == n",
    )
    tile = _choose_tile(x.shape[0], y.shape[0], 1, res.workspace_bytes)
    return _masked_nn(x, y, adj, jnp.asarray(group_host, jnp.int32), bool(sqrt), tile)
