"""Masked L2 nearest neighbor.

Re-design of raft::distance::masked_l2_nn (cpp/include/raft/distance/
masked_nn.cuh; detail/masked_distance_base.cuh, compress_to_bits.cuh).
The reference computes, per row of ``x``, the 1-NN over ``y`` restricted by a
boolean adjacency matrix: ``y`` rows are partitioned into groups (given as
exclusive prefix ends ``group_idxs``) and ``adj[i, g]`` says whether x_i may
match group g. On the GPU this is a tiled fused kernel that skips fully-masked
tiles; on TPU the distance matrix is one MXU GEMM and the mask is a fused
select in the epilogue — XLA's fusion makes the skip a bandwidth question, and
the masked argmin is a single f32 row reduction.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..core.errors import expects

__all__ = ["masked_l2_nn"]

_f32 = jnp.float32


@functools.partial(jax.jit, static_argnames=("sqrt",))
def _masked_nn(x, y, adj, group_ends, sqrt: bool):
    xf = x.astype(_f32)
    yf = y.astype(_f32)
    d2 = (
        jnp.sum(xf * xf, axis=1)[:, None]
        + jnp.sum(yf * yf, axis=1)[None, :]
        - 2.0
        * lax.dot_general(
            xf, yf, (((1,), (1,)), ((), ())), precision=lax.Precision.HIGHEST,
            preferred_element_type=_f32,
        )
    )
    d2 = jnp.maximum(d2, 0.0)
    if sqrt:
        d2 = jnp.sqrt(d2)
    # column j belongs to group g(j) = searchsorted(group_ends, j, 'right')
    n = y.shape[0]
    col_group = jnp.searchsorted(group_ends, jnp.arange(n), side="right")
    col_mask = adj[:, col_group]
    masked = jnp.where(col_mask, d2, jnp.inf)
    idx = jnp.argmin(masked, axis=1)
    val = jnp.take_along_axis(masked, idx[:, None], axis=1)[:, 0]
    # rows with no admissible group keep idx = -1 (ref initializes to maxVal/-1)
    any_valid = jnp.any(col_mask, axis=1)
    return jnp.where(any_valid, val, jnp.inf), jnp.where(any_valid, idx, -1)


def masked_l2_nn(x, y, adj, group_idxs, sqrt: bool = False):
    """Masked L2 1-nearest-neighbor of each ``x`` row over admissible ``y`` groups.

    Reference: raft::distance::masked_l2_nn (masked_nn.cuh:109-150).

    Parameters
    ----------
    x : (m, d) array. y : (n, d) array.
    adj : (m, num_groups) boolean — whether x_i may match group g.
    group_idxs : (num_groups,) int — *exclusive* end offset of each group in y
        (monotone, last == n), as in the reference.
    sqrt : report sqrt distances.

    Returns ``(distances (m,), indices (m,))`` — index −1 and distance +inf
    where every group is masked out.
    """
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    adj = jnp.asarray(adj, bool)
    group_idxs = jnp.asarray(group_idxs, jnp.int32)
    expects(x.ndim == 2 and y.ndim == 2 and x.shape[1] == y.shape[1], "bad x/y shapes")
    expects(adj.shape == (x.shape[0], group_idxs.shape[0]), "adj must be (m, num_groups)")
    return _masked_nn(x, y, adj, group_idxs, bool(sqrt))
