"""raft_tpu.distance — pairwise distances and fused nearest-neighbor.

Reference: cpp/include/raft/distance/ (L4) + pylibraft.distance (L6).
"""

from .fused_nn import fused_l2_nn, fused_l2_nn_argmin
from .kernels import KernelParams, KernelType, gram_matrix, kernel_factory
from .masked_nn import masked_l2_nn
from .pairwise import distance, pairwise_distance
from .types import DISTANCE_TYPES, SUPPORTED_DISTANCES, DistanceType, resolve_metric

__all__ = [
    "DistanceType",
    "DISTANCE_TYPES",
    "SUPPORTED_DISTANCES",
    "resolve_metric",
    "pairwise_distance",
    "distance",
    "fused_l2_nn",
    "fused_l2_nn_argmin",
    "masked_l2_nn",
    "KernelType",
    "KernelParams",
    "gram_matrix",
    "kernel_factory",
]
