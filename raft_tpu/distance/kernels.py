"""Gram (kernel) matrices — linear / polynomial / tanh / RBF.

Re-design of the reference's SVM-style kernel stack
(cpp/include/raft/distance/detail/kernels/{gram_matrix.cuh,
kernel_matrices.cuh, kernel_factory.cuh}; public header
cpp/include/raft/distance/kernels.cuh). The reference evaluates a cuBLAS /
cusparse GEMM and then launches an epilogue kernel per kernel type
(polynomial_kernel / tanh_kernel / rbf kernel expansion,
kernel_matrices.cuh). On TPU the GEMM rides the MXU and XLA fuses the
epilogue into the matmul output — so each kernel is one fused expression.

Sparse inputs are the framework's padded :class:`~raft_tpu.sparse.types.CsrMatrix`;
they are densified before the GEMM (the output Gram matrix is dense anyway,
so this bounds memory at O(m·d + m·n) — fine for the SVM-style workloads the
reference targets, whose csr×dense / csr×csr overloads likewise produce a
dense output via cusparse SpMM, gram_matrix.cuh).
"""

from __future__ import annotations

import dataclasses
import enum

import jax.numpy as jnp
from jax import lax

from ..core.errors import expects
from ..sparse.types import CsrMatrix

__all__ = ["KernelType", "KernelParams", "gram_matrix", "kernel_factory"]

_f32 = jnp.float32


class KernelType(enum.Enum):
    """Mirrors raft::distance::kernels::KernelType (distance_types.hpp:88)."""

    LINEAR = "linear"
    POLYNOMIAL = "polynomial"
    RBF = "rbf"
    TANH = "tanh"


@dataclasses.dataclass(frozen=True)
class KernelParams:
    """Mirrors raft::distance::kernels::KernelParams (distance_types.hpp:98)."""

    kernel: KernelType = KernelType.LINEAR
    degree: int = 3
    gamma: float = 1.0
    coef0: float = 0.0


def _as_dense(x):
    if isinstance(x, CsrMatrix):
        return x.todense().astype(_f32)
    return jnp.asarray(x).astype(_f32)


def _mxu_dot(x, y):
    return lax.dot_general(
        x,
        y,
        (((1,), (1,)), ((), ())),
        precision=lax.Precision.HIGHEST,
        preferred_element_type=_f32,
    )


def gram_matrix(params: KernelParams, x, y=None, norm_x=None, norm_y=None):
    """Evaluate the (m, n) Gram matrix K(x_i, y_j).

    Reference: GramMatrixBase::evaluate / {Polynomial,Tanh,RBF}Kernel
    (detail/kernels/kernel_matrices.cuh). ``x``/``y`` may be dense arrays or
    padded CsrMatrix; ``y=None`` means K(x, x). ``norm_x``/``norm_y`` are
    optional precomputed squared L2 row norms for the RBF expansion path
    (the reference's rbf_fin_op receives them the same way).
    """
    xd = _as_dense(x)
    yd = xd if y is None else _as_dense(y)
    expects(xd.ndim == 2 and yd.ndim == 2, "gram inputs must be 2-D")
    expects(xd.shape[1] == yd.shape[1], "feature dims must match")

    dot = _mxu_dot(xd, yd)
    k = params.kernel
    if k == KernelType.LINEAR:
        return dot
    if k == KernelType.POLYNOMIAL:
        # ref: polynomial_kernel — (gain·K + offset)^degree
        return jnp.power(params.gamma * dot + params.coef0, params.degree)
    if k == KernelType.TANH:
        # ref: tanh_kernel — tanh(gain·K + offset)
        return jnp.tanh(params.gamma * dot + params.coef0)
    if k == KernelType.RBF:
        # ref: rbf kernel expansion — exp(-gain·(‖x‖² + ‖y‖² − 2·K))
        nx = jnp.sum(xd * xd, axis=1) if norm_x is None else jnp.asarray(norm_x, _f32)
        ny = (
            nx
            if (y is None and norm_y is None)
            else (jnp.sum(yd * yd, axis=1) if norm_y is None else jnp.asarray(norm_y, _f32))
        )
        d2 = jnp.maximum(nx[:, None] + ny[None, :] - 2.0 * dot, 0.0)
        return jnp.exp(-params.gamma * d2)
    raise ValueError(f"Kernel not implemented: {k}")


def kernel_factory(params: KernelParams):
    """Return ``f(x, y=None) -> K`` for the given params.

    Mirrors KernelFactory::create (detail/kernels/kernel_factory.cuh:29),
    which returns a GramMatrixBase* evaluator object.
    """

    def evaluate(x, y=None, norm_x=None, norm_y=None):
        return gram_matrix(params, x, y, norm_x=norm_x, norm_y=norm_y)

    return evaluate
