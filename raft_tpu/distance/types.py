"""Distance metric vocabulary.

Mirrors the reference enum (cpp/include/raft/distance/distance_types.hpp:23-66,
20 metric values) and the Python name mapping
(python/pylibraft/pylibraft/distance/pairwise_distance.pyx:62-88) so user code
written against pylibraft's metric strings works unchanged.
"""

from __future__ import annotations

import enum

__all__ = ["DistanceType", "DISTANCE_TYPES", "SUPPORTED_DISTANCES", "resolve_metric"]


class DistanceType(enum.IntEnum):
    """Reference: raft::distance::DistanceType (distance_types.hpp:23)."""

    L2Expanded = 0
    L2SqrtExpanded = 1
    CosineExpanded = 2
    L1 = 3
    L2Unexpanded = 4
    L2SqrtUnexpanded = 5
    InnerProduct = 6
    Linf = 7
    Canberra = 8
    LpUnexpanded = 9
    CorrelationExpanded = 10
    JaccardExpanded = 11
    HellingerExpanded = 12
    Haversine = 13
    BrayCurtis = 14
    JensenShannon = 15
    HammingUnexpanded = 16
    KLDivergence = 17
    RusselRaoExpanded = 18
    DiceExpanded = 19
    Precomputed = 100


# Name → enum map, identical strings to pylibraft (pairwise_distance.pyx:62-83).
DISTANCE_TYPES = {
    "l2": DistanceType.L2SqrtUnexpanded,
    "sqeuclidean": DistanceType.L2Unexpanded,
    "euclidean": DistanceType.L2SqrtUnexpanded,
    "l1": DistanceType.L1,
    "cityblock": DistanceType.L1,
    "inner_product": DistanceType.InnerProduct,
    "chebyshev": DistanceType.Linf,
    "canberra": DistanceType.Canberra,
    "cosine": DistanceType.CosineExpanded,
    "lp": DistanceType.LpUnexpanded,
    "correlation": DistanceType.CorrelationExpanded,
    "jaccard": DistanceType.JaccardExpanded,
    "hellinger": DistanceType.HellingerExpanded,
    "haversine": DistanceType.Haversine,
    "braycurtis": DistanceType.BrayCurtis,
    "jensenshannon": DistanceType.JensenShannon,
    "hamming": DistanceType.HammingUnexpanded,
    "kl_divergence": DistanceType.KLDivergence,
    "minkowski": DistanceType.LpUnexpanded,
    "russellrao": DistanceType.RusselRaoExpanded,
    "dice": DistanceType.DiceExpanded,
}

SUPPORTED_DISTANCES = sorted(DISTANCE_TYPES)


def resolve_metric(metric) -> DistanceType:
    """Accept a metric string or DistanceType (reference: DISTANCE_TYPES lookup)."""
    from ..core.errors import RaftError

    if isinstance(metric, DistanceType):
        return metric
    try:
        return DISTANCE_TYPES[str(metric).lower()]
    except KeyError:
        raise RaftError(
            f"metric {metric!r} is not supported; valid metrics: {SUPPORTED_DISTANCES}"
        ) from None
