"""Fused L2 nearest-neighbor (distance + argmin in one pass).

Re-design of the reference's fused_l2_nn (distance/fused_l2_nn-inl.cuh,
detail/fused_l2_nn.cuh) — the k-means assignment hot kernel. On TPU the fusion
is expressed, not hand-written: per X-row-tile, one MXU GEMM produces the
partial scores ``-2·x·yᵀ + ‖y‖²`` and the argmin reduces them before the next
tile materializes, so the full (m, n) matrix never exists in HBM — the same
memory property the CUDA kernel achieves with in-register reduction.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..core.errors import expects
from ..core.resources import Resources, default_resources
from .pairwise import _choose_tile, _dot, _pad_to_tiles, _row_norms_sq

__all__ = ["fused_l2_nn", "fused_l2_nn_argmin"]


@functools.partial(jax.jit, static_argnames=("sqrt", "tile"))
def _fused_l2_nn(x, y, sqrt: bool, tile: int):
    m, d = x.shape
    n = y.shape[0]
    yn2 = _row_norms_sq(y)  # (n,)
    xn2 = _row_norms_sq(x)  # (m,)
    xt, num = _pad_to_tiles(x, tile)

    def body(xb):
        # score_ij = ‖y_j‖² - 2·x_i·y_j ; adding ‖x_i‖² (a per-row constant)
        # later doesn't change the argmin.
        scores = yn2[None, :] - 2.0 * _dot(xb, y.T)  # (tile, n) f32
        idx = jnp.argmin(scores, axis=1).astype(jnp.int32)
        val = jnp.min(scores, axis=1)
        return val, idx

    vals, idxs = lax.map(body, xt)
    vals = vals.reshape(num * tile)[:m] + xn2
    vals = jnp.maximum(vals, 0.0)
    if sqrt:
        vals = jnp.sqrt(vals)
    return vals, idxs.reshape(num * tile)[:m]


def fused_l2_nn(x, y, sqrt: bool = False, res: Resources | None = None):
    """For each row of ``x``, the L2 distance and index of its nearest row of ``y``.

    Reference: raft::distance::fused_l2_nn producing KeyValuePair<idx, dist>
    (fused_l2_nn-inl.cuh). Returns ``(min_distances, argmin_indices)`` with
    float32 distances (squared unless ``sqrt``) and int32 indices.
    """
    res = res or default_resources()
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    expects(x.ndim == 2 and y.ndim == 2, "inputs must be 2-D matrices")
    expects(x.shape[1] == y.shape[1], "feature dims must match")
    # Large candidate sets on TPU: this is exactly the fused kNN kernel with
    # k=1 (scores never reach HBM). Small n (e.g. k-means assignment against
    # ~1k centers) stays on the XLA path where the score block is tiny and
    # the GEMM dominates anyway; the shared gate also keeps small-d inputs
    # (which would mostly multiply lane padding) on the XLA path.
    from ..ops.fused_knn import fused_backend_ok, shapes_eligible

    backend_ok, interpret = fused_backend_ok()
    if backend_ok and shapes_eligible(y.shape[0], y.shape[1], 1):
        from ..ops.fused_knn import fused_knn

        dist, idx = fused_knn(y, x, 1, metric="l2", sqrt=sqrt,
                              interpret=interpret)
        return dist[:, 0], idx[:, 0]
    # Only the (tile, n) score block is live per step (d≈0 in the memory
    # model), so tiles are ~d× larger than the elementwise-metric path's.
    tile = _choose_tile(x.shape[0], y.shape[0], 1, res.workspace_bytes)
    return _fused_l2_nn(x, y, sqrt, tile)


def fused_l2_nn_argmin(x, y, sqrt: bool = False, res: Resources | None = None):
    """Argmin-only variant — the pylibraft surface
    (distance/pairwise_distance.pyx fused_l2_nn_argmin)."""
    return fused_l2_nn(x, y, sqrt=sqrt, res=res)[1]
