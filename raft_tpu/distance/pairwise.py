"""Pairwise distances, TPU-first.

Re-design of the reference's pairwise-distance stack
(cpp/include/raft/distance/distance-inl.cuh:238 pairwise_distance, runtime→
compile-time dispatch at :252-306; tiled kernel
distance/detail/pairwise_distance_base.cuh:69; per-metric functors
distance/detail/distance_ops/*.cuh). On TPU there is no hand-written tiling:

- **Expanded metrics** (L2/cosine/correlation/inner-product/Hellinger/
  Russel-Rao/KL/Jaccard/Dice) decompose into one MXU GEMM plus row statistics
  and a fused epilogue — the same math the reference routes to CUTLASS on SM80
  (detail/pairwise_matrix/dispatch-inl.cuh:98-113), expressed so XLA fuses the
  epilogue into the matmul's output.
- **Unexpanded metrics** (L1/Linf/Canberra/Lp/Bray-Curtis/Jensen-Shannon/
  Hamming/unexpanded-L2) need an elementwise |x-y|-style accumulation. They
  are evaluated per X-row-tile under ``lax.map`` so the (tile, n, d) broadcast
  stays within the workspace budget — the TPU analogue of the reference's
  grid-stride tiling (Contractions_NT, linalg/detail/contractions.cuh:26).

All distances accumulate in float32 regardless of input dtype (bf16 inputs
ride the MXU at full rate with f32 accumulation via preferred_element_type).
"""

from __future__ import annotations

from ..config import auto_convert_output

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

from ..core.errors import expects
from ..core.resources import Resources, default_resources
from ..obs.instrument import dtype_of, instrument, nrows
from .types import DistanceType, resolve_metric

__all__ = ["pairwise_distance", "distance"]

_f32 = jnp.float32

# MXU contraction precision for f32 operands. "float32" is 6-pass bf16
# emulation (bit-accurate f32 products, the reference's cuBLAS-f32
# equivalent); "bfloat16" is the native single-pass MXU mode — ~2^-8
# relative error on products, ~6x the contraction throughput. kNN exposes
# this as `compute=` (ordering, not values, is what matters there).
_PRECISIONS = {
    "float32": lax.Precision.HIGHEST,
    "bfloat16": lax.Precision.DEFAULT,
}


def _dot(x, y, prec=lax.Precision.HIGHEST):
    """MXU inner-product block: (m,d)@(d,n) with f32 accumulation."""
    return lax.dot_general(
        x,
        y,
        (((1,), (0,)), ((), ())),
        precision=prec,
        preferred_element_type=_f32,
    )


def _row_norms_sq(x):
    return jnp.sum(x.astype(_f32) * x.astype(_f32), axis=1)


# ---------------------------------------------------------------------------
# Expanded (GEMM-shaped) metrics. Each returns an (m, n) f32 matrix.
# ---------------------------------------------------------------------------


def _l2_expanded(x, y, sqrt: bool, prec=lax.Precision.HIGHEST):
    # ref: distance_ops/l2_exp.cuh — xn + yn - 2·x·y, clamped at 0 before sqrt.
    d2 = _row_norms_sq(x)[:, None] + _row_norms_sq(y)[None, :] - 2.0 * _dot(x, y.T, prec)
    d2 = jnp.maximum(d2, 0.0)
    return jnp.sqrt(d2) if sqrt else d2


def _cosine(x, y, prec=lax.Precision.HIGHEST):
    # ref: distance_ops/cosine.cuh — 1 - x·y / (‖x‖‖y‖).
    xn = jnp.sqrt(_row_norms_sq(x))
    yn = jnp.sqrt(_row_norms_sq(y))
    return 1.0 - _dot(x, y.T, prec) / (xn[:, None] * yn[None, :])


def _correlation(x, y, prec=lax.Precision.HIGHEST):
    # ref: distance_ops/correlation.cuh — 1 - Pearson r (centered cosine).
    xc = x.astype(_f32) - jnp.mean(x, axis=1, dtype=_f32)[:, None]
    yc = y.astype(_f32) - jnp.mean(y, axis=1, dtype=_f32)[:, None]
    return _cosine(xc, yc, prec)


def _inner_product(x, y, prec=lax.Precision.HIGHEST):
    # ref: distance_ops cover IP via CUTLASS path; raw inner product, not 1-ip.
    return _dot(x, y.T, prec)


def _hellinger(x, y, prec=lax.Precision.HIGHEST):
    # ref: distance_ops/hellinger.cuh — sqrt(max(0, 1 - Σ√(xᵢyᵢ))).
    acc = _dot(jnp.sqrt(x.astype(_f32)), jnp.sqrt(y.astype(_f32)).T, prec)
    return jnp.sqrt(jnp.maximum(1.0 - acc, 0.0))


def _russelrao(x, y, prec=lax.Precision.HIGHEST):
    # ref: distance_ops/russel_rao.cuh — (k - x·y)/k, k = n_features.
    k = x.shape[1]
    return (k - _dot(x, y.T, prec)) / k


def _kl_divergence(x, y, prec=lax.Precision.HIGHEST):
    # ref: distance_ops/kl_divergence.cuh — 0.5·Σ x(log x - log y) with
    # zero-guards: terms with x==0 vanish; log y is treated as 0 where y==0.
    xf = x.astype(_f32)
    yf = y.astype(_f32)
    xlogx = jnp.sum(jnp.where(xf > 0, xf * jnp.log(jnp.where(xf > 0, xf, 1.0)), 0.0), axis=1)
    glog_y = jnp.where(yf > 0, jnp.log(jnp.where(yf > 0, yf, 1.0)), 0.0)
    return 0.5 * (xlogx[:, None] - _dot(x, glog_y.T, prec))


def _jaccard(x, y, prec=lax.Precision.HIGHEST):
    # Binary-set semantics (reference keeps Jaccard in the sparse stack,
    # sparse/distance; provided densely here): 1 - |x∧y| / |x∨y|.
    inter = _dot(x, y.T, prec)
    sx = jnp.sum(x.astype(_f32), axis=1)
    sy = jnp.sum(y.astype(_f32), axis=1)
    union = sx[:, None] + sy[None, :] - inter
    return jnp.where(union > 0, 1.0 - inter / jnp.where(union > 0, union, 1.0), 0.0)


def _dice(x, y, prec=lax.Precision.HIGHEST):
    # Binary-set semantics: 1 - 2|x∧y| / (|x| + |y|).
    inter = _dot(x, y.T, prec)
    sx = jnp.sum(x.astype(_f32), axis=1)
    sy = jnp.sum(y.astype(_f32), axis=1)
    tot = sx[:, None] + sy[None, :]
    return jnp.where(tot > 0, 1.0 - 2.0 * inter / jnp.where(tot > 0, tot, 1.0), 0.0)


# ---------------------------------------------------------------------------
# Unexpanded (elementwise-accumulation) metrics: f(xt, yt) with
# xt: (t, 1, d), yt: (1, n, d) → (t, n).
# ---------------------------------------------------------------------------


def _ew_l1(xt, yt, _):
    return jnp.sum(jnp.abs(xt - yt), axis=-1)


def _ew_l2(sqrt: bool):
    def f(xt, yt, _):
        d2 = jnp.sum(jnp.square(xt - yt), axis=-1)
        return jnp.sqrt(d2) if sqrt else d2

    return f


def _ew_linf(xt, yt, _):
    return jnp.max(jnp.abs(xt - yt), axis=-1)


def _ew_canberra(xt, yt, _):
    # ref: distance_ops/canberra.cuh — Σ|x-y|/(|x|+|y|), 0/0 → 0.
    num = jnp.abs(xt - yt)
    den = jnp.abs(xt) + jnp.abs(yt)
    return jnp.sum(jnp.where(den > 0, num / jnp.where(den > 0, den, 1.0), 0.0), axis=-1)


def _ew_lp(p: float):
    # ref: distance_ops/lp_unexp.cuh — (Σ|x-y|^p)^(1/p).
    def f(xt, yt, _):
        return jnp.power(jnp.sum(jnp.power(jnp.abs(xt - yt), p), axis=-1), 1.0 / p)

    return f


def _ew_braycurtis(xt, yt, _):
    den = jnp.sum(jnp.abs(xt + yt), axis=-1)
    num = jnp.sum(jnp.abs(xt - yt), axis=-1)
    return jnp.where(den > 0, num / jnp.where(den > 0, den, 1.0), 0.0)


def _ew_jensenshannon(xt, yt, _):
    # ref: distance_ops/jensen_shannon.cuh — sqrt(0.5·Σ[x log(x/m) + y log(y/m)]),
    # m = (x+y)/2, zero-guarded.
    m = 0.5 * (xt + yt)
    logm = jnp.where(m > 0, jnp.log(jnp.where(m > 0, m, 1.0)), 0.0)
    lx = jnp.where(xt > 0, jnp.log(jnp.where(xt > 0, xt, 1.0)), 0.0)
    ly = jnp.where(yt > 0, jnp.log(jnp.where(yt > 0, yt, 1.0)), 0.0)
    acc = jnp.sum(-xt * (logm - lx) - yt * (logm - ly), axis=-1)
    return jnp.sqrt(jnp.maximum(0.5 * acc, 0.0))


def _ew_hamming(xt, yt, _):
    # ref: distance_ops/hamming.cuh — mean(xᵢ ≠ yᵢ).
    return jnp.mean((xt != yt).astype(_f32), axis=-1)


def _ew_haversine(xt, yt, _):
    # ref: spatial/knn/detail/haversine_distance.cuh — 2·asin√(sin²Δφ/2 +
    # cosφ₁cosφ₂ sin²Δλ/2) on (lat, lon) radians, d == 2.
    lat1, lon1 = xt[..., 0], xt[..., 1]
    lat2, lon2 = yt[..., 0], yt[..., 1]
    s1 = jnp.sin(0.5 * (lat2 - lat1))
    s2 = jnp.sin(0.5 * (lon2 - lon1))
    h = s1 * s1 + jnp.cos(lat1) * jnp.cos(lat2) * s2 * s2
    return 2.0 * jnp.arcsin(jnp.sqrt(jnp.clip(h, 0.0, 1.0)))


def _choose_tile(m: int, n: int, d: int, budget_bytes: int) -> int:
    """Memory-aware X-row tile size — the TPU analogue of the reference's
    chooseTileSize (knn_brute_force.cuh:78). ``d`` is the broadcast depth:
    the feature dim for (tile, n, d) elementwise metrics, or ~0 for
    GEMM-shaped paths that only materialize a (tile, n) score matrix."""
    per_row = max(n * (d + 2) * 4, 1)
    tile = max(min(budget_bytes // per_row, m), 8)
    # round to the f32 sublane multiple so padding stays layout-friendly
    return int(min(m, max(8, (tile // 8) * 8)))


def _pad_to_tiles(x, tile: int):
    """Pad rows up to a tile multiple and reshape to (num_tiles, tile, d)."""
    m, d = x.shape
    num = -(-m // tile)
    pad = num * tile - m
    xp = jnp.pad(x, ((0, pad), (0, 0))) if pad else x
    return xp.reshape(num, tile, d), num


def _tiled_rows(x, y, fn, tile: int):
    """Evaluate fn over X row tiles sequentially (lax.map ≡ grid-stride loop)."""
    m, _ = x.shape
    n = y.shape[0]
    xt, num = _pad_to_tiles(x, tile)
    yb = y[None, :, :]
    out = lax.map(lambda xb: fn(xb[:, None, :].astype(_f32), yb.astype(_f32), None), xt)
    return out.reshape(num * tile, n)[:m]


@functools.partial(jax.jit, static_argnames=("metric", "metric_arg", "tile", "compute"))
def _pairwise(x, y, metric: DistanceType, metric_arg: float, tile: int,
              compute: str = "float32"):
    prec = _PRECISIONS[compute]
    if metric == DistanceType.L2Expanded:
        return _l2_expanded(x, y, sqrt=False, prec=prec)
    if metric == DistanceType.L2SqrtExpanded:
        return _l2_expanded(x, y, sqrt=True, prec=prec)
    if metric == DistanceType.CosineExpanded:
        return _cosine(x, y, prec)
    if metric == DistanceType.CorrelationExpanded:
        return _correlation(x, y, prec)
    if metric == DistanceType.InnerProduct:
        return _inner_product(x, y, prec)
    if metric == DistanceType.HellingerExpanded:
        return _hellinger(x, y, prec)
    if metric == DistanceType.RusselRaoExpanded:
        return _russelrao(x, y, prec)
    if metric == DistanceType.KLDivergence:
        return _kl_divergence(x, y, prec)
    if metric == DistanceType.JaccardExpanded:
        return _jaccard(x, y, prec)
    if metric == DistanceType.DiceExpanded:
        return _dice(x, y, prec)

    ew = {
        DistanceType.L1: _ew_l1,
        DistanceType.L2Unexpanded: _ew_l2(False),
        DistanceType.L2SqrtUnexpanded: _ew_l2(True),
        DistanceType.Linf: _ew_linf,
        DistanceType.Canberra: _ew_canberra,
        DistanceType.LpUnexpanded: _ew_lp(metric_arg),
        DistanceType.BrayCurtis: _ew_braycurtis,
        DistanceType.JensenShannon: _ew_jensenshannon,
        DistanceType.HammingUnexpanded: _ew_hamming,
        DistanceType.Haversine: _ew_haversine,
    }[metric]
    return _tiled_rows(x, y, ew, tile)


@instrument(
    "distance.pairwise_distance",
    items=lambda a, kw: nrows(a[0] if a else kw["x"]),
    labels=lambda a, kw: {
        "metric": str(a[2] if len(a) > 2 else kw.get("metric", "euclidean")),
        "dtype": dtype_of(a[0] if a else kw["x"]),
    },
)
@auto_convert_output
def pairwise_distance(x, y=None, metric="euclidean", metric_arg: float = 2.0,
                      compute: str = "float32", res: Resources | None = None):
    """Compute all-pairs distances between the rows of ``x`` and ``y``.

    Reference: raft::distance::pairwise_distance (distance-inl.cuh:238) and the
    pylibraft wrapper (distance/pairwise_distance.pyx:93). Accepts numpy or JAX
    arrays; ``y=None`` means self-distance. Returns an (m, n) float32 JAX array.

    Parameters mirror pylibraft: ``metric`` is a string from
    :data:`SUPPORTED_DISTANCES` or a :class:`DistanceType`; ``metric_arg`` is
    the Minkowski ``p``. ``compute`` selects the MXU contraction mode for the
    GEMM-shaped metrics (L2/cosine/correlation/inner-product): "float32"
    (default, bit-accurate products) or "bfloat16" (single-pass MXU, ~6x the
    contraction throughput, ~2^-8 relative error on the dot term).
    """
    res = res or default_resources()
    mt = resolve_metric(metric)
    x = jnp.asarray(x)
    y = x if y is None else jnp.asarray(y)
    expects(x.ndim == 2 and y.ndim == 2, "inputs must be 2-D matrices")
    expects(
        x.shape[1] == y.shape[1],
        "feature dims must match: %d vs %d",
        x.shape[1],
        y.shape[1],
    )
    if mt == DistanceType.Haversine:
        expects(x.shape[1] == 2, "haversine requires (lat, lon) inputs with d == 2")
    expects(compute in _PRECISIONS, "compute must be 'float32' or 'bfloat16', got %r", compute)
    tile = _choose_tile(x.shape[0], y.shape[0], x.shape[1], res.workspace_bytes)
    return _pairwise(x, y, mt, float(metric_arg), tile, compute)


# pylibraft exposes the same call as `distance(...)` (pairwise_distance.pyx:93).
distance = pairwise_distance
