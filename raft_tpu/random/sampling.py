"""Permutation and sampling.

Reference: cpp/include/raft/random/permute.cuh and
random/sample_without_replacement.cuh (weighted reservoir-free variant).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.errors import expects
from .rng import as_key

__all__ = ["permute", "sample_without_replacement", "excess_subsample"]


def permute(rng, x):
    """Random row permutation; returns (permuted_rows, permutation_indices)
    (reference: random/permute.cuh)."""
    x = jnp.asarray(x)
    perm = jax.random.permutation(as_key(rng), x.shape[0])
    return jnp.take(x, perm, axis=0), perm.astype(jnp.int32)


def sample_without_replacement(rng, n_population: int, n_samples: int, weights=None):
    """Draw distinct indices, optionally weighted (reference:
    random/sample_without_replacement.cuh — Gumbel-top-k style on TPU)."""
    expects(n_samples <= n_population, "cannot sample %d from %d", n_samples, n_population)
    key = as_key(rng)
    if weights is None:
        return jax.random.permutation(key, n_population)[:n_samples].astype(jnp.int32)
    w = jnp.maximum(jnp.asarray(weights, jnp.float32), 0.0)
    # Gumbel-top-k = weighted sampling without replacement in one vector op.
    g = jax.random.gumbel(key, (n_population,)) + jnp.log(jnp.maximum(w, 1e-30))
    return jax.lax.top_k(g, n_samples)[1].astype(jnp.int32)


def excess_subsample(rng, n_population: int, n_samples: int):
    """Uniform subsample of row ids, sorted ascending — the dataset-subsetting
    helper IVF builds use (reference: random/detail/rng_impl.hpp usage in
    neighbors/detail/ivf_pq_build.cuh)."""
    idx = sample_without_replacement(rng, n_population, n_samples)
    return jnp.sort(idx)
