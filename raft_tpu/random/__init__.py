"""raft_tpu.random — RNG, distributions, synthetic data, sampling, R-MAT.

Reference: cpp/include/raft/random/ (L3, P9).
"""

from .datagen import make_blobs, make_regression, multi_variable_gaussian
from .rmat import rmat, rmat_rectangular_gen
from .rng import (
    RngState,
    as_key,
    bernoulli,
    discrete,
    exponential,
    gumbel,
    laplace,
    logistic,
    lognormal,
    normal,
    rayleigh,
    scaled_bernoulli,
    uniform,
    uniform_int,
)
from .sampling import excess_subsample, permute, sample_without_replacement

__all__ = [
    "RngState",
    "as_key",
    "uniform",
    "uniform_int",
    "normal",
    "lognormal",
    "gumbel",
    "logistic",
    "exponential",
    "rayleigh",
    "laplace",
    "bernoulli",
    "scaled_bernoulli",
    "discrete",
    "make_blobs",
    "make_regression",
    "multi_variable_gaussian",
    "permute",
    "sample_without_replacement",
    "excess_subsample",
    "rmat",
    "rmat_rectangular_gen",
]
