"""Random number generation.

Re-design of the reference's device RNG (cpp/include/raft/random/rng.cuh,
rng_state.hpp:28-32 GeneratorType{Philox,PCG,...}). The counter-based design
goal — reproducible, order-independent streams — is native to JAX
(threefry); per SURVEY.md §2.3 we keep the *API* (RngState + distribution
fillers), not the generator internals. ``RngState(seed)`` carries a JAX PRNG
key and hands out independent subkeys per call, so repeated calls draw fresh
values exactly like the reference's advancing state.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

__all__ = [
    "RngState",
    "as_key",
    "uniform",
    "uniform_int",
    "normal",
    "lognormal",
    "gumbel",
    "logistic",
    "exponential",
    "rayleigh",
    "laplace",
    "bernoulli",
    "scaled_bernoulli",
    "discrete",
]


@dataclasses.dataclass
class RngState:
    """Mutable RNG stream (reference: raft::random::RngState, rng_state.hpp).

    Each distribution call consumes one subkey, so successive calls are
    independent — mirroring the reference's advancing counter.
    """

    seed: int = 0

    def __post_init__(self):
        self._key = jax.random.key(self.seed)

    def next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def advance(self, n: int = 1) -> None:
        for _ in range(n):
            self._key, _ = jax.random.split(self._key)


def as_key(rng):
    """Accept an RngState, an int seed, or a raw JAX key."""
    if isinstance(rng, RngState):
        return rng.next_key()
    if isinstance(rng, int):
        return jax.random.key(rng)
    return rng


def uniform(rng, shape, low=0.0, high=1.0, dtype=jnp.float32):
    """Reference: rng.cuh uniform()."""
    return jax.random.uniform(as_key(rng), shape, dtype=dtype, minval=low, maxval=high)


def uniform_int(rng, shape, low, high, dtype=jnp.int32):
    return jax.random.randint(as_key(rng), shape, low, high, dtype=dtype)


def normal(rng, shape, mu=0.0, sigma=1.0, dtype=jnp.float32):
    return mu + sigma * jax.random.normal(as_key(rng), shape, dtype=dtype)


def lognormal(rng, shape, mu=0.0, sigma=1.0, dtype=jnp.float32):
    return jnp.exp(normal(rng, shape, mu, sigma, dtype))


def gumbel(rng, shape, mu=0.0, beta=1.0, dtype=jnp.float32):
    return mu + beta * jax.random.gumbel(as_key(rng), shape, dtype=dtype)


def logistic(rng, shape, mu=0.0, scale=1.0, dtype=jnp.float32):
    return mu + scale * jax.random.logistic(as_key(rng), shape, dtype=dtype)


def exponential(rng, shape, lam=1.0, dtype=jnp.float32):
    return jax.random.exponential(as_key(rng), shape, dtype=dtype) / lam


def rayleigh(rng, shape, sigma=1.0, dtype=jnp.float32):
    u = jax.random.uniform(as_key(rng), shape, dtype=dtype, minval=jnp.finfo(dtype).tiny)
    return sigma * jnp.sqrt(-2.0 * jnp.log(u))


def laplace(rng, shape, mu=0.0, scale=1.0, dtype=jnp.float32):
    return mu + scale * jax.random.laplace(as_key(rng), shape, dtype=dtype)


def bernoulli(rng, shape, prob=0.5):
    return jax.random.bernoulli(as_key(rng), prob, shape)


def scaled_bernoulli(rng, shape, prob=0.5, scale=1.0, dtype=jnp.float32):
    """Reference: rng.cuh scaled_bernoulli — ±scale with probability prob."""
    b = jax.random.bernoulli(as_key(rng), prob, shape)
    return jnp.where(b, scale, -scale).astype(dtype)


def discrete(rng, shape, weights):
    """Sample indices proportional to weights (reference: rng.cuh discrete)."""
    logits = jnp.log(jnp.maximum(jnp.asarray(weights, jnp.float32), 1e-30))
    return jax.random.categorical(as_key(rng), logits, shape=shape).astype(jnp.int32)
