"""Synthetic dataset generators.

Re-design of the reference's generators (cpp/include/raft/random/make_blobs.cuh,
make_regression.cuh, multi_variable_gaussian.cuh — the latter using cusolver
potrf; here `jnp.linalg.cholesky`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.errors import expects
from .rng import as_key

__all__ = ["make_blobs", "make_regression", "multi_variable_gaussian"]


def make_blobs(
    n_samples: int,
    n_features: int,
    n_clusters: int = 3,
    cluster_std: float = 1.0,
    centers=None,
    center_box=(-10.0, 10.0),
    shuffle: bool = True,
    seed=0,
    dtype=jnp.float32,
):
    """Gaussian-blob clusters (reference: random/make_blobs.cuh).

    Returns ``(X (n_samples, n_features), labels (n_samples,) int32)``.
    ``centers`` may be a precomputed (n_clusters, n_features) array.
    """
    key = as_key(seed)
    kc, kl, kn, ks = jax.random.split(key, 4)
    if centers is None:
        centers = jax.random.uniform(
            kc, (n_clusters, n_features), dtype=dtype, minval=center_box[0], maxval=center_box[1]
        )
    else:
        centers = jnp.asarray(centers, dtype=dtype)
        n_clusters = centers.shape[0]
    labels = jax.random.randint(kl, (n_samples,), 0, n_clusters, dtype=jnp.int32)
    noise = jax.random.normal(kn, (n_samples, n_features), dtype=dtype) * cluster_std
    x = jnp.take(centers, labels, axis=0) + noise
    if shuffle:
        perm = jax.random.permutation(ks, n_samples)
        x, labels = x[perm], labels[perm]
    return x, labels


def make_regression(
    n_samples: int,
    n_features: int,
    n_informative: int | None = None,
    n_targets: int = 1,
    bias: float = 0.0,
    noise: float = 0.0,
    shuffle: bool = True,
    seed=0,
    dtype=jnp.float32,
):
    """Linear-model regression data (reference: random/make_regression.cuh).

    Returns ``(X, y, coef)`` with ``y = X @ coef + bias + N(0, noise)``.
    """
    n_informative = n_features if n_informative is None else min(n_informative, n_features)
    key = as_key(seed)
    kx, kw, kn, ks = jax.random.split(key, 4)
    x = jax.random.normal(kx, (n_samples, n_features), dtype=dtype)
    coef = jnp.zeros((n_features, n_targets), dtype=dtype)
    w = 100.0 * jax.random.uniform(kw, (n_informative, n_targets), dtype=dtype)
    coef = coef.at[:n_informative].set(w)
    y = x @ coef + bias
    if noise > 0:
        y = y + noise * jax.random.normal(kn, y.shape, dtype=dtype)
    if shuffle:
        perm = jax.random.permutation(ks, n_samples)
        x, y = x[perm], y[perm]
    return x, jnp.squeeze(y, axis=1) if n_targets == 1 else y, coef


def multi_variable_gaussian(rng, mean, cov, n_samples: int, dtype=jnp.float32):
    """Samples from N(mean, cov) via Cholesky (reference:
    random/multi_variable_gaussian.cuh, cusolver potrf path)."""
    mean = jnp.asarray(mean, dtype=dtype)
    cov = jnp.asarray(cov, dtype=dtype)
    expects(cov.shape == (mean.shape[0], mean.shape[0]), "cov must be (d, d)")
    chol = jnp.linalg.cholesky(cov + 1e-6 * jnp.eye(cov.shape[0], dtype=dtype))
    z = jax.random.normal(as_key(rng), (n_samples, mean.shape[0]), dtype=dtype)
    return mean[None, :] + z @ chol.T
