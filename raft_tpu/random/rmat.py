"""R-MAT rectangular graph generator.

Re-design of the reference's rmat_rectangular_generator
(cpp/include/raft/random/rmat_rectangular_generator.cuh; pylibraft binding
random/rmat_rectangular_generator.pyx). Each edge's source/destination bits
are chosen level-by-level from quadrant probabilities theta = (a, b, c, d);
on TPU all edges and all levels vectorize into one (n_edges, scale) draw —
no per-edge loop.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.errors import expects
from .rng import as_key

__all__ = ["rmat_rectangular_gen", "rmat"]


def rmat_rectangular_gen(rng, theta, r_scale: int, c_scale: int, n_edges: int):
    """Generate R-MAT edges.

    ``theta``: (4,) quadrant probabilities (a, b, c, d) used at every level, or
    (max_scale, 4) per-level probabilities — both reference-supported layouts.
    Returns ``(src (n_edges,), dst (n_edges,))`` int32 with src < 2**r_scale,
    dst < 2**c_scale.
    """
    theta = jnp.asarray(theta, jnp.float32)
    max_scale = max(r_scale, c_scale)
    expects(0 < max_scale <= 31, "scales must be in [1, 31] for int32 vertex ids")
    if theta.ndim == 1:
        expects(theta.shape[0] == 4, "flat theta must have 4 entries")
        theta = jnp.tile(theta[None, :], (max_scale, 1))
    expects(theta.shape == (max_scale, 4), "theta must be (max_scale, 4)")
    theta = theta / jnp.sum(theta, axis=1, keepdims=True)

    key = as_key(rng)
    u = jax.random.uniform(key, (n_edges, max_scale))
    # cumulative quadrant thresholds per level: [a, a+b, a+b+c]
    cum = jnp.cumsum(theta, axis=1)  # (L, 4)
    q = (u[:, :, None] >= cum[None, :, :3]).sum(-1)  # (n_edges, L) in {0,1,2,3}
    src_bit = (q >> 1) & 1  # quadrant c/d -> lower half of rows? (b=1 sets col bit)
    dst_bit = q & 1

    # Levels beyond a side's scale contribute no bit to that side (rectangular
    # adjacency: extra levels only subdivide the larger dimension).
    lv = jnp.arange(max_scale)
    src_w = jnp.where(lv < r_scale, 1 << jnp.maximum(r_scale - 1 - lv, 0), 0)
    dst_w = jnp.where(lv < c_scale, 1 << jnp.maximum(c_scale - 1 - lv, 0), 0)
    src = jnp.sum(src_bit * src_w[None, :], axis=1).astype(jnp.int32)
    dst = jnp.sum(dst_bit * dst_w[None, :], axis=1).astype(jnp.int32)
    return src, dst


# pylibraft exposes the camel-free short name
rmat = rmat_rectangular_gen
