"""The sweep engine: drive the search pipeline over a knob grid, measure
recall-vs-QPS, choose, and record why.

One :func:`sweep` call is one decision: it runs every grid point through
the real search path (the same entry points the serving tier dispatches),
measures recall against exact ground truth and best-of-N wall-clock QPS,
emits each trial as structured obs events (``raft_tpu_tune_*``), and
returns a :class:`~.decisions.Decision` whose evidence holds the full
trial table and the measured frontier.

The choice rule is the ANN-Benchmarks frontier read: among trials meeting
the recall target, take the QPS argmax; if none meet it, take the recall
argmax (and say so in the evidence). ``recall_target="default"`` anchors
the target to the FIRST grid point's measured recall — the grid's head is
by convention the incumbent hand-picked operating point, so the chosen
point then matches or beats the incumbent on both axes by construction
(the incumbent is itself a feasible candidate). That is the acceptance
contract ROADMAP item 5 set: ``auto`` must never lose to a hand-picked
point that is in its own search space.

:func:`sweep_select_k` is the prim-level twin for the parked wide-select
column threshold: it measures ``lax.top_k`` vs the streaming Pallas
selector at explicit (rows, cols, k) shapes. On a backend where the Pallas
arm is ineligible (CPU mesh), the decision records exactly that — the
"needs hardware" question becomes a recorded measurement either way, and
the TPU run just overwrites the entry with real numbers.
"""

from __future__ import annotations

import functools
import time

from ..core.errors import expects
from ..obs import metrics
from .decisions import (Decision, DecisionLog, family_of, kind_of,
                        shape_family)

__all__ = ["Trial", "sweep", "sweep_select_k", "default_grid", "smoke_grid",
           "funnel_grid"]


@functools.lru_cache(maxsize=None)
def _trials_total():
    return metrics.counter(
        "raft_tpu_tune_trials_total",
        "autotune sweep trials measured, by index kind and shape family")


@functools.lru_cache(maxsize=None)
def _trial_seconds():
    return metrics.histogram(
        "raft_tpu_tune_trial_seconds",
        "wall seconds per sweep trial (warm + timed repeats)",
        unit="seconds")


@functools.lru_cache(maxsize=None)
def _frontier_points():
    return metrics.gauge(
        "raft_tpu_tune_frontier_points",
        "points on the measured recall-vs-QPS frontier of the last sweep")


@functools.lru_cache(maxsize=None)
def _chosen_over_default():
    return metrics.gauge(
        "raft_tpu_tune_chosen_qps_over_default",
        "chosen operating point's QPS over the grid-head (default) point's")


class Trial(dict):
    """One measured grid point: ``{"params", "recall", "qps", "wall_s"}``
    (+ ``"error"`` for arms that could not run, e.g. a Pallas impl off its
    backend). A dict subclass so evidence serializes as plain JSON."""

    @property
    def ok(self) -> bool:
        return "error" not in self


# Default grids. The HEAD of each grid is the incumbent hand-picked
# operating point from BASELINE's tables (ivf_pq pq4+refine4 at p8, cagra
# itopk=32, ivf_flat p8) so recall_target="default" anchors to it.
_GRIDS = {
    "ivf_flat": [{"n_probes": p} for p in (8, 4, 16, 32)],
    "ivf_pq": [
        {"n_probes": 8, "refine_ratio": 4},
        {"n_probes": 4, "refine_ratio": 4},
        {"n_probes": 16, "refine_ratio": 4},
        {"n_probes": 8, "refine_ratio": 1},
        {"n_probes": 8, "refine_ratio": 8},
        {"n_probes": 16, "refine_ratio": 8},
        {"n_probes": 32, "refine_ratio": 4},
    ],
    "cagra": [
        {"itopk_size": 32},
        {"itopk_size": 64},
        {"itopk_size": 96},
        {"itopk_size": 32, "search_width": 2},
    ],
    "brute_force": [{}],
}


def default_grid(kind: str) -> list[dict]:
    """The per-kind default sweep grid (head = the incumbent operating
    point). Callers pass their own ``grid=`` to widen it; decisions record
    whichever grid actually ran."""
    expects(kind in _GRIDS, "no default grid for kind %r (one of %s)",
            kind, ", ".join(sorted(_GRIDS)))
    return [dict(g) for g in _GRIDS[kind]]


def smoke_grid(kind: str) -> list[dict]:
    """A 3-point budget grid (head kept) for CI smokes and the bench
    ``--tune-smoke`` row — proves the measure→choose→record loop without
    the full grid's wall clock."""
    return default_grid(kind)[:3]


def funnel_grid(widths=(4, 8, 16), refine_ratios=(1, 4)) -> list[dict]:
    """The quantization-funnel sweep grid for an IVF-PQ index built with a
    ``fast_scan`` tier: the two funnel widths as first-class knobs —
    ``funnel_widen`` (binary tier → PQ rerank pool, per probed chunk) and
    ``refine_ratio`` (PQ → exact refine pool). HEAD is the classic scan
    (``funnel_widen=1``, bit-identical to a no-tier index), so
    ``recall_target="default"`` anchors the funnel's recall to the classic
    operating point — a funnel pin only wins by holding that anchor at
    better QPS/bytes (docs/tuning.md "Quantization funnel")."""
    grid = [{"n_probes": 8, "funnel_widen": 1, "refine_ratio": 4}]
    for rr in refine_ratios:
        for w in widths:
            grid.append({"n_probes": 8, "funnel_widen": int(w),
                         "refine_ratio": int(rr)})
    return grid


def _ground_truth(dataset, queries, k: int, metric="sqeuclidean"):
    import numpy as np

    from ..neighbors.brute_force import knn

    _, ids = knn(dataset, queries, k, metric=metric)
    return np.asarray(ids)


def _recall(ids, gt) -> float:
    import numpy as np

    ids, gt = np.asarray(ids), np.asarray(gt)
    kk = gt.shape[1]
    return float(np.mean([len(set(ids[r, :kk].tolist())
                              & set(gt[r].tolist())) / kk
                          for r in range(gt.shape[0])]))


def _measure(fn, queries, repeats: int):
    """Warm once, then best-of-``repeats`` wall time, host-materialized
    (the bench harness protocol — async dispatch reports fantasy QPS)."""
    import jax
    import numpy as np

    out = fn(queries)
    np.asarray(jax.tree_util.tree_leaves(out)[0])
    best = float("inf")
    for _ in range(max(repeats, 1)):
        t0 = time.perf_counter()
        out = fn(queries)
        np.asarray(jax.tree_util.tree_leaves(out)[0])
        best = min(best, time.perf_counter() - t0)
    return float(queries.shape[0]) / best, out


def _frontier(trials: list[Trial]) -> list[int]:
    """Indices of the non-dominated (recall, qps) points, by descending
    QPS — the measured operating frontier the evidence records."""
    ok = [(i, t) for i, t in enumerate(trials) if t.ok]
    ok.sort(key=lambda it: (-it[1]["qps"], -it[1]["recall"]))
    out, best_recall = [], -1.0
    for i, t in ok:
        if t["recall"] > best_recall:
            out.append(i)
            best_recall = t["recall"]
    return sorted(out)


def sweep(index, queries, *, k: int = 10, dataset=None, gt=None,
          recall_target="default", grid: list[dict] | None = None,
          base_params=None, repeats: int = 3, log: DecisionLog | None = None,
          attach: bool = False) -> Decision:
    """Measure a knob grid on one built index and pin the winner.

    ``dataset`` supplies the exact-ground-truth rows and the refine pool
    for ``refine_ratio`` trials (CAGRA indexes fall back to their own
    stored dataset); pass precomputed ``gt`` (rows, k) ids to skip the
    brute-force pass. ``recall_target`` is a float, or ``"default"`` to
    anchor at the grid head's measured recall (see module doc).
    ``base_params`` seeds the non-swept SearchParams fields. ``log`` adds
    the decision to a :class:`DecisionLog`; ``attach=True`` also pins it
    onto the index (``index.tuned``, persisted by ``save``).
    """
    import jax
    import numpy as np

    from .apply import attach as _attach
    from .apply import search_fn as _search_fn

    kind = kind_of(index)
    dtype = getattr(index, "data_kind", "float32")
    dtype = dtype if dtype in ("int8", "uint8") else "float32"
    queries = np.asarray(queries)
    expects(queries.ndim == 2, "queries must be (rows, d)")
    if dataset is None and kind == "cagra":
        dataset = index.dataset
    # keyed AFTER dataset resolution: the scale-skew classifier (the
    # heavytail discriminator) needs raw rows for PQ indexes
    family = family_of(index, dataset)
    if gt is None:
        expects(dataset is not None,
                "sweep needs exact ground truth: pass dataset= (the indexed "
                "rows) or precomputed gt= (rows, k) ids")
        # ground truth in the INDEX's metric — recall against L2 neighbors
        # would silently mis-score an inner-product sweep
        gt = _ground_truth(dataset, queries, k,
                           metric=getattr(index, "metric", "sqeuclidean"))
    gt = np.asarray(gt)
    expects(gt.shape[0] == queries.shape[0],
            "gt rows (%d) must match queries rows (%d)", gt.shape[0],
            queries.shape[0])
    grid = [dict(g) for g in (grid if grid is not None else
                              default_grid(kind))]
    expects(len(grid) >= 1, "sweep grid is empty")

    trials: list[Trial] = []
    for params in grid:
        t0 = time.perf_counter()
        try:
            fn = _search_fn(index, params, dataset=dataset,
                            base_params=base_params)
            qps, out = _measure(lambda q: fn(q, k), queries, repeats)
            rec = _recall(np.asarray(out[1]), gt)
            trials.append(Trial(params=dict(params), recall=round(rec, 4),
                                qps=round(qps, 1),
                                wall_s=round(time.perf_counter() - t0, 3)))
        except Exception as e:
            # an arm that cannot run on this backend/shape is evidence,
            # not a failure: the decision records WHY it was not chosen
            trials.append(Trial(params=dict(params),
                                error=f"{type(e).__name__}: {str(e)[:160]}",
                                wall_s=round(time.perf_counter() - t0, 3)))
        if metrics.enabled():
            _trials_total().inc(1, kind=kind, family=family)
            _trial_seconds().observe(trials[-1]["wall_s"], kind=kind)

    ok = [t for t in trials if t.ok]
    expects(bool(ok), "every sweep trial failed; first error: %s",
            trials[0].get("error"))
    default_trial = trials[0] if trials[0].ok else ok[0]
    if recall_target == "default":
        target = default_trial["recall"]
    else:
        target = float(recall_target)
    feasible = [t for t in ok if t["recall"] >= target]
    met = bool(feasible)
    chosen = (max(feasible, key=lambda t: t["qps"]) if met
              else max(ok, key=lambda t: t["recall"]))
    frontier = _frontier(trials)
    ratio = (chosen["qps"] / default_trial["qps"]
             if default_trial["qps"] else 0.0)
    if metrics.enabled():
        _frontier_points().set(len(frontier), kind=kind, family=family)
        _chosen_over_default().set(round(ratio, 3), kind=kind, family=family)

    decision = Decision(
        kind=kind, dtype=dtype, family=family, params=dict(chosen["params"]),
        evidence={
            "recall_target": round(target, 4), "target_met": met,
            "k": int(k), "n": int(getattr(index, "size", 0) or 0),
            "dim": int(getattr(index, "dim", queries.shape[1])),
            "queries": int(queries.shape[0]), "repeats": int(repeats),
            "backend": jax.default_backend(),
            "trials": [dict(t) for t in trials],
            "frontier": frontier,
            "default_params": dict(default_trial["params"]),
            "default_recall": default_trial["recall"],
            "default_qps": default_trial["qps"],
            "chosen_recall": chosen["recall"], "chosen_qps": chosen["qps"],
            "chosen_qps_over_default": round(ratio, 3),
        })
    if log is not None:
        log.add(decision)
    if attach:
        _attach(index, decision)
    return decision


# -- the parked wide-select threshold ---------------------------------------

def sweep_select_k(*, rows: int = 256, cols=(32768, 65536, 131072),
                   ks=(10, 128), repeats: int = 3,
                   log: DecisionLog | None = None) -> Decision:
    """Measure ``lax.top_k`` vs the streaming Pallas selector over explicit
    (rows, cols, k) shapes and pin the wide-dispatch column threshold.

    The chosen ``wide_cols_min`` is the smallest measured column width at
    which the Pallas arm won for EVERY measured k (conservative: a
    threshold must not regress any k it gates). Where the Pallas arm is
    ineligible (non-TPU backend, k over the cap), the trial records the
    reason and the shipped 65536 default is kept — the decision log then
    says "unmeasured on this backend" in so many words, which is the whole
    point: the next TPU run replaces the guess with numbers.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..matrix.select_k import (SELECT_K_DISPATCH_MAX_K, select_k_impl,
                                   wide_cols_threshold)

    backend = jax.default_backend()
    trials: list[Trial] = []
    win_cols: dict[int, set] = {int(kk): set() for kk in ks}
    for n in cols:
        key = jax.random.key(int(n))
        vals = jax.random.uniform(key, (int(rows), int(n)), jnp.float32)
        jax.block_until_ready(vals)
        for kk in ks:
            arm_qps = {}
            for impl in ("xla", "pallas"):
                t0 = time.perf_counter()
                if impl == "pallas" and (backend != "tpu"
                                         or kk > SELECT_K_DISPATCH_MAX_K):
                    trials.append(Trial(
                        params={"impl": impl, "cols": int(n), "k": int(kk)},
                        error=f"ineligible: backend={backend}, k={kk} "
                              f"(cap {SELECT_K_DISPATCH_MAX_K})",
                        wall_s=0.0))
                    # ineligible arms still count as measured trials —
                    # sweep() counts its failed arms the same way, and the
                    # scrape must match the evidence's trial count
                    if metrics.enabled():
                        _trials_total().inc(1, kind="select_k",
                                            family="wide")
                        _trial_seconds().observe(0.0, kind="select_k")
                    continue
                try:
                    fn = jax.jit(functools.partial(
                        select_k_impl, in_idx=None, k=int(kk),
                        select_min=True, impl=impl))
                    qps, _ = _measure(fn, vals, repeats)
                    arm_qps[impl] = qps
                    trials.append(Trial(
                        params={"impl": impl, "cols": int(n), "k": int(kk)},
                        recall=1.0, qps=round(qps, 1),
                        wall_s=round(time.perf_counter() - t0, 3)))
                except Exception as e:
                    trials.append(Trial(
                        params={"impl": impl, "cols": int(n), "k": int(kk)},
                        error=f"{type(e).__name__}: {str(e)[:160]}",
                        wall_s=round(time.perf_counter() - t0, 3)))
                if metrics.enabled():
                    _trials_total().inc(1, kind="select_k", family="wide")
                    _trial_seconds().observe(trials[-1]["wall_s"],
                                             kind="select_k")
            if arm_qps.get("pallas", 0.0) > arm_qps.get("xla", float("inf")):
                win_cols[int(kk)].add(int(n))

    # smallest col width that wins for every k, with every wider measured
    # width also winning (a non-monotone win is noise, not a threshold)
    current = wide_cols_threshold()
    chosen = current
    measured = bool(any(t.ok and t["params"]["impl"] == "pallas"
                        for t in trials))
    if measured:
        for n in sorted(int(c) for c in cols):
            if all(all(w in win_cols[int(kk)]
                       for w in sorted(int(c) for c in cols) if w >= n)
                   for kk in ks):
                chosen = n
                break
    decision = Decision(
        kind="select_k", dtype="float32", family="wide",
        params={"wide_cols_min": int(chosen)},
        evidence={
            "backend": backend, "rows": int(rows),
            "cols": [int(c) for c in cols], "ks": [int(kk) for kk in ks],
            "repeats": int(repeats), "pallas_measured": measured,
            "previous_threshold": int(current),
            "trials": [dict(t) for t in trials],
        })
    # no frontier/ratio gauges here: a threshold sweep has no recall-vs-QPS
    # frontier, and filler values would contradict the catalogued semantics
    if log is not None:
        log.add(decision)
    return decision
