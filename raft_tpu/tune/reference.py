"""The committed reference sweep: deterministic families, reproducible pins.

``cpu_mesh_decisions()`` rebuilds a fixed set of dataset families on the
8-device virtual CPU mesh (the tests' hardware), sweeps each through
:func:`~.sweep.sweep`, and returns the :class:`~.decisions.DecisionLog`
that is committed at the repo root as ``TUNE_rXX.json`` (``bench/
tune_sweep.py --cpu-mesh`` writes it; r08 is the first). Everything here
is seeded jax.random on CPU, so recall numbers are bit-stable across runs
of the same code — which is what lets ``tests/test_tune.py`` drift-pin the
artifact: rebuild a family, re-measure the chosen and default operating
points, and fail if the measured recall moved past tolerance. QPS is NOT
pinned (wall clock on a shared CPU is noise); the choice rule's guarantee
— chosen matches-or-beats the grid-head hand-picked point at
equal-or-better recall — is asserted from the artifact's own numbers.

Families (scaled for CI wall clock; the TPU driver runs the same shapes
at bench scale):

- ``ivf_flat_bal`` / ``ivf_pq_bal`` — isotropic clustered rows, the bench
  harness's distribution (gaussian blobs, full-dimensional residuals).
- ``ivf_pq_skew`` — Zipf-populated clusters (the heavytail signature from
  BASELINE round 5, where operating points measurably did not transfer):
  keyed to a DIFFERENT family by the list-size CV classifier, so its pin
  never leaks onto balanced data.
- ``cagra_bal`` — the graph index on the isotropic set.
- ``select_k`` — the wide-select column-threshold prim sweep (on CPU the
  Pallas arm records "ineligible"; the TPU run replaces the entry).
"""

from __future__ import annotations

import functools

from ..core.errors import expects
from .decisions import DecisionLog
from .sweep import default_grid, sweep, sweep_select_k

__all__ = ["FAMILY_NAMES", "build_family", "run_family",
           "cpu_mesh_decisions", "ROUND"]

ROUND = "r08"

FAMILY_NAMES = ("ivf_flat_bal", "ivf_pq_bal", "ivf_pq_skew", "cagra_bal",
                "select_k")

# one shared small-scale config so the drift test and the artifact
# generator cannot diverge
_SCALE = {
    "ivf": dict(n=12_000, d=64, ncl=256, n_lists=64, m=512, k=10),
    "cagra": dict(n=4_096, d=48, ncl=64, m=256, k=10),
}


def _clustered(n, d, m, ncl, seed, heavytail=False):
    """Gaussian-blob rows + queries (the bench generator's distribution).
    ``heavytail`` draws per-cluster residual SCALES from a lognormal
    (sigma 1.0) — the BASELINE round-5 family whose operating points did
    not transfer (one global quantizer spans orders of magnitude of
    residual norm); detected by the tune scale-skew classifier."""
    import jax
    import jax.numpy as jnp

    kc, ks, kl, kn, kql, kqn = jax.random.split(jax.random.key(seed), 6)
    centers = jax.random.uniform(kc, (ncl, d), jnp.float32) * 10.0
    scales = (0.5 * jnp.exp(jax.random.normal(ks, (ncl,)))
              if heavytail else 0.5 * jnp.ones((ncl,), jnp.float32))
    labels = jax.random.randint(kl, (n,), 0, ncl)
    qlabels = jax.random.randint(kql, (m,), 0, ncl)
    x = centers[labels] + scales[labels, None] * jax.random.normal(kn, (n, d))
    q = (centers[qlabels]
         + scales[qlabels, None] * jax.random.normal(kqn, (m, d)))
    jax.block_until_ready((x, q))
    return x, q


@functools.lru_cache(maxsize=None)
def _ivf_dataset(skew: bool):
    c = _SCALE["ivf"]
    return _clustered(c["n"], c["d"], c["m"], c["ncl"],
                      seed=29 if skew else 23, heavytail=skew)


def build_family(name: str) -> dict:
    """Build one reference family: returns ``{index, queries, dataset,
    grid, k, sweep_kwargs}`` — the exact inputs :func:`run_family` sweeps,
    exposed so the drift test can re-measure single operating points
    without paying a full sweep."""
    expects(name in FAMILY_NAMES, "unknown reference family %r (one of %s)",
            name, ", ".join(FAMILY_NAMES))
    if name == "select_k":
        return {"sweep_kwargs": dict(rows=64, cols=(32768, 65536),
                                     ks=(10, 128))}
    if name == "cagra_bal":
        from ..neighbors import cagra

        c = _SCALE["cagra"]
        x, q = _clustered(c["n"], c["d"], c["m"], c["ncl"], seed=31)
        idx = cagra.build(cagra.IndexParams(seed=0), x)
        grid = [{"itopk_size": 32}, {"itopk_size": 16}, {"itopk_size": 64}]
        return {"index": idx, "queries": q, "dataset": x, "grid": grid,
                "k": c["k"]}
    c = _SCALE["ivf"]
    skew = name.endswith("_skew")
    x, q = _ivf_dataset(skew)
    if name.startswith("ivf_flat"):
        from ..neighbors import ivf_flat

        idx = ivf_flat.build(
            ivf_flat.IndexParams(n_lists=c["n_lists"], seed=0), x)
        grid = default_grid("ivf_flat")
    else:
        from ..neighbors import ivf_pq

        idx = ivf_pq.build(
            ivf_pq.IndexParams(n_lists=c["n_lists"], pq_bits=4,
                               pq_dim=c["d"] // 2, seed=0), x)
        grid = default_grid("ivf_pq")
    return {"index": idx, "queries": q, "dataset": x, "grid": grid,
            "k": c["k"]}


def run_family(name: str, log: DecisionLog | None = None,
               repeats: int = 2):
    """Sweep one reference family into ``log`` (created if None); returns
    the Decision."""
    fam = build_family(name)
    if name == "select_k":
        return sweep_select_k(log=log, repeats=repeats,
                              **fam["sweep_kwargs"])
    return sweep(fam["index"], fam["queries"], k=fam["k"],
                 dataset=fam["dataset"], grid=fam["grid"],
                 recall_target="default", repeats=repeats, log=log)


def cpu_mesh_decisions(names=FAMILY_NAMES, repeats: int = 2) -> DecisionLog:
    """Run every reference family; returns the artifact-ready log."""
    import jax

    log = DecisionLog(meta={
        "round": ROUND,
        "backend": jax.default_backend(),
        "devices": len(jax.devices()),
        "scale": {k: dict(v) for k, v in _SCALE.items()},
        "note": "CPU-mesh reference sweep (bench/tune_sweep.py --cpu-mesh);"
                " recall values are drift-pinned by tests/test_tune.py, QPS"
                " is environment-local. The TPU driver overwrites entries"
                " at bench scale.",
    })
    for name in names:
        run_family(name, log=log, repeats=repeats)
    return log
