"""raft_tpu.tune — the obs-driven autotuner: measure, calibrate, pin.

The reference compiles its dispatch heuristics in as constants (the
select_radix vs warpsort cutoff table, ``detail/select_k-inl.cuh:46``;
fixed ``n_probes`` defaults) and this repo accumulated the same debt as
parked conservative guesses: the wide-k select 65536-column threshold, the
CAGRA build-chunk select A/B "waiting on a TPU run", the hop-merge impl
choice, and per-dataset-family ``probes/itopk/refine_ratio`` — which
BASELINE round 5 proved do NOT transfer across families (heavytail 0.31 vs
0.82 recall at the same operating point).

This package closes those decisions the ANN-Benchmarks way (Aumüller et
al., 2017): an operating point is only meaningful as a measured point on a
recall-vs-QPS frontier, so every choice here is a recorded measurement —
the Google-Wide-Profiling pattern (Ren et al., IEEE Micro 2010) of
always-on observation feeding optimization decisions, applied at library
scale. Measure (``sweep`` drives the search pipeline over a param grid,
emitting ``raft_tpu_tune_*`` obs events per trial), calibrate (the chosen
point is the QPS argmax meeting the recall target, with the full trial
evidence kept inline), pin (the :class:`DecisionLog` persists per
``(index kind, dtype, shape family)`` — in a JSON artifact, and in the
index file itself via the raft_tpu/9 ``tuned`` section) — with a drift
test re-measuring the committed artifact, exactly as the calibrated
seed-pool estimator did (BASELINE round 5).

Surface:

- :mod:`.decisions` — :class:`Decision` / :class:`DecisionLog`,
  :func:`shape_family` / :func:`family_of` (the keying rule).
- :mod:`.sweep` — :func:`sweep` (recall-vs-QPS trials over one index),
  :func:`sweep_select_k` (the select-impl × column-width prim sweep).
- :mod:`.apply` — :func:`tuned_search_params` / :func:`make_searcher`
  (decision → SearchParams / serving hook), :func:`attach` (pin onto an
  index, persisted by save/load), :func:`apply_global` (process-wide
  dispatch thresholds, e.g. the wide-select column cutoff).

``serve.publish(name, index, tuned=log)`` applies a decision at publish
time alongside ``warm_data=``; the registry's warm ladder then covers the
tuned programs, so applying a decision never introduces a cold compile on
the hot path (asserted via obs compile attribution). See docs/tuning.md.
"""

from . import reference
from .apply import (apply_global, attach, make_searcher, resolve,
                    tuned_search_params)
from .decisions import Decision, DecisionLog, family_of, kind_of, shape_family
from .sweep import (Trial, default_grid, funnel_grid, smoke_grid, sweep,
                    sweep_select_k)

__all__ = [
    "Decision", "DecisionLog", "shape_family", "family_of", "kind_of",
    "Trial", "sweep", "sweep_select_k", "default_grid", "smoke_grid",
    "funnel_grid",
    "tuned_search_params", "make_searcher", "attach", "resolve",
    "apply_global", "reference",
]
