"""The decision log: pinned operating points with their evidence inline.

A *decision* is one pinned set of search-time knobs for one ``(index kind,
query dtype, shape family)`` key, carrying the measurement that justified
it. BASELINE round 5's negative result is the design constraint: operating
points do NOT transfer across dataset families (the heavytail set needed a
different probes/refine point than the isotropic set at 0.31 vs 0.82
recall), so decisions are keyed by family, never globally.

**Shape family** is a coarse, deterministic bucketing — decisions must be
reusable across rebuilds of "the same kind of index", so the key uses
magnitudes, not exact shapes:

- row count bucketed to its nearest decade (``10k``/``100k``/``1m``/...),
- dimensionality bucketed to its nearest power of two (``d64``/``d128``),
- a balance class read off the built index itself: ``skew`` when an IVF
  index's list-size coefficient of variation exceeds
  :data:`SKEW_CV_THRESHOLD` (the heavytail signature — population skew is
  exactly what broke transfer), ``clump`` when a CAGRA build measured
  local-mode structure (``seed_pool_hint > 0``), ``bal`` otherwise.

The log serializes to a human-auditable JSON artifact (``TUNE_rXX.json``
at the repo root is the committed CPU-mesh reference, drift-pinned by
``tests/test_tune.py``) and each entry also rides inside the index file it
was pinned to (the raft_tpu/9 ``tuned`` section), so a loaded index
carries its own provenance.
"""

from __future__ import annotations

import dataclasses
import json
import math

from ..core.errors import expects

__all__ = [
    "Decision", "DecisionLog", "shape_family", "family_of", "kind_of",
    "local_scale_cv", "list_size_cv",
    "SCALE_CV_THRESHOLD", "SKEW_CV_THRESHOLD",
]

# Skew classifiers, calibrated on the CPU mesh (tune.reference families).
# Per-LIST statistics do NOT work here: the balanced k-means trainer
# actively equalizes both list populations (split cap) and per-list
# variance (centers chase high-variance regions), which was measured to
# wash the heavytail signature out of any list-level stat. So:
#
# - Local-SCALE CV (std/mean of nearest-neighbor radii over a row
#   subsample, index-independent): the BASELINE-r5 heavytail signature —
#   lognormal per-cluster residual scales (what collapsed IVF-PQ recall
#   0.31 vs 0.82 and made operating points non-transferable) spread local
#   densities over orders of magnitude. Measured 0.43 on the isotropic
#   reference family vs 1.54 on the lognormal one; 0.75 splits with wide
#   margin on both sides.
# - List-SIZE CV (std/mean over non-empty lists): population skew that
#   survived balancing (e.g. extend()-grown indexes); threshold 1.0 (the
#   balanced trainer leaves ~0.5 even on isotropic data at small scale).
SCALE_CV_THRESHOLD = 0.75
SKEW_CV_THRESHOLD = 1.0

_KINDS = ("brute_force", "ivf_flat", "ivf_pq", "cagra", "select_k")


@dataclasses.dataclass(frozen=True)
class Decision:
    """One pinned operating point + its evidence.

    ``params`` is the applied knob set (plain JSON scalars — e.g.
    ``{"n_probes": 8, "refine_ratio": 4}``); ``evidence`` is the
    measurement that chose it (recall target, every trial's params/recall/
    QPS, the chosen-vs-default deltas, backend, shapes). The evidence
    travels WITH the decision — a pinned constant whose provenance is a
    commit message is exactly the debt this module exists to retire.
    """

    kind: str
    dtype: str
    family: str
    params: dict
    evidence: dict = dataclasses.field(default_factory=dict)

    @property
    def key(self) -> str:
        return f"{self.kind}/{self.dtype}/{self.family}"

    def to_dict(self) -> dict:
        return {"kind": self.kind, "dtype": self.dtype,
                "family": self.family, "params": dict(self.params),
                "evidence": dict(self.evidence)}

    @classmethod
    def from_dict(cls, d: dict) -> "Decision":
        expects(isinstance(d, dict) and "kind" in d and "params" in d,
                "not a decision dict (need at least kind+params): %r",
                type(d).__name__)
        return cls(kind=d["kind"], dtype=d.get("dtype", "float32"),
                   family=d.get("family", "any"),
                   params=dict(d["params"]),
                   evidence=dict(d.get("evidence", {})))


def _n_bucket(n: int) -> str:
    """Nearest-decade row-count label: 12_000 → "10k", 800_000 → "1m"."""
    expects(n >= 1, "row count must be positive, got %d", n)
    e = int(round(math.log10(max(n, 1))))
    if e <= 3:
        return "1k"
    for exp, label in ((4, "10k"), (5, "100k"), (6, "1m"), (7, "10m"),
                       (8, "100m")):
        if e == exp:
            return label
    return "1b"


def _d_bucket(d: int) -> str:
    expects(d >= 1, "dim must be positive, got %d", d)
    return f"d{2 ** int(round(math.log2(max(d, 1))))}"


def shape_family(n: int, d: int, balance: str = "bal") -> str:
    """The family key string for (rows, dim, balance class) — e.g.
    ``"10k-d64-bal"``. ``balance`` ∈ bal/skew/clump (see module doc)."""
    expects(balance in ("bal", "skew", "clump"),
            "balance must be 'bal', 'skew' or 'clump', got %r", balance)
    return f"{_n_bucket(int(n))}-{_d_bucket(int(d))}-{balance}"


def kind_of(index) -> str:
    """Index object → decision kind string (duck-typed, so tune never
    imports the neighbors modules at module scope)."""
    name = type(index).__name__
    table = {"BruteForce": "brute_force", "IvfFlatIndex": "ivf_flat",
             "IvfPqIndex": "ivf_pq", "CagraIndex": "cagra"}
    expects(name in table, "no tune support for index type %r "
            "(expected BruteForce, IvfFlatIndex, IvfPqIndex or CagraIndex)",
            name)
    return table[name]


def list_size_cv(list_sizes) -> float:
    import jax
    import numpy as np

    sizes = np.asarray(jax.device_get(list_sizes)).astype(np.float64)
    sizes = sizes[sizes > 0]
    if sizes.size == 0 or sizes.mean() == 0:
        return 0.0
    return float(sizes.std() / sizes.mean())


def local_scale_cv(dataset, sample: int = 1024) -> float:
    """CV of nearest-neighbor radii over a deterministic row subsample
    (one (sample, sample) GEMM on host — cheap at any scale, and
    independent of how any index balanced its lists). The measured
    heavytail discriminator: lognormal per-cluster residual scales read
    ~1.5, isotropic clustered data ~0.4 (see SCALE_CV_THRESHOLD). Public:
    :class:`raft_tpu.obs.quality.DriftDetector` re-runs this classifier
    ONLINE — on canary query samples and compaction-time corpus stats —
    to detect the live distribution leaving a pinned decision's family."""
    import jax
    import numpy as np

    x = np.asarray(jax.device_get(dataset)).astype(np.float64)
    step = max(x.shape[0] // int(sample), 1)
    x = x[::step][:sample]
    if x.shape[0] < 8:
        return 0.0
    sq = (x * x).sum(1)
    d2 = sq[:, None] + sq[None, :] - 2.0 * (x @ x.T)
    np.fill_diagonal(d2, np.inf)
    nn = np.sqrt(np.maximum(d2.min(1), 0.0))
    if nn.mean() == 0:
        return 0.0
    return float(nn.std() / nn.mean())


def family_of(index, dataset=None) -> str:
    """Measure the family key off a built index: row count and dim from
    the index, the balance class from measured structure — local-scale CV
    over raw rows (the heavytail signature; needs ``dataset`` for PQ
    indexes, whose lists store only codes) plus list-size CV for IVF
    kinds, the measured clump hint (``seed_pool_hint``) for CAGRA. With
    no rows available the scale stat is skipped and only population skew
    can classify — pass ``dataset=`` when keying PQ indexes (the sweep
    engine does; decisions attached at sweep time ride the index, so
    loaded indexes rarely need re-keying)."""
    kind = kind_of(index)
    if kind == "brute_force":
        n, d = index.dataset.shape
        balance = ("skew" if local_scale_cv(index.dataset)
                   > SCALE_CV_THRESHOLD else "bal")
    elif kind == "cagra":
        n, d = index.size, index.dim
        balance = "clump" if int(index.seed_pool_hint) > 0 else "bal"
    else:  # ivf_flat / ivf_pq
        n, d = index.size, index.dim
        balance = "bal"
        if list_size_cv(index.list_sizes) > SKEW_CV_THRESHOLD:
            balance = "skew"
        else:
            if dataset is None and kind == "ivf_flat":
                # raw rows live in the lists: sample a few leading rows
                # from EVERY list on device FIRST (the classifier needs
                # ~1k rows SPREAD ACROSS clusters — whole-list sampling
                # would measure within-cluster scale only and miss the
                # cross-cluster heavytail signature; pulling the full
                # 1M-scale storage to host per resolve would cost a ~GB
                # copy), then fold padding out
                import jax
                import numpy as np

                n_lists, cap = index.list_data.shape[:2]
                lstep = max(n_lists // 4096, 1)
                per_list = max(4096 * lstep // n_lists, 1)
                data = np.asarray(jax.device_get(
                    index.list_data[::lstep, :per_list])).astype(np.float32)
                ids = np.asarray(jax.device_get(
                    index.list_ids[::lstep, :per_list]))
                dataset = data.reshape(-1, d)[ids.reshape(-1) >= 0]
            if dataset is not None and local_scale_cv(
                    dataset) > SCALE_CV_THRESHOLD:
                balance = "skew"
    return shape_family(n, d, balance)


def _query_dtype_of(index) -> str:
    kind = getattr(index, "data_kind", "float32")
    return kind if kind in ("int8", "uint8") else "float32"


class DecisionLog:
    """Keyed collection of decisions + artifact (de)serialization.

    ``meta`` records the measurement context once (backend, round label,
    generator seeds) so the artifact is self-describing.
    """

    def __init__(self, meta: dict | None = None):
        self.meta: dict = dict(meta or {})
        self._entries: dict[str, Decision] = {}

    # -- collection ----------------------------------------------------------
    def add(self, decision: Decision) -> Decision:
        expects(decision.kind in _KINDS, "unknown decision kind %r",
                decision.kind)
        self._entries[decision.key] = decision
        return decision

    def __len__(self) -> int:
        return len(self._entries)

    def entries(self) -> tuple[Decision, ...]:
        return tuple(self._entries[k] for k in sorted(self._entries))

    def get(self, kind: str, dtype: str, family: str) -> Decision | None:
        return self._entries.get(f"{kind}/{dtype}/{family}")

    def resolve(self, index, dataset=None) -> Decision | None:
        """Look up the decision for a built index: exact family first, then
        the nearest same-kind same-dtype family within the SAME balance
        class (matching dim bucket scores higher than matching row decade
        — probes/itopk track dim far more than absolute scale). Crossing
        the balance class is never a fallback: that transfer is the
        measured recall collapse this keying exists to prevent (BASELINE
        r5, 0.31 vs 0.82), so a log holding only the other class returns
        None and the caller keeps its defaults. ``dataset`` rows let the
        scale-skew classifier run for PQ indexes (see :func:`family_of`).
        Hand-authored entries with an unstructured family (``"any"``)
        resolve as a last resort below any structured match."""
        kind, dtype = kind_of(index), _query_dtype_of(index)
        fam = family_of(index, dataset)
        exact = self.get(kind, dtype, fam)
        if exact is not None:
            return exact
        n_lab, d_lab, bal = fam.split("-")
        best, best_score = None, -1.0
        for dec in self._entries.values():
            if dec.kind != kind or dec.dtype != dtype:
                continue
            parts = dec.family.split("-")
            if len(parts) == 3:
                dn, dd, db = parts
                if db != bal:
                    continue  # never transfer across balance classes
                score = 1.0 + 2.0 * (dd == d_lab) + 1.0 * (dn == n_lab)
            else:
                # hand-authored entries (e.g. from_dict's "any" default)
                # still resolve, below any structured-family match
                score = 0.5
            if score > best_score:
                best, best_score = dec, score
        return best

    # -- artifact ------------------------------------------------------------
    def to_json(self) -> dict:
        return {"format": "raft_tpu_tune/1", "meta": dict(self.meta),
                "decisions": [d.to_dict() for d in self.entries()]}

    @classmethod
    def from_json(cls, obj: dict) -> "DecisionLog":
        expects(isinstance(obj, dict)
                and obj.get("format", "").startswith("raft_tpu_tune/"),
                "not a tune decision-log artifact (format=%r)",
                obj.get("format") if isinstance(obj, dict) else type(obj))
        log = cls(meta=obj.get("meta", {}))
        for d in obj.get("decisions", []):
            log.add(Decision.from_dict(d))
        return log

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_json(), f, indent=1, sort_keys=True)
            f.write("\n")

    @classmethod
    def load(cls, path: str) -> "DecisionLog":
        with open(path) as f:
            return cls.from_json(json.load(f))
