"""Applying pinned decisions: decision → SearchParams → serving hook.

Three application surfaces, one resolution rule:

- :func:`tuned_search_params` maps a decision's knob dict onto the owning
  module's ``SearchParams`` (unknown knobs are an error — a decision must
  never half-apply silently).
- :func:`make_searcher` builds the full serving hook, including the exact
  ``refine`` epilogue for ``refine_ratio`` operating points (the flagship
  IVF-PQ pattern from BASELINE's tables). ``serve.publish(..., tuned=)``
  routes through this, and each module's ``batched_searcher`` consults the
  index's attached decision when no explicit params are given — so a
  loaded raft_tpu/9 index serves at its pinned operating point with zero
  caller code.
- :func:`apply_global` pins process-wide dispatch thresholds (today: the
  wide-select column cutoff in :mod:`raft_tpu.matrix.select_k`) from a
  ``select_k`` decision. Applied at trace time, so do it before the first
  search of a shape, like the ``RAFT_TPU_WIDE_SELECT_CAP`` escape hatch.

Every application increments ``raft_tpu_tune_applied_total`` — the serve
tier's scrape says which indexes run pinned and which run defaults.
"""

from __future__ import annotations

import dataclasses
import functools

from ..core.errors import expects
from ..obs import metrics
from .decisions import Decision, DecisionLog, kind_of

__all__ = ["tuned_search_params", "search_fn", "make_searcher", "attach",
           "resolve", "apply_global"]

# Knobs each kind's SearchParams accepts from a decision; refine_ratio is
# the cross-cutting epilogue knob (IVF kinds only — CAGRA already scores
# candidates exactly, brute force IS the oracle).
_PARAM_FIELDS = {
    "brute_force": frozenset(),
    "ivf_flat": frozenset({"n_probes"}),
    "ivf_pq": frozenset({"n_probes", "lut_dtype", "scan_impl", "scan_order",
                         "group_size", "select_impl", "funnel_widen"}),
    "cagra": frozenset({"itopk_size", "max_iterations", "search_width",
                        "seed_pool", "hop_impl"}),
}
_REFINE_KINDS = frozenset({"ivf_flat", "ivf_pq"})


@functools.lru_cache(maxsize=None)
def _applied_total():
    return metrics.counter(
        "raft_tpu_tune_applied_total",
        "tuned decisions applied to a searcher or dispatch threshold")


def _module_for(kind: str):
    from ..neighbors import brute_force, cagra, ivf_flat, ivf_pq

    return {"brute_force": brute_force, "ivf_flat": ivf_flat,
            "ivf_pq": ivf_pq, "cagra": cagra}[kind]


def _as_params_dict(tuned) -> dict:
    if isinstance(tuned, Decision):
        return dict(tuned.params)
    return dict(tuned)


def tuned_search_params(kind: str, params, base=None):
    """Decision knobs → ``(SearchParams, refine_ratio)`` for ``kind``.

    ``params`` is a :class:`Decision` or its knob dict; ``base`` seeds the
    fields the decision does not pin. ``refine_ratio`` (default 1) is
    returned separately — it configures the exact-refine epilogue, not the
    index search itself. Unknown knobs raise.
    """
    expects(kind in _PARAM_FIELDS, "no tuned-params mapping for kind %r",
            kind)
    knobs = _as_params_dict(params)
    refine_ratio = int(knobs.pop("refine_ratio", 1))
    expects(refine_ratio >= 1, "refine_ratio must be >= 1, got %d",
            refine_ratio)
    expects(refine_ratio == 1 or kind in _REFINE_KINDS,
            "refine_ratio applies to IVF kinds only (got kind=%r)", kind)
    unknown = set(knobs) - set(_PARAM_FIELDS[kind])
    expects(not unknown,
            "decision knobs %s are not %s search params (accepted: %s)",
            sorted(unknown), kind, sorted(_PARAM_FIELDS[kind]) or "none")
    if kind == "brute_force":
        return None, refine_ratio
    mod = _module_for(kind)
    sp = base if base is not None else mod.SearchParams()
    if knobs:
        sp = dataclasses.replace(sp, **knobs)
    return sp, refine_ratio


def search_fn(index, params, *, dataset=None, base_params=None):
    """``(queries, k) -> (distances, ids)`` closure applying a decision's
    knobs to ``index`` — the shared core of the sweep engine's trial arms
    and :func:`make_searcher`. ``refine_ratio > 1`` widens the index search
    to ``k * refine_ratio`` candidates and re-ranks them exactly against
    ``dataset`` (required then; CAGRA supplies its own stored rows)."""
    kind = kind_of(index)
    sp, refine_ratio = tuned_search_params(kind, params, base=base_params)
    if kind == "brute_force":
        return lambda queries, k: index.search(queries, int(k))
    mod = _module_for(kind)
    if refine_ratio == 1:
        return lambda queries, k: mod.search(sp, index, queries, int(k))
    expects(dataset is not None,
            "a refine_ratio operating point needs the raw rows: pass "
            "dataset= (e.g. the array the index was built from)")
    from ..neighbors.refine import refine

    metric = index.metric  # the refine re-rank must score like the index

    def fn(queries, k):
        _, cand = mod.search(sp, index, queries, int(k) * refine_ratio)
        return refine(dataset, queries, cand, int(k), metric=metric)

    return fn


def resolve(index, tuned, dataset=None) -> Decision | None:
    """Normalize a ``tuned=`` argument against an index: a
    :class:`DecisionLog` resolves by the index's measured family
    (``dataset`` rows enable the scale-skew classifier for PQ indexes),
    a :class:`Decision`/dict passes through (kind-checked), ``True`` reads
    the decision attached to the index (``index.tuned``, e.g. restored by
    a raft_tpu/9 load), ``None``/no-match returns None (caller defaults).
    """
    if tuned is None:
        return None
    if tuned is True:
        tuned = getattr(index, "tuned", None)
        if tuned is None:
            return None
    if isinstance(tuned, DecisionLog):
        return tuned.resolve(index, dataset)
    if isinstance(tuned, dict):
        tuned = Decision.from_dict(tuned)
    expects(isinstance(tuned, Decision),
            "tuned= must be a DecisionLog, Decision, decision dict, or "
            "True (use the index's attached decision); got %r",
            type(tuned).__name__)
    expects(tuned.kind == kind_of(index),
            "decision %r pins %s params but the index is %s",
            tuned.key, tuned.kind, kind_of(index))
    return tuned


def make_searcher(index, tuned, *, dataset=None, base_params=None,
                  degrade_without_rows: bool = False):
    """Build the serving hook for an index at a pinned operating point —
    what ``serve.publish(..., tuned=)`` warms and flips to. The hook
    carries the standard ``kind``/``dim``/``query_dtype`` contract plus
    ``tuned`` (the decision key) so a publish report can say WHICH pin is
    live.

    ``degrade_without_rows=True`` is the LOADED-index contract (the
    ``batched_searcher`` auto-consult path): a ``refine_ratio`` pin whose
    raw rows are unavailable serves the refine-free remainder of the
    decision — with a WARNING, never an error, because an attached pin
    must not make a previously-working default publish crash. Explicit
    application (``tuned=`` at publish, or calling this directly) stays
    strict: pass ``dataset=`` or get a clear error."""
    from ..neighbors._hooks import make_hook

    decision = resolve(index, tuned, dataset)
    expects(decision is not None,
            "no decision resolved for this index (empty log, or tuned=True "
            "on an index with nothing attached)")
    kind = kind_of(index)
    if dataset is None and kind == "cagra":
        dataset = index.dataset
    refine_ratio = int(decision.params.get("refine_ratio", 1))
    if refine_ratio > 1 and dataset is None and degrade_without_rows:
        from ..core.logger import logger

        logger.warning(
            "tuned decision %s pins refine_ratio=%d but no raw rows are "
            "available on this %s index; serving the refine-free remainder "
            "of the pin (pass dataset= to tune.make_searcher for the full "
            "operating point)", decision.key, refine_ratio, kind)
        trimmed = {kk: v for kk, v in decision.params.items()
                   if kk != "refine_ratio"}
        decision = Decision(kind=decision.kind, dtype=decision.dtype,
                            family=decision.family, params=trimmed,
                            evidence=decision.evidence)
        refine_ratio = 1
    fn = search_fn(index, decision, dataset=dataset,
                   base_params=base_params)
    hook_kind = kind + ("+refine" if refine_ratio > 1 else "")
    if kind == "brute_force":
        dim, data_kind = index.dataset.shape[1], str(index.dataset.dtype)
    else:
        dim, data_kind = index.dim, getattr(index, "data_kind", "float32")
    hook = make_hook(fn, hook_kind, dim, data_kind)
    hook.tuned = decision.key
    if metrics.enabled():
        _applied_total().inc(1, kind=kind)
    return hook


def attach(index, decision) -> None:
    """Pin a decision onto the index object (``index.tuned``, a plain
    JSON-able dict). Persisted by the module ``save``/``write_index``
    (raft_tpu/9) and consulted by ``batched_searcher`` when no explicit
    params are passed. Like ``CagraIndex.seed_pool_hint``, the attribute
    is NOT part of the pytree: ``device_put``/``tree_map`` round trips
    drop it back to None (defaults — never an error)."""
    if isinstance(decision, dict):
        decision = Decision.from_dict(decision)
    expects(isinstance(decision, Decision),
            "attach() takes a Decision or its dict, got %r",
            type(decision).__name__)
    expects(decision.kind == kind_of(index),
            "decision %r pins %s params but the index is %s",
            decision.key, decision.kind, kind_of(index))
    # validate now: a bad knob must fail at pin time, not first search
    tuned_search_params(decision.kind, decision)
    index.tuned = decision.to_dict()


def apply_global(log: DecisionLog) -> dict:
    """Apply the process-wide dispatch decisions a log carries (today: the
    ``select_k`` wide-column threshold). Returns ``{what: value}`` for
    each pin applied; empty dict when the log has none. Thresholds are
    read at trace time — apply before the first search of a shape."""
    from ..matrix.select_k import set_wide_cols_threshold

    applied = {}
    dec = log.get("select_k", "float32", "wide")
    if dec is not None:
        cols = int(dec.params["wide_cols_min"])
        set_wide_cols_threshold(cols)
        applied["select_k.wide_cols_min"] = cols
        if metrics.enabled():
            _applied_total().inc(1, kind="select_k")
    return applied
