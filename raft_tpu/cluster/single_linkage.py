"""Single-linkage hierarchical agglomerative clustering.

Reference: raft/cluster/single_linkage.cuh (single_linkage :85) — pipeline
(SURVEY.md K3): connectivities (full pairwise or kNN graph,
cluster/detail/connectivities.cuh) → MST + connect_components fix-up
(cluster/detail/mst.cuh) → agglomerative dendrogram + cut_tree labeling
(cluster/detail/agglomerative.cuh).

TPU split: the O(n²)/O(nk) graph construction and Borůvka MST run on device
(MXU distances, while-loop MST); the dendrogram build is a strictly
sequential n-1-step union-find — inherently serial, so it runs as a small
host numpy loop over the already-sorted device MST edges (the reference
dedicates a serial device kernel to the same step, which a TPU has no
latitude for).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ..core.errors import expects
from ..core.resources import Resources, default_resources
from ..distance.types import DistanceType, resolve_metric
from ..neighbors.brute_force import knn as dense_knn
from ..solver.mst import mst
from ..sparse.convert import sort_coo
from ..sparse.neighbors import connect_components
from ..sparse.op import max_duplicates
from ..sparse.types import CooMatrix

__all__ = ["SingleLinkageOutput", "single_linkage", "build_dendrogram_host", "cut_tree_host"]


@dataclasses.dataclass
class SingleLinkageOutput:
    """Reference: linkage_output (cluster/single_linkage_types.hpp)."""

    labels: jax.Array  # (n,) int32
    children: np.ndarray  # (n-1, 2) merge tree (scipy linkage convention)
    deltas: np.ndarray  # (n-1,) merge distances
    sizes: np.ndarray  # (n-1,) merged cluster sizes
    n_clusters: int


def build_dendrogram_host(src, dst, weights, n: int):
    """Sequential union-find dendrogram from sorted MST edges.

    Reference: cluster/detail/agglomerative.cuh build_dendrogram_host — the
    same algorithm (it, too, runs the serial merge on host via managed
    memory). Returns (children (n-1, 2), deltas, sizes) in scipy convention
    (new cluster ids n, n+1, ...).
    """
    parent = np.arange(2 * n - 1, dtype=np.int64)

    def find(a):
        root = a
        while parent[root] != root:
            root = parent[root]
        while parent[a] != root:
            parent[a], a = root, parent[a]
        return root

    children = np.zeros((n - 1, 2), np.int64)
    deltas = np.zeros((n - 1,), np.float64)
    sizes = np.zeros((n - 1,), np.int64)
    csize = np.ones(2 * n - 1, np.int64)
    nxt = n
    m = 0
    for e in range(len(src)):
        a, b = int(src[e]), int(dst[e])
        if a >= n or b >= n:
            continue
        ra, rb = find(a), find(b)
        if ra == rb:
            continue
        children[m] = (min(ra, rb), max(ra, rb))
        deltas[m] = float(weights[e])
        sizes[m] = csize[ra] + csize[rb]
        parent[ra] = parent[rb] = nxt
        csize[nxt] = sizes[m]
        nxt += 1
        m += 1
        if m == n - 1:
            break
    return children[:m], deltas[:m], sizes[:m]


def cut_tree_host(children, n: int, n_clusters: int):
    """Flatten the dendrogram at n_clusters (reference:
    cluster/detail/agglomerative.cuh extract_flattened_clusters)."""
    n_merges = max(n - n_clusters, 0)
    parent = np.arange(2 * n - 1, dtype=np.int64)

    def find(a):
        while parent[a] != a:
            parent[a] = parent[parent[a]]
            a = parent[a]
        return a

    for m in range(min(n_merges, len(children))):
        a, b = children[m]
        nxt = n + m
        parent[find(a)] = nxt
        parent[find(b)] = nxt
    roots = {}
    labels = np.zeros(n, np.int32)
    for i in range(n):
        r = find(i)
        if r not in roots:
            roots[r] = len(roots)
        labels[i] = roots[r]
    return labels


def single_linkage(
    x,
    n_clusters: int,
    connectivity: str = "knn",
    n_neighbors: int = 15,
    metric: str = "sqeuclidean",
    res: Resources | None = None,
) -> SingleLinkageOutput:
    """Single-linkage clustering of dense points.

    Reference: raft::cluster::single_linkage (cluster/single_linkage.cuh:85;
    LinkageDistance {PAIRWISE, KNN_GRAPH} cluster/single_linkage_types.hpp).
    ``connectivity``: "pairwise" builds the complete graph; "knn" builds an
    n_neighbors graph and repairs disconnected components with
    connect_components (the reference's KNN_GRAPH path). As in the
    reference, the knn path is an approximation: with small ``n_neighbors``
    the kNN subgraph can be connected yet miss true-MST edges, so merge
    heights may deviate slightly from exact single linkage — use
    "pairwise" (or a larger ``n_neighbors``) for exact dendrograms.
    """
    res = res or default_resources()
    x = jnp.asarray(x)
    expects(x.ndim == 2, "x must be (n, d)")
    n = x.shape[0]
    expects(1 <= n_clusters <= n, "n_clusters must be in [1, n]")
    mt = resolve_metric(metric)

    if connectivity == "pairwise":
        from ..distance.pairwise import pairwise_distance

        d = pairwise_distance(x, x, metric=mt, res=res)
        iu, ju = jnp.triu_indices(n, k=1)
        graph = CooMatrix(
            iu.astype(jnp.int32), ju.astype(jnp.int32), d[iu, ju],
            jnp.int32(iu.shape[0]), (n, n),
        )
    else:
        expects(connectivity == "knn", "connectivity must be 'pairwise' or 'knn'")
        expects(
            mt in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
                   DistanceType.L2Unexpanded, DistanceType.L2SqrtUnexpanded),
            "knn connectivity requires an L2 metric (reference parity: "
            "cluster/detail/connectivities.cuh knn path is L2-only), got %s", mt.name,
        )
        k = min(n_neighbors, n - 1)
        dists, idx = dense_knn(x, x, k + 1, metric=mt, res=res)
        rows = jnp.repeat(jnp.arange(n, dtype=jnp.int32), k + 1)
        cols = idx.reshape(-1).astype(jnp.int32)
        vals = dists.reshape(-1).astype(jnp.float32)
        keep = rows != cols
        # canonicalize to (min, max) so asymmetric kNN membership still keeps
        # the edge under mst()'s u<v filter; dedupe reciprocal pairs by max
        lo = jnp.minimum(rows, cols)
        hi = jnp.maximum(rows, cols)
        coo = CooMatrix(
            jnp.where(keep, lo, n), jnp.where(keep, hi, n),
            jnp.where(keep, vals, 0.0), jnp.sum(keep.astype(jnp.int32)), (n, n),
        )
        graph = max_duplicates(sort_coo(coo))

    out = mst(graph)

    # repair forest → tree (knn graphs can be disconnected; ref detail/mst.cuh
    # build_sorted_mst loop with connect_components)
    for _ in range(32):
        if int(out.n_edges) >= n - 1:
            break
        extra = connect_components(x, out.colors, res=res)
        # connect_components emits squared-L2 weights; match the graph's units
        extra_vals = extra.vals
        if mt in (DistanceType.L2SqrtExpanded, DistanceType.L2SqrtUnexpanded):
            extra_vals = jnp.sqrt(jnp.maximum(extra_vals, 0.0))
        merged = CooMatrix(
            jnp.concatenate([out.src, extra.rows]),
            jnp.concatenate([out.dst, extra.cols]),
            jnp.concatenate([out.weights, extra_vals]),
            out.n_edges + extra.nnz,
            (n, n),
        )
        # re-pack valid entries: mst() masks on row<col, so canonicalize pairs;
        # max dedupe keeps reciprocal winner edges at their true weight
        valid = merged.rows < n
        rows = jnp.where(valid, jnp.minimum(merged.rows, merged.cols), n)
        cols = jnp.where(valid, jnp.maximum(merged.rows, merged.cols), n)
        vals = jnp.where(valid & jnp.isfinite(merged.vals), merged.vals, 0.0)
        packed = max_duplicates(sort_coo(CooMatrix(rows, cols, vals, merged.nnz, (n, n))))
        out = mst(packed)

    ne = int(out.n_edges)
    src = np.asarray(out.src[:ne])
    dst = np.asarray(out.dst[:ne])
    w = np.asarray(out.weights[:ne])
    children, deltas, sizes = build_dendrogram_host(src, dst, w, n)
    labels = cut_tree_host(children, n, n_clusters)
    return SingleLinkageOutput(
        labels=jnp.asarray(labels), children=children, deltas=deltas,
        sizes=sizes, n_clusters=n_clusters,
    )
