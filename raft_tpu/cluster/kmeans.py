"""k-means clustering.

Re-design of the reference's kmeans (cpp/include/raft/cluster/kmeans.cuh,
detail/kmeans.cuh: kmeansPlusPlus :90, Lloyd loop kmeans_fit_main :361,
update_centroids :287, auto-k detail/kmeans_auto_find_k.cuh). TPU shape of the
algorithm:

- assignment = fused L2 1-NN (one MXU GEMM per X tile, argmin fused) — the
  same math the reference's minClusterAndDistance kernel computes;
- centroid update = one-hot weighted GEMM (linalg.reduce_rows_by_key) — the
  reference's reduce_rows_by_key;
- the Lloyd loop is a lax.while_loop on (centroids, inertia, iter), so the
  whole fit compiles to a single XLA program with no host round-trips.
"""

from __future__ import annotations

import dataclasses
import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

from ..core import tracing
from ..core.errors import expects
from ..core.resources import Resources, default_resources
from ..distance.fused_nn import _fused_l2_nn
from ..distance.pairwise import _choose_tile, _l2_expanded, pairwise_distance
from ..obs.instrument import instrument, nrows
from ..random.rng import as_key

__all__ = [
    "KMeansParams",
    "KMeansOutput",
    "fit",
    "predict",
    "fit_predict",
    "transform",
    "cluster_cost",
    "find_k",
    "init_plus_plus",
    "update_centroids",
]


@dataclasses.dataclass(frozen=True)
class KMeansParams:
    """Reference: raft::cluster::kmeans::KMeansParams (cluster/kmeans_types.hpp)."""

    n_clusters: int = 8
    max_iter: int = 300
    tol: float = 1e-4
    init: str = "kmeans++"  # "kmeans++" | "random" | "array"
    seed: int = 0
    n_init: int = 1
    oversampling_factor: float = 2.0  # kept for param parity; ++ is exact here
    batch_samples: int = 1 << 15  # assignment tile rows (memory heuristic)
    # EM iteration cost policy for the DISTRIBUTED driver
    # (raft_tpu.parallel.kmeans.fit): "minibatch" iterates Lloyd over
    # rotating per-shard mini-batches of ``batch_rows`` global rows with the
    # streaming 1/c center update, closing with one full pass for labels +
    # inertia; "auto" switches to minibatch above 2 x batch_rows (the
    # kmeans_balanced.resolve_train_mode rule). The single-chip fit() always
    # runs full Lloyd — tol-based convergence is its contract.
    train_mode: str = "full"
    batch_rows: int = 1 << 16


@dataclasses.dataclass
class KMeansOutput:
    centroids: jax.Array  # (k, d)
    labels: jax.Array | None  # (n,) int32
    inertia: jax.Array  # scalar f32
    n_iter: int


# ---------------------------------------------------------------------------


def _assign(x, centroids, tile: int):
    """Nearest centroid per row: (sq_distances, labels)."""
    return _fused_l2_nn(x, centroids, False, tile)


def _update(x, labels, weights, k: int):
    """Weighted centroid update via one-hot GEMM (ref: update_centroids:287)."""
    onehot = jax.nn.one_hot(labels, k, dtype=jnp.float32, axis=0)  # (k, n)
    if weights is not None:
        onehot = onehot * weights[None, :]
    sums = onehot @ x.astype(jnp.float32)  # (k, d)
    counts = jnp.sum(onehot, axis=1)  # (k,)
    return sums, counts


@functools.partial(jax.jit, static_argnames=("k", "max_iter", "tol", "tile"))
def _lloyd(x, init_centroids, weights, k: int, max_iter: int, tol: float, tile: int):
    """The Lloyd loop (ref: kmeans_fit_main, cluster/detail/kmeans.cuh:361)."""

    def cond(state):
        _, shift2, it = state
        return jnp.logical_and(it < max_iter, shift2 > tol * tol)

    def body(state):
        centroids, _, it = state
        _, labels = _assign(x, centroids, tile)
        sums, counts = _update(x, labels, weights, k)
        # divisor must be the true (possibly fractional) weight total — a
        # max(counts, 1) clamp would shrink centroids whenever a cluster's
        # weight sum is < 1
        denom = jnp.where(counts > 0, counts, 1.0)
        new_centroids = jnp.where(counts[:, None] > 0, sums / denom[:, None], centroids)
        shift2 = jnp.sum(jnp.square(new_centroids - centroids))
        return new_centroids, shift2, it + 1

    centroids, _, n_iter = lax.while_loop(
        cond, body, (init_centroids.astype(jnp.float32), jnp.inf, 0)
    )
    d2, labels = _assign(x, centroids, tile)
    w = weights if weights is not None else 1.0
    inertia = jnp.sum(d2 * w)
    return centroids, labels, inertia, n_iter


@functools.partial(jax.jit, static_argnames=("k", "tile"))
def _kmeans_plus_plus(x, key, k: int, tile: int):
    """Greedy k-means++ seeding (ref: kmeansPlusPlus, cluster/detail/
    kmeans.cuh:90 — batched trials at :113-255, n_trials = 2 + ⌈log k⌉).

    lax.fori_loop over k steps; each step draws ``n_trials`` candidates with
    probability ∝ current min squared distance (D² sampling) and keeps the
    one that lowers total cost most. Plain 1-trial D² sampling merges
    clusters at large k (e.g. ~2.3x the inertia floor on 1024 separated
    blobs); greedy trials are what the reference and sklearn use to avoid
    that. Each step is one (T, n) MXU contraction.
    """
    n, d = x.shape
    trials = 2 + int(math.ceil(math.log(max(k, 2))))
    xf = x.astype(jnp.float32)
    key, k0 = jax.random.split(key)
    first = jax.random.randint(k0, (), 0, n)
    centers = jnp.zeros((k, d), jnp.float32).at[0].set(xf[first])
    mind2 = _l2_expanded(xf[first][None, :], xf, sqrt=False)[0]  # (n,), HIGHEST prec

    def body(i, carry):
        centers, mind2, key = carry
        key, kc = jax.random.split(key)
        logits = jnp.log(jnp.maximum(mind2, 1e-30))
        cand = jax.random.categorical(kc, logits, shape=(trials,))  # (T,)
        cvec = xf[cand]  # (T, d)
        d2 = _l2_expanded(cvec, xf, sqrt=False)  # (T, n)
        newmin = jnp.minimum(mind2[None, :], d2)  # (T, n)
        best = jnp.argmin(jnp.sum(newmin, axis=1))
        centers = centers.at[i].set(cvec[best])
        return centers, newmin[best], key

    centers, _, _ = lax.fori_loop(1, k, body, (centers, mind2, key))
    return centers


def _init_centroids(params: KMeansParams, x, centroids, key, tile: int):
    if params.init == "array":
        expects(centroids is not None, "init='array' requires centroids")
        return jnp.asarray(centroids, jnp.float32)
    if params.init == "random":
        idx = jax.random.choice(key, x.shape[0], (params.n_clusters,), replace=False)
        return jnp.take(x, idx, axis=0).astype(jnp.float32)
    expects(params.init == "kmeans++", "unknown init %s", params.init)
    return _kmeans_plus_plus(x, key, params.n_clusters, tile)


@instrument("cluster.kmeans.fit",
            items=lambda a, kw: nrows(a[1] if len(a) > 1 else kw["x"]),
            labels=lambda a, kw: {
                "n_clusters": (a[0] if a else kw["params"]).n_clusters})
def fit(params: KMeansParams, x, sample_weights=None, centroids=None, res: Resources | None = None) -> KMeansOutput:
    """Fit k-means (reference: raft::cluster::kmeans::fit, cluster/kmeans.cuh;
    runtime entry raft_runtime/cluster/kmeans.hpp:53)."""
    res = res or default_resources()
    x = jnp.asarray(x)
    expects(x.ndim == 2, "X must be (n_samples, n_features)")
    expects(params.n_clusters <= x.shape[0], "n_clusters > n_samples")
    w = None if sample_weights is None else jnp.asarray(sample_weights, jnp.float32)
    tile = _choose_tile(x.shape[0], params.n_clusters, 1, res.workspace_bytes)

    best = None
    key = as_key(params.seed)
    for trial in range(max(params.n_init, 1)):
        key, kt = jax.random.split(key)
        with tracing.range("kmeans.fit.init"):
            init_c = _init_centroids(params, x, centroids, kt, tile)
        with tracing.range("kmeans.fit.lloyd"):
            c, labels, inertia, n_iter = _lloyd(
                x, init_c, w, params.n_clusters, params.max_iter, params.tol, tile
            )
        if best is None or float(inertia) < float(best.inertia):
            best = KMeansOutput(c, labels, inertia, int(n_iter))
    return best


@instrument("cluster.kmeans.predict",
            items=lambda a, kw: nrows(a[0] if a else kw["x"]))
def predict(x, centroids, sample_weights=None, res: Resources | None = None):
    """Assign labels (reference: kmeans::predict). Returns (labels, inertia)."""
    res = res or default_resources()
    x = jnp.asarray(x)
    centroids = jnp.asarray(centroids)
    tile = _choose_tile(x.shape[0], centroids.shape[0], 1, res.workspace_bytes)
    d2, labels = _assign(x, centroids, tile)
    w = 1.0 if sample_weights is None else jnp.asarray(sample_weights, jnp.float32)
    return labels, jnp.sum(d2 * w)


def fit_predict(params: KMeansParams, x, sample_weights=None, res: Resources | None = None):
    out = fit(params, x, sample_weights, res=res)
    return out.labels, out


def transform(x, centroids, res: Resources | None = None):
    """Distances to every centroid (reference: kmeans::transform)."""
    return pairwise_distance(x, centroids, metric="sqeuclidean", res=res)


def cluster_cost(x, centroids, res: Resources | None = None):
    """Total squared distance to nearest centroid (reference:
    raft_runtime/cluster/kmeans.hpp cluster_cost)."""
    _, inertia = predict(x, centroids, res=res)
    return inertia


def init_plus_plus(x, n_clusters: int, seed: int = 0, res: Resources | None = None):
    """Standalone k-means++ seeding (reference:
    raft_runtime/cluster/kmeans.hpp init_plus_plus; pylibraft
    cluster.kmeans.init_plus_plus). Returns (n_clusters, d) centroids."""
    res = res or default_resources()
    x = jnp.asarray(x)
    expects(x.ndim == 2, "X must be (n_samples, n_features)")
    expects(n_clusters <= x.shape[0], "n_clusters > n_samples")
    tile = _choose_tile(x.shape[0], n_clusters, 1, res.workspace_bytes)
    return _kmeans_plus_plus(x, as_key(seed), int(n_clusters), tile)


def update_centroids(x, centroids, sample_weights=None, res: Resources | None = None):
    """One weighted Lloyd update step (reference:
    raft_runtime/cluster/kmeans.hpp update_centroids; pylibraft
    cluster.kmeans.compute_new_centroids). Returns (new_centroids, labels)."""
    res = res or default_resources()
    x = jnp.asarray(x)
    centroids = jnp.asarray(centroids, jnp.float32)
    k = centroids.shape[0]
    tile = _choose_tile(x.shape[0], k, 1, res.workspace_bytes)
    w = None if sample_weights is None else jnp.asarray(sample_weights, jnp.float32)
    _, labels = _assign(x, centroids, tile)
    sums, counts = _update(x, labels, w, k)
    denom = jnp.where(counts > 0, counts, 1.0)
    new_centroids = jnp.where(counts[:, None] > 0, sums / denom[:, None], centroids)
    return new_centroids, labels


def find_k(x, k_range, params: KMeansParams | None = None, res: Resources | None = None):
    """Auto-select k by maximizing the Calinski–Harabasz index — the
    reference's criterion (detail/kmeans_auto_find_k.cuh:196 "maximize
    Calinski-Harabasz Index, minimize resid/cluster"; its binary search is
    replaced by a scan of the caller's candidate list).
    Returns (best_k, {k: CH score})."""
    from ..stats.metrics import dispersion as _dispersion

    params = params or KMeansParams()
    x = jnp.asarray(x)
    n = x.shape[0]
    scores = {}
    best_k, best_score = None, None
    for k in k_range:
        k = int(k)
        out = fit(dataclasses.replace(params, n_clusters=k), x, res=res)
        sizes = jnp.bincount(out.labels, length=k).astype(jnp.float32)
        bgss = float(_dispersion(out.centroids, sizes)) ** 2
        wss = max(float(out.inertia), 1e-30)
        ch = (n - k) / max(k - 1, 1) * bgss / wss
        scores[k] = ch
        if best_score is None or ch > best_score:
            best_k, best_score = k, ch
    return best_k, scores
