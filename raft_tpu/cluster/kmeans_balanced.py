"""Balanced k-means — the coarse quantizer trainer for IVF indexes.

Re-design of the reference's kmeans_balanced
(cpp/include/raft/cluster/kmeans_balanced.cuh, detail/kmeans_balanced.cuh:
EM loop balancing_em_iters :618, center adjustment adjust_centers :524,
assignment predict :371 / minibatch predict_core :85, hierarchical
build_hierarchical :758). Differences from plain k-means: a fixed number of
EM iterations (no tol), and a balancing step that re-seeds centers of
under-populated clusters from members of over-populated ones so inverted
lists stay usable.

TPU shape: assignment is the fused-1-NN GEMM; the balancing step is fully
vectorized — small clusters are detected with a size threshold and their
centers replaced by data points drawn (categorical, size-weighted) from large
clusters, in one masked gather instead of the reference's sequential
per-center scan.

Training cost: the Round-6 build A/B named the EM loop's full-dataset
assignment passes as the dominant cost of every IVF build (~22 passes,
50.3-51.3 s of the 1M build). ``train_mode="minibatch"`` (the default via
"auto" at scale) replaces them with rotating mini-batches — Sculley's
web-scale k-means (WWW 2010) with the balancing re-seed preserved — so the
EM loop touches ``batch_rows`` rows per iteration and only the final
sharpening pass (plus the caller's list-fill assignment) walks the full
trainset: at most two full-data passes per build.
"""

from __future__ import annotations

import dataclasses
import functools
import time

import jax
import jax.numpy as jnp
from jax import lax

from ..core.errors import expects
from ..core.resources import Resources, default_resources
from ..distance.fused_nn import _fused_l2_nn
from ..distance.pairwise import _choose_tile
from ..obs import build as build_metrics
from ..obs import metrics
from ..random.rng import as_key

__all__ = ["KMeansBalancedParams", "fit", "predict", "fit_predict",
           "build_clusters", "resolve_train_mode"]


@dataclasses.dataclass(frozen=True)
class KMeansBalancedParams:
    """Reference: kmeans_balanced_params (cluster/kmeans_balanced_types.hpp)."""

    n_iters: int = 20
    # assignment metric: the reference supports L2Expanded and InnerProduct
    # (kmeans_balanced.cuh requirement); same pair here.
    metric: str = "sqeuclidean"
    seed: int = 0
    # clusters smaller than avg_size * small_ratio get re-seeded (ref:
    # adjust_centers' threshold logic)
    small_ratio: float = 0.25
    max_train_points: int | None = None  # subsample cap for fit (ref: IVF builds train on a subset)
    # EM iteration cost policy (reference analogue: detail/kmeans_balanced
    # predict_core's minibatch assignment :85, generalized to the whole EM
    # loop per Sculley, WWW 2010):
    #   "full"      — every EM iteration assigns the whole trainset (the
    #                 pre-r07 behavior; ~n_iters+2 full-data passes).
    #   "minibatch" — EM iterates over rotating ``batch_rows``-row
    #                 mini-batches of a fixed shuffle; centers move by the
    #                 streaming 1/c mean update, the balancing re-seed runs
    #                 on per-batch counts (re-seeded centers reset their
    #                 cumulative count so they re-adapt at Lloyd speed), and
    #                 ONE full-data sharpening pass closes the fit. Total
    #                 full-data passes: 1 here + 1 list-fill assignment in
    #                 the caller — the "at most two" contract.
    #   "auto"      — minibatch when the trainset exceeds 2 x batch_rows
    #                 (below that the batches cover most of the data anyway
    #                 and full EM is at least as accurate per wall-second).
    train_mode: str = "auto"
    batch_rows: int = 65536


def resolve_train_mode(mode: str, n_train: int, batch_rows: int) -> str:
    """Resolve the ``train_mode`` policy for a trainset size — one rule
    shared by the single-chip fit and the distributed psum-EM drivers
    (parallel/kmeans.py, parallel/ivf.py) so "auto" means the same thing
    everywhere."""
    expects(mode in ("full", "minibatch", "auto"),
            "train_mode must be 'full', 'minibatch' or 'auto', got %r", mode)
    expects(batch_rows >= 1, "batch_rows must be >= 1, got %d", batch_rows)
    if mode == "auto":
        return "minibatch" if n_train > 2 * batch_rows else "full"
    return mode




def _assign_labels(x, centers, tile: int, inner: bool):
    if inner:
        # inner-product assignment: argmax of the score GEMM
        scores = x.astype(jnp.float32) @ centers.T
        return jnp.argmax(scores, axis=1).astype(jnp.int32)
    return _fused_l2_nn(x, centers, False, tile)[1]


def _reseed_small(centers, counts, labels_or_w, pool_vecs, key, k: int,
                  avg: float, small_ratio: float):
    """The balancing step (ref: adjust_centers :524), shared by both EM
    modes: replace centers of under-populated clusters with candidate points
    drawn from a pool, weighted by the crowdedness of each candidate's
    cluster, via Gumbel top-k (weighted WITHOUT replacement — two small
    clusters never re-seed to the same point, which would starve one of
    them permanently). Returns (centers, small_mask)."""
    small = counts < (avg * small_ratio)  # (k,)
    logits = jnp.log(jnp.maximum(labels_or_w, 1e-6))
    gumbel = -jnp.log(-jnp.log(
        jax.random.uniform(key, (pool_vecs.shape[0],), minval=1e-20,
                           maxval=1.0)))
    repl = pool_vecs[lax.top_k(logits + gumbel, k)[1]]
    return jnp.where(small[:, None], repl, centers), small


@functools.partial(jax.jit, static_argnames=("k", "n_iters", "small_ratio", "tile", "inner"))
def _balanced_em(x, init_centers, key, k: int, n_iters: int, small_ratio: float, tile: int, inner: bool):
    """Full-data EM loop (train_mode="full"); returns unsharpened centers."""
    n = x.shape[0]
    xf = x.astype(jnp.float32)

    def body(i, carry):
        centers, key = carry
        labels = _assign_labels(x, centers, tile, inner)
        onehot = jax.nn.one_hot(labels, k, dtype=jnp.float32, axis=0)  # (k, n)
        sums = onehot @ xf
        counts = jnp.sum(onehot, axis=1)
        centers = jnp.where(counts[:, None] > 0, sums / jnp.maximum(counts, 1.0)[:, None], centers)

        # -- balancing (ref: adjust_centers :524) --
        key, kc, kp = jax.random.split(key, 3)
        # draw replacement points, favoring members of crowded clusters.
        # categorical(shape=(k,)) over all n logits broadcasts a (k, n)
        # gumbel block — 2 GB/iter at 500k x 1024 and the dominant cost of
        # the whole EM loop. Instead draw from a small uniform pool of
        # candidate points re-weighted by their cluster's crowdedness: same
        # bias, (k, pool) work.
        pool = min(max(4 * k, 4096), n)
        # without replacement: duplicate pool entries would let two small
        # clusters re-seed to the same point, the starvation the Gumbel
        # top-k below exists to prevent
        pool_idx = jax.random.choice(kp, n, (pool,), replace=False)
        pool_w = counts[labels[pool_idx]]  # crowdedness of each candidate
        centers, _ = _reseed_small(centers, counts, pool_w, xf[pool_idx], kc,
                                   k, n / k, small_ratio)

        # Note: no hot-cluster splitting here — actively relocating centers
        # each iteration proved unstable (center churn prevents Lloyd
        # convergence and *grows* the max list). Skew is instead handled at
        # the index layer: oversized lists split into capacity-bounded
        # sub-lists sharing a center (neighbors/_list_utils.split_oversized).
        return centers, key

    centers, _ = lax.fori_loop(0, n_iters, body, (init_centers.astype(jnp.float32), key))
    return centers


@functools.partial(jax.jit, static_argnames=("k", "n_iters", "small_ratio",
                                             "tile", "inner", "batch"))
def _balanced_em_minibatch(x, init_centers, key, k: int, n_iters: int,
                           small_ratio: float, tile: int, inner: bool,
                           batch: int):
    """Mini-batch EM loop (train_mode="minibatch"); returns unsharpened
    centers. Rotating batches of a fixed shuffle (every point is visited
    before any repeats), the streaming 1/c center update (Sculley's
    per-center learning rate, batched: c += (sum_b - n_b*c) / c_total), and
    the same crowdedness-weighted Gumbel re-seed as the full loop — run on
    the BATCH's counts against the batch-scaled small threshold. A re-seeded
    center's cumulative count resets to zero so its next batch update is a
    full replacement by the batch mean (Lloyd-speed re-adaptation instead of
    a 1/c-crippled crawl)."""
    n = x.shape[0]
    key, kperm = jax.random.split(key)
    perm = jax.random.permutation(kperm, n).astype(jnp.int32)
    offs = jnp.arange(batch, dtype=jnp.int32)

    def body(i, carry):
        centers, ccounts, key = carry
        # rotating batches of one up-front shuffle — the discipline ivf_pq's
        # OPQ rotation trainer (_train_opq_rotation) borrows for its
        # alternating codebook-fit / Procrustes rounds
        idx = perm[(i * batch + offs) % n]
        xb = jnp.take(x, idx, axis=0)
        xbf = xb.astype(jnp.float32)
        labels = _assign_labels(xb, centers, tile, inner)
        onehot = jax.nn.one_hot(labels, k, dtype=jnp.float32, axis=0)  # (k, b)
        sums = onehot @ xbf
        counts = jnp.sum(onehot, axis=1)
        ccounts = ccounts + counts
        # streaming mean: exact if centers were the running mean of their
        # ccounts assigned points; counts==0 rows contribute a zero delta
        centers = centers + (sums - counts[:, None] * centers) / jnp.maximum(
            ccounts, 1.0)[:, None]

        # -- balancing on batch statistics --
        key, kc, kp = jax.random.split(key, 3)
        pool = min(max(4 * k, 4096), batch)
        pool_idx = jax.random.choice(kp, batch, (pool,), replace=False)
        pool_w = counts[labels[pool_idx]]
        centers, small = _reseed_small(centers, counts, pool_w, xbf[pool_idx],
                                       kc, k, batch / k, small_ratio)
        ccounts = jnp.where(small, 0.0, ccounts)
        return centers, ccounts, key

    centers, _, _ = lax.fori_loop(
        0, n_iters, body,
        (init_centers.astype(jnp.float32), jnp.zeros((k,), jnp.float32), key))
    return centers


@functools.partial(jax.jit, static_argnames=("k", "tile", "inner"))
def _final_sharpen(x, centers, k: int, tile: int, inner: bool):
    """One full-data pass without balancing so centers are true means — the
    single full-trainset pass both EM modes close with."""
    xf = x.astype(jnp.float32)
    labels = _assign_labels(x, centers, tile, inner)
    onehot = jax.nn.one_hot(labels, k, dtype=jnp.float32, axis=0)
    sums = onehot @ xf
    counts = jnp.sum(onehot, axis=1)
    return jnp.where(counts[:, None] > 0,
                     sums / jnp.maximum(counts, 1.0)[:, None], centers)


def fit(params: KMeansBalancedParams, x, n_clusters: int, res: Resources | None = None):
    """Train balanced cluster centers (reference: kmeans_balanced::fit).

    Returns (n_clusters, d) float32 centers.
    """
    from ..core import chunked

    res = res or default_resources()
    # chunked readers (core.chunked — the out-of-core build path) stay
    # un-materialized until the trainset subsample gather below; the PRNG
    # key chain is IDENTICAL in both modes, so the streamed build's
    # centers are bit-equal to the in-core twin's
    if not chunked.is_reader(x):
        x = jnp.asarray(x)
    expects(x.ndim == 2, "X must be 2-D")
    n = int(x.shape[0])
    expects(n_clusters <= n, "n_clusters > n_samples")
    key = as_key(params.seed)

    if params.max_train_points is not None and n > params.max_train_points:
        key, ks = jax.random.split(key)
        sub = jax.random.choice(ks, n, (params.max_train_points,), replace=False)
        # same indices, one gather seam: jnp.take in-core, a host
        # fancy-gather (+ ingest conversion) on a reader — the ONE host
        # sync the streamed build pays before its chunk loops
        x = chunked.take_rows(x, sub)
        n = params.max_train_points
    elif chunked.is_reader(x):
        x = chunked.materialize(x)

    key, ki, ke = jax.random.split(key, 3)
    init_idx = jax.random.choice(ki, n, (n_clusters,), replace=False)
    init_centers = jnp.take(x, init_idx, axis=0)
    tile = _choose_tile(n, n_clusters, 1, res.workspace_bytes)
    inner = _is_inner(params.metric)
    mode = resolve_train_mode(params.train_mode, n, params.batch_rows)
    t0 = time.perf_counter()
    if mode == "minibatch":
        # the balancing pool (and the Gumbel top-k over it) needs at least
        # n_clusters candidates per batch
        batch = min(n, max(params.batch_rows, n_clusters))
        centers = _balanced_em_minibatch(
            x, init_centers, ke, n_clusters, params.n_iters,
            params.small_ratio, min(tile, batch), inner, batch)
        em_rows = batch
    else:
        centers = _balanced_em(
            x, init_centers, ke, n_clusters, params.n_iters,
            params.small_ratio, tile, inner)
        em_rows = n
    if metrics._enabled:
        jax.block_until_ready(centers)
        build_metrics.build_phase().observe(time.perf_counter() - t0,
                                 phase="kmeans_balanced/em")
        build_metrics.assignment_passes().inc(params.n_iters, phase="em", mode=mode,
                               driver="single")
        build_metrics.sampled_rows().set(em_rows, mode=mode, driver="single")
    t0 = time.perf_counter()
    centers = _final_sharpen(x, centers, n_clusters, tile, inner)
    if metrics._enabled:
        jax.block_until_ready(centers)
        build_metrics.build_phase().observe(time.perf_counter() - t0,
                                 phase="kmeans_balanced/final")
        build_metrics.assignment_passes().inc(1, phase="final", mode=mode,
                               driver="single")
    return centers


def _is_inner(metric: str) -> bool:
    from ..distance.types import DistanceType, resolve_metric

    mt = resolve_metric(metric)
    expects(
        mt
        in (
            DistanceType.L2Expanded,
            DistanceType.L2SqrtExpanded,
            DistanceType.L2Unexpanded,
            DistanceType.L2SqrtUnexpanded,
            DistanceType.InnerProduct,
        ),
        "kmeans_balanced supports L2 / inner_product metrics, got %s",
        mt.name,
    )
    return mt == DistanceType.InnerProduct


def predict(x, centers, metric: str = "sqeuclidean", res: Resources | None = None):
    """Nearest-center labels (reference: kmeans_balanced::predict)."""
    res = res or default_resources()
    x = jnp.asarray(x)
    centers = jnp.asarray(centers)
    tile = _choose_tile(x.shape[0], centers.shape[0], 1, res.workspace_bytes)
    return _assign_labels(x, centers, tile, _is_inner(metric))


def fit_predict(params: KMeansBalancedParams, x, n_clusters: int, res: Resources | None = None):
    centers = fit(params, x, n_clusters, res=res)
    return centers, predict(x, centers, metric=params.metric, res=res)


def build_clusters(params: KMeansBalancedParams, x, n_clusters: int, res: Resources | None = None):
    """Train + assign + sizes in one call — the IVF-build entry point
    (reference: detail::kmeans_balanced::build_clusters, used by
    ivf_pq_build.cuh:412). Returns (centers, labels, cluster_sizes)."""
    centers = fit(params, x, n_clusters, res=res)
    labels = predict(x, centers, metric=params.metric, res=res)
    sizes = jnp.bincount(labels, length=n_clusters).astype(jnp.int32)
    return centers, labels, sizes
