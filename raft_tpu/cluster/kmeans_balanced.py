"""Balanced k-means — the coarse quantizer trainer for IVF indexes.

Re-design of the reference's kmeans_balanced
(cpp/include/raft/cluster/kmeans_balanced.cuh, detail/kmeans_balanced.cuh:
EM loop balancing_em_iters :618, center adjustment adjust_centers :524,
assignment predict :371 / minibatch predict_core :85, hierarchical
build_hierarchical :758). Differences from plain k-means: a fixed number of
EM iterations (no tol), and a balancing step that re-seeds centers of
under-populated clusters from members of over-populated ones so inverted
lists stay usable.

TPU shape: assignment is the fused-1-NN GEMM; the balancing step is fully
vectorized — small clusters are detected with a size threshold and their
centers replaced by data points drawn (categorical, size-weighted) from large
clusters, in one masked gather instead of the reference's sequential
per-center scan.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..core.errors import expects
from ..core.resources import Resources, default_resources
from ..distance.fused_nn import _fused_l2_nn
from ..distance.pairwise import _choose_tile
from ..random.rng import as_key

__all__ = ["KMeansBalancedParams", "fit", "predict", "fit_predict", "build_clusters"]


@dataclasses.dataclass(frozen=True)
class KMeansBalancedParams:
    """Reference: kmeans_balanced_params (cluster/kmeans_balanced_types.hpp)."""

    n_iters: int = 20
    # assignment metric: the reference supports L2Expanded and InnerProduct
    # (kmeans_balanced.cuh requirement); same pair here.
    metric: str = "sqeuclidean"
    seed: int = 0
    # clusters smaller than avg_size * small_ratio get re-seeded (ref:
    # adjust_centers' threshold logic)
    small_ratio: float = 0.25
    max_train_points: int | None = None  # subsample cap for fit (ref: IVF builds train on a subset)


def _assign_labels(x, centers, tile: int, inner: bool):
    if inner:
        # inner-product assignment: argmax of the score GEMM
        scores = x.astype(jnp.float32) @ centers.T
        return jnp.argmax(scores, axis=1).astype(jnp.int32)
    return _fused_l2_nn(x, centers, False, tile)[1]


@functools.partial(jax.jit, static_argnames=("k", "n_iters", "small_ratio", "tile", "inner"))
def _balanced_em(x, init_centers, key, k: int, n_iters: int, small_ratio: float, tile: int, inner: bool):
    n = x.shape[0]
    xf = x.astype(jnp.float32)

    def body(i, carry):
        centers, key = carry
        labels = _assign_labels(x, centers, tile, inner)
        onehot = jax.nn.one_hot(labels, k, dtype=jnp.float32, axis=0)  # (k, n)
        sums = onehot @ xf
        counts = jnp.sum(onehot, axis=1)
        centers = jnp.where(counts[:, None] > 0, sums / jnp.maximum(counts, 1.0)[:, None], centers)

        # -- balancing (ref: adjust_centers :524) --
        avg = n / k
        small = counts < (avg * small_ratio)  # (k,)
        key, kc, kp = jax.random.split(key, 3)
        # draw replacement points, favoring members of crowded clusters.
        # categorical(shape=(k,)) over all n logits broadcasts a (k, n)
        # gumbel block — 2 GB/iter at 500k x 1024 and the dominant cost of
        # the whole EM loop. Instead draw from a small uniform pool of
        # candidate points re-weighted by their cluster's crowdedness: same
        # bias, (k, pool) work.
        pool = min(max(4 * k, 4096), n)
        # without replacement: duplicate pool entries would let two small
        # clusters re-seed to the same point, the starvation the Gumbel
        # top-k below exists to prevent
        pool_idx = jax.random.choice(kp, n, (pool,), replace=False)
        pool_w = counts[labels[pool_idx]]  # crowdedness of each candidate
        logits = jnp.log(jnp.maximum(pool_w, 1e-6))
        # Gumbel top-k = weighted sampling WITHOUT replacement: k distinct
        # candidates, so two small clusters never re-seed to the same point
        # (a duplicated center starves one of them permanently)
        gumbel = -jnp.log(-jnp.log(
            jax.random.uniform(kc, (pool,), minval=1e-20, maxval=1.0)))
        repl_idx = pool_idx[lax.top_k(logits + gumbel, k)[1]]
        repl = xf[repl_idx]
        centers = jnp.where(small[:, None], repl, centers)

        # Note: no hot-cluster splitting here — actively relocating centers
        # each iteration proved unstable (center churn prevents Lloyd
        # convergence and *grows* the max list). Skew is instead handled at
        # the index layer: oversized lists split into capacity-bounded
        # sub-lists sharing a center (neighbors/_list_utils.split_oversized).
        return centers, key

    centers, _ = lax.fori_loop(0, n_iters, body, (init_centers.astype(jnp.float32), key))
    # final sharpening pass without balancing so centers are true means
    labels = _assign_labels(x, centers, tile, inner)
    onehot = jax.nn.one_hot(labels, k, dtype=jnp.float32, axis=0)
    sums = onehot @ xf
    counts = jnp.sum(onehot, axis=1)
    centers = jnp.where(counts[:, None] > 0, sums / jnp.maximum(counts, 1.0)[:, None], centers)
    return centers


def fit(params: KMeansBalancedParams, x, n_clusters: int, res: Resources | None = None):
    """Train balanced cluster centers (reference: kmeans_balanced::fit).

    Returns (n_clusters, d) float32 centers.
    """
    res = res or default_resources()
    x = jnp.asarray(x)
    expects(x.ndim == 2, "X must be 2-D")
    n = x.shape[0]
    expects(n_clusters <= n, "n_clusters > n_samples")
    key = as_key(params.seed)

    if params.max_train_points is not None and n > params.max_train_points:
        key, ks = jax.random.split(key)
        sub = jax.random.choice(ks, n, (params.max_train_points,), replace=False)
        x = jnp.take(x, sub, axis=0)
        n = params.max_train_points

    key, ki, ke = jax.random.split(key, 3)
    init_idx = jax.random.choice(ki, n, (n_clusters,), replace=False)
    init_centers = jnp.take(x, init_idx, axis=0)
    tile = _choose_tile(n, n_clusters, 1, res.workspace_bytes)
    return _balanced_em(
        x, init_centers, ke, n_clusters, params.n_iters, params.small_ratio, tile,
        _is_inner(params.metric),
    )


def _is_inner(metric: str) -> bool:
    from ..distance.types import DistanceType, resolve_metric

    mt = resolve_metric(metric)
    expects(
        mt
        in (
            DistanceType.L2Expanded,
            DistanceType.L2SqrtExpanded,
            DistanceType.L2Unexpanded,
            DistanceType.L2SqrtUnexpanded,
            DistanceType.InnerProduct,
        ),
        "kmeans_balanced supports L2 / inner_product metrics, got %s",
        mt.name,
    )
    return mt == DistanceType.InnerProduct


def predict(x, centers, metric: str = "sqeuclidean", res: Resources | None = None):
    """Nearest-center labels (reference: kmeans_balanced::predict)."""
    res = res or default_resources()
    x = jnp.asarray(x)
    centers = jnp.asarray(centers)
    tile = _choose_tile(x.shape[0], centers.shape[0], 1, res.workspace_bytes)
    return _assign_labels(x, centers, tile, _is_inner(metric))


def fit_predict(params: KMeansBalancedParams, x, n_clusters: int, res: Resources | None = None):
    centers = fit(params, x, n_clusters, res=res)
    return centers, predict(x, centers, metric=params.metric, res=res)


def build_clusters(params: KMeansBalancedParams, x, n_clusters: int, res: Resources | None = None):
    """Train + assign + sizes in one call — the IVF-build entry point
    (reference: detail::kmeans_balanced::build_clusters, used by
    ivf_pq_build.cuh:412). Returns (centers, labels, cluster_sizes)."""
    centers = fit(params, x, n_clusters, res=res)
    labels = predict(x, centers, metric=params.metric, res=res)
    sizes = jnp.bincount(labels, length=n_clusters).astype(jnp.int32)
    return centers, labels, sizes
