"""raft_tpu.cluster — raft/cluster (K1-K3). Under construction."""
