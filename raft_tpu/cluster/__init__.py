"""raft_tpu.cluster — k-means family and (later) single-linkage.

Reference: cpp/include/raft/cluster/ (L4, K1-K3).
"""

from . import kmeans, kmeans_balanced
from .kmeans import KMeansOutput, KMeansParams
from .kmeans_balanced import KMeansBalancedParams

__all__ = [
    "kmeans",
    "kmeans_balanced",
    "KMeansParams",
    "KMeansOutput",
    "KMeansBalancedParams",
]
