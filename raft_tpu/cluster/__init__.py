"""raft_tpu.cluster — k-means family and single-linkage HAC.

Reference: cpp/include/raft/cluster/ (L4, K1-K3).
"""

from . import kmeans, kmeans_balanced
from .kmeans import KMeansOutput, KMeansParams
from .kmeans_balanced import KMeansBalancedParams
from .single_linkage import SingleLinkageOutput, single_linkage

__all__ = [
    "kmeans",
    "kmeans_balanced",
    "single_linkage",
    "KMeansParams",
    "KMeansOutput",
    "KMeansBalancedParams",
    "SingleLinkageOutput",
]
