"""Class-label utilities.

Reference: raft/label/classlabels.cuh — getUniquelabels (:41), getOvrlabels
(:65, one-vs-rest binarization), make_monotonic (:91/:114, dense relabeling
to a contiguous range, 1-based by default with optional ``zero_based``).

TPU re-design: the reference sorts labels with CUB and compacts adjacent
duplicates; here the same sort → adjacent-diff → prefix-sum pipeline is
expressed in jnp so XLA owns the sort, and the per-element relabel is a
``searchsorted`` into the sorted array instead of a binary-search kernel.
``make_monotonic`` is fully jittable (static output shape); ``unique_labels``
has a dynamic result size and therefore does a host round-trip, with a
jittable padded variant ``unique_labels_padded`` for in-jit consumers.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core.errors import expects

__all__ = [
    "unique_labels",
    "unique_labels_padded",
    "get_ovr_labels",
    "make_monotonic",
]


def unique_labels(y):
    """Sorted unique labels (reference: getUniquelabels, classlabels.cuh:41).

    Dynamic output size ⇒ host round-trip; use :func:`unique_labels_padded`
    inside jit.
    """
    return jnp.asarray(np.unique(np.asarray(y)))


@jax.jit
def unique_labels_padded(y):
    """Jittable unique: (sorted_unique_padded, n_unique).

    The output has the same length as ``y``; slots past ``n_unique`` hold the
    maximum label (harmless for searchsorted-based relabeling).
    """
    y = y.ravel()
    s = jnp.sort(y)
    is_new = jnp.concatenate([jnp.ones((1,), bool), s[1:] != s[:-1]])
    n_unique = jnp.sum(is_new, dtype=jnp.int32)
    # stable-compact the firsts to the front, pad with the max label
    pos = jnp.where(is_new, jnp.cumsum(is_new) - 1, y.shape[0] - 1)
    out = jnp.full_like(s, s[-1]).at[pos].set(s, mode="drop")
    # positions past n_unique may have been overwritten by the drop trick;
    # re-fill them with the max label for determinism
    out = jnp.where(jnp.arange(y.shape[0]) < n_unique, out, s[-1])
    return out, n_unique


def get_ovr_labels(y, unique, idx: int, one=1, zero=0):
    """One-vs-rest binarize (reference: getOvrlabels, classlabels.cuh:65).

    Labels equal to ``unique[idx]`` map to ``one``, everything else to
    ``zero``.
    """
    expects(0 <= idx < unique.shape[0], "ovr index %d out of range [0, %d)", idx, unique.shape[0])
    target = unique[idx]
    return jnp.where(y == target, one, zero).astype(jnp.asarray(y).dtype)


@functools.partial(jax.jit, static_argnames=("zero_based",))
def _make_monotonic(y, mask, zero_based: bool):
    flat = y.ravel()
    big = jnp.iinfo(flat.dtype).max if jnp.issubdtype(flat.dtype, jnp.integer) else jnp.inf
    keyed = jnp.where(mask.ravel(), flat, big)  # filtered values sort to the back
    s = jnp.sort(keyed)
    is_new = jnp.concatenate([jnp.ones((1,), bool), s[1:] != s[:-1]])
    dense = (jnp.cumsum(is_new) - 1).astype(jnp.int32)
    pos = jnp.searchsorted(s, keyed)
    out = dense[pos] + (0 if zero_based else 1)
    out = jnp.where(mask.ravel(), out, flat.astype(jnp.int32))
    return out.reshape(y.shape)


def make_monotonic(y, filter_op=None, zero_based: bool = False):
    """Relabel to a contiguous monotonic set (reference: make_monotonic,
    classlabels.cuh:91).

    Labels become ``1..n_classes`` (or ``0..n_classes-1`` when
    ``zero_based``), ordered by label value. Elements for which
    ``filter_op(label)`` is False keep their original value — the same
    contract the reference uses to protect sentinel labels (e.g. DBSCAN's
    untouched marker).
    """
    y = jnp.asarray(y)
    mask = jnp.ones(y.shape, bool) if filter_op is None else filter_op(y)
    return _make_monotonic(y, mask, bool(zero_based))
