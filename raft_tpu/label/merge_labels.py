"""Merge two labellings according to a core-point mask.

Reference: raft/label/merge_labels.cuh + detail/merge_labels.cuh — an
iterated ``propagate_label_kernel`` (atomicMin on a label-equivalence map R)
until a host-polled change flag clears, then ``reassign_label_kernel``.
Contract (detail/merge_labels.cuh:85-108): labels take values 1..N,
``max_label`` marks unlabelled points; wherever ``mask`` is true the point's
two labels become equivalent, every equivalence class is relabelled to its
minimum member, and the result is ``min`` over both relabelled inputs.

TPU re-design: the atomicMin rounds become `.at[].min` scatter-mins over a
dense R of static size N inside one `lax.while_loop`; a pointer-jumping step
(R ← R[R], valid because R only decreases) replaces the reference's
"R[min(ra,rb)] speeds up convergence" trick and gives O(log N) rounds.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.errors import expects

__all__ = ["merge_labels"]


@jax.jit
def _merge(labels_a, labels_b, mask, max_label):
    n = labels_a.shape[0]
    labelled = mask & (labels_a != max_label) & (labels_b != max_label)
    # 0-based label ids; unlabelled points scatter to the dropped slot n
    la = jnp.where(labelled, labels_a - 1, n).astype(jnp.int32)
    lb = jnp.where(labelled, labels_b - 1, n).astype(jnp.int32)
    r0 = jnp.arange(n, dtype=jnp.int32)

    def cond(state):
        _, changed = state
        return changed

    def body(state):
        r, _ = state
        ra = r[jnp.minimum(la, n - 1)]
        rb = r[jnp.minimum(lb, n - 1)]
        rmin = r[jnp.minimum(ra, rb)]
        r = r.at[la].min(jnp.where(labelled, rmin, n), mode="drop")
        r = r.at[lb].min(jnp.where(labelled, rmin, n), mode="drop")
        # pointer jumping: R only ever decreases, so composing it with itself
        # is still a valid equivalence-preserving lower bound
        r = r[r]
        changed = jnp.any(labelled & (ra != rb))
        return r, changed

    r, _ = lax.while_loop(cond, body, (r0, jnp.bool_(True)))

    def relabel(lx):
        l0 = jnp.where(lx == max_label, 0, lx - 1).astype(jnp.int32)
        return jnp.where(lx == max_label, max_label, r[l0] + 1)

    return jnp.minimum(relabel(labels_a), relabel(labels_b))


def merge_labels(labels_a, labels_b, mask, max_label=None):
    """Merge labellings A and B (reference: label/merge_labels.cuh:57).

    Returns the merged label array (the reference updates ``labels_a``
    in-place). ``max_label`` defaults to the dtype max, matching the
    reference's MAX_LABEL sentinel for unlabelled points.
    """
    labels_a = jnp.asarray(labels_a)
    labels_b = jnp.asarray(labels_b)
    mask = jnp.asarray(mask, bool)
    expects(labels_a.shape == labels_b.shape == mask.shape, "shape mismatch")
    if max_label is None:
        max_label = jnp.iinfo(labels_a.dtype).max
    return _merge(labels_a, labels_b, mask, jnp.asarray(max_label, labels_a.dtype))
