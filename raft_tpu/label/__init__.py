"""raft_tpu.label — raft/label (K6). Under construction."""
