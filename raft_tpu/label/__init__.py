"""raft_tpu.label — label utilities (reference: raft/label, K6 in SURVEY §2.6)."""

from .classlabels import (
    get_ovr_labels,
    make_monotonic,
    unique_labels,
    unique_labels_padded,
)
from .merge_labels import merge_labels

__all__ = [
    "get_ovr_labels",
    "make_monotonic",
    "merge_labels",
    "unique_labels",
    "unique_labels_padded",
]
