"""Deterministic fault injection for the availability layer.

The failure modes the availability axis exists for — a replica that raises
or wedges mid-search, a WAL write that fails partway through a batch, a
process that dies between the WAL append and the memtable insert, a crash
in the middle of a snapshot write — cannot be provoked on demand by real
hardware, and tests that kill processes or sleep past deadlines are slow
and flaky. This module is the alternative: **named fault points** compiled
into the production code paths (one module-flag check when disarmed, the
same discipline as ``obs.metrics.disable()``), armed explicitly by tests
and the ``--fault-smoke`` bench rows.

Usage::

    from raft_tpu.testing import faults

    with faults.scope():                      # disarms everything on exit
        faults.inject("replica/search", exc=RuntimeError("replica died"),
                      match=lambda ctx: ctx.get("replica", "").endswith("/r0"))
        ...                                    # r0's scans now raise; its
        ...                                    # twin serves every query
        assert faults.fired("replica/search") > 0

Fault points in the tree (grep ``faults.fire`` for the live list):

- ``replica/search`` — fired per replica scan attempt inside
  :class:`raft_tpu.stream.ReplicatedShard` (ctx: ``replica`` name). An
  injected ``callback`` can advance the shard's injected clock instead of
  raising — that is how a WEDGED replica is simulated: the scan "takes"
  longer than the fencing deadline and trips the slow-strike breaker, with
  no wall-clock sleep anywhere.
- ``replica/upsert`` — fired per replica write inside
  ``ReplicatedShard.upsert`` (ctx: ``replica``); a raise marks the replica
  STALE (it missed an acknowledged write) and fences it from reads.
- ``wal/append`` — fired per record before it is written
  (:meth:`raft_tpu.stream.wal.WriteAheadLog.append`); arm with ``after=k``
  to fail the k-th record of a batch.
- ``wal/fsync`` — fired before each batched fsync.
- ``stream/post-wal`` — fired between the WAL append and the memtable
  insert in ``MutableIndex.upsert``/``delete`` — the crash window the
  replay path must cover (arm with :class:`SimulatedCrash`).
- ``serialize/atomic-write`` — fired between writing the temp file and the
  ``os.replace`` in :func:`raft_tpu.core.serialize.atomic_write`: a crash
  here must leave the previous snapshot readable.
- ``tier/fetch`` — fired per tiered-store gather
  (:meth:`raft_tpu.stream.tiered.TieredStore.fetch`; ctx: ``name``,
  ``residency``): a crash mid-refine-hop must recover via ``load()`` +
  WAL replay with id-for-id parity (the ``tiering`` suite pins it).
- ``reshard/split`` — fired per donor fold inside
  :meth:`raft_tpu.stream.ShardedMutableIndex.reshard` (ctx: ``donors``,
  ``action``), BEFORE the successors are built: a crash mid-migration
  leaves the mesh (and its on-disk manifest) on the old topology.
- ``reshard/flip`` — fired between the in-memory topology swap and the
  manifest write: the commit-window crash — recovery reads the OLD
  manifest and replays the donor shards' WALs, losing nothing (no write
  is admitted inside the window; the mesh write lock is held).
- ``reshard/manifest`` — fired immediately before the topology manifest's
  atomic write: a crash here also recovers to the old topology (the
  manifest's ``os.replace`` is the durable commit point of a reshard).

Every helper is thread-safe; ``fire`` holds no lock on the disarmed fast
path. Injected exceptions should derive from :class:`FaultError` (or any
caller-chosen type — the registry raises whatever it was given).
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Callable

from ..core.errors import RaftError, expects

__all__ = ["FaultError", "SimulatedCrash", "inject", "clear", "fire",
           "fired", "armed", "scope"]


class FaultError(RaftError):
    """Base type for injected failures (so test fences can catch exactly
    the injected class and nothing else)."""


class SimulatedCrash(FaultError):
    """An injected process death: the code path stops HERE, mid-operation,
    and recovery is proven by reopening the on-disk state — the in-memory
    object is considered gone. Derives from :class:`FaultError` (not
    ``BaseException``) so an un-simulated leak of one still fails tests
    loudly instead of killing the runner."""


class _Fault:
    __slots__ = ("exc", "callback", "times", "after", "match", "fired",
                 "skipped")

    def __init__(self, exc, callback, times, after, match):
        self.exc = exc
        self.callback = callback
        self.times = times        # None = every call once armed
        self.after = int(after)   # skip this many matching calls first
        self.match = match
        self.fired = 0
        self.skipped = 0


_lock = threading.Lock()
_points: dict[str, list[_Fault]] = {}
_counts: dict[str, int] = {}
_armed = False  # module fast-path flag: fire() is one read when False


def inject(point: str, exc: BaseException | None = None, *,
           callback: Callable[[dict], None] | None = None,
           times: int | None = None, after: int = 0,
           match: Callable[[dict], bool] | None = None) -> None:
    """Arm fault ``point``. ``exc`` is raised at each triggering call (or
    ``callback(ctx)`` runs — it may raise itself, or mutate state such as
    advancing an injected clock to simulate a hang). ``times`` bounds how
    many calls trigger (None = every one), ``after`` skips the first N
    matching calls (fail the k-th record of a batch), ``match(ctx)``
    restricts the fault to matching contexts (one replica of a group).
    Multiple injections on one point stack in arming order."""
    global _armed
    expects(exc is not None or callback is not None,
            "inject(%r) needs exc= or callback=", point)
    with _lock:
        _points.setdefault(point, []).append(
            _Fault(exc, callback, times, after, match))
        _armed = True


def clear(point: str | None = None) -> None:
    """Disarm one point (or everything); fired counts reset with it."""
    global _armed
    with _lock:
        if point is None:
            _points.clear()
            _counts.clear()
        else:
            _points.pop(point, None)
            _counts.pop(point, None)
        _armed = bool(_points)


def fire(point: str, **ctx) -> None:
    """Production-side hook: trigger any armed faults at ``point``. A
    single module-flag read when nothing is armed anywhere — safe on hot
    paths (the ``obs.metrics._enabled`` discipline)."""
    if not _armed:
        return
    with _lock:
        flist = _points.get(point)
        if not flist:
            return
        _counts[point] = _counts.get(point, 0) + 1
        todo = []
        for f in flist:
            if f.match is not None and not f.match(ctx):
                continue
            if f.skipped < f.after:
                f.skipped += 1
                continue
            if f.times is not None and f.fired >= f.times:
                continue
            f.fired += 1
            todo.append(f)
    # run actions OUTSIDE the lock: a callback may touch code that fires
    # other points (or re-enter inject/clear)
    for f in todo:
        if f.callback is not None:
            f.callback(dict(ctx, point=point))
        if f.exc is not None:
            raise f.exc


def fired(point: str) -> int:
    """How many times any armed fault at ``point`` actually triggered."""
    with _lock:
        return sum(f.fired for f in _points.get(point, ()))


def armed(point: str | None = None) -> bool:
    with _lock:
        return bool(_points if point is None else _points.get(point))


@contextmanager
def scope():
    """Context manager for tests: everything injected inside is disarmed
    on exit, pass or fail — a leaked fault must never poison the next
    test."""
    try:
        yield
    finally:
        clear()
