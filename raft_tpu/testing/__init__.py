"""raft_tpu.testing — deterministic test/bench harnesses.

:mod:`raft_tpu.testing.faults` is the fault-injection registry the
availability layer (replica failover, WAL durability, crash recovery) is
proven with: named fault points threaded through serve/stream fire injected
failures deterministically — no wall-clock sleeps, no real process kills —
so tier-1 can assert every failover and replay path (docs/streaming.md
"Durability & replication").
"""

from . import faults

__all__ = ["faults"]
