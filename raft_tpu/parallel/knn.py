"""Multi-chip exact kNN: shard the dataset, search locally, merge globally.

The reference leaves multi-GPU kNN to users composing raft::comms + per-shard
search + knn_merge_parts (SURVEY.md §5 "long-context" entry;
docs/source/using_comms.rst). Here it is a first-class driver: the dataset is
row-sharded over a mesh axis, every chip runs the local brute-force search on
its shard — the fused Pallas distance+top-k kernel (ops/fused_knn.py) when
the per-shard shapes qualify on TPU, the XLA GEMM+top_k pipeline otherwise —
and one all_gather + select_k merge produces the global result (the
reference's knn_merge_parts pattern, detail/knn_merge_parts.cuh): candidates
ride ICI, never the full distance matrix.

Non-divisible datasets self-pad: the tail shard is filled with masked rows
(the same trick the reference uses for padded inverted lists), so callers
never see the shard-divisibility invariant.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..comms.comms import Comms, replicated, shard_along
from ..core import tracing
from ..core.errors import expects
from ..distance.types import DistanceType, resolve_metric
from ..matrix.select_k import _select_k
from ..neighbors.brute_force import _bf_knn, _bf_knn_fused, _fused_eligible
from ..obs.instrument import instrument, nrows
from ._progcache import ProgramCache

__all__ = ["knn"]

# memoized jitted programs per (comms, static config) — releasable per
# communicator at mesh teardown (parallel.release_programs), since the
# cached closures pin the Comms/Mesh/devices they were staged for
_PROGRAMS = ProgramCache(maxsize=256)


def _knn_fn(comms: Comms, k: int, mt: DistanceType, metric_arg: float,
            tile: int, inner_tile: int, compute: str, use_fused: bool,
            shard_rows: int, has_keep: bool):
    """Memoized jitted program per static config. The drivers used to build
    a fresh closure + jax.jit wrapper on every call, which forced a full
    retrace per search — measured as a 38-45% driver overhead on a 1-device
    mesh (BASELINE.md "Round-5 parallel-driver overhead"); with the program
    cached the overhead is the collectives' true cost."""
    key = (comms, k, mt, metric_arg, tile, inner_tile, compute, use_fused,
           shard_rows, has_keep)
    return _PROGRAMS.get_or_build(key, lambda: _build_knn_fn(
        comms, k, mt, metric_arg, tile, inner_tile, compute, use_fused,
        shard_rows, has_keep))


def _build_knn_fn(comms: Comms, k: int, mt: DistanceType, metric_arg: float,
                  tile: int, inner_tile: int, compute: str, use_fused: bool,
                  shard_rows: int, has_keep: bool):
    size = comms.size()
    select_min = mt != DistanceType.InnerProduct

    def local_search(x_shard, q, keep_shard):
        with tracing.range("parallel.knn.local_search"):
            if use_fused:
                return _bf_knn_fused(x_shard, q, k, mt, compute, keep_shard)
            comp = "float32" if compute == "float32x3" else compute
            return _bf_knn(x_shard, q, k, mt, metric_arg,
                           min(tile, q.shape[0]), inner_tile, keep_shard,
                           compute=comp)

    def merge(d_loc, i_loc, m):
        with tracing.range("parallel.knn.merge"):
            i_glob = jnp.where(i_loc >= 0,
                               i_loc + comms.rank().astype(jnp.int32) * shard_rows,
                               -1)
            d_all = comms.allgather(d_loc)
            i_all = comms.allgather(i_glob)
            d_flat = jnp.moveaxis(d_all, 0, 1).reshape(m, size * k)
            i_flat = jnp.moveaxis(i_all, 0, 1).reshape(m, size * k)
            return _select_k(d_flat, i_flat, k, select_min)

    if has_keep:
        def step(x_shard, keep_shard, q):
            d_loc, i_loc = local_search(x_shard, q, keep_shard)
            return merge(d_loc, i_loc, q.shape[0])

        return jax.jit(comms.shard_map(
            step, in_specs=(P(comms.axis), P(comms.axis), P()),
            out_specs=(P(), P())))

    def step(x_shard, q):
        d_loc, i_loc = local_search(x_shard, q, None)
        return merge(d_loc, i_loc, q.shape[0])

    return jax.jit(comms.shard_map(
        step, in_specs=(P(comms.axis), P()), out_specs=(P(), P())))


@instrument("parallel.knn",
            items=lambda a, kw: nrows(a[2] if len(a) > 2 else kw["queries"]),
            labels=lambda a, kw: {"k": a[3] if len(a) > 3 else kw["k"],
                                  "size": (a[0] if a else kw["comms"]).size()})
def knn(comms: Comms, dataset, queries, k: int, metric="sqeuclidean", metric_arg: float = 2.0,
        tile: int = 2048, inner_tile: int = 512, compute: str = "float32"):
    """Distributed exact kNN (multi-chip analogue of brute_force.knn).

    ``dataset`` is sharded along ``comms.axis`` (row-wise; a non-divisible
    row count is padded with masked rows internally); ``queries`` are
    replicated. ``compute`` selects the local kernel's contraction mode
    ("float32" | "float32x3" | "bfloat16", as brute_force.knn). Returns
    replicated (distances (m, k), global indices). ``k`` must fit one shard's
    rows (the per-shard candidate width of the merge).
    """
    dataset = jnp.asarray(dataset)
    queries = jnp.asarray(queries)
    n, d = dataset.shape
    size = comms.size()
    n_pad = -(-n // size) * size
    shard_rows = n_pad // size
    expects(0 < k <= shard_rows,
            "k=%d must be <= per-shard rows (%d rows over %d shards)",
            k, shard_rows, size)
    mt = resolve_metric(metric)
    keep = None
    if n_pad != n:
        dataset = jnp.pad(dataset, ((0, n_pad - n), (0, 0)))
        keep = jnp.arange(n_pad) < n
    use_fused = _fused_eligible(mt, int(k), shard_rows, d, "exact", compute)
    x_sharded = shard_along(comms.mesh, comms.axis, dataset)
    q_repl = replicated(comms.mesh, queries)
    fn = _knn_fn(comms, int(k), mt, float(metric_arg), int(tile),
                 int(inner_tile), compute, bool(use_fused), int(shard_rows),
                 keep is not None)
    if keep is None:
        return fn(x_sharded, q_repl)
    keep_sh = shard_along(comms.mesh, comms.axis, keep)
    return fn(x_sharded, keep_sh, q_repl)
