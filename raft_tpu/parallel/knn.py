"""Multi-chip exact kNN: shard the dataset, search locally, merge globally.

The reference leaves multi-GPU kNN to users composing raft::comms + per-shard
search + knn_merge_parts (SURVEY.md §5 "long-context" entry;
docs/source/using_comms.rst). Here it is a first-class driver: the dataset is
row-sharded over a mesh axis, every chip runs the tiled brute-force search on
its shard (MXU GEMM + fused top-k), and one all_gather + select_k merge
produces the global result — candidates ride ICI, never the full distance
matrix.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..comms.comms import Comms, replicated, shard_along
from ..core.errors import expects
from ..distance.types import DistanceType, resolve_metric
from ..matrix.select_k import _select_k
from ..neighbors.brute_force import _bf_knn

__all__ = ["knn"]


def knn(comms: Comms, dataset, queries, k: int, metric="sqeuclidean", metric_arg: float = 2.0,
        tile: int = 2048, inner_tile: int = 512):
    """Distributed exact kNN (multi-chip analogue of brute_force.knn).

    ``dataset`` is sharded along ``comms.axis`` (row-wise, equal shards —
    pad the tail shard like the reference pads inverted lists); ``queries``
    are replicated. Returns replicated (distances (m, k), global indices).
    """
    dataset = jnp.asarray(dataset)
    queries = jnp.asarray(queries)
    n = dataset.shape[0]
    size = comms.size()
    expects(n % size == 0, "dataset rows (%d) must divide the mesh axis (%d); pad first", n, size)
    shard_rows = n // size
    expects(0 < k <= shard_rows, "k must be <= per-shard rows")
    mt = resolve_metric(metric)
    select_min = mt != DistanceType.InnerProduct

    def step(x_shard, q):
        # local exact search on this chip's rows
        d_loc, i_loc = _bf_knn(x_shard, q, k, mt, metric_arg,
                               min(tile, q.shape[0]), inner_tile)
        # shard-local → global ids
        i_glob = i_loc + comms.rank().astype(jnp.int32) * shard_rows
        # candidates ride ICI: (size, m, k) each
        d_all = comms.allgather(d_loc)
        i_all = comms.allgather(i_glob)
        m = q.shape[0]
        d_flat = jnp.moveaxis(d_all, 0, 1).reshape(m, size * k)
        i_flat = jnp.moveaxis(i_all, 0, 1).reshape(m, size * k)
        return _select_k(d_flat, i_flat, k, select_min)

    x_sharded = shard_along(comms.mesh, comms.axis, dataset)
    q_repl = replicated(comms.mesh, queries)
    fn = comms.shard_map(step, in_specs=(P(comms.axis), P()), out_specs=(P(), P()))
    return jax.jit(fn)(x_sharded, q_repl)
