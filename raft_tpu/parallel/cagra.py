"""Multi-chip CAGRA: one graph index per dataset shard, beam searches run
shard-local, candidates merge over ICI.

A CAGRA graph cannot be row-sharded naively — pruned edges cross arbitrary
rows, so a beam on one chip would constantly dereference vectors living on
another. The multi-GPU pattern the reference ecosystem uses instead (per-GPU
indexes over dataset partitions, query fan-out, heap merge — the raft::comms +
knn_merge_parts composition of docs/source/using_comms.rst and
detail/knn_merge_parts.cuh) maps cleanly to SPMD: each shard owns an
independent CAGRA graph over its rows (builds are embarrassingly parallel —
on a real pod every host builds its own shard), searches are replicated
queries against every shard's graph inside one shard_map, and a single
all_gather + select_k produces the global top-k. Recall of the merged result
is at least the per-shard recall: every shard contributes its own true local
top-k candidates.
"""

from __future__ import annotations

import dataclasses
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..comms.comms import Comms, replicated, shard_along
from ..core import tracing
from ..core.errors import expects
from ._progcache import ProgramCache
from ..distance.types import DistanceType
from ..matrix.select_k import _select_k
from ..obs.instrument import instrument, nrows
from ..random.rng import as_key
from ..neighbors.cagra import (CagraIndex, IndexParams, SearchParams, _cagra_search,
                               estimate_seed_pool, resolve_hop_impl,
                               resolve_max_iterations, resolve_seed_pool)
from ..neighbors.cagra import build as build_single

__all__ = ["ShardedCagraIndex", "build", "build_merged", "merged_builder",
           "search"]


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class ShardedCagraIndex:
    """Stacked per-shard CAGRA indexes: shard s owns dataset rows
    [s*rows_per_shard, (s+1)*rows_per_shard) of the original ordering."""

    dataset: jax.Array   # (S, n/S, d) — f32, or int8 for byte datasets
    graph: jax.Array     # (S, n/S, graph_degree) int32, shard-local ids
    metric: DistanceType = DistanceType.L2Expanded
    # "float32" | "int8" | "uint8" — same contract as CagraIndex.data_kind
    data_kind: str = "float32"

    @property
    def n_shards(self) -> int:
        return self.dataset.shape[0]

    @property
    def rows_per_shard(self) -> int:
        return self.dataset.shape[1]

    @property
    def dim(self) -> int:
        return self.dataset.shape[2]

    def tree_flatten(self):
        return (self.dataset, self.graph), (self.metric, self.data_kind)

    @classmethod
    def tree_unflatten(cls, aux, children):
        kind = aux[1] if len(aux) > 1 else "float32"
        return cls(*children, metric=aux[0], data_kind=kind)


@instrument("parallel.cagra.build",
            items=lambda a, kw: nrows(a[2] if len(a) > 2 else kw["dataset"]),
            labels=lambda a, kw: {"size": (a[0] if a else kw["comms"]).size()})
def build(comms: Comms, params: IndexParams, dataset) -> ShardedCagraIndex:
    """Build one CAGRA graph per shard (host loop; on a multi-host pod each
    host builds only its own shard — the graphs are fully independent)."""
    dataset = jnp.asarray(dataset)
    n = dataset.shape[0]
    size = comms.size()
    expects(n % size == 0, "dataset rows (%d) must divide the mesh axis (%d); pad first",
            n, size)
    rows = n // size
    expects(params.graph_degree < rows, "graph_degree must be < rows per shard (%d)", rows)
    with tracing.range("parallel.cagra.build.shards"):
        shards = [build_single(params, dataset[s * rows:(s + 1) * rows])
                  for s in range(size)]
    return ShardedCagraIndex(
        dataset=jnp.stack([s.dataset for s in shards]),
        graph=jnp.stack([s.graph for s in shards]),
        metric=shards[0].metric,
        data_kind=shards[0].data_kind,
    )


def _shard_bounds(n: int, size: int) -> list[tuple[int, int]]:
    """Contiguous near-equal shard row ranges; the first ``n % size`` shards
    carry one extra row. Unlike the shard_map drivers there is NO
    divisibility requirement — the merged build is a host loop, so uneven
    live-row counts (the compaction-rebuild case) need no padding."""
    base, extra = divmod(n, size)
    bounds, lo = [], 0
    for s in range(size):
        hi = lo + base + (1 if s < extra else 0)
        bounds.append((lo, hi))
        lo = hi
    return bounds


@instrument("parallel.cagra.build_merged",
            items=lambda a, kw: nrows(a[2] if len(a) > 2 else kw["dataset"]),
            labels=lambda a, kw: {"size": (a[0] if a else kw["comms"]).size()})
def build_merged(comms: Comms, params: IndexParams, dataset,
                 res=None) -> CagraIndex:
    """Sharded CAGRA build merged into ONE plain :class:`CagraIndex`.

    Each of the mesh's S shards builds an independent graph over its
    contiguous row range, then the per-shard graphs concatenate — edge ids
    offset to global — into a single index over the full dataset that every
    single-chip consumer (``cagra.search``, serve hooks,
    ``stream.MutableIndex``, save/load) takes unchanged. NOTE the loop runs
    the S builds SERIALLY in this process (like :func:`build`): the
    measured win below is the smaller-shard superlinearity alone. The
    shard builds are independent, so a multi-host deployment CAN run one
    per host and concatenate (each shard is ``build_single`` on a
    contiguous slice), but this driver does not orchestrate that.

    Why this is a *build-speed* lever: the build's dominant cost is the
    IVF-PQ self-search, whose per-row cost grows with the shard's row
    count, so S shard-local builds cost well under the global build's
    self-search even run serially on one chip (r07 CPU artifact: warm 180 s
    -> 103 s at 32k/8 — the whole measured win; no cross-host parallelism
    is involved).

    Recall contract: the merged graph has no cross-shard edges, so ONE
    beam over it splits across S disconnected subgraphs — widen itopk by
    ~S/2-S/4 to hold the single-graph operating point (measured at 32k/8:
    0.9371 @ itopk32 -> 0.995 @ 64 -> 0.9999 @ 128 vs single 1.0 @ 32), or
    search through the per-shard composition (:func:`search` on
    :func:`build`'s ShardedCagraIndex), which runs S full-width beams and
    measured NO recall cost (r06, 64k/8). Sizing details in
    docs/using_comms.md; keep shards above ~4k rows (below that the graph
    regime itself stops paying — same bound as :func:`build`).
    """
    x = jnp.asarray(dataset)
    n = x.shape[0]
    size = comms.size()
    bounds = _shard_bounds(n, size)
    min_rows = min(hi - lo for lo, hi in bounds)
    expects(params.graph_degree < min_rows,
            "graph_degree (%d) must be < rows per shard (%d)",
            params.graph_degree, min_rows)
    with tracing.range("parallel.cagra.build_merged.shards"):
        shards = [build_single(params, x[lo:hi], res=res)
                  for lo, hi in bounds]
    graph = jnp.concatenate(
        [s.graph + jnp.int32(lo) for s, (lo, _) in zip(shards, bounds)])
    merged = jnp.concatenate([s.dataset for s in shards])
    # seed-pool hint re-estimated over the MERGED graph: local-mode counts
    # add across shards, so per-shard hints undercount by up to S x
    hint = estimate_seed_pool(merged, graph, seed=params.seed)
    return CagraIndex(dataset=merged, graph=graph, metric=shards[0].metric,
                      data_kind=shards[0].data_kind, seed_pool_hint=hint)


def merged_builder(comms: Comms, params: IndexParams):
    """A ``builder=`` callable for :class:`raft_tpu.stream.MutableIndex`:
    rebuild compactions construct the successor sealed index with
    :func:`build_merged`, cutting the compaction wall that bounds the
    sustainable write churn rate (docs/streaming.md) by the sharded
    build's measured factor (~-43% at 32k/8 even serially — see
    :func:`build_merged` for what is and is not parallel)."""
    def build_fn(dataset, res=None):
        return build_merged(comms, params, dataset, res=res)

    return build_fn


@instrument("parallel.cagra.search",
            items=lambda a, kw: nrows(a[3] if len(a) > 3 else kw["queries"]),
            labels=lambda a, kw: {"k": a[4] if len(a) > 4 else kw["k"],
                                  "size": (a[0] if a else kw["comms"]).size()})
def search(comms: Comms, params: SearchParams, index: ShardedCagraIndex,
           queries, k: int):
    """Distributed CAGRA search: per-shard beam search + ICI merge.

    Returns replicated (distances (m, k), global ids (m, k)); ids refer to
    the original (pre-sharding) dataset row ordering.
    """
    from ..neighbors.brute_force import _coerce_queries

    queries = jnp.asarray(queries)
    expects(queries.ndim == 2 and queries.shape[1] == index.dim, "query dim mismatch")
    expects(k <= params.itopk_size, "k must be <= itopk_size")
    queries = _coerce_queries(index.data_kind, queries)
    size = comms.size()
    expects(index.n_shards == size, "index has %d shards but mesh axis is %d",
            index.n_shards, size)
    rows = index.rows_per_shard
    itopk = params.itopk_size
    max_iter = resolve_max_iterations(params)
    sqrt_out = index.metric in (DistanceType.L2SqrtExpanded,
                                DistanceType.L2SqrtUnexpanded)
    # shared resolution with the single-chip driver: -1 (auto) must not
    # leak into _cagra_search (a negative pool silently means random
    # entries), and hop_impl picks the fused Pallas hop when eligible.
    # Per-shard indexes carry no seed_pool_hint; auto falls to the default.
    seed_pool = resolve_seed_pool(params)  # _cagra_search clamps to shard rows
    hop_impl = resolve_hop_impl(
        params, index.graph.shape[-1], index.dim,
        itemsize=index.dataset.dtype.itemsize)

    mesh, axis = comms.mesh, comms.axis
    args = (
        shard_along(mesh, axis, index.dataset),
        shard_along(mesh, axis, index.graph),
        replicated(mesh, queries),
    )
    fn = _cagra_search_fn(comms, int(k), int(itopk), int(max_iter),
                          int(params.search_width), bool(sqrt_out),
                          int(seed_pool), hop_impl, index.metric,
                          int(rows))
    return fn(*args, replicated(mesh, as_key(params.seed)))


_PROGRAMS = ProgramCache(maxsize=256)


def _cagra_search_fn(comms: Comms, k: int, itopk: int, max_iter: int,
                     width: int, sqrt_out: bool, seed_pool: int,
                     hop_impl: str, metric, rows: int):
    """Memoized jitted program per static config (see parallel/knn._knn_fn
    — a fresh jax.jit wrapper per call forces a retrace per search);
    releasable per communicator (parallel.release_programs)."""
    key = (comms, k, itopk, max_iter, width, sqrt_out, seed_pool, hop_impl,
           metric, rows)
    return _PROGRAMS.get_or_build(key, lambda: _build_cagra_search_fn(
        comms, k, itopk, max_iter, width, sqrt_out, seed_pool, hop_impl,
        metric, rows))


def _build_cagra_search_fn(comms: Comms, k: int, itopk: int, max_iter: int,
                           width: int, sqrt_out: bool, seed_pool: int,
                           hop_impl: str, metric, rows: int):
    size = comms.size()
    inner = metric == DistanceType.InnerProduct

    def step(data, graph, q, key):
        with tracing.range("parallel.cagra.local_search"):
            shard = CagraIndex(dataset=data[0], graph=graph[0], metric=metric)
            d_loc, i_loc = _cagra_search(shard, q, key, k, itopk,
                                         max_iter, width, sqrt_out, seed_pool,
                                         hop_impl)
        with tracing.range("parallel.cagra.merge"):
            i_glob = jnp.where(i_loc >= 0,
                               i_loc + comms.rank().astype(jnp.int32) * rows, i_loc)
            d_all = comms.allgather(d_loc)
            i_all = comms.allgather(i_glob)
            m = q.shape[0]
            d_flat = jnp.moveaxis(d_all, 0, 1).reshape(m, size * k)
            i_flat = jnp.moveaxis(i_all, 0, 1).reshape(m, size * k)
            return _select_k(d_flat, i_flat, k, not inner)

    axis = comms.axis
    return jax.jit(comms.shard_map(
        step,
        in_specs=(P(axis), P(axis), P(), P()),
        out_specs=(P(), P()),
    ))
