"""Releasable memoization for the distributed drivers' jitted programs.

The drivers memoize one jitted shard_map program per static config
(``parallel/knn._knn_fn``, ``parallel/cagra._cagra_search_fn``) — the
Round-5 fix for the fresh-closure retrace overhead. Those caches key on
the live :class:`~raft_tpu.comms.Comms` instance, and the cached program
closures hold it (and through it the Mesh and its devices) strongly: a
retired mesh stays pinned in memory for the cache's lifetime. That was
fine when a process owned one mesh forever; the sharded serving tier
churns mesh configs, so the caches must be evictable per communicator.

This is the plain-dict replacement for the old ``functools.lru_cache``:
same bounded-LRU semantics and hit behavior (same key → the SAME program
object, so nothing retraces), plus :meth:`release` — drop every entry
keyed on one comms — and :meth:`clear`. Callers go through
:func:`raft_tpu.parallel.release_programs` at mesh teardown; pair it with
``jax.clear_caches()`` when the goal is releasing device memory too (jax's
own trace/executable caches also reference the mesh).
"""

from __future__ import annotations

import collections
import threading
from typing import Callable

__all__ = ["ProgramCache"]


class ProgramCache:
    """Thread-safe bounded LRU keyed on ``(comms, *static_config)``.

    The first key element must be the communicator — that is what
    :meth:`release` matches on. ``build`` runs UNDER the cache lock: it
    only constructs a jit wrapper (no trace, no compile — cheap and
    non-reentrant), and an insert that raced a concurrent
    :meth:`release` of the same communicator would otherwise re-pin the
    mesh the release just claimed to free."""

    def __init__(self, maxsize: int = 256):
        self.maxsize = int(maxsize)
        self._d: collections.OrderedDict = collections.OrderedDict()
        self._lock = threading.Lock()

    def get_or_build(self, key: tuple, build: Callable):
        with self._lock:
            fn = self._d.get(key)
            if fn is None:
                fn = self._d[key] = build()
                while len(self._d) > self.maxsize:
                    self._d.popitem(last=False)
            else:
                self._d.move_to_end(key)
            return fn

    def release(self, comms) -> int:
        """Evict every program whose key's communicator == ``comms``;
        returns how many were dropped."""
        with self._lock:
            dead = [k for k in self._d if k[0] == comms]
            for k in dead:
                del self._d[k]
        return len(dead)

    def clear(self) -> None:
        with self._lock:
            self._d.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)

    def keys_for(self, comms) -> list:
        """The cached keys pinned to one communicator (leak-check hook)."""
        with self._lock:
            return [k for k in self._d if k[0] == comms]
