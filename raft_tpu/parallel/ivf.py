"""Multi-chip IVF-Flat search: shard the inverted lists, probe locally,
merge candidates over ICI.

The reference leaves multi-GPU ANN serving to users composing raft::comms
with per-shard indexes and knn_merge_parts (SURVEY.md §5; the cuML/cuGraph
pattern over docs/source/using_comms.rst). Here it is a first-class driver:
the padded list arrays (and their coarse centers) are sharded along
``n_lists`` over the mesh axis; each chip ranks its own local centers and
scans its local top-``n_probes`` lists, then one all_gather + select_k merge
produces global results. Per-shard probing means each chip's scan work is
identical (batch-synchronous, no load imbalance) and the effective probe
count is ``size x n_probes`` local lists rather than a global top-n_probes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..comms.comms import Comms, replicated, shard_along
from ..core.errors import expects
from ..distance.types import DistanceType
from ..matrix.select_k import _select_k
from ..neighbors.ivf_flat import IvfFlatIndex, SearchParams, _ivf_search

__all__ = ["search"]


def _pad_lists_to_multiple(index: IvfFlatIndex, size: int) -> IvfFlatIndex:
    """Pad the index with empty lists so n_lists divides the mesh axis —
    needed because sub-list splitting (_list_utils.split_oversized) makes
    n_lists data-dependent. Padding centers sit at +1e30 so L2 coarse scores
    rank them last; even if probed, their slots are all id -1 / +inf and
    cannot win the merge. Inner-product has no constant worst-rank center (the
    sign of q·c depends on q), so there the list count must already divide."""
    L = index.n_lists
    pad = (-L) % size
    if pad == 0:
        return index
    expects(
        index.metric != DistanceType.InnerProduct,
        "inner-product distributed search needs n_lists (%d) divisible by the "
        "mesh axis (%d) — rebuild with a different n_lists",
        L, size,
    )
    d = index.dim
    cap = index.capacity
    return IvfFlatIndex(
        centers=jnp.concatenate(
            [index.centers, jnp.full((pad, d), 1e30, index.centers.dtype)]
        ),
        list_data=jnp.concatenate(
            [index.list_data, jnp.zeros((pad, cap, d), index.list_data.dtype)]
        ),
        list_ids=jnp.concatenate(
            [index.list_ids, jnp.full((pad, cap), -1, jnp.int32)]
        ),
        list_norms=jnp.concatenate(
            [index.list_norms, jnp.full((pad, cap), jnp.inf, jnp.float32)]
        ),
        list_sizes=jnp.concatenate(
            [index.list_sizes, jnp.zeros((pad,), jnp.int32)]
        ),
        metric=index.metric,
    )


def search(comms: Comms, params: SearchParams, index: IvfFlatIndex, queries, k: int):
    """Distributed IVF-Flat search (multi-chip analogue of ivf_flat.search).

    The index's lists are sharded along ``comms.axis``; every shard probes its
    own ``n_probes`` best local lists and the candidates merge with one
    all_gather + select_k. With L lists over S chips each chip scans
    n_probes of its L/S lists, so total probed work is S x n_probes lists —
    recall can only exceed the single-chip setting at equal ``n_probes``.

    Returns replicated (distances (m, k), global ids (m, k)).
    """
    queries = jnp.asarray(queries)
    size = comms.size()
    index = _pad_lists_to_multiple(index, size)
    L = index.n_lists
    lists_per_shard = L // size
    n_probes = min(params.n_probes, lists_per_shard)
    expects(0 < k <= n_probes * index.capacity, "k exceeds per-shard candidate pool")
    inner = index.metric == DistanceType.InnerProduct

    def step(centers, data, ids, norms, sizes, q):
        shard = IvfFlatIndex(centers, data, ids, norms, sizes, index.metric)
        d_loc, i_loc = _ivf_search(
            shard, q, n_probes, k,
            query_tile=min(256, q.shape[0]), probe_chunk=n_probes,
            metric=index.metric,
        )
        d_all = comms.allgather(d_loc)  # (S, m, k) over ICI
        i_all = comms.allgather(i_loc)
        m = q.shape[0]
        d_flat = jnp.moveaxis(d_all, 0, 1).reshape(m, size * k)
        i_flat = jnp.moveaxis(i_all, 0, 1).reshape(m, size * k)
        return _select_k(d_flat, i_flat, k, not inner)

    mesh, axis = comms.mesh, comms.axis
    args = (
        shard_along(mesh, axis, index.centers),
        shard_along(mesh, axis, index.list_data),
        shard_along(mesh, axis, index.list_ids),
        shard_along(mesh, axis, index.list_norms),
        shard_along(mesh, axis, index.list_sizes),
        replicated(mesh, queries),
    )
    fn = comms.shard_map(
        step,
        in_specs=(P(axis), P(axis), P(axis), P(axis), P(axis), P()),
        out_specs=(P(), P()),
    )
    return jax.jit(fn)(*args)
