"""Multi-chip IVF-Flat search: shard the inverted lists, probe locally,
merge candidates over ICI.

The reference leaves multi-GPU ANN serving to users composing raft::comms
with per-shard indexes and knn_merge_parts (SURVEY.md §5; the cuML/cuGraph
pattern over docs/source/using_comms.rst). Here it is a first-class driver:
the padded list arrays (and their coarse centers) are sharded along
``n_lists`` over the mesh axis; each chip ranks its own local centers and
scans its local top-``n_probes`` lists, then one all_gather + select_k merge
produces global results. Per-shard probing means each chip's scan work is
identical (batch-synchronous, no load imbalance) and the effective probe
count is ``size x n_probes`` local lists rather than a global top-n_probes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..comms.comms import Comms, replicated, shard_along
from ..core.errors import expects
from ..distance.types import DistanceType
from ..matrix.select_k import _select_k
from ..neighbors.ivf_flat import IvfFlatIndex, SearchParams, _ivf_search

__all__ = ["search", "search_pq"]


def _pad_lists_to_multiple(index: IvfFlatIndex, size: int) -> IvfFlatIndex:
    """Pad the index with empty lists so n_lists divides the mesh axis —
    needed because sub-list splitting (_list_utils.split_oversized) makes
    n_lists data-dependent. Padding centers sit at +1e30 so L2 coarse scores
    rank them last; even if probed, their slots are all id -1 / +inf and
    cannot win the merge. Inner-product has no constant worst-rank center (the
    sign of q·c depends on q), so there the list count must already divide."""
    L = index.n_lists
    pad = (-L) % size
    if pad == 0:
        return index
    expects(
        index.metric != DistanceType.InnerProduct,
        "inner-product distributed search needs n_lists (%d) divisible by the "
        "mesh axis (%d) — rebuild with a different n_lists",
        L, size,
    )
    d = index.dim
    cap = index.capacity
    return IvfFlatIndex(
        centers=jnp.concatenate(
            [index.centers, jnp.full((pad, d), 1e30, index.centers.dtype)]
        ),
        list_data=jnp.concatenate(
            [index.list_data, jnp.zeros((pad, cap, d), index.list_data.dtype)]
        ),
        list_ids=jnp.concatenate(
            [index.list_ids, jnp.full((pad, cap), -1, jnp.int32)]
        ),
        list_norms=jnp.concatenate(
            [index.list_norms, jnp.full((pad, cap), jnp.inf, jnp.float32)]
        ),
        list_sizes=jnp.concatenate(
            [index.list_sizes, jnp.zeros((pad,), jnp.int32)]
        ),
        metric=index.metric,
    )


def search(comms: Comms, params: SearchParams, index: IvfFlatIndex, queries, k: int):
    """Distributed IVF-Flat search (multi-chip analogue of ivf_flat.search).

    The index's lists are sharded along ``comms.axis``; every shard probes its
    own ``n_probes`` best local lists and the candidates merge with one
    all_gather + select_k. With L lists over S chips each chip scans
    n_probes of its L/S lists, so total probed work is S x n_probes lists —
    recall can only exceed the single-chip setting at equal ``n_probes``.

    Returns replicated (distances (m, k), global ids (m, k)).
    """
    queries = jnp.asarray(queries)
    size = comms.size()
    index = _pad_lists_to_multiple(index, size)
    L = index.n_lists
    lists_per_shard = L // size
    n_probes = min(params.n_probes, lists_per_shard)
    expects(0 < k <= n_probes * index.capacity, "k exceeds per-shard candidate pool")
    inner = index.metric == DistanceType.InnerProduct

    def step(centers, data, ids, norms, sizes, q):
        shard = IvfFlatIndex(centers, data, ids, norms, sizes, index.metric)
        d_loc, i_loc = _ivf_search(
            shard, q, n_probes, k,
            query_tile=min(256, q.shape[0]), probe_chunk=n_probes,
            metric=index.metric,
        )
        d_all = comms.allgather(d_loc)  # (S, m, k) over ICI
        i_all = comms.allgather(i_loc)
        m = q.shape[0]
        d_flat = jnp.moveaxis(d_all, 0, 1).reshape(m, size * k)
        i_flat = jnp.moveaxis(i_all, 0, 1).reshape(m, size * k)
        return _select_k(d_flat, i_flat, k, not inner)

    mesh, axis = comms.mesh, comms.axis
    args = (
        shard_along(mesh, axis, index.centers),
        shard_along(mesh, axis, index.list_data),
        shard_along(mesh, axis, index.list_ids),
        shard_along(mesh, axis, index.list_norms),
        shard_along(mesh, axis, index.list_sizes),
        replicated(mesh, queries),
    )
    fn = comms.shard_map(
        step,
        in_specs=(P(axis), P(axis), P(axis), P(axis), P(axis), P()),
        out_specs=(P(), P()),
    )
    return jax.jit(fn)(*args)


def _pad_pq_lists(index, size: int):
    """Pad an IvfPqIndex with empty lists so n_lists divides the mesh axis
    (same trick as _pad_lists_to_multiple: far-away centers rank last in the
    L2 coarse scoring; padded lists are size-0 so their slots can never win)."""
    from ..neighbors.ivf_pq import IvfPqIndex

    L = index.n_lists
    pad = (-L) % size
    if pad == 0:
        return index
    expects(
        index.metric != DistanceType.InnerProduct,
        "inner-product distributed search needs n_lists (%d) divisible by the "
        "mesh axis (%d) — rebuild with a different n_lists",
        L, size,
    )
    cap = index.capacity
    pq_dim = index.list_codes.shape[-1]
    far = 1e15
    codebooks = index.codebooks
    if index.codebook_kind == "per_cluster":
        codebooks = jnp.concatenate(
            [codebooks, jnp.zeros((pad,) + codebooks.shape[1:], codebooks.dtype)])
    return IvfPqIndex(
        centers=jnp.concatenate(
            [index.centers, jnp.full((pad, index.dim), far, index.centers.dtype)]),
        centers_rot=jnp.concatenate(
            [index.centers_rot,
             jnp.full((pad, index.centers_rot.shape[1]), far, index.centers_rot.dtype)]),
        rotation=index.rotation,
        codebooks=codebooks,
        list_codes=jnp.concatenate(
            [index.list_codes, jnp.zeros((pad, cap, pq_dim), index.list_codes.dtype)]),
        list_ids=jnp.concatenate(
            [index.list_ids, jnp.full((pad, cap), -1, jnp.int32)]),
        list_sizes=jnp.concatenate(
            [index.list_sizes, jnp.zeros((pad,), jnp.int32)]),
        list_consts=jnp.concatenate(
            [index.list_consts,
             jnp.zeros((pad, index.list_consts.shape[1]), jnp.float32)]),
        metric=index.metric,
        codebook_kind=index.codebook_kind,
        pq_bits=index.pq_bits,
        split_factor=index.split_factor,
        pq_split=index.pq_split,
    )


def search_pq(comms: Comms, params, index, queries, k: int,
              res=None):
    """Distributed IVF-PQ search: lists sharded over the mesh axis, local LUT
    scans, one all_gather + select_k merge (the same composition as IVF-Flat
    ``search`` above; reference pattern: per-shard indexes + knn_merge_parts,
    docs/source/using_comms.rst + detail/knn_merge_parts.cuh).

    ``params`` is :class:`raft_tpu.neighbors.ivf_pq.SearchParams`. Distances
    are PQ-approximate, like the single-chip search; run
    :func:`raft_tpu.neighbors.refine` against the (locally stored) dataset
    shard to sharpen candidates — the PQ index itself carries no raw vectors.

    Returns replicated (distances (m, k), global ids (m, k)).
    """
    from ..core.resources import default_resources
    from ..neighbors._list_utils import (plan_search_tiles,
                                         pq_scan_bytes_per_probe_row)
    from ..neighbors.ivf_pq import IvfPqIndex, _pq_search

    res = res or default_resources()
    queries = jnp.asarray(queries)
    size = comms.size()
    index = _pad_pq_lists(index, size)
    L = index.n_lists
    lists_per_shard = L // size
    n_probes = min(params.n_probes, lists_per_shard)
    expects(0 < k <= n_probes * index.capacity, "k exceeds per-shard candidate pool")
    # same workspace model as the single-chip ivf_pq.search, with shard-local
    # n_probes/capacity
    n_codes = index.codebooks.shape[-2]
    query_tile, probe_chunk = plan_search_tiles(
        queries.shape[0], n_probes, int(k), index.capacity,
        bytes_per_probe_row=pq_scan_bytes_per_probe_row(
            index.capacity, index.pq_dim, n_codes),
        budget_bytes=res.workspace_bytes,
        max_query_tile=128,
    )
    inner = index.metric == DistanceType.InnerProduct
    per_cluster = index.codebook_kind == "per_cluster"
    expects(params.lut_dtype in ("float32", "bfloat16", "int8"),
            "lut_dtype must be 'float32', 'bfloat16' or 'int8', got %r",
            params.lut_dtype)
    # same validation + resolution as the single-chip search (auto = the
    # one-hot contraction, fastest measured — BASELINE.md r04 scan study),
    # and the same clear error for a pq_split index missing its cross terms
    from ..neighbors.ivf_pq import _check_split_consts, resolve_scan_impl

    _check_split_consts(index)
    scan_impl = resolve_scan_impl(params, index, n_codes)
    expects(params.scan_order in ("auto", "tiled"),
            "the distributed search runs the tiled scan order; "
            "scan_order=%r is single-chip only", params.scan_order)

    def step(centers, centers_rot, codebooks, codes, ids, sizes, consts, q):
        shard = IvfPqIndex(
            centers, centers_rot, index.rotation, codebooks, codes, ids, sizes,
            list_consts=consts,
            metric=index.metric, codebook_kind=index.codebook_kind,
            pq_bits=index.pq_bits, split_factor=index.split_factor,
            pq_split=index.pq_split)
        d_loc, i_loc = _pq_search(
            shard, q, n_probes, k,
            query_tile=query_tile, probe_chunk=probe_chunk,
            metric=index.metric, codebook_kind=index.codebook_kind,
            lut_dtype=params.lut_dtype, scan_impl=scan_impl)
        d_all = comms.allgather(d_loc)
        i_all = comms.allgather(i_loc)
        m = q.shape[0]
        d_flat = jnp.moveaxis(d_all, 0, 1).reshape(m, size * k)
        i_flat = jnp.moveaxis(i_all, 0, 1).reshape(m, size * k)
        return _select_k(d_flat, i_flat, k, not inner)

    mesh, axis = comms.mesh, comms.axis
    cb_spec = P(axis) if per_cluster else P()
    cb_arg = (shard_along(mesh, axis, index.codebooks) if per_cluster
              else replicated(mesh, index.codebooks))
    args = (
        shard_along(mesh, axis, index.centers),
        shard_along(mesh, axis, index.centers_rot),
        cb_arg,
        shard_along(mesh, axis, index.list_codes),
        shard_along(mesh, axis, index.list_ids),
        shard_along(mesh, axis, index.list_sizes),
        shard_along(mesh, axis, index.list_consts),
        replicated(mesh, queries),
    )
    fn = comms.shard_map(
        step,
        in_specs=(P(axis), P(axis), cb_spec, P(axis), P(axis), P(axis), P(axis), P()),
        out_specs=(P(), P()),
    )
    return jax.jit(fn)(*args)
