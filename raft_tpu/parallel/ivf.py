"""Multi-chip IVF: distributed BUILD (no chip ever holds the full dataset)
and distributed SEARCH (shard the inverted lists, probe locally, merge
candidates over ICI).

The reference leaves multi-GPU ANN serving to users composing raft::comms
with per-shard indexes and knn_merge_parts (SURVEY.md §5; the cuML/cuGraph
pattern over docs/source/using_comms.rst). Here both halves are first-class
drivers:

- **build/build_pq/extend** (VERDICT r4 #3): dataset rows sharded over the
  mesh axis; coarse centers via the psum-EM balanced k-means (the cuML MNMG
  k-means pattern, docs/source/using_comms.rst:1-40); every per-row step
  (assignment, residual encode, norms) runs shard-local; the padded list
  arrays are then materialized ALREADY SHARDED BY LISTS with one
  S-step psum loop whose working set is one list-block (L/S lists) — at no
  point does any chip hold the full dataset or the full index.
- **search/search_pq**: the padded list arrays (and their coarse centers)
  are sharded along ``n_lists``; each chip ranks its own local centers and
  scans its local top-``n_probes`` lists, then one all_gather + select_k
  merge produces global results. Per-shard probing means each chip's scan
  work is identical (batch-synchronous, no load imbalance) and the effective
  probe count is ``size x n_probes`` local lists. A build()-produced index
  feeds search() without any resharding gather: the arrays already carry the
  list sharding the search expects.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..comms.comms import Comms, replicated, shard_along
from ..core import tracing
from ..core.errors import expects
from ..distance.types import DistanceType
from ..matrix.select_k import _select_k
from ._progcache import ProgramCache
from ..neighbors.ivf_flat import IvfFlatIndex, SearchParams, _ivf_search
from ..obs.instrument import instrument, nrows

__all__ = ["build", "build_pq", "extend", "search", "search_pq"]


def _pad_lists_to_multiple(index: IvfFlatIndex, size: int) -> IvfFlatIndex:
    """Pad the index with empty lists so n_lists divides the mesh axis —
    needed because sub-list splitting (_list_utils.split_oversized) makes
    n_lists data-dependent. Padding centers sit at +1e30 so L2 coarse scores
    rank them last; even if probed, their slots are all id -1 / +inf and
    cannot win the merge. Inner-product has no constant worst-rank center (the
    sign of q·c depends on q), so there the list count must already divide."""
    L = index.n_lists
    pad = (-L) % size
    if pad == 0:
        return index
    expects(
        index.metric != DistanceType.InnerProduct,
        "inner-product distributed search needs n_lists (%d) divisible by the "
        "mesh axis (%d) — rebuild with a different n_lists",
        L, size,
    )
    d = index.dim
    cap = index.capacity
    return IvfFlatIndex(
        centers=jnp.concatenate(
            [index.centers, jnp.full((pad, d), 1e30, index.centers.dtype)]
        ),
        list_data=jnp.concatenate(
            [index.list_data, jnp.zeros((pad, cap, d), index.list_data.dtype)]
        ),
        list_ids=jnp.concatenate(
            [index.list_ids, jnp.full((pad, cap), -1, jnp.int32)]
        ),
        list_norms=jnp.concatenate(
            [index.list_norms, jnp.full((pad, cap), jnp.inf, jnp.float32)]
        ),
        list_sizes=jnp.concatenate(
            [index.list_sizes, jnp.zeros((pad,), jnp.int32)]
        ),
        metric=index.metric,
        split_factor=index.split_factor,
        data_kind=index.data_kind,
    )


_PROGRAMS = ProgramCache(maxsize=256)


def _flat_search_fn(comms: Comms, n_probes: int, k: int, metric,
                    split_factor: float, data_kind: str):
    """Memoized jitted program per static config (see parallel/knn._knn_fn:
    a fresh jax.jit wrapper per call was measured as 38-45% overhead);
    releasable per communicator (parallel.release_programs)."""
    key = (comms, "flat", n_probes, k, metric, split_factor, data_kind)
    return _PROGRAMS.get_or_build(key, lambda: _build_flat_search_fn(
        comms, n_probes, k, metric, split_factor, data_kind))


def _build_flat_search_fn(comms: Comms, n_probes: int, k: int, metric,
                          split_factor: float, data_kind: str):
    size = comms.size()
    inner = metric == DistanceType.InnerProduct

    def step(centers, data, ids, norms, sizes, q):
        with tracing.range("parallel.ivf.local_search"):
            shard = IvfFlatIndex(centers, data, ids, norms, sizes, metric,
                                 split_factor, data_kind)
            d_loc, i_loc = _ivf_search(
                shard, q, n_probes, k,
                query_tile=min(256, q.shape[0]), probe_chunk=n_probes,
                metric=metric,
            )
        with tracing.range("parallel.ivf.merge"):
            d_all = comms.allgather(d_loc)  # (S, m, k) over ICI
            i_all = comms.allgather(i_loc)
            m = q.shape[0]
            d_flat = jnp.moveaxis(d_all, 0, 1).reshape(m, size * k)
            i_flat = jnp.moveaxis(i_all, 0, 1).reshape(m, size * k)
            return _select_k(d_flat, i_flat, k, not inner)

    axis = comms.axis
    return jax.jit(comms.shard_map(
        step,
        in_specs=(P(axis), P(axis), P(axis), P(axis), P(axis), P()),
        out_specs=(P(), P()),
    ))


@instrument("parallel.ivf.search",
            items=lambda a, kw: nrows(a[3] if len(a) > 3 else kw["queries"]),
            labels=lambda a, kw: {"k": a[4] if len(a) > 4 else kw["k"],
                                  "size": (a[0] if a else kw["comms"]).size()})
def search(comms: Comms, params: SearchParams, index: IvfFlatIndex, queries, k: int):
    """Distributed IVF-Flat search (multi-chip analogue of ivf_flat.search).

    The index's lists are sharded along ``comms.axis``; every shard probes its
    own ``n_probes`` best local lists and the candidates merge with one
    all_gather + select_k. With L lists over S chips each chip scans
    n_probes of its L/S lists, so total probed work is S x n_probes lists —
    recall can only exceed the single-chip setting at equal ``n_probes``.

    Returns replicated (distances (m, k), global ids (m, k)).
    """
    from ..neighbors.brute_force import _coerce_queries

    queries = _coerce_queries(index.data_kind, jnp.asarray(queries))
    size = comms.size()
    index = _pad_lists_to_multiple(index, size)
    L = index.n_lists
    lists_per_shard = L // size
    n_probes = min(params.n_probes, lists_per_shard)
    expects(0 < k <= n_probes * index.capacity, "k exceeds per-shard candidate pool")

    mesh, axis = comms.mesh, comms.axis
    args = (
        shard_along(mesh, axis, index.centers),
        shard_along(mesh, axis, index.list_data),
        shard_along(mesh, axis, index.list_ids),
        shard_along(mesh, axis, index.list_norms),
        shard_along(mesh, axis, index.list_sizes),
        replicated(mesh, queries),
    )
    fn = _flat_search_fn(comms, int(n_probes), int(k), index.metric,
                         float(index.split_factor), index.data_kind)
    return fn(*args)


def _pad_pq_lists(index, size: int):
    """Pad an IvfPqIndex with empty lists so n_lists divides the mesh axis
    (same trick as _pad_lists_to_multiple: far-away centers rank last in the
    L2 coarse scoring; padded lists are size-0 so their slots can never win)."""
    from ..neighbors.ivf_pq import IvfPqIndex

    L = index.n_lists
    pad = (-L) % size
    if pad == 0:
        return index
    expects(
        index.metric != DistanceType.InnerProduct,
        "inner-product distributed search needs n_lists (%d) divisible by the "
        "mesh axis (%d) — rebuild with a different n_lists",
        L, size,
    )
    cap = index.capacity
    pq_dim = index.list_codes.shape[-1]
    far = 1e15
    codebooks = index.codebooks
    if index.codebook_kind == "per_cluster":
        codebooks = jnp.concatenate(
            [codebooks, jnp.zeros((pad,) + codebooks.shape[1:], codebooks.dtype)])
    return IvfPqIndex(
        centers=jnp.concatenate(
            [index.centers, jnp.full((pad, index.dim), far, index.centers.dtype)]),
        centers_rot=jnp.concatenate(
            [index.centers_rot,
             jnp.full((pad, index.centers_rot.shape[1]), far, index.centers_rot.dtype)]),
        rotation=index.rotation,
        codebooks=codebooks,
        list_codes=jnp.concatenate(
            [index.list_codes, jnp.zeros((pad, cap, pq_dim), index.list_codes.dtype)]),
        list_ids=jnp.concatenate(
            [index.list_ids, jnp.full((pad, cap), -1, jnp.int32)]),
        list_sizes=jnp.concatenate(
            [index.list_sizes, jnp.zeros((pad,), jnp.int32)]),
        list_consts=jnp.concatenate(
            [index.list_consts,
             jnp.zeros((pad, index.list_consts.shape[1]), jnp.float32)]),
        metric=index.metric,
        codebook_kind=index.codebook_kind,
        pq_bits=index.pq_bits,
        split_factor=index.split_factor,
        pq_split=index.pq_split,
        data_kind=index.data_kind,
    )


@instrument("parallel.ivf.search_pq",
            items=lambda a, kw: nrows(a[3] if len(a) > 3 else kw["queries"]),
            labels=lambda a, kw: {"k": a[4] if len(a) > 4 else kw["k"],
                                  "size": (a[0] if a else kw["comms"]).size()})
def search_pq(comms: Comms, params, index, queries, k: int,
              res=None):
    """Distributed IVF-PQ search: lists sharded over the mesh axis, local LUT
    scans, one all_gather + select_k merge (the same composition as IVF-Flat
    ``search`` above; reference pattern: per-shard indexes + knn_merge_parts,
    docs/source/using_comms.rst + detail/knn_merge_parts.cuh).

    ``params`` is :class:`raft_tpu.neighbors.ivf_pq.SearchParams`. Distances
    are PQ-approximate, like the single-chip search; run
    :func:`raft_tpu.neighbors.refine` against the (locally stored) dataset
    shard to sharpen candidates — the PQ index itself carries no raw vectors.

    Returns replicated (distances (m, k), global ids (m, k)).
    """
    from ..core.resources import default_resources
    from ..neighbors._list_utils import (plan_search_tiles,
                                         pq_scan_bytes_per_probe_row)
    from ..neighbors.ivf_pq import IvfPqIndex, _pq_search

    from ..neighbors.brute_force import _coerce_queries

    res = res or default_resources()
    queries = _coerce_queries(index.data_kind, jnp.asarray(queries))
    size = comms.size()
    index = _pad_pq_lists(index, size)
    L = index.n_lists
    lists_per_shard = L // size
    n_probes = min(params.n_probes, lists_per_shard)
    expects(0 < k <= n_probes * index.capacity, "k exceeds per-shard candidate pool")
    # same workspace model as the single-chip ivf_pq.search, with shard-local
    # n_probes/capacity
    n_codes = index.codebooks.shape[-2]
    query_tile, probe_chunk = plan_search_tiles(
        queries.shape[0], n_probes, int(k), index.capacity,
        bytes_per_probe_row=pq_scan_bytes_per_probe_row(
            index.capacity, index.pq_dim, n_codes),
        budget_bytes=res.workspace_bytes,
        max_query_tile=128,
    )
    per_cluster = index.codebook_kind == "per_cluster"
    expects(params.lut_dtype in ("float32", "bfloat16", "int8"),
            "lut_dtype must be 'float32', 'bfloat16' or 'int8', got %r",
            params.lut_dtype)
    # same validation + resolution as the single-chip search (auto = the
    # one-hot contraction, fastest measured — BASELINE.md r04 scan study),
    # and the same clear error for a pq_split index missing its cross terms
    from ..neighbors.ivf_pq import _check_split_consts, resolve_scan_impl

    _check_split_consts(index)
    scan_impl = resolve_scan_impl(params, index, n_codes)
    expects(not index.scale_normed,
            "distributed PQ search does not shard list_scales yet; a "
            "residual_scale_norm index is single-chip only")
    expects(params.scan_order in ("auto", "tiled"),
            "the distributed search runs the tiled scan order; "
            "scan_order=%r is single-chip only", params.scan_order)

    mesh, axis = comms.mesh, comms.axis
    cb_arg = (shard_along(mesh, axis, index.codebooks) if per_cluster
              else replicated(mesh, index.codebooks))
    args = (
        shard_along(mesh, axis, index.centers),
        shard_along(mesh, axis, index.centers_rot),
        replicated(mesh, index.rotation),
        cb_arg,
        shard_along(mesh, axis, index.list_codes),
        shard_along(mesh, axis, index.list_ids),
        shard_along(mesh, axis, index.list_sizes),
        shard_along(mesh, axis, index.list_consts),
        replicated(mesh, queries),
    )
    fn = _pq_search_fn(comms, int(n_probes), int(k), int(query_tile),
                       int(probe_chunk), index.metric, index.codebook_kind,
                       int(index.pq_bits), float(index.split_factor),
                       bool(index.pq_split), params.lut_dtype, scan_impl)
    return fn(*args)


def _pq_search_fn(comms: Comms, n_probes: int, k: int, query_tile: int,
                  probe_chunk: int, metric, codebook_kind: str, pq_bits: int,
                  split_factor: float, pq_split: bool, lut_dtype: str,
                  scan_impl: str):
    """Memoized jitted PQ-search program (see _flat_search_fn); the
    rotation travels as a replicated argument, not a closure constant, so
    two indexes of the same config share one compiled program."""
    key = (comms, "pq", n_probes, k, query_tile, probe_chunk, metric,
           codebook_kind, pq_bits, split_factor, pq_split, lut_dtype,
           scan_impl)
    return _PROGRAMS.get_or_build(key, lambda: _build_pq_search_fn(
        comms, n_probes, k, query_tile, probe_chunk, metric, codebook_kind,
        pq_bits, split_factor, pq_split, lut_dtype, scan_impl))


def _build_pq_search_fn(comms: Comms, n_probes: int, k: int, query_tile: int,
                        probe_chunk: int, metric, codebook_kind: str,
                        pq_bits: int, split_factor: float, pq_split: bool,
                        lut_dtype: str, scan_impl: str):
    from ..neighbors.ivf_pq import IvfPqIndex, _pq_search

    size = comms.size()
    inner = metric == DistanceType.InnerProduct
    per_cluster = codebook_kind == "per_cluster"

    def step(centers, centers_rot, rotation, codebooks, codes, ids, sizes,
             consts, q):
        with tracing.range("parallel.ivf.local_search_pq"):
            shard = IvfPqIndex(
                centers, centers_rot, rotation, codebooks, codes, ids, sizes,
                list_consts=consts,
                metric=metric, codebook_kind=codebook_kind,
                pq_bits=pq_bits, split_factor=split_factor,
                pq_split=pq_split)
            d_loc, i_loc = _pq_search(
                shard, q, n_probes, k,
                query_tile=query_tile, probe_chunk=probe_chunk,
                metric=metric, codebook_kind=codebook_kind,
                lut_dtype=lut_dtype, scan_impl=scan_impl)
        with tracing.range("parallel.ivf.merge"):
            d_all = comms.allgather(d_loc)
            i_all = comms.allgather(i_loc)
            m = q.shape[0]
            d_flat = jnp.moveaxis(d_all, 0, 1).reshape(m, size * k)
            i_flat = jnp.moveaxis(i_all, 0, 1).reshape(m, size * k)
            return _select_k(d_flat, i_flat, k, not inner)

    axis = comms.axis
    cb_spec = P(axis) if per_cluster else P()
    return jax.jit(comms.shard_map(
        step,
        in_specs=(P(axis), P(axis), P(), cb_spec, P(axis), P(axis), P(axis),
                  P(axis), P()),
        out_specs=(P(), P()),
    ))


# ---------------------------------------------------------------------------
# distributed build / extend (VERDICT r4 #3)
# ---------------------------------------------------------------------------
#
# Reference pattern: the MNMG builds the docs tell users to compose from
# raft::comms collectives (/root/reference/docs/source/using_comms.rst:1-40;
# the cuML MNMG k-means psum-EM over kmeans_balanced.cuh). The TPU shape:
#
#   phase 1 (one shard_map program): balanced psum-EM — per-shard fused-1-NN
#     assignment, psum center sums/counts, balancing re-seeds drawn from a
#     pooled (allgathered) subsample so every shard computes IDENTICAL
#     replicated centers; returns centers + sharded labels + global counts.
#   phase 2 (host): static capacity from the global counts (no sub-list
#     splitting in the distributed build — balanced k-means bounds skew, and
#     a data-dependent list count would break the static sharding layout).
#   phase 3 (one shard_map program): materialize the padded list arrays
#     ALREADY SHARDED BY LISTS. Cross-shard write positions come from an
#     exclusive prefix over the allgathered per-shard list counts; the
#     arrays are filled one list-block (L/S lists) at a time — scatter local
#     rows into the block, psum, owner keeps — so the peak per-chip working
#     set is one block (~dataset/S), never the dataset or the index.
#
# The produced index's arrays carry exactly the list sharding search()
# expects, so build -> search composes with no resharding gather.


def _pooled_balanced_centers(comms: Comms, x_shard, keys, L: int,
                             n_iters: int, small_ratio: float, n_global: int,
                             sub: int, inner: bool, tile: int,
                             batch_shard: int = 0):
    """Distributed balanced EM (inside shard_map). Returns replicated
    (centers, labels_shard, global_counts). Deterministic: all replicated
    math consumes identical inputs (allgathered pool, psum'd stats).

    ``batch_shard > 0`` selects mini-batch EM (the distributed twin of
    kmeans_balanced._balanced_em_minibatch): every iteration assigns one
    rotating ``batch_shard``-row mini-batch per shard (a fixed per-shard
    shuffle), the psum'd batch sums/counts drive the streaming 1/c center
    update, and the balancing re-seed runs on the psum'd batch counts
    against the batch-scaled threshold — so the EM loop's full-dataset
    passes (the Round-6-measured ~22-pass, +187%-warm overhead) collapse to
    the two closing passes (sharpening + list-fill labels) below."""
    from ..cluster.kmeans_balanced import _assign_labels, _reseed_small

    xf = x_shard.astype(jnp.float32)
    shard_rows = x_shard.shape[0]
    ksub = jax.random.fold_in(keys[0], comms.rank())
    idx = jax.random.choice(ksub, shard_rows, (sub,), replace=False)
    pool = comms.allgather(jnp.take(xf, idx, axis=0), tiled=True)  # (S*sub, d)
    init_idx = jax.random.choice(keys[1], pool.shape[0], (L,), replace=False)
    centers0 = jnp.take(pool, init_idx, axis=0)
    ptile = min(tile, pool.shape[0])
    S = comms.size()

    if batch_shard:
        kperm = jax.random.fold_in(keys[0], comms.rank() + S)
        perm = jax.random.permutation(kperm, shard_rows).astype(jnp.int32)
        offs = jnp.arange(batch_shard, dtype=jnp.int32)
        batch_global = batch_shard * S

        def body(i, carry):
            centers, ccounts, key = carry
            bidx = perm[(i * batch_shard + offs) % shard_rows]
            xb = jnp.take(xf, bidx, axis=0)
            labels = _assign_labels(xb, centers, min(tile, batch_shard), inner)
            onehot = jax.nn.one_hot(labels, L, dtype=jnp.float32, axis=0)
            sums = comms.allreduce(onehot @ xb)
            counts = comms.allreduce(jnp.sum(onehot, axis=1))
            ccounts = ccounts + counts
            # streaming 1/c mean update (exact running mean of the ccounts
            # points each center has absorbed); zero-count rows are a no-op
            centers = centers + (
                sums - counts[:, None] * centers) / jnp.maximum(
                    ccounts, 1.0)[:, None]
            # balancing on the psum'd batch counts; replacements from the
            # replicated pooled subsample (crowdedness-weighted Gumbel
            # top-k, identical on every shard)
            key, kc = jax.random.split(key)
            pool_w = counts[_assign_labels(pool, centers, ptile, inner)]
            centers, small = _reseed_small(
                centers, counts, pool_w, pool, kc, L, batch_global / L,
                small_ratio)
            # re-seeded centers forget their history: next batch replaces
            # them with its mean at Lloyd speed instead of a 1/c crawl
            ccounts = jnp.where(small, 0.0, ccounts)
            return centers, ccounts, key

        centers, _, _ = lax.fori_loop(
            0, n_iters, body,
            (centers0, jnp.zeros((L,), jnp.float32), keys[2]))
    else:
        def body(i, carry):
            centers, key = carry
            labels = _assign_labels(x_shard, centers, tile, inner)
            onehot = jax.nn.one_hot(labels, L, dtype=jnp.float32, axis=0)
            sums = comms.allreduce(onehot @ xf)
            counts = comms.allreduce(jnp.sum(onehot, axis=1))
            centers = jnp.where(counts[:, None] > 0,
                                sums / jnp.maximum(counts, 1.0)[:, None],
                                centers)
            # balancing (single-chip _balanced_em's pool trick, already sized
            # for this): re-seed small clusters from the replicated pooled
            # subsample, weighted by crowdedness, Gumbel top-k for
            # distinctness
            key, kc = jax.random.split(key)
            pool_w = counts[_assign_labels(pool, centers, ptile, inner)]
            centers, _ = _reseed_small(
                centers, counts, pool_w, pool, kc, L, n_global / L,
                small_ratio)
            return centers, key

        centers, _ = lax.fori_loop(0, n_iters, body, (centers0, keys[2]))
    # final sharpening pass without balancing so centers are true means
    labels = _assign_labels(x_shard, centers, tile, inner)
    onehot = jax.nn.one_hot(labels, L, dtype=jnp.float32, axis=0)
    sums = comms.allreduce(onehot @ xf)
    counts = comms.allreduce(jnp.sum(onehot, axis=1))
    centers = jnp.where(counts[:, None] > 0,
                        sums / jnp.maximum(counts, 1.0)[:, None], centers)
    labels = _assign_labels(x_shard, centers, tile, inner)
    gcounts = comms.allreduce(jnp.bincount(labels, length=L)).astype(jnp.int32)
    return centers, labels.astype(jnp.int32), gcounts


def _global_positions(comms: Comms, labels, L: int, base=None):
    """Write position of each local row inside its global list: exclusive
    prefix of the allgathered per-shard list counts + within-shard rank
    (+ optional replicated per-list base, used by extend)."""
    from ..neighbors._list_utils import list_positions

    lc = jnp.bincount(labels, length=L)
    all_counts = comms.allgather(lc)  # (S, L) replicated
    offs = jnp.cumsum(all_counts, axis=0) - all_counts
    my_off = offs[comms.rank()]  # (L,)
    pos, _ = list_positions(labels, L)
    gpos = my_off[labels].astype(jnp.int32) + pos
    if base is not None:
        gpos = gpos + base[labels].astype(jnp.int32)
    return gpos


def _fill_blocks(comms: Comms, payloads, labels, gpos, L: int, cap: int):
    """Materialize list-sharded padded arrays one list-block at a time.

    ``payloads``: list of (values (n_shard, ...), f32/i32 scatter dtype).
    Returns one (L/S, cap, ...) block per payload (out_spec P(axis) makes it
    the caller's (L, cap, ...) list-sharded global array). Peak per-chip
    working set: ONE block per payload — the no-full-dataset invariant."""
    S = comms.size()
    Lb = L // S
    rank = comms.rank()

    def block(b, accs):
        lo = b * Lb
        in_blk = (labels >= lo) & (labels < lo + Lb)
        # OOB sentinel (Lb / cap) + mode="drop": rows outside the block are
        # dropped by the scatter, and never wrap (negative indices would)
        lloc = jnp.where(in_blk, labels - lo, Lb)
        p = jnp.where(in_blk, gpos, cap)
        out = []
        for (vals, dt), acc in zip(payloads, accs):
            blk = jnp.zeros((Lb, cap) + vals.shape[1:], dt)
            blk = blk.at[lloc, p].set(vals.astype(dt), mode="drop")
            blk = comms.allreduce(blk)
            out.append(jnp.where(rank == b, blk, acc))
        return tuple(out)

    zeros = tuple(jnp.zeros((Lb, cap) + v.shape[1:], dt) for v, dt in payloads)
    return lax.fori_loop(0, S, block, zeros)


def _build_capacity(gcounts, extra=0) -> int:
    import numpy as np

    from ..neighbors._list_utils import round_up

    return round_up(max(int(np.asarray(gcounts).max()) + extra, 8), 8)


def _resolve_batch_shard(params, n: int, S: int, shard_rows: int) -> int:
    """Per-shard mini-batch rows for the coarse psum-EM (0 = full EM).
    The mode/threshold rule is the single-chip trainer's
    (kmeans_balanced.resolve_train_mode) applied to the GLOBAL row count —
    the distributed build trains on the full sharded dataset, there is no
    trainset-fraction subsample here."""
    from ..cluster.kmeans_balanced import resolve_train_mode

    mode = resolve_train_mode(
        getattr(params, "kmeans_train_mode", "auto"), n,
        getattr(params, "kmeans_batch_rows", 65536))
    if mode != "minibatch":
        return 0
    batch_rows = getattr(params, "kmeans_batch_rows", 65536)
    return min(shard_rows, max(batch_rows // S, 1))


def _timed_coarse_em(fn, xs, keys, n_iters: int, batch_shard: int, S: int,
                     n: int):
    """Run the jitted coarse-EM phase with the shared build metrics
    (assignment-pass counter, sampled-rows gauge, phase wall — the same
    raft_tpu_build_* series the single-chip trainer emits, labeled
    driver="distributed")."""
    import time

    from ..obs import build as build_metrics
    from ..obs import metrics

    if not metrics._enabled:
        return fn(xs, keys)
    mode = "minibatch" if batch_shard else "full"
    t0 = time.perf_counter()
    out = fn(xs, keys)
    jax.block_until_ready(out)
    build_metrics.build_phase().observe(time.perf_counter() - t0,
                             phase="parallel.ivf/coarse_em")
    build_metrics.assignment_passes().inc(n_iters, phase="em", mode=mode,
                                          driver="distributed")
    # the two closing full passes ride inside the same program, counted
    # under the SAME phase labels the single-chip driver uses (final =
    # sharpening, fill = list-fill assignment) so the series compare 1:1
    build_metrics.assignment_passes().inc(1, phase="final", mode=mode,
                                          driver="distributed")
    build_metrics.assignment_passes().inc(1, phase="fill", mode=mode,
                                          driver="distributed")
    build_metrics.sampled_rows().set(batch_shard * S if batch_shard else n, mode=mode,
                          driver="distributed")
    return out


@instrument("parallel.ivf.build",
            items=lambda a, kw: nrows(a[2] if len(a) > 2 else kw["dataset"]),
            labels=lambda a, kw: {"size": (a[0] if a else kw["comms"]).size()})
def build(comms: Comms, params, dataset, res=None) -> IvfFlatIndex:
    """Distributed IVF-Flat build: dataset rows sharded over ``comms.axis``,
    index lists sharded the way :func:`search` consumes them. ``params`` is
    :class:`raft_tpu.neighbors.ivf_flat.IndexParams` (list_dtype honored,
    incl. int8/uint8 ingestion; ``split_factor`` is ignored — the
    distributed build does not split hot lists, see module docstring)."""
    from ..distance.pairwise import _choose_tile
    from ..neighbors.ivf_flat import _resolve_storage
    from ..distance.types import resolve_metric

    x = jnp.asarray(dataset)
    expects(x.ndim == 2, "dataset must be (n, d)")
    n, d = x.shape
    S = comms.size()
    expects(n % S == 0, "dataset rows (%d) must divide the mesh axis (%d); "
            "pad first", n, S)
    L = params.n_lists
    expects(L % S == 0, "n_lists (%d) must divide the mesh axis (%d)", L, S)
    expects(L <= n, "n_lists > n_samples")
    mt = resolve_metric(params.metric)
    kind, x, _ = _resolve_storage(params.list_dtype, x, mt)
    storage = x.dtype
    inner = mt == DistanceType.InnerProduct
    mesh, axis = comms.mesh, comms.axis
    shard_rows = n // S
    sub = min(max(8 * L // S, 64), shard_rows)
    tile = _choose_tile(shard_rows, L, 1, 1 << 28)
    batch_shard = _resolve_batch_shard(params, n, S, shard_rows)

    def phase1(x_shard, keys):
        return _pooled_balanced_centers(
            comms, x_shard, keys, L, params.kmeans_n_iters, 0.25, n, sub,
            inner, tile, batch_shard=batch_shard)

    keys = replicated(mesh, jax.random.split(jax.random.key(params.seed), 3))
    xs = shard_along(mesh, axis, x)
    with tracing.range("parallel.ivf.build.coarse_kmeans"):
        centers, labels, gcounts = _timed_coarse_em(
            jax.jit(comms.shard_map(
                phase1, in_specs=(P(axis), P()),
                out_specs=(P(), P(axis), P()))),
            xs, keys, params.kmeans_n_iters, batch_shard, S, n)
    cap = _build_capacity(gcounts)

    def phase3(x_shard, lab, ids):
        xf = x_shard.astype(jnp.float32)
        gpos = _global_positions(comms, lab, L)
        data, idb, nrm = _fill_blocks(
            comms,
            [(xf, jnp.float32), (ids + 1, jnp.int32),
             (jnp.sum(xf * xf, axis=1), jnp.float32)],
            lab, gpos, L, cap)
        idb = idb - 1  # 0 (additive identity) back to the -1 empty sentinel
        nrm = jnp.where(idb < 0, jnp.inf, nrm)
        return data.astype(storage), idb, nrm

    ids = shard_along(mesh, axis, jnp.arange(n, dtype=jnp.int32))
    with tracing.range("parallel.ivf.build.fill_lists"):
        data, idb, nrm = jax.jit(comms.shard_map(
            phase3, in_specs=(P(axis), P(axis), P(axis)),
            out_specs=(P(axis), P(axis), P(axis))))(xs, labels, ids)
    return IvfFlatIndex(
        centers=centers, list_data=data, list_ids=idb, list_norms=nrm,
        list_sizes=gcounts, metric=mt, split_factor=params.split_factor,
        data_kind=kind)


@instrument("parallel.ivf.extend",
            items=lambda a, kw: nrows(a[2] if len(a) > 2 else kw["new_vectors"]))
def extend(comms: Comms, index: IvfFlatIndex, new_vectors, new_ids=None) -> IvfFlatIndex:
    """Distributed IVF-Flat extend: new rows sharded over the mesh axis are
    assigned and appended shard-locally; old list contents never leave their
    owning chip (they are re-padded in place to the grown capacity)."""
    from ..distance.pairwise import _choose_tile
    from ..neighbors._list_utils import assign_to_lists
    from ..neighbors.brute_force import _as_signed

    x = jnp.asarray(new_vectors)
    S = comms.size()
    expects(x.ndim == 2 and x.shape[1] == index.dim, "vector dim mismatch")
    expects(x.shape[0] % S == 0, "new rows (%d) must divide the mesh axis "
            "(%d); pad first", x.shape[0], S)
    L = index.n_lists
    expects(L % S == 0, "index n_lists (%d) must divide the mesh axis (%d) "
            "— was it built by parallel.ivf.build?", L, S)
    if index.data_kind in ("int8", "uint8"):
        expects(str(x.dtype) == index.data_kind,
                "this index stores %s vectors; got %s", index.data_kind, x.dtype)
        x = _as_signed(x)
    else:
        x = x.astype(index.list_data.dtype)
    n_new = x.shape[0]
    if new_ids is None:
        new_ids = index.size + jnp.arange(n_new, dtype=jnp.int32)
    else:
        new_ids = jnp.asarray(new_ids, jnp.int32)
    mesh, axis = comms.mesh, comms.axis
    tile = _choose_tile(n_new // S, L, 1, 1 << 28)

    def assign(x_shard, centers):
        xa = (x_shard.astype(jnp.float32)
              if x_shard.dtype == jnp.int8 else x_shard)
        lab = assign_to_lists(xa, centers, index.metric, tile)
        return lab, comms.allreduce(jnp.bincount(lab, length=L)).astype(jnp.int32)

    xs = shard_along(mesh, axis, x)
    labels, new_counts = jax.jit(comms.shard_map(
        assign, in_specs=(P(axis), P()), out_specs=(P(axis), P())))(
        xs, replicated(mesh, index.centers))
    import numpy as np

    new_sizes = np.asarray(index.list_sizes) + np.asarray(new_counts)
    cap = _build_capacity(new_sizes, extra=0)
    old_cap = index.capacity
    storage = index.list_data.dtype

    def phase3(x_shard, lab, ids, old_data, old_ids, old_norms, sizes):
        xf = x_shard.astype(jnp.float32)
        gpos = _global_positions(comms, lab, L, base=sizes)
        data, idb, nrm = _fill_blocks(
            comms,
            [(xf, jnp.float32), (ids + 1, jnp.int32),
             (jnp.sum(xf * xf, axis=1), jnp.float32)],
            lab, gpos, L, cap)
        idb = idb - 1
        # graft old list contents back in: slots below the old sizes belong
        # to the resident data, slots at/above them to the new psum'd rows
        grow = ((0, 0), (0, cap - old_cap), (0, 0))
        od = jnp.pad(old_data.astype(jnp.float32), grow)
        oi = jnp.pad(old_ids, grow[:2], constant_values=-1)
        on = jnp.pad(old_norms, grow[:2], constant_values=jnp.inf)
        keep_old = oi >= 0
        data = jnp.where(keep_old[..., None], od, data)
        idb = jnp.where(keep_old, oi, idb)
        nrm = jnp.where(idb < 0, jnp.inf, jnp.where(keep_old, on, nrm))
        return data.astype(storage), idb, nrm

    ids = shard_along(mesh, axis, new_ids)
    data, idb, nrm = jax.jit(comms.shard_map(
        phase3,
        in_specs=(P(axis), P(axis), P(axis), P(axis), P(axis), P(axis), P()),
        out_specs=(P(axis), P(axis), P(axis))))(
        xs, labels, ids,
        shard_along(mesh, axis, index.list_data),
        shard_along(mesh, axis, index.list_ids),
        shard_along(mesh, axis, index.list_norms),
        replicated(mesh, index.list_sizes))
    return IvfFlatIndex(
        centers=index.centers, list_data=data, list_ids=idb, list_norms=nrm,
        list_sizes=jnp.asarray(new_sizes, jnp.int32), metric=index.metric,
        split_factor=index.split_factor, data_kind=index.data_kind)


@instrument("parallel.ivf.build_pq",
            items=lambda a, kw: nrows(a[2] if len(a) > 2 else kw["dataset"]),
            labels=lambda a, kw: {"size": (a[0] if a else kw["comms"]).size()})
def build_pq(comms: Comms, params, dataset, res=None):
    """Distributed IVF-PQ build (``params`` =
    :class:`raft_tpu.neighbors.ivf_pq.IndexParams`): same three phases as
    :func:`build`, plus replicated codebook training on a pooled residual
    subsample between them, and a shard-local encode feeding the list fill.
    Restrictions vs the single-chip build: per-subspace codebooks only
    ("auto" resolves to per_subspace without the per-cluster trial) and no
    sub-list splitting."""
    from ..distance.pairwise import _choose_tile
    from ..distance.types import resolve_metric
    from ..neighbors import ivf_pq as pq_mod
    from ..random.rng import as_key

    x = jnp.asarray(dataset)
    expects(x.ndim == 2, "dataset must be (n, d)")
    n, d = x.shape
    S = comms.size()
    expects(n % S == 0, "dataset rows (%d) must divide the mesh axis (%d); "
            "pad first", n, S)
    L = params.n_lists
    expects(L % S == 0, "n_lists (%d) must divide the mesh axis (%d)", L, S)
    mt = resolve_metric(params.metric)
    # int8/uint8 ingestion, identical to the single-chip build (shift into
    # the s8 domain, work in the exact f32 image)
    data_kind, x = pq_mod._resolve_pq_ingest(x, mt)
    expects(params.codebook_kind in ("auto", "per_subspace"),
            "the distributed build trains per-subspace codebooks "
            "(codebook_kind=%r is single-chip only)", params.codebook_kind)
    expects(not getattr(params, "residual_scale_norm", False),
            "residual_scale_norm is single-chip only (the distributed "
            "build's pooled codebook training does not yet normalize "
            "per-list scales)")
    pq_dim = params.pq_dim or pq_mod._default_pq_dim(d, params.pq_bits)
    pq_len = -(-d // pq_dim)
    d_rot = pq_dim * pq_len
    n_codes = 1 << params.pq_bits
    split_pref = (params.pq8_split if params.pq8_split is not None
                  else mt != DistanceType.InnerProduct)
    split = params.pq_bits == 8 and split_pref
    inner = mt == DistanceType.InnerProduct
    mesh, axis = comms.mesh, comms.axis
    shard_rows = n // S
    sub = min(max(8 * L // S, 64), shard_rows)
    tile = _choose_tile(shard_rows, L, 1, 1 << 28)

    # phase 1: coarse centers (identical machinery to the flat build)
    batch_shard = _resolve_batch_shard(params, n, S, shard_rows)

    def phase1(x_shard, keys):
        return _pooled_balanced_centers(
            comms, x_shard, keys, L, params.kmeans_n_iters, 0.25, n, sub,
            inner, tile, batch_shard=batch_shard)

    keys = replicated(mesh, jax.random.split(jax.random.key(params.seed), 3))
    xs = shard_along(mesh, axis, x)
    with tracing.range("parallel.ivf.build_pq.coarse_kmeans"):
        centers, labels, gcounts = _timed_coarse_em(
            jax.jit(comms.shard_map(
                phase1, in_specs=(P(axis), P()),
                out_specs=(P(), P(axis), P()))),
            xs, keys, params.kmeans_n_iters, batch_shard, S, n)
    cap = _build_capacity(gcounts)

    # phase 2: rotation (host, deterministic from the seed — replicated
    # constant) + replicated codebook training on a pooled residual sample
    key = as_key(params.seed)
    key, kr = jax.random.split(key)
    rotation = pq_mod._make_rotation(kr, d_rot, d, params.force_random_rotation)
    key, kc = jax.random.split(key)

    def phase2(x_shard, lab, c, kk):
        ksub = jax.random.fold_in(kk[0], comms.rank())
        idx = jax.random.choice(ksub, x_shard.shape[0], (sub,), replace=False)
        xt = jnp.take(x_shard.astype(jnp.float32), idx, axis=0)
        lt = jnp.take(lab, idx, axis=0)
        resid = (xt - jnp.take(c, lt, axis=0)) @ rotation.T
        pool = comms.allgather(resid, tiled=True)  # (S*sub, d_rot) replicated
        sub_pools = jnp.moveaxis(
            pool.reshape(pool.shape[0], pq_dim, pq_len), 1, 0)
        if split:
            return pq_mod._train_split_codebooks(
                sub_pools, kk[1], params.kmeans_n_iters)
        return pq_mod._train_codebooks_batched(
            sub_pools, kk[1], n_codes, params.kmeans_n_iters)

    cb_keys = replicated(mesh, jnp.stack([keys[0], kc]))
    with tracing.range("parallel.ivf.build_pq.train_codebooks"):
        codebooks = jax.jit(comms.shard_map(
            phase2, in_specs=(P(axis), P(axis), P(), P()),
            out_specs=P()))(xs, labels, centers, cb_keys)

    # phase 3: shard-local encode + block fill
    enc_cb_host = (pq_mod._composed_codebooks(codebooks) if split
                   else codebooks)
    consts_l2 = split and not inner

    def phase3(x_shard, lab, ids, c, enc_cb, cb):
        resid = ((x_shard.astype(jnp.float32) - jnp.take(c, lab, axis=0))
                 @ rotation.T).reshape(x_shard.shape[0], pq_dim, pq_len)
        codes = pq_mod._encode(resid, enc_cb, lab, per_cluster=False,
                               tile=min(x_shard.shape[0], 8192))
        gpos = _global_positions(comms, lab, L)
        payloads = [(codes, jnp.int32), (ids + 1, jnp.int32)]
        if consts_l2:
            payloads.append(
                (pq_mod._pq_cross_consts(codes, cb, lab, False), jnp.float32))
        out = _fill_blocks(comms, payloads, lab, gpos, L, cap)
        cbuf = (out[2] if consts_l2
                else jnp.zeros((L // comms.size(), 0), jnp.float32))
        return out[0].astype(jnp.uint8), out[1] - 1, cbuf

    ids = shard_along(mesh, axis, jnp.arange(n, dtype=jnp.int32))
    with tracing.range("parallel.ivf.build_pq.encode_fill"):
        codes_arr, idb, cbuf = jax.jit(comms.shard_map(
            phase3, in_specs=(P(axis), P(axis), P(axis), P(), P(), P()),
            out_specs=(P(axis), P(axis), P(axis))))(
            xs, labels, ids, centers, replicated(mesh, enc_cb_host),
            replicated(mesh, codebooks))
    return pq_mod.IvfPqIndex(
        centers=centers, centers_rot=centers @ rotation.T, rotation=rotation,
        codebooks=codebooks, list_codes=codes_arr, list_ids=idb,
        list_sizes=gcounts, list_consts=cbuf, metric=mt,
        codebook_kind="per_subspace", pq_bits=params.pq_bits,
        split_factor=params.split_factor, pq_split=split,
        data_kind=data_kind)
