"""Multi-chip k-means: the cuML-over-raft::comms pattern, TPU-native.

The reference keeps MNMG k-means in cuML, built on raft::comms collectives
(SURVEY.md §3.E note): each worker assigns its shard and allreduces per-center
sums/counts. Here the whole distributed Lloyd loop is ONE jitted shard_map
program — assignment is the per-shard fused-1-NN GEMM, the update is a psum
over ICI, and the while_loop runs on-device with no host round trips.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..cluster.kmeans import KMeansOutput, KMeansParams, _kmeans_plus_plus
from ..comms.comms import Comms, replicated, shard_along
from ..core.errors import expects
from ..distance.fused_nn import _fused_l2_nn

__all__ = ["fit", "predict"]


def fit(comms: Comms, params: KMeansParams, x, tile: int = 4096) -> KMeansOutput:
    """Distributed Lloyd (same contract as cluster.kmeans.fit, data sharded
    along ``comms.axis``). Init = k-means++ on a cross-shard subsample: each
    chip contributes random rows, the pooled candidates are allgathered
    (identical on every chip), and ++ runs replicated — no serialized
    global D² sampling over the full dataset.

    ``params.train_mode`` (see :class:`~raft_tpu.cluster.kmeans.KMeansParams`)
    selects mini-batch EM: each iteration assigns one rotating per-shard
    mini-batch (``batch_rows`` rows globally) and moves centers by the
    streaming 1/c mean update — the same full-pass elimination as the
    balanced coarse trainer — with tol applied to the per-iteration center
    shift; labels and inertia always come from one closing full pass."""
    from ..cluster.kmeans_balanced import resolve_train_mode

    x = jnp.asarray(x)
    n, d = x.shape
    size = comms.size()
    expects(n % size == 0, "dataset rows must divide the mesh axis; pad first")
    k = params.n_clusters
    shard_rows = n // size
    sub = min(max(8 * k, 64), shard_rows)
    mode = resolve_train_mode(params.train_mode, n, params.batch_rows)
    batch = (min(shard_rows, max(params.batch_rows // size, 1))
             if mode == "minibatch" else 0)

    def step(x_shard, key):
        # per-shard distinct subsample → pooled ++ seeding
        ksub = jax.random.fold_in(key[0], comms.rank())
        idx = jax.random.choice(ksub, shard_rows, (sub,), replace=False)
        pool = comms.allgather(jnp.take(x_shard, idx, axis=0), tiled=True)  # (size*sub, d)
        init_c = _kmeans_plus_plus(pool.astype(jnp.float32), key[1], k, tile)

        def cond(state):
            _, shift2, it = state
            return jnp.logical_and(it < params.max_iter, shift2 > params.tol**2)

        def body(state):
            centers, _, it = state
            _, labels = _fused_l2_nn(x_shard, centers, False, min(tile, x_shard.shape[0]))
            onehot = jax.nn.one_hot(labels, k, dtype=jnp.float32, axis=0)
            sums = comms.allreduce(onehot @ x_shard.astype(jnp.float32), "sum")
            counts = comms.allreduce(jnp.sum(onehot, axis=1), "sum")
            new_centers = jnp.where(
                counts[:, None] > 0, sums / jnp.maximum(counts, 1.0)[:, None], centers
            )
            return new_centers, jnp.sum(jnp.square(new_centers - centers)), it + 1

        if batch:
            kperm = jax.random.fold_in(key[0], comms.rank() + size)
            perm = jax.random.permutation(kperm, shard_rows).astype(jnp.int32)
            offs = jnp.arange(batch, dtype=jnp.int32)

            def mb_body(state):
                centers, ccounts, _, it = state
                bidx = perm[(it * batch + offs) % shard_rows]
                xb = jnp.take(x_shard, bidx, axis=0).astype(jnp.float32)
                _, labels = _fused_l2_nn(xb, centers, False, min(tile, batch))
                onehot = jax.nn.one_hot(labels, k, dtype=jnp.float32, axis=0)
                sums = comms.allreduce(onehot @ xb, "sum")
                counts = comms.allreduce(jnp.sum(onehot, axis=1), "sum")
                ccounts = ccounts + counts
                new_centers = centers + (
                    sums - counts[:, None] * centers) / jnp.maximum(
                        ccounts, 1.0)[:, None]
                return (new_centers, ccounts,
                        jnp.sum(jnp.square(new_centers - centers)), it + 1)

            def mb_cond(state):
                _, _, shift2, it = state
                return jnp.logical_and(it < params.max_iter,
                                       shift2 > params.tol**2)

            centers, _, _, n_iter = lax.while_loop(
                mb_cond, mb_body,
                (init_c, jnp.zeros((k,), jnp.float32), jnp.inf, 0))
        else:
            centers, _, n_iter = lax.while_loop(cond, body, (init_c, jnp.inf, 0))
        d2, labels = _fused_l2_nn(x_shard, centers, False, min(tile, x_shard.shape[0]))
        inertia = comms.allreduce(jnp.sum(d2), "sum")
        return centers, labels, inertia, n_iter

    x_sharded = shard_along(comms.mesh, comms.axis, x)
    key = replicated(comms.mesh, jax.random.split(jax.random.key(params.seed), 2))
    fn = comms.shard_map(step, in_specs=(P(comms.axis), P()),
                         out_specs=(P(), P(comms.axis), P(), P()))
    centers, labels, inertia, n_iter = jax.jit(fn)(x_sharded, key)
    return KMeansOutput(centers, labels, inertia, int(n_iter))


def predict(comms: Comms, x, centroids, tile: int = 4096):
    """Distributed assignment; labels come back sharded like ``x``."""
    x = jnp.asarray(x)
    centroids = jnp.asarray(centroids)

    def step(x_shard, c):
        d2, labels = _fused_l2_nn(x_shard, c, False, min(tile, x_shard.shape[0]))
        return labels, comms.allreduce(jnp.sum(d2), "sum")

    x_sharded = shard_along(comms.mesh, comms.axis, x)
    c_repl = replicated(comms.mesh, centroids)
    fn = comms.shard_map(step, in_specs=(P(comms.axis), P()), out_specs=(P(comms.axis), P()))
    return jax.jit(fn)(x_sharded, c_repl)
