"""raft_tpu.parallel — distributed algorithm drivers over raft_tpu.comms.

The reference ships the communicator and leaves distributed algorithms to
consumers (cuML/cuGraph over raft::comms, docs/source/using_comms.rst); here
the canonical ones are in-tree: sharded exact kNN with global merge, multi-chip k-means, and
list-sharded IVF-Flat/IVF-PQ search, and per-shard CAGRA with ICI merge.
"""

from . import cagra, ivf, kmeans, knn

__all__ = ["knn", "kmeans", "ivf", "cagra", "release_programs"]


def release_programs(comms=None) -> int:
    """Evict the drivers' memoized jitted programs pinned to ``comms``
    (every communicator when None) — the mesh-teardown hook: the program
    caches (``knn._knn_fn``, ``ivf._flat_search_fn``/``_pq_search_fn``,
    ``cagra._cagra_search_fn``) hold the Comms —
    and through it the Mesh and its devices — strongly, so a process that
    churns mesh configs (the sharded serving tier) must release retired
    ones or they pin memory for the cache's lifetime. Returns how many
    programs were dropped. Note jax's own trace/executable caches also
    reference the mesh; pair with ``jax.clear_caches()`` when the goal is
    releasing device memory, not just this library's references."""
    caches = (knn._PROGRAMS, ivf._PROGRAMS, cagra._PROGRAMS)
    if comms is None:
        n = sum(len(c) for c in caches)
        for c in caches:
            c.clear()
        return n
    return sum(c.release(comms) for c in caches)
