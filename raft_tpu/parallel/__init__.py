"""raft_tpu.parallel — distributed algorithm drivers over raft_tpu.comms.

The reference ships the communicator and leaves distributed algorithms to
consumers (cuML/cuGraph over raft::comms, docs/source/using_comms.rst); here
the canonical ones are in-tree: sharded exact kNN with global merge, multi-chip k-means, and
list-sharded IVF-Flat/IVF-PQ search, and per-shard CAGRA with ICI merge.
"""

from . import cagra, ivf, kmeans, knn

__all__ = ["knn", "kmeans", "ivf", "cagra"]
