"""raft_tpu.parallel — distributed algorithm drivers over raft_tpu.comms. Under construction."""
