"""Multi-process mesh: shard groups owned by worker processes behind a
router, scatter-gather crossing process boundaries candidates-only.

The in-process "mesh" (threads over one process's devices) becomes real
here: each ``(shard, replica)`` pair is a separate OS process owning its
shard's rows — a sealed index wrapped in a
:class:`~raft_tpu.stream.MutableIndex` carrying the GLOBAL ids, published
into a process-local :class:`~raft_tpu.serve.SearchService` behind its
own :class:`~raft_tpu.net.server.NetServer`. The router
(:class:`ProcessMesh`) is submit-shaped, so the same front door (and the
same client retry discipline) serves a process fleet exactly as it
serves one service.

Contracts, in order of importance:

- **candidates-only on the wire** — a scatter part returns k global ids
  + k distances per query row, NEVER raw vectors; the router merges
  parts host-side (ascending distances — the brute-force L2 convention)
  and truncates to k. Rows cross the wire once, at load time.
- **kill-a-worker is a strike→fence→failover event, not an outage** —
  per-worker breakers mirror the PR 11
  :class:`~raft_tpu.stream.replicated.FencingPolicy` semantics: a
  connection-level failure strikes the worker, fences it for a doubling
  backoff, and the SAME scatter call retries the surviving twin in the
  group. Expired fences are half-open probes; a success unfences. Only
  a group at zero pickable workers raises
  :class:`~raft_tpu.serve.errors.ReplicaUnavailableError` (that IS an
  outage). Fences and failovers journal as ``net_worker_*`` events and
  count in ``raft_tpu_net_worker_*_total``.
- **routing is the shared hash** — rows land on shard
  ``stream.shard_of(ids, n_shards)``, the SplitMix64 contract a router
  in front of a real fleet shares with the build side; writes route by
  the same hash and apply to EVERY replica of the owning group (twins
  stay twins).
- **zero cold compiles on the wire path** — each worker rehearses the
  warm-before-flip publish ladder at boot, settles the first-call path,
  and only then opens its compile-attribution window; the router's
  :meth:`~ProcessMesh.stats` sums ``compile_s``/``cache_misses`` across
  workers, which is the fleet-wide proof the bench asserts.

Validation errors (bad shape/dim/k — a 400 from any worker) raise
without striking: every twin would refuse identically, and a caller-side
bug must not fence the fleet. ``OverloadedError`` / ``DeadlineExceededError``
pass through untouched — backpressure belongs to the client's retry
policy, not the router's breaker.
"""

from __future__ import annotations

import functools
import multiprocessing
import os
import threading
import time
import traceback
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass

import numpy as np

from ..core.errors import RaftError, expects
from ..obs import events as obs_events
from ..obs import metrics
from ..serve.errors import (DeadlineExceededError, OverloadedError,
                            ReplicaUnavailableError, ServeError)
from .client import NetClient

__all__ = ["MeshSpec", "ProcessMesh"]


@functools.lru_cache(maxsize=None)
def _c_fenced():
    return metrics.counter(
        "raft_tpu_net_worker_fenced_total",
        "mesh worker processes fenced after a strike (connection-level "
        "or server-side failure) — each co-journals net_worker_fenced")


@functools.lru_cache(maxsize=None)
def _c_failovers():
    return metrics.counter(
        "raft_tpu_net_worker_failovers_total",
        "scatter parts retried on a surviving twin in the SAME call "
        "after the picked worker failed — each co-journals "
        "net_worker_failover")


@dataclass(frozen=True)
class MeshSpec:
    """Topology + per-worker serving config for a :class:`ProcessMesh`."""

    n_shards: int = 2
    n_replicas: int = 1
    name: str = "corpus"
    ks: tuple = (10,)
    max_batch: int = 64
    max_queue_rows: int = 4096
    host: str = "127.0.0.1"
    start_timeout_s: float = 120.0
    # breaker: strikes before fencing, initial fence backoff, cap
    max_consecutive: int = 1
    fence_backoff_s: float = 0.5
    max_backoff_s: float = 8.0


def _worker_main(conn, spec: dict) -> None:
    """Worker process entry (spawn target). Boots a shard replica:
    build → wrap with global ids → publish (the warm ladder) → settle →
    open the compile-attribution window → serve. Reports ``{"port": p}``
    (or ``{"error": tb}``) over the pipe, then blocks on it for stop."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        from ..neighbors import brute_force
        from ..obs import compile as obs_compile
        from ..obs.requestlog import RequestLog
        from ..serve.service import SearchService
        from ..stream.mutable import MutableIndex
        from .server import NetServer

        rows = np.asarray(spec["rows"], np.float32)
        ids = np.asarray(spec["ids"])
        name = spec["name"]
        idx = MutableIndex(brute_force.BruteForce().build(rows),
                           ids=ids, name=name)
        rlog = RequestLog()
        svc = SearchService(max_batch=spec["max_batch"],
                            max_queue_rows=spec["max_queue_rows"],
                            request_log=rlog)
        svc.publish(name, idx, k=tuple(spec["ks"]))  # warm-before-flip
        # settle any residual first-call host paths OUTSIDE the window
        for k in spec["ks"]:
            svc.search(name, rows[:1], int(k))
        with obs_compile.attribution() as rec:
            srv = NetServer(svc, host=spec["host"], request_log=rlog,
                            stats=lambda: {"pid": os.getpid(),
                                           "compile_s": rec.compile_s,
                                           "cache_misses": rec.cache_misses,
                                           "rows": int(rows.shape[0])})
            conn.send({"port": srv.port, "pid": os.getpid()})
            try:
                conn.recv()  # stop signal (or EOF when the router died)
            except EOFError:
                pass
            srv.stop()
            svc.shutdown()
    except Exception:
        try:
            conn.send({"error": traceback.format_exc()})
        except Exception:
            pass
        raise


@dataclass
class _Worker:
    shard: int
    replica: int
    proc: object
    conn: object
    port: int = 0
    client: NetClient | None = None
    # breaker state (router-side; guarded by the mesh lock)
    fails: int = 0
    fenced_until: float = 0.0
    backoff: float = 0.0
    fenced: bool = False

    @property
    def label(self) -> str:
        return f"s{self.shard}r{self.replica}"


class ProcessMesh:
    """Router over ``n_shards × n_replicas`` worker processes (see
    module doc). Submit-shaped: hand it to a
    :class:`~raft_tpu.net.server.NetServer` as the backend, or call
    :meth:`search` directly."""

    def __init__(self, dataset, ids=None, *, spec: MeshSpec | None = None,
                 clock=time.monotonic):
        from ..stream.sharded import shard_of  # heavy import, router-only

        self.spec = spec or MeshSpec()
        self.name = self.spec.name
        self._clock = clock
        self._lock = threading.Lock()
        # per-shard round-robin seeds: each group rotates independently,
        # so successive searches alternate a group's primary
        # deterministically (a global counter would correlate rotation
        # across shards through thread-arrival order)
        self._rr = [0] * self.spec.n_shards
        dataset = np.asarray(dataset, np.float32)
        expects(dataset.ndim == 2, "dataset must be (rows, d)")
        ids = (np.arange(dataset.shape[0], dtype=np.int64) if ids is None
               else np.asarray(ids, np.int64))
        expects(ids.shape[0] == dataset.shape[0],
                "ids must match dataset rows")
        owner = np.asarray(shard_of(ids, self.spec.n_shards))
        ctx = multiprocessing.get_context("spawn")
        self._workers: list[list[_Worker]] = []
        for s in range(self.spec.n_shards):
            mask = owner == s
            group = []
            for r in range(self.spec.n_replicas):
                parent, child = ctx.Pipe()
                p = ctx.Process(
                    target=_worker_main,
                    args=(child, {"rows": dataset[mask], "ids": ids[mask],
                                  "name": self.name, "ks": self.spec.ks,
                                  "max_batch": self.spec.max_batch,
                                  "max_queue_rows": self.spec.max_queue_rows,
                                  "host": self.spec.host}),
                    daemon=True, name=f"raft-net-worker-s{s}r{r}")
                p.start()
                child.close()
                group.append(_Worker(s, r, p, parent))
            self._workers.append(group)
        # collect handshakes AFTER all workers launched (parallel boots)
        deadline = time.monotonic() + self.spec.start_timeout_s
        for group in self._workers:
            for w in group:
                if not w.conn.poll(max(0.1, deadline - time.monotonic())):
                    self.close()
                    raise RaftError(f"worker {w.label} did not report a "
                                    f"port within "
                                    f"{self.spec.start_timeout_s:g}s")
                msg = w.conn.recv()
                if "error" in msg:
                    self.close()
                    raise RaftError(f"worker {w.label} failed to boot:\n"
                                    f"{msg['error']}")
                w.port = int(msg["port"])
                w.client = NetClient(
                    f"http://{self.spec.host}:{w.port}")
        self._pool = ThreadPoolExecutor(
            max_workers=self.spec.n_shards * self.spec.n_replicas,
            thread_name_prefix="raft-net-scatter")
        self._closed = False

    # -- breaker -------------------------------------------------------------
    def _strike(self, w: _Worker, exc: BaseException) -> None:
        with self._lock:
            w.fails += 1
            if w.fails < self.spec.max_consecutive or w.fenced:
                return
            w.fenced = True
            w.backoff = (self.spec.fence_backoff_s if w.backoff == 0.0
                         else min(w.backoff * 2.0, self.spec.max_backoff_s))
            w.fenced_until = self._clock() + w.backoff
        if metrics._enabled:
            _c_fenced().inc(1, shard=f"s{w.shard}")
        obs_events.emit("net_worker_fenced",
                        subject=("net", self.name, w.shard, None),
                        evidence={"worker": w.label,
                                  "backoff_s": w.backoff,
                                  "error": repr(exc)})

    def _observe_ok(self, w: _Worker) -> None:
        with self._lock:
            was_fenced, w.fails, w.backoff, w.fenced = w.fenced, 0, 0.0, False
            w.fenced_until = 0.0
        if was_fenced:
            obs_events.emit("net_worker_unfenced",
                            subject=("net", self.name, w.shard, None),
                            evidence={"worker": w.label})

    def _pick_order(self, shard: int, group: list[_Worker]) -> list[_Worker]:
        """Unfenced workers first (rotated for load spread), then expired
        fences as half-open probes; a still-fenced worker is skipped."""
        now = self._clock()
        with self._lock:
            self._rr[shard] += 1
            rot = self._rr[shard]
            live = [w for w in group if not w.fenced]
            probes = [w for w in group if w.fenced and now >= w.fenced_until]
        live = live[rot % len(live):] + live[:rot % len(live)] if live else []
        return live + probes

    # -- scatter-gather ------------------------------------------------------
    def _scatter_one(self, shard: int, queries, k: int,
                     timeout_s, rid):
        group = self._workers[shard]
        order = self._pick_order(shard, group)
        tried = 0
        last_exc = None
        for w in order:
            tried += 1
            try:
                dists, ids_part, _ = w.client.request(
                    self.name, queries, k, timeout_s=timeout_s, rid=rid)
            except (OverloadedError, DeadlineExceededError):
                # backpressure/deadline: the client's retry policy owns
                # these — the breaker must not fence a merely busy worker
                raise
            except RaftError as exc:
                if isinstance(exc, ServeError):
                    # worker-side failure (closed, 5xx) — strike, failover
                    last_exc = exc
                    self._strike(w, exc)
                    continue
                raise  # validation: every twin refuses identically
            except Exception as exc:  # noqa: BLE001 - connection-level
                last_exc = exc
                self._strike(w, exc)
                continue
            self._observe_ok(w)
            if tried > 1:
                if metrics._enabled:
                    _c_failovers().inc(tried - 1, shard=f"s{shard}")
                obs_events.emit("net_worker_failover",
                                subject=("net", self.name, shard, None),
                                evidence={"retried": tried - 1,
                                          "worker": w.label,
                                          "error": repr(last_exc)})
            return np.asarray(dists), np.asarray(ids_part)
        with self._lock:
            fenced = sum(1 for w in group if w.fenced)
        raise ReplicaUnavailableError(
            f"shard {shard} of {self.name!r}: no worker could serve "
            f"(last: {last_exc!r})", name=f"{self.name}/s{shard}",
            replicas=len(group), fenced=fenced)

    def _search(self, queries, k: int, timeout_s, rid):
        q = np.asarray(queries, np.float32)
        expects(q.ndim == 2, "queries must be (rows, d); got ndim=%d",
                q.ndim)
        parts = list(self._pool.map(
            lambda s: self._scatter_one(s, q, k, timeout_s, rid),
            range(self.spec.n_shards)))
        # host-side candidates-only merge: ascending distances win
        dists = np.concatenate([p[0] for p in parts], axis=1)
        ids = np.concatenate([p[1] for p in parts], axis=1)
        k = min(int(k), dists.shape[1])
        sel = np.argpartition(dists, k - 1, axis=1)[:, :k]
        rows = np.arange(dists.shape[0])[:, None]
        dists, ids = dists[rows, sel], ids[rows, sel]
        order = np.argsort(dists, axis=1, kind="stable")
        return dists[rows, order], ids[rows, order]

    # -- the submit-shaped surface -------------------------------------------
    def submit(self, name: str, queries, k: int = 10, *,
               timeout_s: float | None = None,
               rid: str | None = None) -> Future:
        """Scatter-gather across the fleet; ``SearchService.submit``-shaped
        (refusals raise synchronously, success is a resolved Future), so
        the front door and ``submit_with_retry`` compose unchanged."""
        if self._closed:
            from ..serve.errors import ServiceClosedError

            raise ServiceClosedError("mesh is closed")
        if name != self.name:
            raise RaftError(f"no index published under {name!r} "
                            f"(this mesh serves {self.name!r})")
        fut: Future = Future()
        fut.set_result(self._search(queries, int(k), timeout_s, rid))
        return fut

    def search(self, name: str, queries, k: int = 10, *,
               timeout_s: float | None = None):
        return self.submit(name, queries, k, timeout_s=timeout_s).result()

    # -- write path ----------------------------------------------------------
    def _write_group(self, shard: int, apply) -> list:
        """Apply one write to every replica of a group; a replica that
        fails is STRUCK (it missed the write — it must not serve until it
        proves itself again) and the write succeeds as long as at least
        one twin took it. In this mesh the only replica failure mode is
        process death, which is permanent, so a struck-stale twin can
        never probe back in with missing rows; a mesh over transient
        transports would need a catch-up path before unfencing. Zero
        successes is an outage: :class:`ReplicaUnavailableError`."""
        results, last_exc = [], None
        for w in self._workers[shard]:
            try:
                results.append(apply(w))
            except RaftError as exc:
                if not isinstance(exc, ServeError):
                    raise  # validation: identical on every twin
                last_exc = exc
                self._strike(w, exc)
            except Exception as exc:  # noqa: BLE001 - connection-level
                last_exc = exc
                self._strike(w, exc)
        if not results:
            group = self._workers[shard]
            with self._lock:
                fenced = sum(1 for w in group if w.fenced)
            raise ReplicaUnavailableError(
                f"shard {shard} of {self.name!r}: no worker took the "
                f"write (last: {last_exc!r})", name=f"{self.name}/s{shard}",
                replicas=len(group), fenced=fenced)
        return results

    def upsert(self, name: str, rows, ids=None):
        """Route rows to their owning shard groups by the shared hash and
        apply to EVERY live replica (twins stay twins; see
        :meth:`_write_group` for the failed-twin rule). Global ids are
        required — workers must never mint (they would collide)."""
        from ..stream.sharded import shard_of

        expects(name == self.name, "this mesh serves %r", self.name)
        expects(ids is not None,
                "mesh upsert requires explicit global ids")
        rows = np.asarray(rows, np.float32)
        ids = np.asarray(ids, np.int64)
        owner = np.asarray(shard_of(ids, self.spec.n_shards))
        for s in range(self.spec.n_shards):
            mask = owner == s
            if mask.any():
                self._write_group(
                    s, lambda w, m=mask: w.client.upsert(
                        self.name, rows[m], ids[m]))
        return ids

    def delete(self, name: str, ids) -> int:
        from ..stream.sharded import shard_of

        expects(name == self.name, "this mesh serves %r", self.name)
        ids = np.asarray(ids, np.int64)
        owner = np.asarray(shard_of(ids, self.spec.n_shards))
        deleted = 0
        for s in range(self.spec.n_shards):
            mask = owner == s
            if mask.any():
                counts = self._write_group(
                    s, lambda w, m=mask: w.client.delete(self.name, ids[m]))
                deleted += counts[0]  # live twins report identically
        return deleted

    # -- introspection / chaos ----------------------------------------------
    def health(self) -> dict:
        """Shaped like the sharded replica-health payload, so the obs
        exporter's ``/healthz`` fold applies unchanged: a group at zero
        pickable workers is failing/503."""
        with self._lock:
            shards = []
            for s, group in enumerate(self._workers):
                reps = [{"name": w.label, "fenced": bool(w.fenced),
                         "alive": bool(w.proc.is_alive()),
                         "port": w.port} for w in group]
                shards.append({"shard": s, "replicas": reps,
                               "healthy": sum(1 for r in reps
                                              if not r["fenced"]
                                              and r["alive"])})
        return {"shards": shards}

    def stats(self) -> dict:
        """Fleet-summed worker stats — ``compile_s``/``cache_misses``
        across every live worker is the zero-cold-compile proof for the
        whole wire path. Fenced/dead workers are skipped (and listed)."""
        total = {"compile_s": 0.0, "cache_misses": 0, "workers": 0,
                 "unreachable": []}
        for group in self._workers:
            for w in group:
                try:
                    st = w.client.stats()
                except Exception:  # noqa: BLE001 - dead worker
                    total["unreachable"].append(w.label)
                    continue
                total["compile_s"] += float(st.get("compile_s", 0.0))
                total["cache_misses"] += int(st.get("cache_misses", 0))
                total["workers"] += 1
        return total

    def kill_worker(self, shard: int = 0, replica: int = 0) -> int:
        """SIGKILL one worker process (chaos hook for tests/bench);
        returns its pid. The next scatter that picks it strikes, fences
        and fails over within the same call."""
        w = self._workers[shard][replica]
        pid = w.proc.pid
        w.proc.kill()
        w.proc.join(5.0)
        return pid

    def close(self) -> None:
        """Stop every worker (graceful via the pipe, kill stragglers)."""
        self._closed = True
        workers = [w for g in self._workers for w in g]
        for w in workers:
            try:
                w.conn.send("stop")
            except Exception:  # noqa: BLE001 - already dead
                pass
        for w in workers:
            w.proc.join(5.0)
            if w.proc.is_alive():
                w.proc.kill()
                w.proc.join(5.0)
            w.conn.close()
        if getattr(self, "_pool", None) is not None:
            self._pool.shutdown(wait=False)

    def __enter__(self) -> "ProcessMesh":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
