"""The network front door: an HTTP/JSON surface over ``SearchService``.

Zero dependencies — the same stdlib server pattern as the obs exporter
(shared :class:`~raft_tpu.net._httpd.Httpd`). Routes:

- ``POST /v1/search`` — a :mod:`~raft_tpu.net.wire` query batch in, a
  candidate set out. The admission taxonomy maps to HTTP status
  (``OverloadedError``→429 with ``Retry-After`` from
  :meth:`~raft_tpu.serve.SearchService.retry_after_hint`,
  ``DeadlineExceededError``→504, ``MemoryBudgetError``→507,
  ``ReplicaUnavailableError``→503, ``ServiceClosedError``→503,
  validation→400) with the structured error body
  :func:`~raft_tpu.net.wire.decode_error` reconstructs exactly.
  ``X-Raft-Request-Id`` threads the wire id into the request log
  (one trace spans wire→queue→flush); ``X-Raft-Deadline-Ms`` carries
  the client's REMAINING budget, which becomes the submit's
  ``timeout_s`` — the server's deadline accounting is the client's.
- ``POST /v1/control`` — the write/flush path as explicit wire control:
  ``{"op": "upsert", "rows": <array>, "ids": <array>?}``,
  ``{"op": "delete", "ids": <array>}``, ``{"op": "flush"}`` (drains a
  ``start_workers=False`` service via ``pump(force=True)``; a no-op
  with live workers). Publish stays a process-local act — an index
  never crosses the wire (candidates-only rule; workers publish at
  boot, see :mod:`~raft_tpu.net.mesh`).
- ``GET /healthz`` — ready verdict + queue depth; a backend exposing
  ``health()`` (the process mesh) folds per-worker breaker health in,
  with the same zero-pickable-twins→503 rule as the obs exporter.
- ``GET /v1/stats`` — queue depth plus whatever the ``stats=`` callable
  reports (the mesh wires compile-attribution counters through here for
  the zero-cold-compile proof).

The backend is anything submit-shaped: a
:class:`~raft_tpu.serve.SearchService` or a
:class:`~raft_tpu.net.mesh.ProcessMesh` router — the front door is a
layer, not a fork.
"""

from __future__ import annotations

import functools
import itertools
import os
import time

from ..core.errors import RaftError
from ..obs import metrics
from ..serve.errors import OverloadedError
from . import wire
from ._httpd import Httpd, Response, json_response

__all__ = ["NetServer"]


@functools.lru_cache(maxsize=None)
def _c_requests():
    return metrics.counter(
        "raft_tpu_net_requests_total",
        "wire requests served by the net front door, by route and HTTP "
        "status code")


@functools.lru_cache(maxsize=None)
def _g_inflight():
    return metrics.gauge(
        "raft_tpu_net_inflight",
        "wire requests currently inside the net front door "
        "(decode → submit → resolve → encode)")


@functools.lru_cache(maxsize=None)
def _h_wire():
    return metrics.histogram(
        "raft_tpu_net_wire_seconds",
        "server-side wire wall per request (decode → submit → resolve → "
        "encode), by route — subtract the serve queue/flush spans for "
        "the pure wire overhead", unit="seconds")


class NetServer:
    """One front door over one backend (see module doc).

    ``request_log=`` should be the SAME log the backend's service was
    built with — the front door adopts/mints the wire request id, passes
    it to ``submit(rid=)``, and lands the ``wire`` span on the completed
    trace (best-effort: the batcher logs completions after futures
    resolve, so a span header can miss a just-resolved entry; the bench
    reads p99 decomposition from the histograms, not headers).
    """

    def __init__(self, service, *, port: int = 0, host: str = "127.0.0.1",
                 request_log=None, stats=None):
        self.service = service
        self.request_log = request_log
        self._stats = stats
        self._rid = itertools.count(1)
        self._pid = os.getpid()
        self._server = Httpd({
            ("POST", "/v1/search"): self._wrap("/v1/search", self._search),
            ("POST", "/v1/control"): self._wrap("/v1/control", self._control),
            ("GET", "/healthz"): self._healthz,
            ("GET", "/v1/stats"): self._stats_route,
        }, port=port, host=host, name="raft-net-front")
        self.host = host
        self.port = self._server.port

    # -- plumbing ------------------------------------------------------------
    def _wrap(self, route: str, fn):
        """Meter a handler: requests by status, inflight, wire wall."""
        def handler(req) -> Response:
            t0 = time.perf_counter()
            if metrics._enabled:
                _g_inflight().inc(1)
            resp = None
            try:
                resp = fn(req, t0)
                return resp
            finally:
                if metrics._enabled:
                    _g_inflight().inc(-1)
                    code = resp.code if resp is not None else 500
                    _c_requests().inc(1, route=route, code=str(code))
                    _h_wire().observe(time.perf_counter() - t0, route=route)
        return handler

    def _error(self, exc: BaseException, hdrs: dict) -> Response:
        retry_after = None
        if (isinstance(exc, OverloadedError)
                and hasattr(self.service, "retry_after_hint")):
            retry_after = float(self.service.retry_after_hint())
            hdrs[wire.H_RETRY_AFTER] = f"{retry_after:.3f}"
        code, body = wire.encode_error(exc, retry_after_s=retry_after)
        return json_response(code, body, hdrs)

    def _rid_of(self, req) -> str:
        return (req.headers.get(wire.H_REQUEST_ID)
                or f"wire-{self._pid}-{next(self._rid):08d}")

    # -- routes --------------------------------------------------------------
    def _search(self, req, t0: float) -> Response:
        rid = self._rid_of(req)
        hdrs = {wire.H_REQUEST_ID: rid}
        timeout_s = None
        deadline_ms = req.headers.get(wire.H_DEADLINE_MS)
        if deadline_ms is not None:
            try:
                timeout_s = float(deadline_ms) / 1e3
            except ValueError:
                return self._error(RaftError(
                    f"malformed {wire.H_DEADLINE_MS} header: "
                    f"{deadline_ms!r}"), hdrs)
        try:
            name, queries, k = wire.decode_query_batch(req.json())
        except RaftError as exc:
            return self._error(exc, hdrs)
        except ValueError as exc:
            return self._error(RaftError(f"body is not JSON: {exc}"), hdrs)
        try:
            fut = self.service.submit(name, queries, k,
                                      timeout_s=timeout_s, rid=rid)
            dists, ids = fut.result()
        except Exception as exc:  # noqa: BLE001 - taxonomy → status
            return self._error(exc, hdrs)
        wire_s = time.perf_counter() - t0
        if self.request_log is not None:
            # best-effort: lands the wire span on the completed trace and
            # surfaces the queue/flush decomposition to the client. The
            # batcher logs the completion just AFTER resolving the future,
            # so one bounded wait covers the common race without ever
            # blocking the response on the log.
            if self.request_log.get(rid) is None:
                time.sleep(0.002)
            self.request_log.attach_span(rid, "wire", wire_s)
            entry = self.request_log.get(rid)
            if entry is not None:
                spans = {n: ms / 1e3
                         for n, ms in entry.get("spans_ms", {}).items()
                         if n in ("queue", "flush")}
                spans["wire"] = wire_s
                hdrs[wire.H_SPANS] = wire.encode_spans(spans)
        return json_response(200, wire.encode_candidates(dists, ids), hdrs)

    def _control(self, req, t0: float) -> Response:
        rid = self._rid_of(req)
        hdrs = {wire.H_REQUEST_ID: rid}
        try:
            op, payload = wire.decode_control(req.json())
        except RaftError as exc:
            return self._error(exc, hdrs)
        except ValueError as exc:
            return self._error(RaftError(f"body is not JSON: {exc}"), hdrs)
        try:
            if op == "upsert":
                rows = wire.decode_array(payload["rows"])
                ids = (wire.decode_array(payload["ids"])
                       if payload.get("ids") is not None else None)
                out = self.service.upsert(payload.get("name", "default"),
                                          rows, ids)
                return json_response(
                    200, {"v": wire.WIRE_VERSION,
                          "ids": wire.encode_array(out)}, hdrs)
            if op == "delete":
                ids = wire.decode_array(payload["ids"])
                n = self.service.delete(payload.get("name", "default"), ids)
                return json_response(
                    200, {"v": wire.WIRE_VERSION, "deleted": int(n)}, hdrs)
            if op == "flush":
                n = (self.service.pump(force=True)
                     if hasattr(self.service, "pump") else 0)
                return json_response(
                    200, {"v": wire.WIRE_VERSION, "flushed": int(n)}, hdrs)
            return self._error(
                RaftError(f"unknown control op {op!r} (ops: upsert, "
                          "delete, flush)"), hdrs)
        except KeyError as exc:
            return self._error(
                RaftError(f"control op {op!r} missing field {exc}"), hdrs)
        except Exception as exc:  # noqa: BLE001 - taxonomy → status
            return self._error(exc, hdrs)

    def _healthz(self, req) -> Response:
        body = {"status": "ready"}
        code = 200
        if hasattr(self.service, "queue_depth"):
            body["queue_depth"] = int(self.service.queue_depth())
        if hasattr(self.service, "health"):
            # same fold as the obs exporter: zero pickable twins in any
            # group is an outage (503), fenced-but-surviving degrades
            from ..obs.http import _fold_replica_health

            code, body = _fold_replica_health(code, body,
                                              self.service.health())
        return json_response(code, body)

    def _stats_route(self, req) -> Response:
        body = {"v": wire.WIRE_VERSION}
        if hasattr(self.service, "queue_depth"):
            body["queue_depth"] = int(self.service.queue_depth())
        if self._stats is not None:
            body.update(self._stats())
        return json_response(200, body)

    # -- lifecycle -----------------------------------------------------------
    def stop(self, timeout_s: float = 5.0) -> None:
        """Stop the listener (the backend service is the caller's to
        shut down — the front door never owns it). Idempotent."""
        server, self._server = self._server, None
        if server is not None:
            server.stop(timeout_s)

    def __enter__(self) -> "NetServer":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
