"""Wire schemas for every serve-path message + the error/status mapping.

The in-process serve path passes Python objects (numpy blocks, exception
instances, keyword control); the wire forces explicit schemas on all of
them. Three message families, all JSON envelopes (version-tagged so a
rolling fleet can skew one version):

- **query batch** — ``{"v", "name", "k", "queries": <array>}`` where
  ``<array>`` is the base64 raw-buffer encoding below (never JSON float
  lists: a float32 row serializes to exactly 4 bytes/dim + base64
  overhead, round-trips bit-exact, and decodes with one ``frombuffer``);
- **candidate set** — ``{"v", "rows", "dists": <array>, "ids": <array>}``
  — the scatter-gather rule made schema: k ids + distances per part,
  NEVER raw vectors (candidates-only on the wire);
- **control** — ``{"v", "op", ...}`` for publish/flush/upsert/delete/
  warm/stop between router and workers.

Errors ride ``{"error": {"type", "message", "fields"}}`` bodies plus the
HTTP status from :data:`STATUS_BY_ERROR`; :func:`decode_error`
reconstructs the EXACT original exception class with structured fields
intact, so a caller's existing ``except OverloadedError`` fences work
unchanged across the wire. A ``retry_after_s`` field (mirrored in the
``Retry-After`` header) carries the server's backoff hint — see
:func:`raft_tpu.serve.submit_with_retry`.

Request ids and deadline budgets ride headers (:data:`H_REQUEST_ID`,
:data:`H_DEADLINE_MS`) so one trace spans wire→queue→flush; the server
returns its span decomposition in :data:`H_SPANS` so clients (and the
bench) can split p99 into wire vs queue vs flush without scraping.
"""

from __future__ import annotations

import base64

import numpy as np

from ..core.errors import RaftError
from ..serve.errors import (DeadlineExceededError, MemoryBudgetError,
                            OverloadedError, ReplicaUnavailableError,
                            ServeError, ServiceClosedError)

__all__ = [
    "WIRE_VERSION", "H_REQUEST_ID", "H_DEADLINE_MS", "H_RETRY_AFTER",
    "H_SPANS", "STATUS_BY_ERROR",
    "encode_array", "decode_array",
    "encode_query_batch", "decode_query_batch",
    "encode_candidates", "decode_candidates",
    "encode_control", "decode_control",
    "status_of", "encode_error", "decode_error",
    "encode_spans", "decode_spans",
]

WIRE_VERSION = 1

H_REQUEST_ID = "X-Raft-Request-Id"    # rid threading: wire→queue→flush
H_DEADLINE_MS = "X-Raft-Deadline-Ms"  # remaining budget, not a wall time
H_RETRY_AFTER = "Retry-After"         # seconds (float accepted)
H_SPANS = "X-Raft-Spans"              # "queue=1.2e-3,flush=3.4e-3"

# Admission taxonomy → HTTP status. ORDER MATTERS: subclasses before
# bases (MemoryBudgetError IS an OverloadedError; 507 Insufficient
# Storage is more specific than 429 Too Many Requests).
STATUS_BY_ERROR: tuple = (
    (MemoryBudgetError, 507),
    (OverloadedError, 429),          # includes stream.DeltaFullError
    (DeadlineExceededError, 504),
    (ReplicaUnavailableError, 503),
    (ServiceClosedError, 503),
)


# -- array codec ------------------------------------------------------------

def encode_array(a) -> dict:
    """``{"dtype", "shape", "b64"}`` — C-order raw buffer, little-endian
    (the only byte order the stack runs on), base64 for JSON transport."""
    a = np.ascontiguousarray(a)
    if a.dtype.byteorder == ">":
        a = a.astype(a.dtype.newbyteorder("<"))
    return {"dtype": str(a.dtype), "shape": list(a.shape),
            "b64": base64.b64encode(a.tobytes()).decode("ascii")}


def decode_array(d: dict) -> np.ndarray:
    a = np.frombuffer(base64.b64decode(d["b64"]), dtype=np.dtype(d["dtype"]))
    return a.reshape(d["shape"]).copy()  # writable, owns its buffer


# -- query batch ------------------------------------------------------------

def encode_query_batch(name: str, queries, k: int) -> dict:
    q = np.asarray(queries)
    return {"v": WIRE_VERSION, "name": str(name), "k": int(k),
            "queries": encode_array(q)}


def decode_query_batch(d: dict):
    """-> ``(name, queries, k)``; raises :class:`RaftError` (→400) on a
    malformed envelope so schema drift fails loudly at the door."""
    try:
        return str(d["name"]), decode_array(d["queries"]), int(d["k"])
    except (KeyError, TypeError, ValueError) as exc:
        raise RaftError(f"malformed query batch: {exc}") from exc


# -- candidate set ----------------------------------------------------------

def encode_candidates(dists, ids) -> dict:
    dists = np.asarray(dists)
    ids = np.asarray(ids)
    return {"v": WIRE_VERSION, "rows": int(dists.shape[0]),
            "dists": encode_array(dists), "ids": encode_array(ids)}


def decode_candidates(d: dict):
    """-> ``(dists, ids)`` host arrays."""
    try:
        return decode_array(d["dists"]), decode_array(d["ids"])
    except (KeyError, TypeError, ValueError) as exc:
        raise RaftError(f"malformed candidate set: {exc}") from exc


# -- control ----------------------------------------------------------------

def encode_control(op: str, **kw) -> dict:
    """Publish/flush/upsert/delete/warm/stop control envelope. Array
    values must already be :func:`encode_array` dicts (the caller knows
    which fields are arrays; this stays schema-agnostic)."""
    env = {"v": WIRE_VERSION, "op": str(op)}
    env.update(kw)
    return env


def decode_control(d: dict):
    """-> ``(op, payload_dict)``."""
    try:
        op = str(d["op"])
    except (KeyError, TypeError) as exc:
        raise RaftError(f"malformed control message: {exc}") from exc
    return op, {k: v for k, v in d.items() if k not in ("v", "op")}


# -- span decomposition header ----------------------------------------------

def encode_spans(spans: dict) -> str:
    return ",".join(f"{k}={float(v):.6g}" for k, v in spans.items())


def decode_spans(s: str | None) -> dict:
    if not s:
        return {}
    out = {}
    for part in s.split(","):
        k, _, v = part.partition("=")
        try:
            out[k.strip()] = float(v)
        except ValueError:
            continue  # a skewed peer's unknown span never fails a response
    return out


# -- error mapping ----------------------------------------------------------

# structured fields preserved across the wire, per class
_FIELDS = {
    "MemoryBudgetError": ("site", "budget_bytes", "accounted_bytes",
                          "need_bytes"),
    "ReplicaUnavailableError": ("name", "replicas", "fenced"),
}


def status_of(exc: BaseException) -> int:
    """HTTP status for a serve-path exception: the taxonomy table, then
    400 for any other :class:`RaftError` (validation — the request was
    wrong, not the server), else 500."""
    for cls, code in STATUS_BY_ERROR:
        if isinstance(exc, cls):
            return code
    return 400 if isinstance(exc, RaftError) else 500


def encode_error(exc: BaseException, *,
                 retry_after_s: float | None = None) -> tuple[int, dict]:
    """-> ``(status, body)``. The body's ``type`` is the concrete class
    name (so ``DeltaFullError`` survives as itself, not as its 429
    base); structured fields ride ``fields`` verbatim."""
    fields = {f: getattr(exc, f)
              for f in _FIELDS.get(type(exc).__name__, ()) if hasattr(exc, f)}
    if retry_after_s is not None:
        fields["retry_after_s"] = float(retry_after_s)
    return status_of(exc), {"error": {"type": type(exc).__name__,
                                      "message": str(exc),
                                      "fields": fields}}


def _error_class(name: str):
    table = {
        "RaftError": RaftError,
        "ServeError": ServeError,
        "OverloadedError": OverloadedError,
        "MemoryBudgetError": MemoryBudgetError,
        "ReplicaUnavailableError": ReplicaUnavailableError,
        "DeadlineExceededError": DeadlineExceededError,
        "ServiceClosedError": ServiceClosedError,
    }
    if name in table:
        return table[name]
    if name == "DeltaFullError":
        # lazy: stream is a heavy import the read-path client never needs
        from ..stream.mutable import DeltaFullError
        return DeltaFullError
    return None


def decode_error(body: dict, *, status: int = 0) -> BaseException:
    """Reconstruct the exact exception the server raised. Unknown types
    (a newer server's taxonomy) degrade to the nearest base the status
    implies, so old clients still shed/retry correctly."""
    err = (body or {}).get("error") or {}
    name = err.get("type", "")
    msg = err.get("message", f"server error (HTTP {status})")
    fields = dict(err.get("fields") or {})
    retry_after = fields.pop("retry_after_s", None)
    cls = _error_class(name)
    if cls is None:  # degrade by status family
        cls = {429: OverloadedError, 507: MemoryBudgetError,
               504: DeadlineExceededError, 503: ServiceClosedError,
               400: RaftError}.get(status, ServeError)
    kwargs = {f: fields[f]
              for f in _FIELDS.get(cls.__name__, ()) if f in fields}
    try:
        exc = cls(msg, **kwargs)
    except TypeError:  # constructor drift on a skewed peer
        exc = cls(msg)
    if retry_after is not None:
        exc.retry_after_s = float(retry_after)
    return exc
