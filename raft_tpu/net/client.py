"""Client library for the net front door.

Wraps stdlib ``urllib`` around the :mod:`~raft_tpu.net.wire` schemas and
re-raises the EXACT serve-taxonomy exception the server refused with —
status + structured JSON body → :func:`wire.decode_error` — so a caller's
existing ``except OverloadedError`` / ``except DeadlineExceededError``
fences work unchanged over the wire, structured fields
(``budget_bytes``, ``fenced``, ...) intact.

:meth:`NetClient.submit` is shaped exactly like
:meth:`SearchService.submit` (raises admission refusals synchronously,
returns a Future) — which makes
:func:`raft_tpu.serve.submit_with_retry` the client-side retry
discipline with NO wire-specific fork:

    client = NetClient(f"http://127.0.0.1:{server.port}")
    fut = serve.submit_with_retry(client, "corpus", q, k=10, timeout_s=0.2)
    dists, ids = fut.result()

The server's ``Retry-After`` hint rides the refusal as
``retry_after_s``, which ``submit_with_retry`` prefers over blind
exponential backoff; ``timeout_s`` becomes the ``X-Raft-Deadline-Ms``
header (remaining budget, re-computed per attempt by the retry loop), so
the server's deadline accounting stays truthful across retries.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from concurrent.futures import Future

from ..core.errors import RaftError
from ..serve.errors import ServiceClosedError
from . import wire

__all__ = ["NetClient"]


class NetClient:
    """One front door endpoint (``base_url`` like ``http://host:port``).

    ``http_timeout_s`` bounds the socket when the caller gives no
    deadline; a request WITH ``timeout_s`` uses that budget plus a small
    margin (the server, not the socket, should win the deadline race and
    answer 504 with a trace id)."""

    def __init__(self, base_url: str, *, http_timeout_s: float = 30.0):
        self.base_url = base_url.rstrip("/")
        self.http_timeout_s = float(http_timeout_s)

    # -- low-level -----------------------------------------------------------
    def _post(self, path: str, payload: dict, headers: dict,
              timeout_s: float | None):
        body = json.dumps(payload, default=float).encode()
        req = urllib.request.Request(
            self.base_url + path, data=body, method="POST",
            headers={"Content-Type": "application/json", **headers})
        sock_timeout = (self.http_timeout_s if timeout_s is None
                        else float(timeout_s) + 5.0)
        try:
            with urllib.request.urlopen(req, timeout=sock_timeout) as resp:
                return (resp.status, json.loads(resp.read().decode()),
                        dict(resp.headers))
        except urllib.error.HTTPError as e:
            raw = e.read()
            try:
                err_body = json.loads(raw.decode())
            except ValueError:
                err_body = {"error": {"type": "", "message":
                                      raw.decode(errors="replace")}}
            exc = wire.decode_error(err_body, status=e.code)
            if not hasattr(exc, "retry_after_s"):
                ra = e.headers.get(wire.H_RETRY_AFTER)
                if ra is not None:
                    try:
                        exc.retry_after_s = float(ra)
                    except ValueError:
                        pass
            raise exc from None
        except urllib.error.URLError as e:
            # connection-level failure: the front door itself is gone —
            # the closest taxonomy fact (callers' shutdown fences apply)
            raise ServiceClosedError(
                f"front door unreachable at {self.base_url}: "
                f"{e.reason}") from None

    def _get(self, path: str):
        try:
            with urllib.request.urlopen(self.base_url + path,
                                        timeout=self.http_timeout_s) as resp:
                return resp.status, json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            try:
                return e.code, json.loads(e.read().decode())
            except ValueError:
                return e.code, {}
        except urllib.error.URLError as e:
            raise ServiceClosedError(
                f"front door unreachable at {self.base_url}: "
                f"{e.reason}") from None

    # -- read path -----------------------------------------------------------
    def request(self, name: str, queries, k: int = 10, *,
                timeout_s: float | None = None, rid: str | None = None):
        """One wire search; returns ``(dists, ids, meta)`` where ``meta``
        carries ``rid`` (server-confirmed) and ``spans`` (the server's
        wire/queue/flush decomposition when available). Raises the
        reconstructed taxonomy error on refusal."""
        headers = {}
        if rid is not None:
            headers[wire.H_REQUEST_ID] = str(rid)
        if timeout_s is not None:
            headers[wire.H_DEADLINE_MS] = f"{float(timeout_s) * 1e3:.3f}"
        _, body, resp_headers = self._post(
            "/v1/search", wire.encode_query_batch(name, queries, k),
            headers, timeout_s)
        dists, ids = wire.decode_candidates(body)
        meta = {"rid": resp_headers.get(wire.H_REQUEST_ID),
                "spans": wire.decode_spans(resp_headers.get(wire.H_SPANS))}
        return dists, ids, meta

    def submit(self, name: str, queries, k: int = 10, *,
               timeout_s: float | None = None,
               rid: str | None = None) -> Future:
        """``SearchService.submit``-shaped: refusals raise synchronously
        (reconstructed taxonomy type, ``retry_after_s`` hint attached on
        429s), success returns an already-resolved Future of
        ``(dists, ids)`` — hand this object to
        :func:`raft_tpu.serve.submit_with_retry` as the service."""
        dists, ids, _ = self.request(name, queries, k,
                                     timeout_s=timeout_s, rid=rid)
        fut: Future = Future()
        fut.set_result((dists, ids))
        return fut

    def search(self, name: str, queries, k: int = 10, *,
               timeout_s: float | None = None):
        """Blocking convenience: ``(dists, ids)``."""
        dists, ids, _ = self.request(name, queries, k, timeout_s=timeout_s)
        return dists, ids

    # -- write / control path ------------------------------------------------
    def upsert(self, name: str, rows, ids=None):
        payload = wire.encode_control(
            "upsert", name=name, rows=wire.encode_array(rows),
            ids=None if ids is None else wire.encode_array(ids))
        _, body, _ = self._post("/v1/control", payload, {}, None)
        return wire.decode_array(body["ids"])

    def delete(self, name: str, ids) -> int:
        payload = wire.encode_control("delete", name=name,
                                      ids=wire.encode_array(ids))
        _, body, _ = self._post("/v1/control", payload, {}, None)
        return int(body["deleted"])

    def flush(self) -> int:
        _, body, _ = self._post("/v1/control", wire.encode_control("flush"),
                                {}, None)
        return int(body["flushed"])

    # -- introspection -------------------------------------------------------
    def healthz(self):
        """-> ``(status_code, body)`` — 503 means eject this endpoint."""
        return self._get("/healthz")

    def stats(self) -> dict:
        code, body = self._get("/v1/stats")
        if code != 200:
            raise RaftError(f"/v1/stats answered HTTP {code}: {body}")
        return body
