"""raft_tpu.net — the network front door: wire surface + process mesh.

ROADMAP item 5. Three layers, each usable alone:

- :mod:`~raft_tpu.net.wire` — explicit schemas for every serve-path
  message (query batch, candidate set, publish/flush control) plus the
  admission-taxonomy ↔ HTTP status mapping. Arrays ride base64-encoded
  raw buffers with dtype/shape, never Python floats; errors ride
  structured JSON bodies that reconstruct the exact exception type with
  fields intact on the client.
- :class:`~raft_tpu.net.server.NetServer` /
  :class:`~raft_tpu.net.client.NetClient` — a zero-dependency HTTP/JSON
  front end over :class:`raft_tpu.serve.SearchService` and the client
  library that wraps :func:`raft_tpu.serve.submit_with_retry`'s
  backoff/deadline discipline around the wire calls. Deadline budgets
  and request ids ride headers so one trace spans wire→queue→flush in
  the request log.
- :class:`~raft_tpu.net.mesh.ProcessMesh` — shard groups owned by
  separate worker *processes* behind a router, the scatter-gather merge
  crossing process boundaries with candidates-only on the wire (k ids +
  distances per part, never raw rows). Replica groups are placed across
  processes, so killing a worker is a strike→fence→failover event, not
  an outage; each worker rehearses the warm-before-flip publish ladder
  so the wire path serves with zero cold compiles.

The shared stdlib server plumbing lives in :mod:`~raft_tpu.net._httpd`
(also backing the obs exporter — one server pattern, not two). Heavy
submodules are imported lazily so ``obs.http → net._httpd`` never drags
the serve stack (or jax) into an import cycle.

See docs/serving.md § "Network front door".
"""

from __future__ import annotations

import importlib

from ._httpd import Httpd, Request, Response, json_response

__all__ = ["Httpd", "Request", "Response", "json_response",
           "wire", "NetServer", "NetClient", "ProcessMesh", "MeshSpec"]

_LAZY = {
    "NetServer": ("server", "NetServer"),
    "NetClient": ("client", "NetClient"),
    "ProcessMesh": ("mesh", "ProcessMesh"),
    "MeshSpec": ("mesh", "MeshSpec"),
    "wire": ("wire", None),
}


def __getattr__(name):
    if name in _LAZY:
        modname, attr = _LAZY[name]
        mod = importlib.import_module(f".{modname}", __name__)
        val = mod if attr is None else getattr(mod, attr)
        globals()[name] = val
        return val
    raise AttributeError(f"module 'raft_tpu.net' has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
