"""Shared stdlib HTTP server plumbing — one server pattern, not two.

Both the obs exporter (:mod:`raft_tpu.obs.http`) and the net front door
(:mod:`raft_tpu.net.server`) serve from a daemon-threaded stdlib
``http.server`` with the same conventions:

- **routing table** — an explicit ``{(method, path): handler}`` dict;
  handlers take a parsed :class:`Request` and return a :class:`Response`;
- **404 contract** — unknown paths fail loudly with the endpoint listing
  (in registration order) so a scrape-config or client-URL typo surfaces
  at deploy time instead of silently hitting a catch-all;
- **ephemeral-port bind** — ``port=0`` binds an OS-assigned port, read it
  off ``.port`` (tests and multi-worker meshes never race on a fixed
  port);
- **clean shutdown** — ``stop()`` shuts the listener down and joins the
  serving thread; also a context manager. Threads are daemons, so an
  unstopped server never blocks interpreter exit.

This module is intentionally dependency-free (stdlib only, no imports
from :mod:`raft_tpu`) so the import graph stays acyclic: ``obs.http``
imports it while ``net.server`` imports :mod:`raft_tpu.serve`, which
imports :mod:`raft_tpu.obs`.
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Mapping

__all__ = ["Request", "Response", "json_response", "Httpd",
           "JSON_TYPE", "TEXT_TYPE"]

JSON_TYPE = "application/json; charset=utf-8"
TEXT_TYPE = "text/plain; charset=utf-8"


class Request:
    """One parsed HTTP request as handed to a route handler."""

    __slots__ = ("method", "path", "query", "headers", "body")

    def __init__(self, method: str, path: str, query: dict,
                 headers, body: bytes):
        self.method = method
        self.path = path            # path only, query string stripped
        self.query = query          # parse_qs dict: key -> [values]
        self.headers = headers      # email.message.Message (case-insensitive)
        self.body = body

    def param(self, key: str, default=None):
        """Last query-string value for ``key`` (or ``default``)."""
        vals = self.query.get(key)
        return vals[-1] if vals else default

    def json(self):
        """Decode the body as JSON (raises ``ValueError`` on garbage)."""
        return json.loads(self.body.decode("utf-8"))


class Response:
    """What a route handler returns: status, body, content type, extra
    headers (``Content-Type``/``Content-Length`` are set by the server)."""

    __slots__ = ("code", "content_type", "body", "headers")

    def __init__(self, code: int, body, content_type: str = TEXT_TYPE,
                 headers: Mapping[str, str] | None = None):
        self.code = int(code)
        self.body = body.encode() if isinstance(body, str) else bytes(body)
        self.content_type = content_type
        self.headers = dict(headers) if headers else {}


def json_response(code: int, obj,
                  headers: Mapping[str, str] | None = None) -> Response:
    """A :class:`Response` carrying ``obj`` as JSON (numpy scalars and
    other floatables serialize via ``default=float``)."""
    return Response(code, json.dumps(obj, default=float).encode(),
                    JSON_TYPE, headers)


class Httpd:
    """A routed ``ThreadingHTTPServer`` on a daemon thread.

    ``routes`` maps ``(method, path)`` — e.g. ``("GET", "/metrics")``,
    ``("POST", "/v1/search")`` — to ``handler(Request) -> Response``.
    A handler that raises is answered with a 500 JSON error body rather
    than a hung socket. The 404 body lists the registered endpoints in
    registration order.
    """

    def __init__(self, routes: Mapping[tuple[str, str],
                                       Callable[[Request], Response]],
                 *, port: int = 0, host: str = "127.0.0.1",
                 name: str = "raft-httpd"):
        table = dict(routes)
        # registration order, deduped across methods — the 404 listing
        listing = ", ".join(dict.fromkeys(p for _, p in table))

        class Handler(BaseHTTPRequestHandler):
            def _dispatch(self, method: str) -> None:
                split = urllib.parse.urlsplit(self.path)
                handler = table.get((method, split.path))
                if handler is None:
                    resp = Response(
                        404,
                        f"unknown path {split.path!r}; endpoints: "
                        f"{listing}\n")
                else:
                    n = int(self.headers.get("Content-Length") or 0)
                    req = Request(method, split.path,
                                  urllib.parse.parse_qs(split.query),
                                  self.headers,
                                  self.rfile.read(n) if n else b"")
                    try:
                        resp = handler(req)
                    except Exception as exc:  # noqa: BLE001 - 500, not a hang
                        resp = json_response(
                            500, {"error": f"{type(exc).__name__}: {exc}"})
                self.send_response(resp.code)
                self.send_header("Content-Type", resp.content_type)
                self.send_header("Content-Length", str(len(resp.body)))
                for k, v in resp.headers.items():
                    self.send_header(k, str(v))
                self.end_headers()
                self.wfile.write(resp.body)

            def do_GET(self):  # noqa: N802 - http.server API
                self._dispatch("GET")

            def do_POST(self):  # noqa: N802 - http.server API
                self._dispatch("POST")

            def log_message(self, fmt, *args):
                # request-per-query traffic must not spam stderr; counts
                # are observable via metrics on the app side
                pass

        self._server = ThreadingHTTPServer((host, int(port)), Handler)
        self._server.daemon_threads = True
        self.host = host
        self.port = int(self._server.server_address[1])
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name=f"{name}-{self.port}", daemon=True)
        self._thread.start()

    def stop(self, timeout_s: float = 5.0) -> None:
        """Shut the listener down and join the serving thread. Idempotent."""
        server, self._server = self._server, None
        if server is None:
            return
        server.shutdown()
        server.server_close()
        self._thread.join(timeout_s)

    def __enter__(self) -> "Httpd":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
