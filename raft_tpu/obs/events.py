"""The unified operations event plane: one causally-ordered journal.

Every advisory and state transition the serving stack produces — drift
``retune_advised``, compactor ``reshard_advised`` and fold lifecycle, mem
budget refusals and pressure relief, tier promote/spill, replica
fence/unfence/probe/failover/stale, reshard split/flip/commit/abort, WAL
truncate/recovery, registry publish/retire, SLO verdict flips — lands in
ONE process-wide structured journal through :func:`emit`, instead of the
per-subsystem ad-hoc surfaces that preceded it (``DriftDetector.events``,
``Compactor.last_advice`` — both survive as thin views over this
journal). An operator (and a test) can then read a single timeline:
sequence numbers are strictly increasing across all emitters, every
event carries a ``(component, name, shard, epoch)`` subject and an
optional request id, and ``/debug/events`` (obs/http.py) pages it by
``since_seq``.

Semantics worth knowing:

- **One emit = log line + metric + journal entry, atomically.** A call
  site passes its pre-formatted WARNING (``message``/``log_args``) and
  its legacy per-site counter constructor (``counter=``/
  ``counter_labels=``) into the same :func:`emit` that appends the ring
  entry and bumps ``raft_tpu_events_total{kind,severity}`` — the three
  can no longer disagree on re-arm paths (previously the WARNING fired
  unconditionally while the counter was gated, or vice versa).
- **Disabled mode is one flag check.** Under ``obs.disable()``
  :func:`emit` returns on the first line after reading
  ``metrics._enabled`` — the ``obs_overhead`` discipline; nothing is
  appended, logged, counted, tapped or sunk.
- **Transition dedup lives here.** :func:`transition` records the last
  state (and a standing payload) per key, returning True only on
  change — the once-per-transition bookkeeping the compactor's
  ``_advice_key`` used to duplicate. The payload store is what makes
  ``Compactor.last_advice`` eviction-proof: a standing advisory survives
  even after its emitting event scrolls off the bounded ring.
- **Subscriber taps are the controller seam** (ROADMAP item 2): a tap
  sees every event, in sequence order, delivered synchronously inside
  the journal lock — taps must be fast and non-blocking (queue and
  return); a raising tap is dropped from delivery for that event but
  never breaks the emitter.
- **The JSONL sink rides the WAL's durability discipline**: appended
  line-per-event and rotated atomically (``os.replace`` + directory
  fsync, the ``core/serialize.atomic_write`` rename discipline), and
  :func:`load_jsonl` tolerates a torn tail exactly like WAL replay — a
  crash mid-append loses at most the unacknowledged last line.
- **The flight recorder** turns an SLO ``failing`` verdict (or an
  explicit :func:`snapshot`) into a postmortem bundle — recent event
  window, ``obs.mem.debug_payload()``, slowest request traces, a full
  metrics snapshot — written file-by-file through ``atomic_write`` and
  rate-limited on the journal's injected clock.

Kind catalogue: :data:`KINDS` below is the single source of truth
(``emit`` rejects unknown kinds); docs/observability.md mirrors it and
``tests/test_obs_catalogue.py`` lints both directions.
"""

from __future__ import annotations

import functools
import json
import os
import threading
import time
from collections import deque
from typing import Callable

from . import metrics

__all__ = [
    "EventJournal", "KINDS", "SEVERITIES", "emit", "subscribe",
    "unsubscribe", "transition", "transition_payload", "query", "tail",
    "last_seq", "counts_by_kind", "attach_sink", "detach_sink",
    "load_jsonl", "arm_flight_recorder", "disarm_flight_recorder",
    "snapshot", "clear", "default_journal", "configure",
]

SEVERITIES = ("info", "warning", "error")

# kind -> default severity. THE catalogue: emit() rejects kinds not
# listed here, docs/observability.md mirrors this table, and the
# catalogue lint (tests/test_obs_catalogue.py) holds the two equal in
# both directions — a new kind ships with its doc row or not at all.
KINDS = {
    # quality / tuning
    "retune_advised": "warning",
    # compaction lifecycle (stream/compactor.py)
    "reshard_advised": "warning",
    "reshard_advice_cleared": "info",
    "compaction_started": "info",
    "compaction_completed": "info",
    "compaction_failed": "error",
    # memory ledger (obs/mem.py)
    "budget_refusal": "error",
    "mem_pressure": "warning",
    # tiered storage (stream/tiered.py)
    "tier_promote": "info",
    "tier_spill": "info",
    # replica group (stream/replicated.py)
    "replica_fenced": "warning",
    "replica_unfenced": "info",
    "replica_probe": "info",
    "replica_stale": "error",
    "replica_failover": "warning",
    # elastic resharding (stream/sharded.py)
    "reshard_started": "info",
    "reshard_flip": "info",
    "reshard_committed": "info",
    "reshard_aborted": "error",
    # write-ahead log (stream/wal.py)
    "wal_truncated": "info",
    "wal_recovered": "info",
    # serve registry (serve/registry.py)
    "serve_published": "info",
    "serve_retired": "info",
    # SLO verdict transitions (obs/slo.py)
    "slo_verdict": "info",
    # closed-loop controller decisions (control/controller.py) — every
    # event embeds the triggering sensor event's seq + evidence inline,
    # so a decision is replayable from the journal alone
    "control/decision": "info",
    "control/skipped": "info",
    "control/action_completed": "info",
    "control/action_failed": "error",
    "control/degraded": "warning",
    "control/restored": "info",
    # process-mesh worker breakers (net/mesh.py) — the cross-process
    # mirror of the replica_* family: a killed worker process fences,
    # the scatter fails over to its twin in the same call
    "net_worker_fenced": "warning",
    "net_worker_unfenced": "info",
    "net_worker_failover": "warning",
    # the recorder's own breadcrumb (this module)
    "flight_recorder": "info",
}

_SUBJECT_KEYS = ("component", "name", "shard", "epoch")

_LOG_LEVELS = {"info": "info", "warning": "warning", "error": "error"}


@functools.lru_cache(maxsize=None)
def _c_events():
    return metrics.counter(
        "raft_tpu_events_total",
        "journal events by kind and severity (the unified operations "
        "event plane — every advisory/transition call site emits here)")


def _norm_subject(subject) -> dict:
    """``(component, name, shard, epoch)`` tuple (trailing entries
    optional) or dict → the four flat subject keys (None-padded)."""
    if subject is None:
        vals = ()
    elif isinstance(subject, dict):
        return {k: subject.get(k) for k in _SUBJECT_KEYS}
    else:
        vals = tuple(subject)
    out = dict.fromkeys(_SUBJECT_KEYS)
    for k, v in zip(_SUBJECT_KEYS, vals):
        out[k] = v
    return out


class EventJournal:
    """One bounded, lock-guarded event ring (see module doc). The
    process-wide instance lives behind the module-level veneer; tests
    construct their own with an injected clock and a small capacity."""

    def __init__(self, capacity: int = 2048,
                 clock: Callable[[], float] = time.monotonic):
        # RLock: a subscriber tap may emit (the controller seam reacts
        # in-line); delivery stays in-lock so tap order == seq order
        self._lock = threading.RLock()
        self._ring: deque = deque(maxlen=int(capacity))
        self._seq = 0
        self._clock = clock
        # cumulative per-kind counts — survive ring eviction, so a bench
        # window's per-kind attribution never undercounts
        self._counts: dict[str, int] = {}
        self._taps: list = []
        # transition-dedup state: key -> (state, payload). Plain dict
        # bookkeeping, NOT gated on metrics._enabled — standing
        # advisories (Compactor.last_advice) must answer correctly even
        # while the observable surface is off.
        self._transitions: dict = {}
        # durable JSONL sink (attach_sink)
        self._sink_path: str | None = None
        self._sink_f = None
        self._sink_bytes = 0
        self._sink_rotate = 0
        # flight recorder (arm_flight_recorder)
        self._rec_dir: str | None = None
        self._rec_request_log = None
        self._rec_interval = 300.0
        self._rec_window = 256
        self._rec_last_at: float | None = None

    # -- emit ----------------------------------------------------------------
    def emit(self, kind: str, severity: str | None = None, *,
             subject=None, evidence: dict | None = None,
             request_id: str | None = None, message: str | None = None,
             log_args: tuple = (), counter=None,
             counter_labels: dict | None = None) -> dict | None:
        """Append one event; returns the event dict (None when obs is
        disabled — the single flag check below IS the disabled path).
        ``counter`` is the call site's legacy lru-cached metric
        constructor (zero-arg, returns the Metric), incremented here so
        the per-site counter, the WARNING (``message`` + lazy
        ``log_args``) and the journal entry are one atomic emission."""
        if not metrics._enabled:
            return None
        sev = KINDS.get(kind)
        if sev is None:
            raise ValueError(
                f"unknown event kind {kind!r}: add it to "
                f"raft_tpu.obs.events.KINDS (and the docs/observability.md "
                "catalogue) first")
        if severity is not None:
            if severity not in SEVERITIES:
                raise ValueError(f"unknown severity {severity!r} "
                                 f"(one of {SEVERITIES})")
            sev = severity
        ev = dict(_norm_subject(subject))
        with self._lock:
            self._seq += 1
            ev.update(seq=self._seq, at=round(self._clock(), 6), kind=kind,
                      severity=sev, evidence=dict(evidence or {}),
                      request_id=request_id)
            self._ring.append(ev)
            self._counts[kind] = self._counts.get(kind, 0) + 1
            _c_events().inc(1, kind=kind, severity=sev)
            if counter is not None:
                counter().inc(1, **(counter_labels or {}))
            if message is not None:
                from ..core.logger import logger

                getattr(logger, _LOG_LEVELS[sev])(message, *log_args)
            for fn in list(self._taps):
                try:
                    fn(ev)
                except Exception:  # a tap must never break the emitter
                    pass
            if self._sink_f is not None:
                self._sink_write(ev)
            if (self._rec_dir is not None and kind == "slo_verdict"
                    and ev["evidence"].get("status") == "failing"):
                self._snapshot_locked(reason="slo_failing", force=False)
        return ev

    # -- taps ----------------------------------------------------------------
    def subscribe(self, fn) -> Callable:
        """Register a tap called with every event dict, in sequence
        order, inside the journal lock (be fast; never block). Returns
        ``fn`` for decorator use."""
        with self._lock:
            if fn not in self._taps:
                self._taps.append(fn)
        return fn

    def unsubscribe(self, fn) -> None:
        with self._lock:
            if fn in self._taps:
                self._taps.remove(fn)

    # -- transition dedup ----------------------------------------------------
    def transition(self, key, state, payload=None) -> bool:
        """Record ``state`` under ``key``; True iff it CHANGED (the
        emit-once-per-transition guard). ``payload`` is the standing
        value :meth:`transition_payload` answers — eviction-proof
        storage for "current advisory" style views."""
        with self._lock:
            prev = self._transitions.get(key)
            if prev is not None and prev[0] == state:
                return False
            self._transitions[key] = (state, payload)
            return prev is not None or state is not None

    def transition_payload(self, key):
        with self._lock:
            entry = self._transitions.get(key)
            return None if entry is None else entry[1]

    # -- reads ---------------------------------------------------------------
    def last_seq(self) -> int:
        with self._lock:
            return self._seq

    def tail(self, n: int = 50) -> list:
        with self._lock:
            if n <= 0:
                return []
            return [dict(e) for e in list(self._ring)[-int(n):]]

    def query(self, *, kind: str | None = None, severity: str | None = None,
              component: str | None = None, name: str | None = None,
              since_seq: int = 0, limit: int | None = None) -> list:
        """Filtered, seq-ordered slice of the ring. ``since_seq`` is
        EXCLUSIVE (pass the last seq you saw — the pagination cursor);
        ``limit`` caps from the FRONT so pages walk forward."""
        with self._lock:
            out = [dict(e) for e in self._ring
                   if e["seq"] > int(since_seq)
                   and (kind is None or e["kind"] == kind)
                   and (severity is None or e["severity"] == severity)
                   and (component is None or e["component"] == component)
                   and (name is None or e["name"] == name)]
        if limit is not None:
            out = out[:max(int(limit), 0)]
        return out

    def counts_by_kind(self) -> dict:
        """Cumulative events per kind since construction/clear —
        eviction-proof (unlike ``len(query(...))``), so a bench window
        attributes counts by subtracting two calls."""
        with self._lock:
            return dict(self._counts)

    # -- durable JSONL sink --------------------------------------------------
    def attach_sink(self, path: str, *,
                    rotate_bytes: int = 4_000_000) -> None:
        """Mirror every event to ``path`` as one JSON line each. When the
        file exceeds ``rotate_bytes`` it rotates to ``path + ".1"``
        atomically (``os.replace`` + directory fsync — the
        ``core/serialize`` rename discipline); one rotated generation is
        kept. Reload with :func:`load_jsonl` (torn-tail tolerant)."""
        with self._lock:
            self._sink_close_locked()
            self._sink_path = str(path)
            self._sink_rotate = int(rotate_bytes)
            self._sink_f = open(self._sink_path, "ab")
            self._sink_bytes = self._sink_f.tell()

    def detach_sink(self) -> None:
        with self._lock:
            self._sink_close_locked()

    def _sink_close_locked(self) -> None:
        if self._sink_f is not None:
            try:
                self._sink_f.close()
            except OSError:
                pass
        self._sink_f = None
        self._sink_path = None
        self._sink_bytes = 0

    def _sink_write(self, ev: dict) -> None:
        from ..core import serialize

        try:
            line = (json.dumps(ev, default=float, sort_keys=True)
                    + "\n").encode()
            self._sink_f.write(line)
            self._sink_f.flush()
            self._sink_bytes += len(line)
            if self._sink_bytes >= self._sink_rotate:
                self._sink_f.close()
                os.replace(self._sink_path, self._sink_path + ".1")
                serialize.fsync_dir(os.path.dirname(
                    os.path.abspath(self._sink_path)))
                self._sink_f = open(self._sink_path, "ab")
                self._sink_bytes = 0
        except (OSError, ValueError):
            # a full/broken disk (or a descriptor closed under us) must
            # not take the emitter down; the ring and metrics still
            # carry the event
            self._sink_close_locked()

    # -- flight recorder -----------------------------------------------------
    def arm_flight_recorder(self, dir_: str, *, request_log=None,
                            min_interval_s: float = 300.0,
                            window: int = 256) -> None:
        """Arm automatic incident bundles: an SLO ``failing`` verdict
        event triggers :meth:`snapshot` into ``dir_``, rate-limited to
        one bundle per ``min_interval_s`` on the journal clock.
        ``request_log`` (an :class:`~raft_tpu.obs.requestlog.RequestLog`)
        contributes the slowest-request traces."""
        os.makedirs(dir_, exist_ok=True)
        with self._lock:
            self._rec_dir = str(dir_)
            self._rec_request_log = request_log
            self._rec_interval = float(min_interval_s)
            self._rec_window = int(window)

    def disarm_flight_recorder(self) -> None:
        with self._lock:
            self._rec_dir = None
            self._rec_request_log = None
            self._rec_last_at = None

    def snapshot(self, reason: str = "manual", *, dir_: str | None = None,
                 force: bool = True) -> str | None:
        """Write one incident bundle NOW (the explicit trigger; bypasses
        the rate limit unless ``force=False``). Returns the bundle
        directory, or None when skipped (rate-limited, or no directory
        armed and none passed)."""
        with self._lock:
            return self._snapshot_locked(reason=reason, dir_=dir_,
                                         force=force)

    def _snapshot_locked(self, *, reason: str, dir_: str | None = None,
                         force: bool) -> str | None:
        base = dir_ if dir_ is not None else self._rec_dir
        if base is None:
            return None
        now = self._clock()
        if (not force and self._rec_last_at is not None
                and now - self._rec_last_at < self._rec_interval):
            return None
        self._rec_last_at = now
        bundle = os.path.join(
            base, f"incident-{self._seq:08d}-{reason}")
        os.makedirs(bundle, exist_ok=True)
        window = [dict(e) for e in list(self._ring)[-self._rec_window:]]
        self._write_bundle(bundle, reason, now, window)
        self.emit("flight_recorder", subject=("obs", reason),
                  evidence={"dir": bundle, "events": len(window)})
        return bundle

    def _write_bundle(self, bundle: str, reason: str, now: float,
                      window: list) -> None:
        from ..core import serialize

        def dump(fname: str, payload) -> None:
            with serialize.atomic_write(os.path.join(bundle, fname)) as f:
                f.write(json.dumps(payload, default=float,
                                   indent=1).encode())

        dump("events.json", window)
        try:
            from . import mem as obs_mem

            dump("mem.json", obs_mem.debug_payload())
        except Exception:  # the recorder must never take the process down
            pass
        rlog = self._rec_request_log
        try:
            dump("requests.json",
                 None if rlog is None else rlog.to_json(recent=50,
                                                        slowest=10))
        except Exception:
            pass
        try:
            dump("metrics.json", metrics.snapshot())
        except Exception:
            pass
        dump("meta.json", {"reason": reason, "at": round(now, 6),
                           "last_seq": self._seq,
                           "window_events": len(window)})

    # -- lifecycle -----------------------------------------------------------
    def clear(self) -> None:
        """Drop ring contents, counts and transition state (tests).
        ``seq`` keeps counting — like a WAL's sequence, it coordinates
        with ``since_seq`` cursors and must never restart."""
        with self._lock:
            self._ring.clear()
            self._counts.clear()
            self._transitions.clear()


# -- process-wide journal + module-level veneer ------------------------------

_journal = EventJournal()


def default_journal() -> EventJournal:
    return _journal


def configure(capacity: int | None = None,
              clock: Callable[[], float] | None = None) -> EventJournal:
    """Swap the process-wide journal (tests: injected clock / small
    ring). Returns the NEW journal; taps, sinks and transition state of
    the old one are dropped."""
    global _journal
    old = _journal
    _journal = EventJournal(
        capacity=capacity if capacity is not None else old._ring.maxlen,
        clock=clock if clock is not None else old._clock)
    old.detach_sink()
    return _journal


def emit(kind: str, severity: str | None = None, *, subject=None,
         evidence: dict | None = None, request_id: str | None = None,
         message: str | None = None, log_args: tuple = (),
         counter=None, counter_labels: dict | None = None) -> dict | None:
    return _journal.emit(kind, severity, subject=subject,
                         evidence=evidence, request_id=request_id,
                         message=message, log_args=log_args,
                         counter=counter, counter_labels=counter_labels)


def subscribe(fn) -> Callable:
    return _journal.subscribe(fn)


def unsubscribe(fn) -> None:
    _journal.unsubscribe(fn)


def transition(key, state, payload=None) -> bool:
    return _journal.transition(key, state, payload)


def transition_payload(key):
    return _journal.transition_payload(key)


def query(**kw) -> list:
    return _journal.query(**kw)


def tail(n: int = 50) -> list:
    return _journal.tail(n)


def last_seq() -> int:
    return _journal.last_seq()


def counts_by_kind() -> dict:
    return _journal.counts_by_kind()


def attach_sink(path: str, *, rotate_bytes: int = 4_000_000) -> None:
    _journal.attach_sink(path, rotate_bytes=rotate_bytes)


def detach_sink() -> None:
    _journal.detach_sink()


def arm_flight_recorder(dir_: str, *, request_log=None,
                        min_interval_s: float = 300.0,
                        window: int = 256) -> None:
    _journal.arm_flight_recorder(dir_, request_log=request_log,
                                 min_interval_s=min_interval_s,
                                 window=window)


def disarm_flight_recorder() -> None:
    _journal.disarm_flight_recorder()


def snapshot(reason: str = "manual", *, dir_: str | None = None,
             force: bool = True) -> str | None:
    return _journal.snapshot(reason, dir_=dir_, force=force)


def clear() -> None:
    _journal.clear()


def load_jsonl(path: str) -> list:
    """Reload a sink file: one event dict per intact line, stopping at
    the first undecodable one — the WAL's torn-tail discipline (a crash
    mid-append loses only the unacknowledged tail; everything before it
    is returned)."""
    out: list = []
    try:
        with open(path, "rb") as f:
            for raw in f:
                try:
                    out.append(json.loads(raw))
                except ValueError:
                    break
    except OSError:
        pass
    return out
