"""Shared build-cost metrics (the ``raft_tpu_build_*`` catalogue,
docs/observability.md): emitted by the balanced coarse trainer
(cluster/kmeans_balanced), the distributed psum-EM drivers (parallel/ivf)
and the CAGRA build (neighbors/cagra). One home so no build subsystem
reaches into another's private helpers for a metric handle."""

from __future__ import annotations

import functools

from . import metrics

__all__ = ["assignment_passes", "sampled_rows", "build_phase",
           "ooc_chunks", "ooc_staged_bytes", "ooc_chunk_rows"]


@functools.lru_cache(maxsize=None)
def assignment_passes():
    return metrics.counter(
        "raft_tpu_build_assignment_passes_total",
        "coarse-trainer assignment passes by phase (em = one per EM "
        "iteration, final = the closing sharpening pass, fill = the "
        "list-fill assignment) and rows walked per pass (mode=full walks "
        "the trainset, minibatch one batch)")


@functools.lru_cache(maxsize=None)
def sampled_rows():
    return metrics.gauge(
        "raft_tpu_build_sampled_rows",
        "rows the coarse trainer assigns per EM iteration (batch_rows in "
        "minibatch mode, the whole trainset in full mode)", unit="rows")


@functools.lru_cache(maxsize=None)
def build_phase():
    return metrics.histogram(
        "raft_tpu_build_phase_seconds",
        "per-phase build walls (coarse trainer EM/final pass, CAGRA knn "
        "chunk loop / optimize)", unit="seconds")


@functools.lru_cache(maxsize=None)
def ooc_chunks():
    return metrics.counter(
        "raft_tpu_build_ooc_chunks_total",
        "corpus chunks processed by the out-of-core streamed build, by "
        "index kind and pipeline stage (assign = the label pass, fill = "
        "the scatter/encode pass, materialize = chunked device upload "
        "for dataset-resident kinds)")


@functools.lru_cache(maxsize=None)
def ooc_staged_bytes():
    return metrics.counter(
        "raft_tpu_build_ooc_staged_bytes_total",
        "host bytes staged through the out-of-core build's "
        "double-buffered chunk stager (core.chunked.ChunkStager); "
        "resident staging bytes stay constant — this counts traffic",
        unit="bytes")


@functools.lru_cache(maxsize=None)
def ooc_chunk_rows():
    return metrics.gauge(
        "raft_tpu_build_ooc_chunk_rows",
        "rows per streamed-build chunk (the reader's chunk_rows after "
        "clamping to the corpus)", unit="rows")
