"""Shared build-cost metrics (the ``raft_tpu_build_*`` catalogue,
docs/observability.md): emitted by the balanced coarse trainer
(cluster/kmeans_balanced), the distributed psum-EM drivers (parallel/ivf)
and the CAGRA build (neighbors/cagra). One home so no build subsystem
reaches into another's private helpers for a metric handle."""

from __future__ import annotations

import functools

from . import metrics

__all__ = ["assignment_passes", "sampled_rows", "build_phase"]


@functools.lru_cache(maxsize=None)
def assignment_passes():
    return metrics.counter(
        "raft_tpu_build_assignment_passes_total",
        "coarse-trainer assignment passes by phase (em = one per EM "
        "iteration, final = the closing sharpening pass, fill = the "
        "list-fill assignment) and rows walked per pass (mode=full walks "
        "the trainset, minibatch one batch)")


@functools.lru_cache(maxsize=None)
def sampled_rows():
    return metrics.gauge(
        "raft_tpu_build_sampled_rows",
        "rows the coarse trainer assigns per EM iteration (batch_rows in "
        "minibatch mode, the whole trainset in full mode)", unit="rows")


@functools.lru_cache(maxsize=None)
def build_phase():
    return metrics.histogram(
        "raft_tpu_build_phase_seconds",
        "per-phase build walls (coarse trainer EM/final pass, CAGRA knn "
        "chunk loop / optimize)", unit="seconds")
