"""SLO tracking: availability / latency / quality objectives with
multi-window error-budget burn rates and a /healthz verdict.

A serving fleet is not operated on raw metrics — it is operated on
*objectives* and how fast they consume their error budget (the
multi-window, multi-burn-rate alerting pattern of the Google SRE workbook,
ch. 5). This module is the stdlib-only tracker the serve tier feeds:

- **availability** — the non-overload admission fraction: every
  :meth:`SearchService.submit` either admits (good) or sheds at the queue
  bound (bad). Target e.g. 99.9% → a 0.1% error budget.
- **latency** — the p99 bound, computed from the queue-wait/flush
  decomposition the batcher already measures: a request is good when
  ``queue_wait + flush <= latency_bound_s``; the target fraction (default
  0.99) makes "p99 <= bound" a budgeted objective instead of a gauge.
- **quality** — the recall floor, fed by the
  :class:`~raft_tpu.obs.quality.RecallCanary`: every scored neighbor slot
  is good (matched the exact oracle) or bad; the budget is
  ``1 - recall_floor``.

Events land in an injected-clock ring of fixed time slots, so burn rates
over each window are exact and deterministic under test (no wall-clock
sleeps — the same discipline as the serve/stream suites). ``burn rate =
(bad fraction in window) / error budget``: 1.0 means the budget is being
consumed exactly at the sustainable rate; the degraded/failing thresholds
fire only when EVERY window agrees (the short window proves it is still
happening, the long one that it matters).

:meth:`healthz` renders the verdict for the HTTP endpoint
(``obs.start_http_exporter(port, slo=tracker)`` serves it at ``/healthz``):
ready/degraded → 200, failing → 503 so load balancers eject the replica.
Burn rates and the status are also published as ``raft_tpu_slo_*`` gauges
(catalogue: docs/observability.md).
"""

from __future__ import annotations

import functools
import threading
import time
from dataclasses import dataclass
from typing import Callable

from ..core.errors import expects
from . import events as obs_events
from . import metrics

__all__ = ["SLOPolicy", "SLOTracker", "OBJECTIVES"]

OBJECTIVES = ("availability", "latency", "quality")

_STATUS_CODE = {"ready": 0.0, "degraded": 1.0, "failing": 2.0}


@functools.lru_cache(maxsize=None)
def _g_burn():
    return metrics.gauge(
        "raft_tpu_slo_burn_rate",
        "error-budget burn rate per objective and window (1.0 = consuming "
        "the budget exactly at the sustainable rate)")


@functools.lru_cache(maxsize=None)
def _g_status():
    return metrics.gauge(
        "raft_tpu_slo_status",
        "SLO verdict: 0 ready, 1 degraded, 2 failing (the /healthz answer)")


@functools.lru_cache(maxsize=None)
def _c_events():
    return metrics.counter(
        "raft_tpu_slo_events_total",
        "SLO events per objective and outcome (good/bad)")


@dataclass(frozen=True)
class SLOPolicy:
    """Objectives + windowing (see module doc). Targets are GOOD-event
    fractions; budgets are their complements. ``windows_s`` must be
    multiples of ``slot_s`` (the ring's resolution)."""

    availability_target: float = 0.999
    latency_bound_s: float = 0.25
    latency_target: float = 0.99     # fraction under the bound == p99 bound
    recall_floor: float = 0.90
    windows_s: tuple = (300.0, 3600.0)
    slot_s: float = 30.0
    degraded_burn: float = 1.0
    failing_burn: float = 10.0


class SLOTracker:
    """Multi-window burn-rate tracker over an injected-clock slot ring."""

    def __init__(self, policy: SLOPolicy = SLOPolicy(), *,
                 name: str = "default",
                 clock: Callable[[], float] = time.monotonic):
        for target in (policy.availability_target, policy.latency_target,
                       policy.recall_floor):
            expects(0.0 < target < 1.0,
                    "SLO targets must be in (0, 1), got %r", target)
        expects(policy.slot_s > 0, "slot_s must be positive")
        for w in policy.windows_s:
            expects(w >= policy.slot_s
                    and abs(w / policy.slot_s - round(w / policy.slot_s))
                    < 1e-9,
                    "window %rs must be a multiple of slot_s=%rs",
                    w, policy.slot_s)
        self.policy = policy
        self.name = name
        self._clock = clock
        self._lock = threading.Lock()
        self._n_slots = int(round(max(policy.windows_s) / policy.slot_s))
        # ring[objective][pos] = [good, bad]; _slot is the absolute slot id
        # currently written at _slot % n_slots
        self._ring = {o: [[0.0, 0.0] for _ in range(self._n_slots)]
                      for o in OBJECTIVES}
        self._slot: int | None = None
        # last verdict seen by status() — the transition edge the
        # slo_verdict journal event (and the flight recorder) fires on
        self._last_status: str | None = None
        self._budget = {
            "availability": 1.0 - policy.availability_target,
            "latency": 1.0 - policy.latency_target,
            "quality": 1.0 - policy.recall_floor,
        }

    # -- ring mechanics ------------------------------------------------------
    def _advance_locked(self, now: float) -> int:
        idx = int(now // self.policy.slot_s)
        if self._slot is None:
            self._slot = idx
        elif idx > self._slot:
            gap = idx - self._slot
            if gap >= self._n_slots:  # everything in the ring expired
                for o in OBJECTIVES:
                    for slot in self._ring[o]:
                        slot[0] = slot[1] = 0.0
            else:
                for s in range(self._slot + 1, idx + 1):
                    pos = s % self._n_slots
                    for o in OBJECTIVES:
                        self._ring[o][pos][0] = 0.0
                        self._ring[o][pos][1] = 0.0
            self._slot = idx
        return self._slot % self._n_slots

    def _record(self, objective: str, good: float, bad: float) -> None:
        if good <= 0 and bad <= 0:
            return
        with self._lock:
            pos = self._advance_locked(self._clock())
            self._ring[objective][pos][0] += good
            self._ring[objective][pos][1] += bad
        if metrics._enabled:
            if good:
                _c_events().inc(good, objective=objective, outcome="good")
            if bad:
                _c_events().inc(bad, objective=objective, outcome="bad")

    # -- feeds ---------------------------------------------------------------
    def record_admission(self, admitted: bool) -> None:
        """One submit outcome: admitted, or shed at the queue bound."""
        self._record("availability", 1.0 if admitted else 0.0,
                     0.0 if admitted else 1.0)

    def record_request(self, queue_wait_s: float, flush_s: float) -> None:
        """One served request's latency decomposition (the batcher's
        queue-wait + flush walls); good iff the sum is under the bound."""
        ok = (queue_wait_s + flush_s) <= self.policy.latency_bound_s
        self._record("latency", 1.0 if ok else 0.0, 0.0 if ok else 1.0)

    def record_quality(self, matched_slots: float, scored_slots: float)\
            -> None:
        """Canary rerank outcome: ``matched`` of ``scored`` neighbor slots
        agreed with the exact oracle."""
        matched = float(matched_slots)
        scored = float(scored_slots)
        expects(0.0 <= matched <= scored,
                "matched_slots (%r) must be within [0, scored_slots=%r]",
                matched_slots, scored_slots)
        self._record("quality", matched, scored - matched)

    # -- burn rates ----------------------------------------------------------
    def _window_counts_locked(self, objective: str, window_s: float,
                              now: float) -> tuple[float, float]:
        cur = self._advance_locked(now)
        n = int(round(window_s / self.policy.slot_s))
        ring = self._ring[objective]
        good = bad = 0.0
        for back in range(min(n, self._n_slots)):
            slot = ring[(cur - back) % self._n_slots]
            good += slot[0]
            bad += slot[1]
        return good, bad

    def burn_rate(self, objective: str, window_s: float) -> float:
        """``(bad fraction over the window) / error budget``; 0.0 when the
        window holds no events (an idle service is not burning budget)."""
        expects(objective in OBJECTIVES, "unknown objective %r (one of %s)",
                objective, ", ".join(OBJECTIVES))
        with self._lock:
            good, bad = self._window_counts_locked(
                objective, float(window_s), self._clock())
        total = good + bad
        if total <= 0:
            return 0.0
        return (bad / total) / self._budget[objective]

    def burn_rates(self) -> dict:
        """{objective: {"<window>s": burn}} for every configured window,
        published to the ``raft_tpu_slo_burn_rate`` gauge as a side
        effect."""
        out: dict = {}
        for o in OBJECTIVES:
            out[o] = {}
            for w in self.policy.windows_s:
                burn = self.burn_rate(o, w)
                label = f"{int(w)}s"
                out[o][label] = round(burn, 4)
                if metrics._enabled:
                    _g_burn().set(round(burn, 4), objective=o, window=label)
        return out

    def burn_snapshot(self, window_s: float | None = None) -> dict:
        """Every objective's burn over ONE window (default the shortest
        configured) plus the window itself — the single-ring-walk
        snapshot a controller takes at decision time and inlines as
        evidence (``raft_tpu.control``: reshard admission, the
        degrade/restore loop, compaction pacing). One dict, one walk:
        the admission check and its journal evidence can never disagree
        on a slot boundary."""
        w = (float(window_s) if window_s is not None
             else min(self.policy.windows_s))
        out = {o: round(self.burn_rate(o, w), 4) for o in OBJECTIVES}
        out["window_s"] = w
        return out

    # -- verdict -------------------------------------------------------------
    def status(self, rates: dict | None = None) -> str:
        """ready / degraded / failing. An objective degrades (fails) the
        service only when its burn exceeds the threshold in EVERY window —
        the multi-window AND that keeps one bad slot from flapping a
        long-window alert, and one stale hour from paging on a problem
        that already stopped. ``rates`` (a :meth:`burn_rates` result) lets
        a caller make verdict and evidence atomic — :meth:`healthz` passes
        its own so the body's rates can never disagree with the status a
        slot boundary later."""
        if rates is None:
            rates = self.burn_rates()
        status = "ready"
        for o in OBJECTIVES:
            burns = rates[o].values()
            if all(b >= self.policy.failing_burn for b in burns):
                status = "failing"
                break
            if all(b >= self.policy.degraded_burn for b in burns):
                status = "degraded"
        if metrics._enabled:
            _g_status().set(_STATUS_CODE[status], name=self.name)
        if status != self._last_status:
            prev, self._last_status = self._last_status, status
            # verdict TRANSITIONS journal once each (ready→failing and
            # back both matter in a postmortem); a failing transition
            # also trips the armed flight recorder inside emit()
            obs_events.emit(
                "slo_verdict",
                severity=("error" if status == "failing" else
                          "warning" if status == "degraded" else "info"),
                subject=("slo", self.name, None, None),
                evidence={"status": status, "previous": prev,
                          "burn_rates": rates},
                message=("SLO verdict for %r: %s (was %s)"
                         if status != "ready" else None),
                log_args=(self.name, status, prev))
        return status

    def healthz(self) -> tuple[int, dict]:
        """The /healthz answer: (http status code, body dict). Failing maps
        to 503 so load balancers eject the replica; degraded stays 200 —
        it is an alert, not an outage. The verdict is computed from the
        SAME burn-rate snapshot the body reports (one ring walk)."""
        rates = self.burn_rates()
        status = self.status(rates)
        body = {
            "status": status,
            "name": self.name,
            "objectives": {
                o: {"burn_rates": rates[o],
                    "budget": round(self._budget[o], 6)}
                for o in OBJECTIVES
            },
            "policy": {
                "availability_target": self.policy.availability_target,
                "latency_bound_s": self.policy.latency_bound_s,
                "latency_target": self.policy.latency_target,
                "recall_floor": self.policy.recall_floor,
                "windows_s": list(self.policy.windows_s),
                "degraded_burn": self.policy.degraded_burn,
                "failing_burn": self.policy.failing_burn,
            },
        }
        return (503 if status == "failing" else 200), body
