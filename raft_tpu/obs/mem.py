"""Memory ledger: live-bytes attribution, retirement audits, budget gate.

The serving stack's correctness-critical free paths — registry
retire-after-drain, compaction swap, per-shard fold, `release_programs` —
were asserted nowhere until PR 9's ProgramCache-pins-a-retired-Comms leak
proved the failure class is live, and ROADMAP item 2 (beyond-HBM tiering)
needs memory-budget-aware planning before it can split bytes across
HBM/host/disk. This module is the groundwork for both: the system's view of
its own bytes. Three pieces:

- **The ledger** (:class:`MemLedger`, module singleton behind the veneer
  functions). Every long-lived device/host allocation is
  :func:`account`\\ ed to ``(component, name, shard, epoch)`` — index stores
  (``index/<kind>``, hooked into every ``neighbors/*`` build and extend),
  delta memtables + tombstone bitsets + id maps (``stream``, per state
  epoch, per shard under the sharded tier), serve registry versions
  (``serve/version``). Totals publish as the ``raft_tpu_mem_device_bytes``
  / ``raft_tpu_mem_host_bytes`` gauges (per component+name) with process
  peak watermarks; per-device HBM occupancy rides
  ``raft_tpu_mem_hbm_bytes`` from ``device.memory_stats()`` where the
  backend provides it (TPU/GPU; the CPU backend has none — there the
  ledger IS the fallback, which is why it exists as accounting rather
  than a stats poll).

  Entries hold a **weakref** to their owner (the index / stream state /
  searcher closure): when the owner is garbage-collected the entry
  auto-releases, so accounted bytes are live bytes — an entry can never
  outlive its arrays, and an owner that survives its retirement is
  visible instead of silent.

- **The retirement audit**. :func:`retire` marks an allocation as
  expected-to-free (the registry marks a version at retire-after-drain,
  a compaction swap marks the pre-swap epoch and replaced sealed index).
  A retired entry that stays accounted — its owner still strongly
  referenced somewhere — is a LEAK of exactly the PR 9 class;
  :func:`audit` lists them (optionally after a forced ``gc.collect()``)
  and the ``raft_tpu_mem_retired_unfreed`` gauge tracks the count. The
  tier-1 ``mem`` marker suite pins the free paths with this.

- **The footprint estimator + budget gate**. :func:`plan` predicts the
  long-lived index bytes (and a coarse build peak) per index kind from
  the same sizing rules the builds use; ``Resources.memory_budget_bytes``
  (None = unenforced, the default) is checked at ``build`` / ``publish``
  / ``upsert`` admission through :func:`gate`, raising
  :class:`raft_tpu.serve.errors.MemoryBudgetError` — an
  ``OverloadedError``, so it joins the existing admission taxonomy and
  is whole-or-nothing like every other admission refusal: the gate runs
  before any state lands.

``obs.disable()`` reduces every ledger touch point to a single module-flag
check (``account`` returns ``None`` and every entry point no-ops on
``None`` — pinned by the ``obs_overhead`` marker); ``/debug/mem`` on the
:mod:`raft_tpu.obs.http` exporter serves the component/shard/epoch
breakdown, top allocations and audit status. Catalogue + worked example:
docs/observability.md; sizing formulas: docs/serving.md and
docs/streaming.md "Capacity planning".
"""

from __future__ import annotations

import functools
import threading
import time
import weakref

from . import events as obs_events
from . import metrics

__all__ = [
    "MemLedger", "ledger", "account", "account_index", "release", "retire",
    "reaccount", "totals", "reset_peak", "breakdown", "audit", "plan",
    "gate", "unaccounted_index_bytes", "hbm_stats", "note_workspace",
    "debug_payload", "register_pressure_handler",
    "register_debug_section", "gate_host", "headroom",
]


# -- metrics (catalogue: docs/observability.md) ------------------------------

@functools.lru_cache(maxsize=None)
def _g_device():
    return metrics.gauge(
        "raft_tpu_mem_device_bytes",
        "ledger-accounted live device bytes per component and name",
        unit="bytes")


@functools.lru_cache(maxsize=None)
def _g_host():
    return metrics.gauge(
        "raft_tpu_mem_host_bytes",
        "ledger-accounted live host bytes per component and name",
        unit="bytes")


@functools.lru_cache(maxsize=None)
def _g_device_peak():
    return metrics.gauge(
        "raft_tpu_mem_device_peak_bytes",
        "peak ledger-accounted device bytes since process start (or the "
        "last reset_peak)", unit="bytes")


@functools.lru_cache(maxsize=None)
def _g_host_peak():
    return metrics.gauge(
        "raft_tpu_mem_host_peak_bytes",
        "peak ledger-accounted host bytes since process start (or the "
        "last reset_peak)", unit="bytes")


@functools.lru_cache(maxsize=None)
def _g_hbm():
    return metrics.gauge(
        "raft_tpu_mem_hbm_bytes",
        "per-device allocator occupancy from device.memory_stats() "
        "(stat in bytes_in_use/peak_bytes_in_use/bytes_limit); absent on "
        "backends without memory stats (CPU) — the ledger gauges are the "
        "fallback there", unit="bytes")


@functools.lru_cache(maxsize=None)
def _g_retired_unfreed():
    return metrics.gauge(
        "raft_tpu_mem_retired_unfreed",
        "allocations marked retired whose owner is still alive — the "
        "leak class the retirement audit exists to catch")


@functools.lru_cache(maxsize=None)
def _c_refusals():
    return metrics.counter(
        "raft_tpu_mem_budget_refusals_total",
        "admissions refused by the memory_budget_bytes gate, by site "
        "(build/publish/upsert)")


@functools.lru_cache(maxsize=None)
def _g_workspace():
    return metrics.gauge(
        "raft_tpu_mem_workspace_bytes",
        "transient workspace bytes implied by the last memory-aware tile "
        "choice per op — always <= Resources.workspace_bytes (the "
        "batching-heuristic contract, pinned by test)", unit="bytes")


# -- the ledger --------------------------------------------------------------

def _nbytes(arrays) -> int:
    """Total nbytes of one array or an iterable of arrays (duck-typed on
    ``.nbytes`` so jax and numpy arrays both count without importing
    either here)."""
    if arrays is None:
        return 0
    if hasattr(arrays, "nbytes"):
        return int(arrays.nbytes)
    return sum(int(a.nbytes) for a in arrays if a is not None)


class _Alloc:
    """One ledger entry. ``released`` flips exactly once (under the ledger
    lock); the owner weakref's callback routes through the ledger so a
    collected owner releases its entry automatically."""

    __slots__ = ("token", "component", "name", "shard", "epoch",
                 "device_bytes", "host_bytes", "created_at", "retired_at",
                 "released", "wref", "owner_key")

    def __init__(self, token, component, name, shard, epoch,
                 device_bytes, host_bytes, created_at):
        self.token = token
        self.component = component
        self.name = name
        self.shard = shard
        self.epoch = epoch
        self.device_bytes = device_bytes
        self.host_bytes = host_bytes
        self.created_at = created_at
        self.retired_at = None
        self.released = False
        self.wref = None
        self.owner_key = None


class MemLedger:
    """Thread-safe live-bytes ledger (see module doc). The module-level
    veneer functions operate on the process singleton (:func:`ledger`);
    construct directly for an isolated instance (tests)."""

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        # REENTRANT: owner-weakref callbacks route through release(), and
        # the gc can run them on THIS thread at any allocation point —
        # including inside a ledger-locked section (a plain Lock would
        # deadlock). Re-entrant releases are safe: each completes atomically
        # in program order, and a just-created entry's owner is pinned by
        # the caller's frame, so the entry being built can never release
        # mid-account.
        self._lock = threading.RLock()
        self._allocs: dict[int, _Alloc] = {}
        # (id(owner), component) -> token: account() is idempotent per
        # owner+component — re-accounting replaces the entry (the stream
        # state's delta bucket grows; a wrapped sealed index re-attributes
        # under its serving name)
        self._owners: dict[tuple, int] = {}
        self._next = 1
        self._dev = 0
        self._host = 0
        self._dev_peak = 0
        self._host_peak = 0
        # per-(component, name) sums backing the labeled gauges
        self._cn: dict[tuple, list] = {}

    # -- internals (call under self._lock) ----------------------------------
    def _bump(self, a: _Alloc, dev_delta: int, host_delta: int) -> None:
        self._dev += dev_delta
        self._host += host_delta
        self._dev_peak = max(self._dev_peak, self._dev)
        self._host_peak = max(self._host_peak, self._host)
        cn = self._cn.setdefault((a.component, a.name), [0, 0])
        cn[0] += dev_delta
        cn[1] += host_delta
        if metrics._enabled:
            _g_device().set(cn[0], component=a.component, name=a.name)
            _g_host().set(cn[1], component=a.component, name=a.name)
            _g_device_peak().set(self._dev_peak)
            _g_host_peak().set(self._host_peak)

    def _release_locked(self, a: _Alloc) -> None:
        if a.released:
            return
        a.released = True
        self._bump(a, -a.device_bytes, -a.host_bytes)
        self._allocs.pop(a.token, None)
        # prune the owner map (a replacement already repointed the key —
        # only remove it while it still names THIS entry), or the ledger
        # would leak one dead mapping per publish→retire cycle forever
        if (a.owner_key is not None
                and self._owners.get(a.owner_key) == a.token):
            del self._owners[a.owner_key]
        if metrics._enabled and a.retired_at is not None:
            self._set_retired_gauge_locked()

    def _set_retired_gauge_locked(self) -> None:
        # list() snapshot: a gc-triggered owner callback can re-enter
        # release() on this thread (the RLock admits it) and mutate the
        # dict mid-iteration otherwise
        n = sum(1 for a in list(self._allocs.values())
                if a.retired_at is not None)
        _g_retired_unfreed().set(n)

    # -- accounting ----------------------------------------------------------
    def account(self, component: str, *, name: str = "default",
                shard: int | None = None, epoch: int = 0,
                device=None, host=None, device_bytes: int = 0,
                host_bytes: int = 0, owner=None) -> int | None:
        """Register a long-lived allocation; returns an opaque token (or
        ``None`` when obs is disabled — every other entry point no-ops on
        ``None``, which keeps the disabled hot path to one flag check).

        ``device=`` / ``host=`` take an array or iterable of arrays
        (``.nbytes`` summed) on top of the explicit ``*_bytes``. ``owner``
        (weakref-able) auto-releases the entry when collected — accounted
        bytes are live bytes — and makes the entry idempotent: a second
        ``account`` for the same ``(owner, component)`` replaces the first
        (re-attribution, e.g. a sealed index wrapped under a serving name).
        """
        if not metrics._enabled:
            return None
        dev_b = int(device_bytes) + _nbytes(device)
        host_b = int(host_bytes) + _nbytes(host)
        with self._lock:
            if owner is not None:
                old = self._owners.get((id(owner), component))
                if old is not None and old in self._allocs:
                    self._release_locked(self._allocs[old])
            token = self._next
            self._next += 1
            a = _Alloc(token, str(component), str(name),
                       None if shard is None else int(shard), int(epoch),
                       dev_b, host_b, self._clock())
            if owner is not None:
                # the callback releases through the ledger; a manual
                # release() beforehand just makes it a no-op
                a.wref = weakref.ref(owner, lambda _r, t=token:
                                     self.release(t))
                a.owner_key = (id(owner), component)
                self._owners[a.owner_key] = token
            self._allocs[token] = a
            self._bump(a, dev_b, host_b)
        return token

    def reaccount(self, token: int | None, *, device=None, host=None,
                  device_bytes: int = 0, host_bytes: int = 0,
                  epoch: int | None = None) -> None:
        """Replace an entry's byte counts in place (the stream state's
        delta bucket grows and shrinks within one epoch)."""
        if token is None or not metrics._enabled:
            return
        dev_b = int(device_bytes) + _nbytes(device)
        host_b = int(host_bytes) + _nbytes(host)
        with self._lock:
            a = self._allocs.get(token)
            if a is None or a.released:
                return
            if epoch is not None:
                a.epoch = int(epoch)
            self._bump(a, dev_b - a.device_bytes, host_b - a.host_bytes)
            a.device_bytes, a.host_bytes = dev_b, host_b

    def release(self, token: int | None) -> None:
        """Drop an entry (idempotent; ``None`` no-ops)."""
        if token is None:
            return
        with self._lock:
            a = self._allocs.get(token)
            if a is not None:
                self._release_locked(a)

    def retire(self, token: int | None) -> None:
        """Mark an entry expected-to-free: its owner SHOULD become
        unreachable now (a serve version past its last lease, a pre-swap
        stream epoch). The entry stays accounted until the owner actually
        dies — a retired entry still alive is what :func:`audit` reports
        as a leak."""
        if token is None:
            return
        with self._lock:
            a = self._allocs.get(token)
            if a is None or a.released or a.retired_at is not None:
                return
            a.retired_at = self._clock()
            if metrics._enabled:
                self._set_retired_gauge_locked()

    def has_owner(self, owner, component: str | None = None) -> bool:
        """Whether ``owner`` has a live entry (under ``component``, or any)."""
        with self._lock:
            if component is not None:
                t = self._owners.get((id(owner), component))
                return t is not None and t in self._allocs
            return any(t in self._allocs
                       for (oid, _c), t in self._owners.items()
                       if oid == id(owner))

    # -- read side -----------------------------------------------------------
    def totals(self) -> dict:
        with self._lock:
            return {"device_bytes": self._dev, "host_bytes": self._host,
                    "device_peak_bytes": self._dev_peak,
                    "host_peak_bytes": self._host_peak,
                    "allocations": len(self._allocs)}

    def reset_peak(self) -> None:
        """Re-base the peak watermarks to the current totals (the bench
        scopes each row's peak this way; rows run sequentially)."""
        with self._lock:
            self._dev_peak, self._host_peak = self._dev, self._host
            if metrics._enabled:
                _g_device_peak().set(self._dev_peak)
                _g_host_peak().set(self._host_peak)

    def breakdown(self) -> list[dict]:
        """Every live entry as a dict, largest device footprint first."""
        now = self._clock()
        with self._lock:
            # list() snapshot — see _set_retired_gauge_locked: building the
            # row dicts allocates, allocation can run gc, and a dead
            # owner's callback re-enters release() through the RLock
            rows = [{
                "component": a.component, "name": a.name, "shard": a.shard,
                "epoch": a.epoch, "device_bytes": a.device_bytes,
                "host_bytes": a.host_bytes,
                "age_s": round(now - a.created_at, 3),
                "retired": a.retired_at is not None,
            } for a in list(self._allocs.values())]
        rows.sort(key=lambda r: (-r["device_bytes"], -r["host_bytes"],
                                 r["component"], r["name"]))
        return rows

    def audit(self, collect: bool = False) -> dict:
        """Retirement-audit status: entries marked retired whose owner is
        still alive (each one a leak of the PR 9 class — something still
        pins what the free path claimed to release). ``collect=True`` runs
        ``gc.collect()`` first so reference CYCLES that are merely
        not-yet-swept don't report as leaks (the tier-1 audits use it;
        the ``/debug/mem`` endpoint defaults off — forcing gc from a
        debug scrape would be rude)."""
        if collect:
            import gc

            gc.collect()
        now = self._clock()
        with self._lock:
            pending = [{
                "component": a.component, "name": a.name, "shard": a.shard,
                "epoch": a.epoch, "device_bytes": a.device_bytes,
                "host_bytes": a.host_bytes,
                "retired_for_s": round(now - a.retired_at, 3),
            } for a in list(self._allocs.values())
                if a.retired_at is not None]
            if metrics._enabled:
                self._set_retired_gauge_locked()
        pending.sort(key=lambda r: -r["retired_for_s"])
        return {"retired_unfreed": pending, "clean": not pending,
                "live_allocations": self.totals()["allocations"]}


_ledger = MemLedger()


def ledger() -> MemLedger:
    """The process-global ledger behind the module-level veneer."""
    return _ledger


def account(component, **kw):
    return _ledger.account(component, **kw)


def reaccount(token, **kw):
    return _ledger.reaccount(token, **kw)


def release(token):
    return _ledger.release(token)


def retire(token):
    return _ledger.retire(token)


def totals() -> dict:
    return _ledger.totals()


def reset_peak() -> None:
    return _ledger.reset_peak()


def breakdown() -> list[dict]:
    return _ledger.breakdown()


def audit(collect: bool = False) -> dict:
    return _ledger.audit(collect=collect)


# -- index accounting --------------------------------------------------------

def _index_kind_and_leaves(index):
    """(kind, device leaves) of a sealed index, or (None, ()) for unknown
    types (accounting must never be the thing that breaks a build)."""
    from ..neighbors import brute_force, cagra, ivf_flat, ivf_pq

    if isinstance(index, brute_force.BruteForce):
        return "brute_force", ([] if index.dataset is None
                               else [index.dataset])
    for kind, cls in (("ivf_flat", ivf_flat.IvfFlatIndex),
                      ("ivf_pq", ivf_pq.IvfPqIndex),
                      ("cagra", cagra.CagraIndex)):
        if isinstance(index, cls):
            leaves, _ = index.tree_flatten()
            return kind, [x for x in leaves if x is not None]
    return None, ()


def unaccounted_index_bytes(index) -> int:
    """Device bytes of ``index`` NOT already in the ledger — what a publish
    of it would newly pin. 0 for already-accounted indexes (their bytes are
    in the totals the gate compares) and for non-index serving hooks
    (closure-held arrays are not enumerable; a ``stream`` hook's bytes ride
    the mutable's own entries)."""
    kind, leaves = _index_kind_and_leaves(index)
    if kind is None or _ledger.has_owner(index, f"index/{kind}"):
        return 0
    return _nbytes(leaves)


def account_index(index, *, name: str = "default", shard: int | None = None,
                  epoch: int = 0):
    """Account a sealed index's device arrays under ``index/<kind>``
    (idempotent per index object — wrapping re-attributes the same entry
    under the serving name). The entry auto-releases when the index is
    collected. Returns the token (``None`` when disabled/unknown)."""
    if not metrics._enabled:
        return None
    kind, leaves = _index_kind_and_leaves(index)
    if kind is None:
        return None
    return _ledger.account(f"index/{kind}", name=name, shard=shard,
                           epoch=epoch, device=leaves, owner=index)


# -- per-device allocator stats ---------------------------------------------

def hbm_stats(update_gauges: bool = True) -> dict:
    """Per-device allocator occupancy from ``device.memory_stats()``
    (TPU/GPU backends; the CPU backend reports none — callers fall back to
    the ledger gauges, which is the documented CPU story). Publishes
    ``raft_tpu_mem_hbm_bytes{device,stat}`` unless told not to."""
    import jax

    out: dict = {}
    for d in jax.local_devices():
        try:
            ms = d.memory_stats()
        except Exception:
            ms = None
        if not ms:
            continue
        stats = {k: int(v) for k, v in ms.items()
                 if k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")}
        if not stats:
            continue
        out[f"{d.platform}:{d.id}"] = stats
        if update_gauges and metrics._enabled:
            for stat, v in stats.items():
                _g_hbm().set(v, device=f"{d.platform}:{d.id}", stat=stat)
    return out


# -- workspace attribution (Resources.workspace_bytes satellite) -------------

def note_workspace(op: str, nbytes: int) -> None:
    """Record the transient workspace a memory-aware tile choice implies
    (``raft_tpu_mem_workspace_bytes{op=}``) — the observable half of the
    ``Resources.workspace_bytes`` contract: the gauge must never exceed
    the budget the tile was sized under (pinned by test)."""
    if metrics._enabled:
        _g_workspace().set(int(nbytes), op=op)


# -- footprint estimator -----------------------------------------------------

_DTYPE_BYTES = {"float32": 4, "bfloat16": 2, "int8": 1, "uint8": 1}


def _ivf_capacity(rows: int, n_lists: int, split_factor: float) -> int:
    """The build's list-capacity policy bound — ``_list_utils
    .list_cap_target``, the SAME expression ``bound_capacity`` caps with,
    so a policy change moves the estimator too. Oversized lists split, so
    the allocated capacity is at most this — and on real (clustered) data
    the balanced trainer's residual skew means the cap binds, which is
    what makes this the estimate rather than just the bound. A build over
    near-uniform lists can come in below it."""
    from ..neighbors._list_utils import list_cap_target

    return list_cap_target(rows, n_lists, split_factor)


def plan(kind: str, params=None, rows: int = 0, dim: int = 0, *,
         dtype: str = "float32", storage: str = "hbm",
         tier=None, streamed: bool = False,
         chunk_rows: int | None = None) -> dict:
    """Predict the long-lived (serve) device bytes and a coarse build peak
    for an index of ``kind`` over ``(rows, dim)`` data — the sizing half of
    memory-budget-aware planning (docs/serving.md "Capacity planning" for
    the worked formulas). ``params`` is the kind's ``IndexParams`` (or
    ``None`` for defaults; ``brute_force`` takes none). Accuracy contract:
    ``index_bytes`` within ±20% of the measured ledger at 100k+ rows for
    all four kinds (pinned in tier-1; the dominant arrays are exact, the
    slack is IVF list padding) — per TIER under ``storage="tiered"``.

    ``storage="tiered"`` grows the estimate per tier: the index's own
    scan structures stay device-resident (the device figure is UNCHANGED
    — for brute-force/CAGRA that includes their stored dataset, which IS
    their scan operand), and the host/disk tier prices the RETAINED
    raw-row store (``rows x dim x B``) a ``MutableIndex(storage=
    "tiered")`` wrap keeps cold — a real, separate copy for every kind
    (it feeds rebuild compaction, the exact oracle and IVF-PQ's refine
    epilogue), landing on host RAM or on disk when ``tier`` (a
    :class:`raft_tpu.stream.tiered.TierPolicy`) sets ``disk_path``. The
    budget gates price the DEVICE figure only; host bytes gate against
    ``Resources.host_budget_bytes``.

    ``streamed=True`` prices the OUT-OF-CORE build instead (a
    ``core.chunked.ChunkedReader`` corpus, ``chunk_rows`` per chunk —
    default ``DEFAULT_CHUNK_ROWS``): the whole-corpus f32 working copy —
    the very term streaming exists to remove — is replaced by two staged
    chunks plus, for the IVF kinds, the device-resident label/id vectors
    of the chunked scatter (8 B/row). ``host_peak_bytes`` turns nonzero:
    the stager's two host buffers plus (IVF kinds) the trainset gather
    off the reader — what the ``site="build_stream"`` admission gate
    prices against ``Resources.host_budget_bytes`` BEFORE the coarse
    trainer spends anything. Accuracy: within ±20% of the measured
    ledger device peak of a chunked build at 100k rows (pinned in
    tier-1).

    Returns ``{"kind", "rows", "dim", "index_bytes", "build_peak_bytes",
    "host_peak_bytes", "breakdown": {array: bytes},
    "tiers": {"device", "host", "disk"}}`` (``index_bytes`` stays the
    device figure — the budget-gate comparator; ``host_peak_bytes`` is 0
    unless ``streamed``).
    """
    from ..core.errors import expects

    rows, dim = int(rows), int(dim)
    expects(rows > 0 and dim > 0, "plan() needs rows > 0 and dim > 0")
    item = _DTYPE_BYTES.get(str(dtype))
    expects(item is not None, "unknown dtype %r", dtype)
    bk: dict[str, int] = {}
    f32_copy = rows * dim * 4  # the build's working copy / ingest view
    train_rows = 0  # coarse-trainer subsample (IVF kinds; streamed host term)

    if kind == "brute_force":
        bk["dataset"] = rows * dim * item
        build_peak = bk["dataset"] + (f32_copy if item != 4 else 0)
    elif kind == "ivf_flat":
        from ..neighbors import ivf_flat

        p = params or ivf_flat.IndexParams()
        n_lists = min(int(p.n_lists), rows)
        # list_dtype "auto" stores bytes natively and f32 otherwise
        store = item if p.list_dtype == "auto" else _DTYPE_BYTES.get(
            p.list_dtype, 4)
        cap = _ivf_capacity(rows, n_lists, p.split_factor)
        bk["centers"] = n_lists * dim * 4
        bk["list_data"] = n_lists * cap * dim * store
        bk["list_ids"] = n_lists * cap * 4
        bk["list_norms"] = n_lists * cap * 4
        bk["list_sizes"] = n_lists * 4
        train_rows = min(max(int(rows * p.kmeans_trainset_fraction),
                             n_lists), rows)
        build_peak = sum(bk.values()) + f32_copy
    elif kind == "ivf_pq":
        from ..distance.types import DistanceType, resolve_metric
        from ..neighbors import ivf_pq

        p = params or ivf_pq.IndexParams()
        n_lists = min(int(p.n_lists), rows)
        pq_dim = p.pq_dim or ivf_pq._default_pq_dim(dim, p.pq_bits)
        pq_len = -(-dim // pq_dim)
        d_rot = pq_dim * pq_len
        # the build's pq8_split resolution rule, mirrored (ivf_pq.build):
        # split 8-bit codebooks are two 16-entry stages (32 rows), and L2
        # split indexes carry a per-slot cross-term constant
        ip = resolve_metric(p.metric) == DistanceType.InnerProduct
        split = p.pq_bits == 8 and (p.pq8_split if p.pq8_split is not None
                                    else not ip)
        n_codes = 32 if split else 1 << p.pq_bits
        cap = _ivf_capacity(rows, n_lists, p.split_factor)
        bk["centers"] = n_lists * dim * 4
        bk["centers_rot"] = n_lists * d_rot * 4
        bk["rotation"] = d_rot * dim * 4
        if p.codebook_kind == "per_cluster":
            bk["codebooks"] = n_lists * n_codes * pq_len * 4
        else:  # per_subspace (and the auto default's common outcome)
            bk["codebooks"] = pq_dim * n_codes * pq_len * 4
        bk["list_codes"] = n_lists * cap * pq_dim
        bk["list_ids"] = n_lists * cap * 4
        bk["list_sizes"] = n_lists * 4
        if split and not ip:
            bk["list_consts"] = n_lists * cap * 4
        if getattr(p, "residual_scale_norm", False):
            bk["list_scales"] = n_lists * 4
        # fast-scan funnel tier (IndexParams.fast_scan): bit-packed
        # signatures ride next to the codes (1bit: d_rot/8 B/slot, 4bit:
        # d_rot/2) plus the per-list decode scales
        fast_scan = getattr(p, "fast_scan", "none")
        if fast_scan != "none":
            sig_words = ivf_pq._sig_words(d_rot, fast_scan)
            bk["list_sig"] = n_lists * cap * sig_words
            bk["sig_scales"] = n_lists * 4
        # build peak: the f32 working copy plus the rotated-residual
        # trainset ((trainset, d_rot) f32) dominate the transients
        n_train = max(int(rows * p.kmeans_trainset_fraction), n_lists)
        train_rows = min(n_train, rows)
        build_peak = (sum(bk.values()) + f32_copy
                      + train_rows * d_rot * 4)
    elif kind == "cagra":
        from ..neighbors import cagra

        p = params or cagra.IndexParams()
        bk["dataset"] = rows * dim * item
        bk["graph"] = rows * int(p.graph_degree) * 4
        # build peak: the internal IVF-PQ knn-graph index + the
        # intermediate graph (ids + distances at the refine width)
        k, gpu_top_k, n_lists, pq_bits = cagra.knn_build_plan(p, rows, dim)
        from ..neighbors import ivf_pq

        pq_plan = plan("ivf_pq", ivf_pq.IndexParams(
            n_lists=n_lists, pq_bits=pq_bits), rows, dim)
        build_peak = (sum(bk.values()) + f32_copy
                      + pq_plan["index_bytes"] + rows * gpu_top_k * 8)
    else:
        from ..core.errors import RaftError

        raise RaftError(
            f"plan(): unknown index kind {kind!r} (expected brute_force, "
            "ivf_flat, ivf_pq or cagra)")
    expects(storage in ("hbm", "tiered"),
            "plan() storage must be 'hbm' or 'tiered', got %r", storage)
    host_peak = 0
    if streamed:
        from ..core.chunked import DEFAULT_CHUNK_ROWS

        cr = min(int(chunk_rows or DEFAULT_CHUNK_ROWS), rows)
        expects(cr >= 1, "plan() chunk_rows must be >= 1")
        # device canonicalization caps staged chunks at 4 B/elt; two
        # chunks are in flight at once (upload N+1 overlaps compute N)
        staged_dev = 2 * cr * dim * min(item, 4)
        host_peak = 2 * cr * dim * item
        if kind in ("ivf_flat", "ivf_pq"):
            # the chunked passes remove the whole-corpus working copy;
            # the scatter keeps the full label + id vectors
            # device-resident (int32 each) across both passes
            build_peak = build_peak - f32_copy + staged_dev + rows * 8
            # trainset gather off the reader lands a fresh host array
            host_peak += train_rows * dim * item
        else:
            # dataset-resident kinds (brute_force, cagra) stream only the
            # UPLOAD — the dataset still lands device-whole, so the peak
            # keeps every in-core term and adds the staged chunks; what
            # streaming removes is the host-side whole-corpus asarray
            build_peak += staged_dev
    tiers = {"device": int(sum(bk.values())), "host": 0, "disk": 0}
    if storage == "tiered":
        raw = rows * dim * item  # the full-precision refine rows
        cold = ("disk" if getattr(tier, "disk_path", None) is not None
                else "host")
        tiers[cold] = raw
        bk[f"tier_{cold}_rows"] = raw
    return {"kind": kind, "rows": rows, "dim": dim,
            "index_bytes": tiers["device"],
            "build_peak_bytes": int(build_peak),
            "host_peak_bytes": int(host_peak), "breakdown": bk,
            "tiers": tiers}


# -- budget gate -------------------------------------------------------------

# budget-pressure relief: callables ``fn(need_bytes) -> freed_bytes`` the
# gate consults ONCE before refusing a device admission — how tiered
# stores (raft_tpu.stream.tiered) spill their device mirrors to make room
# for a write instead of shedding it. Handlers must never raise (the gate
# swallows nothing) and must only drop REBUILDABLE state (caches).
_pressure_handlers: list = []

# extra /debug/mem sections: key -> zero-arg payload callable (the tiered
# registry contributes "tiers"); a failing provider is skipped — a debug
# endpoint must never take the process down
_debug_sections: dict = {}


def register_pressure_handler(fn) -> None:
    """Register a budget-pressure relief hook (see ``_pressure_handlers``
    above). Idempotent per callable."""
    if fn not in _pressure_handlers:
        _pressure_handlers.append(fn)


def register_debug_section(key: str, fn) -> None:
    """Register an extra ``/debug/mem`` payload section under ``key``."""
    _debug_sections[str(key)] = fn


def _relieve(need_bytes: int) -> None:
    for fn in list(_pressure_handlers):
        try:
            fn(int(need_bytes))
        except Exception:  # relief is best-effort; the re-check decides
            pass


def gate(res, need_bytes, *, site: str, detail: str = "",
         host_bytes=0) -> None:
    """Admission check against ``res.memory_budget_bytes`` (device) and
    ``res.host_budget_bytes`` (host): refuse when the ledger's accounted
    bytes plus the projected growth would exceed the armed budget. Both
    budgets default ``None`` = a single attribute check — the gate costs
    nothing unless armed. ``need_bytes``/``host_bytes`` may be callables
    (evaluated only when armed — plan() is not free). Raises
    :class:`raft_tpu.serve.errors.MemoryBudgetError` BEFORE the caller
    touches any state (whole-or-nothing; the error carries ``site`` /
    ``budget_bytes`` / ``accounted_bytes`` / ``need_bytes``).

    A device overage consults the registered PRESSURE HANDLERS once
    before refusing: a tiered store's device mirror is a cache, and
    spilling a cache (a counted, ``/debug/mem``-visible event) beats
    shedding the admission — only if the re-check still exceeds the
    budget does the gate raise.

    An armed budget REQUIRES observability: under ``obs.disable()`` the
    ledger stops accounting, so every gate would compare against a frozen
    (usually zero) total and cumulative enforcement would be silently void
    — three dark builds would each see 0 used and all admit. That is a
    configuration error and fails loudly here rather than enforcing a
    budget that does not hold."""
    from ..core.errors import RaftError

    budget = getattr(res, "memory_budget_bytes", None)
    host_budget = getattr(res, "host_budget_bytes", None)
    if budget is None and host_budget is None:
        return
    if not metrics._enabled:
        raise RaftError(
            f"memory_budget_bytes/host_budget_bytes is set but "
            f"observability is disabled: the ledger the budget gates "
            f"against does not account under obs.disable(), so "
            f"enforcement at {site!r} would be silently void — "
            "obs.enable() or unset the budget")
    if budget is not None:
        need = int(need_bytes() if callable(need_bytes) else need_bytes)
        used = _ledger.totals()["device_bytes"]
        if used + need > int(budget):
            # budget pressure: let registered relief (tier spills) free
            # device bytes, then re-check once
            obs_events.emit(
                "mem_pressure", subject=("mem", site, None, None),
                evidence={"site": site, "need_bytes": need,
                          "accounted_bytes": used,
                          "budget_bytes": int(budget),
                          "overage_bytes": used + need - int(budget)})
            _relieve(used + need - int(budget))
            used = _ledger.totals()["device_bytes"]
        if used + need > int(budget):
            from ..serve.errors import MemoryBudgetError

            obs_events.emit(
                "budget_refusal", subject=("mem", site, None, None),
                evidence={"site": site, "need_bytes": need,
                          "accounted_bytes": used,
                          "budget_bytes": int(budget)},
                counter=_c_refusals, counter_labels={"site": site})
            raise MemoryBudgetError(
                f"memory budget exceeded at {site}: accounted {used} B + "
                f"needed {need} B > budget {int(budget)} B"
                + (f" ({detail})" if detail else ""),
                site=site, budget_bytes=int(budget), accounted_bytes=used,
                need_bytes=need)
    if host_budget is not None:
        need_h = int(host_bytes() if callable(host_bytes) else host_bytes)
        used_h = _ledger.totals()["host_bytes"]
        # zero host need always admits: every DEVICE-side caller reaches
        # here with the host_bytes=0 default, and un-gated host growth
        # (delta memtables, bitsets — ledger-visible but not admitted
        # here) must not turn those into refusals. The device side has
        # the OPPOSITE pinned contract (budgets armed after builds land
        # refuse zero-growth publishes) — do not unify them.
        if need_h and used_h + need_h > int(host_budget):
            from ..serve.errors import MemoryBudgetError

            obs_events.emit(
                "budget_refusal",
                subject=("mem", f"{site}/host", None, None),
                evidence={"site": f"{site}/host", "need_bytes": need_h,
                          "accounted_bytes": used_h,
                          "budget_bytes": int(host_budget)},
                counter=_c_refusals,
                counter_labels={"site": f"{site}/host"})
            raise MemoryBudgetError(
                f"host memory budget exceeded at {site}: accounted "
                f"{used_h} B + needed {need_h} B > host budget "
                f"{int(host_budget)} B"
                + (f" ({detail})" if detail else ""),
                site=f"{site}/host", budget_bytes=int(host_budget),
                accounted_bytes=used_h, need_bytes=need_h)


def gate_host(res, host_bytes, *, site: str, detail: str = "") -> None:
    """The HOST half of :func:`gate` alone — for admissions that add
    zero device bytes (a tiered store's cold rows). The device budget
    deliberately does NOT run here: its cumulative check refuses any
    growth while the ledger sits over budget (the budgets-armed-late
    contract), which must not fail an operation that allocates no device
    memory at all — e.g. the successor store of a compaction fold while
    the double-buffered predecessor epoch is still accounted."""
    budget = getattr(res, "host_budget_bytes", None)
    if budget is None:
        return
    if not metrics._enabled:
        from ..core.errors import RaftError

        raise RaftError(
            f"host_budget_bytes is set but observability is disabled: "
            f"enforcement at {site!r} would be silently void — "
            "obs.enable() or unset the budget")

    class _HostOnly:
        host_budget_bytes = int(budget)
        memory_budget_bytes = None

    gate(_HostOnly(), 0, site=site, detail=detail, host_bytes=host_bytes)


def headroom(res=None) -> dict | None:
    """Device-budget headroom snapshot, or ``None`` when no
    ``memory_budget_bytes`` is armed (an unarmed budget has no headroom
    to reason about). The control plane's reshard admission reads this —
    a topology doubling is a double-buffered migration, so it is refused
    unless enough of the budget is free OR reclaimable by a pressure
    spill. ``spillable_bytes``/``spillable_frac`` count the tiered
    stores' device mirrors (caches the gate's pressure handlers drop on
    demand); both are 0 when no tiered store is live. Fractions are of
    the budget, so ``headroom_frac + spillable_frac`` is the admission
    quantity — and the dict inlines as journal evidence verbatim, so a
    control decision and its admission check can never disagree."""
    if res is None:
        from ..core.resources import default_resources

        res = default_resources()
    budget = getattr(res, "memory_budget_bytes", None)
    if budget is None:
        return None
    budget = int(budget)
    used = _ledger.totals()["device_bytes"]
    spillable = 0
    try:
        from ..stream.tiered import spillable_bytes

        spillable = int(spillable_bytes())
    except Exception:  # headroom is a sensor — never the failure itself
        pass
    return {
        "budget_bytes": budget,
        "device_bytes": int(used),
        "headroom_bytes": max(0, budget - int(used)),
        "headroom_frac": (round(max(0.0, 1.0 - used / budget), 4)
                          if budget else 0.0),
        "spillable_bytes": spillable,
        "spillable_frac": (round(spillable / budget, 4) if budget else 0.0),
    }


# -- /debug/mem payload ------------------------------------------------------

def debug_payload(top: int = 20) -> dict:
    """The ``/debug/mem`` JSON: totals + peaks, per-component aggregates,
    the ``top`` largest allocations (component/name/shard/epoch), audit
    status, per-device HBM stats where the backend has them, plus every
    registered extra section (``tiers`` — per-store residency, tier
    bytes and spill/promote events — once a tiered store is live)."""
    rows = _ledger.breakdown()
    by_comp: dict[str, dict] = {}
    for r in rows:
        c = by_comp.setdefault(r["component"], {
            "device_bytes": 0, "host_bytes": 0, "allocations": 0})
        c["device_bytes"] += r["device_bytes"]
        c["host_bytes"] += r["host_bytes"]
        c["allocations"] += 1
    try:
        hbm = hbm_stats()
    except Exception:  # a debug endpoint must never take the process down
        hbm = {}
    out = {"totals": _ledger.totals(), "by_component": by_comp,
           "top": rows[:int(top)], "audit": _ledger.audit(),
           "hbm": hbm}
    for key, fn in list(_debug_sections.items()):
        try:
            out[key] = fn()
        except Exception:  # a debug endpoint must never take the process down
            pass
    return out
