"""Per-flush device-dispatch counting for the serve hot path.

The host-free flush pipeline's third claim — fewer dispatches on the mesh
path — needs a meter: how many device interactions (jitted program calls
and host→device transfers at the instrumented serve/stream sites) one
flush actually performs. This module is that meter: a thread-local
counter the serve flush opens around the searcher call
(:func:`count`), with the stream/sharded scan, pad, gather, merge and
staging-upload sites calling :func:`note` as they dispatch. The batcher
publishes the total per flush as
``raft_tpu_serve_dispatches_per_flush`` (catalogue:
docs/observability.md), so the fused scatter-gather's dispatch reduction
is attributable in the bench artifact instead of asserted from memory.

This counts INSTRUMENTED DISPATCH SITES, not XLA ops: a single
``ivf_pq.search`` call is one site even though it runs several programs.
The number is a relative fusion meter — comparable across builds of the
same serve path — not an absolute op count. Cost discipline matches
:mod:`raft_tpu.obs.requestlog`: one thread-local ``getattr`` per site
when no counter is open.
"""

from __future__ import annotations

import threading

__all__ = ["count", "note"]

_tls = threading.local()


class count:
    """Context manager opening a dispatch counter on the current thread;
    read ``.total`` after exit. Reentrant-safe (inner scopes shadow, their
    counts roll up into the outer scope on exit so a nested open never
    loses dispatches)."""

    total: int

    def __enter__(self) -> "count":
        self.total = 0
        self._prev = getattr(_tls, "counter", None)
        _tls.counter = self
        return self

    def __exit__(self, *exc) -> None:
        _tls.counter = self._prev
        if self._prev is not None:
            self._prev.total += self.total


def note(n: int = 1) -> None:
    """Record ``n`` dispatches against the active counter (no-op without
    one — instrumented sites pay one getattr when no flush is counting)."""
    c = getattr(_tls, "counter", None)
    if c is not None:
        c.total += n
