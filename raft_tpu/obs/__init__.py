"""raft_tpu.obs — the observability surface (metrics, compile attribution,
instrumentation).

The reference operates through NVTX ranges (core/nvtx.hpp:95), spdlog runtime
control, and a bench harness that always writes structured results
(benchmark.hpp:111-200). The TPU rebuild's analogue is this package:

- :mod:`.metrics` — zero-dependency counters/gauges/histograms with labels;
  ``snapshot()`` (nested dict), ``to_prometheus()`` (text exposition format
  for scraping), ``to_json()`` (flat, subtractable — BENCH artifacts).
- :mod:`.instrument` — the ``@instrument`` decorator applied across the
  search/build/prims entry points (brute_force/ivf_flat/ivf_pq/cagra,
  pairwise_distance, select_k, kmeans).
- :mod:`.compile` — jax.monitoring subscription splitting compile vs execute
  and counting persistent-cache hits/misses.

Trace annotation (the NVTX analogue) lives in :mod:`raft_tpu.core.tracing`;
per-collective counters ride inside :mod:`raft_tpu.comms.comms`; the serving
layer's queue/occupancy/swap metrics ride inside :mod:`raft_tpu.serve`
(``raft_tpu_serve_*`` — docs/serving.md).

``disable()`` turns the whole surface off; the remaining overhead per
instrumented call is a single module-flag check (guarded by the
``obs_overhead`` smoke test in tier-1). See docs/observability.md for the
metric catalogue.
"""

from . import build
from . import compile  # noqa: A004 - submodule named like the builtin
from . import http
from . import metrics
from .compile import CompileRecord, attribution
from .http import MetricsExporter, start_http_exporter, stop_http_exporter
# NOTE: this deliberately rebinds the package attribute `obs.instrument` from
# the submodule to the decorator (the ergonomic call site); reach the helper
# fns via `from raft_tpu.obs.instrument import nrows`, not attribute access.
from .instrument import instrument
from .metrics import (DEFAULT_BUCKETS, Registry, counter, delta, disable,
                      enable, enabled, gauge, histogram, quantile, reset,
                      snapshot, to_json, to_prometheus)

__all__ = [
    "metrics", "compile", "http", "instrument", "attribution",
    "CompileRecord", "MetricsExporter", "start_http_exporter",
    "stop_http_exporter", "Registry", "DEFAULT_BUCKETS", "counter", "gauge",
    "histogram", "snapshot", "to_prometheus", "to_json", "delta", "quantile",
    "reset", "enable", "disable", "enabled",
]
