"""raft_tpu.obs — the observability surface (metrics, compile attribution,
instrumentation).

The reference operates through NVTX ranges (core/nvtx.hpp:95), spdlog runtime
control, and a bench harness that always writes structured results
(benchmark.hpp:111-200). The TPU rebuild's analogue is this package:

- :mod:`.metrics` — zero-dependency counters/gauges/histograms with labels;
  ``snapshot()`` (nested dict), ``to_prometheus()`` (text exposition format
  for scraping), ``to_json()`` (flat, subtractable — BENCH artifacts).
- :mod:`.instrument` — the ``@instrument`` decorator applied across the
  search/build/prims entry points (brute_force/ivf_flat/ivf_pq/cagra,
  pairwise_distance, select_k, kmeans).
- :mod:`.compile` — jax.monitoring subscription splitting compile vs execute
  and counting persistent-cache hits/misses.
- :mod:`.quality` — ONLINE quality: the live recall canary (reservoir
  sampling at the serve flush path, exact shadow rerank over the live
  corpus, streaming recall@k with a Wilson interval) and dataset-family
  drift detection against pinned tune decisions.
- :mod:`.slo` — availability/latency/quality objectives with multi-window
  error-budget burn rates over an injected-clock ring; the ``/healthz``
  verdict.
- :mod:`.requestlog` — request ids minted at admission, span timings
  through batcher → flush → lease → index search → stream merge, served
  at ``/debug/requests`` with latency-bucket exemplars.
- :mod:`.mem` — the memory ledger: live device/host bytes attributed to
  ``(component, name, shard, epoch)`` with weakref retirement audits
  (leak detection on the registry/compaction/fold free paths), the
  per-kind footprint estimator ``mem.plan()``, and the
  ``Resources.memory_budget_bytes`` admission gate.
- :mod:`.events` — the unified operations event plane: one process-wide
  causally-ordered journal every advisory/transition call site emits
  into (``emit(kind, subject=(component, name, shard, epoch), ...)``),
  with subscriber taps, a durable JSONL sink, per-kind counts and the
  incident flight recorder (SLO ``failing`` → postmortem bundle).
- :mod:`.http` — the opt-in stdlib endpoint routing ``/metrics``,
  ``/healthz``, ``/debug/requests``, ``/debug/mem`` and
  ``/debug/events`` (404 elsewhere).

Trace annotation (the NVTX analogue) lives in :mod:`raft_tpu.core.tracing`;
per-collective counters ride inside :mod:`raft_tpu.comms.comms`; the serving
layer's queue/occupancy/swap metrics ride inside :mod:`raft_tpu.serve`
(``raft_tpu_serve_*`` — docs/serving.md).

``disable()`` turns the whole surface off; the remaining overhead per
instrumented call is a single module-flag check (guarded by the
``obs_overhead`` smoke test in tier-1). See docs/observability.md for the
metric catalogue.
"""

from . import build
from . import compile  # noqa: A004 - submodule named like the builtin
from . import dispatch
from . import events
from . import http
from . import mem
from . import metrics
from . import quality
from . import requestlog
from . import slo
from .compile import CompileRecord, attribution
from .http import MetricsExporter, start_http_exporter, stop_http_exporter
# NOTE: this deliberately rebinds the package attribute `obs.instrument` from
# the submodule to the decorator (the ergonomic call site); reach the helper
# fns via `from raft_tpu.obs.instrument import nrows`, not attribute access.
from .instrument import instrument
from .metrics import (DEFAULT_BUCKETS, RATIO_BUCKETS, Registry, counter,
                      delta, disable, enable, enabled, gauge, histogram,
                      quantile, reset, snapshot, to_json, to_prometheus)
from .events import EventJournal
from .quality import DriftDetector, RecallCanary, exact_oracle, wilson_interval
from .requestlog import RequestLog
from .slo import SLOPolicy, SLOTracker

__all__ = [
    "metrics", "compile", "dispatch", "http", "instrument", "attribution",
    "CompileRecord", "MetricsExporter", "start_http_exporter",
    "stop_http_exporter", "Registry", "DEFAULT_BUCKETS", "RATIO_BUCKETS",
    "counter", "gauge", "histogram", "snapshot", "to_prometheus", "to_json",
    "delta", "quantile", "reset", "enable", "disable", "enabled",
    "quality", "slo", "requestlog", "mem", "RecallCanary", "DriftDetector",
    "exact_oracle", "wilson_interval", "SLOPolicy", "SLOTracker",
    "RequestLog", "events", "EventJournal",
]
