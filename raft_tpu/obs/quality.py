"""Online quality: a live recall canary and dataset-family drift detection.

Everything before this module measured recall OFFLINE — bench runs against
a frozen ground truth. A serving stack whose indexes mutate under load
(delta memtable, tombstones, compaction hot-swaps, pinned tune decisions)
can rot silently: FreshDiskANN (Singh et al., 2021) measures recall
degrading under streaming insert/delete churn unless actively monitored,
and BASELINE round 5's negative result — operating points do NOT transfer
across dataset families — means a pinned tune decision is only valid while
live traffic stays in the family it was measured on. This module closes
both gaps online:

- :class:`RecallCanary` — reservoir-samples a configurable fraction of
  live queries at the serve flush path (host-side, microseconds), then
  shadow-reranks them OFF the hot path with the exact fused kNN over the
  *live* corpus (sealed rows + delta memtable, tombstones applied) at
  warmed power-of-two bucket shapes, and publishes a streaming recall@k
  estimate with a Wilson confidence interval (``raft_tpu_quality_*``).
  The rerank batches ride the same bucket discipline as everything else in
  the serving stack, so a warmed canary adds ZERO cold compiles on or off
  the hot path (asserted via obs compile attribution by
  ``tests/test_obs_quality.py`` and the ``--canary-smoke`` bench row).
- :class:`DriftDetector` — re-runs :mod:`raft_tpu.tune`'s family
  classifier (local-scale CV of nearest-neighbor radii; the measured
  heavytail discriminator) on canary query samples and on compaction-time
  corpus stats, and raises ``raft_tpu_quality_family_drift`` plus a
  ``retune_advised`` structured event when the live distribution leaves
  the pinned decision's ``(kind, dtype, family)`` key. It NEVER applies a
  decision across balance classes itself — the r5 non-transfer collapse
  (0.31 vs 0.82 recall) is exactly why a drift is an *advice to re-sweep*,
  not a pin to borrow.

Wiring: ``SearchService(canary=...)`` taps flushes into
:meth:`RecallCanary.offer`; ``stream.Compactor(drift=...)`` feeds
compaction-time corpus stats; ``slo=`` forwards per-query rerank outcomes
into the quality objective of an :class:`raft_tpu.obs.slo.SLOTracker`.
See docs/observability.md for the metric catalogue and docs/tuning.md for
the drift → retune loop.
"""

from __future__ import annotations

import functools
import itertools
import math
import random
import threading
import time
from typing import Callable

from ..core.errors import expects
from . import events as obs_events
from . import metrics

__all__ = ["RecallCanary", "DriftDetector", "exact_oracle", "wilson_interval"]

# per-DriftDetector journal tags (see DriftDetector.events)
_detector_ids = itertools.count()

# the canary's rerank-batch ladder (power-of-two query buckets, mirroring
# serve's): every rerank dispatch is one of these shapes, so warm() bounds
# the canary's program set exactly like the batcher bounds the hot path's
DEFAULT_CANARY_BUCKETS = (1, 2, 4, 8, 16, 32, 64)


# -- metrics (catalogue: docs/observability.md) ------------------------------

@functools.lru_cache(maxsize=None)
def _g_recall():
    return metrics.gauge(
        "raft_tpu_quality_recall",
        "streaming canary recall@k point estimate (served ids vs the exact "
        "fused kNN over the live corpus)")


@functools.lru_cache(maxsize=None)
def _g_wilson_low():
    return metrics.gauge(
        "raft_tpu_quality_recall_wilson_low",
        "lower bound of the 95% Wilson interval on the canary recall "
        "estimate")


@functools.lru_cache(maxsize=None)
def _g_wilson_high():
    return metrics.gauge(
        "raft_tpu_quality_recall_wilson_high",
        "upper bound of the 95% Wilson interval on the canary recall "
        "estimate")


@functools.lru_cache(maxsize=None)
def _c_sampled():
    return metrics.counter(
        "raft_tpu_quality_canary_sampled_total",
        "live queries reservoir-sampled into the canary at the flush path")


@functools.lru_cache(maxsize=None)
def _c_reranked():
    return metrics.counter(
        "raft_tpu_quality_canary_reranked_total",
        "sampled queries shadow-reranked against the exact live-corpus kNN")


@functools.lru_cache(maxsize=None)
def _c_dropped():
    return metrics.counter(
        "raft_tpu_quality_canary_dropped_total",
        "sampled queries displaced from a full canary reservoir before "
        "rerank (raise reservoir= or drain more often)")


@functools.lru_cache(maxsize=None)
def _h_canary_recall():
    return metrics.histogram(
        "raft_tpu_quality_canary_recall",
        "per-query canary recall@k observations (0-1 ratio buckets; the "
        "per-bucket series ride BENCH artifacts via obs.to_json)",
        buckets=metrics.RATIO_BUCKETS)


@functools.lru_cache(maxsize=None)
def _g_drift():
    return metrics.gauge(
        "raft_tpu_quality_family_drift",
        "1 while the measured live family differs from the pinned tune "
        "decision's family, else 0")


@functools.lru_cache(maxsize=None)
def _c_retune():
    return metrics.counter(
        "raft_tpu_quality_retune_advised_total",
        "drift transitions that emitted a retune_advised event (advice "
        "only — decisions never auto-apply across balance classes)")


# -- statistics --------------------------------------------------------------

def wilson_interval(successes: float, trials: float,
                    z: float = 1.96) -> tuple[float, float]:
    """Wilson score interval for a binomial proportion (default z=1.96,
    the two-sided 95% level). Unlike the normal approximation it stays
    inside [0, 1] and behaves at p near 1 — where recall lives — and at
    small n. ``trials == 0`` returns the vacuous (0, 1)."""
    n = float(trials)
    if n <= 0:
        return (0.0, 1.0)
    p = float(successes) / n
    z2 = z * z
    denom = 1.0 + z2 / n
    center = (p + z2 / (2.0 * n)) / denom
    half = (z / denom) * math.sqrt(p * (1.0 - p) / n + z2 / (4.0 * n * n))
    return (max(0.0, center - half), min(1.0, center + half))


# -- the shadow oracle -------------------------------------------------------

def exact_oracle(index, dataset=None) -> Callable:
    """Resolve an index to its exact shadow-rerank oracle: a
    ``fn(queries, k) -> (distances, ids)`` over the LIVE corpus.

    A :class:`raft_tpu.stream.MutableIndex` (duck-typed — obs never imports
    stream) resolves to :meth:`~raft_tpu.stream.MutableIndex.exact_search`:
    the exact fused kNN over the retained sealed rows (tombstones applied
    via the same keep mask the serving path uses) merged with the delta
    scan, so the oracle tracks every upsert/delete/compaction the served
    index sees. A plain sealed index needs its raw rows via ``dataset=``
    (PQ codes cannot reconstruct them) and reranks with
    ``brute_force.knn`` in the index's own metric."""
    if hasattr(index, "upsert") and hasattr(index, "exact_search"):
        fn = index.exact_search
        fn_dim, fn_dtype = index.dim, index.query_dtype
    else:
        expects(dataset is not None,
                "exact_oracle needs the raw rows for a sealed %s index — "
                "pass dataset= (or wrap in stream.MutableIndex with a "
                "retained store)", type(index).__name__)
        import jax.numpy as jnp

        from ..distance.types import resolve_metric
        from ..neighbors import brute_force

        ds = jnp.asarray(dataset)
        metric = resolve_metric(getattr(index, "metric", "sqeuclidean"))
        # parameterized metrics (lp) carry their exponent on the index —
        # an L2 "oracle" for an L3 index would report a spurious deficit
        metric_arg = float(getattr(index, "metric_arg", 2.0))
        dk = str(ds.dtype)
        fn_dim = int(ds.shape[1])
        fn_dtype = dk if dk in ("int8", "uint8") else "float32"

        def fn(queries, k):
            return brute_force.knn(ds, queries, int(k), metric, metric_arg)

    fn = _wrap_oracle(fn, fn_dim, fn_dtype)
    return fn


def _wrap_oracle(fn, dim: int, query_dtype: str):
    def oracle(queries, k):
        return fn(queries, int(k))

    oracle.dim = int(dim)
    oracle.query_dtype = query_dtype
    return oracle


# -- the canary --------------------------------------------------------------

class RecallCanary:
    """Live recall canary (see module doc).

    ``oracle`` is the exact shadow searcher (:func:`exact_oracle`);
    ``sample_rate`` is the fraction of served queries sampled at the flush
    path (0 disables sampling entirely — one float compare per flush);
    ``reservoir`` bounds pending host memory between drains (overflow
    displaces uniformly — algorithm R — and counts as dropped). ``buckets``
    is the rerank batch ladder; :meth:`warm` compiles the oracle at every
    bucket so a drain never cold-compiles. ``k`` must match the serving
    width whose results are offered. ``slo=`` forwards per-query outcomes
    to an :class:`~raft_tpu.obs.slo.SLOTracker`'s quality objective;
    ``drift=`` forwards the sampled query rows to a
    :class:`DriftDetector`. Sampling (RNG) is seeded — deterministic for
    tests — and all entry points are thread-safe.
    """

    def __init__(self, oracle: Callable, *, k: int = 10,
                 sample_rate: float = 0.01, reservoir: int = 256,
                 buckets=DEFAULT_CANARY_BUCKETS, name: str = "default",
                 seed: int = 0, slo=None, drift=None,
                 clock: Callable[[], float] = time.monotonic):
        expects(callable(oracle), "oracle must be callable (exact_oracle())")
        expects(0.0 <= float(sample_rate) <= 1.0,
                "sample_rate must be in [0, 1], got %r", sample_rate)
        expects(int(reservoir) >= 1, "reservoir must be >= 1")
        self._oracle = oracle
        self.k = int(k)
        self.name = name
        self.reservoir = int(reservoir)
        self._buckets = tuple(sorted(set(int(b) for b in buckets)))
        expects(bool(self._buckets) and self._buckets[0] >= 1,
                "buckets must be positive batch sizes")
        self._rate = float(sample_rate)
        self._rng = random.Random(seed)
        self._seed = int(seed)
        self._clock = clock
        self._slo = slo
        self._drift = drift
        self._lock = threading.Lock()
        self._pending: list = []
        self._cands = 0       # candidates offered to the reservoir this window
        self._seen = 0        # queries observed at the flush path (lifetime)
        self._successes = 0   # matched neighbor slots (lifetime)
        self._trials = 0      # scored neighbor slots (lifetime)
        self._reranked = 0
        self._worker: threading.Thread | None = None
        self._stop = threading.Event()

    # -- hot-path tap --------------------------------------------------------
    def set_rate(self, sample_rate: float) -> None:
        expects(0.0 <= float(sample_rate) <= 1.0,
                "sample_rate must be in [0, 1], got %r", sample_rate)
        with self._lock:
            self._rate = float(sample_rate)

    def offer(self, queries, served_ids) -> int:
        """Reservoir-sample served (query, ids) rows — called by the serve
        flush path with the VALID rows of one flush. Host-side and bounded:
        one RNG draw per row, one row copy per kept sample. Returns how
        many rows were sampled. ``sample_rate == 0`` is a single compare."""
        if self._rate <= 0.0:
            return 0
        import numpy as np

        qs = np.asarray(queries)
        ids = np.asarray(served_ids)
        kept = dropped = 0
        with self._lock:
            for i in range(qs.shape[0]):
                self._seen += 1
                if self._rng.random() >= self._rate:
                    continue
                kept += 1
                self._cands += 1
                item = (qs[i].copy(), ids[i].copy())
                if len(self._pending) < self.reservoir:
                    self._pending.append(item)
                else:
                    # algorithm R over this drain window's candidates: the
                    # reservoir stays a uniform sample of them
                    j = self._rng.randrange(self._cands)
                    if j < self.reservoir:
                        self._pending[j] = item
                    dropped += 1
        if metrics._enabled and kept:
            _c_sampled().inc(kept, name=self.name)
            if dropped:
                _c_dropped().inc(dropped, name=self.name)
        return kept

    def pending(self) -> int:
        with self._lock:
            return len(self._pending)

    # -- the shadow rerank (off the hot path) --------------------------------
    def drain(self) -> int:
        """Shadow-rerank everything sampled since the last drain: batch the
        reservoir into power-of-two buckets (partial tails padded by
        repeating the first row — padding results are discarded), run the
        exact oracle, score served-vs-exact overlap per query, and publish
        the streaming estimate + Wilson interval. Returns queries reranked.
        Runs on the caller's thread — a background drainer (:meth:`start`)
        or a deterministic test loop."""
        import numpy as np

        with self._lock:
            pending, self._pending = self._pending, []
            self._cands = 0
        if not pending:
            return 0
        max_b = self._buckets[-1]
        i = 0
        while i < len(pending):
            chunk = pending[i:i + max_b]
            i += len(chunk)
            b = next(bb for bb in self._buckets if bb >= len(chunk))
            q = np.stack([c[0] for c in chunk])
            if len(chunk) < b:
                pad = np.broadcast_to(q[:1], (b - len(chunk),) + q.shape[1:])
                q = np.concatenate([q, pad])
            _, oids = self._oracle(q, self.k)
            oids = np.asarray(oids)[:len(chunk)]
            matched = scored = 0
            for (_, sids), orow in zip(chunk, oids):
                valid = orow[orow >= 0]
                if valid.size == 0:
                    continue  # empty live corpus: nothing to score
                m = len(set(np.asarray(sids).tolist())
                        & set(valid.tolist()))
                matched += m
                scored += int(valid.size)
                if metrics._enabled:
                    _h_canary_recall().observe(m / valid.size, name=self.name)
            with self._lock:
                self._successes += matched
                self._trials += scored
                self._reranked += len(chunk)
            if metrics._enabled:
                _c_reranked().inc(len(chunk), name=self.name)
            if self._slo is not None:
                self._slo.record_quality(matched, scored)
            if self._drift is not None:
                self._drift.offer_rows(np.stack([c[0] for c in chunk]))
        self._publish()
        return len(pending)

    def _publish(self) -> None:
        est = self.estimate()
        if metrics._enabled:
            _g_recall().set(est["recall"], name=self.name)
            _g_wilson_low().set(est["wilson_low"], name=self.name)
            _g_wilson_high().set(est["wilson_high"], name=self.name)

    # -- estimate ------------------------------------------------------------
    def estimate(self) -> dict:
        """The streaming recall estimate: point value, 95% Wilson bounds,
        and the sample counts that produced them."""
        with self._lock:
            s, t = self._successes, self._trials
            reranked, seen = self._reranked, self._seen
        low, high = wilson_interval(s, t)
        return {"recall": (s / t) if t else float("nan"),
                "wilson_low": low, "wilson_high": high,
                "matched_slots": int(s), "scored_slots": int(t),
                "reranked": int(reranked), "seen": int(seen)}

    def in_interval(self, recall: float) -> bool:
        """Whether an offline recall measurement falls inside the canary's
        current Wilson interval — the acceptance check that the live
        estimate tracks the fresh-oracle truth."""
        est = self.estimate()
        return est["wilson_low"] <= float(recall) <= est["wilson_high"]

    # -- warmup --------------------------------------------------------------
    def warm(self, sample=None) -> dict:
        """Compile the oracle's program set at every rerank bucket (the
        canary analogue of ``_warmup.warm_buckets``): after this, a drain
        over the SAME corpus epoch dispatches only warmed programs — zero
        cold compiles on or off the hot path. A MutableIndex oracle's
        sealed-store shape changes per compaction epoch, so epoch swaps
        re-warm (off the hot path; the churn bench covers epochs by
        rehearsal). Returns per-bucket compile attribution."""
        import jax

        from .._warmup import _random_queries
        from . import compile as obs_compile

        dim = int(getattr(self._oracle, "dim"))
        dtype = getattr(self._oracle, "query_dtype", "float32")
        out = {}
        key = jax.random.key(self._seed)
        for b in self._buckets:
            key, kq = jax.random.split(key)
            q = _random_queries(kq, b, dim, dtype, sample=sample)
            t0 = time.perf_counter()
            with obs_compile.attribution() as rec:
                jax.block_until_ready(self._oracle(q, self.k))
            out[b] = {"wall_s": round(time.perf_counter() - t0, 3),
                      **rec.summary()}
        return out

    # -- background drainer --------------------------------------------------
    def start(self, poll_interval_s: float = 0.05) -> "RecallCanary":
        """Run :meth:`drain` on a daemon poll loop (library mode; tests and
        the churn bench drive :meth:`drain` directly). Idempotent."""
        if self._worker is not None and not self._worker.is_alive():
            self._worker = None
        self._stop.clear()
        if self._worker is None:
            self._worker = threading.Thread(
                target=self._run, args=(float(poll_interval_s),),
                name=f"raft-canary-{self.name}", daemon=True)
            self._worker.start()
        return self

    def _run(self, poll_s: float) -> None:
        from ..core.logger import logger

        while not self._stop.wait(poll_s):
            try:
                self.drain()
            except Exception as e:  # never kill the drainer; advise loudly
                logger.warning("canary %r drain failed (will retry): %s",
                               self.name, e)

    def stop(self, timeout_s: float = 10.0) -> None:
        """Stop the background drainer and flush what is pending."""
        self._stop.set()
        if self._worker is not None:
            self._worker.join(timeout_s)
            self._worker = None
        self.drain()


# -- drift detection ---------------------------------------------------------

class DriftDetector:
    """Detect the live distribution leaving a pinned decision's family.

    ``pinned_family`` is the tune decision's structured family key
    (``"100k-d128-bal"`` — :func:`raft_tpu.tune.shape_family`); construct
    from a pinned :class:`~raft_tpu.tune.Decision` via
    :meth:`from_decision`. Two feeds re-run the tune classifier:

    - **canary query samples** (:meth:`offer_rows` + :meth:`check`): the
      local-scale CV of nearest-neighbor radii over the buffered rows —
      the measured heavytail discriminator (isotropic ~0.4 vs lognormal
      scales ~1.5, threshold 0.75) — reclassifies the balance class.
      Queries cannot see the corpus' row count, so this feed holds the
      pinned size labels and moves only the balance class.
    - **compaction-time corpus stats** (:meth:`check` with ``rows=`` and
      ``n_rows=``/``dim=``, fed by ``stream.Compactor(drift=...)``): a
      corpus subsample plus the live row count, so size-decade drift is
      visible too.

    On a drift TRANSITION (family leaves the pin; re-entering clears it)
    the detector emits one ``retune_advised`` structured event (counter +
    WARNING log + :attr:`events`) and holds ``raft_tpu_quality_family_drift``
    at 1. It never applies another family's decision: cross-balance-class
    transfer is the measured r5 recall collapse, so the ONLY safe action
    is a fresh sweep (docs/tuning.md, "Drift → retune").
    """

    def __init__(self, pinned_family: str, *, name: str = "default",
                 min_rows: int = 256, sample_cap: int = 2048,
                 max_events: int = 64,
                 clock: Callable[[], float] = time.monotonic):
        parts = str(pinned_family).split("-")
        expects(len(parts) == 3,
                "pinned_family must be a structured 'rows-dim-balance' key "
                "(tune.shape_family), got %r", pinned_family)
        self.pinned_family = str(pinned_family)
        self._n_lab, self._d_lab, self._balance = parts
        expects(self._balance in ("bal", "skew", "clump"),
                "unknown balance class %r in pinned family", self._balance)
        self.name = name
        self.min_rows = int(min_rows)
        self.sample_cap = int(sample_cap)
        self._clock = clock
        self._lock = threading.Lock()
        self._buf: list = []
        self._buf_rows = 0
        # drift state is PER FEED: the query-sample and compaction-stat
        # feeds observe different things (traffic vs corpus), and the
        # early-warning case is exactly query drift while the corpus is
        # still clean — a clean corpus check must not clear (and re-arm)
        # a standing query-side drift
        self._drifted: dict[str, bool] = {}
        # per-instance journal tag: the `events` view below filters the
        # process-wide journal by it, so two detectors sharing a name
        # (or test cases reusing one) never read each other's advisories
        self._jtag = f"{name}/{next(_detector_ids)}"
        self._max_events = int(max_events)
        self.last_report: dict | None = None

    @classmethod
    def from_decision(cls, decision, **kwargs) -> "DriftDetector":
        """Arm a detector for one pinned :class:`raft_tpu.tune.Decision`."""
        return cls(decision.family, **kwargs)

    def offer_rows(self, rows) -> None:
        """Buffer live-sample rows (canary queries) for the next
        :meth:`check`; keeps the LATEST ``sample_cap`` rows."""
        import numpy as np

        arr = np.asarray(rows)
        if arr.ndim != 2 or arr.shape[0] == 0:
            return
        with self._lock:
            self._buf.append(arr)
            self._buf_rows += arr.shape[0]
            while self._buf and self._buf_rows - self._buf[0].shape[0] \
                    >= self.sample_cap:
                self._buf_rows -= self._buf.pop(0).shape[0]

    def buffered(self) -> int:
        with self._lock:
            return self._buf_rows

    def check(self, rows=None, *, n_rows: int | None = None,
              dim: int | None = None, source: str = "queries") -> dict | None:
        """Re-run the tune family classifier and compare against the pin.

        With no ``rows``, classifies the buffered canary samples (returns
        None below ``min_rows`` — too few rows to trust the CV). With
        ``rows`` (plus ``n_rows``/``dim``), classifies that corpus
        subsample directly — the compaction-time feed. Returns the report
        dict (also kept as :attr:`last_report`)."""
        import numpy as np

        # lazy: obs must stay importable without dragging the tune package
        # in at obs-import time (tune itself imports obs.metrics)
        from ..tune import decisions

        if rows is None:
            with self._lock:
                if self._buf_rows < self.min_rows:
                    return None
                rows = np.concatenate(self._buf)[-self.sample_cap:]
        else:
            rows = np.asarray(rows)
        cv = decisions.local_scale_cv(rows)
        balance = ("skew" if cv > decisions.SCALE_CV_THRESHOLD else "bal")
        if n_rows is not None and dim is not None:
            observed = decisions.shape_family(int(n_rows), int(dim), balance)
        else:
            # query samples carry no corpus size: hold the pinned size
            # labels, move only the measured balance class
            observed = f"{self._n_lab}-{self._d_lab}-{balance}"
        drifted = observed != self.pinned_family
        # the measured evidence rides the report (and the retune_advised
        # event) INLINE: the classifier value AND its threshold, plus both
        # balance classes — a controller (or a postmortem) replays the
        # decision from the journal alone, re-probing nothing
        report = {"drifted": drifted, "pinned": self.pinned_family,
                  "observed": observed, "scale_cv": round(float(cv), 4),
                  "scale_cv_threshold": decisions.SCALE_CV_THRESHOLD,
                  "pinned_balance": self._balance,
                  "observed_balance": balance,
                  "rows": int(rows.shape[0]), "source": source,
                  "at": self._clock()}
        was = self._drifted.get(source, False)
        self._drifted[source] = drifted
        if metrics._enabled:
            # the gauge reports drift on ANY feed: a clean corpus check
            # must not drop it while query-side drift stands
            _g_drift().set(1.0 if any(self._drifted.values()) else 0.0,
                           name=self.name)
        if drifted and not was:
            self._emit_retune_advised(report)
        self.last_report = report
        return report

    def drifted(self) -> bool:
        """Whether any feed currently observes the live family off the
        pin (what the ``raft_tpu_quality_family_drift`` gauge reports)."""
        return any(self._drifted.values())

    @property
    def events(self) -> list:
        """The retune-advised history, as a thin view over the process
        journal (:mod:`raft_tpu.obs.events`) — legacy dict shape
        preserved (``{"event": "retune_advised", "name", "auto_apply",
        **report}``), newest last, capped at ``max_events``."""
        out = []
        for ev in obs_events.query(kind="retune_advised", name=self.name):
            e = ev["evidence"]
            if e.get("tag") != self._jtag:
                continue
            out.append({"event": "retune_advised", "name": self.name,
                        **{k: v for k, v in e.items() if k != "tag"}})
        return out[-self._max_events:]

    def _emit_retune_advised(self, report: dict) -> None:
        # one emit = journal entry + counter + WARNING, atomically (the
        # three can no longer disagree on re-arm paths); advice only:
        # applying another balance class's pin is the measured r5 recall
        # collapse — run a fresh sweep instead (auto_apply stays False)
        obs_events.emit(
            "retune_advised",
            subject=("quality", self.name),
            evidence={"auto_apply": False, "tag": self._jtag, **report},
            counter=_c_retune, counter_labels={"name": self.name},
            message=(
                "family drift on %r: live distribution measures %s but the "
                "pinned tune decision is keyed %s (scale_cv=%.3f, "
                "source=%s) — retune advised; decisions are never "
                "auto-applied across balance classes (BASELINE r5 "
                "non-transfer)"),
            log_args=(self.name, report["observed"], report["pinned"],
                      report["scale_cv"], report["source"]))
