"""Zero-dependency metrics registry: counters, gauges, histograms with labels.

The reference exposes its runtime signals through spdlog levels and the
benchmark harness's structured result files (benchmark.hpp:111-200); a served
system needs the same numbers scrapeable from the process. This module is the
raft_tpu metrics surface: stdlib-only, thread-safe, Prometheus-text
exportable, and JSON-flattenable for BENCH artifacts.

Semantics worth knowing:

- **Disabled mode** (:func:`disable`) is a single module-attribute check on
  every hot-path touch point — instrumented entry points fall straight
  through to the wrapped function and metric mutators return immediately.
- **Labels** are free-form str->str (ints/floats are stringified). Series are
  keyed by the sorted label set, so ``inc(op="a", k="5")`` and
  ``inc(k="5", op="a")`` hit the same series.
- **Histograms** use fixed cumulative buckets (Prometheus convention); the
  default bucket ladder spans 100 us .. 60 s, sized for call latencies.
  Metrics whose values live on [0, 1] — recall, ratios, fractions — pass
  ``buckets=RATIO_BUCKETS`` instead (a latency ladder would dump every
  observation into the first two buckets and :func:`quantile` would report
  garbage). Re-registering a histogram under a different bucket ladder
  raises: the first registration would otherwise silently win and the
  later call site would read quantiles against buckets it never asked for.
  :func:`quantile` interpolates within the owning bucket.
"""

from __future__ import annotations

import json
import math
import threading
from typing import Iterable

__all__ = [
    "Registry", "counter", "gauge", "histogram", "snapshot", "to_prometheus",
    "to_json", "delta", "quantile", "reset", "enable", "disable", "enabled",
    "DEFAULT_BUCKETS", "RATIO_BUCKETS",
]

# Latency ladder: 100 us .. 60 s (jit dispatch to cold 1M build).
DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

# [0, 1] ladder for recall/ratio/fraction metrics: dense near 1.0, where
# recall lives (the gap between 0.95 and 0.99 is the whole quality story).
RATIO_BUCKETS = (
    0.05, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 0.95, 0.99, 1.0,
)

_enabled = True


def enable() -> None:
    """Turn metric recording on (the default)."""
    global _enabled
    _enabled = True


def disable() -> None:
    """Turn all metric recording off. Instrumented entry points reduce to a
    single module-flag check; mutators become no-ops."""
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Metric:
    """One named metric holding many labeled series.

    Series state: float for counter/gauge; ``[count, sum, bucket_counts]``
    for histogram (bucket_counts is per-bucket, NON-cumulative internally;
    export cumulates, as the Prometheus text format requires).
    """

    def __init__(self, name: str, kind: str, help: str, unit: str,
                 buckets: tuple, lock: threading.RLock):
        self.name = name
        self.kind = kind
        self.help = help
        self.unit = unit
        self.buckets = buckets
        self._lock = lock
        self._series: dict[tuple, object] = {}

    # -- mutators -----------------------------------------------------------
    def inc(self, value: float = 1.0, **labels) -> None:
        if not _enabled:
            return
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + value

    def set(self, value: float, **labels) -> None:
        if not _enabled:
            return
        key = _label_key(labels)
        with self._lock:
            self._series[key] = float(value)

    def observe(self, value: float, **labels) -> None:
        if not _enabled:
            return
        key = _label_key(labels)
        with self._lock:
            st = self._series.get(key)
            if st is None:
                st = [0, 0.0, [0] * (len(self.buckets) + 1)]
                self._series[key] = st
            st[0] += 1
            st[1] += value
            # first bucket whose upper bound holds the value; last slot = +Inf
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    st[2][i] += 1
                    break
            else:
                st[2][len(self.buckets)] += 1

    # -- accessors ----------------------------------------------------------
    def series(self) -> dict[tuple, object]:
        with self._lock:
            return {k: (list(v) if isinstance(v, list) else v)
                    for k, v in self._series.items()}

    def quantile(self, q: float, /, **labels) -> float:
        """Histogram quantile estimate by linear interpolation inside the
        owning bucket (Inf bucket reports the last finite bound). ``q`` is
        positional-only so a series labeled ``q=...`` stays addressable."""
        assert self.kind == "histogram", "quantile() is histogram-only"
        key = _label_key(labels)
        with self._lock:
            st = self._series.get(key)
            if st is None or st[0] == 0:
                return math.nan
            count, _, per_bucket = st[0], st[1], list(st[2])
        rank = q * count
        cum = 0.0
        lo = 0.0
        for i, n in enumerate(per_bucket):
            ub = self.buckets[i] if i < len(self.buckets) else math.inf
            if cum + n >= rank and n > 0:
                if math.isinf(ub):
                    return self.buckets[-1]
                frac = (rank - cum) / n
                return lo + (ub - lo) * min(max(frac, 0.0), 1.0)
            cum += n
            lo = ub
        return self.buckets[-1]


class Registry:
    """Thread-safe named-metric registry (get-or-create semantics)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics: dict[str, Metric] = {}

    def _get(self, name: str, kind: str, help: str, unit: str,
             buckets: tuple) -> Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Metric(name, kind, help, unit, buckets, self._lock)
                self._metrics[name] = m
            elif m.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {kind}")
            elif kind == "histogram" and m.buckets != buckets:
                # first-registration-wins would silently hand the later call
                # site quantiles over a bucket ladder it never asked for
                # (e.g. a recall metric read against the latency ladder)
                raise ValueError(
                    f"histogram {name!r} already registered with buckets "
                    f"{m.buckets}, requested {buckets}")
            return m

    def counter(self, name: str, help: str = "", unit: str = "") -> Metric:
        return self._get(name, "counter", help, unit, ())

    def gauge(self, name: str, help: str = "", unit: str = "") -> Metric:
        return self._get(name, "gauge", help, unit, ())

    def histogram(self, name: str, help: str = "", unit: str = "",
                  buckets: Iterable[float] = DEFAULT_BUCKETS) -> Metric:
        return self._get(name, "histogram", help, unit, tuple(buckets))

    def reset(self) -> None:
        """Clear all series (metric definitions survive)."""
        with self._lock:
            for m in self._metrics.values():
                m._series.clear()

    # -- export -------------------------------------------------------------
    def snapshot(self) -> dict:
        """Nested dict of everything: {name: {type, help, unit, series: [
        {labels, value} | {labels, count, sum, buckets}]}} — buckets are
        cumulative keyed by upper bound (str), Prometheus-style."""
        out = {}
        with self._lock:
            for name in sorted(self._metrics):
                m = self._metrics[name]
                series = []
                for key in sorted(m._series):
                    labels = dict(key)
                    st = m._series[key]
                    if m.kind == "histogram":
                        cum, bk = 0, {}
                        for i, n in enumerate(st[2]):
                            cum += n
                            ub = (m.buckets[i] if i < len(m.buckets)
                                  else math.inf)
                            bk[_fmt_le(ub)] = cum
                        series.append({"labels": labels, "count": st[0],
                                       "sum": st[1], "buckets": bk})
                    else:
                        series.append({"labels": labels, "value": st})
                out[name] = {"type": m.kind, "help": m.help, "unit": m.unit,
                             "series": series}
        return out

    def to_prometheus(self) -> str:
        """Prometheus text exposition format (one HELP/TYPE block per metric,
        histogram expands to _bucket/_sum/_count samples)."""
        lines = []
        for name, meta in self.snapshot().items():
            if meta["help"]:
                lines.append(f"# HELP {name} {meta['help']}")
            lines.append(f"# TYPE {name} {meta['type']}")
            for s in meta["series"]:
                if meta["type"] == "histogram":
                    for le, cum in s["buckets"].items():
                        lines.append(_sample(f"{name}_bucket",
                                             {**s["labels"], "le": le}, cum))
                    lines.append(_sample(f"{name}_sum", s["labels"], s["sum"]))
                    lines.append(_sample(f"{name}_count", s["labels"],
                                         s["count"]))
                else:
                    lines.append(_sample(name, s["labels"], s["value"]))
        return "\n".join(lines) + ("\n" if lines else "")

    def to_json(self) -> dict:
        """Flat {'name{l1="v1",...}': number} view — subtractable, and small
        enough to ride inside a BENCH row. Histograms flatten to _sum/_count
        plus one ``_bucket`` key per cumulative bucket with the series'
        OWN labels preserved alongside ``le`` — so a BENCH artifact carries
        the full per-bucket distribution (the canary's per-bucket recall
        histogram) without collapsing label sets, and :func:`delta`
        subtracts bucket counts like any other monotone series."""
        out = {}
        for name, meta in self.snapshot().items():
            for s in meta["series"]:
                lbl = _label_str(s["labels"])
                if meta["type"] == "histogram":
                    out[f"{name}_sum{lbl}"] = s["sum"]
                    out[f"{name}_count{lbl}"] = s["count"]
                    for le, cum in s["buckets"].items():
                        blbl = _label_str({**s["labels"], "le": le})
                        out[f"{name}_bucket{blbl}"] = cum
                else:
                    out[f"{name}{lbl}"] = s["value"]
        return out


def _fmt_le(ub: float) -> str:
    if math.isinf(ub):
        return "+Inf"
    return repr(ub)


def _escape(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


def _label_str(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(str(v))}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _sample(name: str, labels: dict, value) -> str:
    # NaN/±Inf are legal sample values in the exposition format; int(value)
    # on them raises, so only finite integral floats collapse to ints
    if (isinstance(value, float) and math.isfinite(value)
            and value == int(value) and abs(value) < 1e15):
        value = int(value)
    return f"{name}{_label_str(labels)} {value}"


def delta(before: dict, after: dict) -> dict:
    """Difference of two :func:`to_json` snapshots (new/changed numeric keys
    only) — the per-row attribution bench.py attaches to BENCH artifacts."""
    out = {}
    for k, v in after.items():
        d = v - before.get(k, 0)
        if d:
            out[k] = d
    return out


# -- default registry + module-level veneer ---------------------------------

_default = Registry()


def default_registry() -> Registry:
    return _default


def counter(name: str, help: str = "", unit: str = "") -> Metric:
    return _default.counter(name, help, unit)


def gauge(name: str, help: str = "", unit: str = "") -> Metric:
    return _default.gauge(name, help, unit)


def histogram(name: str, help: str = "", unit: str = "",
              buckets: Iterable[float] = DEFAULT_BUCKETS) -> Metric:
    return _default.histogram(name, help, unit, buckets)


def snapshot() -> dict:
    return _default.snapshot()


def to_prometheus() -> str:
    return _default.to_prometheus()


def to_json() -> dict:
    return _default.to_json()


def quantile(name: str, q: float, /, **labels) -> float:
    # positional-only: the serve/stream/quality series all carry a `name`
    # label, which must not collide with the metric-name parameter
    return _default._metrics[name].quantile(q, **labels)


def reset() -> None:
    _default.reset()


def dumps() -> str:
    """snapshot() as a JSON string (debug convenience)."""
    return json.dumps(snapshot(), default=float)
