"""Opt-in stdlib HTTP endpoint: metrics scrape, health verdict, request log.

``start_http_exporter(port)`` serves three explicitly routed paths from a
daemon-threaded stdlib ``http.server``:

- ``/metrics`` — the Prometheus text exposition of the registry;
- ``/healthz`` — the SLO verdict (ready/degraded/failing as JSON; 503 on
  failing so load balancers eject the replica) when an
  :class:`raft_tpu.obs.slo.SLOTracker` is attached, else a bare
  ``{"status": "ready"}``; with ``replicas=`` attached (a
  :class:`~raft_tpu.stream.ReplicatedShard` /
  :class:`~raft_tpu.stream.ShardedMutableIndex`), per-replica breaker
  health folds into the verdict — fenced twins degrade, a group at zero
  pickable twins fails;
- ``/debug/requests`` — the request-trace ring
  (:class:`raft_tpu.obs.requestlog.RequestLog`) when one is attached;
- ``/debug/mem`` — the memory ledger (:mod:`raft_tpu.obs.mem`): totals +
  peaks, per-component aggregates, top allocations by
  ``(component, name, shard, epoch)``, retirement-audit status and
  per-device HBM stats where the backend reports them. Always routed —
  the ledger is a process singleton, nothing to attach.
- ``/debug/events`` — the operations event journal
  (:mod:`raft_tpu.obs.events`): the causally-ordered ring of advisory /
  transition events, filterable by query string (``kind=``,
  ``severity=``, ``component=``, ``name=``, ``since_seq=``, ``limit=``).
  ``since_seq`` is exclusive — poll with the last seen ``seq`` to page
  the tail without gaps or repeats. Always routed (process singleton).
- ``/debug/control`` — the closed-loop controller
  (:class:`raft_tpu.control.Controller`) when one is attached via
  ``controller=``: its :meth:`~raft_tpu.control.Controller.status`
  (cooldowns, in-flight actuation, last action + outcome) plus the most
  recent ``control/*`` journal events.

Every other path is a 404 — a scrape-config typo fails loudly at
deploy time instead of silently scraping metrics from ``/metrcs`` forever
(earlier revisions served the exposition on every GET path; the lint
value of the 404 outweighs the curl convenience). Nothing starts unless
the process asks: no port is opened at import, and the exporter holds no
lock while rendering beyond the registry's own snapshot lock.

The server plumbing itself (routing table, 404 contract, ephemeral-port
bind, clean shutdown) is the shared :class:`raft_tpu.net._httpd.Httpd` —
the same stack that serves the net front door, one server pattern, not
two.

    from raft_tpu import obs

    exp = obs.start_http_exporter(9100, slo=tracker, request_log=rlog)
    ...        # scrape http://host:exp.port/metrics; probe /healthz
    exp.stop()  # clean shutdown (also a context manager; atexit not
                # required — the thread is a daemon)
"""

from __future__ import annotations

import threading

from ..net._httpd import Httpd, Response, json_response
from . import metrics

__all__ = ["MetricsExporter", "start_http_exporter", "stop_http_exporter"]

# Prometheus text exposition content type (version 0.0.4 is the text format)
_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_lock = threading.Lock()
_active: "MetricsExporter | None" = None


def _fold_replica_health(code: int, body: dict, h: dict) -> tuple[int, dict]:
    """Merge a replica-health payload (:meth:`ReplicatedShard.health` or
    :meth:`ShardedMutableIndex.health`) into the ``/healthz`` verdict: a
    group with ZERO pickable twins fails queries — that is an outage
    (``failing``/503, load balancers eject the process); fenced-but-
    surviving twins degrade a ``ready`` verdict (capacity is down, data
    is not)."""
    groups = h["shards"] if "shards" in h else [h]
    body["replicas"] = h
    if h.get("reshard") is not None:
        # a live topology migration folds into the verdict payload
        # (informational — the old topology keeps serving until the flip,
        # so a migration is not degradation)
        body["reshard"] = h["reshard"]
    healthy_min = min((g["healthy"] for g in groups), default=1)
    fenced = sum(1 for g in groups
                 for r in g.get("replicas", []) if r["fenced"])
    if healthy_min == 0:
        return 503, dict(body, status="failing")
    if fenced and body.get("status") == "ready":
        body["status"] = "degraded"
    return code, body


class MetricsExporter:
    """One running exporter: a routed :class:`~raft_tpu.net._httpd.Httpd`
    on a daemon thread. ``slo``/``request_log`` are optional sources for
    ``/healthz`` and ``/debug/requests`` (see module doc)."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 registry: metrics.Registry | None = None,
                 slo=None, request_log=None, replicas=None,
                 controller=None):
        self._registry = registry or metrics.default_registry()
        self.slo = slo
        self.request_log = request_log
        self.replicas = replicas
        self.controller = controller
        # registration order is the 404 listing order
        self._server = Httpd({
            ("GET", "/metrics"): self._metrics,
            ("GET", "/healthz"): self._healthz,
            ("GET", "/debug/requests"): self._debug_requests,
            ("GET", "/debug/mem"): self._debug_mem,
            ("GET", "/debug/events"): self._debug_events,
            ("GET", "/debug/control"): self._debug_control,
        }, port=port, host=host, name="raft-obs-exporter")
        self.host = host
        self.port = self._server.port

    # -- route handlers ------------------------------------------------------
    def _metrics(self, req) -> Response:
        return Response(200, self._registry.to_prometheus().encode(),
                        _CONTENT_TYPE)

    def _healthz(self, req) -> Response:
        if self.slo is None:
            code, body = 200, {"status": "ready", "slo": None,
                               "note": "no SLO tracker attached"}
        else:
            code, body = self.slo.healthz()
        if self.replicas is not None:
            code, body = _fold_replica_health(
                code, dict(body), self.replicas.health())
        if self.controller is not None:
            # compact controller state rides the health body
            # (informational — an automated actuation is not degradation;
            # its failures journal as control/action_failed)
            st = self.controller.status()
            body = dict(body)
            body["control"] = {
                "enabled": st["enabled"],
                "dry_run": st["dry_run"],
                "inflight": st["inflight"],
                "last_action": st["last_action"],
                "degraded": st["degraded"],
            }
        return json_response(code, body)

    def _debug_mem(self, req) -> Response:
        from . import mem as obs_mem

        return json_response(200, obs_mem.debug_payload())

    def _debug_events(self, req) -> Response:
        from . import events as obs_events

        try:
            since = int(req.param("since_seq") or 0)
            limit = (int(req.param("limit"))
                     if req.param("limit") is not None else None)
        except ValueError:
            return json_response(400, {"error": "since_seq and limit must "
                                                "be integers"})
        evs = obs_events.query(
            kind=req.param("kind"), severity=req.param("severity"),
            component=req.param("component"), name=req.param("name"),
            since_seq=since, limit=limit)
        return json_response(200, {"events": evs,
                                   "last_seq": obs_events.last_seq(),
                                   "counts_by_kind":
                                       obs_events.counts_by_kind()})

    def _debug_control(self, req) -> Response:
        if self.controller is None:
            return json_response(404, {"error": "no controller attached — "
                                                "pass controller= to the "
                                                "exporter"})
        from . import events as obs_events

        return json_response(200, {"controller": self.controller.status(),
                                   "recent": obs_events.query(
                                       component="control", limit=50)})

    def _debug_requests(self, req) -> Response:
        if self.request_log is None:
            return json_response(404, {"error": "no request log attached — "
                                                "pass request_log= to the "
                                                "exporter"})
        return json_response(200, self.request_log.to_json())

    # -- lifecycle -----------------------------------------------------------
    def stop(self, timeout_s: float = 5.0) -> None:
        """Shut the listener down and join the serving thread. Idempotent."""
        server, self._server = self._server, None
        if server is None:
            return
        server.stop(timeout_s)

    def __enter__(self) -> "MetricsExporter":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


def start_http_exporter(port: int = 0, host: str = "127.0.0.1",
                        registry: metrics.Registry | None = None,
                        slo=None, request_log=None,
                        replicas=None, controller=None) -> MetricsExporter:
    """Start (or return the already-running) obs HTTP endpoint.

    ``port=0`` binds an ephemeral port (read it off the returned
    ``.port``); ``host`` defaults to loopback — bind "0.0.0.0" explicitly
    to expose beyond the machine. ``slo=``/``request_log=`` attach the
    ``/healthz`` and ``/debug/requests`` sources; ``replicas=`` (a
    :class:`~raft_tpu.stream.ReplicatedShard` or
    :class:`~raft_tpu.stream.ShardedMutableIndex`) folds per-replica
    breaker health into the ``/healthz`` verdict — any group at zero
    pickable twins is ``failing``/503. ``controller=`` (a
    :class:`raft_tpu.control.Controller`) routes ``/debug/control``
    (status + recent ``control/*`` journal events) and folds compact
    controller state into the ``/healthz`` body. One exporter per process
    through this module-level entry (a second call returns the live one —
    attach sources on the first call); construct :class:`MetricsExporter`
    directly for multiples or custom registries.
    """
    global _active
    with _lock:
        if _active is not None:
            return _active
        _active = MetricsExporter(port=port, host=host, registry=registry,
                                  slo=slo, request_log=request_log,
                                  replicas=replicas, controller=controller)
        return _active


def stop_http_exporter() -> None:
    """Stop the module-level exporter (no-op when none is running)."""
    global _active
    with _lock:
        exp, _active = _active, None
    if exp is not None:
        exp.stop()
