"""Opt-in stdlib HTTP exporter: the serve tier becomes scrapeable without
a wrapper framework.

``start_http_exporter(port)`` serves :func:`raft_tpu.obs.to_prometheus`
from a daemon-threaded stdlib ``http.server`` — every GET path returns the
text exposition format (Prometheus convention is ``/metrics``; the path is
not enforced so a curl against ``/`` works too). Nothing starts unless the
process asks: no port is opened at import, and the exporter holds no lock
while rendering beyond the registry's own snapshot lock.

    from raft_tpu import obs

    exp = obs.start_http_exporter(9100)   # or port=0 for an ephemeral port
    ...                                    # scrape http://host:exp.port/metrics
    exp.stop()                             # clean shutdown (also a context
                                           # manager; atexit not required —
                                           # the thread is a daemon)
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import metrics

__all__ = ["MetricsExporter", "start_http_exporter", "stop_http_exporter"]

# Prometheus text exposition content type (version 0.0.4 is the text format)
_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_lock = threading.Lock()
_active: "MetricsExporter | None" = None


class MetricsExporter:
    """One running exporter: a ThreadingHTTPServer on a daemon thread."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 registry: metrics.Registry | None = None):
        reg = registry or metrics.default_registry()

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - http.server API
                body = reg.to_prometheus().encode()
                self.send_response(200)
                self.send_header("Content-Type", _CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                # scrapes every few seconds must not spam stderr; the
                # request count is observable from the scraper side
                pass

        self._server = ThreadingHTTPServer((host, int(port)), Handler)
        self._server.daemon_threads = True
        self.host = host
        self.port = int(self._server.server_address[1])
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name=f"raft-obs-exporter-{self.port}", daemon=True)
        self._thread.start()

    def stop(self, timeout_s: float = 5.0) -> None:
        """Shut the listener down and join the serving thread. Idempotent."""
        server, self._server = self._server, None
        if server is None:
            return
        server.shutdown()
        server.server_close()
        self._thread.join(timeout_s)

    def __enter__(self) -> "MetricsExporter":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


def start_http_exporter(port: int = 0, host: str = "127.0.0.1",
                        registry: metrics.Registry | None = None
                        ) -> MetricsExporter:
    """Start (or return the already-running) metrics HTTP endpoint.

    ``port=0`` binds an ephemeral port (read it off the returned
    ``.port``); ``host`` defaults to loopback — bind "0.0.0.0" explicitly
    to expose beyond the machine. One exporter per process through this
    module-level entry (a second call returns the live one); construct
    :class:`MetricsExporter` directly for multiples or custom registries.
    """
    global _active
    with _lock:
        if _active is not None:
            return _active
        _active = MetricsExporter(port=port, host=host, registry=registry)
        return _active


def stop_http_exporter() -> None:
    """Stop the module-level exporter (no-op when none is running)."""
    global _active
    with _lock:
        exp, _active = _active, None
    if exp is not None:
        exp.stop()
