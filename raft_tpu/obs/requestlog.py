"""Request-level tracing: a request id minted at admission, span timings
through the serve/stream pipeline, and a /debug/requests ring buffer.

Histograms answer "how slow is p99"; they cannot answer "WHY was *that*
request slow". Dapper (Sigelman et al., 2010) is the template: give every
request an id at the door, record per-stage span timings against it, and
keep the recent ones queryable. This module is the stdlib, in-process
version for the serve+stream stack:

- :meth:`RequestLog.begin` mints the id when
  :meth:`~raft_tpu.serve.SearchService.submit` admits a request;
- the batcher records the **queue** span (admission → flush pickup) and
  the **flush** span (flush_fn wall) per request;
- inside the flush, :func:`add_span`/:func:`annotate` accumulate into a
  thread-local collector (:func:`collect`): the service's flush function
  records ``serve/lease`` and ``serve/search``, and
  ``stream.MutableIndex`` carves the search into ``stream/sealed`` /
  ``stream/delta`` / ``stream/merge`` dispatch walls plus the registry
  version the flush leased — so a slow or wrong answer is attributable to
  a specific queue, flush, index epoch, or stream stage;
- completed requests land in a bounded ring served at ``/debug/requests``
  (``obs.start_http_exporter(port, request_log=log)``), with the
  slowest-recent requests and a per-latency-bucket **exemplar** map — each
  bucket of the ``raft_tpu_serve_*_seconds`` histograms links to the most
  recent request id that landed in it, which is how a histogram spike
  turns into a concrete trace to read.

Requests in one flush batch share the flush-level spans (they WERE served
by the same dispatch) and keep per-request queue spans. Span walls inside
a jax pipeline are host dispatch walls — jax is async — so the flush span
(which materializes) bounds them; the decomposition is still the right
attribution order-of-magnitude on the host side, and the device side
belongs to xprof (docs/observability.md).
"""

from __future__ import annotations

import collections
import functools
import os
import threading
import time
from typing import Callable

from . import metrics
from .metrics import DEFAULT_BUCKETS, _fmt_le

__all__ = ["RequestLog", "collect", "add_span", "annotate", "prefix"]


@functools.lru_cache(maxsize=None)
def _c_logged():
    return metrics.counter(
        "raft_tpu_requestlog_requests_total",
        "requests recorded in the /debug/requests ring, by stream and "
        "outcome (ok/error/expired)")


# -- thread-local span collector ---------------------------------------------

_tls = threading.local()


class _Collector:
    __slots__ = ("spans", "notes", "pre", "pid")

    def __init__(self):
        self.spans: dict[str, float] = {}
        self.notes: dict[str, object] = {}
        self.pre = ""  # active span/note name prefix (see `prefix`)
        # owning process: `collect(resume=)` is a SINGLE-PROCESS contract
        # (thread-local handoff, never concurrent) — a collector carried
        # across a fork/spawn boundary must not be resumed there
        self.pid = os.getpid()


class collect:
    """Context manager opening a span collector on the current thread —
    the batcher wraps each flush_fn call in one; :func:`add_span` and
    :func:`annotate` anywhere below (service flush, registry lease, stream
    search) accumulate into it. Reentrant-safe (inner scopes shadow) and a
    no-op-cost check when no scope is open.

    ``resume=`` re-opens an EXISTING collector instead of a fresh one —
    how the pipelined batcher's completion stage (possibly on another
    thread, never concurrently with dispatch) lands its spans on the same
    batch's trace as the dispatch-side ones. The handoff contract is
    same-process only: dispatch and completion are threads of one service.
    A collector that crossed a process boundary (fork-inherited, or
    unpickled in a mesh worker's response path) must NOT be mutated —
    the parent may still complete the same batch, and two processes
    appending to one span dict corrupts the trace. ``__enter__`` checks
    the collector's owning pid and degrades to a FRESH collector (noted
    ``resume_degraded: cross-process``) so wire traces lose the resumed
    spans instead of corrupting them."""

    def __init__(self, resume: _Collector | None = None):
        self._resume = resume

    def __enter__(self) -> _Collector:
        self._prev = getattr(_tls, "collector", None)
        resume = self._resume
        if resume is not None and resume.pid != os.getpid():
            # cross-process resume: the collector belongs to another
            # process's trace — start fresh, mark the degrade
            resume = None
        _tls.collector = resume if resume is not None else _Collector()
        if resume is None and self._resume is not None:
            _tls.collector.notes["resume_degraded"] = "cross-process"
        return _tls.collector

    def __exit__(self, *exc) -> None:
        _tls.collector = self._prev


def add_span(name: str, seconds: float) -> None:
    """Accumulate a span wall into the active collector (no-op without
    one — the stream/serve call sites pay one getattr when tracing is
    off)."""
    c = getattr(_tls, "collector", None)
    if c is not None:
        name = c.pre + name
        c.spans[name] = c.spans.get(name, 0.0) + float(seconds)


def annotate(key: str, value) -> None:
    """Attach a non-timing fact (e.g. the leased registry version) to the
    active collector."""
    c = getattr(_tls, "collector", None)
    if c is not None:
        c.notes[c.pre + key] = value


class prefix:
    """Scope a span/note name prefix on the active collector — how the
    sharded stream tier turns one code path's ``stream/sealed`` /
    ``stream/delta`` spans into per-shard ``stream/shard<i>/...`` entries,
    so ``/debug/requests`` attributes tail latency to the straggler shard.
    Nests (inner prefixes append) and costs one getattr when no collector
    is open."""

    def __init__(self, p: str):
        self._p = str(p)

    def __enter__(self) -> "prefix":
        c = getattr(_tls, "collector", None)
        self._prev = c.pre if c is not None else None
        if c is not None:
            c.pre = c.pre + self._p
        return self

    def __exit__(self, *exc) -> None:
        c = getattr(_tls, "collector", None)
        if c is not None and self._prev is not None:
            c.pre = self._prev


# -- the log -----------------------------------------------------------------

class RequestLog:
    """Bounded ring of completed request traces (see module doc).

    ``capacity`` bounds the completed-trace ring (one small dict per
    request); ``in_flight_capacity`` separately bounds the pending map and
    should cover the service's admission bound (``max_queue_rows``, default
    4096) — sizing it BELOW the queue bound would evict exactly the
    oldest/wedged requests the in-flight view exists to expose. ``clock``
    is injected for deterministic tests. All methods are thread-safe;
    :meth:`begin` is the only hot-path touch (a dict insert under a
    lock)."""

    def __init__(self, capacity: int = 256, *,
                 in_flight_capacity: int = 4096,
                 clock: Callable[[], float] = time.monotonic):
        self.capacity = int(capacity)
        self.in_flight_capacity = int(in_flight_capacity)
        self._clock = clock
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._next = 0
        # rid -> admission context of requests begun but not yet completed
        # (visible as "in_flight" at /debug/requests — a wedged flush shows
        # up HERE, not in the completed ring). Oldest-first eviction only
        # past in_flight_capacity: with the cap at/above the service's
        # queue bound, eviction touches only LEAKED entries (a drain=False
        # shutdown fails futures without complete()), which are by
        # construction the oldest once real traffic resumes.
        self._pending: dict[str, dict] = {}
        # latency-bucket upper bound -> the most recent request that landed
        # there: the exemplar link from the serve latency histograms
        self._exemplars: dict[str, dict] = {}

    # -- lifecycle -----------------------------------------------------------
    def begin(self, stream: str, rows: int, *,
              rid: str | None = None) -> str:
        """Mint a request id at admission and record it in flight.

        ``rid=`` adopts an EXTERNALLY minted id instead (the net front
        door threads the wire request id — ``X-Raft-Request-Id`` — here,
        so one trace spans wire→queue→flush under the id the client
        logged). Adopted ids are recorded as given; uniqueness is the
        caller's contract (a reused id overwrites the pending entry)."""
        now = self._clock()
        with self._lock:
            if rid is None:
                self._next += 1
                rid = f"req-{self._next:08d}"
            else:
                rid = str(rid)
            self._pending[rid] = {"rid": rid, "stream": stream,
                                  "rows": int(rows), "admitted_at": now}
            while len(self._pending) > self.in_flight_capacity:
                self._pending.pop(next(iter(self._pending)))
            return rid

    def attach_span(self, rid: str | None, name: str,
                    seconds: float) -> None:
        """Attach a span to an ALREADY COMPLETED request's ring entry —
        how the net front door lands the ``wire`` span (measured around
        the whole submit→resolve window, so it bounds queue+flush) on a
        trace after the batcher completed it. Searches the ring newest-
        first; a no-op when the rid has been evicted (or ``None``), so
        wire tracing degrades instead of raising."""
        if rid is None:
            return
        with self._lock:
            for entry in reversed(self._ring):
                if entry["rid"] == rid:
                    entry["spans_ms"][name] = round(float(seconds) * 1e3, 4)
                    return

    def complete(self, rid: str | None, *, stream: str, rows: int,
                 spans: dict, bucket: int | None = None, notes: dict = None,
                 outcome: str = "ok") -> None:
        """Record one finished request (rid None → no-op, so call sites
        need no attached-log check). ``spans`` carries at least the queue
        span; the total used for slowest/exemplar ranking is queue +
        flush."""
        if rid is None:
            return
        total = float(spans.get("queue", 0.0)) + float(spans.get("flush", 0.0))
        entry = {
            "rid": rid, "stream": stream, "rows": int(rows),
            "bucket": bucket, "outcome": outcome,
            "spans_ms": {k: round(v * 1e3, 4) for k, v in spans.items()},
            "total_ms": round(total * 1e3, 4),
            "ts": self._clock(),
        }
        if notes:
            entry["notes"] = dict(notes)
        with self._lock:
            self._pending.pop(rid, None)
            self._ring.append(entry)
            if outcome == "ok":
                self._exemplars[_bucket_le(total)] = {
                    "rid": rid, "stream": stream,
                    "total_ms": entry["total_ms"], "ts": entry["ts"]}
        if metrics._enabled:
            _c_logged().inc(1, stream=stream, outcome=outcome)

    # -- read side -----------------------------------------------------------
    def get(self, rid: str) -> dict | None:
        """The completed ring entry for ``rid`` (newest first), or ``None``
        when it never completed / was evicted — the net front door's span
        lookup, deliberately miss-tolerant."""
        with self._lock:
            for entry in reversed(self._ring):
                if entry["rid"] == rid:
                    return dict(entry)
        return None

    def recent(self, n: int = 50) -> list[dict]:
        """The most recent completed requests, oldest first."""
        with self._lock:
            return list(self._ring)[-int(n):]

    def slowest(self, n: int = 10) -> list[dict]:
        """The slowest requests still in the ring (recent by construction —
        the ring is bounded), worst first."""
        with self._lock:
            entries = list(self._ring)
        return sorted(entries, key=lambda e: -e["total_ms"])[:int(n)]

    def exemplars(self) -> dict:
        """{histogram bucket ``le`` → most recent request landing there} —
        the link from a ``raft_tpu_serve_*_seconds`` bucket to a concrete
        trace."""
        with self._lock:
            return dict(self._exemplars)

    def in_flight(self) -> list[dict]:
        """Requests admitted but not yet completed, oldest first — a
        wedged flush shows up here, not in the completed ring."""
        with self._lock:
            return list(self._pending.values())

    def to_json(self, recent: int = 50, slowest: int = 10) -> dict:
        """The /debug/requests payload."""
        return {
            "capacity": self.capacity,
            "in_flight": self.in_flight(),
            "recent": self.recent(recent),
            "slowest": self.slowest(slowest),
            "exemplars": self.exemplars(),
        }


def _bucket_le(total_s: float) -> str:
    """The latency-histogram bucket (upper bound, formatted by the SAME
    ``le``-string rule the metrics exposition uses — ``metrics._fmt_le`` —
    so exemplar keys can never drift out of byte-match with the
    ``raft_tpu_serve_*_seconds`` bucket labels) a request total falls in."""
    for ub in DEFAULT_BUCKETS:
        if total_s <= ub:
            return _fmt_le(ub)
    return _fmt_le(float("inf"))
