"""Entry-point instrumentation: per-call latency, volume, and compile share.

``@instrument("ivf_pq.search", ...)`` wraps a public entry point with three
metrics (the reference's counterpart is the bench harness's per-case timing,
benchmark.hpp:111-200 — here it is first-class in the library):

- ``raft_tpu_call_seconds{op=...}``       histogram, host wall time per call
- ``raft_tpu_call_compile_seconds{op=..}`` histogram, jax compile seconds
  attributed to the call (0 on warm calls — the compile-vs-execute split)
- ``raft_tpu_items_total{op=...}``        counter, rows/queries processed

Wall time is HOST time through dispatch: jax is async, so a call that
returns un-materialized arrays records its dispatch cost, not device time
(device-side stages are carved by ``tracing.range`` names in xprof instead).
For cold calls the compile share dominates and is reported separately.

Disabled mode (``obs.disable()``) reduces the wrapper to one module-flag
check and a tail call — guarded by the ``obs_overhead`` tier-1 smoke.
"""

from __future__ import annotations

import functools
import time

from . import compile as _compile
from . import metrics

__all__ = ["instrument"]


def _call_seconds():
    return metrics.histogram(
        "raft_tpu_call_seconds",
        "host wall time of instrumented raft_tpu entry points",
        unit="seconds")


def _call_compile_seconds():
    return metrics.histogram(
        "raft_tpu_call_compile_seconds",
        "jax compile seconds attributed to instrumented calls "
        "(call_seconds minus this is execute/dispatch time)",
        unit="seconds")


def _items_total():
    return metrics.counter(
        "raft_tpu_items_total",
        "rows/queries processed by instrumented entry points")


def instrument(op: str, items=None, labels=None):
    """Decorator factory. ``items(args, kwargs) -> int`` counts rows/queries;
    ``labels(args, kwargs) -> dict`` adds low-cardinality labels (shape
    class, dtype, k) to the latency series. Both are best-effort: a raising
    helper drops its labels rather than the call."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not metrics._enabled:
                return fn(*args, **kwargs)
            with _compile.attribution() as rec:
                t0 = time.perf_counter()
                out = fn(*args, **kwargs)
                dt = time.perf_counter() - t0
            try:
                lbls = labels(args, kwargs) if labels is not None else {}
            except Exception:
                lbls = {}
            _call_seconds().observe(dt, op=op, **lbls)
            if rec.available:
                _call_compile_seconds().observe(rec.compile_s, op=op, **lbls)
            if items is not None:
                try:
                    _items_total().inc(int(items(args, kwargs)), op=op)
                except Exception:
                    pass
            return out

        return wrapper

    return deco


def nrows(x) -> int:
    """Row count of an array-like (shared by the per-site ``items`` hooks)."""
    shape = getattr(x, "shape", None)
    if shape is not None:
        return int(shape[0]) if len(shape) else 1
    return len(x)


def dtype_of(x) -> str:
    return str(getattr(x, "dtype", type(x).__name__))
