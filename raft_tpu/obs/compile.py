"""Compile attribution: split every wall-clock second into compile vs execute.

The two costs that dominate real deployments are invisible to a stopwatch:
cold-jit compilation (1M ivf_pq: 103.6 s cold vs 7.3 s warm, BASELINE.md) and
persistent-cache outcomes. jax's ``jax.monitoring`` event bus reports exactly
these — per-program trace/lower/compile durations and compilation-cache
hit/miss events — so this module subscribes ONCE (process-global, idempotent)
and fans the events into two sinks:

- the default metrics registry (``raft_tpu_compile_seconds{stage=...}``,
  ``raft_tpu_compile_cache_total{outcome=...}``), always on while metrics are
  enabled;
- any active :func:`attribution` scopes, which accumulate a
  :class:`CompileRecord` for one region of caller code (``_warmup`` and the
  instrumented entry points use this to report per-call compile seconds).

Older jax without the monitoring bus: :func:`install` returns False,
``attribution()`` yields a record with ``available=False``, and callers fall
back to wall-time deltas (``ops/_compat.jax_monitoring`` is the gate).
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading

from . import metrics

__all__ = ["install", "installed", "attribution", "CompileRecord"]

# jax event names -> our stage label (dispatch.py:60-62). "compile" is the
# backend (XLA) compile — the cost the persistent cache saves; trace/lower are
# per-process and NOT cached (the residual warm-process seconds in
# docs/warm_builds.md).
_STAGE_EVENTS = {
    "/jax/core/compile/jaxpr_trace_duration": "trace",
    "/jax/core/compile/jaxpr_to_mlir_module_duration": "lower",
    "/jax/core/compile/backend_compile_duration": "compile",
}
_CACHE_EVENTS = {
    "/jax/compilation_cache/cache_hits": "hit",
    "/jax/compilation_cache/cache_misses": "miss",
}
_SAVED_EVENT = "/jax/compilation_cache/compile_time_saved_sec"

_lock = threading.Lock()
_installed = False
_available = False
_scopes: list["CompileRecord"] = []


@dataclasses.dataclass(eq=False)
class CompileRecord:
    """What happened, compile-wise, inside one ``attribution()`` scope.

    ``eq=False``: records live in the ``_scopes`` list and are removed by
    identity — dataclass value-equality would make nested scopes with
    identical contents (e.g. two all-warm regions) remove each other's
    entries."""

    available: bool = True
    trace_s: float = 0.0
    lower_s: float = 0.0
    compile_s: float = 0.0  # backend-compile seconds (sum over programs)
    cache_hits: int = 0
    cache_misses: int = 0
    saved_s: float = 0.0  # compile seconds the persistent cache avoided
    # per-program backend-compile seconds, in completion order
    program_compile_s: list = dataclasses.field(default_factory=list)

    @property
    def programs(self) -> int:
        return len(self.program_compile_s)

    def summary(self) -> dict:
        return {
            "compile_s": round(self.compile_s, 3),
            "trace_s": round(self.trace_s + self.lower_s, 3),
            "programs": self.programs,
            "cache_hits": self.cache_hits,
            "cache_misses": self.cache_misses,
        }


def _on_duration(event: str, duration: float, **kw) -> None:
    stage = _STAGE_EVENTS.get(event)
    if stage is not None:
        if metrics.enabled():
            metrics.histogram(
                "raft_tpu_compile_seconds",
                "jax program build time by stage (trace/lower/compile)",
                unit="seconds").observe(duration, stage=stage)
        with _lock:
            scopes = list(_scopes)
        for rec in scopes:
            if stage == "trace":
                rec.trace_s += duration
            elif stage == "lower":
                rec.lower_s += duration
            else:
                rec.compile_s += duration
                rec.program_compile_s.append(duration)
    elif event == _SAVED_EVENT:
        if metrics.enabled():
            metrics.counter(
                "raft_tpu_compile_saved_seconds_total",
                "compile seconds avoided by persistent-cache hits",
                unit="seconds").inc(max(duration, 0.0))
        with _lock:
            scopes = list(_scopes)
        for rec in scopes:
            rec.saved_s += max(duration, 0.0)


def _on_event(event: str, **kw) -> None:
    outcome = _CACHE_EVENTS.get(event)
    if outcome is None:
        return
    if metrics.enabled():
        metrics.counter(
            "raft_tpu_compile_cache_total",
            "persistent compilation cache outcomes").inc(1, outcome=outcome)
    with _lock:
        scopes = list(_scopes)
    for rec in scopes:
        if outcome == "hit":
            rec.cache_hits += 1
        else:
            rec.cache_misses += 1


def install() -> bool:
    """Subscribe to jax's monitoring bus (idempotent; one registration per
    process — jax offers no unregister outside tests, so listeners stay for
    the process lifetime and gate on ``metrics.enabled()``). Returns whether
    event-based attribution is live."""
    global _installed, _available
    from ..ops._compat import jax_monitoring

    # registration happens INSIDE the lock so a concurrent first caller
    # cannot observe _installed=True with the listeners (and _available)
    # not yet in place; registering invokes nothing, so no deadlock risk
    with _lock:
        if _installed:
            return _available
        mon = jax_monitoring()
        if mon is not None:
            mon.register_event_duration_secs_listener(_on_duration)
            mon.register_event_listener(_on_event)
            _available = True
        _installed = True
        return _available


def installed() -> bool:
    return _installed and _available


@contextlib.contextmanager
def attribution():
    """Collect compile events for the enclosed region.

    >>> with attribution() as rec:
    ...     idx = ivf_pq.build(params, x)
    >>> rec.compile_s, rec.cache_hits, rec.program_compile_s

    Scopes nest (each sees all events fired while it is open). Events are
    delivered on the thread that compiles — for jax that is the dispatching
    thread, so cross-thread noise only appears if the caller runs concurrent
    jit compiles, in which case attribute at a coarser scope.
    """
    ok = install()
    rec = CompileRecord(available=ok)
    with _lock:
        _scopes.append(rec)
    try:
        yield rec
    finally:
        with _lock:
            _scopes.remove(rec)
