"""Summary statistics over dense matrices.

Re-design of the reference's stats moment kernels (cpp/include/raft/stats/:
mean.cuh, stddev.cuh, meanvar.cuh, cov.cuh, sum.cuh, minmax.cuh,
histogram.cuh (shared-mem binning), weighted_mean.cuh, mean_center.cuh).
Everything is an XLA reduction/GEMM; the histogram's shared-memory binning
strategy becomes a one-hot matmul that rides the MXU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.errors import expects

__all__ = [
    "mean",
    "stddev",
    "vars_",
    "meanvar",
    "cov",
    "sum_",
    "minmax",
    "histogram",
    "weighted_mean",
    "mean_center",
    "mean_add",
]


def mean(m, axis: int = 0, sample: bool = False):
    """Column means (reference: stats/mean.cuh; ``sample`` divides by n-1)."""
    m = jnp.asarray(m).astype(jnp.float32)
    n = m.shape[axis]
    s = jnp.sum(m, axis=axis)
    return s / (n - 1 if sample else n)


def vars_(m, mu=None, axis: int = 0, sample: bool = True):
    """Column variances (reference: stats/vars.cuh)."""
    m = jnp.asarray(m).astype(jnp.float32)
    if mu is None:
        mu = jnp.mean(m, axis=axis)
    n = m.shape[axis]
    sq = jnp.sum(jnp.square(m - jnp.expand_dims(mu, axis)), axis=axis)
    return sq / (n - 1 if sample else n)


def stddev(m, mu=None, axis: int = 0, sample: bool = True):
    """Reference: stats/stddev.cuh."""
    return jnp.sqrt(vars_(m, mu, axis, sample))


def meanvar(m, axis: int = 0, sample: bool = True):
    """Fused mean+variance (reference: stats/meanvar.cuh)."""
    mu = mean(m, axis)
    return mu, vars_(m, mu, axis, sample)


def cov(m, sample: bool = True):
    """Covariance of columns (reference: stats/cov.cuh — gemm on centered
    data)."""
    m = jnp.asarray(m).astype(jnp.float32)
    c = m - jnp.mean(m, axis=0, keepdims=True)
    n = m.shape[0]
    return (c.T @ c) / (n - 1 if sample else n)


def sum_(m, axis: int = 0):
    """Reference: stats/sum.cuh."""
    return jnp.sum(jnp.asarray(m).astype(jnp.float32), axis=axis)


def minmax(m, axis: int = 0):
    """Per-column (min, max) (reference: stats/minmax.cuh)."""
    m = jnp.asarray(m)
    return jnp.min(m, axis=axis), jnp.max(m, axis=axis)


def histogram(m, n_bins: int, lower: float, upper: float):
    """Per-column fixed-width histogram (reference: stats/histogram.cuh).

    Bin index = floor((x - lower)/width) clipped to [0, n_bins); counts are a
    one-hot matmul so the binning rides the MXU instead of shared-mem atomics.
    Returns (n_bins, n_cols) int32 counts.
    """
    m = jnp.asarray(m).astype(jnp.float32)
    expects(upper > lower, "upper must exceed lower")
    width = (upper - lower) / n_bins
    idx = jnp.clip(jnp.floor((m - lower) / width), 0, n_bins - 1).astype(jnp.int32)
    onehot = jax.nn.one_hot(idx, n_bins, dtype=jnp.float32, axis=0)  # (n_bins, n_rows, n_cols)
    return jnp.sum(onehot, axis=1).astype(jnp.int32)


def weighted_mean(m, weights, axis: int = 0):
    """Weighted column means (reference: stats/weighted_mean.cuh)."""
    m = jnp.asarray(m).astype(jnp.float32)
    w = jnp.asarray(weights).astype(jnp.float32)
    w_exp = jnp.expand_dims(w, 1 - axis) if m.ndim == 2 else w
    return jnp.sum(m * w_exp, axis=axis) / jnp.sum(w)


def mean_center(m, mu=None, axis: int = 0):
    """Subtract means (reference: stats/mean_center.cuh)."""
    m = jnp.asarray(m).astype(jnp.float32)
    if mu is None:
        mu = jnp.mean(m, axis=axis)
    return m - jnp.expand_dims(mu, axis)


def mean_add(m, mu, axis: int = 0):
    """Add means back (reference: stats/mean_center.cuh meanAdd)."""
    return jnp.asarray(m).astype(jnp.float32) + jnp.expand_dims(jnp.asarray(mu), axis)
