"""raft_tpu.stats — raft/stats (P10-P11). Under construction."""
