"""Model/clustering evaluation metrics.

Re-design of the reference's stats metric kernels (cpp/include/raft/stats/:
accuracy.cuh, r2_score.cuh, regression_metrics.cuh, entropy.cuh,
mutual_info_score.cuh, rand_index.cuh, adjusted_rand_index.cuh,
homogeneity_score.cuh, completeness_score.cuh, v_measure.cuh,
kl_divergence.cuh, silhouette_score.cuh, trustworthiness_score.cuh,
dispersion.cuh, contingency_matrix.cuh, information_criterion.cuh). The
contingency matrix — the hub all cluster-comparison metrics route through —
is a one-hot GEMM on TPU; everything downstream is small dense math.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.errors import expects
from ..distance.pairwise import pairwise_distance

__all__ = [
    "accuracy",
    "r2_score",
    "regression_metrics",
    "entropy",
    "contingency_matrix",
    "mutual_info_score",
    "rand_index",
    "adjusted_rand_index",
    "homogeneity_score",
    "completeness_score",
    "v_measure",
    "kl_divergence",
    "silhouette_score",
    "dispersion",
    "trustworthiness",
    "information_criterion",
]

_f32 = jnp.float32


def accuracy(predictions, labels):
    """Fraction of exact matches (reference: stats/accuracy.cuh)."""
    p = jnp.asarray(predictions)
    l = jnp.asarray(labels)
    return jnp.mean((p == l).astype(_f32))


def r2_score(y, y_hat):
    """Coefficient of determination (reference: stats/r2_score.cuh)."""
    y = jnp.asarray(y).astype(_f32)
    y_hat = jnp.asarray(y_hat).astype(_f32)
    ss_res = jnp.sum(jnp.square(y - y_hat))
    ss_tot = jnp.sum(jnp.square(y - jnp.mean(y)))
    return 1.0 - ss_res / ss_tot


def regression_metrics(predictions, ref):
    """(mean_abs_error, mean_squared_error, median_abs_error) (reference:
    stats/regression_metrics.cuh)."""
    p = jnp.asarray(predictions).astype(_f32)
    r = jnp.asarray(ref).astype(_f32)
    err = p - r
    return jnp.mean(jnp.abs(err)), jnp.mean(jnp.square(err)), jnp.median(jnp.abs(err))


def _class_counts(labels, n_classes: int):
    return jnp.sum(jax.nn.one_hot(jnp.asarray(labels), n_classes, dtype=_f32), axis=0)


def entropy(labels, n_classes: int):
    """Shannon entropy of a label distribution, in nats (reference:
    stats/entropy.cuh)."""
    counts = _class_counts(labels, n_classes)
    p = counts / jnp.sum(counts)
    return -jnp.sum(jnp.where(p > 0, p * jnp.log(jnp.where(p > 0, p, 1.0)), 0.0))


def contingency_matrix(a, b, n_classes_a: int | None = None, n_classes_b: int | None = None):
    """Joint label-count matrix via one-hot GEMM (reference:
    stats/contingency_matrix.cuh)."""
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    na = int(n_classes_a if n_classes_a is not None else int(jnp.max(a)) + 1)
    nb = int(n_classes_b if n_classes_b is not None else int(jnp.max(b)) + 1)
    oa = jax.nn.one_hot(a, na, dtype=_f32)  # (n, na)
    ob = jax.nn.one_hot(b, nb, dtype=_f32)
    return (oa.T @ ob).astype(jnp.int32)


def _mi_from_contingency(c):
    c = c.astype(_f32)
    n = jnp.sum(c)
    pij = c / n
    pi = jnp.sum(pij, axis=1, keepdims=True)
    pj = jnp.sum(pij, axis=0, keepdims=True)
    logterm = jnp.where(pij > 0, jnp.log(jnp.where(pij > 0, pij, 1.0)) - jnp.log(pi * pj + 1e-30), 0.0)
    return jnp.sum(pij * logterm)


def mutual_info_score(a, b, n_classes: int):
    """Reference: stats/mutual_info_score.cuh."""
    return _mi_from_contingency(contingency_matrix(a, b, n_classes, n_classes).astype(_f32))


def rand_index(a, b):
    """Unadjusted Rand index (reference: stats/rand_index.cuh)."""
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    na = int(jnp.max(a)) + 1
    nb = int(jnp.max(b)) + 1
    c = contingency_matrix(a, b, na, nb).astype(_f32)
    n = jnp.sum(c)
    sum_sq = jnp.sum(jnp.square(c))
    sum_rows_sq = jnp.sum(jnp.square(jnp.sum(c, axis=1)))
    sum_cols_sq = jnp.sum(jnp.square(jnp.sum(c, axis=0)))
    # pairs: agreements = C(n,2) - [ (Σrows² - Σc²)/2 + (Σcols² - Σc²)/2 ]... use standard identity
    comb = lambda x: x * (x - 1.0) / 2.0
    a_pairs = jnp.sum(comb(c))
    row_pairs = comb(jnp.sum(c, axis=1)).sum()
    col_pairs = comb(jnp.sum(c, axis=0)).sum()
    total = comb(n)
    return (total + 2 * a_pairs - row_pairs - col_pairs) / total


def adjusted_rand_index(a, b, n_classes: int | None = None):
    """ARI (reference: stats/adjusted_rand_index.cuh)."""
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    na = n_classes or int(jnp.max(a)) + 1
    nb = n_classes or int(jnp.max(b)) + 1
    c = contingency_matrix(a, b, na, nb).astype(_f32)
    comb = lambda x: x * (x - 1.0) / 2.0
    sum_comb = jnp.sum(comb(c))
    sum_rows = jnp.sum(comb(jnp.sum(c, axis=1)))
    sum_cols = jnp.sum(comb(jnp.sum(c, axis=0)))
    n = jnp.sum(c)
    expected = sum_rows * sum_cols / comb(n)
    max_index = 0.5 * (sum_rows + sum_cols)
    return (sum_comb - expected) / (max_index - expected + 1e-30)


def _conditional_entropy(c):
    """H(A|B) from contingency counts c[a, b]."""
    c = c.astype(_f32)
    n = jnp.sum(c)
    pj = jnp.sum(c, axis=0)  # counts of b
    ratio = c / jnp.maximum(pj[None, :], 1e-30)
    term = jnp.where(c > 0, (c / n) * jnp.log(jnp.where(ratio > 0, ratio, 1.0)), 0.0)
    return -jnp.sum(term)


def homogeneity_score(labels_true, labels_pred, n_classes: int):
    """1 - H(C|K)/H(C) (reference: stats/homogeneity_score.cuh)."""
    c = contingency_matrix(labels_true, labels_pred, n_classes, n_classes)
    h_c = entropy(labels_true, n_classes)
    h_ck = _conditional_entropy(c)
    return jnp.where(h_c > 0, 1.0 - h_ck / jnp.maximum(h_c, 1e-30), 1.0)


def completeness_score(labels_true, labels_pred, n_classes: int):
    """Reference: stats/completeness_score.cuh."""
    return homogeneity_score(labels_pred, labels_true, n_classes)


def v_measure(labels_true, labels_pred, n_classes: int, beta: float = 1.0):
    """Harmonic mean of homogeneity and completeness (reference:
    stats/v_measure.cuh)."""
    h = homogeneity_score(labels_true, labels_pred, n_classes)
    c = completeness_score(labels_true, labels_pred, n_classes)
    return jnp.where(h + c > 0, (1 + beta) * h * c / (beta * h + c + 1e-30), 0.0)


def kl_divergence(p, q):
    """Σ p log(p/q) over two densities (reference: stats/kl_divergence.cuh)."""
    p = jnp.asarray(p).astype(_f32)
    q = jnp.asarray(q).astype(_f32)
    return jnp.sum(jnp.where(p > 0, p * (jnp.log(jnp.where(p > 0, p, 1.0)) - jnp.log(jnp.maximum(q, 1e-30))), 0.0))


def silhouette_score(x, labels, n_classes: int, metric="euclidean"):
    """Mean silhouette coefficient (reference: stats/silhouette_score.cuh,
    batched variant stats/detail/batched/silhouette_score.cuh).

    Per-cluster distance sums come from one (n, n)·(n, k) GEMM against the
    one-hot label matrix — the TPU shape of the reference's per-sample
    accumulations.
    """
    x = jnp.asarray(x)
    labels = jnp.asarray(labels)
    d = pairwise_distance(x, x, metric=metric)  # (n, n)
    onehot = jax.nn.one_hot(labels, n_classes, dtype=_f32)  # (n, k)
    sums = d @ onehot  # (n, k): distance mass from i to each cluster
    counts = jnp.sum(onehot, axis=0)  # (k,)
    own_count = counts[labels]
    own_sum = jnp.take_along_axis(sums, labels[:, None], axis=1)[:, 0]
    a = jnp.where(own_count > 1, own_sum / jnp.maximum(own_count - 1, 1), 0.0)
    other_mean = jnp.where(
        (counts[None, :] > 0) & (jax.nn.one_hot(labels, n_classes) == 0),
        sums / jnp.maximum(counts[None, :], 1),
        jnp.inf,
    )
    b = jnp.min(other_mean, axis=1)
    s = jnp.where(own_count > 1, (b - a) / jnp.maximum(jnp.maximum(a, b), 1e-30), 0.0)
    return jnp.mean(s)


def dispersion(centroids, cluster_sizes, global_centroid=None):
    """Weighted scatter of centroids around the global mean (reference:
    stats/dispersion.cuh)."""
    c = jnp.asarray(centroids).astype(_f32)
    sizes = jnp.asarray(cluster_sizes).astype(_f32)
    if global_centroid is None:
        global_centroid = jnp.sum(c * sizes[:, None], axis=0) / jnp.sum(sizes)
    sq = jnp.sum(jnp.square(c - global_centroid[None, :]), axis=1)
    return jnp.sqrt(jnp.sum(sizes * sq))


def trustworthiness(x, x_embedded, n_neighbors: int, metric="euclidean"):
    """Embedding-quality score (reference:
    stats/trustworthiness_score.cuh): penalizes points that are kNN in the
    embedding but far in the original space."""
    x = jnp.asarray(x)
    e = jnp.asarray(x_embedded)
    n = x.shape[0]
    k = n_neighbors
    expects(k < n / 2, "n_neighbors must be < n/2")
    d_orig = pairwise_distance(x, x, metric=metric)
    d_emb = pairwise_distance(e, e, metric=metric)
    big = jnp.finfo(_f32).max
    eye_mask = jnp.eye(n, dtype=bool)
    d_orig = jnp.where(eye_mask, big, d_orig)
    d_emb = jnp.where(eye_mask, big, d_emb)
    # rank of j in i's original-space ordering (0 = nearest)
    orig_order = jnp.argsort(d_orig, axis=1)
    ranks = jnp.zeros((n, n), jnp.int32)
    ranks = jax.vmap(lambda r, o: r.at[o].set(jnp.arange(n, dtype=jnp.int32)))(ranks, orig_order)
    emb_knn = jnp.argsort(d_emb, axis=1)[:, :k]
    r = jnp.take_along_axis(ranks, emb_knn, axis=1).astype(_f32)  # (n, k)
    penalty = jnp.sum(jnp.maximum(r - (k - 1), 0.0))
    norm = 2.0 / (n * k * (2.0 * n - 3.0 * k - 1.0))
    return 1.0 - norm * penalty


def information_criterion(log_likelihood, n_params: int, n_samples: int, kind: str = "bic"):
    """AIC/AICc/BIC (reference: stats/information_criterion.cuh)."""
    ll = jnp.asarray(log_likelihood).astype(_f32)
    if kind == "aic":
        return -2.0 * ll + 2.0 * n_params
    if kind == "aicc":
        corr = 2.0 * n_params * (n_params + 1.0) / jnp.maximum(n_samples - n_params - 1.0, 1.0)
        return -2.0 * ll + 2.0 * n_params + corr
    expects(kind == "bic", "kind must be aic|aicc|bic")
    return -2.0 * ll + n_params * jnp.log(jnp.asarray(float(n_samples)))
