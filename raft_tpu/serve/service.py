"""SearchService: batcher + registry + admission control.

The front door of the serving layer. One service owns:

- an :class:`~raft_tpu.serve.registry.IndexRegistry` (shared or private) —
  publish/hot-swap the indexes it serves;
- one :class:`~raft_tpu.serve.batcher.MicroBatcher` per *stream* (an index
  name at one ``k``), created lazily — submissions to the same stream share
  program shapes, so they can share batches;
- **admission control**: a bounded queue (``max_queue_rows`` across all
  streams). At the bound, :meth:`submit` raises
  :class:`~raft_tpu.serve.errors.OverloadedError` synchronously — load is
  shed at the door in microseconds, not discovered at the deadline. Each
  request may carry a deadline; requests that expire while queued are
  dropped at drain, BEFORE any device work is spent on them.

The flush path resolves the registry lease per flush, so a
:meth:`publish` hot-swap takes effect on the next flush while in-flight
batches finish on the version they started with — zero requests fail
across a swap (asserted by ``tests/test_serve.py`` and ``bench.py
--serve``).

Determinism for tests: pass ``start_workers=False`` plus an injected
``clock`` and drive the queues with :meth:`pump` — every admission,
deadline and batching decision is then synchronous and clock-exact (the
``serve`` tier-1 marker runs with no wall-clock sleeps in assertions).
"""

from __future__ import annotations

import contextlib
import functools
import inspect
import threading
import time
from concurrent.futures import Future
from typing import Callable

import numpy as np

from ..core import tracing
from ..core.errors import expects
from ..obs import dispatch as obs_dispatch
from ..obs import metrics, requestlog
from .batcher import MicroBatcher, PendingFlush, bucket_sizes, _deadline_total
from .errors import (DeadlineExceededError, OverloadedError,
                     ServiceClosedError)
from .registry import IndexRegistry
from .staging import StagingBuffers, warm_staging

__all__ = ["SearchService"]


@functools.lru_cache(maxsize=None)
def _overload_total():
    return metrics.counter(
        "raft_tpu_serve_overload_total",
        "requests refused at admission (queue at max_queue_rows)")


@functools.lru_cache(maxsize=None)
def _requests_total():
    return metrics.counter(
        "raft_tpu_serve_requests_total", "requests admitted per stream")


class _RowCounter:
    """Service-wide queued-row count with an atomic bounded add.

    LEAF lock: it is touched from under the service lock (submit) and from
    under batcher condition locks (drain callbacks), so it must never take
    another lock itself — that is what keeps the lock order acyclic."""

    def __init__(self, limit: int):
        self.limit = int(limit)
        self._n = 0
        self._lock = threading.Lock()

    def try_add(self, n: int) -> bool:
        with self._lock:
            if self._n + n > self.limit:
                return False
            self._n += n
            return True

    def sub(self, n: int) -> None:
        with self._lock:
            self._n = max(self._n - n, 0)

    def value(self) -> int:
        with self._lock:
            return self._n


class SearchService:
    """Online k-NN serving over the registry's indexes (see module doc).

    ``max_batch`` fixes the bucket ladder (and therefore the warmed program
    set) for every stream; ``max_wait_us`` is the batching latency budget —
    a lone request waits at most this long before flushing under-full.
    ``default_timeout_s`` applies to requests submitted without an explicit
    timeout (``None`` = no deadline).

    The online-quality hooks (all optional, docs/observability.md):
    ``canary`` (an :class:`raft_tpu.obs.quality.RecallCanary`) taps every
    flush of the canary's published name into its reservoir sampler;
    ``slo`` (an :class:`raft_tpu.obs.slo.SLOTracker`) receives every
    admission outcome and every served request's queue-wait/flush split;
    ``request_log`` (an :class:`raft_tpu.obs.requestlog.RequestLog`) mints
    a request id at admission and collects span timings through
    queue → flush → registry lease → index search → stream merge.

    ``pipeline_depth`` (default 2) bounds the pipelined flush path's
    in-flight completion stage (docs/serving.md "Pipelined flush"): the
    flush worker dispatches the search WITHOUT materializing, hands the
    pending result off, and drains the next batch — consecutive flushes
    overlap under jax's async dispatch, with queries staged through
    reusable per-bucket buffers. ``0`` restores the fully synchronous
    flush (the A/B baseline `bench.py --serve-pipeline` measures
    against). ``staging_device`` optionally pins the staging upload to
    one device and enables query-buffer DONATION across flushes
    (`donate_argnums` on the per-bucket stage programs); leave ``None``
    for multi-device searchers — a sharded mesh's per-shard programs
    take committed arrays on their own devices, and a query committed
    elsewhere would conflict.
    """

    def __init__(self, registry: IndexRegistry | None = None, *,
                 max_batch: int = 64, max_wait_us: float = 1000.0,
                 max_queue_rows: int = 4096,
                 default_timeout_s: float | None = None,
                 clock: Callable[[], float] = time.monotonic,
                 start_workers: bool = True,
                 canary=None, slo=None, request_log=None,
                 pipeline_depth: int = 2, staging_device=None):
        self.buckets = bucket_sizes(max_batch)
        self.registry = registry or IndexRegistry(buckets=self.buckets,
                                                  clock=clock)
        # an externally-built registry must warm every shape this service's
        # streams flush, or publish()'s zero-cold-compile swap guarantee is
        # silently void
        expects(set(self.buckets) <= set(self.registry.buckets),
                "registry buckets %s do not cover the service ladder %s",
                self.registry.buckets, self.buckets)
        self.max_batch = int(max_batch)
        self.max_wait_us = float(max_wait_us)
        self.max_queue_rows = int(max_queue_rows)
        # a bound below max_batch would refuse every full-bucket request
        # forever, even on an idle service — a config error, not overload
        expects(self.max_queue_rows >= self.max_batch,
                "max_queue_rows (%d) must be >= max_batch (%d)",
                self.max_queue_rows, self.max_batch)
        self._rows = _RowCounter(max_queue_rows)  # O(1) admission bound
        self.default_timeout_s = default_timeout_s
        self._clock = clock
        self._start_workers = start_workers
        expects(int(pipeline_depth) >= 0, "pipeline_depth must be >= 0")
        self.pipeline_depth = int(pipeline_depth)
        self._staging_device = staging_device
        expects(canary is None or (hasattr(canary, "offer")
                                   and hasattr(canary, "name")),
                "canary must be an obs.quality.RecallCanary (offer()/name)")
        expects(slo is None or hasattr(slo, "record_admission"),
                "slo must be an obs.slo.SLOTracker (record_admission())")
        expects(request_log is None or hasattr(request_log, "begin"),
                "request_log must be an obs.requestlog.RequestLog (begin())")
        self._canary = canary
        self._slo = slo
        self._request_log = request_log
        # guards the batcher map + the closed flag; admission uses the
        # leaf-locked _RowCounter instead, so submit never holds this lock
        # across an enqueue
        self._lock = threading.Lock()
        self._batchers: dict[tuple, MicroBatcher] = {}
        # writable (stream.MutableIndex) handles per name — the write path
        # (upsert/delete) routes through these
        self._mutables: dict[str, object] = {}
        self._closed = False

    # -- publish ------------------------------------------------------------
    def publish(self, name: str, index, *, search_params=None,
                k: int | tuple = 10, version: int | None = None,
                warm: bool = True, warm_data=None, tuned=None,
                res=None, warm_hook=None, cause: dict | None = None) -> dict:
        """Publish/hot-swap through the service's registry, warming against
        the SERVICE's bucket ladder (the shapes its streams actually flush).
        Safe under load: in-flight requests finish on the old version.
        ``warm_data`` (optional (rows, dim) sample in the serving dtype)
        draws the warmup queries from real data — see
        :func:`raft_tpu._warmup.warm_buckets`. ``tuned`` (a
        :class:`raft_tpu.tune.DecisionLog` / ``Decision`` / ``True``)
        serves the index at its pinned operating point; the warm ladder
        covers the tuned programs, so applying a decision is as hiccup-free
        as any other publish (docs/tuning.md).

        Publishing a :class:`raft_tpu.stream.MutableIndex` additionally
        opens the WRITE path: :meth:`upsert`/:meth:`delete` on this name
        route to it (re-publishing the index's ``searcher()`` hook — what a
        ``stream.Compactor`` does after a swap — keeps the handle).
        ``res`` carries ``memory_budget_bytes`` for the publish admission
        gate (:meth:`IndexRegistry.publish`); over budget raises
        :class:`~raft_tpu.serve.errors.MemoryBudgetError` with zero
        partial state — the registry is untouched and the write path
        keeps its previous routing.

        ``warm_hook`` (``fn(searcher, ks)``) forwards to the registry's
        pre-flip seam (:meth:`IndexRegistry.publish`), composed AFTER the
        pipelined flush path's own staging warm — the seam a topology
        change (:meth:`raft_tpu.stream.ShardedMutableIndex.reshard`) uses
        to commit its atomic flip with every new program already warm and
        nothing visible to serving traffic until the registry flips. Its
        return value lands in ``report["warm_hook"]``. ``cause`` forwards
        to the registry and rides the ``serve_published`` event's evidence
        (the control plane's causal chain — see docs/control.md)."""
        with tracing.range("serve/publish/%s", name):
            # hold the registry's per-name publish lock across flip AND
            # handle bookkeeping: a concurrent publish to the same name
            # could otherwise interleave between them and leave the write
            # path routed to an index that lost the flip
            with self.registry.publish_lock(name):
                # the staging leg of the warm ladder rides the registry's
                # pre-flip warm_hook: the per-bucket stage programs (and,
                # with a PINNED staging device, the searcher once per
                # (bucket, k) on committed staged queries — placement is
                # part of jax's executable key, so the registry's
                # uncommitted-query warm alone would leave the flush
                # path's committed-input executables cold) compile BEFORE
                # the flip. A hot-swap under live pipelined load can
                # therefore never serve the new version before its
                # committed-placement executables exist — running this
                # after publish() returned would open exactly that cold
                # window, since serving traffic takes no publish lock.
                hooks = []
                if self.pipeline_depth > 0:
                    def staging_hook(searcher, ks):
                        return warm_staging(
                            self.buckets, searcher.dim,
                            searcher.query_dtype,
                            device=self._staging_device,
                            searcher=(searcher
                                      if self._staging_device is not None
                                      else None),
                            ks=ks)

                    hooks.append(("staging_warmed", staging_hook))
                if warm_hook is not None:
                    # the caller's hook runs LAST — a reshard commit must
                    # see every other pre-flip warm already done
                    hooks.append(("warm_hook", warm_hook))
                combined = None
                if hooks:
                    def combined(searcher, ks, _hooks=tuple(hooks)):
                        return {key: fn(searcher, ks) for key, fn in _hooks}
                report = self.registry.publish(
                    name, index, search_params=search_params, k=k,
                    version=version, warm=warm, warm_data=warm_data,
                    tuned=tuned, res=res, warm_hook=combined, cause=cause)
                parts = report.pop("warm_hook", None)
                if parts:
                    report.update(parts)
                with self._lock:
                    mut = getattr(index, "mutable", None)
                    if hasattr(index, "upsert") and hasattr(index, "searcher"):
                        self._mutables[name] = index
                    elif mut is not None and hasattr(mut, "upsert"):
                        # a MutableIndex's OWN hook (marked by searcher() —
                        # what a stream.Compactor republishes after each
                        # swap): the write path follows it
                        self._mutables[name] = mut
                    else:
                        # anything else — a plain index or an unmarked hook
                        # — closes the write path: keeping a stale handle
                        # would route upserts to an index nobody serves
                        self._mutables.pop(name, None)
            return report

    # -- serving ------------------------------------------------------------
    def _stream(self, name: str, k: int, dim: int | None = None,
                qdtype: str | None = None) -> MicroBatcher:
        key = (name, int(k))
        with self._lock:
            # re-checked under the lock: a submit racing shutdown() must not
            # create a batcher shutdown will never close
            if self._closed:
                raise ServiceClosedError("service is shut down")
            b = self._batchers.get(key)
            if b is None:
                staging = None
                if self.pipeline_depth > 0 and dim is not None:
                    staging = StagingBuffers(
                        self.buckets, dim, qdtype,
                        depth=self.pipeline_depth,
                        device=self._staging_device,
                        stream=f"{name}.k{k}")
                # the canary taps only its own name's flushes AT ITS OWN
                # WIDTH — another stream's results (or the same name served
                # at a different k) scored against this oracle would be a
                # category error, not a recall estimate: |top-k' ∩ exact
                # top-k| / k inflates toward 1 for k' > k and caps at k'/k
                # below it, and either way feeds false slots into the SLO
                # quality objective
                canary = self._canary
                on_result = None
                if (canary is not None and canary.name == name
                        and int(canary.k) == int(k)):
                    def on_result(queries, out, _c=canary):
                        _c.offer(queries, out[1])
                b = MicroBatcher(
                    self._make_flush(name, int(k)),
                    max_batch=self.max_batch, max_wait_us=self.max_wait_us,
                    clock=self._clock, stream=f"{name}.k{k}",
                    start=self._start_workers, on_dequeue=self._rows.sub,
                    request_log=self._request_log, slo=self._slo,
                    on_result=on_result,
                    pipeline_depth=self.pipeline_depth, staging=staging)
                self._batchers[key] = b
        return b

    def _make_flush(self, name: str, k: int):
        if self.pipeline_depth == 0:
            # synchronous flush (the pre-pipeline path, and the A/B
            # baseline): lease, search, block, return materialized arrays
            def flush(padded_queries):
                import jax

                t0 = time.perf_counter()
                with self.registry.lease(name) as v:
                    # span collector no-ops unless this flush is traced;
                    # the leased version pins which index epoch answered
                    requestlog.add_span("serve/lease",
                                        time.perf_counter() - t0)
                    requestlog.annotate("version", v.version)
                    t1 = time.perf_counter()
                    out = v.searcher(padded_queries, k)
                    # materialize before scattering: a future that resolves
                    # is a result the caller can use at memcpy cost, and
                    # the latency histograms measure real work, not async
                    # dispatch
                    jax.block_until_ready(out)
                    requestlog.add_span("serve/search",
                                        time.perf_counter() - t1)
                return out

            return flush

        def flush(padded_queries):
            # pipelined flush: dispatch WITHOUT materializing and hand the
            # pending device result to the batcher's completion stage. The
            # registry lease is held until materialization — an in-flight
            # flush still finishes on the version it leased, and
            # retire-after-drain waits for it exactly like a blocking flush
            t0 = time.perf_counter()
            stack = contextlib.ExitStack()
            v = stack.enter_context(self.registry.lease(name))
            try:
                requestlog.add_span("serve/lease", time.perf_counter() - t0)
                requestlog.annotate("version", v.version)
                t1 = time.perf_counter()
                with obs_dispatch.count() as dc:
                    out = v.searcher(padded_queries, k)
                requestlog.add_span("serve/dispatch",
                                    time.perf_counter() - t1)
            except BaseException:
                # a dispatch that raises fails only its own batch — and
                # must not strand the lease (the version could never
                # retire)
                stack.close()
                raise

            def materialize(_out=out, _t1=t1, _stack=stack):
                try:
                    res = tuple(np.asarray(a) for a in _out)
                    requestlog.add_span("serve/search",
                                        time.perf_counter() - _t1)
                    return res
                finally:
                    _stack.close()

            # uninstrumented searchers (plain sealed indexes) count as one
            # dispatch site — the searcher call itself
            return PendingFlush(materialize,
                                dispatches=dc.total if dc.total else 1)

        return flush

    def submit(self, name: str, queries, k: int = 10, *,
               timeout_s: float | None = None,
               rid: str | None = None) -> Future:
        """Enqueue a ``(rows, d)`` query block (rows <= ``max_batch``) for
        index ``name`` at width ``k``; returns a Future resolving to
        ``(distances (rows, k), ids (rows, k))``.

        Fast-fail admission: raises :class:`ServiceClosedError` after
        shutdown, :class:`OverloadedError` at the queue bound, and
        :class:`DeadlineExceededError` when ``timeout_s <= 0``. A queued
        request whose deadline passes before it is drained fails its future
        with :class:`DeadlineExceededError` without touching the device.

        ``rid=`` adopts an externally minted request id for the trace
        (the net front door passes the wire ``X-Raft-Request-Id`` so one
        trace spans wire→queue→flush); ignored when no request log is
        attached.

        Queries are staged as host NumPy (submit never touches the device;
        the flush dispatches one padded bucket-shaped array) and results
        resolve to host NumPy arrays — the serving contract is materialized
        results, not async device handles.
        """
        if self._closed:
            raise ServiceClosedError("service is shut down")
        # lease for the validation reads: a concurrent publish may retire
        # the version (nulling its searcher) the instant it is unleased
        with self.registry.lease(name) as v:  # raises for unknown names
            dim, qdtype, ks = v.searcher.dim, v.searcher.query_dtype, v.ks
        # only published widths are served: k is a static jit argument, so
        # an unwarmed k would cold-compile every bucket ON the hot path
        # (and leak a worker thread per stray k) — the zero-cold-compile
        # property this layer exists for. Publish with k=(10, 5, ...) to
        # serve several widths.
        expects(int(k) in ks,
                "k=%d was not published for %r (published widths: %s)",
                k, name, ks)
        q = np.asarray(queries)
        expects(q.ndim == 2, "queries must be (rows, d); got ndim=%d", q.ndim)
        expects(q.shape[1] == dim,
                "query dim %d != index dim %d", q.shape[1], dim)
        if qdtype == "float32":
            q = np.asarray(q, np.float32)
        else:
            expects(str(q.dtype) == qdtype,
                    "byte index %r serves %s queries, got %s", name,
                    qdtype, str(q.dtype))
        n = int(q.shape[0])
        timeout_s = (self.default_timeout_s if timeout_s is None
                     else timeout_s)
        deadline = None
        if timeout_s is not None:
            if timeout_s <= 0:
                if metrics._enabled:
                    _deadline_total().inc(1, stream=f"{name}.k{k}")
                raise DeadlineExceededError("timeout_s <= 0 at submit")
            deadline = self._clock() + timeout_s
        b = self._stream(name, k, dim, qdtype)  # re-checks _closed in-lock
        # atomic bounded reservation — the bound is a hard invariant, not a
        # hint, and it is O(1) regardless of how many streams are live;
        # the batcher's on_dequeue callback releases rows at drain
        if not self._rows.try_add(n):
            if metrics._enabled:
                _overload_total().inc(1, name=name)
            if self._slo is not None:
                # the availability objective IS the non-overload admission
                # fraction: shed load burns error budget
                self._slo.record_admission(False)
            raise OverloadedError(
                f"queue at {self._rows.value()}/{self.max_queue_rows} rows; "
                f"request of {n} refused")
        rid = (self._request_log.begin(f"{name}.k{k}", n, rid=rid)
               if self._request_log is not None else None)
        try:
            fut = b.submit(q, deadline=deadline, rid=rid)
        except BaseException:  # closed/shape refusal: release the rows
            self._rows.sub(n)
            raise
        if self._slo is not None:
            self._slo.record_admission(True)
        if metrics._enabled:
            _requests_total().inc(1, stream=f"{name}.k{k}")
        return fut

    # -- write path (stream.MutableIndex names) -----------------------------
    def _mutable(self, name: str):
        if self._closed:
            raise ServiceClosedError("service is shut down")
        with self._lock:
            m = self._mutables.get(name)
        expects(m is not None,
                "%r is not a mutable (stream) index — publish a "
                "raft_tpu.stream.MutableIndex under this name to open the "
                "write path", name)
        return m

    def upsert(self, name: str, rows, ids=None, res=None):
        """Insert/upsert rows into the mutable index published under
        ``name``; returns the global ids. Synchronous with read-your-writes
        at the service boundary — when this returns, the rows win every
        subsequent search, except during a compaction swap's publish window,
        where flushes still leasing the pre-swap epoch serve its frozen view
        for one flush (the swap staleness window, docs/streaming.md
        "Consistency model"). The admission taxonomy matches :meth:`submit`:
        :class:`ServiceClosedError` after shutdown, and a full delta
        memtable raises :class:`raft_tpu.stream.DeltaFullError` — an
        :class:`OverloadedError` — so callers shed write load exactly like
        refused reads (attach a ``stream.Compactor`` to fold the delta
        before the wall). ``res`` carries ``memory_budget_bytes``: a write
        whose delta-bucket growth would exceed it raises
        :class:`~raft_tpu.serve.errors.MemoryBudgetError` (also an
        ``OverloadedError``) with nothing written. Mutables resolve
        duck-typed, so a custom hook whose ``upsert`` takes no ``res=``
        still writes — unless a budget is actually armed, in which case a
        hook that cannot price it fails loudly instead of silently
        voiding the budget."""
        m = self._mutable(name)
        try:
            params = inspect.signature(m.upsert).parameters
            takes_res = ("res" in params or any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                for p in params.values()))
        except (TypeError, ValueError):  # C callables: assume compatible
            takes_res = True
        if takes_res:
            return m.upsert(rows, ids, res=res)
        expects(getattr(res, "memory_budget_bytes", None) is None,
                "memory_budget_bytes is set but the mutable published "
                "under %r has an upsert() without res= — it cannot "
                "enforce the budget", name)
        return m.upsert(rows, ids)

    def delete(self, name: str, ids) -> int:
        """Tombstone ids on the mutable index published under ``name``;
        returns how many were live. Deletes are visible to the very next
        search (read-your-writes; same one-flush swap-staleness caveat as
        :meth:`upsert`); unknown ids are a counted no-op."""
        return self._mutable(name).delete(ids)

    def search(self, name: str, queries, k: int = 10, *,
               timeout_s: float | None = None):
        """Blocking convenience: :meth:`submit` + ``Future.result()``.
        Requires running workers (``start_workers=True``); deterministic
        tests use :meth:`submit` + :meth:`pump` instead."""
        expects(self._start_workers,
                "search() blocks on the worker thread; with "
                "start_workers=False use submit() + pump()")
        return self.submit(name, queries, k, timeout_s=timeout_s).result()

    # -- test / drain hooks -------------------------------------------------
    def pump(self, *, force: bool = False) -> int:
        """Drain-and-flush every stream once, synchronously; returns total
        rows flushed. The deterministic substitute for the worker threads."""
        with self._lock:
            batchers = list(self._batchers.values())
        return sum(b.pump(force=force) for b in batchers)

    def queue_depth(self) -> int:
        return self._rows.value()

    def retry_after_hint(self) -> float:
        """How long an admission-refused caller should wait before
        retrying, from the CURRENT queue depth: the queued rows drain in
        ``ceil(depth / max_batch)`` flushes of at most ``max_wait_us``
        each, so that product is when the queue has provably had a chance
        to empty. Floored at one flush window, capped at 250 ms so a
        momentarily deep queue never tells clients to go away for whole
        seconds (the queue drains far faster than it fills under shed
        load). The net front door serves this as ``Retry-After`` on 429s;
        :func:`~raft_tpu.serve.submit_with_retry` prefers it over blind
        exponential backoff."""
        flushes = max(1, -(-self._rows.value() // self.max_batch))
        return min(0.25, flushes * (self.max_wait_us * 1e-6))

    def staging_stats(self) -> dict:
        """Per-stream staging-buffer counters (uploads, donation frees,
        accounted byte levels) — the bench row's no-growth/donation proof
        reads these; empty in sync mode (``pipeline_depth=0``)."""
        with self._lock:
            batchers = dict(self._batchers)
        return {f"{name}.k{k}": b._staging.stats()
                for (name, k), b in batchers.items()
                if b._staging is not None}

    # -- shutdown -----------------------------------------------------------
    def shutdown(self, *, drain: bool = True, timeout_s: float = 10.0) -> None:
        """Stop the service. New submits fail fast with
        :class:`ServiceClosedError`; ``drain=True`` completes everything
        already queued (each pending future resolves normally),
        ``drain=False`` fails pending futures with
        :class:`ServiceClosedError`. Idempotent."""
        self._closed = True
        with self._lock:
            batchers = list(self._batchers.values())
        for b in batchers:
            b.close(drain=drain, timeout_s=timeout_s)
