"""raft_tpu.serve — the online serving layer.

The reference ships kernels and leaves request scheduling to the user
(SURVEY §5: its parallelism is intra-kernel plus user-composed sharding over
``raft::comms``); a TPU deployment "serving heavy traffic from millions of
users" (ROADMAP north star) needs the host-side half of the story:

- :mod:`.batcher` — dynamic micro-batching of concurrent callers into a
  small fixed set of padded power-of-two batch shapes (the warmed program
  set), flushing on batch-full or a ``max_wait_us`` deadline;
- :mod:`.registry` — versioned index registry with warm, atomic hot-swap:
  ``publish`` compiles the new index against the serving buckets BEFORE the
  flip, in-flight requests drain on the old version, retired versions free
  their arrays;
- :mod:`.service` — :class:`SearchService`: admission control (bounded
  queue with fast-fail :class:`OverloadedError`), per-request deadlines
  (expired requests dropped before batching), clean shutdown/drain;
- :mod:`.errors` — the fast-fail vocabulary.

Observability rides on :mod:`raft_tpu.obs` (queue-depth gauge, wait/occupancy
histograms, swap/overload/deadline counters — catalogue in
docs/observability.md) and flushes are tracing-annotated as
``serve/flush/<bucket>`` for xprof. ONLINE quality hooks thread through the
same layer: ``SearchService(canary=, slo=, request_log=)`` wires the live
recall canary's flush tap, the SLO burn-rate tracker's admission/latency
feeds, and request-level tracing (``raft_tpu.obs.quality`` /
``.slo`` / ``.requestlog``). Worked example + bucket/overload policy:
docs/serving.md.
"""

from . import batcher, errors, registry, retry, service, staging
from .batcher import MicroBatcher, PendingFlush, bucket_for, bucket_sizes
from .errors import (DeadlineExceededError, MemoryBudgetError,
                     OverloadedError, ReplicaUnavailableError, ServeError,
                     ServiceClosedError)
from .registry import IndexRegistry, make_searcher
from .retry import submit_with_retry
from .service import SearchService
from .staging import StagingBuffers, warm_staging

__all__ = [
    "batcher", "registry", "service", "errors", "retry", "staging",
    "MicroBatcher", "PendingFlush", "bucket_sizes", "bucket_for",
    "IndexRegistry", "make_searcher", "SearchService",
    "submit_with_retry", "StagingBuffers", "warm_staging",
    "ServeError", "OverloadedError", "DeadlineExceededError",
    "ServiceClosedError", "MemoryBudgetError", "ReplicaUnavailableError",
]
