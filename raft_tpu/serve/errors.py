"""Serving-layer error types.

All derive from :class:`raft_tpu.core.errors.RaftError` so a caller's
existing ``except RaftError`` fences keep working; the three subclasses are
the serving layer's fast-fail vocabulary (the reference leaves request
scheduling to the user, so it has no counterpart — these follow the standard
serving taxonomy: overload, deadline, shutdown).
"""

from __future__ import annotations

from ..core.errors import RaftError

__all__ = ["ServeError", "OverloadedError", "DeadlineExceededError",
           "ServiceClosedError", "MemoryBudgetError",
           "ReplicaUnavailableError"]


class ServeError(RaftError):
    """Base for serving-layer failures."""


class OverloadedError(ServeError):
    """Admission control rejected the request: the queue is at its bound.

    Raised synchronously from ``submit`` — the caller finds out in
    microseconds, not after its deadline (fast-fail is the point: shed load
    at the door, never queue work that cannot be served in time).
    """


class MemoryBudgetError(OverloadedError):
    """The ``Resources.memory_budget_bytes`` gate refused admission: the
    operation would push the ledger-accounted device bytes past the budget
    (:func:`raft_tpu.obs.mem.gate`).

    An :class:`OverloadedError`, so existing shed-load fences catch it, and
    whole-or-nothing like every admission refusal: raised at
    ``build``/``publish``/``upsert`` BEFORE any state lands. Structured
    fields: ``site`` (which admission point), ``budget_bytes``,
    ``accounted_bytes`` (ledger device total at refusal), ``need_bytes``
    (the projected growth that tripped the gate).
    """

    def __init__(self, msg: str, *, site: str = "", budget_bytes: int = 0,
                 accounted_bytes: int = 0, need_bytes: int = 0):
        super().__init__(msg)
        self.site = site
        self.budget_bytes = int(budget_bytes)
        self.accounted_bytes = int(accounted_bytes)
        self.need_bytes = int(need_bytes)


class ReplicaUnavailableError(ServeError):
    """EVERY replica of a :class:`raft_tpu.stream.ReplicatedShard` is
    fenced or failed — the query cannot be served by any twin. One dead
    replica never raises this (the scatter retries the survivor in the
    same flush, which is the availability contract); all-dead is a real
    outage the caller must see. Structured fields: ``name`` (the shard),
    ``replicas`` (total), ``fenced`` (how many were fenced when the last
    attempt failed)."""

    def __init__(self, msg: str, *, name: str = "", replicas: int = 0,
                 fenced: int = 0):
        super().__init__(msg)
        self.name = name
        self.replicas = int(replicas)
        self.fenced = int(fenced)


class DeadlineExceededError(ServeError):
    """The request's deadline expired.

    Either synchronously at submit (deadline already in the past) or set on
    the request's future when the batcher drains the queue — expired
    requests are dropped BEFORE being batched, so an overloaded service
    never burns device time on results nobody is waiting for.
    """


class ServiceClosedError(ServeError):
    """The service (or one of its streams) has been shut down."""
