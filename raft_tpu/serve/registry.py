"""Versioned index registry with warm, atomic hot-swap.

A production index is rebuilt continuously (fresh embeddings, streaming
inserts compacted offline); the serving fleet must replace it UNDER LOAD.
Three properties make a swap safe on TPU:

1. **Warm before visible** — :meth:`IndexRegistry.publish` runs the new
   index's searcher at every serving bucket shape (``_warmup.warm_buckets``)
   BEFORE flipping the active pointer. The jit/persistent-cache key is the
   HLO, and a rebuilt index of the same static config (n_lists, pq_dim,
   itopk, dtype, bucket shapes) is the SAME set of programs — so a swap
   costs zero cold compiles on the hot path, and the publish report proves
   it (compile attribution per bucket, from :mod:`raft_tpu.obs.compile`).
2. **Atomic flip, lease-pinned flushes** — the active pointer changes under
   a lock; an in-flight FLUSH holds a :meth:`lease` on the version it
   resolved and finishes on it (requests still queued at the flip are
   served by the new version at their flush — same stream contract,
   enforced at publish, so the difference is invisible to callers). No
   request ever sees half a swap.
3. **Retire after drain** — an unpublished version is dropped (index arrays
   released to the allocator) only when its lease count reaches zero.

The registry dispatches through each index module's ``batched_searcher``
hook (the stable serving surface of ``neighbors/*``), so it works uniformly
for brute-force, IVF-Flat, IVF-PQ and CAGRA — including the int8/uint8
byte-dataset variants, whose warmup queries are drawn in the index's own
query dtype so the s8 programs compile exactly as production runs them.
"""

from __future__ import annotations

import contextlib
import functools
import threading
import time
from dataclasses import dataclass, field
from typing import Callable

from ..core.errors import RaftError, expects
from ..core.resources import default_resources
from ..obs import events as obs_events
from ..obs import mem as obs_mem
from ..obs import metrics

__all__ = ["IndexRegistry", "make_searcher", "DEFAULT_BUCKETS"]

DEFAULT_BUCKETS = (1, 2, 4, 8, 16, 32, 64)


@functools.lru_cache(maxsize=None)
def _swap_total():
    return metrics.counter(
        "raft_tpu_serve_swap_total",
        "hot-swaps (publishes that replaced a live version)")


@functools.lru_cache(maxsize=None)
def _retired_total():
    return metrics.counter(
        "raft_tpu_serve_retired_total",
        "index versions retired after their last lease drained")


@functools.lru_cache(maxsize=None)
def _versions_live():
    return metrics.gauge(
        "raft_tpu_serve_versions_live", "live (leasable) versions per name")


def make_searcher(index, search_params=None) -> Callable:
    """Resolve an index object to its module's ``batched_searcher`` hook:
    a ``fn(queries, k) -> (distances, ids)`` closure carrying ``.kind``,
    ``.dim`` and ``.query_dtype`` attributes. Raises for unknown types.
    A :class:`raft_tpu.stream.MutableIndex` (duck-typed, so serve never
    imports stream) resolves to its current-epoch searcher — its search
    params were baked in at wrap time."""
    from ..neighbors import brute_force, cagra, ivf_flat, ivf_pq

    if hasattr(index, "upsert") and hasattr(index, "searcher"):
        expects(search_params is None,
                "a MutableIndex bakes its search params at wrap time; "
                "search_params here would be silently ignored")
        return index.searcher()
    for mod, cls in ((brute_force, brute_force.BruteForce),
                     (ivf_flat, ivf_flat.IvfFlatIndex),
                     (ivf_pq, ivf_pq.IvfPqIndex),
                     (cagra, cagra.CagraIndex)):
        if isinstance(index, cls):
            return mod.batched_searcher(index, search_params)
    raise RaftError(
        f"no serving hook for index type {type(index).__name__!r} "
        "(expected BruteForce, IvfFlatIndex, IvfPqIndex, CagraIndex or "
        "stream.MutableIndex)")


@dataclass
class _Version:
    """One published version of one name. ``leases`` counts in-flight
    flushes pinned to it; ``active=False`` + ``leases==0`` → retire."""

    name: str
    version: int
    searcher: Callable
    published_at: float
    ks: tuple = (10,)  # serving widths this version was published (warmed) for
    active: bool = True
    leases: int = 0
    warm_report: dict = field(default_factory=dict)
    # obs.mem ledger token (owner = the searcher closure): retired at
    # retire-after-drain, released when the closure is actually collected
    # — the gap between the two is the leak the retirement audit catches
    mem: object = None


class IndexRegistry:
    """Thread-safe name → versioned-searcher registry (see module doc)."""

    def __init__(self, *, buckets: tuple = DEFAULT_BUCKETS,
                 clock: Callable[[], float] = time.monotonic):
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        expects(bool(self.buckets) and self.buckets[0] >= 1,
                "buckets must be positive batch sizes")
        self._clock = clock
        self._lock = threading.Lock()
        self._active: dict[str, _Version] = {}
        self._versions: dict[str, list[_Version]] = {}
        # publishes serialize PER NAME (warm-then-flip must not interleave
        # for one name), but a slow warm of one index must not block an
        # urgent hot-swap of another; reentrant so service-layer wrappers
        # can hold it around publish() (see publish_lock)
        self._publish_locks: dict[str, threading.RLock] = {}

    # -- publish / swap -----------------------------------------------------
    def publish(self, name: str, index, *, search_params=None,
                k: int | tuple = 10, version: int | None = None,
                warm: bool = True, warm_data=None, tuned=None,
                res=None, warm_hook=None, cause: dict | None = None) -> dict:
        """Make ``(index, search_params)`` the active version of ``name``.

        Warms the searcher at every registry bucket shape for every ``k``
        (pass the tuple of widths production serves) BEFORE the flip, so the
        swap is invisible to the hot path; returns a report with the new
        version number and per-``k`` per-bucket compile attribution — a
        publish against an already-warm program set reports
        ``compile_s == 0`` everywhere, which is the hiccup-free-swap proof
        (asserted by ``bench.py --serve``). ``warm=False`` skips warmup
        (provisioning scripts that warmed out-of-band). ``warm_data``
        (optional (rows, dim) sample in the serving query dtype) draws the
        warmup queries from real data instead of uniform noise — identical
        program coverage (compilation is shape-keyed), representative
        warm-time walls in the report (:func:`raft_tpu._warmup
        .warm_buckets`).

        ``tuned`` (a :class:`raft_tpu.tune.DecisionLog`, a single
        :class:`~raft_tpu.tune.Decision`/dict, or ``True`` to use the
        decision attached to the index) serves the index at its pinned
        operating point: the searcher is built through
        :func:`raft_tpu.tune.make_searcher`, and the warm ladder below
        covers the TUNED programs — applying a decision never introduces
        a cold compile on the hot path (docs/tuning.md; the report's
        per-bucket attribution proves it per publish). Mutually exclusive
        with ``search_params`` and pre-built hooks; ``refine_ratio``
        operating points need the raw rows, so publish the hook
        ``tune.make_searcher(index, log, dataset=rows)`` builds instead.

        ``res`` (a :class:`raft_tpu.core.Resources`, default the process
        handle) carries ``memory_budget_bytes``: a publish whose index
        would push the accounted device bytes past the budget raises
        :class:`~raft_tpu.serve.errors.MemoryBudgetError` BEFORE the warm
        spend and before any registry mutation — zero partial state, the
        same whole-or-nothing contract as every admission refusal.

        ``warm_hook`` (``fn(searcher, ks) -> Any``, run only when
        ``warm=True``) extends the warm ladder: it runs on the RESOLVED
        searcher after the bucket warm and, critically, BEFORE the flip —
        the seam a wrapper uses to compile extra serving programs (e.g.
        the pipelined flush path's committed-placement staging
        executables) without a cold window between the flip and its own
        post-publish warm. Its return value lands in
        ``report["warm_hook"]``.

        ``cause`` (a small dict — e.g. the control plane's trigger/decision
        journal seqs) rides the ``serve_published`` event's evidence
        verbatim: an automated republish stays causally chained in the
        journal to the sensor event that advised it.
        """
        from .._warmup import warm_buckets

        src_index = index  # the pre-resolution object, for the budget gate
        if tuned is not None:
            from ..tune.apply import make_searcher as tuned_searcher

            expects(search_params is None,
                    "tuned= and search_params= both pin search params — "
                    "pass one")
            expects(not (callable(index) and hasattr(index, "kind"))
                    and not hasattr(index, "upsert"),
                    "tuned= applies to a plain index; pre-built hooks and "
                    "stream.MutableIndex bake their own params")
            index = tuned_searcher(index, tuned)
        if callable(index) and hasattr(index, "kind"):
            # pre-built hook: its params are baked into the closure, so a
            # search_params here would be silently ignored — refuse instead
            expects(search_params is None,
                    "search_params has no effect on a pre-built hook "
                    "(%r bakes its own); build the hook with them",
                    getattr(index, "kind", "?"))
            searcher = index
        else:
            searcher = make_searcher(index, search_params)
        # memory-budget admission (no-op unless res.memory_budget_bytes is
        # set): a plain index counts the device bytes the ledger has not
        # already accounted (an obs-enabled build's bytes are in the totals
        # the gate compares); hooks/mutables carry their bytes in their own
        # stream/index entries and add nothing new at publish
        obs_mem.gate(res or default_resources(),
                     lambda: obs_mem.unaccounted_index_bytes(src_index),
                     site="publish", detail=f"publish {name!r}")
        # an admitted plain index joins the ledger under its serving name
        # (idempotent — an obs-enabled build's entry just re-attributes):
        # without this, a SECOND dark-built publish would gate against a
        # total that never learned about the first
        obs_mem.account_index(src_index, name=name)
        ks = (k,) if isinstance(k, int) else tuple(k)
        with self.publish_lock(name):
            # a replacement must preserve the stream contract: batchers pin
            # (d, dtype) per stream and queued requests flush on the version
            # active at drain, so a dim/dtype-changing republish would fail
            # queued batches and wedge the stream. A new contract is a new
            # NAME, validated here BEFORE the warmup spend.
            with self._lock:
                prev = self._active.get(name)
            if prev is not None:
                expects(
                    searcher.dim == prev.searcher.dim
                    and searcher.query_dtype == prev.searcher.query_dtype,
                    "publish(%r): new version serves (%d, %s) but the live "
                    "version serves (%d, %s) — a changed stream contract "
                    "must be published under a new name", name,
                    searcher.dim, searcher.query_dtype,
                    prev.searcher.dim, prev.searcher.query_dtype)
                # widths are part of the contract too: narrowing would cold-
                # compile queued requests of a dropped width (flushes lease
                # the NEW version) and lock that width's live callers out
                expects(set(prev.ks) <= set(int(kk) for kk in ks),
                        "publish(%r): live widths %s must be kept (got %s) "
                        "— dropping a width orphans its live stream",
                        name, prev.ks, tuple(ks))
            report: dict = {"name": name, "warmed": warm, "warm": {},
                            # decision key when the hook runs a tune pin
                            # (set by tune.make_searcher) — the publish
                            # report says which operating point went live
                            "tuned": getattr(searcher, "tuned", None)}
            if warm:
                for kk in ks:
                    report["warm"][int(kk)] = warm_buckets(
                        searcher, dim=searcher.dim,
                        dtype=searcher.query_dtype,
                        buckets=self.buckets, k=int(kk),
                        sample=warm_data)
                if warm_hook is not None:
                    report["warm_hook"] = warm_hook(
                        searcher, tuple(int(kk) for kk in ks))
            to_retire: list[_Version] = []
            with self._lock:
                old = self._active.get(name)
                if version is None:
                    version = (old.version + 1) if old is not None else 1
                else:
                    expects(old is None or version > old.version,
                            "version %d must exceed the active version %d",
                            version, old.version if old else -1)
                v = _Version(name, int(version), searcher,
                             self._clock(), ks=tuple(int(kk) for kk in ks),
                             warm_report=report["warm"])
                # liveness entry for the retirement audit (bytes ride the
                # index/stream entries; this tracks the closure that pins
                # them — the PR 9 leak class)
                v.mem = obs_mem.account("serve/version", name=name,
                                        epoch=v.version, owner=searcher)
                self._versions.setdefault(name, []).append(v)
                self._active[name] = v
                if old is not None:
                    old.active = False
                    _swap_total().inc(1, name=name)
                    if old.leases == 0:
                        to_retire.append(old)
                        self._versions[name].remove(old)
                _versions_live().set(len(self._versions[name]), name=name)
            for dead in to_retire:
                self._retire(dead)
            report["version"] = v.version
            obs_events.emit(
                "serve_published",
                subject=("serve", name, None, v.version),
                evidence={"swap": old is not None, "warmed": warm,
                          "ks": list(v.ks),
                          **({"cause": dict(cause)} if cause else {})})
            return report

    def publish_lock(self, name: str) -> threading.RLock:
        """The per-name publish serialization lock (reentrant — publish()
        takes it itself). Wrappers that keep name-keyed state consistent
        with the flip (e.g. SearchService's write-path handles) hold it
        AROUND their publish() call so no concurrent publish can interleave
        between the flip and their bookkeeping."""
        with self._lock:
            return self._publish_locks.setdefault(name, threading.RLock())

    def _retire(self, v: _Version) -> None:
        # retirement audit: from here the searcher closure SHOULD become
        # unreachable — obs.mem.audit() reports it as a leak while anything
        # (a program cache, a stray strong ref) still pins it
        obs_mem.retire(v.mem)
        # drop the searcher closure — it owns the only registry reference
        # to the index arrays, so this releases them to the allocator
        v.searcher = None
        _retired_total().inc(1, name=v.name)
        obs_events.emit(
            "serve_retired",
            subject=("serve", v.name, None, v.version),
            evidence={"leases": v.leases})

    # -- read side ----------------------------------------------------------
    def active(self, name: str) -> _Version:
        """Metadata access ONLY (``version``/``ks``/``published_at``): the
        returned object is live, and a concurrent publish may retire it —
        nulling ``searcher`` — the instant it is replaced. To CALL the
        searcher, hold a :meth:`lease`."""
        with self._lock:
            v = self._active.get(name)
        if v is None:
            raise RaftError(f"no index published under {name!r}")
        return v

    @contextlib.contextmanager
    def lease(self, name: str):
        """Pin the active version for one flush: yields the version object
        (use ``.searcher``); the version cannot be retired while leased — a
        flush finishes on the version it leased even if a publish flips the
        pointer mid-flush. (Queued requests not yet flushed lease whatever
        is active at their drain; publish enforces that replacements keep
        the stream contract, so that is indistinguishable to callers.)"""
        with self._lock:
            v = self._active.get(name)
            if v is None:
                raise RaftError(f"no index published under {name!r}")
            v.leases += 1
        try:
            yield v
        finally:
            retire = None
            with self._lock:
                v.leases -= 1
                if not v.active and v.leases == 0:
                    retire = v
                    self._versions[v.name].remove(v)
                    _versions_live().set(
                        len(self._versions[v.name]), name=v.name)
            if retire is not None:
                self._retire(retire)

    def names(self) -> tuple:
        with self._lock:
            return tuple(sorted(self._active))

    def live_versions(self, name: str) -> tuple:
        """Version numbers still leasable (active + draining)."""
        with self._lock:
            return tuple(v.version for v in self._versions.get(name, ()))
